// Payload arena: host-side storage for packet payload bytes.
//
// The device-side engine moves packet *metadata* (a dense SoA pool where
// each packet carries a payload_id); actual bytes never belong on the
// accelerator.  This arena is the native analog of the reference's
// refcounted Payload shared across hosts
// (/root/reference/src/main/routing/payload.c:16-23): one allocation per
// logical payload, shared by every in-flight copy of the packet, freed
// when the last reference drops.
//
// Design: slab-of-slots with an intrusive free list.  Ids are
// (index | generation<<32) so stale ids from a previous occupancy of the
// same slot are detected instead of silently aliasing.  Thread-safe via a
// single mutex -- contention is irrelevant at the host-side call rates
// (payload churn is bounded by app I/O, not the device hot loop).
//
// C ABI so Python binds via ctypes (no pybind11 in this toolchain).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Slot {
  std::vector<uint8_t> data;
  uint32_t generation = 0;
  int32_t refcount = 0;   // 0 = free
  int64_t next_free = -1;
};

struct Arena {
  std::mutex mu;
  std::vector<Slot> slots;
  int64_t free_head = -1;
  uint64_t live = 0;
  uint64_t live_bytes = 0;
  uint64_t total_allocs = 0;
};

constexpr uint64_t kIndexMask = 0xFFFFFFFFull;

inline int64_t slot_of(uint64_t id) {
  return static_cast<int64_t>(id & kIndexMask);
}
inline uint32_t gen_of(uint64_t id) {
  return static_cast<uint32_t>(id >> 32);
}
inline uint64_t make_id(int64_t index, uint32_t gen) {
  return (static_cast<uint64_t>(gen) << 32) | static_cast<uint64_t>(index);
}

Slot* checked_slot(Arena* a, uint64_t id) {
  int64_t idx = slot_of(id);
  if (idx < 0 || idx >= static_cast<int64_t>(a->slots.size())) return nullptr;
  Slot* s = &a->slots[idx];
  if (s->refcount <= 0 || s->generation != gen_of(id)) return nullptr;
  return s;
}

}  // namespace

extern "C" {

// Returns an opaque arena handle.
void* payload_arena_create() { return new Arena(); }

void payload_arena_destroy(void* h) { delete static_cast<Arena*>(h); }

// Store `len` bytes; returns a payload id with refcount 1, or 0 on error
// (0 is never a valid id: slot 0/gen 0 is burned at creation).
uint64_t payload_arena_put(void* h, const uint8_t* data, uint64_t len) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  if (a->slots.empty()) {
    // Burn slot 0 so id 0 stays invalid.
    a->slots.emplace_back();
    a->slots[0].generation = 1;
  }
  int64_t idx;
  if (a->free_head >= 0) {
    idx = a->free_head;
    a->free_head = a->slots[idx].next_free;
  } else {
    idx = static_cast<int64_t>(a->slots.size());
    a->slots.emplace_back();
  }
  Slot* s = &a->slots[idx];
  s->data.assign(data, data + len);
  s->generation++;
  s->refcount = 1;
  a->live++;
  a->live_bytes += len;
  a->total_allocs++;
  return make_id(idx, s->generation);
}

// Share the payload with one more packet copy (reference payload_ref).
int payload_arena_ref(void* h, uint64_t id) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  Slot* s = checked_slot(a, id);
  if (!s) return -1;
  s->refcount++;
  return 0;
}

// Drop one reference; frees the slot at zero (reference payload_unref).
int payload_arena_unref(void* h, uint64_t id) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  Slot* s = checked_slot(a, id);
  if (!s) return -1;
  if (--s->refcount == 0) {
    a->live--;
    a->live_bytes -= s->data.size();
    s->data.clear();
    s->data.shrink_to_fit();
    s->next_free = a->free_head;
    a->free_head = slot_of(id);
  }
  return 0;
}

// Payload size in bytes, or -1 for an invalid/stale id.
int64_t payload_arena_size(void* h, uint64_t id) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  Slot* s = checked_slot(a, id);
  return s ? static_cast<int64_t>(s->data.size()) : -1;
}

// Copy up to `cap` bytes into `out`; returns bytes copied or -1.
int64_t payload_arena_get(void* h, uint64_t id, uint8_t* out, uint64_t cap) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  Slot* s = checked_slot(a, id);
  if (!s) return -1;
  uint64_t n = s->data.size() < cap ? s->data.size() : cap;
  std::memcpy(out, s->data.data(), n);
  return static_cast<int64_t>(n);
}

// Live payload count / bytes / lifetime allocations (the object-census
// hook, reference object_counter.c).
void payload_arena_stats(void* h, uint64_t* live, uint64_t* live_bytes,
                         uint64_t* total) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  *live = a->live;
  *live_bytes = a->live_bytes;
  *total = a->total_allocs;
}

}  // extern "C"
