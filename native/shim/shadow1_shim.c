/* shadow1_shim: LD_PRELOAD syscall interposer for real plugin processes.
 *
 * The TPU-era equivalent of the reference's libshadow-interpose.so
 * (/root/reference/src/preload/interposer.c): a plugin binary runs as a
 * REAL process with this library preloaded; calls touching the simulated
 * world (AF_INET sockets, sleeps, wall-clock reads) are marshaled over a
 * SOCK_SEQPACKET pipe to the host-side sequencer, which answers them in
 * deterministic virtual-time order.  Everything else falls through to
 * libc.
 *
 * Differences from the reference by design (docs/design-process-substrate.md):
 * no dlmopen namespaces (process isolation replaces the custom ELF loader,
 * src/external/elf-loader/) and no cooperative pth threads (the sequencer
 * runs whole processes until they block, the analog of
 * process.c:1197-1275 run-until-blocked).
 *
 * Virtual fds: simulated sockets get descriptor numbers >= VFD_BASE so the
 * shim can route by fd value without tracking real fds.
 *
 * Virtual clock: the sequencer publishes nanoseconds-since-epoch in a
 * shared mmap page (env SHADOW1_TIME_PAGE); clock_gettime and friends are
 * answered in-process from that page, no round trip (emulated epoch starts
 * Jan 1 2000 like the reference, definitions.h:78).
 */

#define _GNU_SOURCE
#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>
#include <pthread.h>
#include <semaphore.h>
#include <stdarg.h>
#include <sys/epoll.h>
#include <sys/timerfd.h>
#include <stddef.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <sys/types.h>
#include <time.h>
#include <unistd.h>

#define VFD_BASE (1 << 20)
#define MAX_VFD 4096
#define MAX_DATA 65536

/* epoll instances are shim-local objects (no sequencer round trip to
 * create one); epoll_wait lowers onto the same OP_POLL readiness RPC
 * poll() uses, so the simulator has ONE readiness model (reference
 * epoll.c:638-671 is likewise the one notify mechanism).  Level-
 * triggered only; EPOLLET is refused at epoll_ctl time. */
#define EPFD_BASE (VFD_BASE + MAX_VFD)
#define MAX_EPFD 64
#define MAX_WATCH 256

/* timerfds are also shim-local: expiry is a pure function of the
 * virtual clock page, so readiness needs no RPC; only BLOCKING (read
 * before expiry, poll with no other ready fd) parks the process in
 * virtual time via OP_SLEEP (reference timer.c/timerfd semantics). */
#define TFD_BASE (EPFD_BASE + MAX_EPFD)
#define MAX_TFD 64

/* ---- wire protocol (must match native/sequencer.cc + substrate) ---- */
enum {
  OP_SOCKET = 1,
  OP_CONNECT = 2,
  OP_SEND = 3,
  OP_RECV = 4,
  OP_CLOSE = 5,
  OP_SLEEP = 6,
  OP_GETTIME = 7,
  OP_BIND = 8,
  OP_LISTEN = 9,
  OP_ACCEPT = 10,
  OP_POLL = 11,
  OP_EXIT = 12,
  OP_PIPE = 13,
  OP_SENDTO = 14,
  OP_RECVFROM = 15,
  OP_RESOLVE = 16,
};

typedef struct {
  uint32_t op;
  int32_t fd;
  int64_t a0;
  int64_t a1;
  uint32_t len;
  unsigned char data[MAX_DATA];
} req_t;

typedef struct {
  int64_t ret;
  int32_t err;
  int64_t vtime_ns;
  uint32_t len;
  unsigned char data[MAX_DATA];
} rep_t;

#define REQ_HDR ((size_t)offsetof(req_t, data))
#define REP_HDR ((size_t)offsetof(rep_t, data))

static int g_seq_fd = -1;
static volatile int64_t *g_time_page = NULL;
static int g_vfd_open[MAX_VFD];
static int g_vfd_nonblock[MAX_VFD];
/* Pending socket error (SO_ERROR), filled from poll replies so a
 * nonblocking connect's failure is observable the way libc callers
 * expect: poll -> POLLERR/POLLOUT -> getsockopt(SO_ERROR). */
static int g_vfd_soerr[MAX_VFD];
/* Extra aliases per vfd beyond the first (dup/dup2/dup3): close() only
 * tears the bridge socket down (OP_CLOSE) when the LAST alias goes --
 * the reference refcounts descriptor handles the same way
 * (descriptor.c ref/unref). */
static int g_vfd_refs[MAX_VFD];

typedef struct {
  int used;
  int nwatch;
  int wfd[MAX_WATCH];
  uint32_t wevents[MAX_WATCH];
  epoll_data_t wdata[MAX_WATCH];
} epoll_inst_t;

static epoll_inst_t g_ep[MAX_EPFD];

typedef struct {
  int used;
  int nonblock;         /* TFD_NONBLOCK: read returns EAGAIN pre-expiry */
  int64_t expiry_ns;    /* absolute virtual ns; 0 = disarmed */
  int64_t interval_ns;  /* periodic re-arm; 0 = one-shot */
} tfd_t;

static tfd_t g_tfd[MAX_TFD];

static int is_tfd(int fd) {
  return fd >= TFD_BASE && fd < TFD_BASE + MAX_TFD && g_tfd[fd - TFD_BASE].used;
}

static ssize_t tfd_read(int fd, void *buf, size_t n);

static ssize_t (*real_read)(int, void *, size_t);
static ssize_t (*real_write)(int, const void *, size_t);
static int (*real_close)(int);
static int (*real_clock_gettime)(clockid_t, struct timespec *);
static int (*real_nanosleep)(const struct timespec *, struct timespec *);

static void shim_init(void) __attribute__((constructor));

static void shim_init(void) {
  real_read = dlsym(RTLD_NEXT, "read");
  real_write = dlsym(RTLD_NEXT, "write");
  real_close = dlsym(RTLD_NEXT, "close");
  real_clock_gettime = dlsym(RTLD_NEXT, "clock_gettime");
  real_nanosleep = dlsym(RTLD_NEXT, "nanosleep");

  const char *fd_s = getenv("SHADOW1_SHIM_FD");
  if (fd_s) g_seq_fd = atoi(fd_s);
  const char *page = getenv("SHADOW1_TIME_PAGE");
  if (page) {
    int pfd = open(page, O_RDONLY);
    if (pfd >= 0) {
      void *m = mmap(NULL, 4096, PROT_READ, MAP_SHARED, pfd, 0);
      if (m != MAP_FAILED) g_time_page = (volatile int64_t *)m;
      ((int (*)(int))real_close)(pfd);
    }
  }
}

static int is_vfd(int fd) {
  return fd >= VFD_BASE && fd < VFD_BASE + MAX_VFD && g_vfd_open[fd - VFD_BASE];
}

/* ---- low fd aliases ---------------------------------------------------
 * Protocol vfd ids are >= 1<<20 (collision-free routing by value), but
 * real programs put fds in fd_sets (select) and assume small numbers.
 * Each vfd therefore RESERVES a real kernel fd (a dup of /dev/null) and
 * the plugin sees that small number; interposed entry points promote
 * alias -> vfd.  Closing releases both.  The reference keeps the same
 * shape as shadow<->OS handle maps (host.c:57-105). */
#define MAX_ALIAS 4096
static int g_alias2vfd[MAX_ALIAS];
static int unix_path_port(const char *path);

static int vfd_promote(int fd) {
  if (fd >= 0 && fd < MAX_ALIAS && g_alias2vfd[fd]) return g_alias2vfd[fd];
  return fd;
}

static int alias_install(int64_t r) {
  if (!(r >= VFD_BASE && r < VFD_BASE + MAX_VFD)) return (int)r;
  int a = open("/dev/null", O_RDONLY | O_CLOEXEC);
  if (a < 0 || a >= MAX_ALIAS) {
    if (a >= 0) real_close(a);
    return (int)r;  /* fall back to the raw vfd id */
  }
  g_alias2vfd[a] = (int)r;
  return a;
}

/* ---- cooperative virtual threads (the rpth analog) -------------------
 *
 * The reference runs real multi-threaded plugins by replacing libpthread
 * with a cooperative userspace scheduler (src/external/rpth/
 * pth_lib.c:98-146; ~90 pthread_* mappings in src/main/host/
 * process.c:1084-1110).  Here the same guarantee -- exactly one plugin
 * thread runs at a time, switching only at deterministic interposed
 * points -- is enforced with a TOKEN over real OS threads: every thread
 * parks on its own condvar until handed the token, blocking calls
 * release it, and when ALL threads are blocked the token holder issues
 * ONE union readiness RPC (the same OP_POLL the single-threaded shim
 * uses), so the sequencer/bridge protocol is completely unchanged and
 * the process still looks like one run-until-blocked unit.
 *
 * Determinism: switches happen only at interposed blocking points; the
 * next thread is chosen round-robin by slot index; wakeups derive from
 * the bridge's deterministic replies and the virtual clock.  A state
 * where every thread waits on a mutex/cond/join (nothing external can
 * ever fire) is a guaranteed deadlock and aborts with a diagnostic
 * instead of hanging the sequencer. */
#define VT_NO_DEADLINE ((int64_t)1 << 62)
static int vt_multi(void);
static void vt_wait_fd(int fd, short ev);
static void vt_wait_sleep(int64_t wake_ns);
static void vt_wait_poll(struct pollfd *fds, int nfds, int64_t wake_ns);
static void vt_wait_tfd(int tfd_idx);

/* One blocking round trip to the sequencer. */
static int64_t rpc(req_t *rq, rep_t *rp) {
  if (g_seq_fd < 0) {
    errno = ENOSYS;
    return -1;
  }
  ssize_t n = send(g_seq_fd, rq, REQ_HDR + rq->len, 0);
  if (n < 0) _exit(117);
  n = recv(g_seq_fd, rp, sizeof(*rp), 0);
  if (n < (ssize_t)REP_HDR) _exit(118);
  if (rp->ret < 0 && rp->err) errno = rp->err;
  return rp->ret;
}

static int64_t vnow(void) {
  if (g_time_page) return *g_time_page;
  req_t rq = {.op = OP_GETTIME, .fd = -1, .len = 0};
  rep_t rp;
  rpc(&rq, &rp);
  return rp.vtime_ns;
}

/* ---- sockets ---- */

int socket(int domain, int type, int protocol) {
  /* AF_UNIX sockets virtualize as loopback TCP/UDP on the process's own
   * host (path -> stable port; reference socket.h:47-78 unix-path map),
   * keeping them inside virtual time instead of leaking to the kernel. */
  if (g_seq_fd >= 0 && (domain == AF_INET || domain == AF_UNIX)) {
    req_t rq = {.op = OP_SOCKET, .fd = -1, .a0 = type, .a1 = protocol,
                .len = 0};
    rep_t rp;
    int64_t r = rpc(&rq, &rp);
    if (r >= VFD_BASE && r < VFD_BASE + MAX_VFD) {
      g_vfd_open[r - VFD_BASE] = 1;
      g_vfd_nonblock[r - VFD_BASE] = (type & SOCK_NONBLOCK) != 0;
      return alias_install(r);
    }
    return (int)r;
  }
  static int (*real_socket)(int, int, int);
  if (!real_socket) real_socket = dlsym(RTLD_NEXT, "socket");
  return real_socket(domain, type, protocol);
}

int connect(int fd, const struct sockaddr *addr, socklen_t alen) {
  fd = vfd_promote(fd);
  if (is_vfd(fd) && addr && addr->sa_family == AF_UNIX) {
    const struct sockaddr_un *u = (const struct sockaddr_un *)addr;
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(0x7F000001);  /* self (bridge loopback) */
    a.sin_port = htons((uint16_t)unix_path_port(u->sun_path));
    return connect(fd, (const struct sockaddr *)&a, sizeof a);
  }
  if (is_vfd(fd) && addr && addr->sa_family == AF_INET) {
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    int user_nb = g_vfd_nonblock[fd - VFD_BASE];
    /* Nonblock flag rides above the 16-bit port in a1; a nonblocking
     * connect returns -1/EINPROGRESS and completes via poll. */
    req_t rq = {.op = OP_CONNECT, .fd = fd,
                .a0 = (int64_t)ntohl(a->sin_addr.s_addr),
                .a1 = (int64_t)ntohs(a->sin_port) |
                      ((int64_t)(user_nb || vt_multi()) << 32),
                .len = 0};
    rep_t rp;
    int r = (int)rpc(&rq, &rp);
    if (user_nb || !vt_multi() || r == 0 || errno != EINPROGRESS)
      return r;
    /* Blocking connect under the thread gate: complete via readiness
     * like a poll(POLLOUT) caller would. */
    for (;;) {
      vt_wait_fd(fd, POLLOUT);
      struct pollfd pf = {.fd = fd, .events = POLLOUT, .revents = 0};
      if (poll(&pf, 1, 0) > 0) {
        if (pf.revents & POLLERR) {
          int soerr = g_vfd_soerr[fd - VFD_BASE];
          g_vfd_soerr[fd - VFD_BASE] = 0;
          errno = soerr ? soerr : ECONNREFUSED;
          return -1;
        }
        if (pf.revents & POLLOUT) return 0;
      }
    }
  }
  static int (*real_connect)(int, const struct sockaddr *, socklen_t);
  if (!real_connect) real_connect = dlsym(RTLD_NEXT, "connect");
  return real_connect(fd, addr, alen);
}

int bind(int fd, const struct sockaddr *addr, socklen_t alen) {
  fd = vfd_promote(fd);
  if (is_vfd(fd) && addr && addr->sa_family == AF_UNIX) {
    const struct sockaddr_un *u = (const struct sockaddr_un *)addr;
    req_t rq = {.op = OP_BIND, .fd = fd, .a0 = 0,
                .a1 = (int64_t)unix_path_port(u->sun_path), .len = 0};
    rep_t rp;
    return (int)rpc(&rq, &rp);
  }
  if (is_vfd(fd) && addr && addr->sa_family == AF_INET) {
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    req_t rq = {.op = OP_BIND, .fd = fd,
                .a0 = (int64_t)ntohl(a->sin_addr.s_addr),
                .a1 = (int64_t)ntohs(a->sin_port), .len = 0};
    rep_t rp;
    return (int)rpc(&rq, &rp);
  }
  static int (*real_bind)(int, const struct sockaddr *, socklen_t);
  if (!real_bind) real_bind = dlsym(RTLD_NEXT, "bind");
  return real_bind(fd, addr, alen);
}

int listen(int fd, int backlog) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) {
    req_t rq = {.op = OP_LISTEN, .fd = fd, .a0 = backlog, .len = 0};
    rep_t rp;
    return (int)rpc(&rq, &rp);
  }
  static int (*real_listen)(int, int);
  if (!real_listen) real_listen = dlsym(RTLD_NEXT, "listen");
  return real_listen(fd, backlog);
}

int accept(int fd, struct sockaddr *addr, socklen_t *alen) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) {
    int user_nb = g_vfd_nonblock[fd - VFD_BASE];
    rep_t rp;
    int64_t r;
    for (;;) {
      req_t rq = {.op = OP_ACCEPT, .fd = fd,
                  .a0 = user_nb || vt_multi(), .len = 0};
      r = rpc(&rq, &rp);
      if (r >= 0 || user_nb || !vt_multi() ||
          (errno != EAGAIN && errno != EWOULDBLOCK))
        break;
      vt_wait_fd(fd, POLLIN);
    }
    if (r >= VFD_BASE && r < VFD_BASE + MAX_VFD) {
      g_vfd_open[r - VFD_BASE] = 1;
      if (addr && alen && *alen >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in a = {0};
        a.sin_family = AF_INET;
        a.sin_addr.s_addr = htonl((uint32_t)rp.vtime_ns); /* unused for MVP */
        *alen = sizeof(a);
        memcpy(addr, &a, sizeof(a));
      }
      return alias_install(r);
    }
    return (int)r;
  }
  static int (*real_accept)(int, struct sockaddr *, socklen_t *);
  if (!real_accept) real_accept = dlsym(RTLD_NEXT, "accept");
  return real_accept(fd, addr, alen);
}

static ssize_t vsend(int fd, const void *buf, size_t n, int flags) {
  size_t chunk = n > MAX_DATA ? MAX_DATA : n;
  int user_nb = g_vfd_nonblock[fd - VFD_BASE];
  for (;;) {
    /* Under the thread gate every op probes nonblocking; a would-block
     * on a BLOCKING socket hands the token off and retries. */
    req_t rq = {.op = OP_SEND, .fd = fd, .a0 = (int64_t)flags,
                .a1 = user_nb || vt_multi(),
                .len = (uint32_t)chunk};
    memcpy(rq.data, buf, chunk);
    rep_t rp;
    ssize_t r = (ssize_t)rpc(&rq, &rp);
    if (r >= 0 || user_nb || !vt_multi() ||
        (errno != EAGAIN && errno != EWOULDBLOCK))
      return r;
    vt_wait_fd(fd, POLLOUT);
  }
}

static ssize_t vrecv(int fd, void *buf, size_t n, int flags) {
  size_t chunk = n > MAX_DATA ? MAX_DATA : n;
  int user_nb = g_vfd_nonblock[fd - VFD_BASE];
  for (;;) {
    req_t rq = {.op = OP_RECV, .fd = fd, .a0 = (int64_t)chunk,
                .a1 = (int64_t)flags |
                      ((user_nb || vt_multi()) ? (1 << 30) : 0),
                .len = 0};
    rep_t rp;
    int64_t r = rpc(&rq, &rp);
    if (r > 0) memcpy(buf, rp.data, (size_t)r);
    if (r >= 0 || user_nb || !vt_multi() ||
        (errno != EAGAIN && errno != EWOULDBLOCK))
      return (ssize_t)r;
    vt_wait_fd(fd, POLLIN);
  }
}

ssize_t send(int fd, const void *buf, size_t n, int flags) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) return vsend(fd, buf, n, flags);
  static ssize_t (*real_send)(int, const void *, size_t, int);
  if (!real_send) real_send = dlsym(RTLD_NEXT, "send");
  return real_send(fd, buf, n, flags);
}

ssize_t sendto(int fd, const void *buf, size_t n, int flags,
               const struct sockaddr *addr, socklen_t alen) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) {
    if (!addr || addr->sa_family != AF_INET)
      return vsend(fd, buf, n, flags);  /* connected-style send */
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    size_t chunk = n > MAX_DATA ? MAX_DATA : n;
    int user_nb = g_vfd_nonblock[fd - VFD_BASE];
    for (;;) {
      req_t rq = {.op = OP_SENDTO, .fd = fd,
                  .a0 = (int64_t)ntohl(a->sin_addr.s_addr),
                  .a1 = (int64_t)ntohs(a->sin_port) |
                        ((int64_t)(user_nb || vt_multi()) << 32),
                  .len = (uint32_t)chunk};
      memcpy(rq.data, buf, chunk);
      rep_t rp;
      ssize_t r = (ssize_t)rpc(&rq, &rp);
      if (r >= 0 || user_nb || !vt_multi() ||
          (errno != EAGAIN && errno != EWOULDBLOCK))
        return r;
      vt_wait_fd(fd, POLLOUT);
    }
  }
  static ssize_t (*real_sendto)(int, const void *, size_t, int,
                                const struct sockaddr *, socklen_t);
  if (!real_sendto) real_sendto = dlsym(RTLD_NEXT, "sendto");
  return real_sendto(fd, buf, n, flags, addr, alen);
}

/* Reply payload: {u32 src_ip, u32 src_port} header + datagram bytes. */
ssize_t recvfrom(int fd, void *buf, size_t n, int flags,
                 struct sockaddr *addr, socklen_t *alen) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) {
    size_t chunk = n > MAX_DATA - 8 ? MAX_DATA - 8 : n;
    int user_nb = g_vfd_nonblock[fd - VFD_BASE];
    rep_t rp;
    int64_t r;
    for (;;) {
      req_t rq = {.op = OP_RECVFROM, .fd = fd, .a0 = (int64_t)chunk,
                  .a1 = (int64_t)flags |
                        ((user_nb || vt_multi()) ? (1 << 30) : 0),
                  .len = 0};
      r = rpc(&rq, &rp);
      if (r >= 0 || user_nb || !vt_multi() ||
          (errno != EAGAIN && errno != EWOULDBLOCK))
        break;
      vt_wait_fd(fd, POLLIN);
    }
    if (r < 0) return (ssize_t)r;
    uint32_t ip = 0, port = 0;
    if (rp.len >= 8) {
      memcpy(&ip, rp.data, 4);
      memcpy(&port, rp.data + 4, 4);
    }
    size_t got = rp.len >= 8 ? rp.len - 8 : 0;
    if (got > n) got = n;
    memcpy(buf, rp.data + 8, got);
    if (addr && alen && *alen >= sizeof(struct sockaddr_in)) {
      struct sockaddr_in a = {0};
      a.sin_family = AF_INET;
      a.sin_addr.s_addr = htonl(ip);
      a.sin_port = htons((uint16_t)port);
      memcpy(addr, &a, sizeof a);
      *alen = sizeof(a);
    }
    return (ssize_t)got;
  }
  static ssize_t (*real_recvfrom)(int, void *, size_t, int,
                                  struct sockaddr *, socklen_t *);
  if (!real_recvfrom) real_recvfrom = dlsym(RTLD_NEXT, "recvfrom");
  return real_recvfrom(fd, buf, n, flags, addr, alen);
}

ssize_t recv(int fd, void *buf, size_t n, int flags) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) return vrecv(fd, buf, n, flags);
  static ssize_t (*real_recv)(int, void *, size_t, int);
  if (!real_recv) real_recv = dlsym(RTLD_NEXT, "recv");
  return real_recv(fd, buf, n, flags);
}

static ssize_t efd_read(int fd, void *buf, size_t n);
static ssize_t efd_write(int fd, const void *buf, size_t n);
static int is_efd_fwd(int fd);
static int efd_poll_fill(struct pollfd *fds, nfds_t nfds);
static void efd_release(int fd);

ssize_t read(int fd, void *buf, size_t n) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) return vrecv(fd, buf, n, 0);
  if (is_tfd(fd)) return tfd_read(fd, buf, n);
  if (is_efd_fwd(fd)) return efd_read(fd, buf, n);
  return real_read(fd, buf, n);
}

ssize_t write(int fd, const void *buf, size_t n) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) return vsend(fd, buf, n, 0);
  if (is_efd_fwd(fd)) return efd_write(fd, buf, n);
  return real_write(fd, buf, n);
}

int close(int fd) {
  if (fd >= 0 && fd < MAX_ALIAS && g_alias2vfd[fd]) {
    int v = g_alias2vfd[fd];
    g_alias2vfd[fd] = 0;
    real_close(fd);        /* release the reserved kernel fd */
    fd = v;
  }
  if (is_vfd(fd)) {
    if (g_vfd_refs[fd - VFD_BASE] > 0) {
      g_vfd_refs[fd - VFD_BASE]--;  /* another alias still references it */
      return 0;
    }
    g_vfd_open[fd - VFD_BASE] = 0;
    req_t rq = {.op = OP_CLOSE, .fd = fd, .len = 0};
    rep_t rp;
    return (int)rpc(&rq, &rp);
  }
  if (fd >= EPFD_BASE && fd < EPFD_BASE + MAX_EPFD) {
    g_ep[fd - EPFD_BASE].used = 0;  /* epoll instance is shim-local */
    return 0;
  }
  if (fd >= TFD_BASE && fd < TFD_BASE + MAX_TFD) {
    g_tfd[fd - TFD_BASE].used = 0;  /* timerfd is shim-local */
    return 0;
  }
  if (is_efd_fwd(fd)) {
    efd_release(fd);  /* eventfd is shim-local */
    return 0;
  }
  return real_close(fd);
}

/* dup family over virtual sockets: each duplicate is one more low-fd
 * alias of the same vfd; the bridge socket survives until the LAST
 * alias closes (g_vfd_refs).  Shim-local timerfd/eventfd/epoll objects
 * have no alias machinery -- duplicating one fails loudly rather than
 * handing back a kernel fd that routes nowhere. */
static int shimlocal_nodup(int fd, const char *who) {
  if (is_tfd(fd) || is_efd_fwd(fd) ||
      (fd >= EPFD_BASE && fd < EPFD_BASE + MAX_EPFD)) {
    fprintf(stderr, "[shadow1-shim] %s(%d): duplicating a virtual "
                    "timerfd/eventfd/epoll fd is not supported\n",
            who, fd);
    errno = EBADF;
    return 1;
  }
  return 0;
}

int dup(int fd) {
  int v = vfd_promote(fd);
  if (is_vfd(v)) {
    int a = alias_install((int64_t)v);  /* may fall back to the raw id */
    g_vfd_refs[v - VFD_BASE]++;
    return a;
  }
  if (shimlocal_nodup(v, "dup")) return -1;
  static int (*real_dup)(int);
  if (!real_dup) real_dup = dlsym(RTLD_NEXT, "dup");
  return real_dup(fd);
}

static int dup2_impl(int oldfd, int newfd, const char *who) {
  int v = vfd_promote(oldfd);
  if (is_vfd(v)) {
    if (newfd == oldfd) return newfd;
    if (newfd < 0 || newfd >= MAX_ALIAS) {
      errno = EBADF;
      return -1;
    }
    if (vfd_promote(newfd) == v) return newfd;  /* already that alias */
    close(newfd);  /* releases whatever lived there (alias or real) */
    /* Pin the target number with a reserved kernel fd, then point the
     * alias table at the vfd. */
    int nul = open("/dev/null", O_RDONLY | O_CLOEXEC);
    if (nul != newfd) {
      static int (*real_dup2)(int, int);
      if (!real_dup2) real_dup2 = dlsym(RTLD_NEXT, "dup2");
      if (nul < 0 || real_dup2(nul, newfd) < 0) {
        if (nul >= 0) real_close(nul);
        errno = EBADF;
        return -1;
      }
      real_close(nul);
    }
    g_alias2vfd[newfd] = v;
    g_vfd_refs[v - VFD_BASE]++;
    return newfd;
  }
  if (shimlocal_nodup(v, who)) return -1;
  static int (*real_d2)(int, int);
  if (!real_d2) real_d2 = dlsym(RTLD_NEXT, "dup2");
  return real_d2(oldfd, newfd);
}

int dup2(int oldfd, int newfd) { return dup2_impl(oldfd, newfd, "dup2"); }

int dup3(int oldfd, int newfd, int flags) {
  (void)flags;  /* O_CLOEXEC is moot: exec under the shim is refused */
  if (oldfd == newfd) {
    errno = EINVAL;
    return -1;
  }
  return dup2_impl(oldfd, newfd, "dup3");
}

int setsockopt(int fd, int level, int name, const void *val, socklen_t len) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) return 0; /* accepted, modeled elsewhere */
  static int (*real_so)(int, int, int, const void *, socklen_t);
  if (!real_so) real_so = dlsym(RTLD_NEXT, "setsockopt");
  return real_so(fd, level, name, val, len);
}

int getsockopt(int fd, int level, int name, void *val, socklen_t *len) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) {
    if (level == SOL_SOCKET && name == SO_ERROR && val && len &&
        *len >= sizeof(int)) {
      /* Serve (and clear, like Linux) the pending error cached from the
       * last poll reply -- the nonblocking-connect failure path. */
      *(int *)val = g_vfd_soerr[fd - VFD_BASE];
      g_vfd_soerr[fd - VFD_BASE] = 0;
      *len = sizeof(int);
      return 0;
    }
    return 0;
  }
  static int (*real_go)(int, int, int, void *, socklen_t *);
  if (!real_go) real_go = dlsym(RTLD_NEXT, "getsockopt");
  return real_go(fd, level, name, val, len);
}

int fcntl(int fd, int cmd, ...) {
  fd = vfd_promote(fd);
  va_list ap;
  va_start(ap, cmd);
  long arg = va_arg(ap, long);
  va_end(ap);
  if (is_vfd(fd)) {
    if (cmd == F_SETFL) {
      g_vfd_nonblock[fd - VFD_BASE] = (arg & O_NONBLOCK) != 0;
      return 0;
    }
    if (cmd == F_GETFL)
      return g_vfd_nonblock[fd - VFD_BASE] ? O_NONBLOCK : 0;
    return 0;
  }
  static int (*real_fcntl)(int, int, ...);
  if (!real_fcntl) real_fcntl = dlsym(RTLD_NEXT, "fcntl");
  return real_fcntl(fd, cmd, arg);
}

/* poll over virtual fds: the readiness multiplexing real event-driven
 * clients are written around (reference epoll.c:638-671 tryNotify; the
 * sim answers with the sockets' transport-register state).  Entries for
 * non-virtual fds are reported not-ready (revents 0) -- plugin loops
 * under the shim only ever wait on simulated sockets.  Wire format:
 * request data = nfds x {int32 fd, int32 events}, a0 = timeout_ms;
 * reply data = nfds x {int32 revents, int32 soerr}, ret = #ready. */
/* Timerfd readiness is local (virtual-clock page); fill revents for tfd
 * entries at time `now`, returning how many are ready. */
static int tfd_fill(struct pollfd *fds, nfds_t nfds, int64_t now) {
  int n = 0;
  for (nfds_t i = 0; i < nfds; i++) {
    if (!is_tfd(fds[i].fd)) continue;
    tfd_t *t = &g_tfd[fds[i].fd - TFD_BASE];
    fds[i].revents = 0;
    if (t->expiry_ns != 0 && now >= t->expiry_ns &&
        (fds[i].events & POLLIN)) {
      fds[i].revents = POLLIN;
      n++;
    }
  }
  return n;
}

static int poll_impl(struct pollfd *fds, nfds_t nfds, int timeout) {
  if (vt_multi() && g_seq_fd >= 0 && timeout != 0 &&
      nfds <= MAX_DATA / 8) {
    /* Thread-gate mode: probe with timeout 0 (the normal body below,
     * which handles vfd/timerfd/real mixes), hand the token off while
     * not ready.  The union park watches this thread's whole entry set
     * plus the earliest timerfd expiry / caller deadline. */
    int64_t caller_dl = VT_NO_DEADLINE;
    if (timeout > 0) caller_dl = vnow() + (int64_t)timeout * 1000000LL;
    for (;;) {
      int r = poll_impl(fds, nfds, 0);
      if (r != 0) return r;
      if (caller_dl != VT_NO_DEADLINE && vnow() >= caller_dl) return 0;
      /* Record only the CALLER's deadline; the union park folds the
       * watched timerfds' live expiries itself (so a sibling re-arming
       * a timer while we are parked retimes the wait). */
      vt_wait_poll(fds, (int)nfds, caller_dl);
    }
  }
  int any_v = 0, any_t = 0, any_e = 0;
  int64_t next_exp = (int64_t)1 << 62;
  for (nfds_t i = 0; i < nfds; i++) {
    /* A CLOSED vfd (in range, g_vfd_open cleared) must still route to
     * the bridge, which answers POLLNVAL for it -- otherwise a set
     * holding only closed vfds would take the OP_SLEEP branch and park
     * forever where Linux returns POLLNVAL immediately. */
    if (fds[i].fd >= VFD_BASE && fds[i].fd < VFD_BASE + MAX_VFD)
      any_v = 1;
    else if (is_efd_fwd(fds[i].fd))
      any_e = 1;
    else if (is_tfd(fds[i].fd)) {
      any_t = 1;
      tfd_t *t = &g_tfd[fds[i].fd - TFD_BASE];
      /* Only a timer the caller can actually observe (POLLIN requested)
       * may bound the wait; otherwise its expiry must not wake poll. */
      if ((fds[i].events & POLLIN) && t->expiry_ns != 0 &&
          t->expiry_ns < next_exp)
        next_exp = t->expiry_ns;
    }
  }
  if (g_seq_fd < 0 || nfds > MAX_DATA / 8) {
    /* Unmanaged, or too many fds to marshal: visible real-poll failure
     * beats a silent virtual sleep over ready simulated fds. */
    static int (*real_poll)(struct pollfd *, nfds_t, int);
    if (!real_poll) real_poll = dlsym(RTLD_NEXT, "poll");
    return real_poll(fds, nfds, timeout);
  }
  if (!any_v && !any_t && !any_e) {
    if (timeout != 0) {
      /* No simulated fds but a wait was requested: sleeping must
       * consume VIRTUAL time (a real sleep here stops the virtual clock
       * and trips the sequencer's wedge watchdog).  Infinite timeout
       * parks forever in sim time (the process is permanently idle). */
      req_t rq = {.op = OP_SLEEP, .fd = -1,
                  .a0 = timeout < 0 ? (int64_t)1 << 62
                                    : (int64_t)timeout * 1000000LL,
                  .len = 0};
      rep_t rp;
      rpc(&rq, &rp);
      for (nfds_t i = 0; i < nfds; i++) fds[i].revents = 0;
      return 0;
    }
    static int (*real_poll0)(struct pollfd *, nfds_t, int);
    if (!real_poll0) real_poll0 = dlsym(RTLD_NEXT, "poll");
    return real_poll0(fds, nfds, 0);
  }

  /* Effective timeout: a pending timerfd expiry (or an already-ready
   * local eventfd) bounds the wait. */
  int64_t now = any_t ? vnow() : 0;
  int t_ready = any_t ? tfd_fill(fds, nfds, now) : 0;
  int e_ready = any_e ? efd_poll_fill(fds, nfds) : 0;
  int eff_timeout = timeout;
  if (any_t) {
    if (t_ready > 0) eff_timeout = 0;
    else if (next_exp < ((int64_t)1 << 62)) {
      int64_t ms = (next_exp - now + 999999) / 1000000;
      if (ms < 1) ms = 1;
      if (ms > 0x7FFFFFFF) ms = 0x7FFFFFFF;  /* far-future: clamp */
      if (timeout < 0 || ms < timeout) eff_timeout = (int)ms;
    }
  }
  if (e_ready > 0) eff_timeout = 0;

  if (!any_v) {
    /* Timerfd/eventfd-only wait: park in virtual time until the expiry
     * (or the caller's timeout), then re-evaluate.  An empty eventfd
     * cannot fire here (single-threaded: only a sibling could write it;
     * gated threads take the vt_multi branch above), so it parks like
     * an unarmed timerfd.  Non-simulated entries report not-ready. */
    for (nfds_t i = 0; i < nfds; i++)
      if (!is_tfd(fds[i].fd) && !is_efd_fwd(fds[i].fd))
        fds[i].revents = 0;
    if (t_ready + e_ready > 0 || eff_timeout == 0)
      return t_ready + e_ready;
    req_t rq = {.op = OP_SLEEP, .fd = -1,
                .a0 = eff_timeout < 0 ? (int64_t)1 << 62
                                      : (int64_t)eff_timeout * 1000000LL,
                .len = 0};
    rep_t rp;
    rpc(&rq, &rp);
    return (any_t ? tfd_fill(fds, nfds, vnow()) : 0) +
           (any_e ? efd_poll_fill(fds, nfds) : 0);
  }

  /* Marshal ONLY simulated-socket entries; timerfds/eventfds are local
   * and real fds are reported not-ready by the bridge contract. */
  req_t rq = {.op = OP_POLL, .fd = -1, .a0 = eff_timeout, .len = 0};
  int32_t *w = (int32_t *)rq.data;
  int widx[MAX_DATA / 8];
  int nw = 0;
  for (nfds_t i = 0; i < nfds; i++) {
    if (is_tfd(fds[i].fd) || is_efd_fwd(fds[i].fd)) continue;
    w[2 * nw] = fds[i].fd;
    w[2 * nw + 1] = fds[i].events;
    widx[nw++] = (int)i;
  }
  rq.len = (uint32_t)(nw * 8);
  rep_t rp;
  int64_t r = rpc(&rq, &rp);
  if (r < 0) return (int)r;
  const int32_t *rv = (const int32_t *)rp.data;
  int total = 0;
  for (int k = 0; k < nw; k++) {
    struct pollfd *p = &fds[widx[k]];
    p->revents = (short)rv[2 * k];
    if (p->revents) total++;
    int soerr = rv[2 * k + 1];
    if (is_vfd(p->fd) && soerr)
      g_vfd_soerr[p->fd - VFD_BASE] = soerr;
  }
  if (any_t) total += tfd_fill(fds, nfds, vnow());
  if (any_e) total += efd_poll_fill(fds, nfds);
  return total;
}

int poll(struct pollfd *fds, nfds_t nfds, int timeout) {
  if (g_seq_fd >= 0 && nfds > 0 && nfds <= MAX_DATA / 8) {
    struct pollfd tr[MAX_DATA / 8];
    int any = 0;
    for (nfds_t i = 0; i < nfds; i++) {
      tr[i] = fds[i];
      tr[i].fd = vfd_promote(fds[i].fd);
      if (tr[i].fd != fds[i].fd) any = 1;
    }
    if (any) {
      int r = poll_impl(tr, nfds, timeout);
      for (nfds_t i = 0; i < nfds; i++) fds[i].revents = tr[i].revents;
      return r;
    }
  }
  return poll_impl(fds, nfds, timeout);
}

/* ---- timerfd (shim-local against the virtual clock) ---- */

int timerfd_create(int clockid, int flags) {
  (void)clockid;
  (void)flags;
  if (g_seq_fd < 0) {
    static int (*real_tc)(int, int);
    if (!real_tc) real_tc = dlsym(RTLD_NEXT, "timerfd_create");
    return real_tc(clockid, flags);
  }
  for (int i = 0; i < MAX_TFD; i++) {
    if (!g_tfd[i].used) {
      g_tfd[i].used = 1;
      g_tfd[i].nonblock = (flags & TFD_NONBLOCK) != 0;
      g_tfd[i].expiry_ns = 0;
      g_tfd[i].interval_ns = 0;
      return TFD_BASE + i;
    }
  }
  errno = EMFILE;
  return -1;
}

int timerfd_settime(int fd, int flags, const struct itimerspec *new_v,
                    struct itimerspec *old_v) {
  if (!is_tfd(fd)) {
    static int (*real_ts)(int, int, const struct itimerspec *,
                          struct itimerspec *);
    if (!real_ts) real_ts = dlsym(RTLD_NEXT, "timerfd_settime");
    return real_ts(fd, flags, new_v, old_v);
  }
  tfd_t *t = &g_tfd[fd - TFD_BASE];
  int64_t now = vnow();
  if (old_v) {
    int64_t rem = t->expiry_ns ? t->expiry_ns - now : 0;
    if (rem < 0) rem = 0;
    old_v->it_value.tv_sec = rem / 1000000000LL;
    old_v->it_value.tv_nsec = rem % 1000000000LL;
    old_v->it_interval.tv_sec = t->interval_ns / 1000000000LL;
    old_v->it_interval.tv_nsec = t->interval_ns % 1000000000LL;
  }
  if (!new_v) { errno = EFAULT; return -1; }
  int64_t val = (int64_t)new_v->it_value.tv_sec * 1000000000LL +
                new_v->it_value.tv_nsec;
  t->interval_ns = (int64_t)new_v->it_interval.tv_sec * 1000000000LL +
                   new_v->it_interval.tv_nsec;
  if (val == 0)
    t->expiry_ns = 0;  /* disarm */
  else
    t->expiry_ns = (flags & 1 /* TFD_TIMER_ABSTIME */) ? val : now + val;
  return 0;
}

int timerfd_gettime(int fd, struct itimerspec *cur) {
  if (!is_tfd(fd)) {
    static int (*real_tg)(int, struct itimerspec *);
    if (!real_tg) real_tg = dlsym(RTLD_NEXT, "timerfd_gettime");
    return real_tg(fd, cur);
  }
  tfd_t *t = &g_tfd[fd - TFD_BASE];
  int64_t rem = t->expiry_ns ? t->expiry_ns - vnow() : 0;
  if (rem < 0) rem = 0;
  cur->it_value.tv_sec = rem / 1000000000LL;
  cur->it_value.tv_nsec = rem % 1000000000LL;
  cur->it_interval.tv_sec = t->interval_ns / 1000000000LL;
  cur->it_interval.tv_nsec = t->interval_ns % 1000000000LL;
  return 0;
}

/* Blocking read on a timerfd parks in VIRTUAL time until expiry, then
 * returns the u64 expiration count (re-arming periodic timers). */
static ssize_t tfd_read(int fd, void *buf, size_t n) {
  if (n < 8) { errno = EINVAL; return -1; }
  tfd_t *t = &g_tfd[fd - TFD_BASE];
  for (;;) {
    int64_t now = vnow();
    if (t->expiry_ns != 0 && now >= t->expiry_ns) {
      uint64_t count = 1;
      if (t->interval_ns > 0) {
        count += (uint64_t)((now - t->expiry_ns) / t->interval_ns);
        t->expiry_ns += (int64_t)count * t->interval_ns;
      } else {
        t->expiry_ns = 0;
      }
      memcpy(buf, &count, 8);
      return 8;
    }
    if (t->nonblock) {
      errno = EAGAIN;
      return -1;
    }
    if (vt_multi()) {
      /* WK_TFD: the union park reads the CURRENT expiry from g_tfd, so
       * a sibling thread re-arming the timer retimes this wait. */
      vt_wait_tfd(fd - TFD_BASE);
      continue;
    }
    int64_t wait_ns = t->expiry_ns == 0 ? (int64_t)1 << 62
                                        : t->expiry_ns - now;
    req_t rq = {.op = OP_SLEEP, .fd = -1, .a0 = wait_ns, .len = 0};
    rep_t rp;
    rpc(&rq, &rp);
  }
}

int shutdown(int fd, int how) {
  fd = vfd_promote(fd);
  if (is_vfd(fd)) {
    req_t rq = {.op = OP_CLOSE, .fd = fd, .a0 = 1 /* half-close */,
                .len = 0};
    rep_t rp;
    return (int)rpc(&rq, &rp);
  }
  static int (*real_shutdown)(int, int);
  if (!real_shutdown) real_shutdown = dlsym(RTLD_NEXT, "shutdown");
  return real_shutdown(fd, how);
}

/* ---- name resolution against the simulator's DNS registry ---- */

int getaddrinfo(const char *node, const char *service,
                const struct addrinfo *hints, struct addrinfo **res) {
  if (g_seq_fd < 0) {
    static int (*real_gai)(const char *, const char *,
                           const struct addrinfo *, struct addrinfo **);
    if (!real_gai) real_gai = dlsym(RTLD_NEXT, "getaddrinfo");
    return real_gai(node, service, hints, res);
  }
  uint32_t ip = 0;
  struct in_addr lit;
  if (node && inet_pton(AF_INET, node, &lit) == 1) {
    ip = ntohl(lit.s_addr);
  } else if (node) {
    req_t rq = {.op = OP_RESOLVE, .fd = -1,
                .len = (uint32_t)strlen(node)};
    if (rq.len >= MAX_DATA) return EAI_NONAME;
    memcpy(rq.data, node, rq.len);
    rep_t rp;
    if (rpc(&rq, &rp) < 0 || rp.len < 4) return EAI_NONAME;
    memcpy(&ip, rp.data, 4);
  }
  int port = service ? atoi(service) : 0;
  int socktype = hints ? hints->ai_socktype : SOCK_STREAM;
  /* One malloc for addrinfo + sockaddr; freeaddrinfo (ours) frees it. */
  struct addrinfo *ai = calloc(1, sizeof(struct addrinfo) +
                               sizeof(struct sockaddr_in));
  if (!ai) return EAI_MEMORY;
  struct sockaddr_in *sa = (struct sockaddr_in *)(ai + 1);
  sa->sin_family = AF_INET;
  sa->sin_addr.s_addr = htonl(ip);
  sa->sin_port = htons((uint16_t)port);
  ai->ai_family = AF_INET;
  ai->ai_socktype = socktype ? socktype : SOCK_STREAM;
  ai->ai_protocol = (ai->ai_socktype == SOCK_DGRAM) ? IPPROTO_UDP
                                                    : IPPROTO_TCP;
  ai->ai_addrlen = sizeof(struct sockaddr_in);
  ai->ai_addr = (struct sockaddr *)sa;
  *res = ai;
  return 0;
}

void freeaddrinfo(struct addrinfo *res) {
  if (g_seq_fd >= 0) {
    free(res);  /* always ours: getaddrinfo above owns all results */
    return;
  }
  static void (*real_fai)(struct addrinfo *);
  if (!real_fai) real_fai = dlsym(RTLD_NEXT, "freeaddrinfo");
  real_fai(res);
}

/* ---- pipes (host-side byte queues; reference channel.c:22-33) ---- */

int pipe(int fds[2]) {
  if (g_seq_fd < 0) {
    static int (*real_pipe)(int[2]);
    if (!real_pipe) real_pipe = dlsym(RTLD_NEXT, "pipe");
    return real_pipe(fds);
  }
  req_t rq = {.op = OP_PIPE, .fd = -1, .len = 0};
  rep_t rp;
  int64_t r = rpc(&rq, &rp);
  if (r < 0 || rp.len < sizeof(int32_t)) return -1;
  int32_t wfd;
  memcpy(&wfd, rp.data, sizeof wfd);
  fds[0] = (int)r;
  fds[1] = wfd;
  if (fds[0] >= VFD_BASE && fds[0] < VFD_BASE + MAX_VFD) {
    g_vfd_open[fds[0] - VFD_BASE] = 1;
    fds[0] = alias_install(fds[0]);
  }
  if (fds[1] >= VFD_BASE && fds[1] < VFD_BASE + MAX_VFD) {
    g_vfd_open[fds[1] - VFD_BASE] = 1;
    fds[1] = alias_install(fds[1]);
  }
  return 0;
}

int pipe2(int fds[2], int flags) {
  int r = pipe(fds);
  if (r == 0 && g_seq_fd >= 0 && (flags & O_NONBLOCK)) {
    g_vfd_nonblock[vfd_promote(fds[0]) - VFD_BASE] = 1;
    g_vfd_nonblock[vfd_promote(fds[1]) - VFD_BASE] = 1;
  }
  return r;
}

/* ---- epoll (shim-local instances over the OP_POLL readiness RPC) ---- */

static int is_epfd(int fd) {
  return fd >= EPFD_BASE && fd < EPFD_BASE + MAX_EPFD && g_ep[fd - EPFD_BASE].used;
}

int epoll_create1(int flags) {
  (void)flags;
  if (g_seq_fd < 0) {
    static int (*real_ec1)(int);
    if (!real_ec1) real_ec1 = dlsym(RTLD_NEXT, "epoll_create1");
    return real_ec1(flags);
  }
  for (int i = 0; i < MAX_EPFD; i++) {
    if (!g_ep[i].used) {
      g_ep[i].used = 1;
      g_ep[i].nwatch = 0;
      return EPFD_BASE + i;
    }
  }
  errno = EMFILE;
  return -1;
}

int epoll_create(int size) {
  (void)size;
  return epoll_create1(0);
}

int epoll_ctl(int epfd, int op, int fd, struct epoll_event *ev) {
  if (!is_epfd(epfd)) {
    static int (*real_ctl)(int, int, int, struct epoll_event *);
    if (!real_ctl) real_ctl = dlsym(RTLD_NEXT, "epoll_ctl");
    return real_ctl(epfd, op, fd, ev);
  }
  epoll_inst_t *e = &g_ep[epfd - EPFD_BASE];
  int at = -1;
  for (int i = 0; i < e->nwatch; i++)
    if (e->wfd[i] == fd) at = i;
  if (op == EPOLL_CTL_DEL) {
    if (at < 0) { errno = ENOENT; return -1; }
    e->nwatch--;
    e->wfd[at] = e->wfd[e->nwatch];
    e->wevents[at] = e->wevents[e->nwatch];
    e->wdata[at] = e->wdata[e->nwatch];
    return 0;
  }
  if (!ev) { errno = EFAULT; return -1; }
  if (ev->events & EPOLLET) { errno = EINVAL; return -1; /* LT only */ }
  if (op == EPOLL_CTL_ADD) {
    if (at >= 0) { errno = EEXIST; return -1; }
    if (e->nwatch >= MAX_WATCH) { errno = ENOSPC; return -1; }
    at = e->nwatch++;
    e->wfd[at] = fd;
  } else if (op == EPOLL_CTL_MOD) {
    if (at < 0) { errno = ENOENT; return -1; }
  } else {
    errno = EINVAL;
    return -1;
  }
  e->wevents[at] = ev->events;
  e->wdata[at] = ev->data;
  return 0;
}

int epoll_wait(int epfd, struct epoll_event *events, int maxevents,
               int timeout) {
  if (!is_epfd(epfd)) {
    static int (*real_wait)(int, struct epoll_event *, int, int);
    if (!real_wait) real_wait = dlsym(RTLD_NEXT, "epoll_wait");
    return real_wait(epfd, events, maxevents, timeout);
  }
  epoll_inst_t *e = &g_ep[epfd - EPFD_BASE];
  if (maxevents <= 0) { errno = EINVAL; return -1; }
  for (;;) {
    struct pollfd pf[MAX_WATCH];
    for (int i = 0; i < e->nwatch; i++) {
      pf[i].fd = e->wfd[i];
      pf[i].events = 0;
      if (e->wevents[i] & EPOLLIN) pf[i].events |= POLLIN;
      if (e->wevents[i] & EPOLLOUT) pf[i].events |= POLLOUT;
      if (e->wevents[i] & EPOLLPRI) pf[i].events |= POLLPRI;
      pf[i].revents = 0;
    }
    int r = poll(pf, e->nwatch, timeout);
    if (r <= 0) return r;
    int n = 0;
    /* Walk backwards so removing a dead fd (swap-with-last) never
     * skips an unvisited entry. */
    for (int i = e->nwatch - 1; i >= 0; i--) {
      if (!pf[i].revents) continue;
      if (pf[i].revents & POLLNVAL) {
        /* Linux silently removes closed fds from epoll sets; the
         * bridge reports them as POLLNVAL.  Mirror the auto-removal
         * so a stale fd can't pin poll() permanently ready. */
        e->nwatch--;
        e->wfd[i] = e->wfd[e->nwatch];
        e->wevents[i] = e->wevents[e->nwatch];
        e->wdata[i] = e->wdata[e->nwatch];
        continue;
      }
      if (n >= maxevents) continue;
      uint32_t rev = 0;
      if (pf[i].revents & POLLIN) rev |= EPOLLIN;
      if (pf[i].revents & POLLOUT) rev |= EPOLLOUT;
      if (pf[i].revents & POLLPRI) rev |= EPOLLPRI;
      if (pf[i].revents & POLLERR) rev |= EPOLLERR;
      if (pf[i].revents & POLLHUP) rev |= EPOLLHUP;
      events[n].events = rev;
      events[n].data = e->wdata[i];
      n++;
    }
    if (n > 0 || timeout == 0) return n;
    /* Every ready entry was a dead fd we just removed: block again
     * (Linux would never have reported them).  A positive timeout is
     * conservatively restarted in full -- the shim's poll runs in
     * virtual time where the remaining-time bookkeeping lives
     * bridge-side. */
  }
}

int epoll_pwait(int epfd, struct epoll_event *events, int maxevents,
                int timeout, const sigset_t *sig) {
  (void)sig;
  if (is_epfd(epfd)) return epoll_wait(epfd, events, maxevents, timeout);
  static int (*real_pwait)(int, struct epoll_event *, int, int,
                           const sigset_t *);
  if (!real_pwait) real_pwait = dlsym(RTLD_NEXT, "epoll_pwait");
  return real_pwait(epfd, events, maxevents, timeout, sig);
}

/* ---- time ---- */

int clock_gettime(clockid_t clk, struct timespec *ts) {
  if (g_seq_fd >= 0 && ts &&
      (clk == CLOCK_REALTIME || clk == CLOCK_MONOTONIC ||
       clk == CLOCK_MONOTONIC_RAW || clk == CLOCK_BOOTTIME)) {
    int64_t t = vnow();
    ts->tv_sec = t / 1000000000LL;
    ts->tv_nsec = t % 1000000000LL;
    return 0;
  }
  return real_clock_gettime(clk, ts);
}

int gettimeofday(struct timeval *tv, void *tz) {
  (void)tz;
  if (g_seq_fd >= 0 && tv) {
    int64_t t = vnow();
    tv->tv_sec = t / 1000000000LL;
    tv->tv_usec = (t % 1000000000LL) / 1000;
    return 0;
  }
  static int (*real_gtod)(struct timeval *, void *);
  if (!real_gtod) real_gtod = dlsym(RTLD_NEXT, "gettimeofday");
  return real_gtod(tv, tz);
}

time_t time(time_t *out) {
  if (g_seq_fd >= 0) {
    time_t t = (time_t)(vnow() / 1000000000LL);
    if (out) *out = t;
    return t;
  }
  static time_t (*real_time)(time_t *);
  if (!real_time) real_time = dlsym(RTLD_NEXT, "time");
  return real_time(out);
}

int nanosleep(const struct timespec *req, struct timespec *rem) {
  if (g_seq_fd >= 0 && req) {
    int64_t dur = (int64_t)req->tv_sec * 1000000000LL + req->tv_nsec;
    if (vt_multi()) {
      int64_t tgt = vnow() + dur;
      while (vnow() < tgt) vt_wait_sleep(tgt);
      if (rem) rem->tv_sec = rem->tv_nsec = 0;
      return 0;
    }
    req_t rq = {.op = OP_SLEEP, .fd = -1, .a0 = dur, .len = 0};
    rep_t rp;
    rpc(&rq, &rp);
    if (rem) rem->tv_sec = rem->tv_nsec = 0;
    return 0;
  }
  return real_nanosleep(req, rem);
}

int usleep(useconds_t us) {
  struct timespec ts = {us / 1000000, (long)(us % 1000000) * 1000};
  return nanosleep(&ts, NULL);
}

unsigned int sleep(unsigned int sec) {
  struct timespec ts = {sec, 0};
  nanosleep(&ts, NULL);
  return 0;
}

/* ================= cooperative virtual threads ========================= */

#define MAX_VT 32
#define MAX_VMX 256
#define MAX_VPOLL_ENT (MAX_DATA / 8)

enum { WK_RUN = 0, WK_FD, WK_POLL, WK_SLEEP, WK_MUTEX, WK_COND,
       WK_JOIN, WK_TFD, WK_SEM };

typedef struct {
  int used, finished, detached;
  int kind;                  /* WK_* */
  int wfd;                   /* WK_FD */
  short wev;
  struct pollfd *pfds;       /* WK_POLL (caller stack; stable while blocked) */
  int pnfds;
  int64_t wake_ns;           /* WK_SLEEP/WK_POLL/WK_COND-timed deadline */
  void *waddr;               /* WK_MUTEX: mutex; WK_COND: cond;
                              * WK_JOIN: target slot as intptr */
  void *(*fn)(void *);
  void *arg;
  void *ret;
  pthread_t os;
  pthread_cond_t cv;
} vt_t;

static vt_t g_vt[MAX_VT];
static volatile int g_vt_on = 0;
static volatile int g_vt_n = 0;     /* live (unfinished) threads */
static int g_vt_cur = 0;            /* token holder slot */
static pthread_mutex_t g_vt_mx = PTHREAD_MUTEX_INITIALIZER;
static __thread int t_self = 0;

/* Real pthread entry points (we interpose the plugin-facing ones). */
static int (*real_pt_create)(pthread_t *, const pthread_attr_t *,
                             void *(*)(void *), void *);
static int (*real_pt_join)(pthread_t, void **);
static int (*real_mxl)(pthread_mutex_t *);
static int (*real_mxu)(pthread_mutex_t *);
static int (*real_mxt)(pthread_mutex_t *);
static int (*real_cw)(pthread_cond_t *, pthread_mutex_t *);
static int (*real_cs)(pthread_cond_t *);
static int (*real_cb)(pthread_cond_t *);

static void vt_resolve_reals(void) {
  if (real_pt_create) return;
  real_pt_create = dlsym(RTLD_NEXT, "pthread_create");
  real_pt_join = dlsym(RTLD_NEXT, "pthread_join");
  real_mxl = dlsym(RTLD_NEXT, "pthread_mutex_lock");
  real_mxu = dlsym(RTLD_NEXT, "pthread_mutex_unlock");
  real_mxt = dlsym(RTLD_NEXT, "pthread_mutex_trylock");
  real_cw = dlsym(RTLD_NEXT, "pthread_cond_wait");
  real_cs = dlsym(RTLD_NEXT, "pthread_cond_signal");
  real_cb = dlsym(RTLD_NEXT, "pthread_cond_broadcast");
}

static int vt_multi(void) { return g_vt_on && g_vt_n > 1; }

/* Virtual mutexes: keyed by address; the gate serializes execution, so a
 * table entry is pure bookkeeping (owner slot + recursion count).  Once a
 * process is managed, plugin mutexes are ALWAYS virtual -- mixing real
 * and virtual locking across the first pthread_create would break mutual
 * exclusion for a mutex held at engagement time. */
typedef struct { void *addr; int owner; int count; } vmx_t;
static vmx_t g_vmx[MAX_VMX];

static vmx_t *vmx_get(void *addr) {
  int free_i = -1;
  for (int i = 0; i < MAX_VMX; i++) {
    if (g_vmx[i].addr == addr) return &g_vmx[i];
    if (!g_vmx[i].addr && free_i < 0) free_i = i;
  }
  if (free_i < 0) {
    fprintf(stderr, "shadow1_shim: virtual-mutex table full (%d)\n",
            MAX_VMX);
    _exit(121);
  }
  g_vmx[free_i].addr = addr;
  g_vmx[free_i].owner = -1;
  g_vmx[free_i].count = 0;
  return &g_vmx[free_i];
}

static int vt_next_runnable(int from) {
  for (int k = 1; k <= MAX_VT; k++) {
    int i = (from + k) % MAX_VT;
    if (g_vt[i].used && !g_vt[i].finished && g_vt[i].kind == WK_RUN)
      return i;
  }
  return -1;
}

/* All threads blocked: one union readiness RPC in the token holder.
 * Called with g_vt_mx held. */
static void vt_union_park(void) {
  req_t rq = {.op = OP_POLL, .fd = -1, .len = 0};
  int32_t *w = (int32_t *)rq.data;
  int map_t[MAX_VPOLL_ENT];
  int nw = 0;
  int64_t min_deadline = VT_NO_DEADLINE;
  int n_blocked = 0, n_sync = 0;
  for (int i = 0; i < MAX_VT; i++) {
    vt_t *t = &g_vt[i];
    if (!t->used || t->finished) continue;
    n_blocked++;
    switch (t->kind) {
      case WK_FD:
        if (nw < MAX_VPOLL_ENT) {
          w[2 * nw] = t->wfd;
          w[2 * nw + 1] = t->wev;
          map_t[nw++] = i;
        }
        break;
      case WK_POLL:
        for (int j = 0; j < t->pnfds && nw < MAX_VPOLL_ENT; j++) {
          int fd = t->pfds[j].fd;
          if (fd >= VFD_BASE && fd < VFD_BASE + MAX_VFD) {
            w[2 * nw] = fd;
            w[2 * nw + 1] = t->pfds[j].events;
            map_t[nw++] = i;
          } else if (is_tfd(fd) && (t->pfds[j].events & POLLIN)) {
            tfd_t *tf = &g_tfd[fd - TFD_BASE];
            if (tf->expiry_ns != 0 && tf->expiry_ns < min_deadline)
              min_deadline = tf->expiry_ns;
          }
        }
        if (t->wake_ns < min_deadline) min_deadline = t->wake_ns;
        break;
      case WK_TFD: {
        tfd_t *tf = &g_tfd[t->wfd];
        if (tf->expiry_ns != 0 && tf->expiry_ns < min_deadline)
          min_deadline = tf->expiry_ns;
        break;
      }
      case WK_SLEEP:
        if (t->wake_ns < min_deadline) min_deadline = t->wake_ns;
        break;
      case WK_COND:
        if (t->wake_ns && t->wake_ns < min_deadline)
          min_deadline = t->wake_ns;  /* timedwait */
        n_sync++;
        break;
      default:
        n_sync++;  /* WK_MUTEX / WK_JOIN: woken only by peers */
    }
  }
  if (nw == 0 && min_deadline == VT_NO_DEADLINE) {
    fprintf(stderr,
            "shadow1_shim: DEADLOCK: all %d plugin threads blocked on "
            "mutex/cond/join with nothing external to wake them\n",
            n_blocked);
    _exit(121);
  }
  int64_t now = vnow();
  rep_t rp;
  if (nw == 0) {
    req_t sq = {.op = OP_SLEEP, .fd = -1,
                .a0 = min_deadline - now > 0 ? min_deadline - now : 1,
                .len = 0};
    rpc(&sq, &rp);
  } else {
    int64_t tmo_ms = -1;
    if (min_deadline != VT_NO_DEADLINE) {
      tmo_ms = (min_deadline - now + 999999) / 1000000;
      if (tmo_ms < 1) tmo_ms = 1;
      if (tmo_ms > 0x7FFFFFFF) tmo_ms = 0x7FFFFFFF;
    }
    rq.a0 = tmo_ms;
    rq.len = (uint32_t)(nw * 8);
    int64_t r = rpc(&rq, &rp);
    if (r >= 0) {
      const int32_t *rv = (const int32_t *)rp.data;
      for (int k = 0; k < nw; k++) {
        int fd = w[2 * k];
        int soerr = rv[2 * k + 1];
        if (soerr && fd >= VFD_BASE && fd < VFD_BASE + MAX_VFD)
          g_vfd_soerr[fd - VFD_BASE] = soerr;
        if (rv[2 * k] != 0) g_vt[map_t[k]].kind = WK_RUN;
      }
    }
  }
  now = vnow();
  for (int i = 0; i < MAX_VT; i++) {
    vt_t *t = &g_vt[i];
    if (!t->used || t->finished) continue;
    if ((t->kind == WK_SLEEP || t->kind == WK_POLL ||
         (t->kind == WK_COND && t->wake_ns)) &&
        t->wake_ns != VT_NO_DEADLINE && t->wake_ns <= now)
      t->kind = WK_RUN;
    if (t->kind == WK_TFD) {
      tfd_t *tf = &g_tfd[t->wfd];
      if (tf->expiry_ns != 0 && tf->expiry_ns <= now) t->kind = WK_RUN;
    }
    if (t->kind == WK_POLL)
      for (int j = 0; j < t->pnfds; j++)
        if (is_tfd(t->pfds[j].fd) && (t->pfds[j].events & POLLIN)) {
          tfd_t *tf = &g_tfd[t->pfds[j].fd - TFD_BASE];
          if (tf->expiry_ns != 0 && tf->expiry_ns <= now)
            t->kind = WK_RUN;
        }
  }
}

/* Block the calling thread until its wait is satisfied.  The caller has
 * already recorded its wait kind/payload; g_vt_mx is held on entry and
 * on exit.  The token is handed round-robin; when nobody is runnable
 * the holder runs the union park. */
static void vt_block_locked(void) {
  for (;;) {
    if (g_vt[t_self].kind == WK_RUN) return;
    int nxt = vt_next_runnable(t_self);
    if (nxt >= 0) {
      g_vt_cur = nxt;
      real_cs(&g_vt[nxt].cv);
      while (g_vt_cur != t_self)
        real_cw(&g_vt[t_self].cv, &g_vt_mx);
    } else {
      vt_union_park();
    }
  }
}

static void vt_wait_fd(int fd, short ev) {
  vt_resolve_reals();
  real_mxl(&g_vt_mx);
  vt_t *t = &g_vt[t_self];
  t->kind = WK_FD;
  t->wfd = fd;
  t->wev = ev;
  t->wake_ns = VT_NO_DEADLINE;
  vt_block_locked();
  real_mxu(&g_vt_mx);
}

static void vt_wait_sleep(int64_t wake_ns) {
  vt_resolve_reals();
  real_mxl(&g_vt_mx);
  vt_t *t = &g_vt[t_self];
  t->kind = WK_SLEEP;
  t->wake_ns = wake_ns;
  vt_block_locked();
  real_mxu(&g_vt_mx);
}

static void vt_wait_poll(struct pollfd *fds, int nfds, int64_t wake_ns) {
  vt_resolve_reals();
  real_mxl(&g_vt_mx);
  vt_t *t = &g_vt[t_self];
  t->kind = WK_POLL;
  t->pfds = fds;
  t->pnfds = nfds;
  t->wake_ns = wake_ns;
  vt_block_locked();
  real_mxu(&g_vt_mx);
}

static void vt_wait_tfd(int tfd_idx) {
  vt_resolve_reals();
  real_mxl(&g_vt_mx);
  vt_t *t = &g_vt[t_self];
  t->kind = WK_TFD;
  t->wfd = tfd_idx;
  t->wake_ns = VT_NO_DEADLINE;
  vt_block_locked();
  real_mxu(&g_vt_mx);
}

/* Thread exit: wake joiners, hand the token on (running the union park
 * ourselves if everyone else is blocked -- we are the token holder). */
static void vt_exit_self(void *ret) {
  real_mxl(&g_vt_mx);
  vt_t *t = &g_vt[t_self];
  t->ret = ret;
  t->finished = 1;
  g_vt_n--;
  if (t->detached) t->used = 0;  /* slot reusable; OS thread self-reaps
                                  * (pthread_detach real-detached it) */
  for (int i = 0; i < MAX_VT; i++)
    if (g_vt[i].used && !g_vt[i].finished && g_vt[i].kind == WK_JOIN &&
        (intptr_t)g_vt[i].waddr == t_self)
      g_vt[i].kind = WK_RUN;
  for (;;) {
    int nxt = vt_next_runnable(t_self);
    if (nxt >= 0) {
      g_vt_cur = nxt;
      real_cs(&g_vt[nxt].cv);
      break;
    }
    if (g_vt_n == 0) break;        /* nobody left to run */
    vt_union_park();
  }
  real_mxu(&g_vt_mx);
}

static void *vt_tramp(void *vp) {
  vt_t *t = (vt_t *)vp;
  t_self = (int)(t - g_vt);
  real_mxl(&g_vt_mx);
  while (g_vt_cur != t_self)
    real_cw(&t->cv, &g_vt_mx);
  real_mxu(&g_vt_mx);
  void *ret = t->fn(t->arg);
  vt_exit_self(ret);
  return ret;
}

int pthread_create(pthread_t *tid, const pthread_attr_t *attr,
                   void *(*fn)(void *), void *arg) {
  vt_resolve_reals();
  if (g_seq_fd < 0) return real_pt_create(tid, attr, fn, arg);
  real_mxl(&g_vt_mx);
  if (!g_vt_on) {
    /* Engage the gate: the calling (main) thread takes slot 0. */
    memset(&g_vt[0], 0, sizeof(g_vt[0]));
    g_vt[0].used = 1;
    g_vt[0].kind = WK_RUN;
    g_vt[0].os = pthread_self();
    pthread_cond_init(&g_vt[0].cv, NULL);
    g_vt_cur = 0;
    g_vt_n = 1;
    g_vt_on = 1;
  }
  int i;
  for (i = 1; i < MAX_VT; i++)
    if (!g_vt[i].used) break;
  if (i >= MAX_VT) {
    real_mxu(&g_vt_mx);
    fprintf(stderr, "shadow1_shim: pthread_create: thread table full "
                    "(%d)\n", MAX_VT);
    return EAGAIN;
  }
  vt_t *t = &g_vt[i];
  memset(t, 0, sizeof(*t));
  t->used = 1;
  t->kind = WK_RUN;
  t->fn = fn;
  t->arg = arg;
  pthread_cond_init(&t->cv, NULL);
  g_vt_n++;
  real_mxu(&g_vt_mx);
  int r = real_pt_create(&t->os, attr, vt_tramp, t);
  if (r != 0) {
    real_mxl(&g_vt_mx);
    t->used = 0;
    g_vt_n--;
    real_mxu(&g_vt_mx);
    return r;
  }
  if (tid) *tid = t->os;
  return 0;
}

static int vt_find(pthread_t tid) {
  for (int i = 0; i < MAX_VT; i++)
    if (g_vt[i].used && pthread_equal(g_vt[i].os, tid)) return i;
  return -1;
}

int pthread_join(pthread_t tid, void **ret) {
  vt_resolve_reals();
  if (g_seq_fd < 0 || !g_vt_on) return real_pt_join(tid, ret);
  real_mxl(&g_vt_mx);
  int i = vt_find(tid);
  if (i < 0) {
    real_mxu(&g_vt_mx);
    return real_pt_join(tid, ret);
  }
  while (!g_vt[i].finished) {
    g_vt[t_self].kind = WK_JOIN;
    g_vt[t_self].waddr = (void *)(intptr_t)i;
    vt_block_locked();
  }
  if (ret) *ret = g_vt[i].ret;
  pthread_t os = g_vt[i].os;
  g_vt[i].used = 0;
  real_mxu(&g_vt_mx);
  real_pt_join(os, NULL);  /* reap the finished OS thread */
  return 0;
}

int pthread_detach(pthread_t tid) {
  vt_resolve_reals();
  if (g_seq_fd < 0 || !g_vt_on) {
    static int (*real_det)(pthread_t);
    if (!real_det) real_det = dlsym(RTLD_NEXT, "pthread_detach");
    return real_det(tid);
  }
  static int (*real_det2)(pthread_t);
  if (!real_det2) real_det2 = dlsym(RTLD_NEXT, "pthread_detach");
  real_mxl(&g_vt_mx);
  int i = vt_find(tid);
  if (i >= 0) {
    g_vt[i].detached = 1;
    if (g_vt[i].finished) g_vt[i].used = 0;
  }
  real_mxu(&g_vt_mx);
  real_det2(tid);  /* the OS thread self-reaps on termination */
  return 0;
}

int pthread_mutex_lock(pthread_mutex_t *m) {
  vt_resolve_reals();
  if (g_seq_fd < 0) return real_mxl(m);
  real_mxl(&g_vt_mx);
  vmx_t *v = vmx_get(m);
  for (;;) {
    if (v->owner < 0 || v->owner == t_self) {
      v->owner = t_self;
      v->count++;
      break;
    }
    g_vt[t_self].kind = WK_MUTEX;
    g_vt[t_self].waddr = m;
    vt_block_locked();
  }
  real_mxu(&g_vt_mx);
  return 0;
}

int pthread_mutex_trylock(pthread_mutex_t *m) {
  vt_resolve_reals();
  if (g_seq_fd < 0) return real_mxt(m);
  real_mxl(&g_vt_mx);
  vmx_t *v = vmx_get(m);
  int r = 0;
  if (v->owner < 0 || v->owner == t_self) {
    v->owner = t_self;
    v->count++;
  } else {
    r = EBUSY;
  }
  real_mxu(&g_vt_mx);
  return r;
}

static void vmx_release(vmx_t *v) {
  v->owner = -1;
  v->count = 0;
  /* wake the first waiter in slot order (deterministic) */
  for (int i = 0; i < MAX_VT; i++)
    if (g_vt[i].used && !g_vt[i].finished && g_vt[i].kind == WK_MUTEX &&
        g_vt[i].waddr == v->addr) {
      g_vt[i].kind = WK_RUN;
      break;
    }
}

int pthread_mutex_unlock(pthread_mutex_t *m) {
  vt_resolve_reals();
  if (g_seq_fd < 0) return real_mxu(m);
  real_mxl(&g_vt_mx);
  vmx_t *v = vmx_get(m);
  if (v->owner == t_self) {
    if (--v->count <= 0) vmx_release(v);
  }
  real_mxu(&g_vt_mx);
  return 0;
}

int pthread_mutex_destroy(pthread_mutex_t *m) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_des)(pthread_mutex_t *);
    if (!real_des) real_des = dlsym(RTLD_NEXT, "pthread_mutex_destroy");
    return real_des(m);
  }
  real_mxl(&g_vt_mx);
  for (int i = 0; i < MAX_VMX; i++)
    if (g_vmx[i].addr == (void *)m) {
      g_vmx[i].addr = NULL;
      break;
    }
  real_mxu(&g_vt_mx);
  return 0;
}

static int vt_cond_wait_common(pthread_cond_t *c, pthread_mutex_t *m,
                               int64_t deadline_ns) {
  real_mxl(&g_vt_mx);
  if (!g_vt_on || g_vt_n <= 1) {
    if (deadline_ns == 0) {
      fprintf(stderr, "shadow1_shim: DEADLOCK: pthread_cond_wait with no "
                      "other thread to signal\n");
      _exit(121);
    }
    /* Timed wait, single thread: pure virtual sleep to the deadline. */
    real_mxu(&g_vt_mx);
    int64_t now = vnow();
    if (deadline_ns > now) {
      struct timespec ts = {.tv_sec = (deadline_ns - now) / 1000000000LL,
                            .tv_nsec = (deadline_ns - now) % 1000000000LL};
      nanosleep(&ts, NULL);
    }
    return ETIMEDOUT;
  }
  vmx_t *v = vmx_get(m);
  int saved = v->count;
  if (v->owner == t_self) vmx_release(v);
  vt_t *t = &g_vt[t_self];
  t->kind = WK_COND;
  t->waddr = c;
  t->wake_ns = deadline_ns;  /* 0 = untimed */
  vt_block_locked();
  int timed_out = deadline_ns != 0 && vnow() >= deadline_ns &&
                  t->waddr != NULL;  /* waddr cleared by signal */
  /* re-acquire the mutex */
  for (;;) {
    if (v->owner < 0) {
      v->owner = t_self;
      v->count = saved > 0 ? saved : 1;
      break;
    }
    t->kind = WK_MUTEX;
    t->waddr = m;
    vt_block_locked();
  }
  real_mxu(&g_vt_mx);
  return timed_out ? ETIMEDOUT : 0;
}

int pthread_cond_wait(pthread_cond_t *c, pthread_mutex_t *m) {
  vt_resolve_reals();
  if (g_seq_fd < 0) return real_cw(c, m);
  return vt_cond_wait_common(c, m, 0);
}

int pthread_cond_timedwait(pthread_cond_t *c, pthread_mutex_t *m,
                           const struct timespec *abs) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_ctw)(pthread_cond_t *, pthread_mutex_t *,
                           const struct timespec *);
    if (!real_ctw) real_ctw = dlsym(RTLD_NEXT, "pthread_cond_timedwait");
    return real_ctw(c, m, abs);
  }
  /* abs is CLOCK_REALTIME, which this shim serves directly from the
   * virtual clock (clock_gettime above returns vnow()), so the deadline
   * is already in virtual-ns. */
  int64_t abs_ns = (int64_t)abs->tv_sec * 1000000000LL + abs->tv_nsec;
  if (abs_ns < 1) abs_ns = 1;
  return vt_cond_wait_common(c, m, abs_ns);
}

static void vt_cond_wake(pthread_cond_t *c, int all) {
  real_mxl(&g_vt_mx);
  for (int i = 0; i < MAX_VT; i++)
    if (g_vt[i].used && !g_vt[i].finished && g_vt[i].kind == WK_COND &&
        g_vt[i].waddr == (void *)c) {
      g_vt[i].kind = WK_RUN;
      g_vt[i].waddr = NULL;  /* signaled (distinguishes from timeout) */
      if (!all) break;
    }
  real_mxu(&g_vt_mx);
}

int pthread_cond_signal(pthread_cond_t *c) {
  vt_resolve_reals();
  if (g_seq_fd < 0) return real_cs(c);
  vt_cond_wake(c, 0);
  return 0;
}

int pthread_cond_broadcast(pthread_cond_t *c) {
  vt_resolve_reals();
  if (g_seq_fd < 0) return real_cb(c);
  vt_cond_wake(c, 1);
  return 0;
}

/* Unsupported thread operations fail loudly (never hang). */
int pthread_cancel(pthread_t tid) {
  (void)tid;
  if (g_seq_fd < 0) {
    static int (*real_can)(pthread_t);
    if (!real_can) real_can = dlsym(RTLD_NEXT, "pthread_cancel");
    return real_can(tid);
  }
  fprintf(stderr, "shadow1_shim: pthread_cancel is not supported under "
                  "the simulation (deterministic cancellation points "
                  "are not modeled)\n");
  return ENOSYS;
}

/* A thread exiting via pthread_exit must leave the gate exactly like a
 * start-routine return, or it would die holding the token and wedge
 * every sibling. */
void pthread_exit(void *ret) {
  vt_resolve_reals();
  static void (*real_exit)(void *) __attribute__((noreturn));
  if (!real_exit) {
    *(void **)&real_exit = dlsym(RTLD_NEXT, "pthread_exit");
  }
  if (g_seq_fd >= 0 && g_vt_on) vt_exit_self(ret);
  real_exit(ret);
}

/* Semaphores: real sem_wait would block the OS thread while holding the
 * token; virtualize them like mutexes (table keyed by address). */
#define MAX_VSEM 128
typedef struct { void *addr; int count; } vsem_t;
static vsem_t g_vsem[MAX_VSEM];

static vsem_t *vsem_get(void *addr, int create_count) {
  int free_i = -1;
  for (int i = 0; i < MAX_VSEM; i++) {
    if (g_vsem[i].addr == addr) return &g_vsem[i];
    if (!g_vsem[i].addr && free_i < 0) free_i = i;
  }
  if (free_i < 0) {
    fprintf(stderr, "shadow1_shim: virtual-semaphore table full\n");
    _exit(121);
  }
  g_vsem[free_i].addr = addr;
  g_vsem[free_i].count = create_count;
  return &g_vsem[free_i];
}

int sem_init(sem_t *s, int pshared, unsigned value) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_si)(sem_t *, int, unsigned);
    if (!real_si) real_si = dlsym(RTLD_NEXT, "sem_init");
    return real_si(s, pshared, value);
  }
  (void)pshared;
  real_mxl(&g_vt_mx);
  vsem_t *v = vsem_get(s, 0);
  v->count = (int)value;
  real_mxu(&g_vt_mx);
  return 0;
}

int sem_wait(sem_t *s) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_sw)(sem_t *);
    if (!real_sw) real_sw = dlsym(RTLD_NEXT, "sem_wait");
    return real_sw(s);
  }
  real_mxl(&g_vt_mx);
  vsem_t *v = vsem_get(s, 0);
  while (v->count <= 0) {
    g_vt[t_self].kind = WK_SEM;
    g_vt[t_self].waddr = s;
    vt_block_locked();
  }
  v->count--;
  real_mxu(&g_vt_mx);
  return 0;
}

int sem_trywait(sem_t *s) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_st)(sem_t *);
    if (!real_st) real_st = dlsym(RTLD_NEXT, "sem_trywait");
    return real_st(s);
  }
  real_mxl(&g_vt_mx);
  vsem_t *v = vsem_get(s, 0);
  int r = 0;
  if (v->count > 0) v->count--;
  else { errno = EAGAIN; r = -1; }
  real_mxu(&g_vt_mx);
  return r;
}

int sem_post(sem_t *s) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_sp)(sem_t *);
    if (!real_sp) real_sp = dlsym(RTLD_NEXT, "sem_post");
    return real_sp(s);
  }
  real_mxl(&g_vt_mx);
  vsem_t *v = vsem_get(s, 0);
  v->count++;
  for (int i = 0; i < MAX_VT; i++)
    if (g_vt[i].used && !g_vt[i].finished && g_vt[i].kind == WK_SEM &&
        g_vt[i].waddr == (void *)s) {
      g_vt[i].kind = WK_RUN;
      break;
    }
  real_mxu(&g_vt_mx);
  return 0;
}

int sem_destroy(sem_t *s) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_sd)(sem_t *);
    if (!real_sd) real_sd = dlsym(RTLD_NEXT, "sem_destroy");
    return real_sd(s);
  }
  real_mxl(&g_vt_mx);
  for (int i = 0; i < MAX_VSEM; i++)
    if (g_vsem[i].addr == (void *)s) { g_vsem[i].addr = NULL; break; }
  real_mxu(&g_vt_mx);
  return 0;
}

/* rwlocks: serialized execution makes the read/write distinction moot;
 * treat both sides as the exclusive virtual mutex keyed by address
 * (strictly safe: never admits an interleaving real rwlocks would
 * forbid).  Unmanaged processes keep the real rwlock (the virtual
 * mutex path only ever uses the ADDRESS, but the unmanaged fallback in
 * pthread_mutex_lock would dereference it as a mutex). */
static int vrw_lock(pthread_rwlock_t *rw, const char *real_name,
                    int try_only) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    int (*real_fn)(pthread_rwlock_t *) = dlsym(RTLD_NEXT, real_name);
    return real_fn(rw);
  }
  real_mxl(&g_vt_mx);
  vmx_t *v = vmx_get(rw);
  int r = 0;
  for (;;) {
    if (v->owner < 0 || v->owner == t_self) {
      v->owner = t_self;
      v->count++;
      break;
    }
    if (try_only) { r = EBUSY; break; }
    g_vt[t_self].kind = WK_MUTEX;
    g_vt[t_self].waddr = rw;
    vt_block_locked();
  }
  real_mxu(&g_vt_mx);
  return r;
}
int pthread_rwlock_rdlock(pthread_rwlock_t *rw) {
  return vrw_lock(rw, "pthread_rwlock_rdlock", 0);
}
int pthread_rwlock_wrlock(pthread_rwlock_t *rw) {
  return vrw_lock(rw, "pthread_rwlock_wrlock", 0);
}
int pthread_rwlock_tryrdlock(pthread_rwlock_t *rw) {
  return vrw_lock(rw, "pthread_rwlock_tryrdlock", 1);
}
int pthread_rwlock_trywrlock(pthread_rwlock_t *rw) {
  return vrw_lock(rw, "pthread_rwlock_trywrlock", 1);
}
int pthread_rwlock_unlock(pthread_rwlock_t *rw) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_ru)(pthread_rwlock_t *);
    if (!real_ru) real_ru = dlsym(RTLD_NEXT, "pthread_rwlock_unlock");
    return real_ru(rw);
  }
  real_mxl(&g_vt_mx);
  vmx_t *v = vmx_get(rw);
  if (v->owner == t_self && --v->count <= 0) vmx_release(v);
  real_mxu(&g_vt_mx);
  return 0;
}

/* Barriers: count arrivals; the last arrival releases the cohort. */
#define MAX_VBAR 32
typedef struct { void *addr; unsigned needed, arrived; } vbar_t;
static vbar_t g_vbar[MAX_VBAR];

int pthread_barrier_init(pthread_barrier_t *b,
                         const pthread_barrierattr_t *attr, unsigned n) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_bi)(pthread_barrier_t *,
                          const pthread_barrierattr_t *, unsigned);
    if (!real_bi) real_bi = dlsym(RTLD_NEXT, "pthread_barrier_init");
    return real_bi(b, attr, n);
  }
  (void)attr;
  real_mxl(&g_vt_mx);
  int free_i = -1;
  for (int i = 0; i < MAX_VBAR; i++) {
    if (g_vbar[i].addr == (void *)b) { free_i = i; break; }
    if (!g_vbar[i].addr && free_i < 0) free_i = i;
  }
  if (free_i < 0) {
    real_mxu(&g_vt_mx);
    fprintf(stderr, "shadow1_shim: barrier table full\n");
    _exit(121);
  }
  g_vbar[free_i].addr = b;
  g_vbar[free_i].needed = n;
  g_vbar[free_i].arrived = 0;
  real_mxu(&g_vt_mx);
  return 0;
}

int pthread_barrier_wait(pthread_barrier_t *b) {
  vt_resolve_reals();
  if (g_seq_fd < 0) {
    static int (*real_bw)(pthread_barrier_t *);
    if (!real_bw) real_bw = dlsym(RTLD_NEXT, "pthread_barrier_wait");
    return real_bw(b);
  }
  real_mxl(&g_vt_mx);
  vbar_t *v = NULL;
  for (int i = 0; i < MAX_VBAR; i++)
    if (g_vbar[i].addr == (void *)b) v = &g_vbar[i];
  if (!v) {
    real_mxu(&g_vt_mx);
    fprintf(stderr, "shadow1_shim: pthread_barrier_wait on uninitialized "
                    "barrier\n");
    _exit(121);
  }
  if (++v->arrived >= v->needed) {
    v->arrived = 0;
    for (int i = 0; i < MAX_VT; i++)
      if (g_vt[i].used && !g_vt[i].finished &&
          g_vt[i].kind == WK_COND && g_vt[i].waddr == (void *)b)
        g_vt[i].kind = WK_RUN;
    real_mxu(&g_vt_mx);
    return PTHREAD_BARRIER_SERIAL_THREAD;
  }
  g_vt[t_self].kind = WK_COND;  /* barrier waiters ride the cond kind */
  g_vt[t_self].waddr = b;
  g_vt[t_self].wake_ns = 0;
  vt_block_locked();
  real_mxu(&g_vt_mx);
  return 0;
}

/* pthread_once: the real one parks waiters on a futex; under the gate a
 * blocked init routine would wedge them.  Serial execution makes a flag
 * table sufficient (the init body itself may block virtually). */
#define MAX_VONCE 128
static struct { void *addr; int state; } g_vonce[MAX_VONCE];

int pthread_once(pthread_once_t *ctl, void (*init)(void)) {
  if (g_seq_fd < 0) {
    static int (*real_on)(pthread_once_t *, void (*)(void));
    if (!real_on) real_on = dlsym(RTLD_NEXT, "pthread_once");
    return real_on(ctl, init);
  }
  vt_resolve_reals();
  real_mxl(&g_vt_mx);
  int slot = -1;
  for (int i = 0; i < MAX_VONCE; i++) {
    if (g_vonce[i].addr == (void *)ctl) { slot = i; break; }
    if (!g_vonce[i].addr && slot < 0) slot = i;
  }
  if (slot < 0) {
    real_mxu(&g_vt_mx);
    fprintf(stderr, "shadow1_shim: pthread_once table full\n");
    _exit(121);
  }
  if (g_vonce[slot].addr == (void *)ctl && g_vonce[slot].state == 2) {
    real_mxu(&g_vt_mx);
    return 0;
  }
  if (g_vonce[slot].addr == (void *)ctl && g_vonce[slot].state == 1) {
    /* another thread is inside init (it blocked virtually): wait on the
     * control address like a cond */
    while (g_vonce[slot].state == 1) {
      g_vt[t_self].kind = WK_COND;
      g_vt[t_self].waddr = ctl;
      g_vt[t_self].wake_ns = 0;
      vt_block_locked();
    }
    real_mxu(&g_vt_mx);
    return 0;
  }
  g_vonce[slot].addr = ctl;
  g_vonce[slot].state = 1;
  real_mxu(&g_vt_mx);
  init();
  real_mxl(&g_vt_mx);
  g_vonce[slot].state = 2;
  for (int i = 0; i < MAX_VT; i++)
    if (g_vt[i].used && !g_vt[i].finished && g_vt[i].kind == WK_COND &&
        g_vt[i].waddr == (void *)ctl)
      g_vt[i].kind = WK_RUN;
  real_mxu(&g_vt_mx);
  return 0;
}

/* ================= syscall-surface breadth (round 5) =================== */

/* select/pselect lower onto poll(), inheriting virtual time, the thread
 * gate, and the bridge's readiness model (reference process_emu_select
 * family). */
#include <sys/select.h>

int select(int nfds, fd_set *rd, fd_set *wr, fd_set *ex,
           struct timeval *tv) {
  if (g_seq_fd < 0) {
    static int (*real_sel)(int, fd_set *, fd_set *, fd_set *,
                           struct timeval *);
    if (!real_sel) real_sel = dlsym(RTLD_NEXT, "select");
    return real_sel(nfds, rd, wr, ex, tv);
  }
  /* Simulated sockets are handed out as LOW alias fds precisely so
   * they fit fd_set; poll() promotes them. */
  struct pollfd pf[FD_SETSIZE];
  int np = 0;
  for (int fd = 0; fd < nfds && fd < FD_SETSIZE; fd++) {
    short ev = 0;
    if (rd && FD_ISSET(fd, rd)) ev |= POLLIN;
    if (wr && FD_ISSET(fd, wr)) ev |= POLLOUT;
    if (ex && FD_ISSET(fd, ex)) ev |= POLLPRI;
    if (!ev) continue;
    pf[np].fd = fd;
    pf[np].events = ev;
    pf[np].revents = 0;
    np++;
  }
  int timeout = -1;
  if (tv) {
    /* Round sub-millisecond timeouts UP: truncation turned a 100us
     * select loop into timeout=0 (pure poll), which spins without
     * consuming virtual time and can trip the sequencer wedge
     * watchdog.  A nonzero timeout always lowers to >= 1ms. */
    long long ms = (long long)tv->tv_sec * 1000 + (tv->tv_usec + 999) / 1000;
    if (ms > 0x7FFFFFFF) ms = 0x7FFFFFFF;
    timeout = (int)ms;
  }
  int r = poll(pf, (nfds_t)np, timeout);
  if (r < 0) return r;
  if (rd) FD_ZERO(rd);
  if (wr) FD_ZERO(wr);
  if (ex) FD_ZERO(ex);
  int total = 0;
  for (int k = 0; k < np; k++) {
    int fd = pf[k].fd;
    int hit = 0;
    if (pf[k].revents & (POLLIN | POLLHUP | POLLERR)) {
      if (rd) { FD_SET(fd, rd); hit = 1; }
    }
    if (pf[k].revents & (POLLOUT | POLLERR)) {
      if (wr) { FD_SET(fd, wr); hit = 1; }
    }
    if (pf[k].revents & POLLPRI) {
      if (ex) { FD_SET(fd, ex); hit = 1; }
    }
    total += hit;
  }
  return total;
}

int pselect(int nfds, fd_set *rd, fd_set *wr, fd_set *ex,
            const struct timespec *ts, const sigset_t *sig) {
  (void)sig;
  if (g_seq_fd < 0) {
    static int (*real_ps)(int, fd_set *, fd_set *, fd_set *,
                          const struct timespec *, const sigset_t *);
    if (!real_ps) real_ps = dlsym(RTLD_NEXT, "pselect");
    return real_ps(nfds, rd, wr, ex, ts, sig);
  }
  struct timeval tv, *tvp = NULL;
  if (ts) {
    tv.tv_sec = ts->tv_sec;
    /* Round up like select(): a sub-microsecond timeout must not
     * become a zero-timeout spin. */
    tv.tv_usec = (ts->tv_nsec + 999) / 1000;
    if (tv.tv_usec >= 1000000) { tv.tv_sec += 1; tv.tv_usec -= 1000000; }
    tvp = &tv;
  }
  return select(nfds, rd, wr, ex, tvp);
}

/* writev/readv/sendmsg/recvmsg: iovec fronts over the existing
 * stream/datagram ops (reference process_emu_writev family). */
#include <sys/uio.h>

ssize_t writev(int fd, const struct iovec *iov, int iovcnt) {
  fd = vfd_promote(fd);
  if (!is_vfd(fd)) {
    static ssize_t (*real_wv)(int, const struct iovec *, int);
    if (!real_wv) real_wv = dlsym(RTLD_NEXT, "writev");
    return real_wv(fd, iov, iovcnt);
  }
  ssize_t total = 0;
  for (int i = 0; i < iovcnt; i++) {
    size_t off = 0;
    while (off < iov[i].iov_len) {
      ssize_t w = vsend(fd, (const char *)iov[i].iov_base + off,
                        iov[i].iov_len - off, 0);
      if (w <= 0)
        return total > 0 ? total : w;   /* partial like Linux */
      off += (size_t)w;
      total += w;
      if ((size_t)w < iov[i].iov_len - (off - (size_t)w))
        return total;                   /* short write: stop */
    }
  }
  return total;
}

ssize_t readv(int fd, const struct iovec *iov, int iovcnt) {
  fd = vfd_promote(fd);
  if (!is_vfd(fd)) {
    static ssize_t (*real_rv)(int, const struct iovec *, int);
    if (!real_rv) real_rv = dlsym(RTLD_NEXT, "readv");
    return real_rv(fd, iov, iovcnt);
  }
  ssize_t total = 0;
  for (int i = 0; i < iovcnt; i++) {
    if (iov[i].iov_len == 0) continue;
    ssize_t r = vrecv(fd, iov[i].iov_base, iov[i].iov_len, 0);
    if (r <= 0) return total > 0 ? total : r;
    total += r;
    if ((size_t)r < iov[i].iov_len) return total;  /* stream drained */
  }
  return total;
}

ssize_t sendmsg(int fd, const struct msghdr *msg, int flags) {
  fd = vfd_promote(fd);
  if (!is_vfd(fd)) {
    static ssize_t (*real_sm)(int, const struct msghdr *, int);
    if (!real_sm) real_sm = dlsym(RTLD_NEXT, "sendmsg");
    return real_sm(fd, msg, flags);
  }
  /* Coalesce the iovec (datagrams must go as one unit; streams don't
   * care).  Control messages are not modeled. */
  size_t total = 0;
  for (size_t i = 0; i < msg->msg_iovlen; i++)
    total += msg->msg_iov[i].iov_len;
  if (total > MAX_DATA) total = MAX_DATA;
  static __thread unsigned char g_coal[MAX_DATA];
  size_t off = 0;
  for (size_t i = 0; i < msg->msg_iovlen && off < total; i++) {
    size_t n = msg->msg_iov[i].iov_len;
    if (n > total - off) n = total - off;
    memcpy(g_coal + off, msg->msg_iov[i].iov_base, n);
    off += n;
  }
  if (msg->msg_name &&
      ((struct sockaddr *)msg->msg_name)->sa_family == AF_INET)
    return sendto(fd, g_coal, off, flags,
                  (const struct sockaddr *)msg->msg_name,
                  msg->msg_namelen);
  return vsend(fd, g_coal, off, flags);
}

ssize_t recvmsg(int fd, struct msghdr *msg, int flags) {
  fd = vfd_promote(fd);
  if (!is_vfd(fd)) {
    static ssize_t (*real_rm)(int, struct msghdr *, int);
    if (!real_rm) real_rm = dlsym(RTLD_NEXT, "recvmsg");
    return real_rm(fd, msg, flags);
  }
  static __thread unsigned char g_coal[MAX_DATA];
  size_t want = 0;
  for (size_t i = 0; i < msg->msg_iovlen; i++)
    want += msg->msg_iov[i].iov_len;
  if (want > MAX_DATA) want = MAX_DATA;
  ssize_t r;
  if (msg->msg_name) {
    socklen_t alen = msg->msg_namelen;
    r = recvfrom(fd, g_coal, want, flags,
                 (struct sockaddr *)msg->msg_name, &alen);
    msg->msg_namelen = alen;
  } else {
    r = vrecv(fd, g_coal, want, flags);
  }
  if (r <= 0) return r;
  size_t off = 0;
  for (size_t i = 0; i < msg->msg_iovlen && off < (size_t)r; i++) {
    size_t n = msg->msg_iov[i].iov_len;
    if (n > (size_t)r - off) n = (size_t)r - off;
    memcpy(msg->msg_iov[i].iov_base, g_coal + off, n);
    off += n;
  }
  msg->msg_flags = 0;
  return r;
}

/* eventfd: shim-local counter object (like timerfd).  Readiness changes
 * only via sibling threads of the same process, so wakes ride the
 * thread gate (write marks waiting readers runnable). */
#include <sys/eventfd.h>

#define EFD_VBASE (TFD_BASE + MAX_TFD)
#define MAX_EFD 64

typedef struct {
  int used, nonblock, semaphore;
  uint64_t count;
} efd_t;

static efd_t g_efd[MAX_EFD];

static int is_efd(int fd) {
  return fd >= EFD_VBASE && fd < EFD_VBASE + MAX_EFD &&
         g_efd[fd - EFD_VBASE].used;
}

static int is_efd_fwd(int fd) { return is_efd(fd); }

static void efd_release(int fd) { g_efd[fd - EFD_VBASE].used = 0; }

/* Poll readiness for shim-local eventfds: POLLIN while the counter is
 * nonzero; always writable (the 0xff..fe overflow block is not
 * modeled).  Mirrors tfd_fill: fills revents for efd entries only and
 * returns how many are ready. */
static int efd_poll_fill(struct pollfd *fds, nfds_t nfds) {
  int n = 0;
  for (nfds_t i = 0; i < nfds; i++) {
    if (!is_efd(fds[i].fd)) continue;
    efd_t *e = &g_efd[fds[i].fd - EFD_VBASE];
    fds[i].revents = 0;
    if ((fds[i].events & POLLIN) && e->count > 0) fds[i].revents |= POLLIN;
    if (fds[i].events & POLLOUT) fds[i].revents |= POLLOUT;
    if (fds[i].revents) n++;
  }
  return n;
}

int eventfd(unsigned int initval, int flags) {
  if (g_seq_fd < 0) {
    static int (*real_efd)(unsigned int, int);
    if (!real_efd) real_efd = dlsym(RTLD_NEXT, "eventfd");
    return real_efd(initval, flags);
  }
  for (int i = 0; i < MAX_EFD; i++)
    if (!g_efd[i].used) {
      g_efd[i].used = 1;
      g_efd[i].count = initval;
      g_efd[i].nonblock = (flags & EFD_NONBLOCK) != 0;
      g_efd[i].semaphore = (flags & EFD_SEMAPHORE) != 0;
      return EFD_VBASE + i;
    }
  errno = EMFILE;
  return -1;
}

static ssize_t efd_read(int fd, void *buf, size_t n) {
  if (n < 8) { errno = EINVAL; return -1; }
  efd_t *e = &g_efd[fd - EFD_VBASE];
  for (;;) {
    if (e->count > 0) {
      uint64_t v = e->semaphore ? 1 : e->count;
      e->count -= v;
      memcpy(buf, &v, 8);
      return 8;
    }
    if (e->nonblock) { errno = EAGAIN; return -1; }
    if (vt_multi()) {
      /* sem-style wait keyed by the efd object; efd_write wakes us */
      real_mxl(&g_vt_mx);
      g_vt[t_self].kind = WK_SEM;
      g_vt[t_self].waddr = e;
      vt_block_locked();
      real_mxu(&g_vt_mx);
      continue;
    }
    /* Single-threaded read on an empty eventfd can never be satisfied:
     * park forever in virtual time (Linux blocks forever too). */
    req_t rq = {.op = OP_SLEEP, .fd = -1, .a0 = (int64_t)1 << 62,
                .len = 0};
    rep_t rp;
    rpc(&rq, &rp);
  }
}

static ssize_t efd_write(int fd, const void *buf, size_t n) {
  if (n < 8) { errno = EINVAL; return -1; }
  efd_t *e = &g_efd[fd - EFD_VBASE];
  uint64_t v;
  memcpy(&v, buf, 8);
  e->count += v;
  if (g_vt_on) {
    vt_resolve_reals();
    real_mxl(&g_vt_mx);
    for (int i = 0; i < MAX_VT; i++) {
      vt_t *t = &g_vt[i];
      if (!t->used || t->finished) continue;
      if (t->kind == WK_SEM && t->waddr == (void *)e) t->kind = WK_RUN;
      if (t->kind == WK_POLL)
        for (int j = 0; j < t->pnfds; j++)
          if (t->pfds[j].fd == fd) t->kind = WK_RUN;
    }
    real_mxu(&g_vt_mx);
  }
  return 8;
}

/* Deterministic rand: the reference routes rand() to the host Random so
 * every run draws the same sequence regardless of libc internals
 * (process.c rand emulation).  Seeded per process by the substrate via
 * SHADOW1_RAND_SEED. */
static uint64_t g_rand_state;
static int g_rand_init;

static void vrand_init(void) {
  if (g_rand_init) return;
  const char *s = getenv("SHADOW1_RAND_SEED");
  uint64_t seed = s ? (uint64_t)strtoull(s, NULL, 10) : 1;
  g_rand_state = seed * 0x9E3779B97F4A7C15ULL + 1;
  g_rand_init = 1;
}

static uint64_t vrand_next(void) {
  vrand_init();
  uint64_t x = g_rand_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  g_rand_state = x;
  return x;
}

int rand(void) {
  if (g_seq_fd < 0) {
    static int (*real_rand)(void);
    if (!real_rand) real_rand = dlsym(RTLD_NEXT, "rand");
    return real_rand();
  }
  return (int)(vrand_next() >> 33);  /* 31-bit non-negative */
}

long random(void) {
  if (g_seq_fd < 0) {
    static long (*real_random)(void);
    if (!real_random) real_random = dlsym(RTLD_NEXT, "random");
    return real_random();
  }
  return (long)(vrand_next() >> 33);
}

void srand(unsigned seed) {
  if (g_seq_fd < 0) {
    static void (*real_srand)(unsigned);
    if (!real_srand) real_srand = dlsym(RTLD_NEXT, "srand");
    real_srand(seed);
    return;
  }
  g_rand_state = (uint64_t)seed * 0x9E3779B97F4A7C15ULL + 1;
  g_rand_init = 1;
}

void srandom(unsigned seed) { srand(seed); }

/* AF_UNIX in virtual time: path-named sockets become loopback TCP on
 * the process's own host (reference keeps a unix-path -> port map,
 * host.c:57-105 + socket.h:47-78).  Distinct paths MUST get distinct
 * ports -- a silent hash collision cross-wires two unrelated sockets --
 * so the FNV hash only seeds the probe into an open-addressed path
 * table whose slot index IS the port offset (a path keeps its port for
 * the process lifetime); exhaustion aborts loudly instead of wrapping. */
#define UPP_SLOTS 512
#define UPP_PORT_BASE 61000
static char g_upp_path[UPP_SLOTS][108];  /* sizeof(sun_path) */
static unsigned char g_upp_used[UPP_SLOTS];

static int unix_path_port(const char *path) {
  uint32_t hsh = 2166136261u;
  for (const char *c = path; *c; c++) hsh = (hsh ^ (uint8_t)*c) * 16777619u;
  for (uint32_t probe = 0; probe < UPP_SLOTS; probe++) {
    int i = (int)((hsh + probe) % UPP_SLOTS);
    if (!g_upp_used[i]) {
      g_upp_used[i] = 1;
      snprintf(g_upp_path[i], sizeof g_upp_path[i], "%s", path);
      return UPP_PORT_BASE + i;
    }
    if (strncmp(g_upp_path[i], path, sizeof g_upp_path[i] - 1) == 0)
      return UPP_PORT_BASE + i;
  }
  fprintf(stderr, "[shadow1-shim] FATAL: AF_UNIX path->port table full "
                  "(%d distinct paths); raise UPP_SLOTS\n", UPP_SLOTS);
  abort();
}
