// sequencer: host-side real-process supervisor for the simulation.
//
// The TPU-era replacement for the reference's in-process plugin machinery
// (dlmopen namespaces + cooperative rpth threads + process_continue,
// /root/reference/src/main/host/process.c:379-564,1197-1275): each plugin
// runs as a REAL operating-system process with the shadow1_shim preloaded;
// this library owns spawning (fork/exec with the shim + virtual-clock
// environment), the per-process SOCK_SEQPACKET request pipe, and the
// shared virtual-time page.  "Run a process until it blocks" is:
// reply to its parked syscall, then block reading its next request --
// a process only runs while the sequencer waits on it, which serializes
// plugin execution exactly like the reference's pth main-thread handoff
// and keeps the simulation deterministic.
//
// Scheduling policy (who to run, in what order, what each syscall means
// against the simulated socket tables) lives in the Python bridge
// (shadow1_tpu/substrate/); this layer is mechanism only.
//
// C API (ctypes-consumed); all functions return >= 0 on success.

#include <cerrno>
#include <cstddef>
#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <poll.h>
#include <string>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kMaxData = 65536;

struct Req {
  uint32_t op;
  int32_t fd;
  int64_t a0;
  int64_t a1;
  uint32_t len;
  unsigned char data[kMaxData];
};

struct Rep {
  int64_t ret;
  int32_t err;
  int64_t vtime_ns;
  uint32_t len;
  unsigned char data[kMaxData];
};

constexpr size_t kReqHdr = offsetof(Req, data);
constexpr size_t kRepHdr = offsetof(Rep, data);

struct Proc {
  pid_t pid = -1;
  int sock = -1;       // our end of the seqpacket pair
  bool exited = false;
  int exit_code = -1;
};

struct Sequencer {
  std::vector<Proc> procs;
  int time_fd = -1;
  volatile int64_t* time_page = nullptr;
  std::string time_path;
};

std::vector<Sequencer*> g_seqs;

Sequencer* get(int h) {
  if (h < 0 || h >= (int)g_seqs.size()) return nullptr;
  return g_seqs[h];
}

}  // namespace

extern "C" {

// Create a sequencer; `time_page_path` is created/truncated and mmapped
// as the shared virtual-clock page the shim reads.
int seq_create(const char* time_page_path) {
  auto* s = new Sequencer();
  s->time_path = time_page_path;
  s->time_fd = open(time_page_path, O_RDWR | O_CREAT | O_TRUNC | O_CLOEXEC,
                    0600);
  if (s->time_fd < 0) {
    delete s;
    return -1;
  }
  if (ftruncate(s->time_fd, 4096) != 0) {
    close(s->time_fd);
    delete s;
    return -1;
  }
  void* m = mmap(nullptr, 4096, PROT_READ | PROT_WRITE, MAP_SHARED,
                 s->time_fd, 0);
  if (m == MAP_FAILED) {
    close(s->time_fd);
    delete s;
    return -1;
  }
  s->time_page = (volatile int64_t*)m;
  *s->time_page = 0;
  g_seqs.push_back(s);
  return (int)g_seqs.size() - 1;
}

int seq_settime(int h, int64_t ns) {
  Sequencer* s = get(h);
  if (!s) return -1;
  *s->time_page = ns;
  return 0;
}

// Spawn argv[0..argc) as a supervised process with the shim preloaded.
// stdout/stderr go to `out_path` (append).  Returns proc id.
int seq_spawn(int h, int argc, const char* const* argv,
              const char* shim_path, const char* out_path) {
  Sequencer* s = get(h);
  if (!s || argc < 1) return -1;
  int sv[2];
  if (socketpair(AF_UNIX, SOCK_SEQPACKET, 0, sv) != 0) return -1;
  // The sequencer's end must not leak into plugin processes (a plugin
  // closing or writing a sibling's channel would break the determinism
  // contract); the child's end stays inheritable for the exec'd binary.
  fcntl(sv[0], F_SETFD, FD_CLOEXEC);

  pid_t pid = fork();
  if (pid < 0) {
    close(sv[0]);
    close(sv[1]);
    return -1;
  }
  if (pid == 0) {
    close(sv[0]);
    // The shim finds its pipe via env; keep the fd number stable.
    char fdbuf[16];
    snprintf(fdbuf, sizeof fdbuf, "%d", sv[1]);
    setenv("SHADOW1_SHIM_FD", fdbuf, 1);
    setenv("SHADOW1_TIME_PAGE", s->time_path.c_str(), 1);
    setenv("LD_PRELOAD", shim_path, 1);
    if (out_path && out_path[0]) {
      int ofd = open(out_path, O_WRONLY | O_CREAT | O_APPEND, 0644);
      if (ofd >= 0) {
        dup2(ofd, 1);
        dup2(ofd, 2);
        if (ofd > 2) close(ofd);
      }
    }
    std::vector<char*> av;
    for (int i = 0; i < argc; i++) av.push_back(const_cast<char*>(argv[i]));
    av.push_back(nullptr);
    execvp(av[0], av.data());
    _exit(127);
  }
  close(sv[1]);
  Proc p;
  p.pid = pid;
  p.sock = sv[0];
  s->procs.push_back(p);
  return (int)s->procs.size() - 1;
}

// Block (up to timeout_ms) for the process's next syscall request.
// Returns 1 = request filled into out buffers, 0 = process exited
// (exit code in *a0_out), -2 = timeout (still running), -1 = error.
int seq_wait_request(int h, int proc, int timeout_ms, uint32_t* op_out,
                     int32_t* fd_out, int64_t* a0_out, int64_t* a1_out,
                     uint8_t* data_out, uint32_t* len_out) {
  Sequencer* s = get(h);
  if (!s || proc < 0 || proc >= (int)s->procs.size()) return -1;
  Proc& p = s->procs[proc];
  if (p.exited) {
    *a0_out = p.exit_code;
    return 0;
  }
  struct pollfd pfd = {p.sock, POLLIN, 0};
  int pr = poll(&pfd, 1, timeout_ms);
  if (pr == 0) return -2;
  if (pr < 0) return -1;

  static thread_local Req rq;
  ssize_t n = recv(p.sock, &rq, sizeof rq, 0);
  if (n <= 0) {
    // EOF: the process exited (or crashed); reap it.
    int st = 0;
    waitpid(p.pid, &st, 0);
    p.exited = true;
    p.exit_code = WIFEXITED(st) ? WEXITSTATUS(st) : -WTERMSIG(st);
    close(p.sock);
    p.sock = -1;
    *a0_out = p.exit_code;
    return 0;
  }
  if ((size_t)n < kReqHdr) return -1;
  *op_out = rq.op;
  *fd_out = rq.fd;
  *a0_out = rq.a0;
  *a1_out = rq.a1;
  uint32_t len = rq.len;
  if (len > kMaxData) len = kMaxData;
  *len_out = len;
  if (len) memcpy(data_out, rq.data, len);
  return 1;
}

// Answer the process's parked syscall (it resumes immediately after).
int seq_reply(int h, int proc, int64_t ret, int32_t err, int64_t vtime_ns,
              const uint8_t* data, uint32_t len) {
  Sequencer* s = get(h);
  if (!s || proc < 0 || proc >= (int)s->procs.size()) return -1;
  Proc& p = s->procs[proc];
  if (p.exited || p.sock < 0) return -1;
  static thread_local Rep rp;
  rp.ret = ret;
  rp.err = err;
  rp.vtime_ns = vtime_ns;
  if (len > kMaxData) len = kMaxData;
  rp.len = len;
  if (len) memcpy(rp.data, data, len);
  ssize_t n = send(p.sock, &rp, kRepHdr + len, 0);
  return n < 0 ? -1 : 0;
}

// 0 = running, 1 = exited (code in *code_out).
int seq_status(int h, int proc, int* code_out) {
  Sequencer* s = get(h);
  if (!s || proc < 0 || proc >= (int)s->procs.size()) return -1;
  Proc& p = s->procs[proc];
  if (!p.exited) {
    int st = 0;
    pid_t r = waitpid(p.pid, &st, WNOHANG);
    if (r == p.pid) {
      p.exited = true;
      p.exit_code = WIFEXITED(st) ? WEXITSTATUS(st) : -WTERMSIG(st);
    }
  }
  if (p.exited) {
    *code_out = p.exit_code;
    return 1;
  }
  return 0;
}

int seq_kill(int h, int proc) {
  Sequencer* s = get(h);
  if (!s || proc < 0 || proc >= (int)s->procs.size()) return -1;
  Proc& p = s->procs[proc];
  if (!p.exited && p.pid > 0) kill(p.pid, SIGKILL);
  return 0;
}

}  // extern "C"
