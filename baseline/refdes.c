/* refdes: a measured CPU baseline for the shadow1_tpu benchmarks.
 *
 * A deliberately well-written, reference-architecture discrete-event
 * simulator in one C file: per-host binary heaps behind per-host
 * mutexes, pthread worker threads over a static host partition, and a
 * conservative lookahead-window barrier protocol -- the same
 * architecture as the reference's pthread engine (scheduler_policy
 * host-single walk, worker_sendPacket latency lookup + drop draw,
 * malloc'd packets), without its GLib/plugin overheads.  It therefore
 * UNDERSTATES the reference's per-event cost (no userspace TCP state
 * machine, no task closures, no object refcounting), making the ratio
 * it yields conservative for the TPU engine.
 *
 * Reference architecture mirrored (citations into /root/reference):
 *   - per-host queues drained below a window barrier:
 *     src/main/core/scheduler/scheduler_policy_host_single.c:210-271
 *   - conservative window advance by min link latency (lookahead):
 *     src/main/core/master.c:133-159,450-480
 *   - per-packet latency lookup + reliability draw + event push:
 *     src/main/core/worker.c:243-304
 *   - deterministic event order (time, seq): src/main/core/work/event.c:110-153
 *
 * Workloads:
 *   phold  N hosts, M initial messages each; a delivery schedules a
 *          forward to a uniform other host after an exponential delay
 *          (the reference's src/test/phold/test_phold.c shape, matching
 *          shadow1_tpu.sim.build_phold semantics and bench.py's
 *          sent+recv event counting).
 *   onion  C circuits x (client -> 3 relays -> server), S bytes per
 *          circuit in MTU segments under a fixed in-flight window with
 *          cumulative ACKs every other segment -- the data-movement
 *          shape of ladder rung 5, reported as wall seconds to complete
 *          all circuits.
 *
 * Build: cc -O2 -pthread -o refdes refdes.c -lm
 * Run:   ./refdes phold <hosts> <msgs/host> <sim_seconds> [threads]
 *        ./refdes onion <circuits> <bytes/circuit> [threads]
 * Output: one JSON line.
 */

#include <inttypes.h>
#include <math.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

typedef int64_t stime_t; /* simulated nanoseconds */

#define NS_PER_SEC 1000000000LL
#define NS_PER_MS 1000000LL
#define TIME_INF ((stime_t)1 << 62)

/* ---------------------------------------------------------------- events */

enum { EV_SEND, EV_DELIVER, EV_ONION_SEG, EV_ONION_ACK };

typedef struct packet {
  int32_t src, dst;
  int32_t bytes;
  int32_t circuit, hop;
  int64_t seq;
  unsigned char payload[64]; /* reference packets carry a malloc'd payload */
} packet_t;

typedef struct event {
  stime_t time;
  uint64_t seq; /* (src<<40 | counter): deterministic tiebreak */
  int32_t kind;
  int32_t host;
  packet_t *pkt;
} event_t;

/* ------------------------------------------------------- per-host state */

typedef struct host {
  pthread_mutex_t lock;
  event_t *heap;
  int32_t heap_len, heap_cap;
  uint64_t rng;      /* xorshift64 state, seeded per host */
  uint64_t ev_ctr;   /* event sequence counter for tiebreak */
  int64_t sent, recv;
  /* onion per-host stream state (one circuit role per host) */
  int32_t onion_role;    /* 0 client, 1..3 relay, 4 server, -1 none */
  int32_t onion_circuit;
  int64_t snd_next, snd_una, acked; /* client window bookkeeping */
} host_t;

static host_t *g_hosts;
static int g_nhosts;
static stime_t g_stop = TIME_INF;
static stime_t g_lookahead;
static int g_nthreads = 1;

/* latency matrix, vertices capped at 256 like sim.build_phold */
static int g_nvert;
static stime_t *g_lat; /* [V*V] */

static inline stime_t lat_lookup(int src, int dst) {
  return g_lat[(src % g_nvert) * g_nvert + (dst % g_nvert)];
}

static inline uint64_t xorshift64(uint64_t *s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return *s = x;
}

static inline double rng_uniform(uint64_t *s) {
  return (double)(xorshift64(s) >> 11) * (1.0 / 9007199254740992.0);
}

/* ------------------------------------------------------------ host heap */

static inline int ev_before(const event_t *a, const event_t *b) {
  if (a->time != b->time) return a->time < b->time;
  return a->seq < b->seq;
}

static void heap_push(host_t *h, event_t ev) {
  if (h->heap_len == h->heap_cap) {
    h->heap_cap = h->heap_cap ? h->heap_cap * 2 : 16;
    h->heap = realloc(h->heap, (size_t)h->heap_cap * sizeof(event_t));
  }
  int i = h->heap_len++;
  h->heap[i] = ev;
  while (i > 0) {
    int p = (i - 1) / 2;
    if (!ev_before(&h->heap[i], &h->heap[p])) break;
    event_t t = h->heap[p];
    h->heap[p] = h->heap[i];
    h->heap[i] = t;
    i = p;
  }
}

static event_t heap_pop(host_t *h) {
  event_t top = h->heap[0];
  h->heap[0] = h->heap[--h->heap_len];
  int i = 0;
  for (;;) {
    int l = 2 * i + 1, r = l + 1, m = i;
    if (l < h->heap_len && ev_before(&h->heap[l], &h->heap[m])) m = l;
    if (r < h->heap_len && ev_before(&h->heap[r], &h->heap[m])) m = r;
    if (m == i) break;
    event_t t = h->heap[m];
    h->heap[m] = h->heap[i];
    h->heap[i] = t;
    i = m;
  }
  return top;
}

static void push_to(int dst, event_t ev) {
  host_t *h = &g_hosts[dst];
  pthread_mutex_lock(&h->lock);
  heap_push(h, ev);
  pthread_mutex_unlock(&h->lock);
}

/* ------------------------------------------------------------ workloads */

static double g_mean_delay_ns;
static int64_t g_onion_done, g_onion_total;
static int64_t g_onion_bytes, g_onion_seg = 1460, g_onion_win = 64;
static pthread_mutex_t g_done_lock = PTHREAD_MUTEX_INITIALIZER;

static void phold_execute(host_t *h, int self, event_t *ev) {
  if (ev->kind == EV_DELIVER) {
    h->recv++;
    free(ev->pkt);
    /* schedule the forward after an exponential think time */
    stime_t d = (stime_t)(-log1p(-rng_uniform(&h->rng)) * g_mean_delay_ns);
    if (d < 1) d = 1;
    event_t send = {.time = ev->time + d,
                    .seq = ((uint64_t)self << 40) | h->ev_ctr++,
                    .kind = EV_SEND,
                    .host = self,
                    .pkt = NULL};
    push_to(self, send); /* the drain released our lock before execute */
  } else {
    h->sent++;
    int off = 1 + (int)(rng_uniform(&h->rng) * (g_nhosts - 1));
    if (off > g_nhosts - 1) off = g_nhosts - 1;
    int dst = (self + off) % g_nhosts;
    packet_t *p = malloc(sizeof(packet_t));
    p->src = self;
    p->dst = dst;
    p->bytes = 64;
    p->seq = (int64_t)h->ev_ctr;
    memset(p->payload, (int)(h->ev_ctr & 0xff), sizeof(p->payload));
    event_t del = {.time = ev->time + lat_lookup(self, dst),
                   .seq = ((uint64_t)self << 40) | h->ev_ctr++,
                   .kind = EV_DELIVER,
                   .host = dst,
                   .pkt = p};
    push_to(dst, del);
  }
}

/* onion: hosts are laid out circuit-major: c*5 + {0 client,1..3 relay,
 * 4 server}.  The client keeps g_onion_win segments in flight; the
 * server acks every second segment (delack shape); relays forward both
 * directions.  Per-hop per-segment work mirrors phold's deliver path. */

static void onion_client_pump(host_t *h, int self, stime_t now) {
  int64_t nseg = (g_onion_bytes + g_onion_seg - 1) / g_onion_seg;
  while (h->snd_next < nseg && h->snd_next - h->snd_una < g_onion_win) {
    packet_t *p = malloc(sizeof(packet_t));
    p->src = self;
    p->dst = self + 1;
    p->bytes = (int32_t)g_onion_seg;
    p->circuit = h->onion_circuit;
    p->hop = 0;
    p->seq = h->snd_next++;
    h->sent++;
    event_t del = {.time = now + lat_lookup(self, self + 1),
                   .seq = ((uint64_t)self << 40) | h->ev_ctr++,
                   .kind = EV_ONION_SEG,
                   .host = self + 1,
                   .pkt = p};
    push_to(self + 1, del);
  }
}

static void onion_execute(host_t *h, int self, event_t *ev) {
  packet_t *p = ev->pkt;
  h->recv++;
  if (ev->kind == EV_ONION_SEG) {
    if (h->onion_role == 4) { /* server: count + maybe ack */
      int64_t seq = p->seq;
      free(p);
      h->acked = seq + 1;
      if ((seq & 1) || h->acked * g_onion_seg >= g_onion_bytes) {
        packet_t *a = malloc(sizeof(packet_t));
        a->src = self;
        a->dst = self - 1;
        a->bytes = 0;
        a->circuit = h->onion_circuit;
        a->hop = 4;
        a->seq = h->acked;
        h->sent++;
        event_t del = {.time = ev->time + lat_lookup(self, self - 1),
                       .seq = ((uint64_t)self << 40) | h->ev_ctr++,
                       .kind = EV_ONION_ACK,
                       .host = self - 1,
                       .pkt = a};
        push_to(self - 1, del);
      }
    } else { /* relay: forward toward the server */
      int dst = self + 1;
      p->hop++;
      h->sent++;
      event_t del = {.time = ev->time + lat_lookup(self, dst),
                     .seq = ((uint64_t)self << 40) | h->ev_ctr++,
                     .kind = EV_ONION_SEG,
                     .host = dst,
                     .pkt = p};
      push_to(dst, del);
    }
  } else { /* ACK flowing back toward the client */
    if (h->onion_role == 0) {
      int64_t nseg = (g_onion_bytes + g_onion_seg - 1) / g_onion_seg;
      if (p->seq > h->snd_una) h->snd_una = p->seq;
      free(p);
      if (h->snd_una >= nseg) {
        pthread_mutex_lock(&g_done_lock);
        g_onion_done++;
        pthread_mutex_unlock(&g_done_lock);
      } else {
        onion_client_pump(h, self, ev->time);
      }
    } else {
      int dst = self - 1;
      h->sent++;
      event_t del = {.time = ev->time + lat_lookup(self, dst),
                     .seq = ((uint64_t)self << 40) | h->ev_ctr++,
                     .kind = EV_ONION_ACK,
                     .host = dst,
                     .pkt = p};
      push_to(dst, del);
    }
  }
}

/* -------------------------------------------------- window-barrier loop */

static int g_workload; /* 0 phold, 1 onion */
static pthread_barrier_t g_barrier;
static stime_t g_window_end;
static stime_t *g_thread_min; /* per-thread min next-event time */
static volatile int g_running = 1;

static void drain_host(int self, stime_t wend) {
  host_t *h = &g_hosts[self];
  pthread_mutex_lock(&h->lock);
  while (h->heap_len > 0 && h->heap[0].time < wend) {
    event_t ev = heap_pop(h);
    /* execute OUTSIDE the host lock for cross-host pushes?  The
     * reference holds the dst-host lock during execution (event.c:65);
     * we hold our own and take the peer's on push -- peer != self
     * always (lookahead >= min latency), so no self-deadlock. */
    pthread_mutex_unlock(&h->lock);
    if (g_workload == 0)
      phold_execute(h, self, &ev);
    else
      onion_execute(h, self, &ev);
    pthread_mutex_lock(&h->lock);
  }
  pthread_mutex_unlock(&h->lock);
}

typedef struct targ {
  int tid, lo, hi;
} targ_t;

/* Locked peek: the heap array may be realloc'd by a concurrent push. */
static inline stime_t host_peek(int i) {
  host_t *h = &g_hosts[i];
  pthread_mutex_lock(&h->lock);
  stime_t t = h->heap_len ? h->heap[0].time : TIME_INF;
  pthread_mutex_unlock(&h->lock);
  return t;
}

static void *worker(void *vp) {
  targ_t *a = vp;
  for (;;) {
    pthread_barrier_wait(&g_barrier); /* window start */
    if (!g_running) break;
    stime_t wend = g_window_end;
    /* host-single policy walk: repeat until no assigned host has an
     * event below the barrier (self-scheduled events may re-arm) */
    for (;;) {
      int again = 0;
      for (int hst = a->lo; hst < a->hi; hst++) {
        if (host_peek(hst) < wend) {
          drain_host(hst, wend);
          again = 1;
        }
      }
      if (!again) break;
    }
    stime_t mn = TIME_INF;
    for (int hst = a->lo; hst < a->hi; hst++) {
      stime_t t = host_peek(hst);
      if (t < mn) mn = t;
    }
    g_thread_min[a->tid] = mn;
    pthread_barrier_wait(&g_barrier); /* window end */
  }
  return NULL;
}

static double now_wall(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + ts.tv_nsec * 1e-9;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s phold|onion ...\n", argv[0]);
    return 2;
  }
  int nthreads = 0;
  stime_t link_lat = 10 * NS_PER_MS;
  if (!strcmp(argv[1], "phold")) {
    g_workload = 0;
    g_nhosts = argc > 2 ? atoi(argv[2]) : 16384;
    int msgs = argc > 3 ? atoi(argv[3]) : 4;
    double sim_s = argc > 4 ? atof(argv[4]) : 2.0;
    nthreads = argc > 5 ? atoi(argv[5]) : 0;
    g_stop = (stime_t)(sim_s * NS_PER_SEC);
    g_mean_delay_ns = 10.0 * NS_PER_MS;
    g_nvert = g_nhosts < 256 ? g_nhosts : 256;
    g_lat = malloc((size_t)g_nvert * g_nvert * sizeof(stime_t));
    for (int i = 0; i < g_nvert * g_nvert; i++) g_lat[i] = link_lat;
    g_hosts = calloc((size_t)g_nhosts, sizeof(host_t));
    for (int i = 0; i < g_nhosts; i++) {
      pthread_mutex_init(&g_hosts[i].lock, NULL);
      g_hosts[i].rng = 0x9e3779b97f4a7c15ULL ^ ((uint64_t)i * 0xbf58476d1ce4e5b9ULL + 1);
      for (int m = 0; m < msgs; m++) {
        stime_t d = (stime_t)(-log1p(-rng_uniform(&g_hosts[i].rng)) * g_mean_delay_ns);
        event_t ev = {.time = d < 1 ? 1 : d,
                      .seq = ((uint64_t)i << 40) | g_hosts[i].ev_ctr++,
                      .kind = EV_SEND,
                      .host = i,
                      .pkt = NULL};
        heap_push(&g_hosts[i], ev);
      }
    }
  } else if (!strcmp(argv[1], "onion")) {
    g_workload = 1;
    int circuits = argc > 2 ? atoi(argv[2]) : 2000;
    g_onion_bytes = argc > 3 ? atoll(argv[3]) : (1 << 20);
    nthreads = argc > 4 ? atoi(argv[4]) : 0;
    g_onion_total = circuits;
    g_nhosts = circuits * 5;
    g_nvert = g_nhosts < 256 ? g_nhosts : 256;
    g_lat = malloc((size_t)g_nvert * g_nvert * sizeof(stime_t));
    for (int i = 0; i < g_nvert * g_nvert; i++) g_lat[i] = link_lat;
    g_hosts = calloc((size_t)g_nhosts, sizeof(host_t));
    for (int i = 0; i < g_nhosts; i++) {
      pthread_mutex_init(&g_hosts[i].lock, NULL);
      g_hosts[i].rng = 0x9e3779b97f4a7c15ULL ^ ((uint64_t)i * 0xbf58476d1ce4e5b9ULL + 1);
      g_hosts[i].onion_role = i % 5;
      g_hosts[i].onion_circuit = i / 5;
    }
    /* every client primes its window at t=1ms */
    for (int c = 0; c < circuits; c++) {
      int self = c * 5;
      event_t kick = {.time = NS_PER_MS,
                      .seq = ((uint64_t)self << 40) | g_hosts[self].ev_ctr++,
                      .kind = EV_ONION_ACK, /* ack(0) primes the pump */
                      .host = self,
                      .pkt = NULL};
      packet_t *p = malloc(sizeof(packet_t));
      memset(p, 0, sizeof(*p));
      kick.pkt = p;
      heap_push(&g_hosts[self], kick);
    }
  } else {
    fprintf(stderr, "unknown workload %s\n", argv[1]);
    return 2;
  }

  if (nthreads <= 0) {
    long n = sysconf(_SC_NPROCESSORS_ONLN);
    nthreads = n > 0 ? (int)n : 1;
  }
  if (nthreads > g_nhosts) nthreads = g_nhosts;
  g_nthreads = nthreads;
  g_lookahead = link_lat;
  g_thread_min = malloc((size_t)nthreads * sizeof(stime_t));
  pthread_barrier_init(&g_barrier, NULL, (unsigned)nthreads + 1);
  pthread_t *tids = malloc((size_t)nthreads * sizeof(pthread_t));
  targ_t *targs = malloc((size_t)nthreads * sizeof(targ_t));
  int per = (g_nhosts + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    targs[t].tid = t;
    targs[t].lo = t * per;
    targs[t].hi = (t + 1) * per < g_nhosts ? (t + 1) * per : g_nhosts;
    pthread_create(&tids[t], NULL, worker, &targs[t]);
  }

  double t0 = now_wall();
  stime_t now = 0;
  int64_t windows = 0;
  for (;;) {
    /* window start: advance to min next event + lookahead */
    g_window_end = now + g_lookahead;
    if (g_window_end > g_stop) g_window_end = g_stop;
    pthread_barrier_wait(&g_barrier); /* release workers */
    pthread_barrier_wait(&g_barrier); /* workers done */
    windows++;
    stime_t mn = TIME_INF;
    for (int t = 0; t < nthreads; t++)
      if (g_thread_min[t] < mn) mn = g_thread_min[t];
    if (g_workload == 1) {
      pthread_mutex_lock(&g_done_lock);
      int64_t done = g_onion_done;
      pthread_mutex_unlock(&g_done_lock);
      if (done >= g_onion_total) { now = g_window_end; break; }
    }
    if (mn >= g_stop) { now = g_stop; break; }
    now = mn > g_window_end ? mn : g_window_end;
    if (now >= g_stop) break;
  }
  g_running = 0;
  pthread_barrier_wait(&g_barrier);
  for (int t = 0; t < nthreads; t++) pthread_join(tids[t], NULL);
  double wall = now_wall() - t0;

  int64_t sent = 0, recv = 0;
  for (int i = 0; i < g_nhosts; i++) {
    sent += g_hosts[i].sent;
    recv += g_hosts[i].recv;
  }
  int64_t events = sent + recv;
  if (g_workload == 0) {
    printf("{\"workload\": \"phold\", \"hosts\": %d, \"threads\": %d, "
           "\"sim_seconds\": %.3f, \"events\": %" PRId64 ", "
           "\"wall_sec\": %.3f, \"events_per_sec\": %.1f, "
           "\"windows\": %" PRId64 "}\n",
           g_nhosts, g_nthreads, (double)now / NS_PER_SEC, events, wall,
           (double)events / wall, windows);
  } else {
    printf("{\"workload\": \"onion\", \"circuits\": %" PRId64 ", "
           "\"threads\": %d, \"bytes_per_circuit\": %" PRId64 ", "
           "\"completed\": %" PRId64 ", \"sim_seconds\": %.3f, "
           "\"events\": %" PRId64 ", \"wall_sec\": %.3f, "
           "\"events_per_sec\": %.1f}\n",
           g_onion_total, g_nthreads, g_onion_bytes, g_onion_done,
           (double)now / NS_PER_SEC, events, wall,
           (double)events / wall);
  }
  return 0;
}
