"""Ensemble worlds: vmap whole simulations over a leading world axis.

Shadow runs one simulated world per process; here one compiled graph
serves an N-world ensemble -- seeded Monte-Carlo batches, netem chaos
sweeps, latency/loss parameter grids -- by stacking every `SimState`
and `NetParams` leaf on a leading world axis and `jax.vmap`-ing the
unmodified engine window loop over it.

Three contracts make the axis safe (tests/test_ensemble.py pins all):

* **Bitwise solo equivalence.**  World k of a stacked ensemble is
  leaf-for-leaf bitwise equal to the same world run solo.  `jax.vmap`
  batches the engine's `lax.while_loop`s by running while ANY lane's
  predicate holds and select-freezing finished lanes, so each world
  advances by its own per-world gmin -- worlds never synchronize each
  other's windows, and a finished world's state is carried through
  untouched.  (The one numerical precondition -- transcendentals must
  not be fusion-context-sensitive -- is handled at the source: see the
  f64 note in apps/phold.py.)

* **HLO identity for ensemble-absent runs.**  The engine body is
  vmap-transparent: `core/engine.py` gains no ensemble branches, so a
  solo run lowers byte-identical HLO whether or not this package is
  imported.

* **RNG hygiene.**  `replicate` seeds world k with
  `rng.world_key(root_key(seed), k)`: world 0 is the identity (bitwise
  the solo run with the same seed), worlds k>0 fold the world id under
  `PURPOSE_WORLD` so their streams are independent of every solo seed.

Mesh composition (world-major rule): `shard_worlds` shards the WORLD
axis across the existing 1-D hosts mesh -- each device owns
n_worlds/n_devices complete worlds, there are no cross-device
collectives (worlds are independent), and the per-world host arrays
stay whole.  A 2-D world x hosts mesh (worlds outer, host-sharding
inner with the parallel/mesh.py collectives nested under vmap) is
deferred: it only pays once a single world outgrows one device's HBM,
and it couples the window-advance collectives to the world axis --
docs/ensemble.md records the rationale.

Megakernel note: stacking forces `params.megakernel = False`.  Pallas
kernels have no batching rule under vmap; the reference path is already
pinned bitwise-identical to the megakernel path (tests/test_megakernel),
so the trajectory contract is unaffected.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import shapes
from ..core import engine, rng, simtime
from ..core.state import world_count

I64 = jnp.int64

__all__ = [
    "EnsembleMismatch", "stack", "replicate", "run_until", "run_chunked",
    "run_until_lanes", "lanes_cache_size",
    "world", "world_count", "shard_worlds", "cache_size",
    "FROZEN_NOW", "freeze_worlds", "frozen_worlds",
]

# A frozen (quarantined) world's clock.  The engine window predicate is
# `(state.now < t_target) & (gmin < t_target)`; parking `now` beyond any
# reachable target makes the predicate false on the first iteration, so
# under vmap the lane is select-carried bitwise-untouched: no window
# bodies run, no sentinel probes fire, conservation checks never see the
# world again.  2^62 ns is ~146 simulated years -- far past any stop
# time -- while leaving headroom below the i64 T_NEVER sentinels used by
# event queues.  The engine tail `state.replace(now=t_target)` rewrites
# every lane's clock after EACH launch, so the supervisor re-freezes its
# quarantine set after every launch rather than relying on `now` to
# stick (checkpoint manifests list frozen worlds for stateless resume).
FROZEN_NOW = 1 << 62


class EnsembleMismatch(ValueError):
    """Worlds cannot share one compiled graph: shapes/statics differ.

    Raised by `stack` naming the first mismatched block or static.  The
    CLI maps it to rc 2 (usage), pointing at `--bucket`."""


def _as_triple(w, k):
    try:
        state, params, app = w
    except (TypeError, ValueError):
        raise EnsembleMismatch(
            f"world {k} is not a (state, params, app) triple: {type(w)!r}")
    return state, params, app


def stack(worlds):
    """Stack N built worlds onto a leading world axis.

    `worlds`: sequence of (state, params, app) triples, all members of
    ONE shape bucket (identical ShapeKey: same hosts/slabs/statics, same
    present-or-None block layout) with equal apps.  Returns
    (estate, eparams, app) where every leaf carries a leading [N] axis.

    Refuses with `EnsembleMismatch` naming the first mismatched
    block/static -- rebuild the members into one bucket (`--bucket` /
    `shapes.bucket_for`; for seed-dependent netem schedules pad with the
    timeline `n_events` bucket) rather than letting `jnp.stack` throw a
    bare shape error.

    `params.megakernel` is forced off on every member before the shape
    comparison (see module docstring)."""
    worlds = [_as_triple(w, k) for k, w in enumerate(worlds)]
    if not worlds:
        raise EnsembleMismatch("stack() needs at least one world")
    worlds = [(s, p.replace(megakernel=False), a) for (s, p, a) in worlds]

    s0, p0, a0 = worlds[0]
    m0 = shapes.key_manifest(shapes.shape_key(s0, p0))
    td0 = (jax.tree_util.tree_structure(s0), jax.tree_util.tree_structure(p0))
    for k, (s, p, a) in enumerate(worlds[1:], start=1):
        if a != a0:
            raise EnsembleMismatch(
                f"world {k} does not stack with world 0: app differs "
                f"({a!r} vs {a0!r}); an ensemble shares ONE app (the app "
                f"is a static argument of the compiled graph)")
        mk = shapes.key_manifest(shapes.shape_key(s, p))
        why = shapes.describe_key_mismatch(
            m0, mk, a_label="world 0", b_label=f"world {k}")
        if why is not None:
            raise EnsembleMismatch(
                f"world {k} does not stack with world 0: {why}; rebuild "
                f"the members into one shape bucket (--bucket / "
                f"shapes.bucket_for; netem schedules take an n_events "
                f"bucket)")
        td = (jax.tree_util.tree_structure(s),
              jax.tree_util.tree_structure(p))
        if td != td0:
            raise EnsembleMismatch(
                f"world {k} does not stack with world 0: pytree "
                f"structure differs (same ShapeKey but different leaf "
                f"layout -- e.g. app state blocks)")
        # Leaf-level shape/dtype sweep: names mismatches the ShapeKey is
        # too coarse for (per-leaf ring capacities, netem tables).
        for (path, l0), lk in zip(
                jax.tree_util.tree_flatten_with_path((s0, p0))[0],
                jax.tree_util.tree_leaves((s, p))):
            a_sh = (jnp.shape(l0), jnp.result_type(l0))
            b_sh = (jnp.shape(lk), jnp.result_type(lk))
            if a_sh != b_sh:
                raise EnsembleMismatch(
                    f"world {k} does not stack with world 0: leaf "
                    f"{jax.tree_util.keystr(path)} is {b_sh[0]}/{b_sh[1]} "
                    f"vs {a_sh[0]}/{a_sh[1]}; rebuild the members into "
                    f"one shape bucket (--bucket; netem schedules take "
                    f"an n_events bucket)")

    estate = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[w[0] for w in worlds])
    eparams = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[w[1] for w in worlds])
    return estate, eparams, a0


def replicate(build, n: int, *, seed: int = 1, vary=None, **kwargs):
    """Build n worlds from one builder under the world-key RNG fold.

    World k calls `build(seed=rng.world_key(root_key(seed), k), ...)`,
    so world 0 is bitwise the solo `build(seed=seed)` world and worlds
    k>0 get independent streams (core/rng.py world_key).  `vary` is an
    optional callable `(k) -> dict` of per-world builder-kwarg
    overrides for parameter grids.  Returns the world list, ready for
    `stack`."""
    root = rng.root_key(seed)
    worlds = []
    for k in range(int(n)):
        kw = dict(kwargs)
        if vary is not None:
            kw.update(vary(k) or {})
        worlds.append(build(seed=rng.world_key(root, k), **kw))
    return worlds


@functools.partial(jax.jit, static_argnames=("app",))
def _run_until(estate, eparams, t_target, *, app):
    return jax.vmap(
        lambda s, p: engine.run_until_impl(s, p, app, t_target)
    )(estate, eparams)


def run_until(estate, eparams, app, t_target):
    """Run every world's window loop until simulated `t_target`, one
    compiled graph for the whole ensemble (vmapped engine.run_until)."""
    return _run_until(estate, eparams, jnp.asarray(t_target, I64), app=app)


def cache_size() -> int:
    """Compiled-graph count of the ensemble runner (ladder rung 10
    asserts one graph serves the whole ensemble)."""
    return _run_until._cache_size()


@functools.partial(jax.jit, static_argnames=("app",))
def _run_until_lanes(estate, eparams, t_targets, *, app):
    return jax.vmap(
        lambda s, p, tt: engine.run_until_impl(s, p, app, tt)
    )(estate, eparams, t_targets)


def run_until_lanes(estate, eparams, app, t_targets):
    """Run every lane to its OWN launch target: `t_targets` is an [N]
    i64 vector vmapped alongside the state, so lanes at different sim
    times advance on their own grids inside one compiled graph -- the
    continuous-batching launch primitive (batch.LaneTrain,
    docs/robustness.md "Continuous batching").

    The targets are traced, not static: varying them never recompiles,
    and the graph is distinct from `run_until`'s (a separate jit cache,
    so ensemble graph-count pins are unaffected).  An idle or finished
    lane must first be PARKED at `FROZEN_NOW` (freeze_worlds -- its
    `now` leaf rewritten, exactly the quarantine mechanics) and then
    passed `FROZEN_NOW` as its target: the window predicate is false on
    iteration one (no window bodies run) and the engine tail rewrite
    `now=t_target` re-parks the lane, so the freeze is self-maintaining
    across launches with no per-launch re-park.  Passing FROZEN_NOW as
    the target of an UNFROZEN lane would instead run it to the end of
    time -- park first, then target.  A lane passed its own current
    `now` is carried through unchanged (zero windows run and the tail
    rewrite is the identity)."""
    return _run_until_lanes(estate, eparams,
                            jnp.asarray(t_targets, I64), app=app)


def lanes_cache_size() -> int:
    """Compiled-graph count of the per-lane runner (the batched-server
    pin asserts one graph serves every co-batched request)."""
    return _run_until_lanes._cache_size()


def run_chunked(estate, eparams, app, t_target: int,
                chunk_ns: int = engine.CHUNK_NS):
    """Host-side loop of bounded ensemble launches up to `t_target` --
    engine.run_chunked with the world axis.  Chunk boundaries are
    absolute times shared by all worlds (each world still advances by
    its own windows inside a launch), so drains see every world at the
    same boundary.

    Lanes parked at FROZEN_NOW (quarantined worlds) stay parked across
    chunk boundaries: the engine tail rewrites every lane's clock to
    the boundary after each launch, which would thaw a frozen lane for
    the next chunk, so the loop re-parks the lanes that entered frozen.
    With no frozen lanes this adds nothing -- the launch sequence is
    byte-for-byte the plain one."""
    from .. import trace

    frozen = estate.now >= FROZEN_NOW
    refreeze = bool(jnp.any(frozen))
    t = int(jnp.min(estate.now))
    t_target = int(t_target)
    prof = trace.current()
    while t < t_target:
        t = min(t + chunk_ns, t_target)
        with prof.span("device_step", t_ns=t):
            estate = run_until(estate, eparams, app, t)
            if refreeze:
                estate = estate.replace(now=jnp.where(
                    frozen, jnp.asarray(FROZEN_NOW, estate.now.dtype),
                    estate.now))
            if prof.sync:
                jax.block_until_ready(estate)
    return estate


def world(estate, eparams, k: int):
    """Slice world k back out of a stacked ensemble: returns
    (state, params) with the world axis removed -- safe to hand to any
    host-side introspection that reads row counts off leaf shapes."""
    n = world_count(estate)
    if n is None:
        raise ValueError("world(): state has no world axis (solo state?)")
    if not 0 <= k < n:
        raise IndexError(f"world {k} out of range [0, {n})")
    return (jax.tree_util.tree_map(lambda x: x[k], estate),
            jax.tree_util.tree_map(lambda x: x[k], eparams))


def freeze_worlds(estate, worlds):
    """Park the listed worlds at `FROZEN_NOW` (quarantine freeze).

    Every other leaf is left bitwise-untouched: with `now` beyond any
    launch target the engine window predicate is false on iteration
    one, so vmap select-carries the lane through whole launches -- no
    window bodies, no sentinel probes, no conservation deltas.  Called
    by the supervisor's quarantine rung after EVERY launch (the engine
    tail rewrites `now=t_target` on all lanes).  `worlds` is an
    iterable of world indices; an empty set is the identity."""
    worlds = sorted({int(k) for k in worlds})
    if not worlds:
        return estate
    n = world_count(estate)
    if n is None:
        raise ValueError("freeze_worlds(): state has no world axis")
    bad = [k for k in worlds if not 0 <= k < n]
    if bad:
        raise IndexError(f"freeze_worlds(): worlds {bad} out of range "
                         f"[0, {n})")
    mask = jnp.zeros((n,), dtype=bool).at[jnp.asarray(worlds)].set(True)
    return estate.replace(
        now=jnp.where(mask, jnp.asarray(FROZEN_NOW, I64), estate.now))


def frozen_worlds(estate):
    """World indices currently parked at `FROZEN_NOW` (sorted list).

    Quarantine state lives IN the state tree, so a resumed run
    re-derives its quarantine set from the loaded checkpoint with no
    side-channel bookkeeping.  Returns [] for a solo state."""
    if world_count(estate) is None:
        return []
    nows = np.asarray(jax.device_get(estate.now)).ravel()
    return [int(k) for k, t in enumerate(nows) if int(t) >= FROZEN_NOW]


def shard_worlds(estate, eparams, mesh=None):
    """Place a stacked ensemble world-major across the hosts mesh.

    The WORLD axis shards over the existing 1-D device mesh
    (parallel/sharding.HOST_AXIS): each device owns complete worlds, so
    the vmapped graph partitions with zero collectives.  Requires
    n_worlds % n_devices == 0 (worlds are whole; there is nothing
    meaningful to pad them with).  See module docstring for why the
    2-D world x hosts mesh is deferred."""
    from ..parallel.sharding import HOST_AXIS, make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        mesh = make_mesh()
    n = world_count(estate)
    if n is None:
        raise ValueError("shard_worlds(): state has no world axis; "
                         "stack() worlds first")
    d = mesh.devices.size
    if n % d:
        raise ValueError(
            f"shard_worlds(): {n} worlds do not divide over {d} devices; "
            f"run a multiple of {d} worlds (worlds are never split)")
    sh = NamedSharding(mesh, P(HOST_AXIS))
    put = lambda x: jax.device_put(x, sh)
    return (jax.tree_util.tree_map(put, estate),
            jax.tree_util.tree_map(put, eparams))
