"""shadow.config.xml parsing.

Implements the reference's simulation-spec schema
(/root/reference/src/main/core/support/configuration.c per
configuration.h:24-101): `<shadow stoptime bootstraptime>`, `<topology
path|cdata>`, `<plugin id path>`, `<host id quantity iphint *hints
bandwidthdown/up ...>` containing `<process plugin starttime stoptime
arguments>`.  Existing reference configs parse unchanged; attributes tied
to real-process execution (preload, startsymbol) are accepted and carried
through for the future real-code substrate.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import xml.etree.ElementTree as ET

# Silent acceptance is worse than rejection (reference consumes every
# configuration.h attribute); anything outside these sets triggers a loud
# warning so config typos cannot pass unnoticed.
_SHADOW_ATTRS = {"stoptime", "bootstraptime", "environment", "preload"}
_HOST_ATTRS = {"id", "quantity", "iphint", "citycodehint", "countrycodehint",
               "geocodehint", "typehint", "bandwidthdown", "bandwidthup",
               "interfacebuffer", "socketrecvbuffer", "socketsendbuffer",
               "cpufrequency", "loglevel", "heartbeatfrequency", "logpcap",
               "pcapdir", "heartbeatloglevel", "heartbeatloginfo"}
_PROCESS_ATTRS = {"plugin", "starttime", "time", "stoptime", "arguments",
                  "preload"}
_PLUGIN_ATTRS = {"id", "path", "startsymbol"}
_NETEM_ATTRS = {"churnrate", "churndowntime", "churnstart", "churnend"}
_NETEM_EVENT_ATTRS = {"time", "kind", "a", "b", "value", "groups"}
_NETEM_GROUP_ATTRS = {"host", "id"}


def _warn_unknown(tag, el, known):
    for a in el.keys():
        if a not in known:
            print(f"[shadow1-tpu] WARNING: unknown <{tag}> attribute "
                  f"{a!r} ignored (known: {sorted(known)})", file=sys.stderr)


@dataclasses.dataclass
class ProcessSpec:
    plugin: str
    starttime_s: int
    arguments: str
    stoptime_s: int | None = None
    preload: str | None = None


@dataclasses.dataclass
class HostSpec:
    id: str
    processes: list
    quantity: int = 1
    iphint: str | None = None
    citycodehint: str | None = None
    countrycodehint: str | None = None
    geocodehint: str | None = None
    typehint: str | None = None
    bandwidthdown_KiBps: int | None = None
    bandwidthup_KiBps: int | None = None
    interfacebuffer: int | None = None
    socketrecvbuffer: int | None = None
    socketsendbuffer: int | None = None
    cpufrequency: int | None = None
    loglevel: str | None = None
    heartbeatfrequency_s: int | None = None
    logpcap: bool = False
    pcapdir: str | None = None

    def hints(self) -> dict:
        return {k: getattr(self, k) for k in
                ("iphint", "citycodehint", "countrycodehint", "geocodehint",
                 "typehint") if getattr(self, k)}


@dataclasses.dataclass
class PluginSpec:
    id: str
    path: str
    startsymbol: str | None = None


@dataclasses.dataclass
class NetemSpec:
    """<netem> fault/dynamics section (docs/netem.md).  `events` uses the
    same schema as the --netem JSON file ({"time", "kind", "a", "b",
    "value", "groups"}, time in seconds, hosts by name); `groups` maps
    host name -> partition group id.  Churn attributes switch on seeded
    chaos mode over every host."""

    events: list = dataclasses.field(default_factory=list)
    groups: dict = dataclasses.field(default_factory=dict)
    churn_rate: float | None = None       # flaps/host/second
    churn_downtime_s: float = 5.0         # mean down-time
    churn_start_s: float = 0.0
    churn_end_s: float | None = None      # default: stoptime


@dataclasses.dataclass
class ShadowConfig:
    stoptime_s: int
    bootstrap_end_s: int
    topology_path: str | None    # resolved against the config's directory
    topology_cdata: str | None
    plugins: dict               # id -> PluginSpec
    hosts: list                 # [HostSpec]
    environment: str | None = None
    preload_path: str | None = None
    base_dir: str = "."
    netem: NetemSpec | None = None

    def topology_source(self) -> str:
        """What routing/graphml.load accepts: inline XML or a path."""
        if self.topology_cdata:
            return self.topology_cdata
        if self.topology_path:
            return self.topology_path
        raise ValueError("config has no <topology>")


def _int(el, name, default=None):
    v = el.get(name)
    return default if v is None else int(v)


def parse(path_or_xml: str) -> ShadowConfig:
    """Parse a shadow.config.xml file path or literal XML string."""
    if path_or_xml.lstrip().startswith("<"):
        text, base = path_or_xml, "."
    else:
        with open(path_or_xml) as f:
            text = f.read()
        base = os.path.dirname(os.path.abspath(path_or_xml))
    root = ET.fromstring(text)
    if root.tag != "shadow":
        raise ValueError(f"expected <shadow> root, got <{root.tag}>")
    _warn_unknown("shadow", root, _SHADOW_ATTRS)
    stoptime = _int(root, "stoptime")
    if stoptime is None:
        raise ValueError("<shadow> requires stoptime")

    topo_path = topo_cdata = None
    plugins: dict = {}
    hosts: list = []
    netem_spec = None
    for el in root:
        if el.tag == "topology":
            p = el.get("path")
            if p:
                p = os.path.expanduser(p)
                topo_path = p if os.path.isabs(p) else os.path.join(base, p)
            if el.text and el.text.strip():
                topo_cdata = el.text.strip()
        elif el.tag == "plugin":
            _warn_unknown("plugin", el, _PLUGIN_ATTRS)
            pid = el.get("id")
            plugins[pid] = PluginSpec(id=pid, path=el.get("path") or "",
                                      startsymbol=el.get("startsymbol"))
        elif el.tag == "netem":
            _warn_unknown("netem", el, _NETEM_ATTRS)
            cr = el.get("churnrate")
            netem_spec = NetemSpec(
                churn_rate=float(cr) if cr is not None else None,
                churn_downtime_s=float(el.get("churndowntime") or 5.0),
                churn_start_s=float(el.get("churnstart") or 0.0),
                churn_end_s=(float(el.get("churnend"))
                             if el.get("churnend") else None),
            )
            for ne in el:
                if ne.tag == "event":
                    _warn_unknown("event", ne, _NETEM_EVENT_ATTRS)
                    ev = {"time": float(ne.get("time") or 0),
                          "kind": ne.get("kind")}
                    for k in ("a", "b"):
                        if ne.get(k) is not None:
                            ev[k] = ne.get(k)
                    if ne.get("value") is not None:
                        ev["value"] = float(ne.get("value"))
                    if ne.get("groups"):
                        ev["groups"] = [int(g) for g in
                                        ne.get("groups").split(",") if g]
                    netem_spec.events.append(ev)
                elif ne.tag == "group":
                    _warn_unknown("group", ne, _NETEM_GROUP_ATTRS)
                    netem_spec.groups[ne.get("host")] = \
                        int(ne.get("id") or 0)
        elif el.tag == "host" or el.tag == "node":  # "node" = legacy alias
            _warn_unknown(el.tag, el, _HOST_ATTRS)
            procs = []
            for pe in el:
                if pe.tag not in ("process", "application"):
                    continue
                _warn_unknown(pe.tag, pe, _PROCESS_ATTRS)
                st = pe.get("starttime") or pe.get("time")
                procs.append(ProcessSpec(
                    plugin=pe.get("plugin"),
                    starttime_s=int(st) if st is not None else 0,
                    arguments=pe.get("arguments") or "",
                    stoptime_s=_int(pe, "stoptime"),
                    preload=pe.get("preload"),
                ))
            hosts.append(HostSpec(
                id=el.get("id"),
                processes=procs,
                quantity=_int(el, "quantity", 1) or 1,
                iphint=el.get("iphint"),
                citycodehint=el.get("citycodehint"),
                countrycodehint=el.get("countrycodehint"),
                geocodehint=el.get("geocodehint"),
                typehint=el.get("typehint"),
                bandwidthdown_KiBps=_int(el, "bandwidthdown"),
                bandwidthup_KiBps=_int(el, "bandwidthup"),
                interfacebuffer=_int(el, "interfacebuffer"),
                socketrecvbuffer=_int(el, "socketrecvbuffer"),
                socketsendbuffer=_int(el, "socketsendbuffer"),
                cpufrequency=_int(el, "cpufrequency"),
                loglevel=el.get("loglevel"),
                heartbeatfrequency_s=_int(el, "heartbeatfrequency"),
                logpcap=(el.get("logpcap") or "").lower() == "true",
                pcapdir=el.get("pcapdir"),
            ))

    return ShadowConfig(
        stoptime_s=stoptime,
        bootstrap_end_s=_int(root, "bootstraptime", 0) or 0,
        topology_path=topo_path,
        topology_cdata=topo_cdata,
        plugins=plugins,
        hosts=hosts,
        environment=root.get("environment"),
        preload_path=root.get("preload"),
        base_dir=base,
        netem=netem_spec,
    )
