"""Config front end: shadow.config.xml + GraphML -> runnable simulation.

`shadowxml.parse` reads the reference's XML schema; `assemble.build`
lowers it onto the TPU engine (the analog of master/slave setup,
/root/reference/src/main/core/master.c:161-398).
"""

from .assemble import Assembled, build, load  # noqa: F401
from .shadowxml import ShadowConfig, parse  # noqa: F401
