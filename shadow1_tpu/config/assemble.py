"""Simulation assembly: shadow.config.xml + GraphML -> runnable sim.

The reference path is master_new -> _master_loadConfiguration /
_master_loadTopology -> slave_addNewVirtualHost (dns_register,
topology_attach, interfaces, router) -> slave_addNewVirtualProcess
(/root/reference/src/main/core/master.c:161-238,271-398,
slave.c:296-336).  This module is that pipeline for the TPU engine:
expand <host quantity=N>, register DNS names/IPs, attach every host to a
topology vertex through the hint ladder, pull per-vertex bandwidths into
NetParams, precompute APSP routing matrices on device, and lower
<process> elements onto modeled applications (tgen action graphs).
"""

from __future__ import annotations

import dataclasses
import os

import jax.numpy as jnp
import numpy as np

import shadow1_tpu as _pkg

from ..apps import tgen as tgen_app
from ..core import simtime
from ..core.params import (NetParams, QDISC_FIFO, QDISC_RR,
                           make_net_params)
from ..core.state import make_sim_state
from ..routing import apsp, graphml
from ..routing.dns import DNS
from ..transport import cong as _cong
from ..transport import tcp

SEC = simtime.SIMTIME_ONE_SECOND

# Reference bandwidth attributes are KiB/s (docs/3.2-Network-Config.md).
_KIB = 1024
# Fallback when neither the host element nor its vertex specifies one.
_DEFAULT_BW_KIBPS = 102400  # 100 MiB/s
# Virtual CPU model base: a 3 GHz machine spends ~1us of CPU per
# simulation event; a host configured with cpufrequency F KHz pays
# 1us * (3e6 / F) per event (reference cpu.c frequencyRatio).
_BASE_CPU_KHZ = 3_000_000
_BASE_EVENT_NS = 1_000


@dataclasses.dataclass
class Assembled:
    """Everything the CLI / driver needs to run and report."""

    state: object            # SimState
    params: NetParams
    app: object
    hostnames: list          # [H]
    dns: DNS
    topology: graphml.Topology
    config: object           # ShadowConfig
    stop_time: int           # ns
    pcap_mask: object = None        # [H] bool: <host logpcap="true">
    pcap_dirs: dict = None          # host index -> pcapdir
    heartbeat_freq_s: object = None  # [H] i64, 0 = default
    loglevels: list = None          # per-host loglevel strings
    real_procs: list = None   # [(host_index, argv, start_ns, stop_ns|None)]
    netem: object = None      # netem.Timeline installed on state, or None


def _expand_hosts(cfg):
    """<host quantity=N> -> N hosts named id, or id1..idN when N > 1
    (reference master.c:309-320)."""
    names, specs = [], []
    for hs in cfg.hosts:
        q = max(1, hs.quantity)
        for i in range(q):
            names.append(hs.id if q == 1 else f"{hs.id}{i + 1}")
            specs.append(hs)
    return names, specs


def _plugin_path(cfg, plugin_id: str) -> str | None:
    """Resolve a plugin's path (one resolver for classification AND
    spawning, so they can never disagree)."""
    spec = cfg.plugins.get(plugin_id)
    if not (spec and spec.path):
        return None
    path = os.path.expanduser(spec.path)
    if not os.path.isabs(path):
        path = os.path.join(cfg.base_dir, path)
    return path


def _plugin_kind(cfg, plugin_id: str) -> str:
    """Classify a plugin: an executable PROGRAM runs as a REAL process
    under the substrate (here fork/exec of the binary itself); a shared
    object (.so, the reference's plugin format) or a known name maps to
    its modeled equivalent (tgen).  Shared objects routinely carry the
    exec bit, so the .so check must come first -- otherwise the same
    config flips between modeled and fork/exec depending on whether the
    plugin file happens to exist on disk."""
    path = _plugin_path(cfg, plugin_id)
    spec = cfg.plugins.get(plugin_id)
    hay = f"{plugin_id} {spec.path if spec else ''}".lower()
    is_shared_obj = bool(path) and (
        path.endswith(".so") or ".so." in os.path.basename(path))
    if is_shared_obj or not (
            path and os.path.isfile(path) and os.access(path, os.X_OK)):
        if "tgen" in hay:
            return "tgen"
        if is_shared_obj:
            raise ValueError(
                f"plugin {plugin_id!r} is a shared object ({path}); "
                f"fork/exec cannot run it and no modeled equivalent is "
                f"known -- point the plugin at an executable program")
        raise ValueError(
            f"plugin {plugin_id!r} is neither an existing executable "
            f"(real-process plugin) nor a known modeled equivalent (tgen)")
    return "real"


def build(cfg, seed: int = 1, sock_slots: int | None = None,
          pool_slab: int = 128, qdisc: str = "fifo",
          cpu_threshold_us: int = -1,
          cpu_precision_us: int = 200, cong: str = "reno",
          bucket: bool = False) -> Assembled:
    """Assemble a parsed ShadowConfig into (state, params, app).

    With `bucket=True` the assembled world is padded up to its shape
    bucket (shapes.pad_world_to_bucket, docs/shapes.md): real-host rows
    stay bitwise identical to the exact-size run, and configs sharing a
    bucket reuse one compiled graph.  Host-side tables (hostnames, DNS,
    pcap masks) keep the real host count.
    """
    names, specs = _expand_hosts(cfg)
    h = len(names)
    if h == 0:
        raise ValueError("config defines no hosts")

    # --- topology + attachment -------------------------------------------
    topo = graphml.load(cfg.topology_source())
    dns = DNS()
    for i, name in enumerate(names):
        dns.register(i, name, requested_ip=specs[i].iphint)
    host_vertex = graphml.attach_all(topo, [s.hints() for s in specs], seed)

    # --- bandwidths (host override, else vertex, else default) -----------
    bw_up = np.empty(h, np.int64)
    bw_dn = np.empty(h, np.int64)
    cpu_ns = np.zeros(h, np.int64)
    snd_buf = np.zeros(h, np.int64)      # 0 = default + autotune
    rcv_buf = np.zeros(h, np.int64)
    iface_pkts = np.zeros(h, np.int32)   # 0 = unbounded
    hb_freq = np.zeros(h, np.int64)      # 0 = tracker default
    pcap_mask = np.zeros(h, bool)
    pcap_dirs: dict = {}
    loglevels: list = [None] * h
    for i, s in enumerate(specs):
        v = host_vertex[i]
        up = s.bandwidthup_KiBps or int(topo.bw_up_KiBps[v]) or _DEFAULT_BW_KIBPS
        dn = s.bandwidthdown_KiBps or int(topo.bw_down_KiBps[v]) or _DEFAULT_BW_KIBPS
        bw_up[i], bw_dn[i] = up * _KIB, dn * _KIB
        if s.cpufrequency:
            cpu_ns[i] = max(1, (_BASE_EVENT_NS * _BASE_CPU_KHZ)
                            // max(1, s.cpufrequency))
        if s.socketsendbuffer:
            snd_buf[i] = s.socketsendbuffer
        if s.socketrecvbuffer:
            rcv_buf[i] = s.socketrecvbuffer
        if s.interfacebuffer:
            # Reference interfacebuffer is bytes; the router backlog is
            # packet-counted, so round up in MTUs.
            from ..core.state import MTU
            iface_pkts[i] = max(1, -(-s.interfacebuffer // MTU))
        if s.heartbeatfrequency_s:
            hb_freq[i] = s.heartbeatfrequency_s
        pcap_mask[i] = s.logpcap
        if s.logpcap and s.pcapdir:
            pcap_dirs[i] = s.pcapdir
        loglevels[i] = s.loglevel

    # --- routing matrices -------------------------------------------------
    # Small graphs resolve APSP + parameter packing on the local CPU
    # backend in one shot (eager ops on a tunneled TPU each cost a round
    # trip); big graphs run the Floyd-Warshall on the device, where the
    # O(V^3) relaxation belongs.
    def _routing_and_params():
        lat_ns, rel, jit_ns = apsp.build_matrices(
            jnp.asarray(topo.lat_ms), jnp.asarray(topo.edge_rel),
            self_lat_ms=jnp.asarray(topo.self_lat_ms),
            self_rel=jnp.asarray(topo.self_rel),
            edge_jitter_ms=jnp.asarray(topo.jitter_ms),
            self_jitter_ms=jnp.asarray(topo.self_jitter_ms))
        return make_net_params(
            latency_ns=lat_ns, reliability=rel,
            host_vertex=host_vertex,
            bw_up_Bps=bw_up, bw_down_Bps=bw_dn,
            seed=seed,
            stop_time=cfg.stoptime_s * SEC,
            bootstrap_end=cfg.bootstrap_end_s * SEC,
            jitter_ns=jit_ns,
            cpu_ns_per_event=cpu_ns,
            cpu_threshold_ns=(cpu_threshold_us * 1000
                              if cpu_threshold_us >= 0 else -1),
            cpu_precision_ns=max(1, cpu_precision_us) * 1000,
            qdisc={"fifo": QDISC_FIFO, "rr": QDISC_RR}[qdisc],
            autotune_snd=(snd_buf == 0),
            autotune_rcv=(rcv_buf == 0),
            iface_buf_pkts=iface_pkts,
            pcap_mask=pcap_mask if pcap_mask.any() else None,
            cong=_cong.validate(cong),
        )

    if topo.num_vertices <= 1024:
        params = _pkg.build_on_host(_routing_and_params)
    else:
        params = _routing_and_params()

    # --- connectivity validation (reference topology.c:371-560: a
    # disconnected graph fails at load, not as silent INF latencies at
    # send time).  Only vertices hosts actually attach to must be
    # mutually routable.
    used = np.unique(np.asarray(host_vertex))
    routable = np.array(  # writable copy: the diagonal is cleared below
        apsp.is_routable(params.latency_ns)[jnp.asarray(used)][:, jnp.asarray(used)])
    # Diagonal excluded: same-host loopback never consults the latency
    # matrix, so an isolated single-attached vertex is fine.
    np.fill_diagonal(routable, True)
    if not routable.all():
        # Normalize to unordered pairs (a one-directional hole on a
        # directed topology must still report, not IndexError).
        pairs = sorted({(min(i, j), max(i, j))
                        for i, j in np.argwhere(~routable)})
        vi, vj = used[pairs[0][0]], used[pairs[0][1]]
        raise ValueError(
            f"topology is not connected: no route between attached "
            f"vertices {topo.names[vi]!r} and {topo.names[vj]!r} "
            f"({len(pairs)} unroutable attached-vertex pairs); every "
            f"pair of vertices that hosts attach to must be connected")

    # --- processes -> modeled apps ---------------------------------------
    # Each distinct tgen arguments file is one parsed action graph; a
    # host's process points it at that graph.
    graph_of_args: dict = {}
    graphs: list = []
    host_graph = np.full(h, -1, np.int64)
    start_t = np.zeros(h, np.int64)
    stop_t = np.full(h, simtime.SIMTIME_INVALID, np.int64)
    real_procs: list = []    # (host_index, argv, start_ns, stop_ns|None)
    for i, s in enumerate(specs):
        if not s.processes:
            continue
        for p in s.processes:
            if _plugin_kind(cfg, p.plugin) == "real":
                argv = [_plugin_path(cfg, p.plugin)] + p.arguments.split()
                real_procs.append(
                    (i, argv, p.starttime_s * SEC,
                     p.stoptime_s * SEC if p.stoptime_s else None))
                continue
            if host_graph[i] >= 0:
                raise ValueError(f"host {names[i]!r}: multiple MODELED "
                                 f"processes per host not yet supported "
                                 f"(real-process plugins compose freely)")
            arg = (p.arguments.strip().split()[0]
                   if p.arguments.strip() else "")
            path = arg if os.path.isabs(arg) \
                else os.path.join(cfg.base_dir, arg)
            if path not in graph_of_args:
                graph_of_args[path] = len(graphs)
                graphs.append(tgen_app.parse_tgen(path))
            host_graph[i] = graph_of_args[path]
            start_t[i] = p.starttime_s * SEC
            if p.stoptime_s:
                stop_t[i] = p.stoptime_s * SEC

    # --- sizing -----------------------------------------------------------
    # Server fan-in bounds the needed socket slots: count clients whose
    # peers list names each server.
    def resolve_peer(spec: str):
        name, _, port = spec.rpartition(":")
        return dns.resolve_name(name).host_index, int(port)

    fan_in = np.zeros(h, np.int64)
    for i in range(h):
        g = host_graph[i]
        if g < 0:
            continue
        for node_peers in graphs[int(g)].peers:
            for ps in node_peers:
                fan_in[resolve_peer(ps)[0]] += 1
    if sock_slots is None:
        sock_slots = int(max(4, min(512, 2 * fan_in.max() + 4)))
        if real_procs:
            # Real processes allocate slots dynamically (sockets, child
            # connections); give them headroom the graph analysis above
            # cannot see.
            sock_slots = max(sock_slots, 16)

    # Packets occupy the *source* host's pool slab until consumed, so a
    # high-fan-in server needs slab room proportional to its concurrent
    # client count; exhaustion degrades to counted drops + the
    # ERR_POOL_OVERFLOW escape hatch rather than corruption.
    # A config whose fan-in pushes the slab into the known-bad tunnel-
    # backend region (slab >= 128 at 10k+ hosts) gets a loud
    # RuntimeWarning from make_sim_state -- see state.warn_known_bad_pool
    # and tools/repro_tunnel_crash.py; pin pool_slab=64 to stay stable.
    slab = int(max(pool_slab, min(4096, 32 * (1 + fan_in.max()))))

    # State construction is hundreds of small array ops; build it on the
    # local CPU backend and ship the finished pytree to the device once
    # (shadow1_tpu.build_on_host) -- on a tunneled TPU backend each tiny
    # op is a full round trip.
    def _build_state():
        state = make_sim_state(h, sock_slots=sock_slots,
                               pool_capacity=h * slab)
        socks = state.socks
        # Per-host socket-buffer defaults (reference <host
        # socketsendbuffer/socketrecvbuffer> -> host.c:162-220); every
        # socket the host creates starts from these.
        if (snd_buf > 0).any():
            socks = socks.replace(def_snd_buf=jnp.where(
                jnp.asarray(snd_buf > 0), jnp.asarray(snd_buf, jnp.int32),
                socks.def_snd_buf))
        if (rcv_buf > 0).any():
            socks = socks.replace(def_rcv_buf=jnp.where(
                jnp.asarray(rcv_buf > 0), jnp.asarray(rcv_buf, jnp.int32),
                socks.def_rcv_buf))
        for gi, g in enumerate(graphs):
            if g.serverport > 0:
                mask = jnp.asarray(host_graph == gi)
                socks = tcp.listen_v(socks, mask, 0, g.serverport,
                                     backlog=int(fan_in.max()) + 1)
        state = state.replace(socks=socks)
        if real_procs and not graphs:
            # Pure real-process world: the substrate datagram ring is
            # the only on-device app (the tgen interpreter cannot run on
            # zero graphs).
            from ..substrate import devapp
            return state.replace(app=devapp.init_state(h))
        tg_state = tgen_app.build_state(
            h, graphs, host_graph, start_t, stop_t,
            resolve_peer=resolve_peer)
        if real_procs:
            # Real processes need the device-side datagram ring; compose
            # it with the modeled tgen interpreter (apps/compose.py).
            from ..substrate import devapp
            return state.replace(app=(devapp.init_state(h), tg_state))
        return state.replace(app=tg_state)

    state = _pkg.build_on_host(_build_state)

    # --- netem (<netem> section): fault/dynamics schedule -----------------
    netem_tl = None
    if cfg.netem is not None:
        from .. import netem as _netem
        spec = cfg.netem
        netem_tl = _netem.load_json(
            {"events": spec.events, "groups": spec.groups},
            resolve=lambda n: dns.resolve_name(n).host_index)
        if spec.churn_rate:
            end_s = (spec.churn_end_s if spec.churn_end_s is not None
                     else cfg.stoptime_s)
            netem_tl.chaos(params.seed_key, h, spec.churn_rate,
                           mean_down_s=spec.churn_downtime_s,
                           t_start=int(spec.churn_start_s * SEC),
                           t_end=int(end_s * SEC))
        state, params = _netem.install(state, params, netem_tl)

    if real_procs:
        from ..apps.compose import Stacked
        from ..substrate import devapp
        if graphs:
            app = Stacked(devapp.SubstrateTx(), tgen_app.Tgen())
        else:
            app = devapp.SubstrateTx()
    else:
        app = tgen_app.Tgen()

    if bucket:
        from .. import shapes
        state, params = shapes.pad_world_to_bucket(state, params)

    return Assembled(state=state, params=params, app=app, hostnames=names,
                     dns=dns, topology=topo, config=cfg,
                     stop_time=cfg.stoptime_s * SEC,
                     pcap_mask=pcap_mask, pcap_dirs=pcap_dirs,
                     heartbeat_freq_s=hb_freq, loglevels=loglevels,
                     real_procs=real_procs, netem=netem_tl)


def load(path: str, **kw) -> Assembled:
    from . import shadowxml
    return build(shadowxml.parse(path), **kw)
