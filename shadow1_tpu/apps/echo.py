"""Modeled TCP echo server: mirrors every received byte back to the peer.

The first real-code workload's counterpart (tests/test_substrate.py): a
real client binary talks to this on-device model, so the whole transport
path -- handshake, windows, delivery timing -- is exercised end-to-end
while the server side stays a pure vectorized app.  Equivalent role to
the reference's shadow-plugin test servers (src/test/tcp/test_tcp.c
server mode).
"""

from __future__ import annotations

from flax import struct
import jax.numpy as jnp

from ..core import simtime
from ..core.state import I64, SOCK_TCP, TCPS_CLOSEWAIT, TCPS_ESTABLISHED
from ..transport.tcp import _sdiff, data_end as tcp_data_end


@struct.dataclass
class EchoState:
    is_server: jnp.ndarray   # [H] bool


class EchoServer:
    """Echo every readable byte on every established server socket."""

    uses_tcp = True
    may_loopback = False
    rx_batch = 4

    def __hash__(self):
        return hash("echo-server")

    def __eq__(self, other):
        return isinstance(other, EchoServer)

    def next_time(self, state):
        h = state.app.is_server.shape[0]
        return jnp.full((h,), simtime.SIMTIME_INVALID, I64)

    def on_tick(self, state, params, em, tick_t, active):
        a = state.app
        socks = state.socks
        srv = a.is_server[:, None] & active[:, None]

        # Children of a listener carry parent >= 0; those are the data
        # sockets (the listener itself never reaches ESTABLISHED).
        live = (socks.stype == SOCK_TCP) & (socks.parent >= 0) & srv & (
            (socks.tcp_state == TCPS_ESTABLISHED) |
            (socks.tcp_state == TCPS_CLOSEWAIT))

        # Clamp at the FIN: without it the echo appends one phantom byte
        # to its reply before closing (tcp.data_end docstring).
        data_end = tcp_data_end(socks)
        avail = _sdiff(data_end, socks.rcv_read)
        used = _sdiff(socks.snd_end, socks.snd_una)
        room = jnp.maximum(socks.snd_buf_cap - used, 0)
        n = jnp.clip(jnp.minimum(avail, room), 0)
        do = live & (n > 0)
        # Writing into a zero peer window must arm the persist timer or
        # nothing ever fires for the socket again (same rule as
        # tcp.write_v; the window-reopening ACK can be lost).
        blocked = do & (socks.snd_wnd == 0) & \
            (socks.t_persist == simtime.SIMTIME_INVALID) & \
            (socks.t_rto == simtime.SIMTIME_INVALID)
        socks = socks.replace(
            snd_end=jnp.where(do, socks.snd_end + n.astype(jnp.uint32),
                              socks.snd_end),
            rcv_read=jnp.where(do, socks.rcv_read + n.astype(jnp.uint32),
                               socks.rcv_read),
            t_persist=jnp.where(blocked, tick_t[:, None] + socks.rto,
                                socks.t_persist),
        )

        # Peer closed and everything echoed: close our side too.
        done = live & (socks.tcp_state == TCPS_CLOSEWAIT) & \
            (_sdiff(data_end, socks.rcv_read) <= 0) & ~socks.app_closed
        socks = socks.replace(app_closed=socks.app_closed | done)
        return state.replace(socks=socks), em


def init_state(is_server) -> EchoState:
    return EchoState(is_server=jnp.asarray(is_server, bool))
