"""Modeled tgen: a traffic-generator action-graph interpreter.

The reference's bring-up workloads run the real `tgen` plugin, which walks
a GraphML *action graph* (/root/reference/resource/examples/
tgen.client.graphml.xml): `start` (peers list) -> `stream`/`transfer`
(sendsize/recvsize) -> `end` (count/time bounds) -> `pause` (time choices)
-> back to `start`.  Servers run a graph with a single `start` node
carrying `serverport`.

Here the same graphs drive an on-device model: the parsed action tables
live in the app-state pytree, every host holds a cursor into its graph,
and one vectorized tick advances every host's interpreter.  A stream is a
paired TCP exchange: the client connects, writes `sendsize` bytes and
half-closes; the server (which learns the stream spec from the peer's
app state -- the modeled equivalent of tgen's stream header) replies with
`recvsize` bytes and closes.  Completion = the client saw the full reply
and the connection tore down cleanly.

This is the stepping stone to the real-code substrate: the interpreter
consumes exactly the information a real tgen would put on the wire, so
swapping in real process execution changes the driver, not the protocol
stack underneath.

Documented divergences from real tgen (violations raise at load, never
silently truncate):

* Single action chain: fan-out graphs (a node with several successors)
  are rejected at parse time.
* One shared peers list per graph (conflicting per-node lists rejected);
  stream clients must declare peers or assembly fails.
* The server learns each stream's recvsize from the client's app
  registers instead of a stream header on the wire -- byte counts and
  timing on the wire are the same, the header bytes themselves are not
  modeled.
* One in-flight stream per host at a time (CLIENT_SLOT), and one process
  per host (config/assemble.py rejects multi-process hosts).
"""

from __future__ import annotations

import dataclasses
import xml.etree.ElementTree as ET

from flax import struct
import jax.numpy as jnp
import numpy as np

from ..core import rng, simtime
from ..core.state import (I32, I64, U32, SOCK_TCP, TCPS_CLOSED,
                          TCPS_CLOSEWAIT, TCPS_ESTABLISHED, TCPS_LASTACK,
                          TCPS_TIMEWAIT, host_ids)
from ..transport import tcp

INV = simtime.SIMTIME_INVALID
SEC = simtime.SIMTIME_ONE_SECOND

# Action-node types.
NT_START = 0
NT_STREAM = 1
NT_END = 2
NT_PAUSE = 3

CLIENT_SLOT = 1      # client-side connection slot (0 = server listener)
EPH_BASE = 41000     # ephemeral local ports cycle so 4-tuples never collide
EPH_RANGE = 20000


# ---------------------------------------------------------------------------
# tgen GraphML parsing (host-side, setup time)
# ---------------------------------------------------------------------------

_NS = "{http://graphml.graphdrawing.org/xmlns}"

_SIZE_UNITS = {
    "b": 1, "byte": 1, "bytes": 1,
    "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12,
    "kib": 1 << 10, "mib": 1 << 20, "gib": 1 << 30, "tib": 1 << 40,
}


def parse_size(text: str) -> int:
    """'1 MiB' / '100 kb' / '5242880' -> bytes (tgen size grammar)."""
    parts = str(text).strip().split()
    if len(parts) == 1:
        return int(float(parts[0]))
    if len(parts) == 2:
        unit = parts[1].lower()
        if unit not in _SIZE_UNITS:
            raise ValueError(f"unknown size unit {parts[1]!r}")
        return int(float(parts[0]) * _SIZE_UNITS[unit])
    raise ValueError(f"cannot parse size {text!r}")


def _parse_times_s(text: str):
    """'1,2,3' or '5' -> list of seconds (floats allowed)."""
    return [float(x) for x in str(text).split(",") if x != ""]


@dataclasses.dataclass
class TgenGraph:
    """One parsed tgen action graph (host-side)."""

    node_ids: list          # node id strings
    ntype: np.ndarray       # [N] NT_*
    nxt: np.ndarray         # [N] successor node (local index), -1 = none
    sendsize: np.ndarray    # [N] i64 bytes (stream nodes)
    recvsize: np.ndarray    # [N] i64 bytes
    count: np.ndarray      # [N] i64 loop bound (end nodes), 0 = unbounded
    pause_s: list           # [N] list of pause-time choices (seconds)
    peers: list             # [N] list of "host:port" strings (start nodes)
    serverport: int         # > 0 if this graph is a server
    start_node: int         # entry node index

    @property
    def num_nodes(self) -> int:
        return len(self.node_ids)


_NODE_TYPES = {"start": NT_START, "stream": NT_STREAM, "transfer": NT_STREAM,
               "end": NT_END, "pause": NT_PAUSE}


def parse_tgen(source: str) -> TgenGraph:
    """Parse a tgen GraphML action graph (path or literal XML)."""
    text = source
    if not source.lstrip().startswith("<"):
        with open(source) as f:
            text = f.read()
    root = ET.fromstring(text)
    keys = {}
    for k in root.iter(_NS + "key"):
        keys[k.get("id")] = k.get("attr.name")
    graph = root.find(_NS + "graph")
    if graph is None:
        raise ValueError("tgen graphml has no <graph>")

    ids, attrs = [], []
    for node in graph.findall(_NS + "node"):
        ids.append(node.get("id"))
        d = {}
        for data in node.findall(_NS + "data"):
            d[keys.get(data.get("key"), data.get("key"))] = data.text or ""
        attrs.append(d)
    index = {n: i for i, n in enumerate(ids)}
    n = len(ids)

    # Node type from the id prefix (tgen convention: ids are the action
    # name, optionally suffixed, e.g. "stream", "pause2").
    ntype = np.zeros(n, np.int32)
    for i, nid in enumerate(ids):
        base = "".join(c for c in nid if not c.isdigit()).strip("-_")
        if base not in _NODE_TYPES:
            raise ValueError(f"unknown tgen action {nid!r}")
        ntype[i] = _NODE_TYPES[base]

    nxt = np.full(n, -1, np.int32)
    for edge in graph.findall(_NS + "edge"):
        s = index[edge.get("source")]
        t = index[edge.get("target")]
        if nxt[s] != -1:
            # Real tgen supports fan-out graphs (parallel successors);
            # this model interprets a single action chain.  Refusing is
            # better than silently truncating the workload.
            raise ValueError(
                f"tgen action node {ids[s]!r} has multiple successors; "
                f"the modeled interpreter supports single-chain graphs "
                f"only (real-tgen fan-out is not modeled)")
        nxt[s] = t

    sendsize = np.zeros(n, np.int64)
    recvsize = np.zeros(n, np.int64)
    count = np.zeros(n, np.int64)
    pause_s = [[] for _ in range(n)]
    peers = [[] for _ in range(n)]
    serverport = 0
    for i, d in enumerate(attrs):
        if "sendsize" in d:
            sendsize[i] = parse_size(d["sendsize"])
        if "recvsize" in d:
            recvsize[i] = parse_size(d["recvsize"])
        if "count" in d:
            count[i] = int(float(d["count"]))
        if "time" in d and ntype[i] == NT_PAUSE:
            pause_s[i] = _parse_times_s(d["time"])
        if "peers" in d:
            peers[i] = [p.strip() for p in d["peers"].split(",") if p.strip()]
        if "serverport" in d and ntype[i] == NT_START:
            serverport = int(d["serverport"])

    start = next(i for i in range(n) if ntype[i] == NT_START)
    return TgenGraph(node_ids=ids, ntype=ntype, nxt=nxt, sendsize=sendsize,
                     recvsize=recvsize, count=count, pause_s=pause_s,
                     peers=peers, serverport=serverport, start_node=start)


# ---------------------------------------------------------------------------
# Device-side interpreter state
# ---------------------------------------------------------------------------


@struct.dataclass
class TgenState:
    """Concatenated action tables + per-host interpreter registers."""

    # --- static tables (concatenation of every distinct graph) ---
    ntype: jnp.ndarray       # [N] i32
    nxt: jnp.ndarray         # [N] i32 global successor, -1 = halt
    sendsize: jnp.ndarray    # [N] i64
    recvsize: jnp.ndarray    # [N] i64
    count: jnp.ndarray      # [N] i64
    pause_t: jnp.ndarray     # [N,PC] i64 ns choices (0-padded)
    pause_n: jnp.ndarray     # [N] i32 number of choices
    peer_host: jnp.ndarray   # [N,MP] i32 resolved host index (-1 pad)
    peer_port: jnp.ndarray   # [N,MP] i32
    peer_n: jnp.ndarray      # [N] i32

    # --- per-host registers ---
    cur: jnp.ndarray         # [H] i32 global node index, -1 = no program
    start_t: jnp.ndarray     # [H] i64 process starttime
    stop_t: jnp.ndarray      # [H] i64 process stoptime, INV = none
    started: jnp.ndarray     # [H] bool
    finished: jnp.ndarray    # [H] bool (end-count reached or stopped)
    iters: jnp.ndarray       # [H] i64 completed end-node visits
    wait_until: jnp.ndarray  # [H] i64 pause deadline, INV = not pausing
    t_next: jnp.ndarray      # [H] i64 instant-transition re-tick, INV = none
    stream_active: jnp.ndarray  # [H] bool
    conn_ctr: jnp.ndarray    # [H] i64 streams initiated (port/peer cycling)
    cur_send: jnp.ndarray    # [H] i64 active stream spec (read by servers)
    cur_recv: jnp.ndarray    # [H] i64
    streams_done: jnp.ndarray   # [H] i64 observable: completed streams
    streams_failed: jnp.ndarray  # [H] i64

    # Mesh-padding fills (parallel.pad_world_to_mesh): a zero row is NOT
    # inert here -- cur=0 is a live program at node 0 and t_next=0 is a
    # tick due at t=0.  Leaves not listed pad with zeros.
    PAD_VALUES = {"cur": -1, "start_t": INV, "stop_t": INV,
                  "wait_until": INV, "t_next": INV}


class Tgen:
    """Static app marker (hashable; tables live in TgenState)."""

    # Bursty TCP fan-in: deliver up to 4 queued arrivals per host per
    # micro-step (engine rx_batch rounds).
    rx_batch = 4

    def __init__(self, client_slot: int = CLIENT_SLOT):
        self.client_slot = int(client_slot)

    def __hash__(self):
        return hash(("tgen", self.client_slot))

    def __eq__(self, other):
        return isinstance(other, Tgen) and other.client_slot == self.client_slot

    # -- engine hooks -------------------------------------------------------

    def next_time(self, state):
        a = state.app
        has = a.cur >= 0
        t_start = jnp.where(has & ~a.started, a.start_t, INV)
        t_pause = jnp.where(has & a.started & ~a.finished, a.wait_until, INV)
        return jnp.minimum(jnp.minimum(t_start, t_pause), a.t_next)

    def on_tick(self, state, params, em, tick_t, active):
        a = state.app
        socks = state.socks
        h = a.cur.shape[0]
        # Global host ids: RNG draws must be keyed identically whether the
        # world runs on one device or sharded (docs/parallel.md).
        rows = host_ids(state)
        slot = jnp.full((h,), self.client_slot, I32)

        # -- start / stop ----------------------------------------------------
        a = a.replace(t_next=jnp.where(active, jnp.asarray(INV, I64), a.t_next))
        start_now = active & ~a.started & (a.cur >= 0) & (a.start_t <= tick_t)
        a = a.replace(started=a.started | start_now)
        stopped = active & a.started & (a.stop_t != INV) & (a.stop_t <= tick_t)
        a = a.replace(finished=a.finished | stopped)

        run = active & a.started & ~a.finished & (a.cur >= 0)
        cur = jnp.clip(a.cur, 0, a.ntype.shape[0] - 1)
        ntype = a.ntype[cur]

        advance = jnp.zeros((h,), bool)   # move cur -> nxt this tick

        # -- START: instant hop into the first action ------------------------
        advance = advance | (run & (ntype == NT_START))

        # -- STREAM ----------------------------------------------------------
        at_stream = run & (ntype == NT_STREAM)
        # initiate: connect to the peers list of the nearest upstream start
        # node -- tables put the start node's peers on every node of its
        # graph (see build_state), so gather from `cur` directly.
        init = at_stream & ~a.stream_active
        np_ = jnp.maximum(a.peer_n[cur], 1)
        pk = (a.conn_ctr % np_.astype(I64)).astype(I32)
        dsth = a.peer_host[cur, jnp.clip(pk, 0, a.peer_host.shape[1] - 1)]
        dstp = a.peer_port[cur, jnp.clip(pk, 0, a.peer_port.shape[1] - 1)]
        init = init & (dsth >= 0)
        lport = (EPH_BASE + (a.conn_ctr % EPH_RANGE)).astype(I32)
        socks = tcp.connect_v(socks, init, slot, dsth, dstp, lport, tick_t)
        a = a.replace(
            stream_active=a.stream_active | init,
            conn_ctr=a.conn_ctr + jnp.where(init, 1, 0),
            cur_send=jnp.where(init, a.sendsize[cur], a.cur_send),
            cur_recv=jnp.where(init, a.recvsize[cur], a.cur_recv),
        )

        # progress: stream request bytes into the send buffer, half-close
        # when fully written.
        streaming = at_stream & a.stream_active
        target = (jnp.uint32(1) + a.cur_send.astype(U32))
        socks = tcp.write_v(socks, streaming, slot, target, now=tick_t)
        cs = self.client_slot  # static -> column slices, not gathers
        written = socks.snd_end[:, cs] == target
        socks = tcp.close_v(socks, streaming & written, slot)

        # completion / failure.
        cstate = socks.tcp_state[:, cs]
        got = socks.bytes_recv[:, cs]
        torn = (cstate == TCPS_TIMEWAIT) | (cstate == TCPS_CLOSED)
        ok = streaming & torn & (got >= a.cur_recv)
        bad = streaming & ~ok & (
            (socks.error[:, cs] != 0) |
            (torn & (got < a.cur_recv)))
        fin_stream = ok | bad
        a = a.replace(
            streams_done=a.streams_done + jnp.where(ok, 1, 0),
            streams_failed=a.streams_failed + jnp.where(bad, 1, 0),
            stream_active=a.stream_active & ~fin_stream,
        )
        advance = advance | fin_stream

        # -- END -------------------------------------------------------------
        at_end = run & (ntype == NT_END)
        iters2 = a.iters + jnp.where(at_end, 1, 0)
        cnt = a.count[cur]
        done = at_end & (cnt > 0) & (iters2 >= cnt)
        a = a.replace(iters=iters2, finished=a.finished | done)
        advance = advance | (at_end & ~done)

        # -- PAUSE -----------------------------------------------------------
        at_pause = run & (ntype == NT_PAUSE)
        need_draw = at_pause & (a.wait_until == INV)
        key = rng.purpose_key(params.seed_key, rng.PURPOSE_HOST_APP)
        u = rng.keyed_uniform(key, rows.astype(jnp.uint32),
                              a.conn_ctr.astype(jnp.uint32),
                              a.iters.astype(jnp.uint32))
        pn = jnp.maximum(a.pause_n[cur], 1)
        pick = jnp.minimum((u * pn.astype(jnp.float32)).astype(I32), pn - 1)
        dur = a.pause_t[cur, jnp.clip(pick, 0, a.pause_t.shape[1] - 1)]
        a = a.replace(wait_until=jnp.where(need_draw, tick_t + dur,
                                           a.wait_until))
        pause_done = at_pause & ~need_draw & (a.wait_until <= tick_t)
        a = a.replace(wait_until=jnp.where(pause_done, jnp.asarray(INV, I64),
                                           a.wait_until))
        advance = advance | pause_done

        # -- cursor advance + instant re-tick --------------------------------
        nxt = a.nxt[cur]
        a = a.replace(
            cur=jnp.where(advance, nxt, a.cur),
            finished=a.finished | (advance & (nxt < 0)),
        )
        # Hosts that advanced onto an instantly-runnable node re-tick now.
        a = a.replace(t_next=jnp.where(
            (advance & (nxt >= 0)) | start_now, tick_t, a.t_next))

        # -- server pass (every host, every tick) ----------------------------
        # A child socket's stream spec comes from the connecting peer's app
        # registers -- the modeled stream header.
        child = (socks.stype == SOCK_TCP) & (socks.parent >= 0) & \
            ((socks.tcp_state == TCPS_ESTABLISHED) |
             (socks.tcp_state == TCPS_CLOSEWAIT))
        # peer_host is a GLOBAL id; on a mesh the peer's registers may live
        # on another shard, so gather the two spec columns globally first.
        if state.hoff is None:
            cur_send_g, cur_recv_g = a.cur_send, a.cur_recv
        else:
            import jax
            from ..core.engine import MESH_AXIS
            cur_send_g = jax.lax.all_gather(a.cur_send, MESH_AXIS,
                                            tiled=True)
            cur_recv_g = jax.lax.all_gather(a.cur_recv, MESH_AXIS,
                                            tiled=True)
        peer = jnp.clip(socks.peer_host, 0, cur_send_g.shape[0] - 1)
        want_send = cur_send_g[peer]
        want_recv = cur_recv_g[peer]
        reply_ready = child & (socks.peer_host >= 0) & \
            (socks.bytes_recv >= want_send) & ~socks.app_closed
        rtarget = (jnp.uint32(1) + want_recv.astype(U32))
        # incremental write bounded by the send buffer
        cap_end = (socks.snd_una + socks.snd_buf_cap.astype(U32)).astype(U32)
        step_end = jnp.where(
            (rtarget - socks.snd_una).astype(I32) <=
            (cap_end - socks.snd_una).astype(I32), rtarget, cap_end)
        grow = reply_ready & ((step_end - socks.snd_end).astype(I32) > 0)
        socks = socks.replace(
            snd_end=jnp.where(grow, step_end, socks.snd_end),
            app_closed=jnp.where(reply_ready & (socks.snd_end == rtarget),
                                 True, socks.app_closed),
        )

        # Sink policy: every host consumes what it receives (keeps windows
        # open); orphaned CLOSEWAIT sockets (peer closed, nothing to send)
        # close too.
        socks = tcp.consume_all(socks)

        return state.replace(app=a, socks=socks), em


# ---------------------------------------------------------------------------
# Assembly: graphs + per-host programs -> TgenState
# ---------------------------------------------------------------------------


def build_state(num_hosts: int, graphs: list, host_graph, host_start_t,
                host_stop_t=None, resolve_peer=None):
    """Concatenate parsed TgenGraphs into device tables.

    graphs: list of TgenGraph.
    host_graph: [H] int, graph index per host (-1 = no tgen program).
    host_start_t / host_stop_t: [H] ns.
    resolve_peer: callable "host:port" -> (host_index, port); required if
      any graph has peers.
    """
    max_p = max([1] + [len(g.pause_s[i]) for g in graphs
                       for i in range(g.num_nodes)])
    max_peer = max([1] + [len(g.peers[i]) for g in graphs
                          for i in range(g.num_nodes)])
    ntype, nxt, sendsize, recvsize, count = [], [], [], [], []
    pause_t = []
    pause_n = []
    peer_host, peer_port, peer_n = [], [], []
    offsets = []
    off = 0
    for g in graphs:
        offsets.append(off)
        n = g.num_nodes
        # peers propagate from the start node to every node of the graph so
        # stream nodes can gather them without a second indirection.
        g_ph = [-1] * max_peer
        g_pp = [0] * max_peer
        g_pn = 0
        seen_peers = None
        for i in range(n):
            if g.peers[i]:
                if seen_peers is not None and g.peers[i] != seen_peers:
                    raise ValueError(
                        f"tgen graph defines conflicting peers lists "
                        f"({seen_peers} vs {g.peers[i]}); the modeled "
                        f"interpreter shares one peers list per graph")
                seen_peers = g.peers[i]
                for j, spec in enumerate(g.peers[i][:max_peer]):
                    hidx, port = resolve_peer(spec)
                    g_ph[j], g_pp[j] = hidx, port
                g_pn = len(g.peers[i][:max_peer])
        # A client graph with stream actions but no resolvable peers would
        # hang at the stream node forever (init never fires); fail loudly
        # at assembly instead.
        has_stream = any(int(t) == NT_STREAM for t in g.ntype)
        if has_stream and g.serverport <= 0 and g_pn == 0:
            raise ValueError(
                "tgen client graph has stream actions but no peers list; "
                "add a 'peers' attribute (host:port, ...) to the start or "
                "stream node")
        for i in range(n):
            ntype.append(int(g.ntype[i]))
            nxt.append(off + int(g.nxt[i]) if g.nxt[i] >= 0 else -1)
            sendsize.append(int(g.sendsize[i]))
            recvsize.append(int(g.recvsize[i]))
            count.append(int(g.count[i]))
            ts = [int(round(s * SEC)) for s in g.pause_s[i]][:max_p]
            pause_t.append(ts + [0] * (max_p - len(ts)))
            pause_n.append(len(ts))
            peer_host.append(list(g_ph))
            peer_port.append(list(g_pp))
            peer_n.append(g_pn)
        off += n

    hg = np.asarray(host_graph, np.int64)
    cur0 = np.full(num_hosts, -1, np.int32)
    for hh in range(num_hosts):
        if hg[hh] >= 0:
            g = graphs[int(hg[hh])]
            # Server graphs (start node only / no successor) never run an
            # interpreter; their listener is installed at assembly.
            if g.serverport <= 0:
                cur0[hh] = offsets[int(hg[hh])] + g.start_node

    if host_stop_t is None:
        host_stop_t = np.full(num_hosts, INV, np.int64)

    zh = lambda dt: jnp.zeros((num_hosts,), dt)
    return TgenState(
        ntype=jnp.asarray(ntype, I32),
        nxt=jnp.asarray(nxt, I32),
        sendsize=jnp.asarray(sendsize, I64),
        recvsize=jnp.asarray(recvsize, I64),
        count=jnp.asarray(count, I64),
        pause_t=jnp.asarray(pause_t, I64),
        pause_n=jnp.asarray(pause_n, I32),
        peer_host=jnp.asarray(peer_host, I32),
        peer_port=jnp.asarray(peer_port, I32),
        peer_n=jnp.asarray(peer_n, I32),
        cur=jnp.asarray(cur0, I32),
        start_t=jnp.asarray(host_start_t, I64),
        stop_t=jnp.asarray(host_stop_t, I64),
        started=zh(jnp.bool_),
        finished=zh(jnp.bool_),
        iters=zh(I64),
        wait_until=jnp.full((num_hosts,), INV, I64),
        t_next=jnp.full((num_hosts,), INV, I64),
        stream_active=zh(jnp.bool_),
        conn_ctr=zh(I64),
        cur_send=zh(I64),
        cur_recv=zh(I64),
        streams_done=zh(I64),
        streams_failed=zh(I64),
    )
