"""Onion-circuit traffic model: multi-hop store-and-forward TCP chains.

The Tor-scale rung of the benchmark ladder (BASELINE.json configs 3/5)
needs onion-routing *traffic shape* -- every circuit is a chain of TCP
hops client -> guard -> middle -> exit -> server, with each relay
store-and-forwarding the stream hop by hop -- without executing real Tor.
This app models exactly that: clients push a stream of cells into their
circuit, every relay forwards bytes from its inbound (accepted) socket to
its outbound connection, and the destination server counts delivery.

Modeled simplifications (documented divergences from real Tor):

* Each circuit gets dedicated relay hosts (one forwarding lane per
  relay) instead of multiplexing many circuits per relay -- the per-hop
  transport work and traffic pattern are identical, the sharing is not.
* One-way cell flow (client -> server); no directory/handshake traffic.
* Cells are byte-stream quantities (512-byte cells arrive back to back,
  so the byte counts and pacing match; cell framing is not modeled).

Roles are positions in a circuit chain: hop 0 = client (originates
`total_bytes`), hops 1..n-2 = relays (forward), hop n-1 = server (sink).
"""

from __future__ import annotations

from flax import struct
import jax.numpy as jnp
import numpy as np

from ..core import simtime
from ..core.state import (I32, I64, SOCK_TCP, TCPS_CLOSEWAIT, host_ids,
                          TCPS_ESTABLISHED, U32)
from ..transport import tcp
from ..transport.tcp import _sdiff

INV = simtime.SIMTIME_INVALID

ONION_PORT = 9001
CLIENT_SLOT = 0     # outbound connection slot on clients and relays
CELL = 512


@struct.dataclass
class OnionState:
    role: jnp.ndarray        # [H] i32: 0 client, 1 relay, 2 server, -1 idle
    next_hop: jnp.ndarray    # [H] i32 downstream host (-1 for servers/idle)
    total: jnp.ndarray       # [H] i64 bytes the circuit's client pushes
    start_t: jnp.ndarray     # [H] i64 client start time
    started: jnp.ndarray     # [H] bool outbound connection opened
    done_t: jnp.ndarray      # [H] i64 server-side completion time (INV)
    forwarded: jnp.ndarray   # [H] i64 bytes this host moved downstream


class Onion:
    """Vectorized circuit interpreter (client send + relay forward)."""

    uses_tcp = True
    may_loopback = False
    # Relays see back-to-back cell bursts: batch arrival delivery.
    rx_batch = 4

    def __hash__(self):
        return hash("onion")

    def __eq__(self, other):
        return isinstance(other, Onion)

    def next_time(self, state):
        # Clients AND relays wake at their start times (a relay that only
        # woke on inbound traffic would open its outbound in the same tick
        # the first SYN spawns a child -- and clobber it in CLIENT_SLOT).
        a = state.app
        return jnp.where((a.role >= 0) & (a.role <= 1) & ~a.started &
                         (a.next_hop >= 0), a.start_t,
                         jnp.asarray(INV, I64))

    def on_tick(self, state, params, em, tick_t, active):
        a = state.app
        socks = state.socks
        h = a.role.shape[0]
        slot = jnp.full((h,), CLIENT_SLOT, I32)

        # -- open outbound connections at start_t.  Relays start BEFORE
        # any client can reach them (build staggers relay starts first):
        # the outbound connection must occupy CLIENT_SLOT before an
        # inbound SYN spawns a child there (children take the lowest free
        # slot).
        want = active & ~a.started & (a.next_hop >= 0) & \
            (a.role <= 1) & (a.start_t <= tick_t)
        # Local ports derive from the GLOBAL host id (identity
        # off-mesh): ports are on the wire, so a shard-local index
        # would break the mesh determinism contract.
        lport = (20000 + host_ids(state, I32) % 20000)
        socks = tcp.connect_v(socks, want, slot, a.next_hop, ONION_PORT,
                              lport, tick_t)
        a = a.replace(started=a.started | want)

        # -- clients: stream total bytes into the outbound socket, then
        # half-close (FIN cascades down the circuit).
        is_cli = active & (a.role == 0) & a.started
        target = (jnp.uint32(1) + a.total.astype(U32))
        socks = tcp.write_v(socks, is_cli, slot, target, now=tick_t)
        cs = CLIENT_SLOT
        written_all = socks.snd_end[:, cs] == target
        socks = tcp.close_v(socks, is_cli & written_all, slot)

        # -- relays: forward inbound bytes to the outbound socket.
        # Inbound legs are accepted children (parent >= 0); a relay serves
        # one circuit, so the sum over child sockets is its one leg.
        child = (socks.stype == SOCK_TCP) & (socks.parent >= 0)
        # Readable DATA bytes: the FIN consumes a sequence number too
        # (rcv_nxt passes it), but it must not be forwarded as payload.
        data_end = tcp.data_end(socks)
        avail2 = jnp.where(child, _sdiff(data_end, socks.rcv_read), 0)
        avail2 = jnp.maximum(avail2, 0)
        in_avail = jnp.sum(avail2, axis=1)
        out_est = (socks.tcp_state[:, cs] == TCPS_ESTABLISHED) | \
            (socks.tcp_state[:, cs] == TCPS_CLOSEWAIT)
        out_used = _sdiff(socks.snd_end[:, cs], socks.snd_una[:, cs])
        out_room = jnp.maximum(socks.snd_buf_cap[:, cs] - out_used, 0)
        fwd = jnp.where(active & (a.role == 1) & a.started & out_est,
                        jnp.minimum(in_avail, out_room), 0)
        do_fwd = fwd > 0
        socks = tcp.write_v(socks, do_fwd, slot,
                            (socks.snd_end[:, cs] + fwd.astype(U32)),
                            now=tick_t)
        # Consume forwarded bytes from the inbound leg (single child, so a
        # full-row masked drain up to `fwd` is exact).
        take2 = jnp.where(child & do_fwd[:, None],
                          jnp.minimum(avail2, fwd[:, None]), 0)
        socks = socks.replace(
            rcv_read=socks.rcv_read + take2.astype(jnp.uint32))
        a = a.replace(forwarded=a.forwarded + fwd)

        # -- servers: consume and count.
        is_srv = (a.role == 2)
        srv_take = jnp.where(is_srv[:, None] & child & active[:, None],
                             avail2, 0)
        socks = socks.replace(
            rcv_read=socks.rcv_read + srv_take.astype(jnp.uint32))
        got = a.forwarded + jnp.sum(srv_take, axis=1)
        newly_done = active & is_srv & (a.done_t == INV) & \
            (got >= a.total) & (a.total > 0)
        a = a.replace(forwarded=got,
                      done_t=jnp.where(newly_done, tick_t, a.done_t))

        # -- teardown cascade: inbound leg closed & fully drained -> close
        # our outbound leg too (relays), mirroring the echo server logic.
        in_closed = jnp.any(child & (socks.tcp_state == TCPS_CLOSEWAIT),
                            axis=1)
        drained = in_avail <= 0
        relay_done = active & (a.role == 1) & a.started & in_closed & drained
        socks = tcp.close_v(socks, relay_done, slot)
        closewait = child & (socks.tcp_state == TCPS_CLOSEWAIT) & \
            (avail2 - take2 - srv_take <= 0) & active[:, None] & \
            ~socks.app_closed
        socks = socks.replace(app_closed=socks.app_closed | closewait)

        return state.replace(app=a, socks=socks), em


def build_circuits(num_circuits: int, hops: int = 3, seed: int = 1):
    """Host layout: per circuit, 1 client + `hops` relays + 1 server
    (dedicated hosts; see module docstring).  Returns role/next_hop arrays
    of length num_circuits * (hops + 2)."""
    per = hops + 2
    h = num_circuits * per
    role = np.full(h, -1, np.int32)
    nxt = np.full(h, -1, np.int32)
    for c in range(num_circuits):
        base = c * per
        for k in range(per):
            role[base + k] = 0 if k == 0 else (2 if k == per - 1 else 1)
            if k < per - 1:
                nxt[base + k] = base + k + 1
    return role, nxt


def init_state(role, next_hop, total_bytes, start_t) -> OnionState:
    h = len(role)
    return OnionState(
        role=jnp.asarray(role, I32),
        next_hop=jnp.asarray(next_hop, I32),
        total=jnp.asarray(total_bytes, I64),
        start_t=jnp.asarray(start_t, I64),
        started=jnp.zeros((h,), bool),
        done_t=jnp.full((h,), INV, I64),
        forwarded=jnp.zeros((h,), I64),
    )
