"""App composition: run several on-device application models in one world.

The engine takes ONE app object; `Stacked` lets a world carry several
(e.g. the substrate's outbound-datagram ring next to a modeled echo
server).  App state becomes a tuple, one element per sub-app, and each
sub-app sees the SimState with `app` rebound to its own element.

Constraint: at most one stacked app may emit on a given emission lane
per tick (emit.SLOT_APP in particular) -- lanes are fixed columns, and a
second writer would overwrite the first.  The compositions used here
(SubstrateTx + a modeled TCP server) satisfy this by construction: TCP
apps emit through the transmitter, not SLOT_APP.

Reference analog: a reference host runs multiple processes
(slave_addNewVirtualProcess); here multiple vectorized models advance in
one compiled step.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp


class Stacked:
    def __init__(self, *apps):
        self.apps = tuple(apps)

    # Static capability flags: the union of the sub-apps'.
    @property
    def uses_tcp(self):
        return any(getattr(a, "uses_tcp", True) for a in self.apps)

    @property
    def may_loopback(self):
        return any(getattr(a, "may_loopback", True) for a in self.apps)

    @property
    def rx_batch(self):
        return max(int(getattr(a, "rx_batch", 1)) for a in self.apps)

    def __hash__(self):
        return hash(("stacked",) + self.apps)

    def __eq__(self, other):
        return isinstance(other, Stacked) and other.apps == self.apps

    def next_time(self, state):
        times = [a.next_time(state.replace(app=state.app[i]))
                 for i, a in enumerate(self.apps)]
        return functools.reduce(jnp.minimum, times)

    def on_tick(self, state, params, em, tick_t, active):
        subs = list(state.app)
        for i, a in enumerate(self.apps):
            sub_state = state.replace(app=subs[i])
            sub_state, em = a.on_tick(sub_state, params, em, tick_t, active)
            subs[i] = sub_state.app
            state = sub_state
        return state.replace(app=tuple(subs)), em
