"""Bitcoin-style P2P gossip: inv/getdata flood over a many-peer overlay.

The missing traffic shape from the measured ladder (BASELINE config 4, a
~500-node Bitcoin network): every node keeps 8-16 peers; a node that
originates or learns of an item announces it (`inv`, small message) to its
peers; a peer that hasn't seen the item requests it (`getdata`) from the
announcer, which replies with the item body; receipt triggers the
receiver's own announcement round.  Fan-out floods of small messages --
nothing like tgen streams (few long TCP flows) or onion chains (relay
pipelines).

TPU-first shape: the whole protocol is a per-(host, item) state machine in
dense [H, ITEMS] arrays advanced by masked vector ops inside the engine
micro-step.  Each host emits ONE datagram per paced tick (the engine's
deterministic SLOT_APP lane); an announcement round to D peers therefore
spreads over D ticks, which is also how a real node serializes onto its
uplink.  Message identity rides the UDP source port (type + item id), so
no payload bytes are needed on device.

Reference analog: the workload class of BASELINE.json configs[3]; the
per-connection version-handshake/inv/getdata exchange a Bitcoin plugin
performs over the reference's TCP stack is modeled at the gossip layer
(message counts, sizes, and fan-out degree), not the wire layer.
"""

from __future__ import annotations

import numpy as np
from flax import struct
import jax.numpy as jnp

from ..core import emit, simtime
from ..core.state import I32, I64, U32, host_ids
from ..transport import udp

GOSSIP_PORT = 8333          # where every node's wildcard socket binds
SPORT_BASE = 30000          # sport = SPORT_BASE + item * 3 + msg type

# Message types (encoded in sport).
MSG_INV, MSG_GETDATA, MSG_ITEM = 0, 1, 2
INV_BYTES = 61              # inv/getdata wire sizes (24B header + payload)
GETDATA_BYTES = 61
ITEM_BYTES = 512            # a transaction-sized item body

# Per-(host, item) phases.
PH_UNKNOWN, PH_WANT, PH_REQUESTED, PH_HAVE = 0, 1, 2, 3


@struct.dataclass
class GossipState:
    # -- static overlay + schedule (constant for the run) --
    peers: jnp.ndarray      # [H, D] i32 peer host ids, valid entries packed
                            # left, -1 padding
    deg: jnp.ndarray        # [H] i32 number of valid peers
    origin: jnp.ndarray     # [ITEMS] i32 originating host per item
    birth: jnp.ndarray      # [ITEMS] i64 origination time per item
    # -- protocol state --
    phase: jnp.ndarray      # [H, ITEMS] i32 PH_*
    src: jnp.ndarray        # [H, ITEMS] i32 who announced it to us / -1
    inv_ptr: jnp.ndarray    # [H, ITEMS] i32 next peer index to announce to
    req_mask: jnp.ndarray   # [H, ITEMS] u32 bitmask of peer indices whose
                            # getdata we still owe an item body
    next_t: jnp.ndarray     # [H] i64 next paced send slot
    # -- counters --
    msgs_sent: jnp.ndarray  # [H] i64
    msgs_recv: jnp.ndarray  # [H] i64


class Gossip:
    """Static app config; hashable so jitted engine calls cache per config."""

    uses_tcp = False
    may_loopback = False
    rx_batch = 4

    def __init__(self, pace_ns: int = 50 * simtime.SIMTIME_ONE_MICROSECOND):
        self.pace_ns = int(pace_ns)

    def __hash__(self):
        return hash(("gossip", self.pace_ns))

    def __eq__(self, other):
        return isinstance(other, Gossip) and other.pace_ns == self.pace_ns

    # -- engine hooks -------------------------------------------------------

    def _pending(self, a):
        """[H, ITEMS] per-type pending-work masks."""
        owe_item = a.req_mask != 0
        want = a.phase == PH_WANT
        announce = (a.phase == PH_HAVE) & (a.inv_ptr < a.deg[:, None])
        return owe_item, want, announce

    def next_time(self, state):
        a = state.app
        owe_item, want, announce = self._pending(a)
        has_work = (owe_item | want | announce).any(axis=1)
        t = jnp.where(has_work, a.next_t,
                      jnp.asarray(simtime.SIMTIME_INVALID, I64))
        # Unborn items wake their origin at birth.  Origins are GLOBAL
        # host ids, so the row comparison uses global ids too (identity
        # arange off-mesh).
        mine = (a.origin[None, :] == host_ids(state, I32)[:, None]) & \
            (a.phase == PH_UNKNOWN)
        birth_t = jnp.min(jnp.where(mine, a.birth[None, :],
                                    jnp.asarray(simtime.SIMTIME_INVALID, I64)),
                          axis=1)
        return jnp.minimum(t, birth_t)

    def on_tick(self, state, params, em, tick_t, active):
        a = state.app
        socks = state.socks
        h, items = a.phase.shape
        rows = host_ids(state, I32)   # GLOBAL ids (origin/peer compares)
        slot = jnp.zeros((h,), I32)

        # ---- birth: originate due items (content appears from thin air) --
        mine = (a.origin[None, :] == rows[:, None]) & \
            (a.phase == PH_UNKNOWN) & (a.birth[None, :] <= tick_t[:, None]) & \
            active[:, None]
        a = a.replace(
            phase=jnp.where(mine, PH_HAVE, a.phase),
            inv_ptr=jnp.where(mine, 0, a.inv_ptr),
            src=jnp.where(mine, -1, a.src))

        # ---- receive: drain up to rx_batch datagrams ----------------------
        for _ in range(self.rx_batch):
            socks, got, src, sport, _len, _pid = udp.pop_ring(
                socks, active, slot)
            code = sport - SPORT_BASE
            item = jnp.clip(code // 3, 0, items - 1)
            mtype = code % 3
            onehot = (jnp.arange(items, dtype=I32)[None, :] == item[:, None])

            ph_i = jnp.take_along_axis(a.phase, item[:, None], 1)[:, 0]

            # inv: unknown -> want(src)
            inv_new = got & (mtype == MSG_INV) & (ph_i == PH_UNKNOWN)
            a = a.replace(
                phase=jnp.where(inv_new[:, None] & onehot, PH_WANT, a.phase),
                src=jnp.where(inv_new[:, None] & onehot, src[:, None], a.src))

            # getdata: mark the requesting peer's bit (requester must be a
            # peer -- it got our inv); unknown requesters are dropped.
            k = jnp.argmax(a.peers == src[:, None], axis=1).astype(I32)
            k_ok = jnp.take_along_axis(a.peers, k[:, None], 1)[:, 0] == src
            gd = got & (mtype == MSG_GETDATA) & k_ok & (ph_i == PH_HAVE)
            bit = (jnp.uint32(1) << k.astype(U32))
            a = a.replace(req_mask=jnp.where(
                gd[:, None] & onehot, a.req_mask | bit[:, None], a.req_mask))

            # item body: want/requested -> have, start announcing.
            it = got & (mtype == MSG_ITEM) & \
                ((ph_i == PH_WANT) | (ph_i == PH_REQUESTED))
            a = a.replace(
                phase=jnp.where(it[:, None] & onehot, PH_HAVE, a.phase),
                inv_ptr=jnp.where(it[:, None] & onehot, 0, a.inv_ptr))

            a = a.replace(msgs_recv=a.msgs_recv + got.astype(I64))

        # ---- send: one paced message per host, deterministic priority ----
        # item replies first (latency of the flood), then getdata, then inv;
        # within a type, lowest item id.
        owe_item, want, announce = self._pending(a)
        due = active & (a.next_t <= tick_t)

        def first_item(mask):
            idx = jnp.argmax(mask, axis=1).astype(I32)
            return idx, jnp.take_along_axis(mask, idx[:, None], 1)[:, 0]

        it_i, it_ok = first_item(owe_item)
        gd_i, gd_ok = first_item(want)
        inv_i, inv_ok = first_item(announce)

        choice = jnp.where(it_ok, 0, jnp.where(gd_ok, 1,
                                               jnp.where(inv_ok, 2, 3)))
        sel_item = jnp.where(choice == 0, it_i,
                             jnp.where(choice == 1, gd_i, inv_i))
        sel_oh = (jnp.arange(items, dtype=I32)[None, :] == sel_item[:, None])

        # item reply: lowest requester bit.
        rm = jnp.take_along_axis(a.req_mask, sel_item[:, None], 1)[:, 0]
        low_k = _ctz32(rm)
        dst_item = _peer_at(a.peers, low_k)
        # getdata: to the announcer.
        dst_gd = jnp.take_along_axis(a.src, sel_item[:, None], 1)[:, 0]
        # inv: to peer[inv_ptr], skipping whoever gave us the item.
        ptr = jnp.take_along_axis(a.inv_ptr, sel_item[:, None], 1)[:, 0]
        dst_inv = _peer_at(a.peers, ptr)
        skip_inv = dst_inv == jnp.take_along_axis(
            a.src, sel_item[:, None], 1)[:, 0]

        send = due & (choice < 3)
        dst = jnp.where(choice == 0, dst_item,
                        jnp.where(choice == 1, dst_gd, dst_inv))
        mtype_out = jnp.where(choice == 0, MSG_ITEM,
                              jnp.where(choice == 1, MSG_GETDATA, MSG_INV))
        length = jnp.where(choice == 0, ITEM_BYTES,
                           jnp.where(choice == 1, GETDATA_BYTES, INV_BYTES))
        emit_ok = send & (dst >= 0) & ~((choice == 2) & skip_inv)

        em = emit.put(
            em, emit_ok, emit.SLOT_APP,
            dst=dst, sport=SPORT_BASE + sel_item * 3 + mtype_out,
            dport=GOSSIP_PORT, proto=17, length=length)

        # consume the action
        sent1 = send[:, None] & sel_oh
        a = a.replace(
            req_mask=jnp.where(sent1 & (choice == 0)[:, None],
                               a.req_mask & ~(jnp.uint32(1) <<
                                              low_k.astype(U32))[:, None],
                               a.req_mask),
            phase=jnp.where(sent1 & (choice == 1)[:, None], PH_REQUESTED,
                            a.phase),
            inv_ptr=jnp.where(sent1 & (choice == 2)[:, None],
                              a.inv_ptr + 1, a.inv_ptr),
            next_t=jnp.where(send, tick_t + self.pace_ns, a.next_t),
            msgs_sent=a.msgs_sent + emit_ok.astype(I64))

        return state.replace(app=a, socks=socks), em


def _ctz32(x):
    """Count trailing zeros of a u32 (index of lowest set bit; 32 if 0).
    A 5-step shift ladder over the isolated lowest bit -- exact for u32."""
    low = x & (~x + jnp.uint32(1))
    n = jnp.zeros_like(x, I32)
    for shift in (16, 8, 4, 2, 1):
        big = (low >> shift) != 0
        n = n + jnp.where(big, shift, 0)
        low = jnp.where(big, low >> shift, low)
    return jnp.where(x == 0, 32, n)


def _peer_at(peers, k):
    kk = jnp.clip(k, 0, peers.shape[1] - 1)
    return jnp.take_along_axis(peers, kk[:, None], 1)[:, 0]


def build_overlay(num_hosts: int, degree: int, seed: int):
    """Symmetric overlay: ring (connectivity) + random chords to ~degree.
    Returns (peers [H,D] i32 packed-left -1-padded, deg [H] i32)."""
    if degree + 2 > 32:
        # req_mask is a u32 bitmask over peer indices; build_overlay can
        # exceed `degree` by up to 2 while symmetrizing.
        raise ValueError(f"gossip degree {degree} too large: peer count "
                         f"must stay <= 32 (u32 request bitmask)")
    rng = np.random.default_rng((seed, 0xB17C0))
    adj = [set() for _ in range(num_hosts)]
    for i in range(num_hosts):
        adj[i].add((i + 1) % num_hosts)
        adj[(i + 1) % num_hosts].add(i)
    for i in range(num_hosts):
        tries = 0
        while len(adj[i]) < degree and tries < 64:
            j = int(rng.integers(0, num_hosts))
            tries += 1
            if j == i or j in adj[i] or len(adj[j]) >= degree + 2:
                continue
            adj[i].add(j)
            adj[j].add(i)
    d = max(len(s) for s in adj)
    peers = np.full((num_hosts, d), -1, np.int32)
    deg = np.zeros(num_hosts, np.int32)
    for i, s in enumerate(adj):
        lst = sorted(s)
        peers[i, :len(lst)] = lst
        deg[i] = len(lst)
    return peers, deg


def init_state(num_hosts: int, degree: int, num_items: int,
               item_interval_ns: int, seed: int,
               first_birth_ns: int = simtime.SIMTIME_ONE_MILLISECOND):
    peers, deg = build_overlay(num_hosts, degree, seed)
    rng = np.random.default_rng((seed, 0xB17C1))
    origin = rng.integers(0, num_hosts, num_items).astype(np.int32)
    birth = (first_birth_ns +
             np.arange(num_items, dtype=np.int64) * item_interval_ns)
    h, items = num_hosts, num_items
    return GossipState(
        peers=jnp.asarray(peers), deg=jnp.asarray(deg),
        origin=jnp.asarray(origin), birth=jnp.asarray(birth),
        phase=jnp.zeros((h, items), I32),
        src=jnp.full((h, items), -1, I32),
        inv_ptr=jnp.zeros((h, items), I32),
        req_mask=jnp.zeros((h, items), U32),
        next_t=jnp.zeros((h,), I64),
        msgs_sent=jnp.zeros((h,), I64),
        msgs_recv=jnp.zeros((h,), I64),
    )
