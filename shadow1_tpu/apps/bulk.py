"""Bulk transfer: a modeled tgen-style file-transfer application.

The reference's bring-up workload is a 2-host tgen file transfer
(/root/reference/resource/examples/shadow.config.xml with
tgen.client.graphml.xml / tgen.server.graphml.xml): clients open TCP
connections to a server and move a configured number of bytes, and the
transfer completion time is the headline observable.  Here the application
is an on-device model: per-host role/size/start arrays, with connect /
write / close driven through the vectorized TCP API each engine tick.

Per-host config lives in `BulkState` (a pytree, so it shards with the
hosts axis):

* `is_client` [H] bool  -- this host actively transfers
* `dst`       [H] i32   -- server host index
* `total`     [H] i64   -- bytes to send
* `start_t`   [H] i64   -- connection start time

Observables: `finish_t` (time the client's FIN was acknowledged, i.e. all
bytes delivered and the close handshake completed through FIN-ACK) and the
socket byte counters.
"""

from __future__ import annotations

from flax import struct
import jax.numpy as jnp

from ..core import simtime
from ..core.state import (I32, I64, U32, SOCK_TCP, TCPS_CLOSED,
                          TCPS_CLOSEWAIT, TCPS_FINWAIT2, TCPS_TIMEWAIT)
from ..transport import tcp

SERVER_PORT = 80
CLIENT_PORT = 40000
LISTEN_SLOT = 0
CLIENT_SLOT = 1

INV = simtime.SIMTIME_INVALID


@struct.dataclass
class BulkState:
    is_client: jnp.ndarray   # [H] bool
    dst: jnp.ndarray         # [H] i32
    total: jnp.ndarray       # [H] i64
    start_t: jnp.ndarray     # [H] i64
    phase: jnp.ndarray       # [H] i32 0=idle 1=running 2=done
    finish_t: jnp.ndarray    # [H] i64 completion time, INV until done


class Bulk:
    """Static app config (hashable: jitted engine calls cache per config)."""

    # Bursty TCP fan-in: deliver up to 4 queued arrivals per host per
    # micro-step (engine rx_batch rounds).
    rx_batch = 4

    def __init__(self, server_port: int = SERVER_PORT,
                 client_slot: int = CLIENT_SLOT):
        self.server_port = int(server_port)
        self.client_slot = int(client_slot)

    def __hash__(self):
        return hash(("bulk", self.server_port, self.client_slot))

    def __eq__(self, other):
        return (isinstance(other, Bulk)
                and other.server_port == self.server_port
                and other.client_slot == self.client_slot)

    # -- engine hooks -------------------------------------------------------

    def next_time(self, state):
        a = state.app
        return jnp.where(a.is_client & (a.phase == 0), a.start_t,
                         jnp.asarray(INV, I64))

    def on_tick(self, state, params, em, tick_t, active):
        a = state.app
        socks = state.socks
        h = a.phase.shape[0]
        slot = jnp.full((h,), self.client_slot, I32)

        # 1. Start due clients: active open to (dst, server_port).
        starting = active & a.is_client & (a.phase == 0) & \
            (a.start_t <= tick_t)
        socks = tcp.connect_v(socks, starting, slot, a.dst,
                              self.server_port, CLIENT_PORT, tick_t)
        a = a.replace(phase=jnp.where(starting, 1, a.phase))

        # 2. Running clients: stream bytes into the send buffer, then close.
        running = active & a.is_client & (a.phase == 1)
        target_end = (jnp.uint32(1) + a.total.astype(U32))
        socks = tcp.write_v(socks, running, slot, target_end, now=tick_t)
        cs = self.client_slot  # static -> column slices, not gathers
        all_written = socks.snd_end[:, cs] == target_end
        socks = tcp.close_v(socks, running & all_written, slot)

        # 3. Completion: the client's FIN has been ACKed, which requires
        # every byte to be delivered first (snd_una == stream end + FIN).
        # A socket torn down by RST/timeout has error != 0 and moves to
        # phase 3 (failed) instead -- never counted as success.
        cstate = socks.tcp_state[:, cs]
        closed = (cstate == TCPS_FINWAIT2) | (cstate == TCPS_TIMEWAIT) | \
            (cstate == TCPS_CLOSED)
        all_acked = socks.snd_una[:, cs] == \
            (target_end + jnp.uint32(1))
        failed = running & (socks.error[:, cs] != 0)
        done = running & closed & all_acked & ~failed
        a = a.replace(
            phase=jnp.where(done, 2, jnp.where(failed, 3, a.phase)),
            finish_t=jnp.where(done, tick_t, a.finish_t),
        )

        # 4. Sink policy on every host: consume all received bytes (keeps
        # the advertised window open) and close-when-peer-closed.
        socks = tcp.consume_all(socks)
        socks = socks.replace(app_closed=jnp.where(
            (socks.stype == SOCK_TCP) & (socks.tcp_state == TCPS_CLOSEWAIT),
            True, socks.app_closed))

        return state.replace(app=a, socks=socks), em


def init_state(num_hosts: int, is_client, dst, total_bytes, start_t):
    return BulkState(
        is_client=jnp.asarray(is_client, bool),
        dst=jnp.asarray(dst, I32),
        total=jnp.asarray(total_bytes, I64),
        start_t=jnp.asarray(start_t, I64),
        phase=jnp.zeros((num_hosts,), I32),
        finish_t=jnp.full((num_hosts,), INV, I64),
    )


def setup_servers(socks, is_server, port: int = SERVER_PORT,
                  slot: int = LISTEN_SLOT):
    """Install TCP listeners on server hosts (setup time)."""
    return tcp.listen_v(socks, jnp.asarray(is_server, bool),
                        jnp.full((socks.num_hosts,), slot, I32), port)
