"""PHOLD: the classic parallel-discrete-event synthetic workload, on-device.

The reference ships phold as a C plugin (/root/reference/src/test/phold/
test_phold.c): N hosts hold messages; each received UDP message triggers
sending a new message to a random host after a random exponential delay.
It doubles as the scheduler stress test and the event-rate performance
probe.

Here phold is an on-device application model: its per-host state is a
pytree, its "receive a message / send a message" logic runs inside the
engine micro-step as masked vector ops, and its randomness is keyed by
(host, per-host draw counter) so the trajectory is bitwise reproducible on
any mesh.
"""

from __future__ import annotations

from flax import struct
import jax.numpy as jnp

from ..core import emit, rng, simtime
from ..core.state import I32, I64, U32
from ..transport import udp

PHOLD_PORT = 9000
MSG_BYTES = 64


@struct.dataclass
class PholdState:
    next_send: jnp.ndarray  # [H] i64 time of next send, SIMTIME_INVALID if none
    pending: jnp.ndarray    # [H] i32 messages held, waiting to be forwarded
    sent: jnp.ndarray       # [H] i64 total messages sent
    recv: jnp.ndarray       # [H] i64 total messages received


class Phold:
    """Static app config; hashable so jitted engine calls cache per config."""

    # Pure-UDP workload: the engine traces the TCP machine out of the
    # compiled step entirely (engine._uses_tcp).  _pick_dst never picks
    # self, so the loopback insert path traces away too.
    uses_tcp = False
    may_loopback = False

    def __init__(self, mean_delay_ns: int, sock_slot: int = 0):
        self.mean_delay_ns = int(mean_delay_ns)
        self.sock_slot = int(sock_slot)

    def __hash__(self):
        return hash(("phold", self.mean_delay_ns, self.sock_slot))

    def __eq__(self, other):
        return (isinstance(other, Phold)
                and other.mean_delay_ns == self.mean_delay_ns
                and other.sock_slot == self.sock_slot)

    # -- engine hooks -------------------------------------------------------

    def next_time(self, state):
        a = state.app
        return jnp.where(a.pending > 0, a.next_send,
                         jnp.asarray(simtime.SIMTIME_INVALID, I64))

    def _delay(self, params, host_ids, ctr):
        """Exponential delay, keyed by (host, draw counter)."""
        key = rng.purpose_key(params.seed_key, rng.PURPOSE_HOST_APP)
        u = rng.keyed_uniform(key, host_ids, ctr, jnp.uint32(1))
        d = -jnp.log1p(-u) * self.mean_delay_ns
        return jnp.maximum(d.astype(I64), 1)

    def _pick_dst(self, params, host_ids, ctr, num_hosts):
        key = rng.purpose_key(params.seed_key, rng.PURPOSE_HOST_APP)
        u = rng.keyed_uniform(key, host_ids, ctr, jnp.uint32(2))
        # Uniform over the other hosts (never self).
        off = 1 + jnp.minimum((u * (num_hosts - 1)).astype(I32), num_hosts - 2)
        return (host_ids.astype(I32) + off) % num_hosts

    def on_tick(self, state, params, em, tick_t, active):
        a = state.app
        socks = state.socks
        h = a.pending.shape[0]
        rows = jnp.arange(h, dtype=U32)
        slot = jnp.full((h,), self.sock_slot, I32)

        # Consume delivered messages from the socket ring: each one becomes
        # a pending message with a fresh send time.  The engine delivers at
        # most one datagram per host per tick and this app always drains on
        # the same tick, so ring depth never exceeds 1; two iterations only
        # bound the unrolled graph, not the throughput.
        for _ in range(2):
            socks, got, _src, _sport, _len, _pid = udp.pop_ring(
                socks, active, slot)
            ctr = state.hosts.rng_ctr
            delay = self._delay(params, rows, ctr)
            cand = tick_t + delay
            a = a.replace(
                pending=a.pending + jnp.where(got, 1, 0),
                next_send=jnp.where(
                    got, jnp.minimum(a.next_send, cand), a.next_send),
                recv=a.recv + jnp.where(got, 1, 0),
            )
            state = state.replace(hosts=state.hosts.replace(
                rng_ctr=state.hosts.rng_ctr + jnp.where(got, 1, 0).astype(U32)))

        # Send one message where due.
        due = active & (a.pending > 0) & (a.next_send <= tick_t)
        ctr = state.hosts.rng_ctr
        dst = self._pick_dst(params, rows, ctr, h)
        em = emit.put(
            em, due, emit.SLOT_APP,
            dst=dst, sport=PHOLD_PORT, dport=PHOLD_PORT,
            proto=17, length=MSG_BYTES,
        )
        # Re-arm: more pending messages draw a new delay (counter +2: one for
        # dst draw, one for the delay draw).
        delay2 = self._delay(params, rows, ctr + 1)
        pending2 = a.pending - jnp.where(due, 1, 0)
        a = a.replace(
            pending=pending2,
            sent=a.sent + jnp.where(due, 1, 0),
            next_send=jnp.where(
                due,
                jnp.where(pending2 > 0, tick_t + delay2,
                          jnp.asarray(simtime.SIMTIME_INVALID, I64)),
                a.next_send),
        )
        state = state.replace(
            app=a,
            socks=socks,
            hosts=state.hosts.replace(
                rng_ctr=state.hosts.rng_ctr + jnp.where(due, 2, 0).astype(U32)),
        )
        return state, em


def init_state(num_hosts: int, params, msgs_per_host: int = 1,
               mean_delay_ns: int = 10 * simtime.SIMTIME_ONE_MILLISECOND):
    """Initial phold population: every host holds `msgs_per_host` messages
    with exponentially distributed first send times."""
    key = rng.purpose_key(params.seed_key, rng.PURPOSE_HOST_APP)
    rows = jnp.arange(num_hosts, dtype=U32)
    u = rng.keyed_uniform(key, rows, jnp.uint32(0), jnp.uint32(1))
    first = jnp.maximum(
        (-jnp.log1p(-u) * mean_delay_ns).astype(I64), 1)
    return PholdState(
        next_send=first,
        pending=jnp.full((num_hosts,), msgs_per_host, I32),
        sent=jnp.zeros((num_hosts,), I64),
        recv=jnp.zeros((num_hosts,), I64),
    )
