"""PHOLD: the classic parallel-discrete-event synthetic workload, on-device.

The reference ships phold as a C plugin (/root/reference/src/test/phold/
test_phold.c): N hosts hold messages; each received UDP message triggers
sending a new message to a random host after a random exponential delay.
It doubles as the scheduler stress test and the event-rate performance
probe.

Here phold is an on-device application model: its per-host state is a
pytree, its "receive a message / send a message" logic runs inside the
engine micro-step as masked vector ops, and its randomness is keyed by
(host, per-host draw counter) so the trajectory is bitwise reproducible on
any mesh.
"""

from __future__ import annotations

from flax import struct
import jax.numpy as jnp

from ..core import emit, rng, simtime
from ..core.state import I32, I64, U32, host_ids
from ..transport import udp

PHOLD_PORT = 9000
MSG_BYTES = 64


@struct.dataclass
class PholdState:
    next_send: jnp.ndarray  # [H] i64 time of next send, SIMTIME_INVALID if none
    pending: jnp.ndarray    # [H] i32 messages held, waiting to be forwarded
    sent: jnp.ndarray       # [H] i64 total messages sent
    recv: jnp.ndarray       # [H] i64 total messages received


class Phold:
    """Static app config; hashable so jitted engine calls cache per config."""

    # Pure-UDP workload: the engine traces the TCP machine out of the
    # compiled step entirely (engine._uses_tcp).  _pick_dst never picks
    # self, so the loopback insert path traces away too.
    uses_tcp = False
    may_loopback = False
    # Arrival batching (rx_batch, an __init__ arg) defaults to 1: serial
    # per-arrival stepping, bitwise-equal across configs, so event counts
    # are apples-to-apples between runs.  Benchmarks opt into rx_batch=2
    # explicitly: with send batching absorbing the send chains, the
    # per-window long pole is the arrival tail (Poisson max ~10
    # arrivals/host/window at 16k hosts).  rx_batch=4 alone measured as a
    # net loss (+30% step cost for -12% steps), but 2 rounds paired
    # with tx lanes is the measured sweet spot.  SEMANTICS NOTE: batched
    # arrivals re-arm their forwards from the batch instant t_post (>=
    # each arrival's own time, so causality holds) and their rng draws
    # sequence before same-tick send draws -- the trajectory is
    # deterministic for a fixed config but NOT bitwise-equal to
    # rx_batch=1 stepping (measured: ~1% send-count shift).  Send-lane
    # batching alone IS bitwise-equal to serial stepping.
    #
    # SEND batching is where phold's steps go: within a window every
    # arrival for a host is already in its inbox (conservative
    # invariant), so pending sends due strictly before min(next own
    # arrival, window_end) can be pre-emitted in ONE step, each lane
    # stamped with its exact send time.  The dst/delay draw sequence is
    # the serial one (two draws per send, in send order), so send-lane
    # batching alone is BITWISE identical to unbatched stepping -- the
    # steps just collapse.  (rx_batch above trades that equivalence away
    # separately; see its note.)  Strict '<' on the bound keeps
    # arrival-tie draw order serial (the arrival's draw precedes the
    # send's).
    app_tx_lanes = 4
    wants_window_end = True
    # NOTE: on_tick is row-local over hosts (every read/write is row-
    # wise, global identity only through host_ids(state)), but f32
    # transcendentals inside it would be fusion-context-sensitive: XLA
    # CPU compiles them to ulp-DIFFERENT results depending on the
    # surrounding fusion context (measured: jit vs eager of the
    # identical reference window loop disagree by 1-2ns per draw with
    # an f32 log1p).  The exponential-delay draw therefore promotes to
    # f64 before the log1p -- f64 transcendentals lower to a libm call
    # whose value is independent of fusion context -- which is what
    # keeps a vmapped ensemble world bitwise equal to the same world
    # run solo (vmap restructures the engine graph and with it every
    # f32 fusion neighborhood; see docs/ensemble.md), and what lets
    # the tick run BETWEEN the per-phase megakernels ("f32 stability")
    # and INSIDE the persistent window kernel ("Persistent window
    # kernel", in-kernel contract) without the trajectory moving --
    # both pinned bitwise in tests/test_megakernel.py.

    def __init__(self, mean_delay_ns: int, sock_slot: int = 0,
                 rx_batch: int = 1):
        self.mean_delay_ns = int(mean_delay_ns)
        self.sock_slot = int(sock_slot)
        self.rx_batch = int(rx_batch)

    def __hash__(self):
        return hash(("phold", self.mean_delay_ns, self.sock_slot,
                     self.rx_batch))

    def __eq__(self, other):
        return (isinstance(other, Phold)
                and other.mean_delay_ns == self.mean_delay_ns
                and other.sock_slot == self.sock_slot
                and other.rx_batch == self.rx_batch)

    # -- engine hooks -------------------------------------------------------

    def next_time(self, state):
        a = state.app
        return jnp.where(a.pending > 0, a.next_send,
                         jnp.asarray(simtime.SIMTIME_INVALID, I64))

    def _delay(self, params, host_ids, ctr):
        """Exponential delay, keyed by (host, draw counter).

        The log1p runs in f64: f32 transcendentals are fusion-context-
        sensitive on XLA CPU (ulp flips when the surrounding graph
        changes, e.g. under vmap), while the f64 path is a stable libm
        call.  The ns result is exact far beyond any plausible mean.
        """
        key = rng.purpose_key(params.seed_key, rng.PURPOSE_HOST_APP)
        u = rng.keyed_uniform(key, host_ids, ctr, jnp.uint32(1))
        d = -jnp.log1p(-u.astype(jnp.float64)) * self.mean_delay_ns
        return jnp.maximum(d.astype(I64), 1)

    def _pick_dst(self, params, host_ids, ctr, num_hosts):
        key = rng.purpose_key(params.seed_key, rng.PURPOSE_HOST_APP)
        u = rng.keyed_uniform(key, host_ids, ctr, jnp.uint32(2))
        # Uniform over the other hosts (never self).
        off = 1 + jnp.minimum((u * (num_hosts - 1)).astype(I32), num_hosts - 2)
        return (host_ids.astype(I32) + off) % num_hosts

    def on_tick(self, state, params, em, tick_t, active, window_end=None):
        a = state.app
        socks = state.socks
        h = a.pending.shape[0]
        # GLOBAL host ids (identity off-mesh): they key every RNG draw and
        # the dst pick, so draws are mesh-invariant.  The world's global
        # host count comes from params.global_hosts() -- the REAL count
        # even when the arrays carry bucket-padding rows -- never the
        # (possibly shard-local, possibly padded) state row count.
        rows = host_ids(state, U32)
        hg = params.global_hosts()
        slot = jnp.full((h,), self.sock_slot, I32)

        # Consume delivered messages from the socket ring: each one becomes
        # a pending message with a fresh send time.  The engine delivers up
        # to rx_batch datagrams per host per tick and this app always
        # drains on the same tick, so the pop unroll covers the batch.
        for _ in range(max(2, self.rx_batch)):
            socks, got, _src, _sport, _len, _pid = udp.pop_ring(
                socks, active, slot)
            ctr = state.hosts.rng_ctr
            delay = self._delay(params, rows, ctr)
            cand = tick_t + delay
            a = a.replace(
                pending=a.pending + jnp.where(got, 1, 0),
                next_send=jnp.where(
                    got, jnp.minimum(a.next_send, cand), a.next_send),
                recv=a.recv + jnp.where(got, 1, 0),
            )
            state = state.replace(hosts=state.hosts.replace(
                rng_ctr=state.hosts.rng_ctr + jnp.where(got, 1, 0).astype(U32)))

        # Send-batch bound: the earliest event that could alter the send
        # chain is this host's next undelivered arrival (cumulative-only
        # effect: arrivals can pull next_send earlier); everything in the
        # current window is already in the inbox, and future windows start
        # at window_end.  Strict '<' keeps arrival-tie order serial.
        if window_end is not None:
            ib = state.inbox
            ki = ib.capacity // h
            t2 = ib.times().reshape(h, ki)
            live = (ib.stage != 0).reshape(h, ki)   # any undelivered entry
            arr_next = jnp.min(
                jnp.where(live, jnp.maximum(t2, tick_t[:, None]),
                          jnp.asarray(simtime.SIMTIME_INVALID, I64)),
                axis=1)
            bound = jnp.minimum(arr_next, window_end)
            lanes = max(1, self.app_tx_lanes)
        else:
            bound = None
            lanes = 1

        for k in range(lanes):
            ctr = state.hosts.rng_ctr
            if k == 0:
                # The tick's own due send.
                due = active & (a.pending > 0) & (a.next_send <= tick_t)
                t_send = 0
            else:
                # Pre-emit the next chained send while it provably
                # precedes any event that could reschedule it.
                due = active & (a.pending > 0) & (a.next_send < bound)
                t_send = a.next_send
            dst = self._pick_dst(params, rows, ctr, hg)
            em = emit.put(
                em, due, emit.SLOT_APP + k,
                dst=dst, sport=PHOLD_PORT, dport=PHOLD_PORT,
                proto=17, length=MSG_BYTES, t_send=t_send,
            )
            # Re-arm: more pending messages draw a new delay (counter +2:
            # one for the dst draw, one for the delay draw).
            delay2 = self._delay(params, rows, ctr + 1)
            base_t = tick_t if k == 0 else a.next_send
            pending2 = a.pending - jnp.where(due, 1, 0)
            a = a.replace(
                pending=pending2,
                sent=a.sent + jnp.where(due, 1, 0),
                next_send=jnp.where(
                    due,
                    jnp.where(pending2 > 0, base_t + delay2,
                              jnp.asarray(simtime.SIMTIME_INVALID, I64)),
                    a.next_send),
            )
            state = state.replace(hosts=state.hosts.replace(
                rng_ctr=state.hosts.rng_ctr +
                jnp.where(due, 2, 0).astype(U32)))
        state = state.replace(app=a, socks=socks)
        return state, em


def init_state(num_hosts: int, params, msgs_per_host: int = 1,
               mean_delay_ns: int = 10 * simtime.SIMTIME_ONE_MILLISECOND):
    """Initial phold population: every host holds `msgs_per_host` messages
    with exponentially distributed first send times."""
    key = rng.purpose_key(params.seed_key, rng.PURPOSE_HOST_APP)
    rows = jnp.arange(num_hosts, dtype=U32)
    u = rng.keyed_uniform(key, rows, jnp.uint32(0), jnp.uint32(1))
    # f64 log1p to match _delay (fusion-context-stable; see its note).
    first = jnp.maximum(
        (-jnp.log1p(-u.astype(jnp.float64)) * mean_delay_ns).astype(I64), 1)
    return PholdState(
        next_send=first,
        pending=jnp.full((num_hosts,), msgs_per_host, I32),
        sent=jnp.zeros((num_hosts,), I64),
        recv=jnp.zeros((num_hosts,), I64),
    )
