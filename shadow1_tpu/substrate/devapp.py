"""Device-side outbound-datagram ring for real processes.

A real process's sendto() cannot create a packet directly -- packets are
born in the engine's emission staging, on device.  The bridge instead
appends (dst, ports, length, payload_id) to this per-host ring at sync
time and wakes the host; `SubstrateTx.on_tick` drains one entry per tick
through the normal emission path, so real-process datagrams get the same
routing, token buckets, reliability draws, and deterministic pkt_ids as
modeled traffic (reference: process syscalls land in the same
worker_sendPacket path as everything else, worker.c:243-304).

Payload bytes live host-side in the native arena keyed by payload_id;
the id rides the packet metadata and the receiving bridge resolves it
back to bytes at recvfrom() (reference packet.c:97-100 payload split).
"""

from __future__ import annotations

from flax import struct
import jax.numpy as jnp

from ..core import emit, simtime
from ..core.state import I32, I64

RING = 32  # per-host pending outbound datagrams


@struct.dataclass
class SubTxState:
    dst: jnp.ndarray      # [H, RING] i32 destination host
    dport: jnp.ndarray    # [H, RING] i32
    sport: jnp.ndarray    # [H, RING] i32
    length: jnp.ndarray   # [H, RING] i32
    payload: jnp.ndarray  # [H, RING] i32 arena id (-1 = none)
    head: jnp.ndarray     # [H] i32
    count: jnp.ndarray    # [H] i32


class SubstrateTx:
    """Drain one queued real-process datagram per host per tick."""

    uses_tcp = True       # real processes also run TCP
    may_loopback = True   # a process may sendto its own host
    rx_batch = 4

    def __hash__(self):
        return hash("substrate-tx")

    def __eq__(self, other):
        return isinstance(other, SubstrateTx)

    def next_time(self, state):
        a = state.app
        # Queued datagrams are due immediately (the bridge stamps
        # t_resume at append time; 0 clamps to `now` in the window loop).
        return jnp.where(a.count > 0, jnp.zeros_like(a.head, I64),
                         jnp.asarray(simtime.SIMTIME_INVALID, I64))

    def on_tick(self, state, params, em, tick_t, active):
        a = state.app
        h = a.head.shape[0]
        do = active & (a.count > 0)
        idx = a.head[:, None]
        col = jnp.arange(RING, dtype=I32)[None, :] == idx

        def at_head(tab):
            return jnp.sum(jnp.where(col, tab, 0), axis=1, dtype=tab.dtype)

        em = emit.put(
            em, do, emit.SLOT_APP,
            dst=at_head(a.dst), sport=at_head(a.sport),
            dport=at_head(a.dport), proto=17,
            length=at_head(a.length), payload_id=at_head(a.payload))
        a = a.replace(
            head=jnp.where(do, (a.head + 1) % RING, a.head),
            count=jnp.where(do, a.count - 1, a.count))
        return state.replace(app=a), em


def init_state(num_hosts: int) -> SubTxState:
    hq = (num_hosts, RING)
    return SubTxState(
        dst=jnp.zeros(hq, I32), dport=jnp.zeros(hq, I32),
        sport=jnp.zeros(hq, I32), length=jnp.zeros(hq, I32),
        payload=jnp.full(hq, -1, I32),
        head=jnp.zeros((num_hosts,), I32),
        count=jnp.zeros((num_hosts,), I32))
