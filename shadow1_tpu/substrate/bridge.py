"""The window-protocol bridge: real processes <-> the device engine.

Architecture (docs/design-process-substrate.md): real plugin binaries run
as OS processes under the native sequencer (native/sequencer.cc) with the
syscall shim preloaded (native/shim/shadow1_shim.c).  Between device
windows the bridge

  1. publishes the virtual clock,
  2. fetches each real socket's transport registers from the device state,
  3. runs every runnable process until it blocks (reply -> next request,
     one process at a time, in deterministic (host, process) order),
  4. applies the produced socket operations to the device state through
     the same vectorized API modeled apps use (tcp.connect_v / write_v /
     close_v, rcv_read advances).

This reproduces the reference's contract -- plugins execute serially
between event-loop steps, blocked syscalls resume on readiness
(process.c:1197-1275 run-until-blocked, epoll.c:638-671 tryNotify) --
with the conservative window, not an in-process scheduler, as the
synchronization boundary.

Payload bytes never touch the device: each virtual socket keeps its sent
byte stream host-side.  Inbound bytes resolve in priority order: (1) a
real peer -- when both endpoints are real processes the connection is
paired at accept() time and each side reads the OTHER side's sent
stream at the device-dictated cursor, so bytes written by process A are
the bytes process B reads; (2) a `content_provider` callback (modeled
peer, e.g. the on-device echo server); (3) zeros.  The device controls
*timing only* -- how many bytes are deliverable when -- which is exactly
the reference's split between Payload refcounts and packet events
(src/main/routing/payload.c:16-23, packet.c:97-100).

Real servers: OP_LISTEN/OP_ACCEPT ride the modeled listener/child-socket
machinery (SocketTable.parent/accepted/backlog, engine SYN handling) --
accept() parks until a child slot reaches ESTABLISHED, then binds a new
vfd to it (reference host_acceptNewPeer, tcp.c:91-115).  OP_POLL parks a
process on a readiness SET and wakes it when any member socket's
registers show readable/writable/error (reference epoll.c:638-671).
"""

from __future__ import annotations

import ctypes
import os
from dataclasses import dataclass, field

import jax
import numpy as np

from .. import build_on_host, trace
from ..core import simtime
from ..transport import tcp
from . import buildlib

# Wire protocol (matches native/shim/shadow1_shim.c + sequencer.cc).
(OP_SOCKET, OP_CONNECT, OP_SEND, OP_RECV, OP_CLOSE, OP_SLEEP, OP_GETTIME,
 OP_BIND, OP_LISTEN, OP_ACCEPT, OP_POLL, OP_EXIT, OP_PIPE, OP_SENDTO,
 OP_RECVFROM, OP_RESOLVE) = range(1, 17)

SOCK_DGRAM = 2  # linux asm-generic socket type

VFD_BASE = 1 << 20
MAX_DATA = 65536

# Reference EMULATED_TIME_OFFSET: plugin wall clocks start at Jan 1 2000
# (definitions.h:78).
EMULATED_EPOCH_NS = 946_684_800 * simtime.SIMTIME_ONE_SECOND

_EAGAIN = 11
_ECONNREFUSED = 111
_EINPROGRESS = 115

# poll(2) event bits (linux asm-generic/poll.h).
POLLIN, POLLPRI, POLLOUT, POLLERR, POLLHUP, POLLNVAL = \
    0x001, 0x002, 0x004, 0x008, 0x010, 0x020


class _SeqLib:
    """ctypes binding of native/sequencer.cc."""

    def __init__(self):
        lib = ctypes.CDLL(buildlib.sequencer_path())
        lib.seq_create.argtypes = [ctypes.c_char_p]
        lib.seq_create.restype = ctypes.c_int
        lib.seq_settime.argtypes = [ctypes.c_int, ctypes.c_int64]
        lib.seq_spawn.argtypes = [ctypes.c_int, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_char_p),
                                  ctypes.c_char_p, ctypes.c_char_p]
        lib.seq_spawn.restype = ctypes.c_int
        lib.seq_wait_request.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint32)]
        lib.seq_wait_request.restype = ctypes.c_int
        lib.seq_reply.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int64,
                                  ctypes.c_int32, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_uint8),
                                  ctypes.c_uint32]
        lib.seq_status.argtypes = [ctypes.c_int, ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_int)]
        lib.seq_kill.argtypes = [ctypes.c_int, ctypes.c_int]
        self.lib = lib


@dataclass
class VPipe:
    """Host-side byte queue behind a real process's pipe(2) pair --
    descriptor plumbing with no network presence (reference
    channel.c:22-33: a buffered descriptor pair internal to the host)."""

    buf: bytearray = field(default_factory=bytearray)
    read_open: bool = True
    write_open: bool = True
    CAP = 65536


@dataclass
class VSocket:
    """Host-side view of one simulated socket owned by a real process."""

    slot: int
    vfd: int
    kind: str = "tcp"          # tcp | udp | pipe_r | pipe_w
    pipe: "VPipe | None" = None
    local_port: int = 0
    connecting: bool = False
    connected: bool = False
    closed: bool = False
    listening: bool = False
    sent: bytearray = field(default_factory=bytearray)  # app->net stream
    recv_cursor: int = 0                                # bytes handed to app
    # The opposite endpoint when BOTH ends are real processes (paired at
    # accept time); recv then reads peer.sent at recv_cursor.
    peer: "VSocket | None" = None
    # Connected-UDP default peer (ip, port) set by connect() on a
    # SOCK_DGRAM socket; send()/recv() then behave like sendto/recvfrom.
    udp_peer: "tuple | None" = None
    # Registry key while an active connect awaits real<->real pairing.
    # Popped at accept-pairing ONLY: the entry must survive a client
    # close/half-close, because the server may accept (and pair) after
    # the client already shut down -- its bytes are still in flight.  A
    # never-accepted connect leaves a dict entry behind (the VSocket
    # itself lives in p.vfds either way); a same-4-tuple reconnect
    # overwrites it.
    conn_key: tuple | None = None


@dataclass
class Parked:
    op: int
    fd: int = -1
    a0: int = 0
    a1: int = 0
    wake_ns: int = -1   # for OP_SLEEP


class RealProcess:
    """One supervised plugin process (reference Process analog)."""

    def __init__(self, host: int, proc_id: int):
        self.host = host
        self.proc_id = proc_id
        self.vfds: dict[int, VSocket] = {}
        self.next_vfd = VFD_BASE
        self.parked: Parked | None = None
        self.started = False
        self.exited = False
        self.exit_code: int | None = None
        self.trace: list[tuple] = []   # deterministic syscall transcript
        self.stop_ns: int | None = None  # <process stoptime> kill point


class Substrate:
    """Owns the sequencer, all real processes, and the device bridge."""

    def __init__(self, resolve_ip, workdir: str, sock_slot_base: int = 0,
                 ephemeral_base: int = 40000, resolve_name=None,
                 host_ip=None, wedge_timeout_ms: int = 30000):
        """resolve_ip: callable(int ipv4) -> host index (DNS analog).
        resolve_name: callable(str) -> int ipv4 for getaddrinfo
        (OP_RESOLVE); host_ip: callable(host index) -> int ipv4 used to
        fill recvfrom()'s source address.  wedge_timeout_ms: how long a
        plugin may compute between syscalls before it is declared wedged
        -- raise it for legitimately compute-heavy plugins (the default
        treats >30s of wall-clock between syscalls as a runaway loop)."""
        self._lib = _SeqLib().lib
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.handle = self._lib.seq_create(
            os.path.join(workdir, "vclock").encode())
        assert self.handle >= 0, "sequencer init failed"
        self.shim = buildlib.shim_path()
        self.resolve_ip = resolve_ip
        self.procs: list[RealProcess] = []
        self.sock_slot_base = sock_slot_base
        self._next_port = ephemeral_base
        self.wedge_timeout_ms = int(wedge_timeout_ms)
        self._spawn_queue: list[tuple] = []   # (start_ns, host, argv)
        self.content_provider = None   # (host, slot, vsock, n) -> bytes
        self._pending = []             # queued device ops for this sync
        self.max_slots = 1 << 30       # refined from the state at sync
        # Connection registry for real<->real pairing:
        # (client_host, client_port, server_host, server_port) -> client VSocket.
        self._conns: dict[tuple, VSocket] = {}
        # Slots handed to real processes that the device may not show
        # non-FREE yet (reservation applies at sync end); per host.
        self._reserved: dict[int, set] = {}
        # Child slots already returned by accept() whose `accepted` bit
        # the device may not show yet; per host.
        self._accept_taken: dict[int, set] = {}
        self.resolve_name = resolve_name
        self.host_ip = host_ip
        # Datagram payload bytes (native refcounted arena); ids ride the
        # device packet metadata.
        from ..payload import PayloadArena
        self.arena = PayloadArena()
        # Device payload_id fields are i32; arena handles are u64 with a
        # generation in the high bits.  Small ids index this map.
        self._pid_map: dict[int, int] = {}
        self._next_pid = 1
        # Device TX-ring occupancy the device hasn't caught up on, and
        # per-sync UDP ring pops (host, slot) -> count.
        self._tx_inflight: dict[int, int] = {}
        self._local_pops: dict[tuple, int] = {}

    # -- process management -------------------------------------------------

    def spawn_at(self, host: int, argv: list[str], start_ns: int,
                 stop_ns: int | None = None) -> None:
        """Defer a spawn until virtual time reaches start_ns; optionally
        kill the process at stop_ns (reference <process starttime /
        stoptime>, slave_addNewVirtualProcess scheduling)."""
        self._spawn_queue.append((int(start_ns), host, list(argv),
                                  int(stop_ns) if stop_ns else None))

    def spawn(self, host: int, argv: list[str]) -> RealProcess:
        arr = (ctypes.c_char_p * len(argv))(*[a.encode() for a in argv])
        out = os.path.join(self.workdir,
                           f"proc-{len(self.procs)}.stdout")
        pid = self._lib.seq_spawn(self.handle, len(argv), arr,
                                  self.shim.encode(), out.encode())
        assert pid >= 0, f"spawn failed: {argv}"
        p = RealProcess(host, pid)
        self.procs.append(p)
        return p

    def _pick_slot(self, host: int, regs) -> int | None:
        """Pick the lowest device-FREE slot at or above sock_slot_base that
        this sync hasn't already handed out.  The device allocates child
        sockets min-free-first too, so the slot is also RESERVED on device
        at sync end ('reserve' op) -- otherwise a SYN arriving before the
        process connects could spawn a child into the same slot."""
        from ..core.state import SOCK_FREE

        taken = set(self._reserved.setdefault(host, set()))
        # A device-freed slot (RST / RTO teardown sets stype=SOCK_FREE
        # immediately) may still be referenced by an OPEN vfd whose owner
        # hasn't observed the error yet -- handing it out again would
        # alias two VSockets onto one slot.  Socket tables are per-host
        # ([H, S]), so only vfds on THIS host can alias a slot index --
        # excluding other hosts' vfds would burn one global slot per open
        # socket anywhere in the world and spuriously EMFILE.
        for p in self.procs:
            if p.host != host or p.exited:
                # Exited processes never marked their vfds closed, but
                # the device has (or will) tear their sockets down; their
                # slots must return to the pool or restart churn shrinks
                # it monotonically.
                continue
            for vs in p.vfds.values():
                if not vs.closed:
                    taken.add(vs.slot)
        stype = regs["stype"][host]
        for s in range(self.sock_slot_base, self.max_slots):
            if s not in taken and int(stype[s]) == SOCK_FREE:
                self._reserved[host].add(s)
                return s
        return None

    def _alloc_port(self) -> int:
        self._next_port += 1
        return self._next_port

    # -- the per-window sync -------------------------------------------------

    def sync(self, state, params, now_ns: int):
        """Publish the clock, run every runnable process until it blocks,
        apply the produced socket ops.  Returns the updated state."""
        with trace.current().span("bridge_sync", t_ns=int(now_ns)):
            return self._sync(state, params, now_ns)

    def _sync(self, state, params, now_ns: int):
        self._lib.seq_settime(self.handle, EMULATED_EPOCH_NS + now_ns)
        # Due deferred spawns become real processes this sync (ordered by
        # (start, queue position) for determinism).
        if self._spawn_queue:
            due = [s for s in self._spawn_queue if s[0] <= now_ns]
            self._spawn_queue = [s for s in self._spawn_queue
                                 if s[0] > now_ns]
            for _t, host, argv, stop_ns in due:
                p = self.spawn(host, argv)
                p.stop_ns = stop_ns
        # <process stoptime>: kill overdue processes (reference process
        # teardown at its configured stop).
        for p in self.procs:
            stop_ns = getattr(p, "stop_ns", None)
            if stop_ns is not None and not p.exited and now_ns >= stop_ns:
                self._lib.seq_kill(self.handle, p.proc_id)
                p.exited = True
                p.exit_code = -15  # SIGTERM-style: stopped by schedule
                p.parked = None
        # Idle fast path: when every live process is parked on a pure
        # timer (sleep/poll-timeout with a future wake), no syscall can
        # run and no socket registers matter -- skip the device fetch
        # entirely (it costs a multi-array device_get per sync, the
        # r3-flagged per-window overhead).
        live = [p for p in self.procs if not p.exited]
        if live and all(
                p.parked is not None and p.parked.op == OP_SLEEP
                and p.parked.wake_ns > now_ns for p in live):
            return state
        regs = self._fetch(state)
        self._pending = []
        # Local deltas so several syscalls within one sync see each
        # other's effects before the device does.
        self._local_written: dict[tuple, int] = {}
        self._local_read: dict[tuple, int] = {}
        self._local_pops = {}

        for p in self.procs:          # deterministic order: spawn order
            self._run_until_blocked(p, regs, now_ns)

        return self._apply(state, now_ns)

    def quiescent(self, now_ns: int) -> bool:
        """True when no substrate work can happen at `now_ns`: every
        live process is parked on a pure timer (sleep with a future
        wake) and no deferred spawn or stoptime is due.  A quiescent
        epoch's syncs would all take the idle fast path in _sync and
        return the state unchanged, so the bridge loop batches its
        per-window RPCs across the park epoch by skipping them
        entirely (bridge.run)."""
        if any(s[0] <= now_ns for s in self._spawn_queue):
            return False
        live = [p for p in self.procs if not p.exited]
        if not live:
            return False
        for p in live:
            stop_ns = getattr(p, "stop_ns", None)
            if stop_ns is not None and now_ns >= stop_ns:
                return False
            if p.parked is None or p.parked.op != OP_SLEEP \
                    or p.parked.wake_ns <= now_ns:
                return False
        return True

    def next_wake(self) -> int | None:
        """Earliest virtual time a parked process needs (sleep expiry or
        a deferred spawn's start time)."""
        wakes = [p.parked.wake_ns for p in self.procs
                 if not p.exited and p.parked is not None
                 and p.parked.op in (OP_SLEEP, OP_POLL)
                 and p.parked.wake_ns >= 0]
        wakes += [s[0] for s in self._spawn_queue]
        wakes += [p.stop_ns for p in self.procs
                  if not p.exited and p.stop_ns is not None]
        return min(wakes) if wakes else None

    def all_exited(self) -> bool:
        return not self._spawn_queue and all(p.exited for p in self.procs)

    # -- internals ------------------------------------------------------------

    def _fetch(self, state):
        socks = state.socks
        self.max_slots = socks.slots
        names = ("tcp_state", "rcv_nxt", "rcv_read", "snd_una", "snd_end",
                 "snd_buf_cap", "error", "fin_seq", "stype",
                 "parent", "accepted", "child_order",
                 "local_port", "peer_host", "peer_port",
                 "udp_head", "udp_count", "udp_src", "udp_sport",
                 "udp_len", "udp_payload")
        vals = jax.device_get(tuple(getattr(socks, n) for n in names))
        trace.current().transfer(sum(v.nbytes for v in vals), count=1)
        regs = dict(zip(names, vals))
        tx = self._find_tx(state)
        self._has_tx = tx is not None
        if tx is not None:
            counts, heads = jax.device_get((tx.count, tx.head))
            trace.current().transfer(counts.nbytes + heads.nbytes, count=1)
            self._tx_inflight = {h: int(c) for h, c in enumerate(counts)}
            self._tx_base = dict(self._tx_inflight)  # count at fetch time
            self._tx_head = {h: int(v) for h, v in enumerate(heads)}
            self._tx_appended = {}  # per-sync appends already applied
        # Reservations/accept-marks the device has caught up on can be
        # forgotten (keeps the sets from growing for the run's lifetime).
        from ..core.state import SOCK_FREE

        for h, taken in self._reserved.items():
            taken.difference_update(
                s for s in list(taken) if int(regs["stype"][h, s]) != SOCK_FREE)
        for h, taken in self._accept_taken.items():
            taken.difference_update(
                s for s in list(taken) if bool(regs["accepted"][h, s]))
        return regs

    def _run_until_blocked(self, p: RealProcess, regs, now_ns):
        if p.exited:
            return
        # A parked syscall must become unblocked before the process runs.
        if p.parked is not None:
            rep = self._try_unpark(p, regs, now_ns)
            if rep is None:
                return
            self._reply(p, *rep)
            p.parked = None
        elif p.started:
            return  # running state impossible: it always parks or exits
        p.started = True

        # Pump: read requests until the process parks or exits.
        while True:
            status, req = self._wait(p)
            if status == 0:
                p.exited = True
                p.exit_code = req
                return
            if status == -2:
                raise RuntimeError(
                    f"process {p.proc_id} wedged (no syscall within "
                    f"timeout); runaway compute loop?")
            if status < 0:
                raise RuntimeError(
                    f"sequencer IPC error for process {p.proc_id} "
                    f"(status {status})")
            rep = self._handle(p, req, regs, now_ns)
            if rep is None:
                return  # parked
            self._reply(p, *rep)

    def _wait(self, p: RealProcess, timeout_ms: int | None = None):
        if timeout_ms is None:
            timeout_ms = self.wedge_timeout_ms
        op = ctypes.c_uint32()
        fd = ctypes.c_int32()
        a0 = ctypes.c_int64()
        a1 = ctypes.c_int64()
        data = (ctypes.c_uint8 * MAX_DATA)()
        length = ctypes.c_uint32()
        # The shim RPC: wall time from handing control to the process
        # until its next syscall arrives (the substrate path's per-RPC
        # latency; histogrammed by the profiler as `bridge_rpc`).
        with trace.current().span("bridge_rpc", proc=p.proc_id):
            r = self._lib.seq_wait_request(
                self.handle, p.proc_id, timeout_ms,
                ctypes.byref(op), ctypes.byref(fd),
                ctypes.byref(a0), ctypes.byref(a1),
                data, ctypes.byref(length))
        if r == 0:
            return 0, int(a0.value)
        if r == 1:
            return 1, (int(op.value), int(fd.value), int(a0.value),
                       int(a1.value), bytes(data[:length.value]))
        return r, None

    def _reply(self, p: RealProcess, ret, err=0, payload=b""):
        buf = (ctypes.c_uint8 * max(1, len(payload)))(*payload)
        r = self._lib.seq_reply(self.handle, p.proc_id, ret, err,
                                EMULATED_EPOCH_NS + self._now, buf,
                                len(payload))
        assert r == 0

    # --- syscall semantics ---------------------------------------------------

    def _handle(self, p: RealProcess, req, regs, now_ns):
        """Returns a reply tuple (ret, err, payload) or None to park."""
        self._now = now_ns
        op, fd, a0, a1, data = req
        h = p.host
        p.trace.append((now_ns, op, fd, a0, a1, len(data)))

        if op == OP_SOCKET:
            if p.next_vfd - VFD_BASE >= 4096:
                return (-1, 24, b"")  # EMFILE: shim table exhausted
            slot = self._pick_slot(h, regs)
            if slot is None:
                return (-1, 24, b"")  # EMFILE: device socket table full
            self._pending.append(("reserve", h, slot))
            vfd = p.next_vfd
            p.next_vfd += 1
            kind = "udp" if (int(a0) & 0xF) == SOCK_DGRAM else "tcp"
            vs = VSocket(slot=slot, vfd=vfd, kind=kind)
            p.vfds[vfd] = vs
            return (vfd, 0, b"")

        if op == OP_PIPE:
            if p.next_vfd - VFD_BASE >= 4095:
                return (-1, 24, b"")
            pipe = VPipe()
            rfd, wfd = p.next_vfd, p.next_vfd + 1
            p.next_vfd += 2
            p.vfds[rfd] = VSocket(slot=-1, vfd=rfd, kind="pipe_r", pipe=pipe)
            p.vfds[wfd] = VSocket(slot=-1, vfd=wfd, kind="pipe_w", pipe=pipe)
            return (rfd, 0, np.asarray([wfd], np.int32).tobytes())

        if op == OP_RESOLVE:
            name = data.decode("utf-8", "replace")
            ip = self.resolve_name(name) if self.resolve_name else None
            if ip is None:
                return (-1, 2, b"")  # ENOENT -> EAI_NONAME shim-side
            return (0, 0, np.asarray([ip], np.uint32).tobytes())

        if op == OP_GETTIME:
            return (0, 0, b"")

        if op == OP_SLEEP:
            p.parked = Parked(OP_SLEEP, wake_ns=now_ns + max(0, a0))
            return None

        if op == OP_POLL:
            return self._do_poll(p, data, timeout_ms=int(a0),
                                 regs=regs, now_ns=now_ns)

        vs = p.vfds.get(fd)
        if vs is None:
            return (-1, 9, b"")  # EBADF

        if op == OP_BIND:
            vs.local_port = int(a1)
            if vs.kind == "udp":
                self._pending.append(("udp_open", h, vs.slot, vs.local_port))
            return (0, 0, b"")

        if op == OP_SENDTO:
            rep = self._do_sendto(p, vs, data, regs, dst_ip=int(a0),
                                  dport=int(a1) & 0xFFFF)
            if rep is not None and rep == ("ring_full",):
                if a1 >> 32:  # nonblocking
                    return (-1, _EAGAIN, b"")
                pk = Parked(OP_SENDTO, fd=fd, a0=int(a0),
                            a1=int(a1) & 0xFFFF)
                pk.data = data  # type: ignore[attr-defined]
                p.parked = pk
                return None
            return rep

        if op == OP_RECVFROM:
            nonblock = bool(a1 & (1 << 30))
            if vs.kind != "udp":
                # recvfrom() on a stream socket/pipe == recv() with a
                # zeroed source address.
                rep = self._do_recv(p, vs, int(a0), regs, nonblock)
                if rep is None:
                    p.parked = Parked(OP_RECVFROM, fd=fd, a0=int(a0))
                return self._wrap_rf(rep)
            rep = self._try_recvfrom(p, vs, int(a0), regs)
            if rep is not None:
                return rep
            if nonblock:
                return (-1, _EAGAIN, b"")
            p.parked = Parked(OP_RECVFROM, fd=fd, a0=int(a0))
            return None

        if op == OP_LISTEN:
            if not vs.local_port:
                vs.local_port = self._alloc_port()
            vs.listening = True
            self._pending.append(("listen", h, vs.slot, vs.local_port,
                                  max(1, int(a0))))
            return (0, 0, b"")

        if op == OP_ACCEPT:
            if not vs.listening:
                return (-1, 22, b"")  # EINVAL
            rep = self._try_accept(p, vs, regs)
            if rep is not None:
                return rep
            if a0:  # nonblocking
                return (-1, _EAGAIN, b"")
            p.parked = Parked(OP_ACCEPT, fd=fd)
            return None

        if op == OP_CONNECT:
            if vs.kind == "udp":
                # Connected UDP: record the default peer; succeeds
                # instantly like Linux (no handshake).
                vs.udp_peer = (int(a0), int(a1) & 0xFFFF)
                vs.connected = True
                return (0, 0, b"")
            # ip 0 or 127.0.0.1: the process's own host (loopback; also
            # how the shim virtualizes AF_UNIX paths -- reference maps
            # unix-path sockets onto ports, socket.h:47-78).
            if int(a0) in (0, 0x7F000001):
                dst = h
            else:
                dst = self.resolve_ip(int(a0))
            if dst is None:
                return (-1, _ECONNREFUSED, b"")
            nonblock = bool(a1 >> 32)
            dport = int(a1) & 0xFFFF
            if not vs.local_port:
                vs.local_port = self._alloc_port()
            vs.connecting = True
            vs.conn_key = (h, vs.local_port, dst, dport)
            self._conns[vs.conn_key] = vs
            self._pending.append(("connect", h, vs.slot, dst, dport,
                                  vs.local_port))
            if nonblock:
                return (-1, _EINPROGRESS, b"")
            p.parked = Parked(OP_CONNECT, fd=fd)
            return None

        if op == OP_SEND:
            return self._do_send(p, vs, data, regs, nonblock=bool(a1))

        if op == OP_RECV:
            nonblock = bool(a1 & (1 << 30))
            return self._do_recv(p, vs, int(a0), regs, nonblock)

        if op == OP_CLOSE:
            if not vs.closed:
                vs.closed = True
                if vs.pipe is not None:
                    if vs.kind == "pipe_r":
                        vs.pipe.read_open = False
                    else:
                        vs.pipe.write_open = False
                elif vs.kind == "udp":
                    # Drop the sync-local pop count with the ring: the
                    # udp_close apply op zeroes udp_head/udp_count, so a
                    # stale _local_pops entry would make a slot-reusing
                    # socket see a negative available count.
                    self._local_pops.pop((p.host, vs.slot), None)
                    self._pending.append(("udp_close", p.host, vs.slot))
                else:
                    self._pending.append(("close", p.host, vs.slot))
            return (0, 0, b"")

        return (-1, 38, b"")  # ENOSYS

    def _room(self, p, vs, regs):
        h = p.host
        key = (h, vs.slot)
        snd_end = int(regs["snd_end"][h, vs.slot]) + \
            self._local_written.get(key, 0)
        used = (snd_end - int(regs["snd_una"][h, vs.slot])) & 0xFFFFFFFF
        return int(regs["snd_buf_cap"][h, vs.slot]) - used

    @staticmethod
    def _find_tx(state):
        """Locate the SubstrateTx ring state: state.app directly, or an
        element of a Stacked app tuple; None if the world has none (then
        real-process UDP sends are unavailable)."""
        from .devapp import SubTxState

        app = state.app
        if isinstance(app, SubTxState):
            return app
        if isinstance(app, tuple):
            for s in app:
                if isinstance(s, SubTxState):
                    return s
        return None

    @staticmethod
    def _replace_tx(state, new_tx):
        from .devapp import SubTxState

        app = state.app
        if isinstance(app, SubTxState):
            return state.replace(app=new_tx)
        subs = tuple(new_tx if isinstance(s, SubTxState) else s
                     for s in app)
        return state.replace(app=subs)

    @staticmethod
    def _fin_reached(rcv_nxt: int, fin_seq: int) -> bool:
        """True once the peer's FIN has been processed (rcv_nxt advanced to
        or past fin_seq; the FIN consumes a sequence slot).  Scalar analog
        of transport.tcp.data_end's clamp condition."""
        return fin_seq != 0 and ((rcv_nxt - fin_seq) & 0xFFFFFFFF) < 0x80000000

    def _avail(self, p, vs, regs):
        h = p.host
        key = (h, vs.slot)
        rcv_nxt = int(regs["rcv_nxt"][h, vs.slot])
        fin_seq = int(regs["fin_seq"][h, vs.slot])
        # Readable data ends at fin_seq, not rcv_nxt -- otherwise a
        # read-until-EOF loop receives one fabricated byte before EOF
        # (transport.tcp.data_end docstring).
        data_end = fin_seq if self._fin_reached(rcv_nxt, fin_seq) else rcv_nxt
        d = (data_end - int(regs["rcv_read"][h, vs.slot])) & 0xFFFFFFFF
        if d >= 0x80000000:   # signed wrap guard: rcv_read never passes
            d -= 1 << 32      # data_end, but stay safe under mod-2^32
        return d - self._local_read.get(key, 0)

    # --- pipes ---------------------------------------------------------------

    def _pipe_send(self, p, vs, data, nonblock):
        pipe = vs.pipe
        if vs.kind != "pipe_w":
            return (-1, 9, b"")  # EBADF: read end
        if not pipe.read_open:
            return (-1, 32, b"")  # EPIPE
        room = VPipe.CAP - len(pipe.buf)
        if room <= 0:
            if nonblock:
                return (-1, _EAGAIN, b"")
            pk = Parked(OP_SEND, fd=vs.vfd)
            pk.data = data  # type: ignore[attr-defined]
            p.parked = pk
            return None
        n = min(len(data), room)
        pipe.buf.extend(data[:n])
        return (n, 0, b"")

    def _pipe_recv(self, p, vs, maxlen, nonblock):
        pipe = vs.pipe
        if vs.kind != "pipe_r":
            return (-1, 9, b"")
        if pipe.buf:
            n = min(maxlen, len(pipe.buf), MAX_DATA)
            out = bytes(pipe.buf[:n])
            del pipe.buf[:n]
            return (n, 0, out)
        if not pipe.write_open:
            return (0, 0, b"")  # EOF
        if nonblock:
            return (-1, _EAGAIN, b"")
        p.parked = Parked(OP_RECV, fd=vs.vfd, a0=maxlen)
        return None

    # --- UDP datagrams -------------------------------------------------------

    @staticmethod
    def _wrap_rf(rep):
        """Adapt a recv()-shaped reply to recvfrom()'s wire format
        ({u32 ip, u32 port} header, zeroed for stream sockets)."""
        if rep is None:
            return None
        ret, err, payload = rep
        if ret > 0:
            return (ret, err, bytes(8) + payload)
        return rep

    def _do_sendto(self, p, vs, data, regs, dst_ip, dport):
        if vs.kind != "udp" or not getattr(self, "_has_tx", False):
            return (-1, 95, b"")  # EOPNOTSUPP (no SubstrateTx ring app)
        h = p.host
        dst = self.resolve_ip(dst_ip)
        if dst is None:
            return (-1, 101, b"")  # ENETUNREACH
        if not vs.local_port:
            vs.local_port = self._alloc_port()
            self._pending.append(("udp_open", h, vs.slot, vs.local_port))
        from .devapp import RING
        if self._tx_inflight.get(h, 0) >= RING:
            # Device TX ring full: the caller parks (blocking) or gets
            # EAGAIN (nonblocking) -- decided by the OP_SENDTO handler.
            return ("ring_full",)
        if data:
            # Entries normally release at the receiver's recvfrom; a
            # datagram dropped in the network (reliability draw, ring
            # overflow) never pops, so bound the map: evict the OLDEST
            # entries past the cap (their content degrades to zeros if
            # such a datagram were still delivered -- it is overwhelmingly
            # already dead).  Python dicts iterate in insertion order.
            if len(self._pid_map) >= 8192:
                import sys
                for old in list(self._pid_map)[:1024]:
                    self.arena.unref(self._pid_map.pop(old))
                print("substrate: evicted 1024 oldest datagram payloads "
                      "(drop-leak bound)", file=sys.stderr)
            handle = self.arena.put(bytes(data))
            pid = self._next_pid
            self._next_pid += 1
            assert pid < (1 << 31), "payload id space exhausted"
            self._pid_map[pid] = handle
        else:
            pid = -1
        self._tx_inflight[h] = self._tx_inflight.get(h, 0) + 1
        self._pending.append(("udp_tx", h, dst, dport, vs.local_port,
                              len(data), pid))
        return (len(data), 0, b"")

    def _try_recvfrom(self, p, vs, maxlen, regs):
        """Reply for recvfrom() if a datagram is queued, else None.
        Payload wire format: {u32 src_ip, u32 src_port} + bytes."""
        if vs.kind != "udp":
            return (-1, 95, b"")
        h, s = p.host, vs.slot
        key = (h, s)
        pops = self._local_pops.get(key, 0)
        if int(regs["udp_count"][h, s]) - pops <= 0:
            return None
        ring = regs["udp_src"].shape[2]
        at = (int(regs["udp_head"][h, s]) + pops) % ring
        src = int(regs["udp_src"][h, s, at])
        sport = int(regs["udp_sport"][h, s, at])
        length = int(regs["udp_len"][h, s, at])
        pid = int(regs["udp_payload"][h, s, at])
        handle = self._pid_map.pop(pid, None) if pid > 0 else None
        if handle is not None:
            content = self.arena.get(handle)[:length]
            self.arena.unref(handle)
        else:
            content = bytes(length)
        n = min(maxlen, len(content))
        self._local_pops[key] = pops + 1
        self._pending.append(("udp_pop", h, s))
        src_ip = self.host_ip(src) if self.host_ip else 0
        hdr = np.asarray([src_ip & 0xFFFFFFFF, sport],
                         np.uint32).tobytes()
        return (n, 0, hdr + content[:n])

    def _do_send(self, p, vs, data, regs, nonblock):
        if vs.pipe is not None:
            return self._pipe_send(p, vs, data, nonblock)
        if vs.kind == "udp":
            if vs.udp_peer is None:
                return (-1, 89, b"")  # EDESTADDRREQ
            rep = self._do_sendto(p, vs, data, regs,
                                  dst_ip=vs.udp_peer[0],
                                  dport=vs.udp_peer[1])
            if rep == ("ring_full",):
                if nonblock:
                    return (-1, _EAGAIN, b"")
                pk = Parked(OP_SENDTO, fd=vs.vfd, a0=vs.udp_peer[0],
                            a1=vs.udp_peer[1])
                pk.data = data  # type: ignore[attr-defined]
                p.parked = pk
                return None
            return rep
        room = self._room(p, vs, regs)
        if room <= 0:
            if nonblock:
                return (-1, _EAGAIN, b"")
            p.parked = Parked(OP_SEND, fd=vs.vfd)
            p.parked.data = data  # type: ignore[attr-defined]
            return None
        n = min(len(data), room)
        vs.sent.extend(data[:n])
        key = (p.host, vs.slot)
        self._local_written[key] = self._local_written.get(key, 0) + n
        self._pending.append(("write", p.host, vs.slot, n))
        return (n, 0, b"")

    def _do_recv(self, p, vs, maxlen, regs, nonblock):
        if vs.pipe is not None:
            return self._pipe_recv(p, vs, maxlen, nonblock)
        if vs.kind == "udp":
            rep = self._try_recvfrom(p, vs, maxlen, regs)
            if rep is not None:
                # recv() drops the address header.
                ret, err, payload = rep
                return (ret, err, payload[8:] if payload else payload)
            if nonblock:
                return (-1, _EAGAIN, b"")
            p.parked = Parked(OP_RECV, fd=vs.vfd, a0=maxlen)
            return None
        avail = self._avail(p, vs, regs)
        if avail <= 0:
            st = int(regs["tcp_state"][p.host, vs.slot])
            err = int(regs["error"][p.host, vs.slot])
            if err != 0:
                # RST/timeout surfaces as a recv error, like Linux
                # (ECONNRESET/ETIMEDOUT), not a clean EOF.
                return (-1, err, b"")
            # Peer closed and everything consumed -> EOF.  The peer's FIN
            # having been processed (rcv_nxt advanced past fin_seq) covers
            # BOTH close orders: passive close (CLOSEWAIT/LASTACK) and
            # active close (FINWAIT2/CLOSING/TIMEWAIT after we half-closed
            # first) -- a state-list check alone parks an active-closing
            # reader forever.
            fin_done = self._fin_reached(
                int(regs["rcv_nxt"][p.host, vs.slot]),
                int(regs["fin_seq"][p.host, vs.slot]))
            if fin_done or st in (tcp.TCPS_CLOSEWAIT, tcp.TCPS_LASTACK,
                                  tcp.TCPS_CLOSED):
                return (0, 0, b"")
            if nonblock:
                return (-1, _EAGAIN, b"")
            p.parked = Parked(OP_RECV, fd=vs.vfd, a0=maxlen)
            return None
        n = min(maxlen, avail, MAX_DATA)
        payload = self._content(p.host, vs, n)
        vs.recv_cursor += n
        key = (p.host, vs.slot)
        self._local_read[key] = self._local_read.get(key, 0) + n
        self._pending.append(("read", p.host, vs.slot, n))
        return (n, 0, payload)

    def _content(self, host, vs, n):
        if vs.peer is not None:
            # Real peer: the bytes ARE the opposite endpoint's sent stream.
            out = bytes(vs.peer.sent[vs.recv_cursor:vs.recv_cursor + n])
            assert len(out) == n, (
                "device delivered bytes the real peer never wrote "
                f"(cursor={vs.recv_cursor} n={n} peer_sent={len(vs.peer.sent)})")
            return out
        if self.content_provider is None:
            return bytes(n)
        out = self.content_provider(host, vs, vs.recv_cursor, n)
        assert len(out) == n, "content provider returned wrong length"
        return out

    def _find_child(self, p: RealProcess, vs: VSocket, regs) -> int | None:
        """Lowest-child_order ESTABLISHED (or later) un-accepted child of
        the listener at vs.slot; None if the accept queue is empty.
        child_order is the SYN's packet id -- deterministic arrival order
        (reference tcp.c child multiplexing orders the accept queue the
        same way)."""
        h = p.host
        taken = self._accept_taken.setdefault(h, set())
        st = regs["tcp_state"][h]
        cand = (regs["parent"][h] == vs.slot) & ~regs["accepted"][h] & \
            ((st == tcp.TCPS_ESTABLISHED) | (st == tcp.TCPS_CLOSEWAIT))
        slots = np.flatnonzero(cand)
        slots = [s for s in slots if s not in taken]
        if not slots:
            return None
        order = regs["child_order"][h]
        return int(min(slots, key=lambda s: (int(order[s]), s)))

    def _try_accept(self, p: RealProcess, vs: VSocket, regs):
        """Reply tuple for accept() if a child connection is ready."""
        cslot = self._find_child(p, vs, regs)
        if cslot is None:
            return None
        h = p.host
        if p.next_vfd - VFD_BASE >= 4096:
            return (-1, 24, b"")  # EMFILE
        self._accept_taken.setdefault(h, set()).add(cslot)
        self._pending.append(("accepted", h, cslot))
        vfd = p.next_vfd
        p.next_vfd += 1
        child = VSocket(slot=cslot, vfd=vfd, local_port=vs.local_port,
                        connected=True)
        p.vfds[vfd] = child
        # Real<->real pairing: the child's device registers carry the
        # remote (host, port); if that endpoint is a real process it
        # registered itself at connect time.
        key = (int(regs["peer_host"][h, cslot]),
               int(regs["peer_port"][h, cslot]), h, vs.local_port)
        mate = self._conns.pop(key, None)  # pairing consumes the entry
        if mate is not None:
            child.peer = mate
            mate.peer = child
        return (vfd, 0, b"")

    def _poll_check(self, p: RealProcess, entries, regs):
        """Compute (nready, payload) for a poll entry list [(fd, events)].
        Payload wire format matches the shim: per entry int32 revents,
        int32 soerr."""
        h = p.host
        out = np.zeros(2 * len(entries), dtype=np.int32)
        nready = 0
        for i, (fd, events) in enumerate(entries):
            vs = p.vfds.get(fd)
            rev = 0
            soerr = 0
            if vs is None:
                # Shim contract: non-virtual fds in a mixed set report
                # not-ready (revents 0); only a DANGLING virtual fd (in
                # the vfd range but unknown) is POLLNVAL.
                if fd >= VFD_BASE:
                    rev = POLLNVAL
            elif vs.closed:
                # A closed vfd left in a poll set must never consult slot
                # registers: _pick_slot may have reused its slot for a
                # newer connection.  Linux reports POLLNVAL for poll on a
                # closed fd; epoll drops it from the set (callers of this
                # helper filter accordingly).
                rev = POLLNVAL
            elif vs.pipe is not None:
                if vs.kind == "pipe_r":
                    if vs.pipe.buf or not vs.pipe.write_open:
                        rev |= POLLIN
                    if not vs.pipe.write_open:
                        rev |= POLLHUP
                else:
                    if not vs.pipe.read_open:
                        rev |= POLLERR
                    elif len(vs.pipe.buf) < VPipe.CAP:
                        rev |= POLLOUT
            elif vs.kind == "udp":
                key = (h, vs.slot)
                if int(regs["udp_count"][h, vs.slot]) - \
                        self._local_pops.get(key, 0) > 0:
                    rev |= POLLIN
                from .devapp import RING
                if self._tx_inflight.get(h, 0) < RING:
                    rev |= POLLOUT
            elif vs.listening:
                if self._find_child(p, vs, regs) is not None:
                    rev |= POLLIN
            else:
                st = int(regs["tcp_state"][h, vs.slot])
                err = int(regs["error"][h, vs.slot])
                if vs.connecting:
                    if st == tcp.TCPS_ESTABLISHED:
                        vs.connecting = False
                        vs.connected = True
                    elif err != 0:
                        vs.connecting = False
                        rev |= POLLERR
                        soerr = err
                if not vs.connecting and not (rev & POLLERR):
                    avail = self._avail(p, vs, regs)
                    fin_done = self._fin_reached(
                        int(regs["rcv_nxt"][h, vs.slot]),
                        int(regs["fin_seq"][h, vs.slot]))
                    if avail > 0 or (fin_done and avail <= 0) or \
                            st in (tcp.TCPS_CLOSEWAIT, tcp.TCPS_LASTACK,
                                   tcp.TCPS_CLOSED):
                        rev |= POLLIN
                    if err != 0:
                        rev |= POLLERR
                        soerr = err
                    elif (vs.connected or st == tcp.TCPS_ESTABLISHED or
                          st == tcp.TCPS_CLOSEWAIT) and not vs.closed and \
                            self._room(p, vs, regs) > 0:
                        rev |= POLLOUT
            rev &= (events | POLLERR | POLLHUP | POLLNVAL)
            if rev:
                nready += 1
            out[2 * i] = rev
            out[2 * i + 1] = soerr
        return nready, out.tobytes()

    def _do_poll(self, p: RealProcess, data: bytes, timeout_ms: int,
                 regs, now_ns: int):
        arr = np.frombuffer(data, dtype=np.int32)
        entries = [(int(arr[2 * i]), int(arr[2 * i + 1]))
                   for i in range(len(arr) // 2)]
        nready, payload = self._poll_check(p, entries, regs)
        if nready > 0 or timeout_ms == 0:
            return (nready, 0, payload)
        pk = Parked(OP_POLL)
        pk.entries = entries  # type: ignore[attr-defined]
        if timeout_ms > 0:
            pk.wake_ns = now_ns + timeout_ms * 1_000_000
        p.parked = pk
        return None

    def _try_unpark(self, p: RealProcess, regs, now_ns):
        """If the parked syscall's condition now holds, produce its reply."""
        self._now = now_ns
        pk = p.parked
        if pk.op == OP_SLEEP:
            return (0, 0, b"") if now_ns >= pk.wake_ns else None
        if pk.op == OP_POLL:
            entries = getattr(pk, "entries", [])
            nready, payload = self._poll_check(p, entries, regs)
            if nready > 0:
                return (nready, 0, payload)
            if pk.wake_ns >= 0 and now_ns >= pk.wake_ns:
                return (0, 0, payload)  # timeout: all revents zero
            return None
        vs = p.vfds.get(pk.fd)
        if vs is None:
            return (-1, 9, b"")
        h = p.host
        if pk.op == OP_ACCEPT:
            return self._try_accept(p, vs, regs)  # None = still parked
        if pk.op == OP_RECVFROM:
            if vs.kind != "udp":
                rep = self._do_recv(p, vs, pk.a0, regs, nonblock=False)
                if rep is None:
                    p.parked = pk
                return self._wrap_rf(rep)
            return self._try_recvfrom(p, vs, pk.a0, regs)
        if pk.op == OP_SENDTO:
            rep = self._do_sendto(p, vs, getattr(pk, "data", b""), regs,
                                  dst_ip=pk.a0, dport=pk.a1)
            if rep == ("ring_full",):
                return None  # still parked
            return rep
        if pk.op == OP_CONNECT:
            st = int(regs["tcp_state"][h, vs.slot])
            err = int(regs["error"][h, vs.slot])
            if st == tcp.TCPS_ESTABLISHED:
                vs.connected = True
                vs.connecting = False
                return (0, 0, b"")
            if err != 0:
                # Every failure path (RST, handshake timeout) sets the
                # socket error register.
                return (-1, _ECONNREFUSED, b"")
            return None
        if pk.op == OP_SEND:
            data = getattr(pk, "data", b"")
            rep = self._do_send(p, vs, data, regs, nonblock=False)
            if rep is None:
                p.parked = pk  # still blocked
            return rep
        if pk.op == OP_RECV:
            rep = self._do_recv(p, vs, pk.a0, regs, nonblock=False)
            if rep is None:
                p.parked = pk
            return rep
        return (-1, 38, b"")

    # --- device application ---------------------------------------------------

    def _apply(self, state, now_ns):
        """Apply queued socket ops through the vectorized transport API."""
        if not self._pending:
            return state
        import jax.numpy as jnp

        socks = state.socks
        hN = socks.num_hosts
        now = jnp.asarray(now_ns, jnp.int64)
        wake = np.zeros(hN, bool)   # hosts that must tick to act on this

        for op in self._pending:
            kind = op[0]
            if kind == "reserve":
                # Mark the slot taken (stype SOCK_TCP, state CLOSED) so the
                # device's min-free child allocation can never collide with
                # a socket the process created but hasn't connected yet.
                from ..core.state import SOCK_TCP
                _, h, slot = op
                socks = socks.replace(
                    stype=socks.stype.at[h, slot].set(SOCK_TCP))
            elif kind == "listen":
                _, h, slot, port, backlog = op
                mask = np.zeros(hN, bool)
                mask[h] = True
                socks = tcp.listen_v(socks, jnp.asarray(mask), slot, port,
                                     backlog)
            elif kind == "accepted":
                _, h, slot = op
                socks = socks.replace(
                    accepted=socks.accepted.at[h, slot].set(True))
            elif kind == "udp_open":
                from ..core.state import SOCK_UDP
                _, h, slot = op[:3]
                port = op[3]
                socks = socks.replace(
                    stype=socks.stype.at[h, slot].set(SOCK_UDP),
                    local_port=socks.local_port.at[h, slot].set(port),
                    peer_host=socks.peer_host.at[h, slot].set(-1),
                    peer_port=socks.peer_port.at[h, slot].set(0))
            elif kind == "udp_close":
                from ..core.state import SOCK_FREE
                _, h, slot = op
                # Zero the datagram ring bookkeeping too: a later UDP
                # socket reusing this slot must not inherit the stale
                # queue (ghost datagrams from _try_recvfrom).
                socks = socks.replace(
                    stype=socks.stype.at[h, slot].set(SOCK_FREE),
                    local_port=socks.local_port.at[h, slot].set(0),
                    udp_head=socks.udp_head.at[h, slot].set(0),
                    udp_count=socks.udp_count.at[h, slot].set(0))
            elif kind == "udp_pop":
                from ..transport import udp as udp_mod
                _, h, slot = op
                mask = np.zeros(hN, bool)
                mask[h] = True
                slot_v = np.zeros(hN, np.int32)
                slot_v[h] = slot
                socks, _g, _s, _p2, _l, _pid = udp_mod.pop_ring(
                    socks, jnp.asarray(mask), jnp.asarray(slot_v))
            elif kind == "udp_tx":
                _, h, dst, dport, sport, length, pid = op
                tx = self._find_tx(state)
                assert tx is not None, (
                    "real-process UDP needs a SubstrateTx app in the "
                    "world (substrate.devapp; compose with apps.compose."
                    "Stacked)")
                from .devapp import RING
                # Ring position from host-side snapshots (head/count at
                # fetch + appends this sync) -- no device round trips.
                k = self._tx_appended.get(h, 0)
                self._tx_appended[h] = k + 1
                pos = (self._tx_head[h] + self._tx_base[h] + k) % RING
                tx = tx.replace(
                    dst=tx.dst.at[h, pos].set(dst),
                    dport=tx.dport.at[h, pos].set(dport),
                    sport=tx.sport.at[h, pos].set(sport),
                    length=tx.length.at[h, pos].set(length),
                    payload=tx.payload.at[h, pos].set(pid),
                    count=tx.count.at[h].add(1))
                state = self._replace_tx(state, tx)
                wake[h] = True
            elif kind == "connect":
                _, h, slot, dst, dport, lport = op
                mask = np.zeros(hN, bool)
                mask[h] = True
                socks = tcp.connect_v(socks, jnp.asarray(mask), slot,
                                      dst, dport, lport, now)
            elif kind == "write":
                _, h, slot, n = op
                mask = np.zeros(hN, bool)
                mask[h] = True
                target = (socks.snd_end[h, slot] + np.uint32(n))
                socks = tcp.write_v(socks, jnp.asarray(mask), slot,
                                    target, now=now)
                wake[h] = True
            elif kind == "read":
                _, h, slot, n = op
                socks = socks.replace(
                    rcv_read=socks.rcv_read.at[h, slot].add(np.uint32(n)))
                wake[h] = True   # reopened window may need an ACK/update
            elif kind == "close":
                _, h, slot = op
                mask = np.zeros(hN, bool)
                mask[h] = True
                socks = tcp.close_v(socks, jnp.asarray(mask), slot)
                wake[h] = True
        self._pending = []
        state = state.replace(socks=socks)
        if wake.any():
            # New sendable work exists outside any tick: the host must
            # micro-step at `now` for the transmitter to see it (modeled
            # apps get this for free because they write during phase C).
            import jax.numpy as jnp2
            hosts = state.hosts
            state = state.replace(hosts=hosts.replace(
                t_resume=jnp2.minimum(hosts.t_resume,
                                      jnp2.where(jnp2.asarray(wake), now,
                                                 jnp2.asarray(
                                                     simtime.SIMTIME_INVALID,
                                                     jnp2.int64)))))
        return state


def run(substrate: Substrate, state, params, app, t_target: int,
        sync_interval_ns: int | None = None):
    """Drive the simulation with real processes attached: alternate device
    windows with substrate syncs until t_target (or everything exits)."""
    from ..core import engine

    if sync_interval_ns is None:
        sync_interval_ns = int(params.min_latency_ns)
    t = int(state.now)
    state = substrate.sync(state, params, t)
    while t < t_target:
        if substrate.all_exited():
            # No process can ever act again: finish the span as a pure
            # engine run (modeled apps may still be trafficking);
            # chunked so no single device launch is unbounded.
            return engine.run_chunked(state, params, app, t_target)
        wake = substrate.next_wake()
        t_next = min(t + sync_interval_ns, t_target)
        if wake is not None:
            t_next = min(max(wake, t + 1), t_next)
        prof = trace.current()
        with prof.span("device_step", t_ns=t_next):
            state = engine.run_until(state, params, app, t_next)
            if prof.sync:
                jax.block_until_ready(state)
        t = t_next
        # Park-epoch RPC batching: while every live process sleeps past
        # t (quiescent), each per-window sync would hit the idle fast
        # path and return the state unchanged -- so skip the RPC round
        # trip (seq_settime + park scan) entirely and publish the clock
        # again at the next epoch with real work.  The device launch
        # grid above is computed before this check and is therefore
        # identical with or without the batching: the trajectory and
        # windows.jsonl cannot be affected.
        if not substrate.quiescent(t):
            state = substrate.sync(state, params, t)
    return state
