"""On-demand native builds for the process substrate.

The shim (.so preloaded into plugin processes) and the sequencer (.so
ctypes-loaded into the simulator) compile from `native/` on first use and
cache by source hash, so tests and CLI runs work from a source checkout
without a build step (the reference needs `./setup build`; here cc is
only invoked for the two small runtime libraries).
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess

_NATIVE = pathlib.Path(__file__).resolve().parents[2] / "native"
_CACHE = pathlib.Path(
    os.environ.get("SHADOW1_TPU_CACHE",
                   os.path.join(os.path.expanduser("~"), ".cache",
                                "shadow1_tpu_xla"))).parent / "shadow1_native"


def _build(src: pathlib.Path, out_name: str, compiler: str,
           extra: list[str]) -> str:
    _CACHE.mkdir(parents=True, exist_ok=True)
    tag = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    out = _CACHE / f"{out_name}-{tag}.so"
    if not out.exists():
        # Compile to a temp path + atomic rename so a concurrent run never
        # dlopens a partially written .so.
        tmp = _CACHE / f".{out_name}-{tag}.{os.getpid()}.so"
        cmd = [compiler, "-shared", "-fPIC", "-O2", "-o", str(tmp),
               str(src)] + extra
        subprocess.run(cmd, check=True, capture_output=True)
        os.rename(tmp, out)
    return str(out)


def shim_path() -> str:
    return _build(_NATIVE / "shim" / "shadow1_shim.c", "shadow1_shim",
                  "cc", ["-ldl", "-lpthread"])


def sequencer_path() -> str:
    return _build(_NATIVE / "sequencer.cc", "sequencer", "c++", [])


def build_binary(src: pathlib.Path, name: str) -> str:
    """Compile a plugin test binary (plain cc, no special flags)."""
    _CACHE.mkdir(parents=True, exist_ok=True)
    tag = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    out = _CACHE / f"{name}-{tag}"
    if not out.exists():
        tmp = _CACHE / f".{name}-{tag}.{os.getpid()}"
        subprocess.run(["cc", "-O1", "-o", str(tmp), str(src),
                        "-lpthread"],
                       check=True, capture_output=True)
        os.rename(tmp, out)
    return str(out)
