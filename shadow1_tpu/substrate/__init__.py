"""Real-code process substrate: run actual compiled binaries against the
simulated network (the reference's defining capability, rebuilt per
docs/design-process-substrate.md).

- native/shim/shadow1_shim.c: LD_PRELOAD syscall interposer (the
  reference's src/preload/interposer.c equivalent).
- native/sequencer.cc: process supervisor + deterministic run-until-
  blocked IPC pump (the process.c/rpth equivalent).
- bridge.py: fd tables, blocking semantics, and the window-protocol
  bridge onto the device engine.
"""

from .bridge import RealProcess, Substrate, run  # noqa: F401
