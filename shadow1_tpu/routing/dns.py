"""DNS / Address: hostname and IP identity for virtual hosts.

The reference keeps a global registry assigning each host a unique IPv4
address, skipping every reserved range, with bidirectional name<->IP
resolution (/root/reference/src/main/routing/dns.c:30-100,
address.c).  Host identity is needed at setup time (config hostnames,
peers lists, iphints) and at log/observability time; the device-side
engine itself addresses hosts by dense index, so this registry is
host-side Python that maps names and IPs onto those indices.
"""

from __future__ import annotations

import dataclasses
import ipaddress


# Reserved IPv4 ranges a generated address must avoid (reference
# _dns_isRestricted, dns.c:74-100).
_RESTRICTED = [ipaddress.ip_network(c) for c in (
    "0.0.0.0/8", "10.0.0.0/8", "100.64.0.0/10", "127.0.0.0/8",
    "169.254.0.0/16", "172.16.0.0/12", "192.0.0.0/29", "192.0.2.0/24",
    "192.88.99.0/24", "192.168.0.0/16", "198.18.0.0/15", "198.51.100.0/24",
    "203.0.113.0/24", "224.0.0.0/4", "240.0.0.0/4", "255.255.255.255/32",
)]


def is_restricted(ip_int: int) -> bool:
    a = ipaddress.ip_address(ip_int)
    return any(a in net for net in _RESTRICTED)


@dataclasses.dataclass
class Address:
    """Refcount-free analog of the reference Address (address.c): the
    (id, ip, hostname) triple."""

    host_index: int
    ip: int          # host-order integer
    name: str

    @property
    def ip_str(self) -> str:
        return str(ipaddress.ip_address(self.ip))


class DNS:
    """Global name/IP registry (reference dns.c)."""

    def __init__(self):
        self._by_name: dict[str, Address] = {}
        self._by_ip: dict[int, Address] = {}
        self._by_index: dict[int, Address] = {}
        self._ip_counter = int(ipaddress.ip_address("1.0.0.0"))

    def _next_ip(self) -> int:
        while True:
            self._ip_counter += 1
            ip = self._ip_counter
            if not is_restricted(ip) and ip not in self._by_ip:
                return ip

    def register(self, host_index: int, name: str,
                 requested_ip: str | None = None) -> Address:
        """Assign `name` a unique IP (honoring a usable requested one, like
        the reference's iphint) and bind it to the dense host index."""
        if name in self._by_name:
            raise ValueError(f"hostname {name!r} already registered")
        ip = None
        if requested_ip and requested_ip != "0.0.0.0":
            cand = int(ipaddress.ip_address(requested_ip))
            if not is_restricted(cand) and cand not in self._by_ip:
                ip = cand
        if ip is None:
            ip = self._next_ip()
        addr = Address(host_index=host_index, ip=ip, name=name)
        self._by_name[name] = addr
        self._by_ip[ip] = addr
        self._by_index[host_index] = addr
        return addr

    def resolve_name(self, name: str) -> Address:
        """name -> Address (reference dns_resolveNameToAddress); dotted
        quads resolve through the IP table."""
        if name in self._by_name:
            return self._by_name[name]
        try:
            ip = int(ipaddress.ip_address(name))
        except ValueError:
            raise KeyError(f"unknown hostname {name!r}") from None
        return self.resolve_ip(ip)

    def resolve_ip(self, ip: int) -> Address:
        if ip not in self._by_ip:
            raise KeyError(f"unknown address {ipaddress.ip_address(ip)}")
        return self._by_ip[ip]

    def address_of(self, host_index: int) -> Address:
        return self._by_index[host_index]

    def __len__(self):
        return len(self._by_name)
