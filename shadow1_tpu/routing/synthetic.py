"""Synthetic topologies for tests and benchmarks.

The reference ships tiny inline GraphML topologies for its test configs
(e.g. the 1-vertex CDATA topology in
/root/reference/src/test/determinism/determinism1.test.shadow.config.xml);
these helpers produce the equivalent dense matrices directly.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.simtime import TIME_DTYPE


def uniform_full_mesh(n_vertices: int, latency_ns: int,
                      reliability: float = 1.0):
    """Complete graph: every pair at `latency_ns`, including the
    self-path (which serves distinct hosts attached to the same vertex;
    same-host loopback bypasses the matrix entirely).  A sub-lookahead
    self-path would let same-vertex traffic arrive inside the current
    conservative window and break causality, so it must not be smaller
    than the uniform latency.

    Returns (latency_ns [V,V] i64, reliability [V,V] f32).
    """
    # Self-paths (distinct hosts on one vertex) get the same latency AND
    # loss as every other pair; same-host loopback never consults the
    # matrix (the engine forces 1ns / no-loss for dst == src).
    lat = jnp.full((n_vertices, n_vertices), latency_ns, TIME_DTYPE)
    rel = jnp.full((n_vertices, n_vertices), reliability, jnp.float32)
    return lat, rel
