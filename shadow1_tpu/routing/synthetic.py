"""Synthetic topologies for tests and benchmarks.

The reference ships tiny inline GraphML topologies for its test configs
(e.g. the 1-vertex CDATA topology in
/root/reference/src/test/determinism/determinism1.test.shadow.config.xml);
these helpers produce the equivalent dense matrices directly.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.simtime import TIME_DTYPE


def uniform_full_mesh(n_vertices: int, latency_ns: int,
                      reliability: float = 1.0):
    """Complete graph: every pair at `latency_ns`, self at 1ns.

    Returns (latency_ns [V,V] i64, reliability [V,V] f32).
    """
    eye = jnp.eye(n_vertices, dtype=bool)
    lat = jnp.where(eye, 1, latency_ns).astype(TIME_DTYPE)
    rel = jnp.where(eye, 1.0, reliability).astype(jnp.float32)
    return lat, rel
