"""GraphML topology ingestion -> dense routing arrays.

Reads the same GraphML the reference feeds igraph
(/root/reference/src/main/routing/topology.c:371-560): node attributes
{ip, citycode, countrycode, geocode, asn, type, bandwidthup, bandwidthdown,
packetloss}, edge attributes {latency (ms), jitter (ms), packetloss},
undirected by default, optional self-loop edges giving explicit
same-vertex path costs.  Existing topology files (including the bundled
`topology.graphml.xml.xz` style) load unchanged; `.xz` is handled
transparently.

Output is numpy adjacency matrices ready for `apsp.build_matrices` plus
per-vertex metadata used by the host-attachment hint ladder, the analog of
topology_attach's ip/city/country/geo/type preference matching
(topology.c:107-138,2371-2430).
"""

from __future__ import annotations

import dataclasses
import lzma
import os
import xml.etree.ElementTree as ET

import numpy as np

from .apsp import INF_MS

_NS = "{http://graphml.graphdrawing.org/xmlns}"


@dataclasses.dataclass
class Topology:
    names: list            # vertex id strings
    index: dict            # name -> vertex index
    ip: list               # dotted-quad strings ("0.0.0.0" = unassigned)
    citycode: list
    countrycode: list
    geocode: list
    typ: list
    asn: np.ndarray        # [V] i64
    bw_up_KiBps: np.ndarray    # [V] i64
    bw_down_KiBps: np.ndarray  # [V] i64
    vertex_loss: np.ndarray    # [V] f64
    lat_ms: np.ndarray     # [V,V] f32 adjacency, INF_MS where no edge, 0 diag
    edge_rel: np.ndarray   # [V,V] f32 per-edge reliability (vertex loss folded
                           # into the receiving end of each edge)
    jitter_ms: np.ndarray  # [V,V] f32 adjacency jitter
    self_lat_ms: np.ndarray  # [V] f32 explicit self-loop latency, nan = none
    self_rel: np.ndarray     # [V] f32
    self_jitter_ms: np.ndarray  # [V] f32

    @property
    def num_vertices(self) -> int:
        return len(self.names)


def _read_text(source: str) -> str:
    """Accept a file path (optionally .xz) or a literal GraphML string."""
    if source.lstrip().startswith("<"):
        return source
    if source.endswith(".xz"):
        with lzma.open(source, "rt") as f:
            return f.read()
    with open(source) as f:
        return f.read()


def load(source: str) -> Topology:
    root = ET.fromstring(_read_text(source))

    # key id -> (domain, attr name)
    keys = {}
    for k in root.iter(_NS + "key"):
        keys[k.get("id")] = (k.get("for"), k.get("attr.name"))

    graph = root.find(_NS + "graph")
    if graph is None:
        raise ValueError("GraphML has no <graph> element")

    def data_of(el):
        out = {}
        for d in el.findall(_NS + "data"):
            dom, name = keys.get(d.get("key"), (None, d.get("key")))
            out[name] = d.text or ""
        return out

    names, meta = [], []
    for node in graph.findall(_NS + "node"):
        names.append(node.get("id"))
        meta.append(data_of(node))
    index = {n: i for i, n in enumerate(names)}
    v = len(names)

    def col(name, default):
        return [m.get(name, default) for m in meta]

    asn = np.array([int(float(x or 0)) for x in col("asn", "0")], np.int64)
    bw_up = np.array([int(float(x or 0)) for x in col("bandwidthup", "0")],
                     np.int64)
    bw_dn = np.array([int(float(x or 0)) for x in col("bandwidthdown", "0")],
                     np.int64)
    vloss = np.array([float(x or 0) for x in col("packetloss", "0")],
                     np.float64)

    lat = np.full((v, v), INF_MS, np.float32)
    np.fill_diagonal(lat, 0.0)
    jit = np.zeros((v, v), np.float32)
    erel = np.ones((v, v), np.float32)
    self_lat = np.full((v,), np.nan, np.float32)
    self_rel = np.ones((v,), np.float32)
    self_jit = np.zeros((v,), np.float32)

    directed = graph.get("edgedefault", "undirected") == "directed"

    for edge in graph.findall(_NS + "edge"):
        s, t = index[edge.get("source")], index[edge.get("target")]
        d = data_of(edge)
        elat = float(d.get("latency", 0) or 0)
        eloss = float(d.get("packetloss", 0) or 0)
        ejit = float(d.get("jitter", 0) or 0)
        if s == t:
            self_lat[s] = elat
            self_rel[s] = (1.0 - eloss) * (1.0 - vloss[s])
            self_jit[s] = ejit
            continue
        # Vertex packet loss is folded into every edge *into* that vertex so
        # reliability composes associatively during the APSP relaxation.
        # Multi-edges keep the lowest-latency edge's full attribute set
        # (GraphML permits parallel edges; min-latency wins like Dijkstra
        # would pick it).
        if elat < lat[s, t]:
            lat[s, t] = elat
            erel[s, t] = (1.0 - eloss) * (1.0 - vloss[t])
            jit[s, t] = ejit
        if not directed and elat < lat[t, s]:
            lat[t, s] = elat
            erel[t, s] = (1.0 - eloss) * (1.0 - vloss[s])
            jit[t, s] = ejit

    return Topology(
        names=names, index=index,
        ip=col("ip", "0.0.0.0"),
        citycode=col("citycode", ""),
        countrycode=col("countrycode", ""),
        geocode=col("geocode", ""),
        typ=col("type", ""),
        asn=asn, bw_up_KiBps=bw_up, bw_down_KiBps=bw_dn, vertex_loss=vloss,
        lat_ms=lat, edge_rel=erel, jitter_ms=jit,
        self_lat_ms=self_lat, self_rel=self_rel, self_jitter_ms=self_jit,
    )


# ---------------------------------------------------------------------------
# Host attachment (the hint ladder)
# ---------------------------------------------------------------------------


def attach(topo: Topology, hints: dict, rng: np.random.Generator) -> int:
    """Pick the topology vertex for one host.

    Preference ladder like the reference's attach-hint matching
    (topology.c:2371-2430): exact iphint -> narrow candidates by
    citycode/countrycode/geocode/type hints in that order (a hint that
    matches nothing is skipped) -> uniform choice among survivors with the
    supplied (seeded, per-host) generator.
    """
    v = topo.num_vertices
    ip = hints.get("iphint")
    if ip:
        for i, vip in enumerate(topo.ip):
            if vip == ip:
                return i
    cand = list(range(v))
    for key, attr in (("citycodehint", topo.citycode),
                      ("countrycodehint", topo.countrycode),
                      ("geocodehint", topo.geocode),
                      ("typehint", topo.typ)):
        want = hints.get(key)
        if want:
            narrowed = [i for i in cand if attr[i] == want]
            if narrowed:
                cand = narrowed
    return int(cand[rng.integers(0, len(cand))])


def attach_all(topo: Topology, hint_list, seed: int) -> np.ndarray:
    """Deterministically attach every host; each host uses its own
    seeded stream so the assignment is independent of host order."""
    out = np.empty(len(hint_list), np.int32)
    for i, hints in enumerate(hint_list):
        out[i] = attach(topo, hints or {},
                        np.random.default_rng((seed, 0xA77AC4, i)))
    return out
