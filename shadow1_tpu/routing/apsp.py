"""All-pairs shortest paths on device: the routing precompute.

The reference runs igraph Dijkstra lazily per source vertex at simulation
time, guarded by a path cache and rwlocks
(/root/reference/src/main/routing/topology.c:1678-1875, cache at :24-79).
On TPU the better shape is the opposite: compute *all* pairs once at
startup with a Floyd-Warshall relaxation entirely on device, then serve
every per-packet lookup as a two-level gather from the resulting dense
[V,V] matrices.  No locks, no cache misses, no per-packet graph walks.

Weights are f32 milliseconds during relaxation (sub-microsecond resolution
at Internet scales); the final latency matrix is rounded to i64
nanoseconds so engine arithmetic stays exact and deterministic.

Reliability composes multiplicatively along the chosen (min-latency) path:
the relaxation carries it next to the latency and updates it wherever the
latency strictly improves -- the vectorized equivalent of the reference
accumulating edge/vertex packet-loss along the Dijkstra path
(topology.c:1407-1523).

Self-paths (two hosts attached to the same vertex) use twice the minimum
incident edge, like the reference's doubled min-incident-edge rule
(topology.c:1545-1643).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.simtime import SIMTIME_ONE_MILLISECOND, TIME_DTYPE

# Unreachable sentinel in ms; far above any real path but small enough that
# INF + INF stays finite in f32.
INF_MS = 1e12


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def floyd_warshall(lat_ms: jnp.ndarray, rel: jnp.ndarray,
                   jit_ms: jnp.ndarray):
    """Relax [V,V] f32 latency (ms) + reliability + jitter through every
    vertex.

    Plain k-loop FW: V iterations of O(V^2) vectorized relaxations; one
    compiled fori_loop, VPU-bound, run once at topology load.  Reliability
    multiplies and jitter sums along the min-latency path (the carried
    quantities update wherever the latency strictly improves).
    """

    def body(k, carry):
        lat, rel, jit = carry
        through = lat[:, k, None] + lat[None, k, :]
        rel_through = rel[:, k, None] * rel[None, k, :]
        jit_through = jit[:, k, None] + jit[None, k, :]
        better = through < lat
        return (jnp.where(better, through, lat),
                jnp.where(better, rel_through, rel),
                jnp.where(better, jit_through, jit))

    v = lat_ms.shape[0]
    return jax.lax.fori_loop(0, v, body, (lat_ms, rel, jit_ms))


def build_matrices(edge_lat_ms: jnp.ndarray, edge_rel: jnp.ndarray,
                   self_lat_ms=None, self_rel=None, edge_jitter_ms=None,
                   self_jitter_ms=None):
    """From directed-adjacency inputs to the final routing matrices.

    edge_lat_ms: [V,V] f32, INF_MS where no edge, 0 on the diagonal.
    edge_rel:    [V,V] f32 per-edge delivery probability (vertex loss
                 already folded into incoming edges by the loader).
    self_lat_ms: optional [V] f32 explicit self-loop latency (nan = absent);
                 vertices without one fall back to the doubled
                 min-incident-edge rule.
    self_rel:    optional [V] f32 reliability paired with self_lat_ms.
    edge_jitter_ms: optional [V,V] f32 per-edge jitter amplitude; per-packet
                 latency is perturbed uniformly within +/- the path sum.

    Returns (latency_ns i64 [V,V], reliability f32 [V,V],
             jitter_ns i64 [V,V]).
    """
    v = edge_lat_ms.shape[0]
    if edge_jitter_ms is None:
        edge_jitter_ms = jnp.zeros_like(edge_lat_ms)
    lat, rel, jit = floyd_warshall(edge_lat_ms, edge_rel, edge_jitter_ms)

    # Self-paths: explicit self-loop if the topology declares one, else out
    # to the nearest neighbor and back.
    eye = jnp.eye(v, dtype=bool)
    off_lat = jnp.where(eye, INF_MS, lat)
    nearest = jnp.argmin(off_lat, axis=1)
    rng_v = jnp.arange(v)
    d_lat = 2.0 * off_lat[rng_v, nearest]
    d_rel = rel[rng_v, nearest] ** 2
    d_jit = 2.0 * jit[rng_v, nearest]
    if self_lat_ms is not None:
        have = ~jnp.isnan(self_lat_ms)
        d_lat = jnp.where(have, self_lat_ms, d_lat)
        d_rel = jnp.where(have, jnp.ones_like(d_rel) if self_rel is None
                          else self_rel, d_rel)
        if self_jitter_ms is not None:
            d_jit = jnp.where(have, self_jitter_ms, d_jit)
    lat = jnp.where(eye, d_lat[:, None] * eye, lat)
    rel = jnp.where(eye, (d_rel[:, None] * eye) + (~eye), rel)
    jit = jnp.where(eye, d_jit[:, None] * eye, jit)

    lat_ns = jnp.round(lat * SIMTIME_ONE_MILLISECOND).astype(TIME_DTYPE)
    jit_ns = jnp.round(jit * SIMTIME_ONE_MILLISECOND).astype(TIME_DTYPE)
    # Jitter can never make a path non-causal: clamp to latency - 1ns.
    jit_ns = jnp.minimum(jit_ns, jnp.maximum(lat_ns - 1, 0))
    return lat_ns, rel.astype(jnp.float32), jit_ns


def is_routable(lat_ns: jnp.ndarray) -> jnp.ndarray:
    """[V,V] bool connectivity, the analog of topology_isRoutable
    (topology.c:2065-2092)."""
    return lat_ns < int(INF_MS) * SIMTIME_ONE_MILLISECOND // 2
