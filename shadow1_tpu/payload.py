"""Python binding for the native payload arena (native/payload_arena.cc).

Payload *bytes* live host-side in a C++ refcounted arena; packets on
device carry only a `payload_id`.  This mirrors the reference's split
between Packet metadata and the shared refcounted Payload
(/root/reference/src/main/routing/packet.c:97-100, payload.c) and is the
storage layer the real-code substrate will feed (app write() bytes in,
recv() bytes out).

The shared library builds on demand with g++ into
`native/build/` (cached by source mtime); ctypes binds the C ABI --
pybind11 is not part of this toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "payload_arena.cc")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_LIB = os.path.join(_BUILD_DIR, "libpayload_arena.so")


def _ensure_built() -> str:
    if (not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _LIB,
             _SRC],
            check=True, capture_output=True, text=True)
    return _LIB


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_ensure_built())
        lib.payload_arena_create.restype = ctypes.c_void_p
        lib.payload_arena_destroy.argtypes = [ctypes.c_void_p]
        lib.payload_arena_put.restype = ctypes.c_uint64
        lib.payload_arena_put.argtypes = [ctypes.c_void_p,
                                          ctypes.c_char_p, ctypes.c_uint64]
        lib.payload_arena_ref.restype = ctypes.c_int
        lib.payload_arena_ref.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.payload_arena_unref.restype = ctypes.c_int
        lib.payload_arena_unref.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.payload_arena_size.restype = ctypes.c_int64
        lib.payload_arena_size.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.payload_arena_get.restype = ctypes.c_int64
        lib.payload_arena_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                          ctypes.c_char_p, ctypes.c_uint64]
        lib.payload_arena_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        _lib = lib
    return _lib


class PayloadArena:
    """Refcounted byte storage; ids are stable u64 handles (never 0)."""

    def __init__(self):
        self._lib = _load()
        self._h = ctypes.c_void_p(self._lib.payload_arena_create())

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.payload_arena_destroy(self._h)
            self._h = None

    def put(self, data: bytes) -> int:
        pid = self._lib.payload_arena_put(self._h, data, len(data))
        if pid == 0:
            raise MemoryError("payload arena allocation failed")
        return pid

    def ref(self, pid: int) -> None:
        if self._lib.payload_arena_ref(self._h, pid) != 0:
            raise KeyError(f"invalid payload id {pid}")

    def unref(self, pid: int) -> None:
        if self._lib.payload_arena_unref(self._h, pid) != 0:
            raise KeyError(f"invalid payload id {pid}")

    def get(self, pid: int) -> bytes:
        size = self._lib.payload_arena_size(self._h, pid)
        if size < 0:
            raise KeyError(f"invalid payload id {pid}")
        buf = ctypes.create_string_buffer(max(size, 1))
        n = self._lib.payload_arena_get(self._h, pid, buf, size)
        if n < 0:  # freed between the size check and the copy
            raise KeyError(f"invalid payload id {pid}")
        return buf.raw[:n]

    def stats(self) -> dict:
        live = ctypes.c_uint64()
        live_bytes = ctypes.c_uint64()
        total = ctypes.c_uint64()
        self._lib.payload_arena_stats(self._h, ctypes.byref(live),
                                      ctypes.byref(live_bytes),
                                      ctypes.byref(total))
        return {"live": live.value, "live_bytes": live_bytes.value,
                "total_allocs": total.value}
