"""Continuous batching: concurrent server requests share one live
vmapped ensemble (docs/robustness.md "Continuous batching").

K submitted builder requests with the same shape hint (server.py
_shape_hint: builder name + shape-determining kwargs) describe worlds
in ONE shape bucket -- each would compile and launch the same graph.
Running them back-to-back serializes K device trains; running them as
K worker threads contends for the device.  This module instead packs
them onto the leading world axis of one ensemble (ensemble.stack) and
drives every lane to its OWN launch target per global launch
(ensemble.run_until_lanes), so K requests cost one launch train.

The train is CONTINUOUS: lanes join and leave while it runs.  A lane
that reaches its stop time (or parks, cancels, times out, or trips the
sentinel) is frozen at ensemble.FROZEN_NOW -- the quarantine parking
mechanics -- and its slot becomes claimable; newly queued compatible
requests are claimed into free slots at launch boundaries
(LaneTrain.claim_more) and start mid-train without a recompile (the
lane targets are traced, not static).

Bitwise identity with solo runs is the load-bearing contract: lane j
advances `min(tau_j + CHUNK_NS, next_sync(tau_j, stop_j, every_ns_j))`
per global launch -- exactly the launch-target sequence
engine.run_chunked walks for the same world solo on the same
checkpoint grid -- and window ends clip at launch targets, so every
lane's windows.jsonl, checkpoints, and summary are byte-identical to
the same request run alone (the tier-0 pin in tests/test_batch.py).
Lanes never wait for each other's sim time: a lane at t=3s and a lane
at t=9s ride the same compiled graph.

Failure handling differs from the solo path in ONE documented way:
batched lanes have no per-request Supervisor, so a sentinel violation
surrenders immediately (crash.json + rc 1 + lane freeze) instead of
walking the degradation ladder -- the other lanes keep running, which
is the same isolation the ensemble quarantine rung provides.  Host
exceptions fail the whole train (every unsettled lane settles rc 3),
matching a solo run's worker behavior.
"""

from __future__ import annotations

import glob
import json
import os
import time

from .core import engine
from .core.simtime import SIMTIME_ONE_SECOND
from .supervise import RC_INVARIANT, RC_OK

SEC = SIMTIME_ONE_SECOND


class Lane:
    """One request riding the train: its solo-built world, its drains
    and checkpointer, and its launch-grid bookkeeping.  `state` holds
    the solo pytree only until the lane is inserted onto the ensemble
    axis; after that the train's stacked state is the ground truth and
    per-lane slices are taken at boundaries (ensemble.world)."""

    def __init__(self, req, run_dir, control, emit, state, params, app,
                 stop_ns, every_ns, flight, ck, sentinel, resumed=None):
        self.req = req
        self.run_dir = run_dir
        self.control = control
        self.emit = emit
        self.state = state       # solo state, until inserted
        self.params = params     # solo params (original statics)
        self.app = app
        self.stop_ns = int(stop_ns)
        self.every_ns = int(every_ns)
        self.flight = flight
        self.ck = ck
        self.sentinel = sentinel
        self.resumed = resumed
        self.tau = int(state.now)
        self.boundary = None     # next_sync target, set per launch
        self.done = False
        self.rc = None
        self.summary = None
        self.settled = False     # server settled this lane's request

    def close(self):
        try:
            self.flight.close()
        except Exception:
            pass


def prepare(req, run_dir, control, emit, *, default_ck_s=2.0):
    """Build one request's lane exactly as sim._run_checkpointed would
    build the solo run: builder world, flight recorder + sentinel
    blocks, auto-resume from the newest readable checkpoint (trim +
    append windows.jsonl), ckpt/run.json recipe, and the win_0 anchor.
    The run.json recipe is identical to the solo server path's, so
    `shadow1-tpu replay` rebuilds batched-run checkpoints with the
    same template."""
    from . import replay as replay_mod
    from . import sim, trace

    spec = req.spec
    name = spec["name"]
    kwargs = dict(spec.get("kwargs") or {})
    ck_s = float(spec.get("checkpoint_every") or default_ck_s)
    every_ns = int(ck_s * SEC)
    state, params, app = getattr(sim, f"build_{name}")(**kwargs)
    hosts_real = int(state.hosts.num_hosts)
    stop_ns = int(params.stop_time)
    state = trace.ensure_flight_recorder(state, shards=1)
    state = trace.ensure_sentinel(state)
    os.makedirs(run_dir, exist_ok=True)

    resumed = None
    if glob.glob(os.path.join(run_dir, "ckpt", "win_*.npz")):
        try:
            path, man = replay_mod.find_checkpoint(run_dir, None)
        except FileNotFoundError:
            path = None          # all torn: start the run over
        if path is not None:
            from . import checkpoint as _ckpt
            from . import supervise as _sup_mod
            state, params = _ckpt.load(path, state, params)
            resumed = {"file": os.path.basename(path),
                       "window": int(man["window"]),
                       "t_ns": int(man["t_ns"])}
            _sup_mod.trim_windows(
                os.path.join(run_dir, "windows.jsonl"),
                resumed["window"])
            if emit is not None:
                emit({"event": "resumed", **resumed})

    flight = trace.FlightDrain(
        os.path.join(run_dir, "windows.jsonl"),
        start=resumed["window"] if resumed else 0,
        mode="a" if resumed else "w")
    ck = replay_mod.Checkpointer(run_dir, every_ns, devices=1,
                                 bucket=False, hosts_real=hosts_real)
    write_recipe = resumed is None
    if resumed is not None:
        try:
            replay_mod.load_run(run_dir)
            write_recipe = False
        except (FileNotFoundError, ValueError, json.JSONDecodeError):
            write_recipe = True
    if write_recipe:
        replay_mod.write_run_json(run_dir, {
            "world": {"kind": "builder", "name": name,
                      "kwargs": kwargs},
            "hb_ns": None, "every_ns": every_ns, "stop_ns": stop_ns,
            "chunk_ns": engine.CHUNK_NS, "devices": 1,
            "bucket": False, "hosts_real": hosts_real,
            "scope": None, "profile": False,
            "flight_rows": int(state.fr.steps.shape[0]),
            "lineage": None, "digest": None, "digest_rows": None,
            "sentinel": True, "supervise": True})
    if resumed is None:
        ck.save(state, params)   # win_0: a replay anchor always exists

    return Lane(req, run_dir, control, emit, state, params, app,
                stop_ns, every_ns, flight, ck,
                trace.SentinelDrain(), resumed=resumed)


def _insert(estate, eparams, j, lane):
    """Place a prepared lane's solo world at ensemble slot j.  The
    static `megakernel` flag is forced off to match the stacked
    params' pytree structure (ensemble.stack does the same); the
    lane's OWN params keep the original statics, and since params
    arrays never change on device, checkpoints saved from lane.params
    are byte-identical to the solo run's."""
    import jax
    st, pp = lane.state, lane.params.replace(megakernel=False)
    estate = jax.tree_util.tree_map(
        lambda e, x: e.at[j].set(x), estate, st)
    eparams = jax.tree_util.tree_map(
        lambda e, x: e.at[j].set(x), eparams, pp)
    return estate, eparams


class LaneTrain:
    """The shared launch train: a fixed-width ensemble (max_lanes
    slots) whose occupied lanes advance on their own solo launch grids
    through one compiled graph (ensemble.run_until_lanes -- one jit
    cache entry serves every co-batched request;
    ensemble.lanes_cache_size is the graph-count pin).

    `claim_more(n)` (optional) is called whenever slots are free --
    at start, at every boundary that retired a lane, and when the
    train would otherwise stop -- and returns up to n newly prepared
    Lanes to insert; the server wires it to its queue so compatible
    requests join mid-flight.  `on_retire(lane)` (optional) fires the
    moment a lane leaves the train (finished, parked, cancelled,
    timed out, or sentinel-tripped), with lane.rc / control.outcome
    already set -- the server settles the request there, so early
    finishers report without waiting for the train."""

    def __init__(self, max_lanes=4, claim_more=None, on_retire=None):
        self.max_lanes = max(1, int(max_lanes))
        self.claim_more = claim_more
        self.on_retire = on_retire
        self.lanes = []          # every lane ever aboard, join order

    def _retire(self, lane):
        lane.done = True
        lane.close()
        if self.on_retire is not None:
            self.on_retire(lane)

    def _boundary(self, lane, estate, eparams, j):
        """Per-lane launch-boundary work, identical in order to the
        solo loop: sentinel check, flight drain, checkpoint cadence,
        progress emit, control poll, stop-time finish.  Returns True
        when the lane retired (caller freezes slot j)."""
        import jax.numpy as jnp

        from . import ensemble, trace
        ls, _lp = ensemble.world(estate, eparams, j)
        prof = lane.req.profiler
        try:
            lane.sentinel.check(ls, prof)
        except trace.SentinelViolation as e:
            # No per-request Supervisor on the train: surrender this
            # lane immediately (evidence drain + crash.json + rc 1)
            # rather than walking the ladder; the other lanes keep
            # running -- quarantine-style isolation.
            try:
                lane.flight.drain(ls, prof)
            except Exception:
                pass             # evidence must not mask the failure
            self._surrender(lane, e)
            self._retire(lane)
            return True
        lane.flight.drain(ls, prof)
        lane.ck.maybe(ls, lane.params, lane.tau)
        if lane.emit is not None:
            lane.emit({"event": "progress", "t_ns": int(lane.tau),
                       "stop_ns": int(lane.stop_ns),
                       "line": f"[shadow1-tpu] "
                               f"{lane.tau / SEC:g}"
                               f"/{lane.stop_ns / SEC:g}s\n"})
        act = lane.control.poll() if lane.control is not None else None
        if act is not None:
            if act == "park":
                lane.ck.save(ls, lane.params)
                lane.control.outcome = "parked"
                if lane.emit is not None:
                    lane.emit({"event": "parked", "t_ns": int(lane.tau),
                               "window": int(ls.n_windows)})
            else:
                lane.control.outcome = ("cancelled" if act == "cancel"
                                        else "timed_out")
            lane.rc = RC_OK      # the server maps the outcome, not rc
            self._retire(lane)
            return True
        if lane.tau >= lane.stop_ns:
            lane.summary = {
                "simulated_seconds": int(ls.now) / SEC,
                "windows": int(ls.n_windows),
                "packets_sent": int(jnp.sum(ls.hosts.pkts_sent)),
                "err_flags": int(ls.err)}
            if lane.emit is not None:
                lane.emit({"event": "summary", "summary": lane.summary})
            lane.rc = RC_OK if int(ls.err) == 0 else RC_INVARIANT
            self._retire(lane)
            return True
        return False

    def _surrender(self, lane, exc):
        """crash.json for a sentinel-tripped lane: same failure schema
        as the Supervisor's surrender (failure class + sentinel row +
        replay hint), with `ladder: []` recording that no rungs exist
        on a batched lane."""
        row = exc.row
        crash = {
            "failure": {"class": "sentinel",
                        "type": type(exc).__name__,
                        "message": str(exc),
                        "note": "batched lane: no degradation ladder; "
                                "resubmit solo to walk the rungs"},
            "window": int(row.get("first_bad_window", -1)),
            "t_ns": int(row.get("first_bad_t", -1)),
            "sentinel": row,
            "checkpoint": None,
            "ladder": [],
        }
        try:
            from . import replay as replay_mod
            path, man = replay_mod.find_checkpoint(lane.run_dir, None)
            crash["checkpoint"] = {
                "file": os.path.basename(path),
                "window": None if man is None else int(man["window"]),
                "t_ns": None if man is None else int(man["t_ns"])}
        except Exception:
            pass
        if crash["window"] >= 0:
            crash["replay"] = (f"shadow1-tpu replay --data-directory "
                               f"{lane.run_dir} --window "
                               f"{crash['window']}")
        out = os.path.join(lane.run_dir, "crash.json")
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(crash, f, indent=1, sort_keys=True, default=str)
        os.replace(tmp, out)
        lane.req.error = str(exc)
        lane.rc = RC_INVARIANT
        if lane.emit is not None:
            lane.emit({"event": "crash", "path": out, "crash": crash})

    def run(self, lanes):
        """Drive the train until every lane has retired and claim_more
        yields nothing.  `lanes` are the initially co-picked requests
        (1..max_lanes, already prepared)."""
        import jax

        from . import ensemble, replay as replay_mod

        w = self.max_lanes
        assert lanes and len(lanes) <= w
        self.lanes = list(lanes)
        # Pad empty slots with copies of lane 0's world; they start
        # frozen and no window bodies ever run in them (the engine
        # predicate is false at FROZEN_NOW), so they are pure shape
        # ballast until a joiner claims the slot.
        slots = list(lanes) + [None] * (w - len(lanes))
        estate, eparams, app = ensemble.stack(
            [(ln.state, ln.params, ln.app) for ln in lanes]
            + [(lanes[0].state, lanes[0].params, lanes[0].app)]
            * (w - len(lanes)))
        if w > len(lanes):
            estate = ensemble.freeze_worlds(
                estate, list(range(len(lanes), w)))
        for ln in lanes:
            ln.state = None      # the ensemble axis owns it now

        def _claim(freeable):
            nonlocal estate, eparams
            if self.claim_more is None or not freeable:
                return False
            joined = self.claim_more(len(freeable)) or []
            for ln in joined:
                j = freeable.pop(0)
                estate, eparams = _insert(estate, eparams, j, ln)
                slots[j] = ln
                ln.state = None
                self.lanes.append(ln)
            return bool(joined)

        _claim([j for j, ln in enumerate(slots)
                if ln is None or ln.done])
        while True:
            active = [j for j, ln in enumerate(slots)
                      if ln is not None and not ln.done]
            if not active:
                if not _claim([j for j, ln in enumerate(slots)
                               if ln is None or ln.done]):
                    return
                continue
            targets = []
            for j, ln in enumerate(slots):
                if ln is None or ln.done:
                    # Frozen lanes re-park themselves: the engine tail
                    # rewrite now=t_target keeps now at FROZEN_NOW.
                    targets.append(ensemble.FROZEN_NOW)
                    continue
                ln.boundary = replay_mod.next_sync(
                    ln.tau, ln.stop_ns, every_ns=ln.every_ns)
                targets.append(min(ln.tau + engine.CHUNK_NS,
                                   ln.boundary))
            t0 = time.perf_counter()
            estate = ensemble.run_until_lanes(estate, eparams, app,
                                              targets)
            jax.block_until_ready(estate)
            t1 = time.perf_counter()
            froze = []
            for j in active:
                ln = slots[j]
                ln.tau = int(targets[j])
                if ln.req.profiler is not None:
                    ln.req.profiler.add_span("device_window", t0, t1,
                                             t_ns=ln.tau, lane=j)
                if ln.tau < ln.boundary:
                    continue     # mid-grid chunk, no boundary work
                if self._boundary(ln, estate, eparams, j):
                    froze.append(j)
            if froze:
                estate = ensemble.freeze_worlds(estate, froze)
                _claim(froze)

    def abort(self, error):
        """A host exception killed the train: close and fail every
        lane that has not already settled (the server maps these to
        rc 3, exactly as a solo worker crash would)."""
        for ln in self.lanes:
            if not ln.done:
                ln.done = True
                ln.close()
                if ln.req.error is None:
                    ln.req.error = error
