"""Client half of the run server: `shadow1-tpu
submit/status/stats/cancel`.

Thin and synchronous: each command opens one connection to the serve
socket (protocol.py), sends one request, and -- for `submit --wait` /
`status --wait` -- relays the streamed progress/summary events until
the terminal `done`, exiting with the RUN'S rc.  The unified exit-code
table (supervise.py) therefore holds across the service boundary: the
rc a scenario would exit the batch CLI with is the rc the submitting
client exits with, and every refusal (queue full, bad spec, timeout,
draining server) is rc 2 with the responsible knob named in the
message.
"""

from __future__ import annotations

import json
import sys

from . import protocol
from .supervise import RC_FAILED, RC_OK, RC_USAGE


def _socket_path(args) -> str | None:
    """Resolve the serve socket from --socket / --server; None (after
    printing the usage error) when neither is given."""
    if getattr(args, "socket", None):
        return args.socket
    if getattr(args, "server", None):
        return protocol.default_socket(args.server)
    print("error: pass --server DIR (the serve --data-directory) or "
          "--socket PATH to locate the run server", file=sys.stderr)
    return None


def _build_submit(args):
    """(kind, spec) from the submit flags, or (None, error-message).
    Exactly one of CONFIG / --world / --replay selects the request
    kind; the spec is what the server's worker needs to reconstruct
    the run on its side."""
    from .cli import world_args
    modes = [bool(args.config), bool(args.world), bool(args.replay)]
    if sum(modes) != 1:
        return None, ("pass exactly one of CONFIG (a shadow.config.xml "
                      "path), --world NAME, or --replay RUN")
    if args.config:
        spec = world_args(args)
        for k in ("heartbeat_frequency", "quiet", "watchdog",
                  "worlds", "sweep"):
            spec[k] = getattr(args, k, None)
        spec["progress"] = bool(args.progress)
        return ("config", spec), None
    if args.world:
        try:
            kwargs = json.loads(args.world_kwargs) \
                if args.world_kwargs else {}
        except json.JSONDecodeError as e:
            return None, f"--world-kwargs is not valid JSON: {e}"
        if not isinstance(kwargs, dict):
            return None, "--world-kwargs must be a JSON object"
        spec = {"name": args.world, "kwargs": kwargs,
                "checkpoint_every": args.checkpoint_every,
                "watchdog": args.watchdog,
                "devices": args.devices if args.devices > 1 else None,
                "bucket": bool(args.bucket), "scope": args.scope,
                "trace_packets": args.trace_packets,
                "digest_every": args.digest_every}
        return ("builder", spec), None
    spec = {"run": args.replay, "window": args.window}
    return ("replay", spec), None


def _stream_until_done(path, msg, quiet=False) -> int:
    """Drive a streamed request to its terminal event; returns the
    run's rc.  A connection that dies mid-stream is rc 3 -- the run
    itself is still journaled server-side (`status` finds it)."""
    rid = None
    try:
        for ev in protocol.stream(path, msg):
            if "event" not in ev:  # the acknowledgement
                if not ev.get("ok"):
                    print(f"error: {ev.get('error')}", file=sys.stderr)
                    return int(ev.get("rc", RC_USAGE))
                rid = ev.get("id")
                if rid and not quiet:
                    print(f"[shadow1-tpu] submitted {rid}",
                          file=sys.stderr)
                continue
            e = ev.get("event")
            if e == "progress":
                line = ev.get("line")
                if line and not quiet:
                    sys.stderr.write(line)
                    sys.stderr.flush()
            elif e == "state" and not quiet:
                print(f"[shadow1-tpu] {ev.get('id')}: "
                      f"{ev.get('state')}", file=sys.stderr)
            elif e == "parked":
                print(f"error: run {ev.get('id') or rid} was "
                      f"checkpointed and parked by a server drain; "
                      f"restart the server with `serve --auto-resume` "
                      f"to finish it", file=sys.stderr)
                return RC_FAILED
            elif e == "done":
                if ev.get("error"):
                    print(f"error: {ev['error']}", file=sys.stderr)
                if ev.get("crash"):
                    print(f"crash report: "
                          f"{(ev['crash'] or {}).get('path')}",
                          file=sys.stderr)
                    print(json.dumps({"crash": ev["crash"]}))
                if ev.get("summary") is not None:
                    print(json.dumps(ev["summary"]))
                return int(ev.get("rc", RC_FAILED))
    except protocol.ServerUnavailable as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_USAGE
    except (ConnectionError, OSError) as e:
        print(f"error: lost the run server connection: {e}",
              file=sys.stderr)
        return RC_FAILED
    print(f"error: the run server closed the connection before "
          f"{rid or 'the request'} finished (server killed?  a "
          f"restarted `serve --auto-resume` re-admits it; check "
          f"`shadow1-tpu status`)", file=sys.stderr)
    return RC_FAILED


def submit_cmd(args) -> int:
    path = _socket_path(args)
    if path is None:
        return RC_USAGE
    built, err = _build_submit(args)
    if err is not None:
        print(f"error: {err}", file=sys.stderr)
        return RC_USAGE
    kind, spec = built
    msg = {"op": "submit", "kind": kind, "spec": spec,
           "timeout": args.timeout, "wait": not args.no_wait,
           "progress": bool(args.progress)}
    if args.no_wait:
        try:
            resp = protocol.request(path, msg)
        except protocol.ServerUnavailable as e:
            print(f"error: {e}", file=sys.stderr)
            return RC_USAGE
        except (ConnectionError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return RC_FAILED
        if not resp.get("ok"):
            print(f"error: {resp.get('error')}", file=sys.stderr)
            return int(resp.get("rc", RC_USAGE))
        print(json.dumps({"id": resp["id"]}))
        return RC_OK
    return _stream_until_done(path, msg, quiet=args.quiet)


def status_cmd(args) -> int:
    path = _socket_path(args)
    if path is None:
        return RC_USAGE
    msg = {"op": "status", "id": args.id, "wait": bool(args.wait)}
    try:
        if args.id and args.wait:
            rc = _wait_status(path, msg)
            return rc
        resp = protocol.request(path, msg)
    except protocol.ServerUnavailable as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_USAGE
    except (ConnectionError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_FAILED
    if not resp.get("ok"):
        print(f"error: {resp.get('error')}", file=sys.stderr)
        return int(resp.get("rc", RC_USAGE))
    print(json.dumps(resp.get("run") or
                     {"server": resp.get("server"),
                      "runs": resp.get("runs")},
                     indent=1, sort_keys=True))
    return RC_OK


def _wait_status(path, msg) -> int:
    """`status ID --wait`: block until the run settles, print its final
    record, exit with its rc (rc 3 for a drain-park)."""
    rc = None
    for ev in protocol.stream(path, msg):
        if "event" not in ev:
            if not ev.get("ok"):
                print(f"error: {ev.get('error')}", file=sys.stderr)
                return int(ev.get("rc", RC_USAGE))
            continue
        if ev.get("event") == "done":
            rc = int(ev.get("rc", RC_FAILED))
            break
        if ev.get("event") == "parked":
            print(f"run {msg['id']} is parked (server drain); restart "
                  f"the server with `serve --auto-resume` to finish "
                  f"it", file=sys.stderr)
            rc = RC_FAILED
            break
    if rc is None:
        print("error: the run server closed the connection before the "
              "run settled", file=sys.stderr)
        return RC_FAILED
    try:
        final = protocol.request(path, {"op": "status", "id": msg["id"]})
        if final.get("ok"):
            print(json.dumps(final.get("run"), indent=1, sort_keys=True))
    except (ConnectionError, OSError):
        pass  # server exited right after the drain-park event
    print(f"[shadow1-tpu] {msg['id']}: exit rc {rc}", file=sys.stderr)
    return rc


def _render_stats(st: dict) -> str:
    """One-screen fleet view (`top` for simulations) from a stats
    snapshot: queue, workers, affinity, journal, recent completions."""
    lines = []
    q = st.get("queue") or {}
    rq = st.get("requests") or {}
    af = st.get("affinity") or {}
    jn = st.get("journal") or {}
    rec = st.get("recovery") or {}
    lines.append(
        f"shadow1-tpu server pid {st.get('pid')}  "
        f"up {st.get('uptime_s', 0):.0f}s  "
        f"{'DRAINING' if st.get('draining') else 'serving'}  "
        f"warm buckets {(st.get('warm') or {}).get('buckets', 0)}")
    states = st.get("states") or {}
    parts = " ".join(f"{k}={v}" for k, v in sorted(states.items()))
    lines.append(f"requests: {rq.get('submitted', 0)} submitted"
                 + (f" | {parts}" if parts else ""))
    hr = af.get("hit_rate")
    lines.append(
        f"queue: {q.get('depth', 0)}/{q.get('limit', '?')} "
        f"(high-water {q.get('high_water', 0)})  affinity "
        f"{af.get('hits', 0)} hit / {af.get('misses', 0)} miss"
        + (f" ({100 * hr:.0f}%)" if hr is not None else ""))
    for w in q.get("queued") or []:
        lines.append(f"  q[{w['position']}] {w['id']} "
                     f"waiting {w['queue_wait_s']:.1f}s")
    for w in st.get("workers") or []:
        cur = w.get("current")
        busy = f"running {cur} for {w.get('busy_for_s'):.1f}s" \
            if cur else "idle"
        lines.append(f"worker {w['id']}: {busy}  "
                     f"(lifetime busy {w.get('busy_s', 0):.1f}s, "
                     f"{w.get('runs', 0)} run(s))")
    fm = jn.get("fsync_ms_mean")
    lines.append(
        f"journal: {jn.get('events', 0)} event(s), "
        f"{jn.get('fsyncs', 0)} fsync(s)"
        + (f" ({fm:.2f} ms mean)" if fm is not None else "")
        + f"  recovery: {rec.get('readmitted', 0)} readmitted, "
          f"{rec.get('parked', 0)} parked, {rec.get('resumes', 0)} "
          f"resume(s), {rec.get('recoveries', 0)} ladder rung(s)")
    recent = st.get("recent") or []
    if recent:
        lines.append("recent:")
        for r in recent[-8:]:
            wall = r.get("wall_s")
            lines.append(
                f"  {r['id']} {r.get('kind')}: {r.get('state')} "
                f"rc {r.get('rc')}  wall "
                + (f"{wall:.1f}s" if wall is not None else "-")
                + f"  queued {r.get('queue_wait_s', 0):.1f}s  "
                + ("hit" if r.get("affinity_hit") else "miss"))
    return "\n".join(lines)


def stats_cmd(args) -> int:
    """`shadow1-tpu stats [--watch N] [--json]`: fleet snapshot(s) from
    a live server's `stats` op."""
    import time as time_mod
    path = _socket_path(args)
    if path is None:
        return RC_USAGE
    while True:
        try:
            resp = protocol.request(path, {"op": "stats"})
        except protocol.ServerUnavailable as e:
            print(f"error: {e}", file=sys.stderr)
            return RC_USAGE
        except (ConnectionError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return RC_FAILED
        if not resp.get("ok"):
            print(f"error: {resp.get('error')}", file=sys.stderr)
            return int(resp.get("rc", RC_USAGE))
        st = resp.get("stats") or {}
        if args.json:
            print(json.dumps(st, indent=1, sort_keys=True))
        else:
            if args.watch:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(_render_stats(st))
            sys.stdout.flush()
        if not args.watch:
            return RC_OK
        try:
            time_mod.sleep(args.watch)
        except KeyboardInterrupt:
            return RC_OK


def cancel_cmd(args) -> int:
    path = _socket_path(args)
    if path is None:
        return RC_USAGE
    try:
        resp = protocol.request(path, {"op": "cancel", "id": args.id})
    except protocol.ServerUnavailable as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_USAGE
    except (ConnectionError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_FAILED
    if not resp.get("ok"):
        print(f"error: {resp.get('error')}", file=sys.stderr)
        return int(resp.get("rc", RC_USAGE))
    print(json.dumps(resp))
    return RC_OK
