"""Runtime tracing & metrics: where wall-time goes inside a run.

`observe.py` watches the *simulated* world (heartbeats, pcap, drops);
this module watches the *simulator*.  A `Profiler` records host-side
phase spans (device launches, tracker/log drains, substrate syncs,
bridge RPCs), device->host transfer volume, and JIT compile events (via
JAX's monitoring hook), while a device-side `TraceCounters` block
(core/state.py) accumulates per-window aggregates -- packets exchanged,
peak inbox-slab occupancy -- inside the compiled step so they cost one
extra scalar fetch per drain, not per window.

Three artifacts per profiled run:

* ``trace.json`` -- Chrome trace-event format; open in chrome://tracing
  or https://ui.perfetto.dev.  Phase spans are duration events; device
  counter snapshots are counter tracks.
* ``metrics.json`` -- aggregates: per-phase count/total/p50/p95/max,
  transfer bytes, compile count, device counters.
* a one-screen summary table (``Profiler.summary_table()``).

The module-level `install()/current()` pair keeps hook sites cheap:
engine/observe/bridge call ``trace.current().span(...)``, which is a
no-op singleton unless a run installed a real Profiler.  Hot compiled
code never consults the profiler -- device-side counting is opted into
by putting a TraceCounters block on the state (``ensure_counters``),
the same present-or-None pattern as the capture and log rings.
"""

from __future__ import annotations

import json
import time

# JAX's backend-compile duration event (jax._src.dispatch
# BACKEND_COMPILE_EVENT): fires once per XLA compile, i.e. on every
# compile-cache miss.  Resolved lazily so a rename in a future JAX only
# degrades compile attribution, never breaks the profiler.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


# ---------------------------------------------------------------------------
# Null profiler: the installed-by-default no-op
# ---------------------------------------------------------------------------


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullProfiler:
    """Inactive profiler: every hook is a constant-time no-op."""

    enabled = False
    sync = False

    def span(self, name, **args):
        return _NULL_SPAN

    def add_span(self, name, t0_abs, t1_abs, **args):
        pass

    def transfer(self, nbytes, count=1):
        pass

    def compile_event(self, dur_s):
        pass

    def counter_sample(self, values):
        pass


_NULL = NullProfiler()
_active = _NULL
_hook_installed = False


def current():
    """The active profiler (a NullProfiler unless a run installed one)."""
    return _active


def install(prof):
    """Install `prof` as the process-wide active profiler (None/falsy
    restores the no-op).  Returns the now-active profiler."""
    global _active
    _active = prof if prof else _NULL
    if _active.enabled:
        _ensure_compile_hook()
    return _active


def _ensure_compile_hook():
    """Register ONE process-global JAX event listener that forwards
    backend-compile durations to whatever profiler is active.  JAX has no
    per-listener unregister, so the listener is permanent and dispatches
    through `current()`."""
    global _hook_installed
    if _hook_installed:
        return
    try:
        from jax._src import monitoring

        def _on_event(event, dur_s, **kw):
            p = _active
            if p.enabled and event == _COMPILE_EVENT:
                p.compile_event(dur_s)

        monitoring.register_event_duration_secs_listener(_on_event)
        _hook_installed = True
    except Exception:  # noqa: BLE001 - compile attribution is best-effort
        pass


# ---------------------------------------------------------------------------
# The real profiler
# ---------------------------------------------------------------------------


class _Span:
    __slots__ = ("prof", "name", "args", "t0")

    def __init__(self, prof, name, args):
        self.prof = prof
        self.name = name
        self.args = args
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        p = self.prof
        t0 = self.t0
        p.events.append((self.name, t0 - p.t0,
                         time.perf_counter() - t0, self.args))
        return False


class Profiler:
    """Host-side run profiler.

    sync=True makes the engine's chunk loop block_until_ready after each
    device launch so `device_step` spans measure execution rather than
    async dispatch (full --profile mode).  sync=False records spans
    without extra synchronization -- the cheap mode bench.py uses.

    counters=False keeps the state pytree untouched: the run loops skip
    `ensure_counters`, so the profiler records host-side spans and
    compile events only.  The run server's per-request accounting uses
    this mode -- a served run must stay byte-identical to an unobserved
    one (zero kernelcount delta); events/s still lands via
    `fetch_counters`, which reads the always-present n_events scalar.
    """

    enabled = True

    def __init__(self, sync: bool = True, counters: bool = True):
        self.sync = sync
        self.counters = counters
        self.t0 = time.perf_counter()
        self.events = []        # (name, t_rel_s, dur_s, args|None)
        self.transfer_bytes = 0
        self.transfer_count = 0
        self.compiles = []      # (t_rel_s, dur_s)
        self.counter_samples = []   # (t_rel_s, {name: value})
        self.kernelcount = None     # tools/kernelcount.py report|None
        self.extra_metrics = {}     # {name: number} via set_metric
        self.flight_rows = []       # drained FlightRecorder rows
        self.flight_summary = None  # aggregate `mesh` section|None
        self.scope_flow_rows = []   # drained FlowScope flow rows
        self.scope_link_rows = []   # drained FlowScope link rows
        self.scope_summary = None   # aggregate `net` section|None
        self.lineage_rows = []      # drained LineageDrain span rows
        self.lineage_summary = None  # aggregate `lineage` section|None
        self.digest_summary = None  # aggregate `digest` section|None

    # -- recording hooks ----------------------------------------------------

    def span(self, name, **args):
        """Context manager timing one phase occurrence."""
        return _Span(self, name, args or None)

    def add_span(self, name, t0_abs, t1_abs, **args):
        """Record one phase occurrence from absolute perf_counter()
        endpoints.  The window pipeline (sim.WindowPipeline) and the
        supervisor use this to record a `device_window` span from
        dispatch time to the block_until_ready at the drain point --
        the span is only known after the fact, so a context manager
        cannot time it."""
        self.events.append((name, t0_abs - self.t0,
                            max(0.0, t1_abs - t0_abs), args or None))

    def transfer(self, nbytes, count=1):
        """Account a device->host transfer of `nbytes` over `count`
        fetch round trips."""
        self.transfer_bytes += int(nbytes)
        self.transfer_count += int(count)

    def compile_event(self, dur_s):
        self.compiles.append((time.perf_counter() - self.t0 - dur_s,
                              float(dur_s)))

    def counter_sample(self, values: dict):
        """Record a snapshot of (already-fetched) device counters."""
        self.counter_samples.append((time.perf_counter() - self.t0,
                                     dict(values)))

    def set_kernelcount(self, report: dict | None):
        """Attach a tools/kernelcount.py report: compiled HLO op/fusion
        counts per engine phase.  Rides metrics()/metrics.json so every
        profiled artifact carries the compiled-graph size alongside the
        wall times (benchdiff gates on it with --kernels)."""
        self.kernelcount = report

    def set_flight(self, rows: list, summary: dict | None):
        """Attach drained flight-recorder rows (FlightDrain.rows) + their
        aggregate.  The aggregate becomes the `mesh` section of
        metrics(); the rows become a simulated-time track (pid 2) in
        trace_events(), so the Chrome trace shows wall time and sim time
        side by side."""
        self.flight_rows = list(rows)
        self.flight_summary = summary

    def set_scope(self, flow_rows: list, link_rows: list,
                  summary: dict | None):
        """Attach drained flowscope rows (ScopeDrain.flow_rows /
        .link_rows) + their aggregate.  The aggregate becomes the `net`
        section of metrics(); the rows become per-sample counter tracks
        on the simulated-time process (pid 2) in trace_events()."""
        self.scope_flow_rows = list(flow_rows)
        self.scope_link_rows = list(link_rows)
        self.scope_summary = summary

    def set_lineage(self, rows: list, summary: dict | None):
        """Attach drained packet-lineage spans (LineageDrain.rows) +
        their aggregate.  The aggregate becomes the `lineage` section of
        metrics(); the rows become a per-packet waterfall track (pid 3)
        in trace_events() -- each traced packet renders as one span from
        its first hop to its last, alongside wall time (pid 1) and sim
        time (pid 2)."""
        self.lineage_rows = list(rows)
        self.lineage_summary = summary

    def set_digest(self, summary: dict | None):
        """Attach the statescope digest aggregate (DigestDrain.summary):
        row/wrap counts and the cadence.  Becomes the `digest` section
        of metrics() -- machine-bound for benchdiff (reported, never
        gated)."""
        self.digest_summary = summary

    def set_metric(self, name: str, value):
        """Attach one named scalar metric (e.g. a measured phase cost
        like stage_emissions_ms) so it rides metrics()/metrics.json and
        tools/benchdiff.py can gate on it across rounds.  None values
        are dropped (a failed measurement must not poison the JSON)."""
        if value is not None:
            self.extra_metrics[name] = value

    # -- aggregation --------------------------------------------------------

    def metrics(self) -> dict:
        """Aggregate recorded data: per-phase percentiles + totals."""
        by_phase = {}
        for name, _t, dur, _a in self.events:
            by_phase.setdefault(name, []).append(dur)
        phases = {}
        for name, durs in sorted(by_phase.items()):
            durs = sorted(durs)
            phases[name] = {
                "count": len(durs),
                "total_s": round(sum(durs), 6),
                "p50_ms": round(_pct(durs, 50) * 1e3, 3),
                "p95_ms": round(_pct(durs, 95) * 1e3, 3),
                "max_ms": round(durs[-1] * 1e3, 3),
            }
        out = {
            "wall_s": round(time.perf_counter() - self.t0, 3),
            "phases": phases,
            "transfers": {"bytes": self.transfer_bytes,
                          "count": self.transfer_count},
            "compile": {"count": len(self.compiles),
                        "total_s": round(sum(d for _t, d in self.compiles),
                                         3)},
            # Flat aliases for benchdiff gating (tools/benchdiff.py):
            # "compiles" is a graph property (0-tolerance -- a new
            # compile in a sweep means a shape bucket broke), while
            # "compile_ms" is machine-bound wall time.
            "compiles": len(self.compiles),
            "compile_ms": round(
                sum(d for _t, d in self.compiles) * 1e3, 1),
        }
        dev = [(t, t + d) for n, t, d, _a in self.events
               if n in ("device_step", "device_window")]
        if dev:
            # The async-window-pipeline judgment metric: how much of
            # the host-drain wall is hidden under device execution.
            # Sync-mode loops sit near 0% by construction (drains run
            # after block_until_ready, outside every device_step span);
            # the pipeline drives it toward 100% by draining window N
            # while window N+1 executes.  The denominator is the DRAIN
            # wall, not the device wall: a correct pipeline hides all
            # of the (small) drain work inside the (large) device work,
            # and the metric should read ~100% then, however cheap the
            # drains are relative to the launches.
            drains = [(t, t + d) for n, t, d, _a in self.events
                      if n in _HOST_DRAIN_PHASES]
            drain_total = sum(b - a for a, b in _union(drains))
            if drain_total > 0:
                out["host_drain_overlap_pct"] = round(
                    100.0 * _overlap(dev, drains) / drain_total, 2)
            else:
                out["host_drain_overlap_pct"] = 0.0
        if self.counter_samples:
            out["device_counters"] = self.counter_samples[-1][1]
        if self.kernelcount is not None:
            out["kernelcount"] = self.kernelcount
        if self.flight_summary is not None:
            out["mesh"] = self.flight_summary
        if self.scope_summary is not None:
            out["net"] = self.scope_summary
        if self.lineage_summary is not None:
            out["lineage"] = self.lineage_summary
        if self.digest_summary is not None:
            out["digest"] = self.digest_summary
        out.update(self.extra_metrics)
        return out

    # -- artifacts ----------------------------------------------------------

    def trace_events(self) -> list:
        """The run as Chrome trace-event dicts (ts/dur in microseconds)."""
        tids = {}

        def tid(name):
            return tids.setdefault(name, len(tids) + 1)

        evs = []
        for name, t, dur, args in sorted(self.events, key=lambda e: e[1]):
            e = {"name": name, "cat": "run", "ph": "X", "pid": 1,
                 "tid": tid(name), "ts": round(t * 1e6, 3),
                 "dur": round(dur * 1e6, 3)}
            if args:
                e["args"] = args
            evs.append(e)
        for t, dur in self.compiles:
            evs.append({"name": "jit_compile", "cat": "jit", "ph": "X",
                        "pid": 1, "tid": tid("jit_compile"),
                        "ts": round(t * 1e6, 3),
                        "dur": round(dur * 1e6, 3)})
        for t, values in self.counter_samples:
            for k, v in values.items():
                evs.append({"name": k, "cat": "counters", "ph": "C",
                            "pid": 1, "ts": round(t * 1e6, 3),
                            "args": {k: v}})
        meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": i,
                 "args": {"name": n}} for n, i in tids.items()]
        if self.flight_rows or self.scope_flow_rows or self.scope_link_rows:
            # Simulated-time track: pid 2's clock is SIM nanoseconds
            # (rendered as trace microseconds), one span per window plus
            # events/routed counter tracks -- wall time (pid 1) and sim
            # time (pid 2) side by side in the same viewer.
            meta.append({"name": "process_name", "ph": "M", "pid": 2,
                         "args": {"name": "simulated time (windows)"}})
            meta.append({"name": "thread_name", "ph": "M", "pid": 2,
                         "tid": 1, "args": {"name": "window"}})
        if self.flight_rows:
            for r in self.flight_rows:
                ts = round(r["t_start"] / 1e3, 3)
                dur = round(max(r["t_end"] - r["t_start"], 1) / 1e3, 3)
                evs.append({"name": "window", "cat": "sim", "ph": "X",
                            "pid": 2, "tid": 1, "ts": ts, "dur": dur,
                            "args": {k: r[k] for k in
                                     ("window", "steps", "events",
                                      "routed", "delivered", "dropped",
                                      "killed")}})
                for k in ("events", "routed"):
                    evs.append({"name": k, "cat": "sim", "ph": "C",
                                "pid": 2, "ts": ts, "args": {k: r[k]}})
        if self.scope_flow_rows:
            # Flowscope counter tracks on the sim-time clock: per-sample
            # aggregate congestion window + worst smoothed RTT.
            agg = {}
            for r in self.scope_flow_rows:
                a = agg.setdefault(r["t"], [0, 0])
                a[0] += r["cwnd"]
                a[1] = max(a[1], r["srtt_ns"])
            for t in sorted(agg):
                ts = round(t / 1e3, 3)
                evs.append({"name": "cwnd_total", "cat": "net", "ph": "C",
                            "pid": 2, "ts": ts,
                            "args": {"cwnd_total": agg[t][0]}})
                evs.append({"name": "srtt_max_us", "cat": "net", "ph": "C",
                            "pid": 2, "ts": ts,
                            "args": {"srtt_max_us":
                                     round(agg[t][1] / 1e3, 1)}})
        if self.scope_link_rows:
            agg = {}
            for r in self.scope_link_rows:
                a = agg.setdefault(r["t"], [0, 0])
                a[0] += r["qdepth"]
                a[1] += r["drops"]
            for t in sorted(agg):
                ts = round(t / 1e3, 3)
                evs.append({"name": "link_qdepth", "cat": "net", "ph": "C",
                            "pid": 2, "ts": ts,
                            "args": {"link_qdepth": agg[t][0]}})
                evs.append({"name": "link_drops", "cat": "net", "ph": "C",
                            "pid": 2, "ts": ts,
                            "args": {"link_drops": agg[t][1]}})
        if self.lineage_rows:
            # Packet-lineage waterfall on the sim-time clock (pid 3):
            # one span per traced packet from its first hop to its last,
            # the hop chain + death reason in args.  Bounded to the
            # first _LINEAGE_TRACK_IDS packets by first-hop time so a
            # high-rate trace cannot bloat trace.json.
            meta.append({"name": "process_name", "ph": "M", "pid": 3,
                         "args": {"name": "packet lineage (spans)"}})
            by_id = {}
            for r in self.lineage_rows:
                by_id.setdefault(r["id"], []).append(r)
            order = sorted(by_id, key=lambda i: by_id[i][0]["t"])
            if len(order) > _LINEAGE_TRACK_IDS:
                order = order[:_LINEAGE_TRACK_IDS]
            for n, pid_ in enumerate(order):
                hops = by_id[pid_]
                t0, t1 = hops[0]["t"], hops[-1]["t"]
                reason = next((h["reason"] for h in hops
                               if h["reason"] != "none"), "none")
                row_tid = (n % 64) + 1
                evs.append({"name": f"pkt {pid_:08x}", "cat": "lineage",
                            "ph": "X", "pid": 3, "tid": row_tid,
                            "ts": round(t0 / 1e3, 3),
                            "dur": round(max(t1 - t0, 1) / 1e3, 3),
                            "args": {"id": pid_,
                                     "chain": "->".join(h["stage"]
                                                        for h in hops),
                                     "reason": reason}})
        return meta + evs

    def write_trace(self, path: str):
        with open(path, "w") as f:
            json.dump({"traceEvents": self.trace_events(),
                       "displayTimeUnit": "ms"}, f)

    def write_metrics(self, path: str, extra: dict | None = None):
        m = self.metrics()
        if extra:
            m.update(extra)
        with open(path, "w") as f:
            json.dump(m, f, indent=2)
        return m

    def summary_table(self) -> str:
        """One-screen end-of-run phase breakdown."""
        m = self.metrics()
        lines = [f"{'phase':<16s} {'count':>7s} {'total_s':>9s} "
                 f"{'p50_ms':>9s} {'p95_ms':>9s} {'max_ms':>9s}"]
        for name, p in m["phases"].items():
            lines.append(f"{name:<16s} {p['count']:>7d} "
                         f"{p['total_s']:>9.3f} {p['p50_ms']:>9.3f} "
                         f"{p['p95_ms']:>9.3f} {p['max_ms']:>9.3f}")
        t = m["transfers"]
        c = m["compile"]
        lines.append(f"transfers: {t['bytes']} bytes in {t['count']} "
                     f"fetches; jit compiles: {c['count']} "
                     f"({c['total_s']:.1f}s); wall: {m['wall_s']:.3f}s")
        dc = m.get("device_counters")
        if dc:
            lines.append("device: " + ", ".join(
                f"{k}={v}" for k, v in dc.items()))
        return "\n".join(lines)


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an ascending list."""
    if not sorted_vals:
        return 0.0
    i = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[i]


# Span names that are host work competing with device launches.  Their
# wall overlap with `device_step` spans is the host_drain_overlap_pct
# metric (the async-window-pipeline yardstick in ROADMAP.md).
_HOST_DRAIN_PHASES = frozenset(
    ("heartbeat", "log_drain", "flight_drain", "scope_drain",
     "lineage_drain", "digest_drain", "progress"))

# Most traced packets rendered as pid-3 waterfall spans in trace.json
# (ordered by first hop); the full span set always lands in spans.jsonl.
_LINEAGE_TRACK_IDS = 256


def _union(intervals):
    """Merge (start, end) intervals into a disjoint ascending list."""
    out = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _overlap(ivals_a, ivals_b) -> float:
    """Total length of the intersection of two interval sets."""
    ua, ub = _union(ivals_a), _union(ivals_b)
    tot, i, j = 0.0, 0, 0
    while i < len(ua) and j < len(ub):
        lo = max(ua[i][0], ub[j][0])
        hi = min(ua[i][1], ub[j][1])
        if hi > lo:
            tot += hi - lo
        if ua[i][1] <= ub[j][1]:
            i += 1
        else:
            j += 1
    return tot


# ---------------------------------------------------------------------------
# Device-counter helpers (the TraceCounters block on SimState)
# ---------------------------------------------------------------------------


def ensure_counters(state):
    """Return `state` with a TraceCounters block installed (idempotent).
    Changes the state pytree structure, so jitted engine calls recompile
    once for the counted variant."""
    if state.tr is None:
        from .core.state import make_trace_counters
        state = state.replace(tr=make_trace_counters())
    return state


def fetch_counters(state, profiler=None) -> dict:
    """ONE device->host fetch of the telemetry scalars + counter block,
    recorded as a counter sample (and a transfer) on `profiler` (default:
    the active one).  Safe to call whether or not counters are installed.
    """
    import jax

    vals = [state.n_steps, state.n_windows, state.n_events]
    names = ["microsteps", "windows", "events"]
    if state.tr is not None:
        vals += [state.tr.exchanges, state.tr.pkts_exchanged,
                 state.tr.occ_max]
        names += ["exchanges", "pkts_exchanged", "inbox_occ_max"]
    if getattr(state, "nm", None) is not None:
        import jax.numpy as _jnp
        vals += [state.nm.cursor, state.nm.killed,
                 _jnp.sum(state.nm.host_up == 0)]
        names += ["netem_events_applied", "netem_killed",
                  "netem_hosts_down"]
    fetched = jax.device_get(vals)
    out = {n: int(v) for n, v in zip(names, fetched)}
    if state.tr is not None:
        ki = state.inbox.capacity // state.hosts.num_hosts
        out["inbox_occ_frac"] = round(out["inbox_occ_max"] / max(ki, 1), 4)
    p = profiler if profiler is not None else _active
    p.transfer(sum(getattr(v, "nbytes", 8) for v in fetched), count=1)
    p.counter_sample(out)
    return out


# ---------------------------------------------------------------------------
# Flight recorder (the FlightRecorder ring on SimState; core/state.py)
# ---------------------------------------------------------------------------


def ensure_flight_recorder(state, capacity: int = 4096, shards: int = 1,
                           rows: int | None = None):
    """Return `state` with a per-window FlightRecorder ring installed
    (idempotent).  `shards` sizes the src->dst exchange matrices and
    must match the device count of a mesh run (1 for single-device);
    the host count and pool capacity must divide it so the logical
    shard of a host is well defined.  `rows` (the `--flight-rows` CLI
    surface) overrides `capacity`: long runs whose drain/checkpoint
    cadence exceeds 4096 windows size the ring up instead of losing
    per-window resolution to wrap (the FlightDrain caveat).

    The ring cursor (`fr.total`) seeds from `state.n_windows`, so the
    row index FlightDrain stamps into windows.jsonl is the GLOBAL
    monotonically increasing window counter of the simulation -- the
    same index `replay --window K` addresses -- even when the recorder
    is installed on a mid-run state."""
    if state.fr is not None:
        return state
    import jax.numpy as _jnp
    from .core.state import I64, make_flight_recorder
    if rows is not None:
        capacity = int(rows)
    if capacity < 1:
        raise ValueError(
            f"ensure_flight_recorder: ring capacity must be positive, "
            f"got {capacity}")
    h = int(state.hosts.num_hosts)
    if shards < 1 or h % shards or int(state.pool.capacity) % shards:
        raise ValueError(
            f"ensure_flight_recorder: shards={shards} must divide the "
            f"host count ({h}) and pool capacity "
            f"({int(state.pool.capacity)}); pad the world to the mesh "
            f"first (parallel.pad_world_to_mesh)")
    fr = make_flight_recorder(capacity, shards)
    fr = fr.replace(total=_jnp.asarray(state.n_windows, I64))
    return state.replace(fr=fr)


def _open_sink(path_or_file, mode: str = "w"):
    """(file, owned) from a drain's output target.

    A str path opens a file the drain OWNS (close() closes it).  An
    already-open file-like (anything with .write) is SHARED -- ensemble
    runs hand one windows.jsonl/flows.jsonl/... to W per-world drains,
    whose rows interleave with a "world" column telling them apart --
    and close() leaves it open for the owner (sim.run_ensemble)."""
    if path_or_file is None:
        return None, False
    if hasattr(path_or_file, "write"):
        return path_or_file, False
    return open(path_or_file, mode), True


class ReplayDivergence(RuntimeError):
    """A replayed trajectory produced a flight-recorder row that differs
    bitwise from the original run's windows.jsonl record.  Raised by
    FlightDrain when draining with `verify_against`; carries the first
    diverging global window index and the differing fields."""

    def __init__(self, window: int, got: dict, want: dict):
        self.window = int(window)
        self.got = got
        self.want = want
        fields = sorted(k for k in want
                        if k in got and got[k] != want[k])
        self.fields = fields
        super().__init__(
            f"replay diverged at window {window}: field(s) "
            f"{', '.join(fields) or '<missing row>'} differ from the "
            f"recorded windows.jsonl (triage: tools/parse.py replaydiff)")


class FlightDrain:
    """Host-side drain of the flight recorder: fetches new rows at chunk
    boundaries (one scalar probe + one bulk fetch only when rows are
    new -- riding the existing sync points, no extra per-window syncs),
    appends them to ``windows.jsonl`` when a path is given, and keeps
    them for Profiler.set_flight / aggregation.

    Every row is stamped with its GLOBAL window index (`"window"`: the
    simulation's monotonic window counter, which ensure_flight_recorder
    seeds the ring cursor from) -- the address `replay --window K`
    restores to.  Ring wrap between drains loses the oldest rows;
    lifetime totals are still exact because the recorder accumulates
    wrap-proof sums on the device (`ex_*_sum`) -- the drain reports
    `rows_lost` so a summary reader knows row-derived aggregates are
    partial.  CAVEAT: past `capacity` (default 4096) windows between
    drains the window INDEX stays exact but the per-window RESOLUTION
    is gone -- wrapped windows have no row, so a replay cross-check (and
    `replay --window K` targeting) can only address windows that
    survived into windows.jsonl; checkpoint cadences that drain at
    least every 4096 windows keep the record gap-free.

    `start` skips rows already drained in an earlier life of the ring:
    a replay restores a checkpoint whose ring carries the original
    run's rows below `fr.total`; starting the drain there emits only
    windows the replay itself produced, numbered exactly as the
    original run numbered them.

    `verify_against` is the replay-verify hook: a {window: row} mapping
    of the ORIGINAL run's windows.jsonl records.  Each drained row that
    has an original counterpart is compared bitwise (full dict
    equality, exchange matrices included); the first mismatch raises
    ReplayDivergence naming the window -- divergence is a loud,
    window-pinpointed error, never silent garbage.

    `mode="a"` appends to an existing windows.jsonl instead of
    truncating it: auto-resume (supervise.py) trims the file to rows
    below the resume checkpoint's window, then appends the re-recorded
    (bitwise-identical) rows from there, keeping one contiguous record
    across process lifetimes.

    `world` stamps every row with an ensemble world id (the drain-layer
    world-column convention, docs/ensemble.md); `path` may be an
    already-open shared file (see _open_sink)."""

    def __init__(self, path=None, start: int = 0,
                 verify_against: dict | None = None, mode: str = "w",
                 world: int | None = None):
        self.path = path
        self.rows = []
        self.rows_lost = 0
        self.shards = None      # learned from the ring at first drain
        self.capacity = None
        self._last = int(start)
        self.verify_against = verify_against
        self.verified = 0       # rows that matched an original record
        self.world = world
        self._f, self._own = _open_sink(path, mode)

    def drain(self, state, profiler=None) -> int:
        """Fetch rows appended since the last drain; returns how many."""
        fr = getattr(state, "fr", None)
        if fr is None:
            return 0
        import jax
        p = profiler if profiler is not None else _active
        with p.span("flight_drain"):
            total = int(jax.device_get(fr.total))
            p.transfer(8, count=1)
            new = total - self._last
            if new <= 0:
                return 0
            self.shards = fr.n_shards
            self.capacity = c = fr.capacity
            arrs = jax.device_get((fr.win_start, fr.win_end, fr.steps,
                                   fr.events, fr.routed, fr.delivered,
                                   fr.dropped, fr.killed, fr.ex_cnt,
                                   fr.ex_bytes))
            p.transfer(sum(a.nbytes for a in arrs), count=1)
            if new > c:
                # Ring wrap between drains: rows [self._last, total - c)
                # are gone.  When this drain is verifying a replay, a
                # wrapped-away verify target can never be checked --
                # fail loudly rather than silently skipping it; if every
                # verify target survived the wrap, verify the surviving
                # suffix but say so explicitly.
                if self.verify_against is not None:
                    gone = [w for w in self.verify_against
                            if self._last <= w < total - c]
                    if gone:
                        self._last = total
                        raise RuntimeError(
                            f"flight-recorder ring wrapped over "
                            f"{len(gone)} window(s) awaiting replay "
                            f"verification (first {min(gone)}, last "
                            f"{max(gone)}): the gap between drains "
                            f"exceeded the ring capacity ({c}); rerun "
                            f"with a larger recorder or a drain/"
                            f"checkpoint cadence under {c} windows")
                    import warnings
                    warnings.warn(
                        f"flight-recorder ring wrapped during a "
                        f"verified replay ({new - c} row(s) lost, none "
                        f"of them verify targets); only the surviving "
                        f"suffix of windows.jsonl is being verified",
                        RuntimeWarning, stacklevel=2)
                self.rows_lost += new - c
                start = total - c
            else:
                start = self._last
            ws, we, steps, ev, rt, dl, dp, kl, xc, xb = arrs
            for w in range(start, total):
                k = w % c
                row = {"window": w,
                       **({} if self.world is None
                          else {"world": self.world}),
                       "t_start": int(ws[k]), "t_end": int(we[k]),
                       "steps": int(steps[k]), "events": int(ev[k]),
                       "routed": int(rt[k]), "delivered": int(dl[k]),
                       "dropped": int(dp[k]), "killed": int(kl[k]),
                       "ex_cnt": xc[k].tolist(),
                       "ex_bytes": xb[k].tolist()}
                self.rows.append(row)
                if self.verify_against is not None and \
                        w in self.verify_against:
                    want = self.verify_against[w]
                    if row != want:
                        self._last = total
                        raise ReplayDivergence(w, row, want)
                    self.verified += 1
                if self._f is not None:
                    self._f.write(json.dumps(row) + "\n")
            if self._f is not None:
                self._f.flush()
            self._last = total
            return new

    def close(self):
        if self._f is not None:
            if self._own:
                self._f.close()
            self._f = None

    def summary(self, state=None, n_devices: int = 1) -> dict:
        """Aggregate the drained rows into the `mesh` metrics section.
        Pass the final state to include the device-side wrap-proof
        exchange totals (exact even when rows were lost to wrap)."""
        d = self.shards or 1
        agg = {k: sum(r[k] for r in self.rows)
               for k in ("steps", "events", "routed", "delivered",
                         "dropped", "killed")}
        mat_c = [[0] * d for _ in range(d)]
        mat_b = [[0] * d for _ in range(d)]
        for r in self.rows:
            for i in range(d):
                for j in range(d):
                    mat_c[i][j] += r["ex_cnt"][i][j]
                    mat_b[i][j] += r["ex_bytes"][i][j]
        if state is not None and getattr(state, "fr", None) is not None:
            import jax
            mat_c, mat_b = (a.tolist() for a in jax.device_get(
                (state.fr.ex_cnt_sum, state.fr.ex_bytes_sum)))
        out = {
            "n_devices": n_devices,
            "recorder": {"capacity": self.capacity, "shards": d},
            "windows": self._last,
            "rows_lost": self.rows_lost,
        }
        out.update(agg)
        out["exchange"] = {
            "movers": sum(map(sum, mat_c)),
            "bytes": sum(map(sum, mat_b)),
            "matrix_movers": mat_c,
            "matrix_bytes": mat_b,
        }
        if self.rows:
            sim_s = (self.rows[-1]["t_end"]
                     - self.rows[0]["t_start"]) / 1e9
            if sim_s > 0:
                out["windows_per_sim_s"] = round(len(self.rows) / sim_s, 3)
        return out


# ---------------------------------------------------------------------------
# Invariant sentinel (the SentinelBlock on SimState; core/state.py)
# ---------------------------------------------------------------------------


def ensure_sentinel(state):
    """Return `state` with the per-window invariant sentinel installed
    (idempotent).  The block is a handful of replicated scalars, so it
    needs no shard sizing -- the same install works single-device and
    on any mesh.  `last_we` seeds from the current sim time so a
    mid-run install never trips the monotonicity probe on its first
    window."""
    if state.sentinel is not None:
        return state
    import jax.numpy as _jnp
    from .core.state import I64, make_sentinel
    sn = make_sentinel()
    sn = sn.replace(last_we=_jnp.asarray(state.now, I64))
    return state.replace(sentinel=sn)


def sentinel_classes(bits: int) -> list:
    """The violation-class names set in a SENTINEL_* bitmask."""
    from .core.state import SENTINEL_CLASS_NAMES
    return [name for bit, name in sorted(SENTINEL_CLASS_NAMES.items())
            if int(bits) & bit]


class SentinelViolation(RuntimeError):
    """A device-side invariant probe fired: the simulation violated
    packet conservation, window-time monotonicity, a stage/queue/cursor
    bound, or finiteness of its float islands.  Raised by
    SentinelDrain.check(); carries the full sentinel row (the same dict
    the supervisor stamps into crash.json).  Ensemble rows name the
    offending world and point the replay hint at `--world K`."""

    def __init__(self, row: dict):
        self.row = row
        names = sentinel_classes(row.get("violations", 0))
        w = row.get("world")
        where = f" in world {w}" if w is not None else ""
        wflag = f" --world {w}" if w is not None else ""
        super().__init__(
            f"sentinel violation ({'+'.join(names) or 'unknown'}){where} "
            f"first at window {row.get('first_bad_window')} "
            f"(t={row.get('first_bad_t')} ns); replay it with "
            f"`shadow1-tpu replay{wflag} --window "
            f"{row.get('first_bad_window')}`"
        )


class SentinelDrain:
    """Host-side drain of the invariant sentinel: ONE bulk fetch of the
    block's scalars at chunk boundaries (riding the existing sync
    points, like FlightDrain).  `drain` returns the current row;
    `check` additionally raises SentinelViolation the moment any sticky
    violation bit is set, which is what the supervisor catches.

    Stacked states drain per world (the sentinel block vmaps like any
    other leaf, so the sticky bits/first_bad_window/first_bad_t are
    already per-world): the returned row aggregates -- checks summed,
    violation bits OR'd -- and carries the earliest-failing world's
    coordinates plus `world` / `bad_worlds` / `worlds` (one sub-row per
    offending world), which is what the supervisor's quarantine rung
    and crash.json consume."""

    _FIELDS = ("checks", "violations", "last_violation",
               "first_bad_window", "first_bad_t", "last_we",
               "resid_low", "resid_high", "nonfinite")

    def __init__(self):
        self.row = None

    @staticmethod
    def _row(checks, bits, last, fw, ft, lwe, rlo, rhi, nf):
        return {
            "checks": checks,
            "violations": bits,
            "classes": sentinel_classes(bits),
            "last_violation": last,
            "first_bad_window": fw,
            "first_bad_t": ft,
            "last_we": lwe,
            "resid_low": rlo,
            "resid_high": rhi,
            "nonfinite": nf,
        }

    def drain(self, state, profiler=None):
        sn = getattr(state, "sentinel", None)
        if sn is None:
            return None
        import jax
        p = profiler if profiler is not None else _active
        with p.span("sentinel_drain"):
            vals = jax.device_get((sn.checks, sn.violations,
                                   sn.last_violation, sn.first_bad_window,
                                   sn.first_bad_t, sn.last_we,
                                   sn.resid_low, sn.resid_high,
                                   sn.nonfinite))
            p.transfer(8 * len(vals), count=1)
        import numpy as np
        if np.ndim(vals[0]) == 0:
            self.row = self._row(*map(int, vals))
            return self.row
        arrs = [np.asarray(v).ravel() for v in vals]
        n = arrs[0].size
        per = [self._row(*(int(a[k]) for a in arrs)) for k in range(n)]
        bad = [k for k in range(n) if per[k]["violations"]]
        # The headline coordinates are the earliest failure's (smallest
        # first_bad_t, ties to the lowest world index).
        lead = min(bad, key=lambda k: (per[k]["first_bad_t"], k)) \
            if bad else None
        row = dict(per[lead if lead is not None else 0])
        bits = 0
        for r in per:
            bits |= r["violations"]
        row.update({
            "checks": sum(r["checks"] for r in per),
            "violations": bits,
            "classes": sentinel_classes(bits),
            "world": lead,
            "n_worlds": n,
            "bad_worlds": bad,
            "worlds": [dict(per[k], world=k) for k in bad],
        })
        self.row = row
        return self.row

    def check(self, state, profiler=None):
        """Drain; raise SentinelViolation if any probe has ever fired."""
        row = self.drain(state, profiler)
        if row is not None and row["violations"]:
            raise SentinelViolation(row)
        return row


# ---------------------------------------------------------------------------
# Statescope digests (the DigestBlock on SimState; core/state.py)
# ---------------------------------------------------------------------------


def ensure_digests(state, every: int = 1, capacity: int = 4096,
                   shards: int = 1):
    """Return `state` with a per-window DigestBlock installed
    (idempotent).  `every` is the cadence in windows (digest every Nth
    window close); `shards` sizes the per-logical-shard checksum
    columns and must match the device count of a mesh run (1 for
    single-device); the host count, pool capacity, and inbox capacity
    must divide it so element ownership is well defined.

    Rows stamp the GLOBAL window index (taken from `state.n_windows` at
    record time), so a mid-run install digests under the same indices
    an always-on block would use -- diff aligns streams by that index."""
    if state.dg is not None:
        return state
    from .core.state import make_digest
    every = int(every)
    if every < 1:
        raise ValueError(
            f"ensure_digests: cadence must be a positive window count, "
            f"got {every}")
    if capacity < 1:
        raise ValueError(
            f"ensure_digests: ring capacity must be positive, "
            f"got {capacity}")
    h = int(state.hosts.num_hosts)
    if shards < 1 or h % shards or int(state.pool.capacity) % shards \
            or int(state.inbox.capacity) % shards:
        raise ValueError(
            f"ensure_digests: shards={shards} must divide the host "
            f"count ({h}), pool capacity ({int(state.pool.capacity)}), "
            f"and inbox capacity ({int(state.inbox.capacity)}); pad the "
            f"world to the mesh first (parallel.pad_world_to_mesh)")
    return state.replace(dg=make_digest(capacity, shards, every))


class DigestDrain:
    """Host-side drain of the digest ring: one cursor probe per drain, a
    bulk fetch only when new rows exist (the FlightDrain recipe), each
    row appended to ``digests.jsonl``:

        {"window": 41, "t_end": 120000000,
         "sums": {"pool": [..D ints..], ..per DIGEST_GROUPS..}}

    Ring wrap between drains loses the oldest rows (`rows_lost`); size
    the ring or the cadence so the gap between drains stays under
    capacity when a complete record matters (the FlightDrain caveat).

    `world` stamps every row with an ensemble world id; `path` may be
    an already-open shared file (see _open_sink)."""

    def __init__(self, path=None, start: int = 0,
                 mode: str = "w", world: int | None = None):
        self.path = path
        self.rows = []
        self.rows_lost = 0
        self.shards = None
        self.capacity = None
        self.every = None
        self.world = world
        self._last = int(start)
        self._f, self._own = _open_sink(path, mode)

    def drain(self, state, profiler=None) -> int:
        """Fetch rows appended since the last drain; returns how many."""
        dg = getattr(state, "dg", None)
        if dg is None:
            return 0
        import jax
        from .core.state import DIGEST_GROUPS
        p = profiler if profiler is not None else _active
        with p.span("digest_drain"):
            total = int(jax.device_get(dg.total))
            p.transfer(8, count=1)
            new = total - self._last
            if new <= 0:
                return 0
            self.shards = dg.n_shards
            self.capacity = c = dg.capacity
            win, t_end, sums, every = jax.device_get(
                (dg.win, dg.t_end, dg.sums, dg.every))
            self.every = int(every)
            p.transfer(win.nbytes + t_end.nbytes + sums.nbytes, count=1)
            if new > c:
                self.rows_lost += new - c
                start = total - c
            else:
                start = self._last
            for r in range(start, total):
                k = r % c
                row = {"window": int(win[k]),
                       **({} if self.world is None
                          else {"world": self.world}),
                       "t_end": int(t_end[k]),
                       "sums": {g: sums[k, gi].tolist()
                                for gi, g in enumerate(DIGEST_GROUPS)}}
                self.rows.append(row)
                if self._f is not None:
                    self._f.write(json.dumps(row) + "\n")
            if self._f is not None:
                self._f.flush()
            self._last = total
            return new

    def close(self):
        if self._f is not None:
            if self._own:
                self._f.close()
            self._f = None

    def summary(self) -> dict:
        """Aggregate for the `digest` metrics section."""
        out = {
            "rows": len(self.rows),
            "rows_lost": self.rows_lost,
            "every": self.every,
            "shards": self.shards or 1,
        }
        if self.rows:
            out["first_window"] = self.rows[0]["window"]
            out["last_window"] = self.rows[-1]["window"]
        return out


# ---------------------------------------------------------------------------
# Flowscope (the FlowScope sampling block on SimState; core/state.py)
# ---------------------------------------------------------------------------


_SCOPE_UNITS = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}


def parse_scope_spec(spec: str) -> dict:
    """Parse a ``--scope`` spec: ``flows[,links][:interval]``.

    The ring list picks what to sample (`flows`, `links`, or both,
    comma-separated, any order); the optional `:interval` suffix sets
    the sim-time cadence (`50ms`, `2s`, `500us`, or a bare nanosecond
    count; default 100ms).  Returns ensure_flowscope kwargs."""
    rings, _, ivl = spec.partition(":")
    names = [r.strip() for r in rings.split(",") if r.strip()]
    bad = [n for n in names if n not in ("flows", "links")]
    if bad or not names:
        raise ValueError(
            f"--scope: unknown ring(s) {bad or ['<empty>']} in {spec!r} "
            f"(expected flows[,links][:interval])")
    out = {"flows": "flows" in names, "links": "links" in names}
    if ivl:
        ivl = ivl.strip()
        unit = 1
        for suffix, mult in sorted(_SCOPE_UNITS.items(),
                                   key=lambda kv: -len(kv[0])):
            if ivl.endswith(suffix):
                unit, ivl = mult, ivl[:-len(suffix)]
                break
        try:
            ns = int(float(ivl) * unit)
        except ValueError:
            raise ValueError(
                f"--scope: bad interval {spec.partition(':')[2]!r} "
                f"(expected e.g. 100ms, 2s, 500us, or nanoseconds)")
        if ns < 1:
            raise ValueError(f"--scope: interval must be positive, got "
                             f"{spec.partition(':')[2]!r}")
        out["interval_ns"] = ns
    return out


def ensure_flowscope(state, flow_capacity: int = 1 << 16,
                     link_capacity: int = 1 << 14,
                     interval_ns: int = 100_000_000, shards: int = 1,
                     flows: bool = True, links: bool = True):
    """Return `state` with a FlowScope sampling block installed
    (idempotent).  `shards` must match the device count of a mesh run
    (1 for single-device) and divide the host count; install AFTER mesh
    padding, like the flight recorder."""
    if state.scope is not None:
        return state
    from .core.state import make_flowscope
    h = int(state.hosts.num_hosts)
    if shards < 1 or h % shards:
        raise ValueError(
            f"ensure_flowscope: shards={shards} must divide the host "
            f"count ({h}); pad the world to the mesh first "
            f"(parallel.pad_world_to_mesh)")
    return state.replace(scope=make_flowscope(
        flow_capacity=flow_capacity, link_capacity=link_capacity,
        interval_ns=interval_ns, shards=shards, flows=flows, links=links))


_FLOW_FIELDS = ("time", "host", "slot", "peer", "cwnd", "ssthresh",
                "srtt", "inflight", "retx", "acked", "sent", "recv")
_LINK_FIELDS = ("time", "host", "tx", "rx", "qdepth", "cap", "drops")


class ScopeDrain:
    """Host-side drain of the flowscope rings: fetches new rows at chunk
    boundaries (one cursor probe, bulk fetch only when rows are new --
    the FlightDrain pattern), merges per-shard ring segments into global
    sim-time order (the LogDrain pattern), and appends them to
    ``flows.jsonl``/``links.jsonl`` when paths are given.

    Row counters (acked/sent/recv/retx, tx/rx/drops) are CUMULATIVE
    lifetime values sampled from the socket/host tables, so a ring wrap
    between drains loses time resolution, never totals: the newest
    surviving row per flow/host still carries the exact lifetime sums.
    The drain derives per-row delivered-rate (`rate_Bps`) host-side from
    consecutive samples of the same flow.

    `real_hosts` drops link rows of padded hosts (global id >= the
    count; padding appends hosts at the end) so a mesh/bucket-padded
    run reports the same links as the exact-size world -- the same
    contract Tracker heartbeats keep by only writing named hosts.
    Padded hosts never open sockets, so flow rows need no filter.

    `world` stamps every row with an ensemble world id; the paths may
    be already-open shared files (see _open_sink)."""

    def __init__(self, flows_path=None,
                 links_path=None,
                 real_hosts: int | None = None,
                 world: int | None = None):
        self.real_hosts = real_hosts
        self.world = world
        self.flow_rows = []
        self.link_rows = []
        self.flow_rows_lost = 0
        self.link_rows_lost = 0
        self.interval_ns = None     # learned from the block at first drain
        self.samples = 0
        self.shards = None
        self._last = {}             # ring prefix -> [shards] cursors
        self._wrap_lost = {}        # ring prefix -> rows lost to wrap
        self._prev = {}             # flow key -> (t, acked) for rate_Bps
        self._ff, self._own_ff = _open_sink(flows_path)
        self._lf, self._own_lf = _open_sink(links_path)

    def drain(self, state, profiler=None) -> int:
        """Fetch rows appended since the last drain; returns how many."""
        scope = getattr(state, "scope", None)
        if scope is None:
            return 0
        import jax
        import numpy as np
        p = profiler if profiler is not None else _active
        with p.span("scope_drain"):
            probe = jax.device_get((scope.interval, scope.samples,
                                    scope.f_total, scope.f_lost,
                                    scope.l_total, scope.l_lost))
            p.transfer(sum(getattr(a, "nbytes", 8) for a in probe),
                       count=1)
            self.interval_ns = int(probe[0])
            self.samples = int(probe[1])
            ft, fl, lt, ll = (np.atleast_1d(np.asarray(a, np.int64))
                              for a in probe[2:])
            self.shards = ft.shape[0]
            n = 0
            if scope.sample_flows:
                n += self._drain_ring(scope, "f", _FLOW_FIELDS, ft, p,
                                      self._flow_row, self.flow_rows,
                                      self._ff)
                self.flow_rows_lost = int(fl.sum()) \
                    + self._wrap_lost.get("f", 0)
            if scope.sample_links:
                n += self._drain_ring(scope, "l", _LINK_FIELDS, lt, p,
                                      self._link_row, self.link_rows,
                                      self._lf)
                self.link_rows_lost = int(ll.sum()) \
                    + self._wrap_lost.get("l", 0)
            return n

    def _drain_ring(self, scope, prefix, fields, tot_a, p, mk_row,
                    rows, f) -> int:
        import jax
        import numpy as np
        shards = tot_a.shape[0]
        last = self._last.setdefault(prefix, np.zeros(shards, np.int64))
        total = int(tot_a.sum())
        if total == int(last.sum()):
            return 0
        arrs = jax.device_get(tuple(
            getattr(scope, f"{prefix}_{name}") for name in fields))
        p.transfer(sum(a.nbytes for a in arrs), count=1)
        per = arrs[0].shape[0] // shards
        parts = []
        for s in range(shards):
            total_s = int(tot_a[s])
            ns = total_s - int(last[s])
            if ns <= 0:
                continue
            if ns > per:
                self._wrap_lost[prefix] = \
                    self._wrap_lost.get(prefix, 0) + ns - per
                start = total_s - per
            else:
                start = int(last[s])
            parts.append(s * per + (np.arange(start, total_s) % per))
            last[s] = total_s
        if not parts:
            return 0
        idx = np.concatenate(parts)
        order = np.argsort(arrs[0][idx], kind="stable")
        n = 0
        for k in idx[order]:
            row = mk_row(fields, [a[k] for a in arrs])
            if prefix == "l" and self.real_hosts is not None \
                    and row["host"] >= self.real_hosts:
                continue
            if self.world is not None:
                row = {"world": self.world, **row}
            rows.append(row)
            if f is not None:
                f.write(json.dumps(row) + "\n")
            n += 1
        if f is not None:
            f.flush()
        return n

    def _flow_row(self, fields, vals) -> dict:
        v = dict(zip(fields, (int(x) for x in vals)))
        row = {"t": v["time"], "host": v["host"], "slot": v["slot"],
               "peer": v["peer"], "cwnd": v["cwnd"],
               "ssthresh": v["ssthresh"], "srtt_ns": v["srtt"],
               "inflight": v["inflight"], "retx": v["retx"],
               "acked": v["acked"], "sent": v["sent"], "recv": v["recv"]}
        key = (v["host"], v["slot"], v["peer"])
        prev = self._prev.get(key)
        rate = 0.0
        if prev is not None:
            dt, da = row["t"] - prev[0], row["acked"] - prev[1]
            if dt > 0 and da > 0:
                rate = da / dt * 1e9
        self._prev[key] = (row["t"], row["acked"])
        row["rate_Bps"] = round(rate, 1)
        return row

    def _link_row(self, fields, vals) -> dict:
        v = dict(zip(fields, (int(x) for x in vals)))
        return {"t": v["time"], "host": v["host"], "tx": v["tx"],
                "rx": v["rx"], "qdepth": v["qdepth"],
                "cap_Bps": v["cap"], "drops": v["drops"]}

    def close(self):
        for f, own in ((self._ff, self._own_ff), (self._lf, self._own_lf)):
            if f is not None and own:
                f.close()
        self._ff = self._lf = None

    def summary(self) -> dict:
        """Aggregate the drained rows into the `net` metrics section.
        Totals come from the newest row per flow/host (the counters are
        cumulative), so they survive ring wraps between drains."""
        out = {"interval_ns": self.interval_ns, "samples": self.samples,
               "shards": self.shards or 1}
        fin_f = {}
        for r in self.flow_rows:
            fin_f[(r["host"], r["slot"], r["peer"])] = r
        if self.flow_rows or self._ff is not None:
            out["flows"] = {
                "rows": len(self.flow_rows),
                "rows_lost": self.flow_rows_lost,
                "flows_seen": len(fin_f),
                "bytes_acked": sum(r["acked"] for r in fin_f.values()),
                "bytes_sent": sum(r["sent"] for r in fin_f.values()),
                "retransmit_segs": sum(r["retx"] for r in fin_f.values()),
            }
        fin_l = {}
        for r in self.link_rows:
            fin_l[r["host"]] = r
        if self.link_rows or self._lf is not None:
            out["links"] = {
                "rows": len(self.link_rows),
                "rows_lost": self.link_rows_lost,
                "hosts_seen": len(fin_l),
                "bytes_forwarded": sum(r["tx"] for r in fin_l.values()),
                "drops": sum(r["drops"] for r in fin_l.values()),
            }
        return out


# ---------------------------------------------------------------------------
# Packet lineage (sampled per-packet span tracing; docs/observability.md)
# ---------------------------------------------------------------------------


def parse_lineage_rate(spec) -> float:
    """Parse a ``--trace-packets`` / ``run(lineage=...)`` rate spec.

    Accepts a float string (``"0.01"``), a percentage (``"1%"``), the
    word ``"all"`` (rate 1.0), or a plain number.  The rate is a
    sampling PROBABILITY in (0, 1]; rates above 1 are an error rather
    than a silent clamp so a fat-fingered ``--trace-packets 10``
    (meant as a percent) fails loudly."""
    if isinstance(spec, (int, float)) and not isinstance(spec, bool):
        rate = float(spec)
    else:
        s = str(spec).strip().lower()
        if s == "all":
            return 1.0
        try:
            if s.endswith("%"):
                rate = float(s[:-1]) / 100.0
            else:
                rate = float(s)
        except ValueError:
            raise ValueError(
                f"--trace-packets: bad rate {spec!r} (expected a "
                f"probability like 0.01, a percentage like 1%, or 'all')")
    if not (0.0 < rate <= 1.0):
        raise ValueError(
            f"--trace-packets: rate must be in (0, 1], got {rate!r} "
            f"(use e.g. 0.01 for one packet in a hundred)")
    return rate


def ensure_lineage(state, rate: float = 0.01, capacity: int = 1 << 16,
                   shards: int = 1):
    """Return `state` with the packet-lineage tracer installed
    (idempotent).  `rate` is the sampling probability (a seeded,
    deterministic function of (src host, emission counter), so every
    device count -- and a replay -- samples the SAME packets);
    `capacity` sizes the span ring (rounded up to a multiple of
    `shards`).  `shards` must match the device count of a mesh run and
    divide the host count, pool capacity, and inbox capacity; install
    AFTER mesh padding, like the flight recorder and flowscope."""
    if state.lineage is not None:
        return state
    from .core.state import make_lineage
    h = int(state.hosts.num_hosts)
    pc, ic = int(state.pool.capacity), int(state.inbox.capacity)
    if shards < 1 or h % shards or pc % shards or ic % shards:
        raise ValueError(
            f"ensure_lineage: shards={shards} must divide the host count "
            f"({h}), pool capacity ({pc}) and inbox capacity ({ic}); pad "
            f"the world to the mesh first (parallel.pad_world_to_mesh)")
    return state.replace(lineage=make_lineage(
        pc, ic, rate=rate, capacity=capacity, shards=shards))


_SPAN_FIELDS = ("s_time", "s_id", "s_host", "s_stage", "s_reason")


class LineageDrain:
    """Host-side drain of the lineage span ring: fetches new rows at
    chunk boundaries (one scalar probe, bulk fetch only when rows are
    new -- the FlightDrain pattern), merges per-shard ring segments
    into global sim-time order (the ScopeDrain pattern), and appends
    them to ``spans.jsonl`` when a path is given.

    Each row is one hop of one traced packet's life story:
    ``{"t", "id", "host", "stage", "reason"}`` with `stage` one of
    emit/stage/tx/link/exchange/deliver and `reason` naming why a
    packet died at that hop (qdisc_overflow, loss, link_down,
    partition, host_down, ack_shed, pool_overflow; "none" for hops
    that succeeded).  Ring wrap between drains loses the OLDEST
    pending rows (append-side policy: the ring keeps the first
    `capacity` rows per drain interval and counts the rest into
    `lineage.lost`); `spans_lost` in the summary makes the gap
    visible, and lifetime counters (`n_assigned`, the drop totals the
    drained rows carry) stay exact.

    `world` stamps every row with an ensemble world id; `spans_path`
    may be an already-open shared file (see _open_sink)."""

    def __init__(self, spans_path=None, world: int | None = None):
        self.rows = []
        self.rows_lost = 0
        self.n_assigned = 0
        self.rate = None            # learned from the block at first drain
        self.shards = None
        self.world = world
        self._last = None           # [shards] drained-cursor array
        self._wrap_lost = 0
        self._f, self._own = _open_sink(spans_path)

    def drain(self, state, profiler=None) -> int:
        """Fetch span rows appended since the last drain; returns how
        many.  Rides existing sync points -- call at chunk boundaries."""
        ln = getattr(state, "lineage", None)
        if ln is None:
            return 0
        import jax
        import numpy as np
        from .core.state import LREASON_NAMES, SPAN_STAGE_NAMES
        p = profiler if profiler is not None else _active
        with p.span("lineage_drain"):
            probe = jax.device_get((ln.rate_x1p32, ln.n_assigned,
                                    ln.total, ln.lost))
            p.transfer(sum(getattr(a, "nbytes", 8) for a in probe),
                       count=1)
            self.rate = (int(probe[0]) + 1) / 4294967296.0
            self.n_assigned = int(probe[1])
            tot = np.atleast_1d(np.asarray(probe[2], np.int64))
            lost = np.atleast_1d(np.asarray(probe[3], np.int64))
            self.shards = tot.shape[0]
            self.rows_lost = int(lost.sum()) + self._wrap_lost
            if self._last is None:
                self._last = np.zeros(self.shards, np.int64)
            if int(tot.sum()) == int(self._last.sum()):
                return 0
            arrs = jax.device_get(tuple(
                getattr(ln, name) for name in _SPAN_FIELDS))
            p.transfer(sum(a.nbytes for a in arrs), count=1)
            per = arrs[0].shape[0] // self.shards
            parts = []
            for s in range(self.shards):
                total_s = int(tot[s])
                ns = total_s - int(self._last[s])
                if ns <= 0:
                    continue
                if ns > per:
                    self._wrap_lost += ns - per
                    self.rows_lost += ns - per
                    start = total_s - per
                else:
                    start = int(self._last[s])
                parts.append(s * per + (np.arange(start, total_s) % per))
                self._last[s] = total_s
            if not parts:
                return 0
            idx = np.concatenate(parts)
            order = np.argsort(arrs[0][idx], kind="stable")
            n = 0
            for k in idx[order]:
                row = {**({} if self.world is None
                          else {"world": self.world}),
                       "t": int(arrs[0][k]), "id": int(arrs[1][k]),
                       "host": int(arrs[2][k]),
                       "stage": SPAN_STAGE_NAMES.get(
                           int(arrs[3][k]), str(int(arrs[3][k]))),
                       "reason": LREASON_NAMES.get(
                           int(arrs[4][k]), str(int(arrs[4][k])))}
                self.rows.append(row)
                if self._f is not None:
                    self._f.write(json.dumps(row) + "\n")
                n += 1
            if self._f is not None:
                self._f.flush()
            return n

    def close(self):
        if self._f is not None and self._own:
            self._f.close()
        self._f = None

    def summary(self) -> dict:
        """Aggregate the drained spans into the `lineage` metrics
        section: span/ID counts, the drop-reason leaderboard, and how
        many traced packets reached delivery."""
        ids = set()
        delivered = set()
        drops = {}
        for r in self.rows:
            ids.add(r["id"])
            if r["reason"] != "none":
                drops[r["reason"]] = drops.get(r["reason"], 0) + 1
            elif r["stage"] == "deliver":
                delivered.add(r["id"])
        out = {"rate": self.rate, "n_assigned": self.n_assigned,
               "spans": len(self.rows), "spans_lost": self.rows_lost,
               "ids_seen": len(ids), "ids_delivered": len(delivered),
               "shards": self.shards or 1}
        if drops:
            out["drops"] = dict(sorted(drops.items(),
                                       key=lambda kv: -kv[1]))
        return out
