"""Runtime tracing & metrics: where wall-time goes inside a run.

`observe.py` watches the *simulated* world (heartbeats, pcap, drops);
this module watches the *simulator*.  A `Profiler` records host-side
phase spans (device launches, tracker/log drains, substrate syncs,
bridge RPCs), device->host transfer volume, and JIT compile events (via
JAX's monitoring hook), while a device-side `TraceCounters` block
(core/state.py) accumulates per-window aggregates -- packets exchanged,
peak inbox-slab occupancy -- inside the compiled step so they cost one
extra scalar fetch per drain, not per window.

Three artifacts per profiled run:

* ``trace.json`` -- Chrome trace-event format; open in chrome://tracing
  or https://ui.perfetto.dev.  Phase spans are duration events; device
  counter snapshots are counter tracks.
* ``metrics.json`` -- aggregates: per-phase count/total/p50/p95/max,
  transfer bytes, compile count, device counters.
* a one-screen summary table (``Profiler.summary_table()``).

The module-level `install()/current()` pair keeps hook sites cheap:
engine/observe/bridge call ``trace.current().span(...)``, which is a
no-op singleton unless a run installed a real Profiler.  Hot compiled
code never consults the profiler -- device-side counting is opted into
by putting a TraceCounters block on the state (``ensure_counters``),
the same present-or-None pattern as the capture and log rings.
"""

from __future__ import annotations

import json
import time

# JAX's backend-compile duration event (jax._src.dispatch
# BACKEND_COMPILE_EVENT): fires once per XLA compile, i.e. on every
# compile-cache miss.  Resolved lazily so a rename in a future JAX only
# degrades compile attribution, never breaks the profiler.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


# ---------------------------------------------------------------------------
# Null profiler: the installed-by-default no-op
# ---------------------------------------------------------------------------


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullProfiler:
    """Inactive profiler: every hook is a constant-time no-op."""

    enabled = False
    sync = False

    def span(self, name, **args):
        return _NULL_SPAN

    def transfer(self, nbytes, count=1):
        pass

    def compile_event(self, dur_s):
        pass

    def counter_sample(self, values):
        pass


_NULL = NullProfiler()
_active = _NULL
_hook_installed = False


def current():
    """The active profiler (a NullProfiler unless a run installed one)."""
    return _active


def install(prof):
    """Install `prof` as the process-wide active profiler (None/falsy
    restores the no-op).  Returns the now-active profiler."""
    global _active
    _active = prof if prof else _NULL
    if _active.enabled:
        _ensure_compile_hook()
    return _active


def _ensure_compile_hook():
    """Register ONE process-global JAX event listener that forwards
    backend-compile durations to whatever profiler is active.  JAX has no
    per-listener unregister, so the listener is permanent and dispatches
    through `current()`."""
    global _hook_installed
    if _hook_installed:
        return
    try:
        from jax._src import monitoring

        def _on_event(event, dur_s, **kw):
            p = _active
            if p.enabled and event == _COMPILE_EVENT:
                p.compile_event(dur_s)

        monitoring.register_event_duration_secs_listener(_on_event)
        _hook_installed = True
    except Exception:  # noqa: BLE001 - compile attribution is best-effort
        pass


# ---------------------------------------------------------------------------
# The real profiler
# ---------------------------------------------------------------------------


class _Span:
    __slots__ = ("prof", "name", "args", "t0")

    def __init__(self, prof, name, args):
        self.prof = prof
        self.name = name
        self.args = args
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        p = self.prof
        t0 = self.t0
        p.events.append((self.name, t0 - p.t0,
                         time.perf_counter() - t0, self.args))
        return False


class Profiler:
    """Host-side run profiler.

    sync=True makes the engine's chunk loop block_until_ready after each
    device launch so `device_step` spans measure execution rather than
    async dispatch (full --profile mode).  sync=False records spans
    without extra synchronization -- the cheap mode bench.py uses.
    """

    enabled = True

    def __init__(self, sync: bool = True):
        self.sync = sync
        self.t0 = time.perf_counter()
        self.events = []        # (name, t_rel_s, dur_s, args|None)
        self.transfer_bytes = 0
        self.transfer_count = 0
        self.compiles = []      # (t_rel_s, dur_s)
        self.counter_samples = []   # (t_rel_s, {name: value})
        self.kernelcount = None     # tools/kernelcount.py report|None
        self.extra_metrics = {}     # {name: number} via set_metric
        self.flight_rows = []       # drained FlightRecorder rows
        self.flight_summary = None  # aggregate `mesh` section|None

    # -- recording hooks ----------------------------------------------------

    def span(self, name, **args):
        """Context manager timing one phase occurrence."""
        return _Span(self, name, args or None)

    def transfer(self, nbytes, count=1):
        """Account a device->host transfer of `nbytes` over `count`
        fetch round trips."""
        self.transfer_bytes += int(nbytes)
        self.transfer_count += int(count)

    def compile_event(self, dur_s):
        self.compiles.append((time.perf_counter() - self.t0 - dur_s,
                              float(dur_s)))

    def counter_sample(self, values: dict):
        """Record a snapshot of (already-fetched) device counters."""
        self.counter_samples.append((time.perf_counter() - self.t0,
                                     dict(values)))

    def set_kernelcount(self, report: dict | None):
        """Attach a tools/kernelcount.py report: compiled HLO op/fusion
        counts per engine phase.  Rides metrics()/metrics.json so every
        profiled artifact carries the compiled-graph size alongside the
        wall times (benchdiff gates on it with --kernels)."""
        self.kernelcount = report

    def set_flight(self, rows: list, summary: dict | None):
        """Attach drained flight-recorder rows (FlightDrain.rows) + their
        aggregate.  The aggregate becomes the `mesh` section of
        metrics(); the rows become a simulated-time track (pid 2) in
        trace_events(), so the Chrome trace shows wall time and sim time
        side by side."""
        self.flight_rows = list(rows)
        self.flight_summary = summary

    def set_metric(self, name: str, value):
        """Attach one named scalar metric (e.g. a measured phase cost
        like stage_emissions_ms) so it rides metrics()/metrics.json and
        tools/benchdiff.py can gate on it across rounds.  None values
        are dropped (a failed measurement must not poison the JSON)."""
        if value is not None:
            self.extra_metrics[name] = value

    # -- aggregation --------------------------------------------------------

    def metrics(self) -> dict:
        """Aggregate recorded data: per-phase percentiles + totals."""
        by_phase = {}
        for name, _t, dur, _a in self.events:
            by_phase.setdefault(name, []).append(dur)
        phases = {}
        for name, durs in sorted(by_phase.items()):
            durs = sorted(durs)
            phases[name] = {
                "count": len(durs),
                "total_s": round(sum(durs), 6),
                "p50_ms": round(_pct(durs, 50) * 1e3, 3),
                "p95_ms": round(_pct(durs, 95) * 1e3, 3),
                "max_ms": round(durs[-1] * 1e3, 3),
            }
        out = {
            "wall_s": round(time.perf_counter() - self.t0, 3),
            "phases": phases,
            "transfers": {"bytes": self.transfer_bytes,
                          "count": self.transfer_count},
            "compile": {"count": len(self.compiles),
                        "total_s": round(sum(d for _t, d in self.compiles),
                                         3)},
            # Flat aliases for benchdiff gating (tools/benchdiff.py):
            # "compiles" is a graph property (0-tolerance -- a new
            # compile in a sweep means a shape bucket broke), while
            # "compile_ms" is machine-bound wall time.
            "compiles": len(self.compiles),
            "compile_ms": round(
                sum(d for _t, d in self.compiles) * 1e3, 1),
        }
        if self.counter_samples:
            out["device_counters"] = self.counter_samples[-1][1]
        if self.kernelcount is not None:
            out["kernelcount"] = self.kernelcount
        if self.flight_summary is not None:
            out["mesh"] = self.flight_summary
        out.update(self.extra_metrics)
        return out

    # -- artifacts ----------------------------------------------------------

    def trace_events(self) -> list:
        """The run as Chrome trace-event dicts (ts/dur in microseconds)."""
        tids = {}

        def tid(name):
            return tids.setdefault(name, len(tids) + 1)

        evs = []
        for name, t, dur, args in sorted(self.events, key=lambda e: e[1]):
            e = {"name": name, "cat": "run", "ph": "X", "pid": 1,
                 "tid": tid(name), "ts": round(t * 1e6, 3),
                 "dur": round(dur * 1e6, 3)}
            if args:
                e["args"] = args
            evs.append(e)
        for t, dur in self.compiles:
            evs.append({"name": "jit_compile", "cat": "jit", "ph": "X",
                        "pid": 1, "tid": tid("jit_compile"),
                        "ts": round(t * 1e6, 3),
                        "dur": round(dur * 1e6, 3)})
        for t, values in self.counter_samples:
            for k, v in values.items():
                evs.append({"name": k, "cat": "counters", "ph": "C",
                            "pid": 1, "ts": round(t * 1e6, 3),
                            "args": {k: v}})
        meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": i,
                 "args": {"name": n}} for n, i in tids.items()]
        if self.flight_rows:
            # Simulated-time track: pid 2's clock is SIM nanoseconds
            # (rendered as trace microseconds), one span per window plus
            # events/routed counter tracks -- wall time (pid 1) and sim
            # time (pid 2) side by side in the same viewer.
            meta.append({"name": "process_name", "ph": "M", "pid": 2,
                         "args": {"name": "simulated time (windows)"}})
            meta.append({"name": "thread_name", "ph": "M", "pid": 2,
                         "tid": 1, "args": {"name": "window"}})
            for r in self.flight_rows:
                ts = round(r["t_start"] / 1e3, 3)
                dur = round(max(r["t_end"] - r["t_start"], 1) / 1e3, 3)
                evs.append({"name": "window", "cat": "sim", "ph": "X",
                            "pid": 2, "tid": 1, "ts": ts, "dur": dur,
                            "args": {k: r[k] for k in
                                     ("window", "steps", "events",
                                      "routed", "delivered", "dropped",
                                      "killed")}})
                for k in ("events", "routed"):
                    evs.append({"name": k, "cat": "sim", "ph": "C",
                                "pid": 2, "ts": ts, "args": {k: r[k]}})
        return meta + evs

    def write_trace(self, path: str):
        with open(path, "w") as f:
            json.dump({"traceEvents": self.trace_events(),
                       "displayTimeUnit": "ms"}, f)

    def write_metrics(self, path: str, extra: dict | None = None):
        m = self.metrics()
        if extra:
            m.update(extra)
        with open(path, "w") as f:
            json.dump(m, f, indent=2)
        return m

    def summary_table(self) -> str:
        """One-screen end-of-run phase breakdown."""
        m = self.metrics()
        lines = [f"{'phase':<16s} {'count':>7s} {'total_s':>9s} "
                 f"{'p50_ms':>9s} {'p95_ms':>9s} {'max_ms':>9s}"]
        for name, p in m["phases"].items():
            lines.append(f"{name:<16s} {p['count']:>7d} "
                         f"{p['total_s']:>9.3f} {p['p50_ms']:>9.3f} "
                         f"{p['p95_ms']:>9.3f} {p['max_ms']:>9.3f}")
        t = m["transfers"]
        c = m["compile"]
        lines.append(f"transfers: {t['bytes']} bytes in {t['count']} "
                     f"fetches; jit compiles: {c['count']} "
                     f"({c['total_s']:.1f}s); wall: {m['wall_s']:.3f}s")
        dc = m.get("device_counters")
        if dc:
            lines.append("device: " + ", ".join(
                f"{k}={v}" for k, v in dc.items()))
        return "\n".join(lines)


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an ascending list."""
    if not sorted_vals:
        return 0.0
    i = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[i]


# ---------------------------------------------------------------------------
# Device-counter helpers (the TraceCounters block on SimState)
# ---------------------------------------------------------------------------


def ensure_counters(state):
    """Return `state` with a TraceCounters block installed (idempotent).
    Changes the state pytree structure, so jitted engine calls recompile
    once for the counted variant."""
    if state.tr is None:
        from .core.state import make_trace_counters
        state = state.replace(tr=make_trace_counters())
    return state


def fetch_counters(state, profiler=None) -> dict:
    """ONE device->host fetch of the telemetry scalars + counter block,
    recorded as a counter sample (and a transfer) on `profiler` (default:
    the active one).  Safe to call whether or not counters are installed.
    """
    import jax

    vals = [state.n_steps, state.n_windows, state.n_events]
    names = ["microsteps", "windows", "events"]
    if state.tr is not None:
        vals += [state.tr.exchanges, state.tr.pkts_exchanged,
                 state.tr.occ_max]
        names += ["exchanges", "pkts_exchanged", "inbox_occ_max"]
    if getattr(state, "nm", None) is not None:
        import jax.numpy as _jnp
        vals += [state.nm.cursor, state.nm.killed,
                 _jnp.sum(state.nm.host_up == 0)]
        names += ["netem_events_applied", "netem_killed",
                  "netem_hosts_down"]
    fetched = jax.device_get(vals)
    out = {n: int(v) for n, v in zip(names, fetched)}
    if state.tr is not None:
        ki = state.inbox.capacity // state.hosts.num_hosts
        out["inbox_occ_frac"] = round(out["inbox_occ_max"] / max(ki, 1), 4)
    p = profiler if profiler is not None else _active
    p.transfer(sum(getattr(v, "nbytes", 8) for v in fetched), count=1)
    p.counter_sample(out)
    return out


# ---------------------------------------------------------------------------
# Flight recorder (the FlightRecorder ring on SimState; core/state.py)
# ---------------------------------------------------------------------------


def ensure_flight_recorder(state, capacity: int = 4096, shards: int = 1):
    """Return `state` with a per-window FlightRecorder ring installed
    (idempotent).  `shards` sizes the src->dst exchange matrices and
    must match the device count of a mesh run (1 for single-device);
    the host count and pool capacity must divide it so the logical
    shard of a host is well defined."""
    if state.fr is not None:
        return state
    from .core.state import make_flight_recorder
    h = int(state.hosts.num_hosts)
    if shards < 1 or h % shards or int(state.pool.capacity) % shards:
        raise ValueError(
            f"ensure_flight_recorder: shards={shards} must divide the "
            f"host count ({h}) and pool capacity "
            f"({int(state.pool.capacity)}); pad the world to the mesh "
            f"first (parallel.pad_world_to_mesh)")
    return state.replace(fr=make_flight_recorder(capacity, shards))


class FlightDrain:
    """Host-side drain of the flight recorder: fetches new rows at chunk
    boundaries (one scalar probe + one bulk fetch only when rows are
    new -- riding the existing sync points, no extra per-window syncs),
    appends them to ``windows.jsonl`` when a path is given, and keeps
    them for Profiler.set_flight / aggregation.

    Ring wrap between drains loses the oldest rows; lifetime totals are
    still exact because the recorder accumulates wrap-proof sums on the
    device (`ex_*_sum`) -- the drain reports `rows_lost` so a summary
    reader knows row-derived aggregates are partial."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.rows = []
        self.rows_lost = 0
        self.shards = None      # learned from the ring at first drain
        self.capacity = None
        self._last = 0
        self._f = open(path, "w") if path else None

    def drain(self, state, profiler=None) -> int:
        """Fetch rows appended since the last drain; returns how many."""
        fr = getattr(state, "fr", None)
        if fr is None:
            return 0
        import jax
        p = profiler if profiler is not None else _active
        with p.span("flight_drain"):
            total = int(jax.device_get(fr.total))
            p.transfer(8, count=1)
            new = total - self._last
            if new <= 0:
                return 0
            self.shards = fr.n_shards
            self.capacity = c = fr.capacity
            arrs = jax.device_get((fr.win_start, fr.win_end, fr.steps,
                                   fr.events, fr.routed, fr.delivered,
                                   fr.dropped, fr.killed, fr.ex_cnt,
                                   fr.ex_bytes))
            p.transfer(sum(a.nbytes for a in arrs), count=1)
            if new > c:
                self.rows_lost += new - c
                start = total - c
            else:
                start = self._last
            ws, we, steps, ev, rt, dl, dp, kl, xc, xb = arrs
            for w in range(start, total):
                k = w % c
                row = {"window": w,
                       "t_start": int(ws[k]), "t_end": int(we[k]),
                       "steps": int(steps[k]), "events": int(ev[k]),
                       "routed": int(rt[k]), "delivered": int(dl[k]),
                       "dropped": int(dp[k]), "killed": int(kl[k]),
                       "ex_cnt": xc[k].tolist(),
                       "ex_bytes": xb[k].tolist()}
                self.rows.append(row)
                if self._f is not None:
                    self._f.write(json.dumps(row) + "\n")
            if self._f is not None:
                self._f.flush()
            self._last = total
            return new

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    def summary(self, state=None, n_devices: int = 1) -> dict:
        """Aggregate the drained rows into the `mesh` metrics section.
        Pass the final state to include the device-side wrap-proof
        exchange totals (exact even when rows were lost to wrap)."""
        d = self.shards or 1
        agg = {k: sum(r[k] for r in self.rows)
               for k in ("steps", "events", "routed", "delivered",
                         "dropped", "killed")}
        mat_c = [[0] * d for _ in range(d)]
        mat_b = [[0] * d for _ in range(d)]
        for r in self.rows:
            for i in range(d):
                for j in range(d):
                    mat_c[i][j] += r["ex_cnt"][i][j]
                    mat_b[i][j] += r["ex_bytes"][i][j]
        if state is not None and getattr(state, "fr", None) is not None:
            import jax
            mat_c, mat_b = (a.tolist() for a in jax.device_get(
                (state.fr.ex_cnt_sum, state.fr.ex_bytes_sum)))
        out = {
            "n_devices": n_devices,
            "recorder": {"capacity": self.capacity, "shards": d},
            "windows": self._last,
            "rows_lost": self.rows_lost,
        }
        out.update(agg)
        out["exchange"] = {
            "movers": sum(map(sum, mat_c)),
            "bytes": sum(map(sum, mat_b)),
            "matrix_movers": mat_c,
            "matrix_bytes": mat_b,
        }
        if self.rows:
            sim_s = (self.rows[-1]["t_end"]
                     - self.rows[0]["t_start"]) / 1e9
            if sim_s > 0:
                out["windows_per_sim_s"] = round(len(self.rows) / sim_s, 3)
        return out
