"""Resident run server: a crash-safe multi-tenant simulation service.

The batch CLI pays the AOT compile price on every invocation; the
server pays it once.  `shadow1-tpu serve` turns the process into a
resident service that warms the standard shape buckets in the
background, accepts scenario requests over a local Unix socket
(protocol.py), schedules them for warm-graph affinity (requests whose
shape hint matches the last-executed one run first, so consecutive
requests hit the already-compiled graph), and runs every request under
the existing supervision stack: per-request data directory
(``DATA/runs/<id>/``), checkpointing, watchdog, the invariant sentinel,
and the full degradation ladder (supervise.Supervisor).

Crash safety is write-ahead: every lifecycle transition is appended and
fsync'd to ``DATA/server/journal.jsonl`` BEFORE the client sees the
acknowledgement, and each request's full record is mirrored atomically
to ``DATA/runs/<id>/request.json``.  A SIGKILL'd server therefore
loses nothing: a restart with ``serve --auto-resume`` folds the
journal, re-admits every queued / running / parked request, and each
re-admitted run auto-resumes from its newest checkpoint -- bitwise
identical to an uninterrupted run (the same trim-and-append contract
single-run --auto-resume already keeps; tools/faultdrill.py's `server`
drill SIGKILLs a loaded server and byte-compares every windows.jsonl
against solo references).

Admission control is loud: a full queue is refused with rc 2 naming
the current depth and the --queue-limit knob; a per-request --timeout
that expires (queued or mid-run) is refused with rc 2 naming
--timeout.  SIGTERM drains: stop admitting, ask every in-flight run to
checkpoint and park at its next launch boundary, journal the park, and
exit 0 -- parked runs re-enter the queue on the next --auto-resume
start.  Exit codes ride supervise.py's unified table end-to-end: the
rc a run would exit the CLI with is the rc `submit --wait` /
`status --wait` exits with.

Observability (Servescope; docs/observability.md "Servescope"): every
request finishes with ``runs/<id>/request_metrics.json`` (queue-wait,
affinity hit/miss, compile count + wall, device-step and host-drain
wall, ``host_drain_overlap_pct``, events/s, park/resume/recovery
counts) assembled from a per-request host-side Profiler
(``sync=False, counters=False`` -- the state pytree is untouched, so a
served run stays byte-identical to an unobserved one); a server-wide
counter registry (`ServerMetrics`) is snapshotted atomically to
``server/metrics.json`` on a cadence and served live by the ``stats``
protocol op; and every lifecycle transition appends one span row to
``server/schedule.jsonl``, which is REGENERATED from the journal on
every start -- the journal is ground truth, so the scheduler trace
survives a SIGKILL with no lost transitions.

See docs/robustness.md "Run server".
"""

from __future__ import annotations

import collections
import glob as glob_mod
import json
import os
import queue as queue_mod
import socket
import sys
import threading
import time
import traceback

from . import protocol
from .core.simtime import SIMTIME_ONE_SECOND
from .supervise import RC_FAILED, RC_INVARIANT, RC_OK, RC_USAGE

SEC = SIMTIME_ONE_SECOND

JOURNAL_VERSION = 1

# Spec keys that determine the compiled graph's ShapeKey for a config
# request (world size and blocks, never seeds or stop times): the
# scheduler's warm-graph affinity hint.  Builder requests hash the
# builder name plus its shape-determining kwargs the same way.
_SHAPE_SPEC_KEYS = (
    "config", "sock_slots", "pool_slab", "tcp_congestion_control",
    "interface_qdisc", "pcap", "pcap_ring", "log_level", "log_ring",
    "bucket", "devices", "scope", "trace_packets", "flight_rows",
    "digest_every", "digest_rows", "profile", "worlds", "sweep")


def _shape_hint(kind: str, spec: dict) -> str:
    if kind == "config":
        return json.dumps({k: spec.get(k) for k in _SHAPE_SPEC_KEYS},
                          sort_keys=True)
    if kind == "builder":
        kw = dict(spec.get("kwargs") or {})
        # Seeds and stop times change the trajectory, never the shapes.
        kw.pop("seed", None)
        kw.pop("stop_time", None)
        return json.dumps({"builder": spec.get("name"), **kw},
                          sort_keys=True)
    return "replay"


class RunControl:
    """The server's handle into a running request: `request("park")` /
    `request("cancel")` is polled by the run loop at launch boundaries
    (cli.run_config / sim._run_checkpointed), and a per-request
    deadline surfaces as a polled "timeout".  The loop records how it
    stopped in `outcome` ("parked" | "cancelled" | "timed_out")."""

    def __init__(self, deadline: float | None = None):
        self._lock = threading.Lock()
        self._action = None
        self.deadline = deadline  # time.monotonic() value, or None
        self.outcome = None

    def request(self, action: str) -> None:
        with self._lock:
            # cancel outranks park outranks nothing; never downgrade.
            if self._action != "cancel":
                self._action = action

    def poll(self) -> str | None:
        with self._lock:
            act = self._action
        if act is not None:
            return act
        if self.deadline is not None and time.monotonic() >= self.deadline:
            return "timeout"
        return None


class Request:
    """One submitted scenario: spec, lifecycle state, and its evidence
    trail.  Mutation happens under the server lock; `record()` is the
    JSON view status reports and request.json mirrors."""

    def __init__(self, rid: str, kind: str, spec: dict,
                 timeout: float | None = None,
                 submitted: float | None = None):
        self.id = rid
        self.kind = kind
        self.spec = spec
        self.timeout = float(timeout) if timeout else None
        self.submitted = submitted if submitted is not None else time.time()
        self.state = protocol.QUEUED
        self.rc = None
        self.trail = ["submitted"]
        self.restarts = 0        # server lives that re-admitted this run
        self.error = None
        self.crash = None        # {"path": ..., "class": ...}
        self.summary = None
        self.shape_hint = _shape_hint(kind, spec)
        self.control = None      # RunControl while running
        self.subscribers = []    # list[queue.Queue] of live streams
        # Servescope scheduler stamps (per-request accounting).
        self.enqueued_at = self.submitted  # when it last entered the queue
        self.queue_wait = 0.0    # accumulated queued seconds, ALL lives
        self.started = None      # wall time the last execution started
        self.finished = None     # wall time the run settled
        self.worker = None       # worker index that picked it
        self.affinity_hit = None  # shape hint matched the warm graph
        self.pick_reason = None  # "affinity" (jumped FIFO) | "fifo"
        self.parks = 0           # server-drain parks taken
        self.resumes = 0         # checkpoint resumes (emit "resumed")
        self.recoveries = 0      # ladder rungs taken (emit "recovered")
        self.quarantines = 0     # worlds quarantined (emit "quarantined")
        self.profiler = None     # per-request trace.Profiler while running

    def queue_wait_s(self) -> float:
        """Accumulated queue-wait over every server life, plus the
        wait-so-far when the request is still queued."""
        w = self.queue_wait
        if self.state == protocol.QUEUED and self.enqueued_at is not None:
            w += max(0.0, time.time() - self.enqueued_at)
        return round(w, 6)

    def record(self, run_dir: str) -> dict:
        return {
            "id": self.id, "kind": self.kind, "state": self.state,
            "rc": self.rc, "dir": run_dir, "spec": self.spec,
            "timeout": self.timeout, "submitted": self.submitted,
            "restarts": self.restarts, "trail": list(self.trail),
            "error": self.error, "crash": self.crash,
            "summary": self.summary,
            "shape_hint": self.shape_hint,
            "queue_wait_s": self.queue_wait_s(),
        }


class ServerMetrics:
    """Server-wide counter registry (Servescope tentpole 2): requests
    by state/kind/rc, queue high-water, per-worker busy time, affinity
    hit rate, journal fsync count + latency, recovery/readmit counts,
    and a recent-completions ring.  All mutation is under one small
    lock; `snapshot()` returns a JSON-able view the stats op and the
    server/metrics.json cadence writer share.  Host-side bookkeeping
    only -- nothing here touches a run's state pytree."""

    RECENT = 16

    def __init__(self, workers: int):
        self._lock = threading.Lock()
        self.t0 = time.time()
        self.submitted = 0
        self.by_state = {}       # terminal outcomes: state -> count
        self.by_kind = {}        # admissions: kind -> count
        self.by_rc = {}          # terminal outcomes: rc -> count
        self.readmitted = 0
        self.parked = 0
        self.resumes = 0
        self.recoveries = 0
        self.quarantines = 0
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.queue_high_water = 0
        self.journal_events = 0
        self.fsyncs = 0
        self.fsync_s = 0.0
        self.workers = [{"busy_s": 0.0, "runs": 0, "current": None,
                         "since": None} for _ in range(workers)]
        self.recent = collections.deque(maxlen=self.RECENT)

    def submit(self, kind: str, depth: int) -> None:
        with self._lock:
            self.submitted += 1
            self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
            self.queue_high_water = max(self.queue_high_water, depth)

    def pick(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.affinity_hits += 1
            else:
                self.affinity_misses += 1

    def journal(self, fsync_s: float) -> None:
        with self._lock:
            self.journal_events += 1
            self.fsyncs += 1
            self.fsync_s += fsync_s

    def worker_start(self, i: int, rid: str) -> None:
        with self._lock:
            w = self.workers[i]
            w["current"], w["since"] = rid, time.time()

    def worker_done(self, i: int) -> None:
        with self._lock:
            w = self.workers[i]
            if w["since"] is not None:
                w["busy_s"] += time.time() - w["since"]
            w["runs"] += 1
            w["current"], w["since"] = None, None

    def event(self, name: str, n: int = 1) -> None:
        """Bump a named lifecycle counter (readmitted / parked /
        resumes / recoveries)."""
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def settle(self, req: "Request") -> None:
        """Account one terminal outcome and ring-buffer it."""
        with self._lock:
            self.by_state[req.state] = self.by_state.get(req.state, 0) + 1
            key = str(req.rc)
            self.by_rc[key] = self.by_rc.get(key, 0) + 1
            wall = None
            if req.started is not None and req.finished is not None:
                wall = round(req.finished - req.started, 3)
            self.recent.append({
                "id": req.id, "kind": req.kind, "state": req.state,
                "rc": req.rc, "wall_s": wall,
                "queue_wait_s": req.queue_wait_s(),
                "affinity_hit": req.affinity_hit})

    def snapshot(self) -> dict:
        now = time.time()
        with self._lock:
            hits, misses = self.affinity_hits, self.affinity_misses
            picks = hits + misses
            return {
                "uptime_s": round(now - self.t0, 3),
                "requests": {
                    "submitted": self.submitted,
                    "by_state": dict(self.by_state),
                    "by_kind": dict(self.by_kind),
                    "by_rc": dict(self.by_rc)},
                "affinity": {
                    "hits": hits, "misses": misses,
                    "hit_rate": round(hits / picks, 4) if picks else None},
                "journal": {
                    "events": self.journal_events,
                    "fsyncs": self.fsyncs,
                    "fsync_ms_total": round(self.fsync_s * 1e3, 3),
                    "fsync_ms_mean": round(
                        self.fsync_s / self.fsyncs * 1e3, 3)
                    if self.fsyncs else None},
                "workers": [{
                    "id": i, "busy_s": round(w["busy_s"], 3),
                    "runs": w["runs"], "current": w["current"],
                    "busy_for_s": round(now - w["since"], 3)
                    if w["since"] is not None else None}
                    for i, w in enumerate(self.workers)],
                "recovery": {
                    "readmitted": self.readmitted,
                    "parked": self.parked,
                    "resumes": self.resumes,
                    "recoveries": self.recoveries,
                    "quarantines": self.quarantines},
                "recent": list(self.recent),
            }


class Server:
    """The resident service.  `start()` recovers the journal, binds the
    socket, and launches the accept + worker threads; `wait()` blocks
    until `shutdown()` (a protocol shutdown op, SIGTERM, or a test)
    completes.  Everything is in-process and thread-based: requests
    run on worker threads inside this process, sharing the warmed
    compile cache -- the whole point of residency."""

    def __init__(self, data_dir: str, *, queue_limit: int = 8,
                 workers: int = 1, checkpoint_every: float = 2.0,
                 watchdog: float | None = None, auto_resume: bool = False,
                 metrics_every: float = 2.0, quiet: bool = True,
                 max_lanes: int = 4):
        self.data_dir = data_dir
        self.sdir = os.path.join(data_dir, "server")
        self.runs_dir = os.path.join(data_dir, "runs")
        self.sock_path = protocol.default_socket(data_dir)
        self.queue_limit = int(queue_limit)
        self.workers = max(1, int(workers))
        self.max_lanes = max(1, int(max_lanes))
        self.checkpoint_every = float(checkpoint_every)
        self.watchdog = watchdog
        self.auto_resume = bool(auto_resume)
        self.metrics_every = float(metrics_every)
        self.quiet = quiet
        self.warmed = None       # shapes.warm_buckets records, if warmed
        self.metrics = ServerMetrics(self.workers)

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._reqs: dict[str, Request] = {}
        self._queue: list[str] = []
        self._last_hint = None
        self._counter = 1
        self._draining = False
        self._stopping = False
        self._done = threading.Event()
        self._journal = None
        self._schedule = None    # server/schedule.jsonl live handle
        self._listener = None
        self._worker_threads = []
        self._readmitted = []

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Server":
        os.makedirs(self.sdir, exist_ok=True)
        os.makedirs(self.runs_dir, exist_ok=True)
        self._recover()
        jpath = os.path.join(self.sdir, "journal.jsonl")
        self._journal = open(jpath, "a", encoding="utf-8")
        # schedule.jsonl is DERIVED: regenerate it from the fsync'd
        # journal on every start, so a SIGKILL never loses a scheduler
        # transition, then keep the handle open for live appends.
        self._schedule = open(os.path.join(self.sdir, "schedule.jsonl"),
                              "w", encoding="utf-8")
        if os.path.exists(jpath):
            with open(jpath, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line from a killed writer
                    self._append_schedule(ev)
        for req in self._readmitted:
            if req.state == protocol.QUEUED:
                # Journal the re-admission so a second crash still
                # counts every restart in the trail.  Stranded (parked,
                # no --auto-resume) requests are only re-mirrored.
                self._log({"ev": "readmit", "id": req.id,
                           "t": req.enqueued_at})
            self._sync_request(req)
        self._readmitted = []
        if self._readmit_count:
            self.metrics.event("readmitted", self._readmit_count)

        # A stale socket file from a killed server blocks bind(); it is
        # only stale if nobody answers on it.
        if os.path.exists(self.sock_path):
            try:
                protocol.request(self.sock_path, {"op": "ping"},
                                 timeout=1.0)
                raise RuntimeError(
                    f"a run server is already listening on "
                    f"{self.sock_path}")
            except protocol.ServerUnavailable:
                os.unlink(self.sock_path)
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.bind(self.sock_path)
        s.listen(64)
        self._listener = s
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="shadow1-serve-accept")
        t.start()
        for i in range(self.workers):
            wt = threading.Thread(target=self._worker_loop, args=(i,),
                                  daemon=True,
                                  name=f"shadow1-serve-worker-{i}")
            wt.start()
            self._worker_threads.append(wt)
        self._write_metrics_snapshot()
        threading.Thread(target=self._metrics_loop, daemon=True,
                         name="shadow1-serve-metrics").start()
        self._say(f"serve: listening on {self.sock_path} "
                  f"(queue-limit {self.queue_limit}, "
                  f"workers {self.workers}"
                  + (f", re-admitted {self._readmit_count} run(s)"
                     if self._readmit_count else "") + ")")
        return self

    def wait(self) -> None:
        self._done.wait()

    def shutdown(self, drain: bool = True) -> None:
        """Stop the service.  `drain=True` (the SIGTERM path) asks every
        in-flight run to checkpoint and park at its next launch
        boundary; `drain=False` cancels them.  Queued requests stay
        journaled as queued either way and re-admit on the next
        --auto-resume start."""
        with self._lock:
            if self._done.is_set() or self._draining:
                return
            self._draining = True
            running = [r for r in self._reqs.values()
                       if r.state == protocol.RUNNING
                       and r.control is not None]
        if running:
            self._say(f"serve: {'parking' if drain else 'cancelling'} "
                      f"{len(running)} in-flight run(s)")
        for r in running:
            r.control.request("park" if drain else "cancel")
        # Wait for the workers to park/cancel their current request.
        while True:
            with self._lock:
                if not any(r.state == protocol.RUNNING
                           for r in self._reqs.values()):
                    break
            time.sleep(0.05)
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
            self._log({"ev": "drain", "parked": [r.id for r in running],
                       "t": time.time()})
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
        for t in self._worker_threads:
            t.join(timeout=10)
        self._write_metrics_snapshot()
        with self._lock:
            self._journal.close()
            if self._schedule is not None:
                self._schedule.close()
                self._schedule = None
        self._say("serve: stopped")
        self._done.set()

    # -- journal + recovery ----------------------------------------------

    def _log(self, ev: dict) -> None:
        """Write-ahead append: the line is on disk (fsync) before any
        caller-visible effect of the event."""
        with self._lock:
            self._journal.write(json.dumps(ev, sort_keys=True) + "\n")
            self._journal.flush()
            t0 = time.perf_counter()
            os.fsync(self._journal.fileno())
            self.metrics.journal(time.perf_counter() - t0)
            self._append_schedule(ev)

    _SCHEDULE_STATE = {
        "submit": protocol.QUEUED, "start": protocol.RUNNING,
        "park": protocol.PARKED, "cancel": protocol.CANCELLED,
        "readmit": protocol.QUEUED}

    def _schedule_row(self, ev: dict) -> dict | None:
        """Map one journal event to one schedule.jsonl span row: the
        lifecycle transition plus the scheduler context (shape hint,
        worker id, affinity hit, pick reason, queue depth at pick)."""
        name = ev.get("ev")
        if name == "drain":
            return {"t": ev.get("t"), "ev": "drain", "id": None,
                    "parked": ev.get("parked")}
        rid = ev.get("id")
        if rid is None or (name not in self._SCHEDULE_STATE
                           and name != "finish"):
            return None
        state = ev.get("state") if name == "finish" \
            else self._SCHEDULE_STATE[name]
        row = {"t": ev.get("t"), "ev": name, "id": rid, "state": state}
        req = self._reqs.get(rid)
        if req is not None:
            row["kind"] = req.kind
            row["shape_hint"] = req.shape_hint
        for k in ("worker", "hit", "reason", "depth", "rc"):
            if k in ev:
                row[k] = ev[k]
        return row

    def _append_schedule(self, ev: dict) -> None:
        """Append the schedule row for a journal event (call under the
        lock).  flush but no fsync: the journal is ground truth and the
        whole file is regenerated from it on start."""
        if self._schedule is None:
            return
        row = self._schedule_row(ev)
        if row is None:
            return
        self._schedule.write(json.dumps(row, sort_keys=True) + "\n")
        self._schedule.flush()

    _readmit_count = 0

    def _recover(self) -> None:
        """Fold the journal into request records.  Non-terminal requests
        (queued, running, parked) re-enter the queue under
        --auto-resume; without it they are parked in place with a loud
        trail note so `status` explains how to finish them."""
        path = os.path.join(self.sdir, "journal.jsonl")
        if not os.path.exists(path):
            return
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line from a killed writer
                self._fold(ev)
        readmit = [r for r in self._reqs.values()
                   if r.state not in protocol.TERMINAL]
        for req in sorted(readmit, key=lambda r: r.id):
            was = req.state
            if self.auto_resume:
                req.restarts += 1
                req.trail.append(
                    f"readmitted (was {was} when the server stopped)")
                req.state = protocol.QUEUED
                # Queue-wait accumulates across server lives: close the
                # open queued segment (includes the dead-server gap --
                # the client was waiting the whole time) and start a new
                # one at re-admission.
                now = time.time()
                if req.enqueued_at is not None:
                    req.queue_wait += max(0.0, now - req.enqueued_at)
                req.enqueued_at = now
                self._queue.append(req.id)
                self._readmitted.append(req)
            else:
                req.trail.append(
                    f"stranded {was} by a server stop; restart with "
                    f"`serve --auto-resume` to re-admit it")
                req.state = protocol.PARKED
                self._readmitted.append(req)  # re-journal + re-mirror
        self._readmit_count = len(self._queue)

    def _fold(self, ev: dict) -> None:
        t = ev.get("ev")
        rid = ev.get("id")
        if t == "submit":
            req = Request(rid, ev.get("kind"), ev.get("spec") or {},
                          timeout=ev.get("timeout"),
                          submitted=ev.get("t"))
            self._reqs[rid] = req
            n = self._id_num(rid)
            if n is not None and n >= self._counter:
                self._counter = n + 1
            return
        req = self._reqs.get(rid) if rid else None
        if req is None:
            return
        if t == "start":
            req.state = protocol.RUNNING
            req.trail.append("started")
            ts = ev.get("t")
            if ts is not None:
                if req.enqueued_at is not None:
                    req.queue_wait += max(0.0, ts - req.enqueued_at)
                req.enqueued_at = None
                req.started = ts
            req.worker = ev.get("worker", req.worker)
            if "hit" in ev:
                req.affinity_hit = ev["hit"]
            if "reason" in ev:
                req.pick_reason = ev["reason"]
        elif t == "finish":
            req.state = ev.get("state", protocol.FAILED)
            req.rc = ev.get("rc")
            req.trail.append(f"finished rc {req.rc}")
            req.finished = ev.get("t")
            # A queued-timeout refusal finishes without a start: the
            # open queued segment still counts as wait.
            if req.finished is not None and req.enqueued_at is not None:
                req.queue_wait += max(
                    0.0, req.finished - req.enqueued_at)
            req.enqueued_at = None
        elif t == "park":
            req.state = protocol.PARKED
            req.trail.append("parked (server drain)")
            req.parks += 1
            req.enqueued_at = None
        elif t == "cancel":
            req.state = protocol.CANCELLED
            req.rc = RC_FAILED
            req.trail.append("cancelled")
            req.finished = ev.get("t")
            if req.finished is not None and req.enqueued_at is not None:
                req.queue_wait += max(
                    0.0, req.finished - req.enqueued_at)
            req.enqueued_at = None
        elif t == "readmit":
            req.restarts += 1
            req.state = protocol.QUEUED
            req.trail.append("readmitted")
            ts = ev.get("t")
            if ts is not None:
                if req.enqueued_at is not None:
                    req.queue_wait += max(0.0, ts - req.enqueued_at)
                req.enqueued_at = ts

    @staticmethod
    def _id_num(rid):
        try:
            return int(str(rid).lstrip("r"))
        except ValueError:
            return None

    def _sync_request(self, req: Request) -> None:
        """Mirror the full record atomically to runs/<id>/request.json
        (tmp + rename -- never torn, like every other state file)."""
        d = os.path.join(self.runs_dir, req.id)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "request.json")
        tmp = path + ".tmp"
        with self._lock:
            rec = req.record(d)
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    # -- socket side ------------------------------------------------------

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True,
                             name="shadow1-serve-conn").start()

    def _handle(self, conn):
        rf = conn.makefile("r", encoding="utf-8")
        wf = conn.makefile("w", encoding="utf-8")
        try:
            msg = protocol.recv(rf)
            if msg is None:
                return
            op = msg.get("op")
            if op == "ping":
                with self._lock:
                    protocol.send(wf, {
                        "ok": True,
                        "version": protocol.PROTOCOL_VERSION,
                        "pid": os.getpid(),
                        "queue_depth": len(self._queue),
                        "queue_limit": self.queue_limit,
                        "draining": self._draining,
                        "warmed": bool(self.warmed)})
            elif op == "submit":
                self._op_submit(msg, wf)
            elif op == "status":
                self._op_status(msg, wf)
            elif op == "stats":
                protocol.send(wf, {"ok": True, "stats": self._stats()})
            elif op == "cancel":
                self._op_cancel(msg, wf)
            elif op == "shutdown":
                protocol.send(wf, {"ok": True})
                threading.Thread(
                    target=self.shutdown,
                    kwargs={"drain": bool(msg.get("drain", True))},
                    daemon=True).start()
            else:
                protocol.send(wf, {"ok": False, "rc": RC_USAGE,
                                   "error": f"unknown op {op!r}"})
        except (BrokenPipeError, ConnectionResetError, OSError,
                json.JSONDecodeError, ValueError):
            pass  # client went away or spoke garbage; drop the stream
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _op_submit(self, msg, wf):
        kind = msg.get("kind")
        spec = msg.get("spec") or {}
        sub = None
        with self._lock:
            err = self._admission_error(kind, spec)
            if err is not None:
                protocol.send(wf, {"ok": False, "rc": RC_USAGE,
                                   "error": err})
                return
            rid = f"r{self._counter:04d}"
            self._counter += 1
            req = Request(rid, kind, spec, timeout=msg.get("timeout"))
            # Write-ahead: the submit is durable BEFORE the client sees
            # the id, so an ack'd request survives any kill.
            self._log({"ev": "submit", "id": rid, "kind": kind,
                       "spec": spec, "timeout": req.timeout,
                       "t": req.submitted})
            self._reqs[rid] = req
            self._queue.append(rid)
            self.metrics.submit(kind, len(self._queue))
            if msg.get("wait"):
                sub = queue_mod.Queue()
                req.subscribers.append(sub)
            self._cond.notify_all()
        self._sync_request(req)
        protocol.send(wf, {"ok": True, "id": rid})
        if sub is not None:
            self._pump(req, sub, wf,
                       progress=bool(msg.get("progress", True)))

    def _admission_error(self, kind, spec):
        """Admission control (call under the lock): loud rc-2 refusals
        that name the knob, per docs/robustness.md."""
        if self._draining or self._stopping:
            return ("server is draining (SIGTERM received): not "
                    "admitting new requests; in-flight runs are being "
                    "checkpointed and parked")
        if len(self._queue) >= self.queue_limit:
            return (f"queue full: {len(self._queue)} queued request(s) "
                    f"at --queue-limit {self.queue_limit}; retry later "
                    f"or restart the server with a higher --queue-limit")
        if kind == "config":
            cfg = spec.get("config")
            if not cfg or not os.path.exists(cfg):
                return (f"config {cfg!r} not found on the server's "
                        f"filesystem (paths are resolved server-side)")
            return None
        if kind == "builder":
            from . import sim
            name = spec.get("name")
            if not name or getattr(sim, f"build_{name}", None) is None:
                return (f"unknown world builder {name!r} (known: the "
                        f"sim.build_* family)")
            if not isinstance(spec.get("kwargs", {}), dict):
                return "builder kwargs must be a JSON object"
            return None
        if kind == "replay":
            target = spec.get("run") or ""
            tdir = target if os.path.isdir(target) \
                else os.path.join(self.runs_dir, target)
            if not os.path.isdir(tdir):
                return (f"replay target {target!r} is neither a run id "
                        f"under {self.runs_dir} nor a data directory")
            return None
        return (f"unknown request kind {kind!r} (expected 'config', "
                f"'builder', or 'replay')")

    def _op_status(self, msg, wf):
        rid = msg.get("id")
        if rid is None:
            with self._lock:
                snap = {
                    "ok": True,
                    "server": {
                        "version": protocol.PROTOCOL_VERSION,
                        "pid": os.getpid(),
                        "data_dir": self.data_dir,
                        "queue_depth": len(self._queue),
                        "queue_limit": self.queue_limit,
                        "workers": self.workers,
                        "draining": self._draining,
                        "warmed": bool(self.warmed)},
                    "runs": [self._record_locked(r)
                             for _, r in sorted(self._reqs.items())]}
            protocol.send(wf, snap)
            return
        sub = None
        with self._lock:
            req = self._reqs.get(rid)
            if req is None:
                protocol.send(wf, {"ok": False, "rc": RC_USAGE,
                                   "error": f"unknown run id {rid!r}"})
                return
            rec = self._record_locked(req)
            wait = bool(msg.get("wait"))
            if wait and req.state in (protocol.QUEUED, protocol.RUNNING):
                sub = queue_mod.Queue()
                req.subscribers.append(sub)
        protocol.send(wf, {"ok": True, "run": rec})
        if sub is not None:
            self._pump(req, sub, wf, progress=True)
        elif msg.get("wait"):
            # Already settled: synthesize the terminal event.
            if req.state == protocol.PARKED:
                protocol.send(wf, {"event": "parked", "id": rid})
            else:
                protocol.send(wf, {"event": "done", "id": rid,
                                   "rc": req.rc, "state": req.state,
                                   "crash": req.crash,
                                   "error": req.error,
                                   "summary": req.summary})

    def _record_locked(self, req: Request) -> dict:
        """record() plus the live queue position (call under the lock):
        a queued request's status names where it sits in line."""
        rec = req.record(os.path.join(self.runs_dir, req.id))
        if req.state == protocol.QUEUED and req.id in self._queue:
            rec["queue_position"] = self._queue.index(req.id)
        return rec

    def _op_cancel(self, msg, wf):
        rid = msg.get("id")
        with self._lock:
            req = self._reqs.get(rid)
            if req is None:
                protocol.send(wf, {"ok": False, "rc": RC_USAGE,
                                   "error": f"unknown run id {rid!r}"})
                return
            if req.state == protocol.QUEUED:
                self._queue.remove(rid)
                req.state = protocol.CANCELLED
                req.rc = RC_FAILED
                req.trail.append("cancelled")
                now = time.time()
                req.finished = now
                if req.enqueued_at is not None:
                    req.queue_wait += max(0.0, now - req.enqueued_at)
                    req.enqueued_at = None
                self._log({"ev": "cancel", "id": rid, "t": now})
                self.metrics.settle(req)
                done = {"event": "done", "id": rid, "rc": RC_FAILED,
                        "state": protocol.CANCELLED}
                subs = list(req.subscribers)
                resp = {"ok": True, "id": rid,
                        "state": protocol.CANCELLED}
            elif req.state == protocol.RUNNING:
                req.control.request("cancel")
                done, subs = None, []
                resp = {"ok": True, "id": rid, "state": "cancelling"}
            else:
                done, subs = None, []
                resp = {"ok": True, "id": rid, "state": req.state,
                        "note": "already settled"}
        for q in subs:
            q.put(done)
        if done is not None:
            self._write_request_metrics(req)
        self._sync_request(req)
        protocol.send(wf, resp)

    def _pump(self, req, sub, wf, progress=True):
        """Relay a request's event stream to one client until its
        terminal event; the connection closing mid-stream just drops
        the subscription (the run itself is unaffected)."""
        try:
            while True:
                try:
                    ev = sub.get(timeout=1.0)
                except queue_mod.Empty:
                    if self._done.is_set():
                        return
                    continue
                if ev.get("event") == "progress" and not progress:
                    continue
                protocol.send(wf, ev)
                if ev.get("event") in ("done", "parked"):
                    return
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self._lock:
                if sub in req.subscribers:
                    req.subscribers.remove(sub)

    def _emit(self, req, ev: dict) -> None:
        with self._lock:
            subs = list(req.subscribers)
        for q in subs:
            q.put(ev)

    # -- scheduler + workers ---------------------------------------------

    def _worker_loop(self, widx: int):
        while True:
            with self._cond:
                while (not self._queue or self._draining) \
                        and not self._stopping:
                    self._cond.wait(0.25)
                if self._stopping:
                    return
                batch = self._pick_batch_locked(widx)
                if not batch:
                    continue
            self.metrics.worker_start(widx, batch[0].id)
            try:
                if len(batch) == 1:
                    self._execute(batch[0])
                else:
                    self._execute_batch(widx, batch)
            finally:
                self.metrics.worker_done(widx)

    def _pick_locked(self, worker: int):
        """Warm-graph affinity: prefer the oldest queued request whose
        shape hint matches the last-executed one (it reuses the
        compiled graph); fall back to FIFO.  Stamps the pick on the
        request: worker id, affinity hit/miss, and whether affinity
        (not queue order) made the choice."""
        if self._draining or not self._queue:
            return None
        idx = 0
        if self._last_hint is not None:
            for i, rid in enumerate(self._queue):
                if self._reqs[rid].shape_hint == self._last_hint:
                    idx = i
                    break
        rid = self._queue.pop(idx)
        req = self._reqs[rid]
        req.worker = worker
        req.affinity_hit = (self._last_hint is not None
                            and req.shape_hint == self._last_hint)
        req.pick_reason = "affinity" if (req.affinity_hit and idx > 0) \
            else "fifo"
        self._last_hint = req.shape_hint
        self.metrics.pick(req.affinity_hit)
        return req

    def _batchable(self, req) -> bool:
        """A request the lane train can carry: a builder world with
        none of the per-request instrumentation/layout knobs that
        change the state pytree or need a solo run loop (devices,
        bucket, scope, lineage, digests)."""
        if req.kind != "builder":
            return False
        spec = req.spec
        return not any(spec.get(k) for k in
                       ("devices", "bucket", "scope", "trace_packets",
                        "digest_every"))

    def _claim_batchable_locked(self, hint, worker, n) -> list:
        """Pop up to n queued batchable requests whose shape hint
        matches `hint` (they share the train's compiled graph by
        construction).  Caller holds the lock."""
        out = []
        i = 0
        while i < len(self._queue) and len(out) < n:
            r = self._reqs[self._queue[i]]
            if self._batchable(r) and r.shape_hint == hint:
                self._queue.pop(i)
                r.worker = worker
                r.affinity_hit = True
                r.pick_reason = "batched"
                self.metrics.pick(True)
                out.append(r)
            else:
                i += 1
        return out

    def _pick_batch_locked(self, worker: int) -> list:
        """One scheduling decision: the affinity/FIFO pick, plus -- when
        it is batchable and compatible peers are queued -- up to
        max_lanes-1 of them, co-batched onto one lane train
        (docs/robustness.md "Continuous batching").  A lone batchable
        request still runs solo (the solo compiled graph stays warm
        for affinity); trains form when >= 2 compatible requests are
        queued together, and accept later joiners mid-flight."""
        req = self._pick_locked(worker)
        if req is None:
            return []
        batch = [req]
        if self.max_lanes > 1 and self._batchable(req):
            batch += self._claim_batchable_locked(
                req.shape_hint, worker, self.max_lanes - 1)
        return batch

    def _begin_exec(self, req: Request):
        """Move a picked request into RUNNING: close its queued
        segment, refuse it if it timed out while queued (returns
        None), then stamp control/profiler/journal and return
        (run_dir, emit) -- the per-request evidence-harvesting emit
        closure shared by the solo and batched paths."""
        from . import trace
        now = time.time()
        with self._lock:
            # Close the open queued segment: the request is off the
            # queue whether it runs or is refused below.
            if req.enqueued_at is not None:
                req.queue_wait += max(0.0, now - req.enqueued_at)
                req.enqueued_at = None
        if req.timeout and now - req.submitted >= req.timeout:
            self._finish(req, RC_USAGE, error=(
                f"request {req.id} spent {now - req.submitted:.1f}s "
                f"queued, past its --timeout {req.timeout:g}s; raise "
                f"--timeout or submit to a less loaded server"))
            return None
        deadline = None
        if req.timeout:
            deadline = time.monotonic() + (req.timeout
                                           - (now - req.submitted))
        run_dir = os.path.join(self.runs_dir, req.id)
        os.makedirs(run_dir, exist_ok=True)
        with self._lock:
            req.control = RunControl(deadline)
            req.state = protocol.RUNNING
            req.started = now
            req.trail.append("started")
            # counters=False: per-request accounting must stay host-side
            # only -- a served run's state pytree (and so its
            # trajectory) is byte-identical to an unobserved one.
            req.profiler = trace.Profiler(sync=False, counters=False)
            self._log({"ev": "start", "id": req.id, "t": now,
                       "worker": req.worker, "hit": req.affinity_hit,
                       "reason": req.pick_reason,
                       "depth": len(self._queue)})
        self._sync_request(req)
        self._emit(req, {"event": "state", "id": req.id,
                         "state": protocol.RUNNING})

        def emit(ev):
            # Harvest evidence off the stream before relaying it.
            if ev.get("event") == "summary":
                req.summary = ev.get("summary")
            elif ev.get("event") == "crash":
                crash = ev.get("crash") or {}
                req.crash = {
                    "path": ev.get("path")
                    or os.path.join(run_dir, "crash.json"),
                    "class": crash.get("failure", {}).get("class")}
            elif ev.get("event") == "resumed":
                req.resumes += 1
                self.metrics.event("resumes")
            elif ev.get("event") == "recovered":
                req.recoveries += 1
                self.metrics.event("recoveries")
            elif ev.get("event") == "quarantined":
                # Ensemble request: world(s) frozen by the quarantine
                # rung while the survivors keep running.
                n = len(ev.get("worlds") or ()) or 1
                req.quarantines += n
                self.metrics.event("quarantines", n)
            self._emit(req, ev)

        return run_dir, emit

    def _settle_exec(self, req: Request, rc: int) -> None:
        """Map a finished execution onto the request's terminal (or
        parked) state -- the shared tail of the solo and batched
        paths.  The control outcome outranks rc: park re-journals for
        the next --auto-resume life, cancel/timeout carry their own
        exit codes."""
        outcome = req.control.outcome
        if outcome == "parked":
            with self._lock:
                req.state = protocol.PARKED
                req.parks += 1
                req.trail.append("parked (server drain)")
                self._log({"ev": "park", "id": req.id,
                           "t": time.time()})
            self.metrics.event("parked")
            self._sync_request(req)
            self._emit(req, {"event": "parked", "id": req.id})
        elif outcome == "cancelled":
            self._finish(req, RC_FAILED, state=protocol.CANCELLED,
                         error=f"request {req.id} cancelled")
        elif outcome == "timed_out":
            self._finish(req, RC_USAGE, error=(
                f"request {req.id} exceeded its --timeout "
                f"{req.timeout:g}s and was stopped at a launch "
                f"boundary; raise --timeout for longer scenarios"))
        else:
            self._finish(req, rc)

    def _execute(self, req: Request) -> None:
        from . import trace
        begun = self._begin_exec(req)
        if begun is None:
            return
        run_dir, emit = begun
        try:
            rc = self._dispatch(req, run_dir, req.control, emit)
        except BaseException as e:  # noqa: BLE001 -- worker must survive
            req.error = f"{type(e).__name__}: {e}"
            if not self.quiet:
                traceback.print_exc()
            rc = RC_FAILED
        finally:
            # The run loop installs req.profiler process-globally; drop
            # it so later requests (or the warm thread) can't attribute
            # their compiles to a finished request.  Best-effort under
            # workers>1 -- the install slot is global by design.
            if trace.current() is req.profiler:
                trace.install(None)
        self._settle_exec(req, rc)

    def _begin_lane(self, req: Request):
        """_begin_exec + batch.prepare for one train member; maps
        preparation failures (bad builder name/kwargs) onto the same
        exit codes _dispatch would give them.  Returns the prepared
        batch.Lane, or None when the request settled already."""
        from . import batch as batch_mod
        begun = self._begin_exec(req)
        if begun is None:
            return None
        run_dir, emit = begun
        try:
            return batch_mod.prepare(
                req, run_dir, req.control, emit,
                default_ck_s=self.checkpoint_every)
        except (ValueError, FileNotFoundError, KeyError, TypeError,
                AttributeError, json.JSONDecodeError) as e:
            req.error = f"{type(e).__name__}: {e}"
            self._settle_exec(req, RC_USAGE)
            return None
        except BaseException as e:  # noqa: BLE001 -- worker must survive
            req.error = f"{type(e).__name__}: {e}"
            if not self.quiet:
                traceback.print_exc()
            self._settle_exec(req, RC_FAILED)
            return None

    def _execute_batch(self, widx: int, reqs: list) -> None:
        """Run co-picked compatible requests as ONE lane train
        (batch.LaneTrain): each request is a lane of a live vmapped
        ensemble, advancing on its own solo launch grid through one
        compiled graph, with per-request checkpoints/windows.jsonl/
        metrics byte-identical to solo runs.  Queued compatible
        requests join free lanes at launch boundaries; each lane
        settles the moment it retires."""
        from . import batch as batch_mod
        from . import trace
        hint = reqs[0].shape_hint
        lanes = [ln for ln in (self._begin_lane(r) for r in reqs)
                 if ln is not None]
        if not lanes:
            return

        def claim_more(n):
            with self._lock:
                if self._draining or self._stopping:
                    return []
                claimed = self._claim_batchable_locked(hint, widx, n)
            return [ln for ln in (self._begin_lane(r) for r in claimed)
                    if ln is not None]

        def on_retire(lane):
            if not lane.settled:
                lane.settled = True
                self._settle_exec(lane.req, lane.rc
                                  if lane.rc is not None else RC_FAILED)

        # Compiles during the train attribute to the primary request's
        # profiler; per-lane spans/drains go to each request's own.
        trace.install(lanes[0].req.profiler)
        train = batch_mod.LaneTrain(self.max_lanes,
                                    claim_more=claim_more,
                                    on_retire=on_retire)
        try:
            train.run(lanes)
        except BaseException as e:  # noqa: BLE001 -- worker must survive
            if not self.quiet:
                traceback.print_exc()
            train.abort(f"{type(e).__name__}: {e}")
            for lane in train.lanes:
                if not lane.settled:
                    lane.settled = True
                    self._settle_exec(lane.req, RC_FAILED)
        finally:
            if trace.current() is lanes[0].req.profiler:
                trace.install(None)

    def _dispatch(self, req, run_dir, control, emit) -> int:
        from .cli import CliError
        try:
            if req.kind == "config":
                return self._run_config_kind(req, run_dir, control, emit)
            if req.kind == "builder":
                return self._run_builder_kind(req, run_dir, control,
                                              emit)
            if req.kind == "replay":
                return self._run_replay_kind(req, run_dir)
            req.error = f"unknown request kind {req.kind!r}"
            return RC_USAGE
        except CliError as e:
            req.error = str(e)
            return e.rc
        except (ValueError, FileNotFoundError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            req.error = f"{type(e).__name__}: {e}"
            return RC_USAGE

    def _run_config_kind(self, req, run_dir, control, emit) -> int:
        from . import cli
        spec = dict(req.spec)
        # Re-parse for a fully-defaulted namespace, then lay the spec
        # over it: the client sent exactly the run-flag set, so an
        # older client simply inherits the server's defaults.
        ns = cli._parser().parse_args(["run", spec.get("config") or "?"])
        for k, v in spec.items():
            if hasattr(ns, k):
                setattr(ns, k, v)
        # Server-side overrides: per-request data directory, always
        # supervised + auto-resumable (the crash-safety contract), the
        # server's cadence/watchdog defaults when the request set none.
        ns.data_directory = run_dir
        ns.quiet = True
        ns.auto_resume = True
        if not getattr(ns, "checkpoint_every", None):
            ns.checkpoint_every = self.checkpoint_every
        if getattr(ns, "watchdog", None) is None:
            ns.watchdog = self.watchdog
        ns.progress = bool(spec.get("progress"))
        return cli.run_config(ns, control=control, emit=emit,
                              profiler=req.profiler)

    def _run_builder_kind(self, req, run_dir, control, emit) -> int:
        from . import sim
        from .supervise import UnrecoveredFailure
        spec = req.spec
        name = spec["name"]
        kwargs = dict(spec.get("kwargs") or {})
        ck_s = float(spec.get("checkpoint_every")
                     or self.checkpoint_every)
        wd = spec.get("watchdog", self.watchdog)
        devices = spec.get("devices")
        state, params, app = getattr(sim, f"build_{name}")(**kwargs)
        try:
            state = sim.run(
                state, params, app,
                devices=devices, bucket=bool(spec.get("bucket")),
                scope=spec.get("scope"),
                lineage=spec.get("trace_packets"),
                digest=spec.get("digest_every"),
                checkpoint_every=int(ck_s * SEC),
                checkpoint_dir=run_dir,
                checkpoint_world=(name, kwargs),
                supervise={"watchdog_s": wd, "quiet": True},
                profiler=req.profiler,
                control=control, emit=emit, resume=True)
        except UnrecoveredFailure as e:
            req.error = str(e)
            req.crash = {"path": e.path,
                         "class": e.crash.get("failure", {}).get("class")}
            return e.rc
        if control.outcome is not None:
            return RC_OK  # _execute maps the outcome, not this rc
        import jax.numpy as jnp
        req.summary = {
            "simulated_seconds": int(state.now) / SEC,
            "windows": int(state.n_windows),
            "packets_sent": int(jnp.sum(state.hosts.pkts_sent)),
            "err_flags": int(state.err)}
        emit({"event": "summary", "summary": req.summary})
        return RC_OK if int(state.err) == 0 else RC_INVARIANT

    def _run_replay_kind(self, req, run_dir) -> int:
        from . import replay as replay_mod
        from .trace import ReplayDivergence
        spec = req.spec
        target = spec.get("run") or ""
        tdir = target if os.path.isdir(target) \
            else os.path.join(self.runs_dir, target)
        try:
            summary = replay_mod.replay(
                tdir, window=spec.get("window"),
                out_dir=os.path.join(run_dir, "replay"), quiet=True)
        except ReplayDivergence as e:
            req.error = str(e)
            req.summary = {"replay_diverged": {
                "window": e.window, "fields": e.fields}}
            return RC_INVARIANT
        req.summary = summary
        sn = summary.get("sentinel")
        if sn and sn.get("violations"):
            req.error = (f"replay reproduced a sentinel violation "
                         f"({'+'.join(sn['classes'])}) at window "
                         f"{sn['first_bad_window']}")
            return RC_INVARIANT
        return RC_OK

    def _finish(self, req, rc, state=None, error=None) -> None:
        with self._lock:
            req.rc = int(rc)
            req.state = state or (protocol.DONE if rc == RC_OK
                                  else protocol.FAILED)
            req.finished = time.time()
            if req.enqueued_at is not None:
                # Settled without ever starting (queued refusal).
                req.queue_wait += max(0.0,
                                      req.finished - req.enqueued_at)
                req.enqueued_at = None
            if error:
                req.error = error
            req.trail.append(f"finished rc {req.rc}")
            if req.crash is None:
                p = os.path.join(self.runs_dir, req.id, "crash.json")
                if os.path.exists(p):
                    req.crash = {"path": p, "class": None}
            self._log({"ev": "finish", "id": req.id, "rc": req.rc,
                       "state": req.state, "t": req.finished})
            self.metrics.settle(req)
        self._write_request_metrics(req)
        self._sync_request(req)
        done = {"event": "done", "id": req.id, "rc": req.rc,
                "state": req.state}
        if req.error:
            done["error"] = req.error
        if req.crash:
            done["crash"] = req.crash
        if req.summary is not None:
            done["summary"] = req.summary
        self._emit(req, done)

    # -- servescope: per-request + fleet metrics --------------------------

    def _write_request_metrics(self, req: Request) -> None:
        """Assemble runs/<id>/request_metrics.json from the scheduler
        stamps plus the per-request Profiler, atomically (tmp +
        rename).  Called once per terminal transition; a re-admitted
        run overwrites it at its real finish with the accumulated
        queue-wait / restart counts."""
        from . import trace
        run_dir = os.path.join(self.runs_dir, req.id)
        os.makedirs(run_dir, exist_ok=True)
        prof = req.profiler
        m = prof.metrics() if prof is not None else {}
        phases = m.get("phases") or {}

        def phase_ms(names):
            return round(sum((phases.get(n) or {}).get("total_s", 0.0)
                             for n in names) * 1e3, 3)

        events = (m.get("device_counters") or {}).get("events")
        wall = None
        if req.started is not None and req.finished is not None:
            wall = round(req.finished - req.started, 6)
        out = {
            "id": req.id, "kind": req.kind, "state": req.state,
            "rc": req.rc, "shape_hint": req.shape_hint,
            "worker": req.worker,
            "queue_wait_s": round(req.queue_wait, 6),
            "affinity_hit": req.affinity_hit,
            "pick_reason": req.pick_reason,
            "wall_s": wall,
            "compiles": m.get("compiles", 0),
            "compile_ms": m.get("compile_ms", 0.0),
            # Pipelined runs record dispatch->ready walls as
            # device_window spans (the engine's per-chunk device_step
            # spans are dispatch-only blips); prefer them when present.
            "device_step_ms": phase_ms(("device_window",))
            or phase_ms(("device_step",)),
            "drain_ms": phase_ms(trace._HOST_DRAIN_PHASES),
            "host_drain_overlap_pct": m.get("host_drain_overlap_pct",
                                            0.0),
            "events": events,
            "events_per_s": round(events / wall, 3)
            if events is not None and wall else None,
            "checkpoints": len(glob_mod.glob(
                os.path.join(run_dir, "ckpt", "win_*.npz"))),
            "parks": req.parks,
            "resumes": req.resumes,
            "recoveries": req.recoveries,
            "quarantines": req.quarantines,
            "n_worlds": (req.summary or {}).get("n_worlds")
            if isinstance(req.summary, dict) else None,
            "restarts": req.restarts,
            "submitted": req.submitted,
            "started": req.started,
            "finished": req.finished,
        }
        path = os.path.join(run_dir, "request_metrics.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        # Builder runs have no CLI end-block to write the trace; drop
        # one here so tools/plot.py can merge it with schedule.jsonl
        # (config runs already wrote theirs via cli.run_config).
        tpath = os.path.join(run_dir, "trace.json")
        if prof is not None and prof.events \
                and not os.path.exists(tpath):
            try:
                prof.write_trace(tpath)
            except OSError:
                pass

    def _stats(self) -> dict:
        """One fleet snapshot: the ServerMetrics counters plus the live
        queue / worker / warm view.  Serves the `stats` protocol op and
        the server/metrics.json cadence writer."""
        with self._lock:
            queue_ids = list(self._queue)
            states = {}
            for r in self._reqs.values():
                states[r.state] = states.get(r.state, 0) + 1
            queued = [{
                "id": rid, "position": i,
                "shape_hint": self._reqs[rid].shape_hint,
                "queue_wait_s": self._reqs[rid].queue_wait_s()}
                for i, rid in enumerate(queue_ids)]
            draining = self._draining
            warmed = self.warmed
            last_hint = self._last_hint
        snap = self.metrics.snapshot()
        snap.update({
            "ts": time.time(),
            "version": protocol.PROTOCOL_VERSION,
            "pid": os.getpid(),
            "data_dir": self.data_dir,
            "draining": draining,
            "states": states,
            "queue": {"depth": len(queue_ids),
                      "limit": self.queue_limit,
                      "high_water": self.metrics.queue_high_water,
                      "queued": queued},
            "warm": {"warmed": bool(warmed),
                     "buckets": len(warmed) if warmed else 0,
                     "last_hint": last_hint},
        })
        return snap

    def _write_metrics_snapshot(self) -> None:
        """Atomically snapshot `_stats()` to server/metrics.json (tmp +
        rename, like every other state file)."""
        path = os.path.join(self.sdir, "metrics.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self._stats(), f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass  # metrics are best-effort; never take the server down

    def _metrics_loop(self) -> None:
        while not self._done.wait(self.metrics_every):
            self._write_metrics_snapshot()

    def _say(self, msg):
        if not self.quiet:
            print(f"[shadow1-tpu] {msg}", file=sys.stderr)


def serve(args) -> int:
    """`shadow1-tpu serve`: run the resident server until SIGTERM /
    SIGINT / a protocol shutdown.  Exit code 0 on a clean drain."""
    import signal

    srv = Server(args.data_directory,
                 queue_limit=args.queue_limit,
                 workers=args.workers,
                 checkpoint_every=args.checkpoint_every,
                 watchdog=args.watchdog,
                 auto_resume=args.auto_resume,
                 quiet=args.quiet,
                 max_lanes=getattr(args, "max_lanes", 4))
    try:
        srv.start()
    except (OSError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_USAGE

    def _term(signum, frame):
        threading.Thread(target=srv.shutdown, kwargs={"drain": True},
                         daemon=True, name="shadow1-serve-drain").start()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    if not args.no_warm:
        # AOT-warm the standard bucket set once, off the accept path:
        # requests admitted during the warm just compile on first use
        # exactly as the batch CLI would.
        def _warm():
            try:
                from . import shapes
                srv.warmed = shapes.warm_buckets(
                    buckets=args.warm_buckets,
                    apps=tuple(args.warm_apps))
                if not args.quiet:
                    print(f"[shadow1-tpu] serve: warmed "
                          f"{len(srv.warmed)} bucket graph(s)",
                          file=sys.stderr)
            except Exception as e:  # noqa: BLE001 -- warm is best-effort
                print(f"[shadow1-tpu] serve: bucket warm failed ({e}); "
                      f"requests will compile on first use",
                      file=sys.stderr)

        threading.Thread(target=_warm, daemon=True,
                         name="shadow1-serve-warm").start()
    srv.wait()
    return RC_OK
