"""Wire protocol of the resident run server (docs/robustness.md
"Run server").

Transport is a local Unix-domain stream socket; messages are one JSON
object per line (newline-delimited, UTF-8).  A client sends exactly one
request object carrying an ``op``; the server answers with one
``{"ok": true/false, ...}`` acknowledgement and then -- for streaming
ops (``submit`` with wait, ``status`` with wait) -- a sequence of
``{"event": ...}`` objects ending with a terminal
``{"event": "done", "rc": N, ...}``.  One request per connection: the
connection closes after the terminal message, so a torn stream is
always distinguishable from a finished one.

Ops (client -> server):

    ping      liveness probe; the ack carries the server's version,
              queue depth, and draining flag
    submit    enqueue a request: {"kind": "config"|"builder"|"replay",
              "spec": {...}, "timeout": seconds|None,
              "wait": bool, "progress": bool}
    status    {"id": run-id|None, "wait": bool}: a run record, or the
              whole server snapshot
    stats     fleet snapshot (Servescope): queue depth/high-water,
              per-worker busy time, affinity hit rate, requests by
              state/kind/rc, journal fsync latency, recent
              completions -- the same JSON server/metrics.json holds
    cancel    {"id": run-id}
    shutdown  {"drain": bool}: park in-flight runs (drain) or stop at
              the next boundary, journal, and exit

Request lifecycle states (server.py journals every transition to
``server/journal.jsonl`` and mirrors the full record to
``runs/<id>/request.json`` atomically):

    queued -> running -> done | failed | parked | cancelled
                         (parked runs re-enter queued on a
                          ``serve --auto-resume`` restart)

Exit codes ride the unified table (supervise.RC_*): the terminal
``done`` event's ``rc`` is what ``submit --wait`` / ``status --wait``
exit with, so a refusal (queue full, bad spec, timeout) is rc 2 at the
client exactly as it would be at the CLI.
"""

from __future__ import annotations

import json
import os
import socket

PROTOCOL_VERSION = 1

# Lifecycle states (journal "state" fields and status output).
QUEUED = "queued"
RUNNING = "running"
DONE = "done"          # rc 0
FAILED = "failed"      # rc 1/2/3 recorded on the request
PARKED = "parked"      # checkpointed and stopped by a drain; resumable
CANCELLED = "cancelled"

TERMINAL = frozenset({DONE, FAILED, CANCELLED})


def default_socket(data_dir: str) -> str:
    """The server's socket path under its data directory."""
    return os.path.join(data_dir, "server", "sock")


def send(f, obj: dict) -> None:
    """Write one message (a JSON object) to a socket file."""
    f.write(json.dumps(obj, sort_keys=True) + "\n")
    f.flush()


def recv(f) -> dict | None:
    """Read one message; None when the peer closed the stream."""
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


class ServerUnavailable(ConnectionError):
    """No server is listening on the socket path (named in args)."""


def connect(path: str, timeout: float | None = 30.0):
    """Open a client connection; returns (socket, rfile, wfile)."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    if timeout is not None:
        s.settimeout(timeout)
    try:
        s.connect(path)
    except (FileNotFoundError, ConnectionRefusedError) as e:
        s.close()
        raise ServerUnavailable(
            f"no run server is listening on {path} (start one with "
            f"`shadow1-tpu serve --data-directory DIR`): {e}") from e
    return s, s.makefile("r", encoding="utf-8"), \
        s.makefile("w", encoding="utf-8")


def request(path: str, obj: dict, timeout: float | None = 30.0) -> dict:
    """One-shot request/ack exchange (ping, cancel, plain status)."""
    s, rf, wf = connect(path, timeout)
    try:
        send(wf, obj)
        resp = recv(rf)
        if resp is None:
            raise ConnectionError(
                f"run server on {path} closed the connection without "
                f"answering")
        return resp
    finally:
        s.close()


def stream(path: str, obj: dict, timeout: float | None = None):
    """Send a request and yield the ack plus every streamed event until
    the server closes the connection.  `timeout=None` waits forever --
    a submitted run may take hours."""
    s, rf, wf = connect(path, timeout)
    try:
        send(wf, obj)
        while True:
            msg = recv(rf)
            if msg is None:
                return
            yield msg
    finally:
        s.close()
