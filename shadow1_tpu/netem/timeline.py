"""Host-side netem front ends: the fluent Timeline builder, seeded chaos
churn, the JSON event-file loader, and `install` (attach a built block to
a world).

Times are absolute simulated nanoseconds (`core.simtime` units)
everywhere in this module; the config front ends convert seconds before
calling in.
"""

from __future__ import annotations

import json

import numpy as np

from ..core import rng, simtime
from . import apply as _apply
from .state import (EV_BW_SCALE, EV_HOST_DOWN, EV_HOST_UP, EV_LINK_DOWN,
                    EV_LINK_LAT, EV_LINK_LOSS, EV_LINK_UP, EV_PARTITION,
                    KIND_BY_NAME, LOSS_ONE, SCALE_ONE, make_netem_block)

SEC = simtime.SIMTIME_ONE_SECOND

_PAIR_KINDS = (EV_LINK_DOWN, EV_LINK_UP)


def _x1000(scale: float) -> int:
    v = int(round(float(scale) * SCALE_ONE))
    if v < 1:
        raise ValueError(f"scale {scale} must be > 0")
    return v


def _x1e6(frac: float) -> int:
    f = float(frac)
    if not 0.0 <= f <= 1.0:
        raise ValueError(f"loss fraction {frac} must be in [0, 1]")
    return int(round(f * LOSS_ONE))


class Timeline:
    """Ordered fault/dynamics schedule under construction.

    Every method returns `self` so scenarios chain:

        netem.timeline().link_down(0, 1, at=2 * SEC) \\
                        .link_up(0, 1, at=4 * SEC) \\
                        .host_flap(3, down_at=1 * SEC, up_at=2 * SEC)
    """

    def __init__(self):
        self.events: list = []       # (t_ns, kind, a, b, val)
        self.groups: dict = {}       # host -> partition group id

    def _add(self, at, kind, a=-1, b=-1, val=0):
        at = int(at)
        if at < 0:
            raise ValueError(f"event time {at} must be >= 0")
        self.events.append((at, kind, int(a), int(b), int(val)))
        return self

    # -- links ------------------------------------------------------------
    def link_down(self, a, b, at):
        if a == b:
            raise ValueError("link_down needs two distinct hosts")
        return self._add(at, EV_LINK_DOWN, a, b)

    def link_up(self, a, b, at):
        return self._add(at, EV_LINK_UP, a, b)

    def latency_scale(self, scale, at, a=None, b=None):
        """Scale latency globally (a/b omitted) or on one link."""
        if (a is None) != (b is None):
            raise ValueError("latency_scale takes both a and b, or neither")
        return self._add(at, EV_LINK_LAT, -1 if a is None else a,
                         -1 if b is None else b, _x1000(scale))

    def loss(self, frac, at, a=None, b=None):
        """Inject loss (a fraction in [0,1]) globally or on one link."""
        if (a is None) != (b is None):
            raise ValueError("loss takes both a and b, or neither")
        return self._add(at, EV_LINK_LOSS, -1 if a is None else a,
                         -1 if b is None else b, _x1e6(frac))

    # -- hosts ------------------------------------------------------------
    def host_down(self, host, at):
        return self._add(at, EV_HOST_DOWN, host)

    def host_up(self, host, at):
        return self._add(at, EV_HOST_UP, host)

    def host_flap(self, host, down_at, up_at):
        if not up_at > down_at:
            raise ValueError("host_flap needs up_at > down_at")
        return self.host_down(host, down_at).host_up(host, up_at)

    # -- partitions -------------------------------------------------------
    def set_group(self, host, group):
        """Assign a host to a partition group (0..30; default 0)."""
        g = int(group)
        if not 0 <= g <= 30:
            raise ValueError("partition group ids must be in 0..30")
        self.groups[int(host)] = g
        return self

    def partition(self, groups, at):
        """Isolate the given group ids from every other group."""
        mask = 0
        for g in ([groups] if isinstance(groups, int) else groups):
            if not 0 <= int(g) <= 30:
                raise ValueError("partition group ids must be in 0..30")
            mask |= 1 << int(g)
        if mask == 0:
            raise ValueError("partition needs at least one group "
                             "(use heal() to clear)")
        return self._add(at, EV_PARTITION, val=mask)

    def heal(self, at):
        return self._add(at, EV_PARTITION, val=0)

    # -- bandwidth ---------------------------------------------------------
    def bandwidth_scale(self, scale, at, host=None):
        return self._add(at, EV_BW_SCALE,
                         -1 if host is None else host, -1, _x1000(scale))

    # -- chaos ------------------------------------------------------------
    def chaos(self, seed_key, num_hosts, rate_per_s, *,
              mean_down_s: float = 5.0, hosts=None,
              t_start: int = 0, t_end: int):
        """Seeded churn: each selected host alternates exponential
        up-times (mean 1/rate_per_s seconds) and down-times (mean
        mean_down_s), drawn from the counter RNG keyed by (host, draw
        index) -- bitwise reproducible for a given seed on any chunking
        or mesh (core/rng.py contract)."""
        if rate_per_s <= 0:
            raise ValueError("churn rate must be > 0 flaps/host/second")
        sel = np.arange(num_hosts) if hosts is None \
            else np.asarray(sorted(set(int(x) for x in hosts)))
        if sel.size == 0:
            return self
        span_s = (int(t_end) - int(t_start)) / SEC
        if span_s <= 0:
            raise ValueError("chaos needs t_end > t_start")
        mean_up_s = 1.0 / rate_per_s
        # Draw enough cycles to cover the span with slack; surplus draws
        # land past t_end and are discarded below.
        n_cyc = int(np.ceil(span_s / (mean_up_s + mean_down_s) * 3 + 4))
        key = rng.purpose_key(seed_key, rng.PURPOSE_CHAOS)
        hh = np.repeat(sel, n_cyc).astype(np.uint32)
        jj = np.tile(np.arange(n_cyc, dtype=np.uint32), sel.size)
        u_up = np.asarray(rng.keyed_uniform(key, hh, 2 * jj),
                          np.float64).reshape(sel.size, n_cyc)
        u_dn = np.asarray(rng.keyed_uniform(key, hh, 2 * jj + 1),
                          np.float64).reshape(sel.size, n_cyc)
        d_up = -mean_up_s * np.log1p(-u_up)
        d_dn = -mean_down_s * np.log1p(-u_dn)
        # Interleave up/down durations and accumulate into event times.
        durs = np.empty((sel.size, 2 * n_cyc))
        durs[:, 0::2] = d_up
        durs[:, 1::2] = d_dn
        times = int(t_start) + np.cumsum(durs * SEC, axis=1).astype(np.int64)
        for hi, host in enumerate(sel):
            for c in range(n_cyc):
                t_down = times[hi, 2 * c]
                t_up = times[hi, 2 * c + 1]
                if t_down >= t_end:
                    break
                self.host_down(int(host), int(t_down))
                # A flap straddling t_end still restores the host.
                self.host_up(int(host), int(min(t_up, int(t_end))))
        return self

    # -- build ------------------------------------------------------------
    def link_pairs(self):
        return {(min(a, b), max(a, b)) for (_t, k, a, b, _v)
                in self.events if k in _PAIR_KINDS or
                (k in (EV_LINK_LAT, EV_LINK_LOSS) and a >= 0)}

    def build(self, num_hosts: int, n_events: int | None = None):
        """Lower to a NetemBlock, or None when the timeline is empty --
        the None fast path keeps untouched worlds bit-identical.

        `n_events` pads the event table to a fixed bucket (slots beyond
        the real schedule carry T_NEVER, which the cursor never reaches)
        so seed-dependent schedules -- chaos churn draws a different
        event count per seed -- share one shape across ensemble worlds."""
        if not self.events and not self.groups:
            return None
        if n_events is not None and len(self.events) > n_events:
            raise ValueError(
                f"timeline has {len(self.events)} events, more than the "
                f"requested n_events bucket {n_events}")
        groups = np.zeros(num_hosts, np.int32)
        for h, g in self.groups.items():
            if not 0 <= h < num_hosts:
                raise ValueError(f"group host {h} out of range "
                                 f"[0, {num_hosts})")
            groups[h] = g
        for (_t, _k, a, b, _v) in self.events:
            for x in (a, b):
                if x >= num_hosts:
                    raise ValueError(f"event host {x} out of range "
                                     f"[0, {num_hosts})")
        return make_netem_block(num_hosts, self.events,
                                link_pairs=self.link_pairs(),
                                groups=groups, n_events=n_events)

    def describe(self) -> dict:
        """Compact summary for bench/metrics config blocks."""
        from .state import KIND_NAMES
        kinds: dict = {}
        for (_t, k, _a, _b, _v) in self.events:
            name = KIND_NAMES[k]
            kinds[name] = kinds.get(name, 0) + 1
        return {"n_events": len(self.events), "kinds": kinds,
                "n_groups": len(set(self.groups.values())) or 0}


def timeline() -> Timeline:
    return Timeline()


def install(state, params, tl: Timeline, n_events: int | None = None):
    """Attach a timeline to a built world: returns (state, params) with
    the block on `state.nm` and the conservative lookahead shrunk by the
    smallest latency scale the schedule can reach (a sub-1.0 scale would
    otherwise let the window overrun the smallest live latency).  An
    empty timeline returns the inputs unchanged (None fast path).
    `n_events` pads the event table to a shared bucket (Timeline.build)."""
    num_hosts = int(state.hosts.num_hosts)
    block = tl.build(num_hosts, n_events=n_events)
    if block is None:
        return state, params
    scale = _apply.min_lat_scale_x1000(tl.events)
    if scale < SCALE_ONE:
        import jax.numpy as jnp
        new_min = jnp.maximum(
            (params.min_latency_ns * scale) // SCALE_ONE,
            jnp.asarray(1, jnp.int64))
        params = params.replace(min_latency_ns=new_min)
    return state.replace(nm=block), params


def load_json(path_or_obj, resolve=None) -> Timeline:
    """Load a timeline from a JSON events file (--netem):

        {"events": [
           {"time": 2.0, "kind": "link_down", "a": "client", "b": "server"},
           {"time": 4.0, "kind": "link_up",   "a": "client", "b": "server"},
           {"time": 1.0, "kind": "host_down", "a": 3},
           {"time": 1.0, "kind": "latency_scale", "value": 2.5},
           {"time": 5.0, "kind": "loss", "value": 0.01, "a": 0, "b": 1},
           {"time": 6.0, "kind": "partition", "groups": [1]},
           {"time": 8.0, "kind": "bandwidth_scale", "value": 0.5, "a": 2}],
         "groups": {"relay1": 1, "relay2": 1}}

    `time` is simulated seconds.  Host references (`a`, `b`, group keys)
    are host indices, or names when `resolve(name) -> index` is given
    (the CLI passes the world's DNS).
    """
    if isinstance(path_or_obj, str):
        with open(path_or_obj) as f:
            obj = json.load(f)
    else:
        obj = path_or_obj

    def host(x):
        if x is None:
            return None
        if isinstance(x, str) and x.lstrip("-").isdigit():
            return int(x)   # XML attributes arrive as strings
        if isinstance(x, str) and resolve is None:
            raise ValueError(f"netem event names a host {x!r} but no "
                             f"resolver is available (use indices)")
        return int(resolve(x)) if isinstance(x, str) else int(x)

    tl = Timeline()
    for name, g in (obj.get("groups") or {}).items():
        tl.set_group(host(name), int(g))
    for e in obj.get("events", []):
        kind = e.get("kind")
        if kind not in KIND_BY_NAME:
            raise ValueError(f"unknown netem event kind {kind!r} "
                             f"(known: {sorted(KIND_BY_NAME)})")
        at = int(float(e["time"]) * SEC)
        a, b = host(e.get("a")), host(e.get("b"))
        k = KIND_BY_NAME[kind]
        if k == EV_LINK_DOWN:
            tl.link_down(a, b, at)
        elif k == EV_LINK_UP:
            tl.link_up(a, b, at)
        elif k == EV_LINK_LAT:
            tl.latency_scale(float(e["value"]), at, a=a, b=b)
        elif k == EV_LINK_LOSS:
            tl.loss(float(e["value"]), at, a=a, b=b)
        elif k == EV_HOST_DOWN:
            tl.host_down(a, at)
        elif k == EV_HOST_UP:
            tl.host_up(a, at)
        elif k == EV_PARTITION:
            groups = e.get("groups", [])
            if groups:
                tl.partition([int(g) for g in groups], at)
            else:
                tl.heal(at)
        elif k == EV_BW_SCALE:
            tl.bandwidth_scale(float(e["value"]), at, host=a)
    return tl
