"""Jit-safe netem operators: cursor advance + overlay consultation.

Everything here traces into the engine step.  `advance` runs once per
conservative window (a `lax.while_loop` that usually does zero
iterations); `route_overlay` / `alive` / `rate` are a few masked
gathers on the staging and delivery hot paths.  All operators are exact
identities when the overlay is neutral -- see netem/state.py's
bitwise-neutrality contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .state import (EV_BW_SCALE, EV_HOST_DOWN, EV_HOST_UP, EV_LINK_DOWN,
                    EV_LINK_LAT, EV_LINK_LOSS, EV_LINK_UP, EV_PARTITION,
                    LOSS_ONE, SCALE_ONE, NetemBlock)

I32 = jnp.int32
I64 = jnp.int64


def _apply_one(nm: NetemBlock) -> NetemBlock:
    """Apply the event at the cursor and advance it."""
    i = jnp.clip(nm.cursor, 0, nm.n_events - 1)
    k = nm.ev_kind[i]
    a = nm.ev_a[i]
    b = nm.ev_b[i]
    v = nm.ev_val[i]
    is_global = a < 0

    hids = jnp.arange(nm.host_up.shape[0], dtype=I32)
    sel_a = hids == a

    host_up = nm.host_up
    host_up = jnp.where((k == EV_HOST_DOWN) & sel_a, 0, host_up)
    host_up = jnp.where((k == EV_HOST_UP) & sel_a, 1, host_up)

    part_mask = jnp.where(k == EV_PARTITION, v, nm.part_mask)

    bw_sel = (k == EV_BW_SCALE) & (is_global | sel_a)
    bw = jnp.where(bw_sel, jnp.maximum(v, 1), nm.bw_x1000)

    lat = jnp.where((k == EV_LINK_LAT) & is_global, jnp.maximum(v, 1),
                    nm.lat_x1000)
    loss = jnp.where((k == EV_LINK_LOSS) & is_global,
                     jnp.clip(v, 0, LOSS_ONE), nm.loss_x1e6)

    nm = nm.replace(host_up=host_up, part_mask=part_mask, bw_x1000=bw,
                    lat_x1000=lat, loss_x1e6=loss)

    if nm.n_links > 0:
        mn = jnp.minimum(a, b)
        mx = jnp.maximum(a, b)
        osel = (nm.ov_a == mn) & (nm.ov_b == mx) & ~is_global
        nm = nm.replace(
            ov_lat_x1000=jnp.where(osel & (k == EV_LINK_LAT),
                                   jnp.maximum(v, 1), nm.ov_lat_x1000),
            ov_loss_x1e6=jnp.where(osel & (k == EV_LINK_LOSS),
                                   jnp.clip(v, 0, LOSS_ONE),
                                   nm.ov_loss_x1e6),
            ov_down=jnp.where(osel & (k == EV_LINK_DOWN), 1,
                              jnp.where(osel & (k == EV_LINK_UP), 0,
                                        nm.ov_down)),
        )
    return nm.replace(cursor=nm.cursor + 1)


def advance(nm: NetemBlock, bound) -> NetemBlock:
    """Apply every event with time < bound (the window's end): an event
    takes effect for the whole conservative window containing it.  The
    engine also advances to t_target at the end of each launch, so the
    cursor position -- and every counter derived from it -- is canonical
    at chunk boundaries regardless of chunking."""
    bound = jnp.asarray(bound, I64)
    n = nm.n_events

    def cond(s):
        i = jnp.clip(s.cursor, 0, n - 1)
        return (s.cursor < n) & (s.ev_time[i] < bound)

    return jax.lax.while_loop(cond, _apply_one, nm)


def _pair_overrides(nm: NetemBlock, src, dst):
    """Per-link override gather for [..] src/dst index arrays.  Returns
    (lat_x1000, loss_x1e6, link_down) with global values where no
    override slot matches."""
    lat = jnp.broadcast_to(nm.lat_x1000, src.shape)
    loss = jnp.broadcast_to(nm.loss_x1e6, src.shape)
    down = jnp.zeros(src.shape, dtype=jnp.bool_)
    if nm.n_links == 0:
        return lat, loss, down
    mn = jnp.minimum(src, dst)
    mx = jnp.maximum(src, dst)
    # [.., L] match against the (tiny) override table; one-hot gather.
    # The loss gather shifts by +1 so the -1 "no override" sentinel
    # survives the masked sum.
    m = (mn[..., None] == nm.ov_a) & (mx[..., None] == nm.ov_b)
    has = jnp.any(m, axis=-1)
    ov_lat = jnp.sum(jnp.where(m, nm.ov_lat_x1000, 0), axis=-1)
    ov_loss = jnp.sum(jnp.where(m, nm.ov_loss_x1e6 + 1, 0), axis=-1) - 1
    lat = jnp.where(has & (ov_lat > 0), ov_lat, lat)
    loss = jnp.where(has & (ov_loss >= 0), ov_loss, loss)
    down = has & (jnp.sum(jnp.where(m, nm.ov_down, 0), axis=-1) > 0)
    return lat, loss, down


def _partitioned(nm: NetemBlock, src, dst):
    """True where src and dst sit on opposite sides of the active
    partition (group bitmask semantics; mask 0 = healed)."""
    m = nm.part_mask
    gs = nm.group[src]
    gd = nm.group[dst]
    one = jnp.asarray(1, I32)
    sa = (jnp.left_shift(one, gs) & m) != 0
    sb = (jnp.left_shift(one, gd) & m) != 0
    return (m != 0) & (sa != sb)


def route_overlay(nm: NetemBlock, src, dst, lat, rel):
    """Apply the overlay to routed (latency, reliability) for src->dst
    packet arrays.  Blocked pairs (either endpoint down, link down, or
    partitioned) get reliability 0.0 so the existing staging drop path
    (`u >= rel`, counted in pkts_dropped_inet) kills them.

    Returns (lat, rel).  Neutral overlay is an exact identity."""
    h = nm.host_up.shape[0]
    dstc = jnp.clip(dst, 0, h - 1)
    lat_s, loss, link_down = _pair_overrides(nm, src, dstc)
    lat = jnp.maximum((lat * lat_s.astype(I64)) // SCALE_ONE,
                      jnp.asarray(1, I64))
    rel = rel * (jnp.asarray(1.0, jnp.float32) -
                 loss.astype(jnp.float32) *
                 jnp.asarray(1.0 / LOSS_ONE, jnp.float32))
    up = (nm.host_up[src] > 0) & (nm.host_up[dstc] > 0)
    blocked = ~up | link_down | _partitioned(nm, src, dstc)
    rel = jnp.where(blocked, jnp.asarray(0.0, jnp.float32), rel)
    return lat, rel


def block_reason(nm: NetemBlock, src, dst):
    """i32 [..] lineage drop-reason code for src->dst pairs the overlay
    blocks (core.state.LREASON_*): host_down > link_down > partition in
    priority, 0 where the pair is routable.  Pure observer for the
    packet-lineage tracer -- the kill itself stays on route_overlay's
    rel=0 path, so installing lineage never perturbs the trajectory."""
    from ..core.state import (LREASON_HOST_DOWN, LREASON_LINK_DOWN,
                              LREASON_PARTITION)
    h = nm.host_up.shape[0]
    dstc = jnp.clip(dst, 0, h - 1)
    _, _, link_down = _pair_overrides(nm, src, dstc)
    host_down = (nm.host_up[src] <= 0) | (nm.host_up[dstc] <= 0)
    reason = jnp.zeros(jnp.broadcast_shapes(src.shape, dstc.shape), I32)
    reason = jnp.where(_partitioned(nm, src, dstc), LREASON_PARTITION, reason)
    reason = jnp.where(link_down, LREASON_LINK_DOWN, reason)
    reason = jnp.where(host_down, LREASON_HOST_DOWN, reason)
    return reason


def alive(nm: NetemBlock):
    """[H] bool: hosts currently up (delivery gate)."""
    return nm.host_up > 0


def rate(nm, bw_Bps):
    """Scale an [H] i64 token-bucket rate by the per-host bandwidth
    overlay; identity (exact) when nm is None or the scale is 1000.

    The scaled uplink rate is what the flowscope link ring samples as
    `cap_Bps` (`--scope links`), so a bandwidth fault landing mid-run is
    visible as a capacity step in links.jsonl -- see docs/netem.md."""
    if nm is None:
        return bw_Bps
    return jnp.maximum((bw_Bps * nm.bw_x1000.astype(I64)) // SCALE_ONE,
                       jnp.asarray(1, I64))


def alive_rows(nm: NetemBlock, hoff, h: int):
    """`alive` for one mesh shard: rows [hoff, hoff+h) of the replicated
    overlay (parallel/mesh.py keeps the whole nm block on every shard so
    route_overlay can gather by global ids; per-host consumers slice)."""
    return jax.lax.dynamic_slice_in_dim(nm.host_up, hoff, h) > 0


def rate_rows(nm, bw_Bps, hoff, h: int):
    """`rate` for one mesh shard: bw_Bps is already the shard's local
    [h] slice, the replicated overlay's scale column is sliced to
    match."""
    if nm is None:
        return bw_Bps
    scale = jax.lax.dynamic_slice_in_dim(nm.bw_x1000, hoff, h)
    return jnp.maximum((bw_Bps * scale.astype(I64)) // SCALE_ONE,
                       jnp.asarray(1, I64))


def min_lat_scale_x1000(events) -> int:
    """Smallest latency scale any event in a host-side schedule can set
    (x1000); the conservative window must shrink by this factor at
    install time or lookahead would exceed the smallest live latency."""
    scales = [max(1, int(v)) for (_t, k, _a, _b, v) in events
              if k == EV_LINK_LAT]
    return min([SCALE_ONE] + scales)
