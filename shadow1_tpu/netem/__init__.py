"""Device-resident network dynamics & fault injection.

Schedules `{time, kind, a, b, value}` events (link/host up-down, latency
and loss scaling, partitions, bandwidth scaling) on a sorted device
block carried by `SimState.nm`, applied *inside* the jitted engine step
with zero host round-trips.  See docs/netem.md.

    from shadow1_tpu import netem
    tl = netem.timeline().link_down(0, 1, at=2 * SEC).link_up(0, 1, at=4 * SEC)
    state, params = netem.install(state, params, tl)
"""

from .state import (EV_BW_SCALE, EV_HOST_DOWN, EV_HOST_UP,  # noqa: F401
                    EV_LINK_DOWN, EV_LINK_LAT, EV_LINK_LOSS, EV_LINK_UP,
                    EV_PARTITION, KIND_BY_NAME, KIND_NAMES, LOSS_ONE,
                    SCALE_ONE, NetemBlock, make_netem_block)
from .timeline import (Timeline, install, load_json,  # noqa: F401
                       timeline)
from . import apply  # noqa: F401
