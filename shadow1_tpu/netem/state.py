"""Device-resident network-dynamics schedule + overlay (the netem block).

The reference mutates its topology under the workload --
`topology_getLatency/getReliability` consult live edge state and
`topology_attach/detach` move hosts (topology.c) -- which is how its Tor
and Bitcoin experiments model relay churn, degraded links, and
partitions.  Our routing matrices are baked at build time, so dynamics
live in a separate compact block: a SORTED event schedule carried on
`SimState.nm` (present-or-None like cap/log/tr) plus small overlay state
the delivery path consults every tick.

Design constraints, in order:

* Zero host round-trips: the cursor advances inside the jitted window
  loop; applying an event is a handful of masked updates.
* Bitwise neutrality: with no block installed the engine compiles the
  overlay away entirely; with a block installed but nothing active, the
  overlay math is integer/float-exact identity (scale 1000/1000 on i64
  latencies, `rel * 1.0` on f32 reliabilities), so a run with an empty
  or not-yet-due schedule is bit-identical to a run without one.
* O(H + L) overlay state, never O(H^2): per-host up/down + group ids,
  global scalars, and an L-slot sparse per-link override table sized at
  build time from the distinct link pairs the schedule names.
"""

from __future__ import annotations

from flax import struct
import jax.numpy as jnp

I32 = jnp.int32
I64 = jnp.int64

# Event kinds (the `kind` column of the schedule).  `a`/`b` are host
# indices (or -1 for "global"); `val` is the kind-specific argument:
# latency/bandwidth scales are fixed-point x1000, loss fractions x1e6,
# partitions carry a group bitmask.
EV_LINK_LAT = 1     # latency scale: a<0 global, else link (a,b)
EV_LINK_LOSS = 2    # injected loss fraction: a<0 global, else link (a,b)
EV_LINK_DOWN = 3    # link (a,b) down (both directions)
EV_LINK_UP = 4      # link (a,b) restored
EV_HOST_DOWN = 5    # host a down (sends and deliveries killed)
EV_HOST_UP = 6      # host a restored
EV_PARTITION = 7    # val = group bitmask isolated from the rest; 0 heals
EV_BW_SCALE = 8     # bandwidth scale: a<0 all hosts, else host a

KIND_NAMES = {
    EV_LINK_LAT: "latency_scale",
    EV_LINK_LOSS: "loss",
    EV_LINK_DOWN: "link_down",
    EV_LINK_UP: "link_up",
    EV_HOST_DOWN: "host_down",
    EV_HOST_UP: "host_up",
    EV_PARTITION: "partition",
    EV_BW_SCALE: "bandwidth_scale",
}
KIND_BY_NAME = {v: k for k, v in KIND_NAMES.items()}

# Fixed-point scales.
SCALE_ONE = 1000       # latency/bandwidth scale 1.0
LOSS_ONE = 1_000_000   # loss fraction 1.0

# Sentinel time for padding past the last event (never reached).
T_NEVER = (1 << 62)


@struct.dataclass
class NetemBlock:
    """Sorted event schedule + the overlay it maintains.

    Schedule arrays are fixed [N] (padded with T_NEVER rows); `cursor`
    counts applied events and doubles as the events-applied counter.
    The overlay is what the hot path reads: per-host up mask and group
    ids, partition bitmask, global latency/loss scalars, per-host
    bandwidth scale, and the sparse per-link override table keyed by
    normalized (min, max) host pairs fixed at build time."""

    # -- schedule ---------------------------------------------------------
    ev_time: jnp.ndarray   # [N] i64 absolute sim ns, ascending
    ev_kind: jnp.ndarray   # [N] i32 EV_*
    ev_a: jnp.ndarray      # [N] i32 host index or -1
    ev_b: jnp.ndarray      # [N] i32 host index or -1
    ev_val: jnp.ndarray    # [N] i32 kind-specific fixed-point argument
    cursor: jnp.ndarray    # i32 scalar: events applied so far

    # -- overlay ----------------------------------------------------------
    host_up: jnp.ndarray          # [H] i32 0/1
    group: jnp.ndarray            # [H] i32 partition group id (0..30)
    part_mask: jnp.ndarray        # i32 scalar group bitmask; 0 = healed
    lat_x1000: jnp.ndarray        # i32 scalar global latency scale
    loss_x1e6: jnp.ndarray        # i32 scalar global injected loss
    bw_x1000: jnp.ndarray         # [H] i32 per-host bandwidth scale

    # -- sparse per-link overrides (L may be 0) ---------------------------
    ov_a: jnp.ndarray             # [L] i32 min(host, host)
    ov_b: jnp.ndarray             # [L] i32 max(host, host)
    ov_lat_x1000: jnp.ndarray     # [L] i32; 0 = no override
    ov_loss_x1e6: jnp.ndarray     # [L] i32; -1 = no override
    ov_down: jnp.ndarray          # [L] i32 0/1

    # -- counters ---------------------------------------------------------
    killed: jnp.ndarray           # i64 packets killed by injected faults

    @property
    def n_events(self) -> int:
        return self.ev_time.shape[0]

    @property
    def n_links(self) -> int:
        return self.ov_a.shape[0]


def make_netem_block(num_hosts: int, events, link_pairs=(),
                     groups=None, n_events=None) -> NetemBlock:
    """Build a NetemBlock from a host-side event list.

    `events`: iterable of (time_ns, kind, a, b, val) -- sorted here
    (stable, so same-time events apply in insertion order).
    `link_pairs`: distinct (a, b) pairs that per-link events reference;
    the override table is sized to exactly these.
    `groups`: optional [H] group-id assignment for partitions.
    `n_events`: optional event-table bucket; extra slots stay T_NEVER
    (never fire), letting worlds with different schedule lengths share
    one shape (ensemble stacking).
    """
    import numpy as np

    evs = sorted(enumerate(events), key=lambda iv: (iv[1][0], iv[0]))
    evs = [v for _, v in evs]
    n = max(1, len(evs), 0 if n_events is None else int(n_events))
    t = np.full(n, T_NEVER, np.int64)
    k = np.zeros(n, np.int32)
    a = np.full(n, -1, np.int32)
    b = np.full(n, -1, np.int32)
    v = np.zeros(n, np.int32)
    for i, (et, ek, ea, eb, ev) in enumerate(evs):
        t[i], k[i], a[i], b[i], v[i] = et, ek, ea, eb, ev

    pairs = sorted({(min(x, y), max(x, y)) for x, y in link_pairs})
    la = np.asarray([p[0] for p in pairs], np.int32)
    lb = np.asarray([p[1] for p in pairs], np.int32)

    if groups is None:
        g = np.zeros(num_hosts, np.int32)
    else:
        g = np.asarray(groups, np.int32)
        if g.shape != (num_hosts,):
            raise ValueError(f"groups must be [{num_hosts}], "
                             f"got {g.shape}")
        if g.min() < 0 or g.max() > 30:
            raise ValueError("partition group ids must be in 0..30 "
                             "(they index an i32 bitmask)")

    return NetemBlock(
        ev_time=jnp.asarray(t, I64),
        ev_kind=jnp.asarray(k, I32),
        ev_a=jnp.asarray(a, I32),
        ev_b=jnp.asarray(b, I32),
        ev_val=jnp.asarray(v, I32),
        cursor=jnp.asarray(0, I32),
        host_up=jnp.ones(num_hosts, I32),
        group=jnp.asarray(g, I32),
        part_mask=jnp.asarray(0, I32),
        lat_x1000=jnp.asarray(SCALE_ONE, I32),
        loss_x1e6=jnp.asarray(0, I32),
        bw_x1000=jnp.full(num_hosts, SCALE_ONE, I32),
        ov_a=jnp.asarray(la, I32),
        ov_b=jnp.asarray(lb, I32),
        ov_lat_x1000=jnp.zeros(len(pairs), I32),
        ov_loss_x1e6=jnp.full(len(pairs), -1, I32),
        ov_down=jnp.zeros(len(pairs), I32),
        killed=jnp.asarray(0, I64),
    )
