"""Pad a world up to its shape bucket, bitwise-neutrally.

`pad_world_to_bucket` reuses the mesh padding machinery
(parallel/sharding.py pad_state_to_hosts / pad_params_to_hosts: fresh
per-host slabs, app PAD_VALUES inert fills, up/neutral netem rows) and
adds the two pieces mesh padding does not have:

* `params.hosts_real` -- a traced i32 scalar carrying the REAL host
  count.  App-level global draws (phold's dst pick) read it via
  params.global_hosts(), so no draw ever changes under padding and no
  packet ever targets a padded host.  Because it is a runtime input
  (not a Python int baked into the graph), every world padded into the
  same bucket shares ONE compiled run_until graph.

* route_blk V-padding: the [V*V, 5] packed routing block is re-laid out
  as a [Vb, Vb] matrix with zero rows for padded vertices.  Real
  vertices keep their indices, n_vertices (derived from the row count)
  becomes Vb, and padded rows are never gathered at runtime -- every
  live packet's src/dst is a real host on a real vertex.

The contract, enforced leaf-for-leaf by tests/test_shapes.py: a padded
world's real-host rows are BITWISE identical to the exact-size world's
trajectory at any horizon.  A world already exactly bucket-shaped
passes through untouched (same objects), so its compiled graph -- and
kernel counts -- are unchanged by bucketing.
"""

from __future__ import annotations

import warnings

import jax.numpy as jnp

from ..core.state import I32
from ..parallel import sharding as _sh
from .key import ShapeKey, bucket_for, shape_key


def _pad_route_blk(blk, v: int, vb: int):
    """Re-lay the packed [v*v, C] routing block out as [vb*vb, C] with
    zero rows for padded vertex pairs (latency 0 = "no route"; never
    gathered at runtime).  Row-major (vs, vd) indexing is preserved for
    real pairs because the whole matrix moves, not just rows."""
    c = blk.shape[1]
    m = jnp.zeros((vb, vb, c), blk.dtype)
    m = m.at[:v, :v, :].set(blk.reshape(v, v, c))
    return m.reshape(vb * vb, c)


def pad_world_to_bucket(state, params, bucket: ShapeKey | None = None):
    """Pad (state, params) up to `bucket` (default: bucket_for of the
    world's own ShapeKey).  Returns the padded pair; identity -- the
    same objects, hence byte-identical graphs -- when the world already
    sits exactly on the bucket's (hosts, vertices)."""
    key = shape_key(state, params)
    if bucket is None:
        bucket = bucket_for(key)
    if bucket.hosts < key.hosts or bucket.vertices < key.vertices:
        raise ValueError(f"pad_world_to_bucket: bucket ({bucket.hosts} "
                         f"hosts, {bucket.vertices} vertices) is smaller "
                         f"than the world ({key.hosts}, {key.vertices})")
    if bucket.hosts == key.hosts and bucket.vertices == key.vertices:
        return state, params
    if params.hosts_real is not None:
        raise ValueError("pad_world_to_bucket: params.hosts_real is "
                         "already set -- the world is already bucket-"
                         "padded; bucket once, then pad_world_to_mesh")
    # The real count rides params as a traced scalar BEFORE any row
    # padding: from here on, "how many hosts" and "how many rows" are
    # different questions with different answers.
    params = params.replace(hosts_real=jnp.asarray(key.hosts, I32))
    if bucket.vertices > key.vertices:
        warnings.warn(
            f"shapes: padded routing matrix from {key.vertices} to "
            f"{bucket.vertices} vertices (bucket)")
        params = params.replace(route_blk=_pad_route_blk(
            params.route_blk, key.vertices, bucket.vertices))
    if bucket.hosts > key.hosts:
        why = f"shape bucket {bucket.hosts}"
        state = _sh.pad_state_to_hosts(state, bucket.hosts, why)
        params = _sh.pad_params_to_hosts(params, bucket.hosts, why)
    return state, params
