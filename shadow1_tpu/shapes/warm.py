"""AOT warm cache: pre-compile the standard bucket set.

The package enables JAX's persistent compilation cache at import
(shadow1_tpu/__init__.py: SHADOW1_TPU_CACHE, default
~/.cache/shadow1_tpu_xla).  `warm_buckets` builds one canonical world
per (app flavor, host bucket), pads it into its bucket
(pad_world_to_bucket -- so the compiled graph is the SHARED one every
bucketed world of that shape hits, hosts_real included), and AOT
lowers + compiles run_until.  The resulting executables land in the
persistent cache; later processes that trace the same graph skip the
backend compile entirely, and `profile.compiles` / `compile_ms`
(trace.py) make the win directly measurable.

Front ends: `shadow1-tpu warm` (cli.py) and tools/warmcache.py.

A warm entry only helps worlds whose ShapeKey AND jit statics match the
canonical flavor, so the canonical worlds are deliberately the sweep
configurations: fixed per-host slab (pool_capacity = H * slab -- a
fixed TOTAL capacity would make the slab vary with H and fragment the
buckets), default flags, default app configs.  Sweeps with custom
shapes can warm themselves by running their smallest member first.
"""

from __future__ import annotations

import time

import jax

from ..core import engine, simtime

# Host buckets warmed by default: the small end of shapes.HOST_LADDER.
# The big rungs cost real compile time and memory, so they are opt-in
# (--buckets).
STANDARD_HOST_BUCKETS = (64, 256, 1024, 4096)

# Canonical per-host slabs (see module docstring): phold is the
# UDP-only/narrow-block flavor, bulk the TCP/wide-block flavor.  tgen/
# onion/gossip match their sim.py builder defaults, which the example
# ladder and scenario sweeps use.
PHOLD_SLAB = 8
BULK_SLAB = 32

# Flowscope config of the scope-present flavor ("bulk-scope"): the
# --scope default interval and both rings, so `--scope flows,links`
# sweeps hit the warm cache.  Non-default intervals reuse the same
# graph (the cadence is traced data, not a jit static); non-default
# ring CAPACITIES do not.
SCOPE_INTERVAL_NS = 100_000_000

WARM_APPS = ("phold", "bulk", "tgen", "onion", "gossip", "bulk-scope")


def _canonical_world(app_name: str, bucket_hosts: int):
    """A canonical world STRICTLY below the bucket size, so
    pad_world_to_bucket actually pads (installing hosts_real) and the
    compiled graph is the bucket-shared one, not the exact-size one."""
    from .. import sim
    h = max(2, bucket_hosts - 1)
    if app_name == "phold":
        s, p, a = sim.build_phold(num_hosts=h,
                                  pool_capacity=h * PHOLD_SLAB,
                                  stop_time=simtime.SIMTIME_ONE_SECOND)
    elif app_name in ("bulk", "bulk-scope"):
        s, p, a = sim.build_bulk(num_hosts=h,
                                 bytes_per_client=1 << 16,
                                 pool_capacity=h * BULK_SLAB,
                                 stop_time=simtime.SIMTIME_ONE_SECOND)
        if app_name == "bulk-scope":
            from .. import trace
            s = trace.ensure_flowscope(s, interval_ns=SCOPE_INTERVAL_NS)
    elif app_name == "tgen":
        s, p, a = sim.build_tgen(num_hosts=h,
                                 stop_time=simtime.SIMTIME_ONE_SECOND)
    elif app_name == "onion":
        # build_onion sizes by circuits (client + hops relays + server
        # per circuit, 5 hosts each at the default 3 hops); the biggest
        # circuit count still strictly below the bucket.
        s, p, a = sim.build_onion(
            num_circuits=max(1, (bucket_hosts - 1) // 5),
            bytes_per_circuit=1 << 16,
            stop_time=simtime.SIMTIME_ONE_SECOND)
    elif app_name == "gossip":
        s, p, a = sim.build_gossip(num_hosts=h,
                                   stop_time=simtime.SIMTIME_ONE_SECOND)
    else:
        raise ValueError(f"warm: unknown app flavor {app_name!r} "
                         f"(known: {', '.join(WARM_APPS)})")
    return s, p, a


def warm_buckets(buckets=None, apps=("phold", "bulk"), log=None):
    """Pre-lower and compile run_until for each (app, bucket) into the
    persistent XLA cache.  Returns a list of records
    {app, bucket_hosts, real_hosts, lower_s, compile_s}.  A bucket that
    is already cached still pays the (cheap) trace+lower, but its
    compile_s collapses to the cache-read time."""
    from .bucket import pad_world_to_bucket

    if buckets is None:
        buckets = STANDARD_HOST_BUCKETS
    # Cache compiles of any duration: the default 2s write floor
    # (shadow1_tpu/__init__.py) would silently skip fast CPU compiles,
    # making `warm` a no-op exactly where it is cheapest to test.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    records = []
    for hb in buckets:
        for app_name in apps:
            state, params, app = _canonical_world(app_name, int(hb))
            real = int(state.hosts.num_hosts)
            state, params = pad_world_to_bucket(state, params)
            # Warm every compiled flavor: megakernel AND persistent are
            # ShapeKey statics (a fused world, its persistent-window
            # variant and the reference oracle all trace different
            # graphs), and benchdiff --kernels compares expect each to
            # be hot.  persistent=True without megakernel never
            # compiles (persistent_enabled requires the fused gate), so
            # three flavors cover the space.
            for mk, ps in ((True, True), (True, False), (False, False)):
                pmk = params.replace(megakernel=mk, persistent=ps)
                t0 = time.perf_counter()
                lowered = engine.run_until.lower(
                    state, pmk, app, simtime.SIMTIME_ONE_SECOND)
                t1 = time.perf_counter()
                lowered.compile()
                t2 = time.perf_counter()
                rec = {"app": app_name, "bucket_hosts": int(hb),
                       "real_hosts": real, "megakernel": bool(mk),
                       "persistent": bool(ps),
                       "lower_s": round(t1 - t0, 3),
                       "compile_s": round(t2 - t1, 3)}
                records.append(rec)
                if log is not None:
                    log(rec)
    return records
