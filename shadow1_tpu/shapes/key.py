"""Shape identity and bucketing for compiled worlds.

XLA compiles one executable per distinct input SHAPE (plus the static
flags baked into the graph), and a run_until compile costs ~30-60s on
the tunnel backend -- so a sweep of dozens of world configs pays the
compile tax dozens of times (ROADMAP: "kill the 35s-per-world compile
tax").  This module makes that tax amortizable:

* `ShapeKey` canonicalizes every determinant of the compiled run_until
  graph's shape: host count H, the per-host pool/inbox slabs, the packed
  block widths (18 UDP-only / 28 TCP), socket slots, the routing vertex
  count V (route_blk is [V*V, 5]), the static NetParams flags
  (cong/has_iface_buf/pds_trail/has_loss/has_jitter/kernel_diet/
  megakernel/persistent, with route_narrow implied by has_jitter), and
  which
  present-or-None blocks
  ride the state (nm/cap/log/log_level/tr/fr/hoff) with their leaf
  shapes.

* `bucket_for(key)` rounds H (and V) up a small geometric ladder so
  different-sized scenarios land on the SAME shape; pad_world_to_bucket
  (bucket.py) then pads the world to the bucket while keeping real-host
  rows bitwise identical to the exact-size trajectory.

Two worlds sharing a bucketed ShapeKey share one compiled graph
PROVIDED their jit statics also match: the app object (__eq__/__hash__
over its config) and the NetParams statics are part of the jit cache
key.  Builders that want sharing should size pools per-slab
(pool_capacity = num_hosts * slab), since a fixed total capacity makes
the slab -- a shape determinant -- vary with H.  See docs/shapes.md.
"""

from __future__ import annotations

import dataclasses
import warnings

import jax

from ..core.state import KNOWN_BAD_POOL_HOSTS, KNOWN_BAD_POOL_SLAB

# Geometric host ladder (x4 per rung): small enough that padding waste
# is bounded (<4x rows, and padded rows are inert so they cost little
# work), large enough that a whole scenario sweep lands on a handful of
# buckets.  Every rung is divisible by any power-of-two device count up
# to 64, so bucketed worlds compose with pad_world_to_mesh without a
# second padding pass (docs/parallel.md).
HOST_LADDER = (64, 256, 1024, 4096, 16384, 65536, 262144, 1048576)

# Vertex ladder for route_blk's [V*V] row axis: quadratic cost, so it
# gets smaller rungs.  Builders cap V at 256 (sim.build_phold) but
# config topologies can exceed it.
VERTEX_LADDER = (16, 64, 256, 1024, 4096)

# The present-or-None SimState blocks whose presence (and shape) changes
# the traced graph.  `app` is keyed separately by type + leaf shapes.
# `scope` (the flowscope sampling block) includes its static
# sample_flows/sample_links flags via leaf shapes + jit statics.
_STATE_BLOCKS = ("nm", "cap", "log", "log_level", "tr", "fr", "scope",
                 "sentinel", "lineage", "dg", "hoff")


@dataclasses.dataclass(frozen=True)
class ShapeKey:
    """Canonical shape identity of a (state, params, app) world.  Two
    worlds with equal ShapeKeys (and equal jit statics: app config,
    NetParams flags already folded in here) trace identical graphs."""

    hosts: int
    vertices: int
    pool_slab: int
    inbox_slab: int
    sock_slots: int
    cols: int           # packed pool/outbox width: 18 UDP-only, 28 TCP
    icols: int          # inbox width: 14 UDP-only, 24 TCP
    has_loss: bool
    has_jitter: bool
    kernel_diet: bool
    megakernel: bool
    persistent: bool
    cong: str
    has_iface_buf: bool
    pds_trail: bool
    app: str | None             # app state type name, or None
    blocks: tuple               # ((name, leaf-shape signature), ...)

    @property
    def route_narrow(self) -> bool:
        """Jitter-free worlds gather the narrow 3-column routing rows
        (core/params.py route_narrow); implied by has_jitter."""
        return not self.has_jitter


def _leaf_shapes(obj):
    """Shape signature of a pytree block: the tuple of its leaf shapes.
    Good enough to distinguish any two blocks that trace differently."""
    return tuple(tuple(getattr(leaf, "shape", ()))
                 for leaf in jax.tree_util.tree_leaves(obj))


def shape_key(state, params) -> ShapeKey:
    """Read the ShapeKey off a built world."""
    h = int(state.hosts.num_hosts)
    blocks = tuple(
        (name, _leaf_shapes(getattr(state, name)))
        for name in _STATE_BLOCKS if getattr(state, name) is not None)
    return ShapeKey(
        hosts=h,
        vertices=int(params.n_vertices),
        pool_slab=int(state.pool.capacity) // h,
        inbox_slab=int(state.inbox.capacity) // h,
        sock_slots=int(state.socks.slots),
        cols=int(state.pool.blk.shape[1]),
        icols=int(state.inbox.blk.shape[1]),
        has_loss=bool(params.has_loss),
        has_jitter=bool(params.has_jitter),
        kernel_diet=bool(params.kernel_diet),
        megakernel=bool(params.megakernel),
        persistent=bool(params.persistent),
        cong=str(params.cong),
        has_iface_buf=bool(params.has_iface_buf),
        pds_trail=bool(params.pds_trail),
        app=(type(state.app).__name__ if state.app is not None else None),
        blocks=blocks,
    )


def key_manifest(key: ShapeKey) -> dict:
    """JSON-serializable form of a ShapeKey for checkpoint manifests
    (checkpoint.py): every static as a plain scalar, the present-or-None
    block signatures as {name: [[shape...], ...]}.  Round-trips through
    json.dumps/loads bitwise, so saved and freshly-computed manifests
    compare with plain ==."""
    d = dataclasses.asdict(key)
    d["blocks"] = {name: [list(s) for s in sig]
                   for name, sig in key.blocks}
    return d


def describe_key_mismatch(saved: dict, current: dict,
                          a_label: str = "checkpoint",
                          b_label: str = "template") -> str | None:
    """Name the first difference between two key_manifest() dicts, or
    None when they match.  Block differences name the BLOCK (a missing
    flight recorder, a log ring sized differently); static differences
    name the STATIC (cong, megakernel, pool_slab, ...) -- the load-time
    diagnosis checkpoint.load prints instead of a bare structure error.
    `a_label`/`b_label` rename the two sides for non-checkpoint callers
    (ensemble.stack compares world 0 against world k)."""
    sb = saved.get("blocks", {})
    cb = current.get("blocks", {})
    for name in _STATE_BLOCKS:
        in_s, in_c = name in sb, name in cb
        if in_s and not in_c:
            return (f"block {name!r} is present in the {a_label} but "
                    f"absent on the {b_label} (install it before loading)"
                    if a_label == "checkpoint" else
                    f"block {name!r} is present on the {a_label} but "
                    f"absent on the {b_label}")
        if in_c and not in_s:
            return (f"block {name!r} is present on the {b_label} but "
                    f"absent in the {a_label} (build the template "
                    f"without it; add instrumentation AFTER loading)"
                    if a_label == "checkpoint" else
                    f"block {name!r} is present on the {b_label} but "
                    f"absent on the {a_label}")
        if in_s and sb[name] != cb[name]:
            return (f"block {name!r} leaf shapes differ: {a_label} "
                    f"{sb[name]} vs {b_label} {cb[name]}")
    for field in sorted(set(saved) | set(current)):
        if field == "blocks":
            continue
        if saved.get(field) != current.get(field):
            return (f"static {field!r} differs: {a_label} "
                    f"{saved.get(field)!r} vs {b_label} "
                    f"{current.get(field)!r}")
    return None


def _round_up(n: int, ladder) -> int:
    for rung in ladder:
        if rung >= n:
            return rung
    return n


def bucket_for(key: ShapeKey, ladder=HOST_LADDER) -> ShapeKey:
    """The bucket a world belongs to: hosts rounded up the geometric
    ladder, vertices rounded up VERTEX_LADDER; every other determinant
    (slab, widths, flags, blocks) is preserved exactly -- rounding a
    slab is trajectory-visible (overflow drops, slot indices), so slabs
    never bucket.

    Slab-aware (core/state.py known-bad region): when rounding hosts up
    would move a world INTO the known-bad (hosts, slab) region that the
    exact-size world is not in, the host count stays exact (warning) --
    bucketing must never fabricate a backend-faulting configuration.
    Worlds already in the region bucket normally (they were warned at
    build time).  Beyond the ladder the host count also stays exact."""
    hb = _round_up(key.hosts, ladder)
    slab = max(key.pool_slab, key.inbox_slab)
    if (hb != key.hosts and slab >= KNOWN_BAD_POOL_SLAB
            and hb >= KNOWN_BAD_POOL_HOSTS
            and key.hosts < KNOWN_BAD_POOL_HOSTS):
        warnings.warn(
            f"shapes: not bucketing {key.hosts} hosts up to {hb}: slab "
            f"{slab} at >={KNOWN_BAD_POOL_HOSTS} hosts is the known-bad "
            f"tunnel-backend region (core/state.py warn_known_bad_pool);"
            f" rebuild with pool_slab<{KNOWN_BAD_POOL_SLAB} to bucket")
        return key
    vb = _round_up(key.vertices, VERTEX_LADDER)
    if hb == key.hosts and vb == key.vertices:
        return key
    return dataclasses.replace(key, hosts=hb, vertices=vb)
