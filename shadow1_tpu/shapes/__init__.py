"""Shape-polymorphic world buckets (docs/shapes.md).

Compiled graphs are keyed by input shapes; this subsystem canonicalizes
a world's shape determinants (`ShapeKey`), rounds host/vertex counts up
a geometric ladder (`bucket_for`), and pads worlds into their bucket
with real-host rows bitwise identical to the exact-size trajectory
(`pad_world_to_bucket`) -- so a sweep of different-sized scenarios
shares one compiled run_until graph instead of paying the 30-60s XLA
compile per world.  `warm_buckets` pre-compiles the standard bucket set
into the persistent XLA cache (`shadow1-tpu warm`).
"""

from .key import (HOST_LADDER, VERTEX_LADDER, ShapeKey, bucket_for,
                  describe_key_mismatch, key_manifest, shape_key)
from .bucket import pad_world_to_bucket
from .warm import STANDARD_HOST_BUCKETS, WARM_APPS, warm_buckets

__all__ = [
    "HOST_LADDER",
    "VERTEX_LADDER",
    "STANDARD_HOST_BUCKETS",
    "WARM_APPS",
    "ShapeKey",
    "bucket_for",
    "describe_key_mismatch",
    "key_manifest",
    "pad_world_to_bucket",
    "shape_key",
    "warm_buckets",
]
