"""Observability: per-host heartbeats + run summary (tracker analog).

The reference Tracker logs per-host heartbeat CSV lines (bytes in/out,
allocation, socket occupancy) at a configurable interval through the
shadow logger (/root/reference/src/main/host/tracker.c:419-607), consumed
by src/tools/parse-shadow.py.  Here the per-host counters already live in
dense device arrays (HostTable), so a heartbeat is one device_get of the
counter block per interval, diffed host-side and appended to
`heartbeat.csv` in the data directory; `tools/parse.py` aggregates them.

The run summary includes an object census (live sockets and packet-pool
occupancy by lifecycle stage) -- the analog of the reference's
ObjectCounter leak check printed at slave teardown (slave.c:480-498).

Heartbeats are host-side samples of whatever counters happen to be on
the device when the chunk boundary lands; for *sim-time-accurate*
per-flow and per-link series use the device-resident flowscope instead
(`--scope`, trace.ensure_flowscope/ScopeDrain, docs/observability.md),
which samples inside the jitted window loop at an exact sim-time
cadence.  LogDrain's sharded segment-merge protocol below is the
pattern ScopeDrain follows for its rings.
"""

from __future__ import annotations

import json
import os

import numpy as np

from . import trace
from .core import simtime
from .core.state import (SOCK_FREE, SOCK_TCP, SOCK_UDP, STAGE_FREE,
                         STAGE_IN_FLIGHT, STAGE_RX_QUEUED, STAGE_TX_QUEUED)

SEC = simtime.SIMTIME_ONE_SECOND

_FIELDS = ("bytes_sent", "bytes_recv", "pkts_sent", "pkts_recv",
           "pkts_dropped_inet", "pkts_dropped_router")


_pack_heartbeat_jit = None


def _pack_heartbeat(hosts):
    # Jitted once at first use (a fresh jax.jit wrapper per call would
    # retrace and recompile every heartbeat).
    global _pack_heartbeat_jit
    if _pack_heartbeat_jit is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def pack(hosts):
            rows = [getattr(hosts, f).astype(jnp.int64) for f in _FIELDS]
            rows.append(hosts.tx_queued.astype(jnp.int64))
            rows.append(hosts.rx_queued.astype(jnp.int64))
            return jnp.stack(rows)

        _pack_heartbeat_jit = pack
    return _pack_heartbeat_jit(hosts)


class Tracker:
    """Appends per-host heartbeat rows; one instance per run.

    Ensemble runs share one heartbeat.csv across W per-world trackers:
    `world` prefixes every row with a world column (and the header with
    `world,`), `write_header=False` keeps trackers 1..W-1 from
    truncating what world 0 wrote -- the drain-layer world-column
    convention (docs/ensemble.md)."""

    HEADER = ("time_s,host,bytes_sent_per_s,bytes_recv_per_s,"
              "pkts_sent,pkts_recv,drops_inet,drops_router,"
              "tx_queued,rx_queued\n")

    def __init__(self, data_dir: str, hostnames, interval_s: int = 1,
                 per_host_interval_s=None, world: int | None = None,
                 write_header: bool = True):
        self.dir = data_dir
        self.world = world
        self.hostnames = list(hostnames)
        self.interval_ns = interval_s * SEC
        h = len(self.hostnames)
        # Per-host heartbeat frequency (reference <host
        # heartbeatfrequency>); 0 = the global default interval.
        per = np.zeros(h, np.int64) if per_host_interval_s is None \
            else np.asarray(per_host_interval_s, np.int64)
        self.per_host_ns = np.where(per > 0, per * SEC, self.interval_ns)
        # The cadence the RUN LOOP must sample at: the finest interval any
        # host configured (else a host asking for finer-than-global rows
        # silently got the coarser global cadence; ADVICE r3).
        self.sample_interval_ns = int(min(self.interval_ns,
                                          self.per_host_ns.min())) \
            if h else self.interval_ns
        self._next_row = np.zeros(h, np.int64)
        self._last_row_t = np.zeros(h, np.int64)
        os.makedirs(data_dir, exist_ok=True)
        self.path = os.path.join(data_dir, "heartbeat.csv")
        if write_header:
            with open(self.path, "w") as f:
                f.write(self.HEADER if world is None
                        else "world," + self.HEADER)
        self._last = {f: np.zeros(h, np.int64) for f in _FIELDS}
        self._last_t = 0  # _last rows advance per written heartbeat row

    def heartbeat(self, state, now_ns: int):
        with trace.current().span("heartbeat", t_ns=int(now_ns)):
            self._heartbeat(state, now_ns)

    def _heartbeat(self, state, now_ns: int):
        # ONE device buffer, ONE transfer: per-buffer fetches each cost a
        # full round trip on a tunneled backend (~0.1-1s), and heartbeats
        # fire once per simulated second.
        packed = np.asarray(_pack_heartbeat(state.hosts))
        trace.current().transfer(packed.nbytes, count=1)
        n = len(_FIELDS)
        cur = {f: packed[i] for i, f in enumerate(_FIELDS)}
        txq, rxq = packed[n], packed[n + 1]
        with open(self.path, "a") as f:
            for i, name in enumerate(self.hostnames):
                if now_ns < self._next_row[i]:
                    continue
                self._next_row[i] = now_ns + self.per_host_ns[i]
                # Rates divide by the PER-HOST elapsed time (a host on a
                # 5s cadence accumulates 5s of deltas per row).
                dt_s = max((now_ns - self._last_row_t[i]) / SEC, 1e-9)
                self._last_row_t[i] = now_ns
                d = {k: int(cur[k][i] - self._last[k][i]) for k in _FIELDS}
                if self.world is not None:
                    f.write(f"{self.world},")
                f.write(f"{now_ns / SEC:.3f},{name},"
                        f"{d['bytes_sent'] / dt_s:.1f},"
                        f"{d['bytes_recv'] / dt_s:.1f},"
                        f"{d['pkts_sent']},{d['pkts_recv']},"
                        f"{d['pkts_dropped_inet']},{d['pkts_dropped_router']},"
                        f"{int(txq[i])},{int(rxq[i])}\n")
                # Baseline advances ONLY for written rows, so skipped
                # hosts' deltas accumulate into their next row instead of
                # vanishing.
                for k in _FIELDS:
                    self._last[k][i] = cur[k][i]
        self._last_t = now_ns

    def summary(self, summary: dict, state):
        summary = dict(summary)
        summary["object_census"] = census(state)
        with open(os.path.join(self.dir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)


def write_pcap(path: str, cap, ip_of_host=None, host_filter=None):
    """Write a CaptureRing to a classic pcap file (LINKTYPE_RAW IPv4).

    The ring stores packet *metadata*; each record is synthesized as an
    IPv4 + TCP/UDP header whose total-length field reflects the real
    payload size (a truncated capture: incl_len = header bytes,
    orig_len = header + payload) -- the same information the reference's
    per-interface capture exposes (utility/pcap_writer.c).

    ip_of_host: optional callable host_index -> 32-bit IP (e.g. from the
    DNS registry); defaults to 10.x.y.z derived from the index.
    host_filter: optional host index -- that host's per-interface view:
    its SENT records plus its RECEIVE-direction records (deliveries and
    router drops), like the reference's per-host logpcap capture which
    records both directions (network_interface.c:337-373,415-418).
    Without a filter, only send-direction records are kept so the global
    wire view lists each packet once.
    """
    import struct as pystruct

    from .core.state import CAP_SEND

    if ip_of_host is None:
        def ip_of_host(i):
            return (10 << 24) | (int(i) & 0xFFFFFF)

    t = np.asarray(cap.time)
    # A sharded ring (make_capture_ring shards=N, mesh runs) has a [N]
    # cursor vector and per-shard segments; a single-device ring is the
    # N=1 degenerate case with a scalar cursor.
    tot_a = np.atleast_1d(np.asarray(cap.total))
    shards = tot_a.shape[0]
    c = t.shape[0]
    per = c // shards
    segs = []
    for s in range(shards):
        total = int(tot_a[s])
        n = min(total, per)
        # Oldest-first order within the segment; wraps at `total % per`.
        start = total % per if total > per else 0
        segs.append(s * per + (np.arange(n) + start) % per)
    order = np.concatenate(segs)
    if shards > 1:
        # Merge shard segments into global time order (stable, so the
        # shard-major walk breaks ties deterministically).
        order = order[np.argsort(t[order], kind="stable")]

    src = np.asarray(cap.src)
    dst = np.asarray(cap.dst)
    kind = np.asarray(cap.kind)
    if host_filter is not None:
        keep = ((src[order] == host_filter) & (kind[order] == CAP_SEND)) | \
            ((dst[order] == host_filter) & (kind[order] != CAP_SEND))
        order = order[keep]
    else:
        order = order[kind[order] == CAP_SEND]
    sport = np.asarray(cap.sport)
    dport = np.asarray(cap.dport)
    proto = np.asarray(cap.proto)
    flags = np.asarray(cap.flags)
    length = np.asarray(cap.length)
    seq = np.asarray(cap.seq)
    ack = np.asarray(cap.ack)

    with open(path, "wb") as f:
        # pcap global header: magic, v2.4, tz 0, sigfigs 0, snaplen,
        # linktype 101 (LINKTYPE_RAW: raw IPv4/IPv6).
        f.write(pystruct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 65535, 101))
        for k in order:
            is_tcp = int(proto[k]) == 6
            l4 = (pystruct.pack(">HHIIBBHHH", int(sport[k]) & 0xFFFF,
                                int(dport[k]) & 0xFFFF, int(seq[k]),
                                int(ack[k]), 5 << 4, int(flags[k]) & 0x3F,
                                65535, 0, 0)
                  if is_tcp else
                  pystruct.pack(">HHHH", int(sport[k]) & 0xFFFF,
                                int(dport[k]) & 0xFFFF,
                                8 + int(length[k]), 0))
            tot_len = 20 + len(l4) + int(length[k])
            ip = pystruct.pack(">BBHHHBBHII", 0x45, 0, tot_len & 0xFFFF, 0,
                               0, 64, int(proto[k]) & 0xFF, 0,
                               ip_of_host(int(src[k])),
                               ip_of_host(int(dst[k])))
            rec = ip + l4
            ts_ns = int(t[k])
            f.write(pystruct.pack("<IIII", ts_ns // 1_000_000_000,
                                  (ts_ns % 1_000_000_000) // 1000,
                                  len(rec), tot_len))
            f.write(rec)
    return len(order)


_LOG_MSG = {
    1: "packet to host {arg} dropped on the wire (reliability)",
    2: "router dropped packet from host {arg} (CoDel)",
    3: "router tail-dropped packet from host {arg} (interface buffer)",
    4: "packet-pool capacity drop ({arg})",
    5: "delivered packet from host {arg}",
    6: "sent packet to host {arg}",
    7: "thinned {arg} pure ACKs at exchange overflow",
    8: "netem: inbound packet from host {arg} killed (host down)",
}


class LogDrain:
    """Drains the device LogRing into sim-time-ordered text lines:

        [  1.234567890] [hostname] message

    The two-tier ShadowLogger analog (core/logger/shadow_logger.c:25-58):
    the device ring buffers records, the host merges and writes them
    between chunks.  Overflow (more records than ring capacity between
    drains) is reported, not silently lost.

    Sharded rings (make_log_ring shards=N, mesh runs) drain per shard
    segment and merge into global sim-time order; record host ids are
    global on every layout, so the hostname mapping is unchanged.

    `world` prefixes every line with a `[w<k>]` tag; `path` may be an
    already-open shared file (ensemble runs interleave W worlds' lines
    into one shadow.log; trace._open_sink ownership rules)."""

    def __init__(self, path, hostnames, world: int | None = None):
        self.path = path
        self.hostnames = list(hostnames)
        self.world = world
        self._last_total = 0
        self._last_tot = None   # [shards] per-segment cursors, lazy
        self._lost_reported = 0
        self._f, self._own = trace._open_sink(path)

    def drain(self, state):
        with trace.current().span("log_drain"):
            return self._drain(state)

    def _drain(self, state):
        import jax
        lg = state.log
        if lg is None:
            return 0
        tot_a, lost_a = jax.device_get((lg.total, lg.lost))
        tot_a = np.atleast_1d(np.asarray(tot_a, np.int64))
        lost_a = np.atleast_1d(np.asarray(lost_a, np.int64))
        shards = tot_a.shape[0]
        trace.current().transfer(16, count=1)
        lost = int(lost_a.sum())
        if lost > self._lost_reported:
            self._f.write(f"[log] WARNING: {lost - self._lost_reported} "
                          f"records lost inside oversized appends\n")
            self._lost_reported = lost
        if self._last_tot is None:
            self._last_tot = np.zeros(shards, np.int64)
        total = int(tot_a.sum())
        if total == self._last_total:
            return 0
        t, host, code, arg = jax.device_get(
            (lg.time, lg.host, lg.code, lg.arg))
        trace.current().transfer(
            t.nbytes + host.nbytes + code.nbytes + arg.nbytes, count=1)
        per = t.shape[0] // shards
        new = total - self._last_total
        wrap_lost = 0
        parts = []
        for s in range(shards):
            total_s = int(tot_a[s])
            ns = total_s - int(self._last_tot[s])
            if ns <= 0:
                continue
            if ns > per:
                wrap_lost += ns - per
                start = total_s - per
            else:
                start = int(self._last_tot[s])
            parts.append(s * per + (np.arange(start, total_s) % per))
            self._last_tot[s] = total_s
        if wrap_lost:
            self._f.write(f"[log] WARNING: {wrap_lost} records lost "
                          f"(ring capacity {per})\n")
        idx = np.concatenate(parts)
        order = np.argsort(t[idx], kind="stable")
        wtag = "" if self.world is None else f"[w{self.world}] "
        for k in idx[order]:
            name = self.hostnames[host[k]] if host[k] < len(self.hostnames) \
                else str(host[k])
            msg = _LOG_MSG.get(int(code[k]), f"event {code[k]}")
            self._f.write(f"[{t[k] / SEC:13.9f}] {wtag}[{name}] "
                          + msg.format(arg=int(arg[k])) + "\n")
        self._f.flush()
        self._last_total = total
        return new

    def close(self):
        if self._own:
            self._f.close()


def census(state) -> dict:
    """Live-object census from the dense tables (ObjectCounter analog).

    Packets live in the source-side outbox (state.pool) until the window
    exchange, then in the destination-side inbox; both are counted."""
    stage = np.asarray(state.pool.stage)
    istage = np.asarray(state.inbox.stage)
    stype = np.asarray(state.socks.stype)
    return {
        "packets_free": int((stage == STAGE_FREE).sum())
        + int((istage == STAGE_FREE).sum()),
        "packets_tx_queued": int((stage == STAGE_TX_QUEUED).sum()),
        "packets_in_flight": int((stage == STAGE_IN_FLIGHT).sum())
        + int((istage == STAGE_IN_FLIGHT).sum()),
        "packets_rx_queued": int((istage == STAGE_RX_QUEUED).sum()),
        "sockets_free": int((stype == SOCK_FREE).sum()),
        "sockets_udp": int((stype == SOCK_UDP).sum()),
        "sockets_tcp": int((stype == SOCK_TCP).sum()),
    }


def _si(v: float) -> str:
    """Compact SI-ish rate formatting: 1234567 -> '1.23M'."""
    for div, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if v >= div:
            return f"{v / div:.2f}{suffix}"
    return f"{v:.0f}"


class Progress:
    """One-line live status for long runs (the CLI's --progress): sim
    time covered, event rate, window rate, and a wall-clock ETA, written
    to stderr at most once per `min_interval_s` of wall time.

    Each report costs one small device_get (n_events + n_windows, both
    replicated scalars under a mesh) and a `progress` profiler span, at
    chunk cadence -- cheap enough to leave on for multi-hour runs, which
    is the point (the reference prints its own heartbeat lines through
    the logger; our heartbeats go to CSV, so silence needed a channel).
    """

    def __init__(self, stop_ns: int, out=None, min_interval_s: float = 2.0,
                 start_ns: int = 0):
        import sys
        import time as _time
        self.stop_ns = int(stop_ns)
        # start_ns anchors the percentage/ETA for spans that begin
        # mid-run (a checkpoint replay): progress covers
        # [start_ns, stop_ns], not [0, stop_ns].
        self.start_ns = int(start_ns)
        self.out = out if out is not None else sys.stderr
        self.min_interval = min_interval_s
        self._clock = _time.perf_counter
        self._wall_last = self._clock()
        self._ev_last = 0
        self._win_last = 0
        self._t_last = self.start_ns

    def update(self, state, t_ns: int, force: bool = False):
        now = self._clock()
        dt = now - self._wall_last
        if not force and dt < self.min_interval:
            return
        import jax
        with trace.current().span("progress"):
            ev, wins = (int(v) for v in jax.device_get(
                (state.n_events, state.n_windows)))
            trace.current().transfer(16, count=1)
        dt = max(dt, 1e-9)
        ev_s = (ev - self._ev_last) / dt
        win_s = (wins - self._win_last) / dt
        sim_per_wall = ((int(t_ns) - self._t_last) / SEC) / dt
        remain_s = max(self.stop_ns - int(t_ns), 0) / SEC
        if sim_per_wall > 0 and remain_s / sim_per_wall < 360000:
            e = int(remain_s / sim_per_wall)
            eta = f"{e // 3600}:{(e // 60) % 60:02d}:{e % 60:02d}"
        else:
            eta = "-:--:--"
        pct = 100.0 * (int(t_ns) - self.start_ns) \
            / max(self.stop_ns - self.start_ns, 1)
        self.out.write(
            f"[progress] sim {int(t_ns) / SEC:.1f}s/"
            f"{self.stop_ns / SEC:.1f}s ({pct:.0f}%) | "
            f"{_si(ev_s)} ev/s | {wins} windows ({win_s:.1f}/s) | "
            f"ETA {eta}\n")
        self.out.flush()
        self._wall_last = now
        self._ev_last = ev
        self._win_last = wins
        self._t_last = int(t_ns)
