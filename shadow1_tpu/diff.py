"""Statescope diff: first-divergence localization between two runs.

The reference debugging story for "two runs disagree" is printf
archaeology: re-run both with more logging and eyeball the logs until
something differs.  Here every run can carry a statescope digest block
(core/state.py DigestBlock, trace.ensure_digests): at the close of every
N-th window the device folds each state field-group -- pool, inbox,
socks, hosts, rng, netem, app -- into a 64-bit checksum per host-shard,
drained to digests.jsonl.  Digests are deterministic and bitwise
trajectory-neutral, and a mesh run's per-shard columns equal the
single-device run's, so two digest streams are directly comparable
across seeds, configs, device counts, and backends (megakernel on/off).

`diff_runs` is the comparison in three escalating stages:

  1. STREAM ALIGN -- index both digests.jsonl streams by global window,
     walk the common windows in order, and name the first divergent
     (window, field group, shard).  When the runs recorded different
     shard counts (mesh vs single device) the per-shard columns are
     wrap-summed first: the group checksum is a commutative i64 sum
     over elements, so the reduction is shard-layout-independent by
     construction.
  2. ANCHOR -- for checkpointed runs (--checkpoint-every), restore each
     run's nearest checkpoint at-or-before the last AGREEING window
     (replay.find_checkpoint + checkpoint.load on the rebuilt world
     template).
  3. RE-EXECUTE + LOCALIZE -- re-run both spans to the same sim time
     (the divergent window's recorded t_end; chunking is trajectory-
     invariant, so an off-grid target is safe for state comparison),
     gather both states to the host, and compare the divergent field
     group leaf-by-leaf, element-by-element: the report names the
     field, flat index, owning host, expected/got values, and -- for
     float leaves -- the absolute and ulp deltas.

Uncheckpointed digest runs stop after stage 1 with a note; the stream
report alone already names the window and field group.

Comparability is validated eagerly and by name (the replay --window
range-error pattern): a directory that is not a digest-recorded run, a
digest-cadence mismatch, a schema mismatch (checkpoint manifests stamp
the field-group schema version), or a --devices override that matches
neither run's recorded layout all raise DiffUsageError before any
device work.  Exit-code mapping lives in cli.diff_cmd: 0 agree,
1 diverged, 2 usage.

See docs/observability.md "Statescope"; tools/divergediff.py drives the
three comparison axes (run-vs-run, mesh-vs-single, backend-vs-backend).
"""

from __future__ import annotations

import json
import os

from .core.state import DIGEST_GROUPS, DIGEST_SCHEMA

_M64 = (1 << 64) - 1


class DiffUsageError(ValueError):
    """A user-facing diff failure: not a digest-recorded run, or two
    runs whose digest configs are incomparable (named in the message)."""


def _wrap_sum(vals) -> int:
    """Wrapping-i64 sum of a shard-column list: the reduction that maps
    a [D]-column digest row onto its single-shard value (the group
    checksum is a commutative mod-2^64 sum over elements)."""
    s = sum(int(v) for v in vals) & _M64
    return s - (1 << 64) if s >= (1 << 63) else s


def load_digests(data_dir: str) -> dict:
    """Load one run's digest record: rows from digests.jsonl plus the
    comparability stamps (cadence, shard count, schema, device count)
    from ckpt/run.json and the newest checkpoint manifest when the run
    was checkpointed.  Raises DiffUsageError when `data_dir` is not a
    digest-recorded run directory."""
    if not os.path.isdir(data_dir):
        raise DiffUsageError(
            f"{data_dir}: not a run data directory (expected the "
            f"--data-directory of a digest-recorded run)")
    path = os.path.join(data_dir, "digests.jsonl")
    if not os.path.exists(path):
        raise DiffUsageError(
            f"{path}: no digest record -- re-run with --digest-every N "
            f"(or sim.run(digest=N)) to make the run diffable")
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        raise DiffUsageError(f"{path}: empty digest record")
    info = {}
    run_json = os.path.join(data_dir, "ckpt", "run.json")
    if os.path.exists(run_json):
        with open(run_json) as f:
            info = json.load(f)
    every = info.get("digest")
    if not every:
        # Uncheckpointed digest run: infer the cadence from the global
        # window stamps (rows record their window index, so the stream
        # itself carries the grid).
        every = (rows[1]["window"] - rows[0]["window"]
                 if len(rows) > 1 else None)
    schema = None
    ckpt_dir = os.path.join(data_dir, "ckpt")
    if os.path.isdir(ckpt_dir):
        from . import replay as replay_mod
        try:
            _, man = replay_mod.find_checkpoint(data_dir, None)
            schema = (man.get("digest") or {}).get("schema")
        except (FileNotFoundError, ValueError):
            pass
    shards = len(rows[0]["sums"][DIGEST_GROUPS[0]])
    # World-axis stamp: run.json for ensemble runs, else the rows
    # themselves (ensemble DigestDrains stamp a "world" column).
    # Missing on both means a legacy/solo record -- 1.
    n_worlds = int(info.get("n_worlds") or 0)
    if not n_worlds:
        n_worlds = len({r["world"] for r in rows if "world" in r}) or 1
    return {"dir": data_dir, "rows": rows, "every": every,
            "shards": shards, "schema": schema,
            "devices": info.get("devices"),
            "n_worlds": n_worlds,
            "checkpointed": os.path.exists(run_json)}


def _check_comparable(a: dict, b: dict, devices) -> None:
    """Named refusals for incomparable digest records -- eager, before
    any stream walk or device work."""
    for r in (a, b):
        if r.get("n_worlds", 1) != 1:
            raise DiffUsageError(
                f"{r['dir']}: digest record of a {r['n_worlds']}-world "
                f"ensemble run -- the stream interleaves per-world rows "
                f"and a pairwise diff would silently mix world axes; "
                f"summarize per world with `tools/parse.py ensemble` "
                f"(first-divergence-from-world-0 is computed there)")
    if a["every"] and b["every"] and int(a["every"]) != int(b["every"]):
        raise DiffUsageError(
            f"digest cadence mismatch: {a['dir']} recorded every "
            f"{a['every']} window(s), {b['dir']} every {b['every']} -- "
            f"the streams sample different windows and cannot be "
            f"aligned; re-run one side with --digest-every "
            f"{a['every']}")
    for r in (a, b):
        if r["schema"] is not None and int(r["schema"]) != DIGEST_SCHEMA:
            raise DiffUsageError(
                f"{r['dir']}: digest field-group schema "
                f"{r['schema']} does not match this build's schema "
                f"{DIGEST_SCHEMA} (core/state.py DIGEST_GROUPS "
                f"changed); re-record the run with this build")
    if devices is not None:
        for r in (a, b):
            orig = int(r["devices"] or 1)
            if r["checkpointed"] and int(devices) not in (orig, 1):
                raise DiffUsageError(
                    f"diff --devices {int(devices)}: {r['dir']} is a "
                    f"checkpoint of a {orig}-device run; it re-executes "
                    f"on the original mesh or gathers to 1 device, "
                    f"nothing in between (the shard layout is baked "
                    f"into the saved rings)")


def compare_streams(rows_a: list, rows_b: list) -> dict:
    """Stage 1: align two digest streams by global window and find the
    first divergent (window, group, shard).

    Returns {"divergence": None | {...}, "windows_compared": n,
    "last_agreeing_window": K | None, "notes": [...]}.  Shard columns
    are compared per-shard when both runs recorded the same count and
    wrap-sum-reduced otherwise (mesh-vs-single)."""
    by_a = {r["window"]: r for r in rows_a}
    by_b = {r["window"]: r for r in rows_b}
    common = sorted(set(by_a) & set(by_b))
    notes = []
    if not common:
        raise DiffUsageError(
            f"the digest streams share no windows (a: "
            f"{min(by_a)}..{max(by_a)}, b: {min(by_b)}..{max(by_b)}) "
            f"-- different cadences or disjoint spans")
    only_a = len(by_a) - len(common)
    only_b = len(by_b) - len(common)
    if only_a or only_b:
        notes.append(f"windows recorded by one run only: "
                     f"{only_a} in a, {only_b} in b (different stop "
                     f"times or ring wrap); compared the "
                     f"{len(common)} common windows")
    last_ok = None
    for w in common:
        ra, rb = by_a[w], by_b[w]
        if int(ra["t_end"]) != int(rb["t_end"]):
            # Same window index ending at different sim times: the
            # trajectories disagree about the window structure itself
            # (or the runs used different launch grids).  The window
            # boundary is part of the state evolution, so this IS the
            # divergence -- attribute it to the earliest group whose
            # checksum also differs, if any.
            notes.append(f"window {w}: t_end differs "
                         f"({int(ra['t_end'])} vs {int(rb['t_end'])})")
        for g in DIGEST_GROUPS:
            ca = [int(v) for v in ra["sums"][g]]
            cb = [int(v) for v in rb["sums"][g]]
            if len(ca) == len(cb):
                if ca != cb:
                    shard = next(i for i, (x, y) in
                                 enumerate(zip(ca, cb)) if x != y)
                    return {"divergence": {
                                "window": int(w),
                                "t_end": {"a": int(ra["t_end"]),
                                          "b": int(rb["t_end"])},
                                "group": g, "shard": shard},
                            "windows_compared": common.index(w) + 1,
                            "last_agreeing_window": last_ok,
                            "notes": notes}
            elif _wrap_sum(ca) != _wrap_sum(cb):
                return {"divergence": {
                            "window": int(w),
                            "t_end": {"a": int(ra["t_end"]),
                                      "b": int(rb["t_end"])},
                            "group": g, "shard": None},
                        "windows_compared": common.index(w) + 1,
                        "last_agreeing_window": last_ok,
                        "notes": notes}
        if int(ra["t_end"]) != int(rb["t_end"]):
            return {"divergence": {
                        "window": int(w),
                        "t_end": {"a": int(ra["t_end"]),
                                  "b": int(rb["t_end"])},
                        "group": None, "shard": None},
                    "windows_compared": common.index(w) + 1,
                    "last_agreeing_window": last_ok,
                    "notes": notes}
        last_ok = int(w)
    return {"divergence": None, "windows_compared": len(common),
            "last_agreeing_window": last_ok, "notes": notes}


# ---------------------------------------------------------------------------
# Stage 2/3: checkpoint-anchored re-execution and element localization.

def _group_fields(state) -> dict:
    """The digest field-groups as named (field, leaf) lists -- the
    human-facing twin of engine._digest_group_leaves (same leaves, same
    grouping, plus pytree path names for the report)."""
    import jax.tree_util as jtu

    out = {g: [] for g in DIGEST_GROUPS}

    def add(group, prefix, tree):
        if tree is None:
            return
        for path, leaf in jtu.tree_flatten_with_path(tree)[0]:
            out[group].append((prefix + jtu.keystr(path), leaf))

    add("pool", "pool", state.pool)
    add("inbox", "inbox", state.inbox)
    add("socks", "socks", state.socks)
    for path, leaf in jtu.tree_flatten_with_path(state.hosts)[0]:
        name = "hosts" + jtu.keystr(path)
        g = "rng" if name.endswith((".rng_ctr", ".send_ctr")) else "hosts"
        out[g].append((name, leaf))
    add("netem", "nm", state.nm)
    # nm.killed is not digested (a per-shard partial under mesh, see
    # engine._digest_group_leaves), so it must not drive localization
    # either -- a mesh-vs-single re-execution pair can legitimately
    # disagree on the partial while every digested leaf matches.
    out["netem"] = [(n, l) for n, l in out["netem"]
                    if not n.endswith(".killed")]
    add("app", "app", state.app)
    return out


def _ulp_delta(a: float, b: float, bits: int) -> int:
    """Distance in representable floats between two same-width values:
    map the raw bit patterns onto the sign-magnitude-ordered integer
    line and subtract."""
    import numpy as np
    ui = np.uint32 if bits == 32 else np.uint64
    fi = np.float32 if bits == 32 else np.float64
    top = 1 << (bits - 1)

    def ordered(x):
        u = int(np.asarray(x, fi).view(ui))
        return (top - (u - top)) if u & top else (u + top)

    return abs(ordered(a) - ordered(b))


def _element_report(name, a, b, num_hosts, max_elements) -> dict | None:
    """Per-leaf comparison: None when bitwise equal, else the field's
    differing-element report (count, first `max_elements` elements with
    index / host / expected / got, float deltas)."""
    import numpy as np
    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape or a.dtype != b.dtype:
        raise DiffUsageError(
            f"field {name}: shapes differ ({a.dtype}{a.shape} vs "
            f"{b.dtype}{b.shape}) -- the runs have different world "
            f"configs and cannot be element-compared")
    fa, fb = a.reshape(-1), b.reshape(-1)
    if a.dtype.kind == "f":
        # Bitwise comparison (NaN == NaN, -0.0 != +0.0): the digest is
        # a function of the raw bits, so the localization must be too.
        ib = np.uint32 if a.dtype.itemsize == 4 else np.uint64
        neq = fa.view(ib) != fb.view(ib)
    else:
        neq = fa != fb
    idxs = np.flatnonzero(neq)
    if idxs.size == 0:
        return None
    n = int(a.shape[0]) if a.ndim else 1
    per_host = (a.size // num_hosts) if a.ndim and n % num_hosts == 0 \
        else None
    elements = []
    for i in idxs[:max_elements]:
        i = int(i)
        el = {"flat_index": i,
              "index": [int(x) for x in np.unravel_index(i, a.shape)]
              if a.ndim else [],
              "expected": _jsonable(fa[i]), "got": _jsonable(fb[i])}
        if per_host:
            el["host"] = i // per_host
        if a.dtype.kind == "f":
            el["abs_delta"] = abs(float(fa[i]) - float(fb[i]))
            el["ulp_delta"] = _ulp_delta(fa[i], fb[i],
                                         a.dtype.itemsize * 8)
        elements.append(el)
    return {"field": name, "dtype": str(a.dtype),
            "shape": list(a.shape), "elements_differing": int(idxs.size),
            "first": elements}


def _jsonable(v):
    import numpy as np
    v = np.asarray(v)
    if v.dtype.kind == "f":
        return float(v)
    return int(v)


def _reexec(data_dir: str, anchor_window: int, target_ns: int,
            devices=None):
    """Restore `data_dir`'s nearest checkpoint at-or-before
    `anchor_window` and re-execute to sim time `target_ns` on the
    original launch grid (capped at the target: off-grid stops are
    trajectory-invariant, engine.run_chunked).  Returns the host-side
    gathered state plus anchor metadata."""
    import jax

    from . import checkpoint as ckpt_mod
    from . import replay as replay_mod
    from .parallel.sharding import unshard

    info = replay_mod.load_run(data_dir)
    path, man = replay_mod.find_checkpoint(data_dir, anchor_window)
    n_orig = int(man.get("devices") or info.get("devices") or 1)
    exec_dev = n_orig if devices is None else int(devices)
    if exec_dev not in (n_orig, 1):
        raise DiffUsageError(
            f"diff --devices {exec_dev}: {data_dir} is a checkpoint of "
            f"a {n_orig}-device run; it re-executes on the original "
            f"mesh or gathers to 1 device, nothing in between")
    built = replay_mod.rebuild_world(info, data_dir,
                                     want_mesh=exec_dev > 1)
    state, params = ckpt_mod.load(path, built["state"], built["params"])
    app, mesh = built["app"], built["mesh"]
    if exec_dev == 1:
        mesh = None
    t = int(state.now)
    hb_ns, every_ns = info.get("hb_ns"), info.get("every_ns")
    stop = int(info["stop_ns"])
    while t < int(target_ns):
        t = min(replay_mod.next_sync(t, stop, hb_ns, every_ns),
                int(target_ns))
        if mesh is not None:
            from . import parallel
            state = parallel.mesh_run_chunked(state, params, app, t,
                                              mesh=mesh)
        else:
            from .core import engine
            state = engine.run_chunked(state, params, app, t)
    jax.block_until_ready(state)
    return {"state": unshard(state),
            "anchor": {"checkpoint": os.path.basename(path),
                       "window": int(man["window"]),
                       "t_ns": int(man["t_ns"]), "devices": exec_dev}}


def localize_elements(dir_a: str, dir_b: str, stream: dict, *,
                      devices=None, max_elements: int = 8) -> dict:
    """Stage 2+3: checkpoint-anchored element localization of a stream
    divergence.  Re-executes both runs from their last agreeing
    anchors to the divergent window's t_end and element-compares the
    divergent field group first, then every other group."""
    div = stream["divergence"]
    # Anchor at the DIVERGENT window, not the last agreeing one: a
    # checkpoint at window W holds the state at W's *start*, so the
    # nearest checkpoint at-or-before the divergent window still
    # predates that window's digest row -- and it is the newest anchor
    # that provably carries each run's own trajectory (including any
    # externally injected state the digests first noticed here).
    anchor_w = int(div["window"])
    # Both streams agreed on every window up to the anchor, so the two
    # t_end stamps agree there; for the divergent window itself they
    # may not -- compare at the earlier of the two (states at one sim
    # time are directly comparable; chunking is trajectory-invariant).
    target = min(int(div["t_end"]["a"]), int(div["t_end"]["b"]))
    a = _reexec(dir_a, anchor_w, target, devices=devices)
    b = _reexec(dir_b, anchor_w, target, devices=devices)
    sa, sb = a["state"], b["state"]
    h = int(sa.hosts.num_hosts)
    if int(sb.hosts.num_hosts) != h:
        raise DiffUsageError(
            f"the runs have different (padded) host counts "
            f"({h} vs {int(sb.hosts.num_hosts)}) and cannot be "
            f"element-compared; pad both to the same layout")
    ga, gb = _group_fields(sa), _group_fields(sb)
    # The stream names the divergent group; element-compare it first so
    # the report leads with the cause, then sweep the rest (a single
    # root divergence usually fans out into several groups by the end
    # of the window).
    order = list(DIGEST_GROUPS)
    if div["group"] in order:
        order.remove(div["group"])
        order.insert(0, div["group"])
    fields = []
    groups_differing = []
    for g in order:
        hit = False
        for (name, la), (_, lb) in zip(ga[g], gb[g]):
            rep = _element_report(name, la, lb, h, max_elements)
            if rep is not None:
                rep["group"] = g
                fields.append(rep)
                hit = True
        if hit:
            groups_differing.append(g)
    return {"anchor": {"a": a["anchor"], "b": b["anchor"]},
            "target_ns": target,
            "groups_differing": groups_differing,
            "fields": fields}


def diff_runs(dir_a: str, dir_b: str, *, localize: bool = True,
              devices=None, max_elements: int = 8,
              quiet: bool = True) -> dict:
    """Compare two digest-recorded runs; returns the report dict.

    `localize=False` stops at the stream comparison (stage 1).  Raises
    DiffUsageError for non-runs or incomparable digest configs."""
    a = load_digests(dir_a)
    b = load_digests(dir_b)
    _check_comparable(a, b, devices)
    stream = compare_streams(a["rows"], b["rows"])
    report = {
        "runs": {"a": dir_a, "b": dir_b},
        "every": a["every"] or b["every"],
        "shards": {"a": a["shards"], "b": b["shards"]},
        "windows_compared": stream["windows_compared"],
        "last_agreeing_window": stream["last_agreeing_window"],
        "divergence": stream["divergence"],
        "localization": None,
        "notes": list(stream["notes"]),
    }
    if stream["divergence"] is None:
        return report
    if not localize:
        report["notes"].append("localization skipped (--no-localize)")
        return report
    if not (a["checkpointed"] and b["checkpointed"]):
        missing = [r["dir"] for r in (a, b) if not r["checkpointed"]]
        report["notes"].append(
            f"element localization needs checkpointed runs; "
            f"{' and '.join(missing)} recorded no checkpoints "
            f"(re-run with --checkpoint-every)")
        return report
    if not quiet:
        import sys
        d = stream["divergence"]
        print(f"[shadow1-tpu] diff: digest streams diverge at window "
              f"{d['window']} (group {d['group']}, shard {d['shard']}); "
              f"re-executing both spans to localize", file=sys.stderr)
    report["localization"] = localize_elements(
        dir_a, dir_b, stream, devices=devices,
        max_elements=max_elements)
    return report


def format_report(report: dict) -> str:
    """The human-readable diff report (the --json flag prints the dict
    instead)."""
    lines = []
    div = report["divergence"]
    if div is None:
        lines.append(
            f"no divergence: {report['windows_compared']} digest "
            f"window(s) agree across every field group "
            f"(a: {report['runs']['a']}, b: {report['runs']['b']})")
    else:
        shard = "" if div["shard"] is None else f", shard {div['shard']}"
        lines.append(
            f"DIVERGED at window {div['window']}: field group "
            f"'{div['group']}'{shard} "
            f"(last agreeing window: {report['last_agreeing_window']})")
    loc = report.get("localization")
    if loc:
        aa, ab = loc["anchor"]["a"], loc["anchor"]["b"]
        lines.append(
            f"  re-executed from {aa['checkpoint']} (window "
            f"{aa['window']}) / {ab['checkpoint']} (window "
            f"{ab['window']}) to t={loc['target_ns']} ns")
        lines.append(f"  field groups differing: "
                     f"{', '.join(loc['groups_differing'])}")
        for f in loc["fields"]:
            lines.append(
                f"  {f['field']} [{f['group']}] {f['dtype']}"
                f"{tuple(f['shape'])}: {f['elements_differing']} "
                f"element(s) differ")
            for el in f["first"]:
                host = f" host {el['host']}" if "host" in el else ""
                delta = ""
                if "ulp_delta" in el:
                    delta = (f" (abs {el['abs_delta']:g}, "
                             f"{el['ulp_delta']} ulp)")
                lines.append(
                    f"    [{','.join(str(i) for i in el['index'])}]"
                    f"{host}: expected {el['expected']}, got "
                    f"{el['got']}{delta}")
    for note in report.get("notes", []):
        lines.append(f"  note: {note}")
    return "\n".join(lines)
