"""Checkpoint / resume: serialize the whole simulation to disk.

The reference has no checkpointing at all (SURVEY.md §5 flags it as a
cheap win for the rebuild): Shadow runs must complete in one process
lifetime.  Here the entire simulation -- packet pool, socket table, host
counters, application state, and the run's NetParams -- is one pytree of
dense arrays, so a checkpoint is a flat .npz of its leaves and resume is
bitwise-exact: run(save -> load -> continue) equals run-straight.

Format: numpy .npz with keys "s<N>" / "p<N>" for the N-th leaf of the
state / params pytree (in tree order), plus tree-structure fingerprints
to catch template mismatches at load time.  Loading requires a *template*
(state, params) pair built the same way as the saved run (same config,
shapes, apps); the template supplies the pytree structure, the file
supplies every value.
"""

from __future__ import annotations

import numpy as np

import jax


def _fingerprint(tree) -> str:
    return str(jax.tree_util.tree_structure(tree))


def save(path: str, state, params) -> None:
    """Write state + params to `path` (.npz)."""
    s_leaves = jax.tree_util.tree_leaves(state)
    p_leaves = jax.tree_util.tree_leaves(params)
    out = {f"s{i}": np.asarray(x) for i, x in enumerate(s_leaves)}
    out.update({f"p{i}": np.asarray(x) for i, x in enumerate(p_leaves)})
    out["_s_struct"] = np.array(_fingerprint(state))
    out["_p_struct"] = np.array(_fingerprint(params))
    with open(path, "wb") as f:
        np.savez(f, **out)


def load(path: str, template_state, template_params):
    """Rebuild (state, params) from `path` using the templates' structure.

    Every leaf value comes from the file; shapes and dtypes must match the
    template (same config/apps), which is also verified structurally.
    """
    with np.load(path, allow_pickle=False) as z:
        if str(z["_s_struct"]) != _fingerprint(template_state) or \
                str(z["_p_struct"]) != _fingerprint(template_params):
            raise ValueError(
                "checkpoint structure does not match the template "
                "(different config, app, or version)")

        def rebuild(template, prefix):
            leaves, treedef = jax.tree_util.tree_flatten(template)
            vals = []
            for i, leaf in enumerate(leaves):
                v = z[f"{prefix}{i}"]
                want = jax.numpy.asarray(leaf)
                if v.shape != want.shape or v.dtype != want.dtype:
                    hint = ""
                    if v.ndim == 2 and want.ndim == 2 and \
                            v.shape[0] == want.shape[0] and \
                            v.shape[1] != want.shape[1]:
                        # Same row count, different column count: almost
                        # certainly a packed-block width mismatch (the
                        # outbox/inbox narrow for TCP-free worlds).
                        hint = ("; packed blocks narrow for TCP-free "
                                "worlds (core/state.py pool_cols) -- "
                                "build the template with the saved "
                                "run's uses_tcp setting")
                    raise ValueError(
                        f"checkpoint leaf {prefix}{i} is {v.dtype}{v.shape}, "
                        f"template wants {want.dtype}{want.shape}{hint}")
                vals.append(jax.numpy.asarray(v))
            return jax.tree_util.tree_unflatten(treedef, vals)

        state = rebuild(template_state, "s")
        params = rebuild(template_params, "p")
    return state, params
