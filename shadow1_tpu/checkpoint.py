"""Checkpoint / resume: serialize the whole simulation to disk.

The reference has no checkpointing at all (SURVEY.md §5 flags it as a
cheap win for the rebuild): Shadow runs must complete in one process
lifetime.  Here the entire simulation -- packet pool, socket table, host
counters, application state, and the run's NetParams -- is one pytree of
dense arrays, so a checkpoint is a flat .npz of its leaves and resume is
bitwise-exact: run(save -> load -> continue) equals run-straight.

Format (version 1): numpy .npz with keys "s<N>" / "p<N>" for the N-th
leaf of the state / params pytree (in tree order), tree-structure
fingerprints to catch template mismatches at load time, and a
"_manifest" JSON blob stamping the world's ShapeKey fingerprint
(shapes.key_manifest: every compile-shape static plus which
present-or-None blocks ride the state and their leaf shapes), the
global window index and sim time of the snapshot, and -- for mesh /
bucketed runs -- the shard layout and padding (devices, hosts_padded,
hosts_real) so replay can restore onto the same mesh or gather down to
a single device (replay.py, docs/observability.md "Time-travel
replay").

Format version 2 extends the manifest to STACKED ensemble states
(docs/ensemble.md): every leaf carries its leading [n_worlds] axis in
the file, the ShapeKey fingerprint comes from a world-0 slice (every
member shares one key -- ensemble.stack refused otherwise), and the
manifest stamps `n_worlds`, the per-world window counters (`windows`),
per-world clocks (`t_ns_worlds`), and any quarantine-frozen world
indices (`frozen`).  `load` refuses only MISMATCHED world counts --
naming both values and the `--worlds N` that matches -- and can slice
one member out solo (`world=K`), which is what `replay --world K`
restores bitwise (the per-world dual-seeding discipline in
docs/ensemble.md makes the solo rerun well-defined).

Loading requires a *template* (state, params) pair built the same way
as the saved run (same config, shapes, apps); the template supplies the
pytree structure, the file supplies every value.  On a mismatch the
error names the differing block or static from the manifest (a missing
flight recorder, a different cong/megakernel/pool_slab, the uses_tcp
packed-block width) rather than a bare structure error.  Files written
before the manifest existed (version 0) still load with the structural
check only.
"""

from __future__ import annotations

import json

import numpy as np

import jax

FORMAT_VERSION = 1
STACKED_FORMAT_VERSION = 2


def _fingerprint(tree) -> str:
    return str(jax.tree_util.tree_structure(tree))


def _world0(tree):
    """World-0 slice of a stacked tree (shape/static probes need a solo
    view; every world shares one ShapeKey by ensemble.stack's refusal)."""
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def world_manifest(state, params, **extra) -> dict:
    """The manifest dict save() stamps: format version, ShapeKey
    fingerprint (statics + block presence/shapes), snapshot position
    (global window index + sim time), and any caller extras (shard
    layout, padding, run identity).

    Always stamps `n_worlds` (1 for a solo run) so replay/diff refuse
    loudly instead of silently mixing world axes.  A STACKED ensemble
    state stamps format 2: the ShapeKey comes from a world-0 slice,
    `window`/`t_ns` summarize the stack (max window for the anchor
    filename; min clock -- the shared launch boundary of the ACTIVE
    worlds, since quarantined worlds park their clock at
    ensemble.FROZEN_NOW), and the per-world `windows` / `t_ns_worlds` /
    `frozen` tables let resume trim and `replay --world K` address each
    member on its own counters (docs/robustness.md "Ensemble
    resilience")."""
    from . import shapes
    from .core.state import world_count
    w = world_count(state)
    if w is None:
        probe_s, probe_p = state, params
        m = {
            "format": FORMAT_VERSION,
            "window": int(state.n_windows),
            "t_ns": int(state.now),
            "n_worlds": 1,
        }
    else:
        from .ensemble import FROZEN_NOW
        probe_s, probe_p = _world0(state), _world0(params)
        wins = [int(x) for x in
                np.asarray(jax.device_get(state.n_windows)).ravel()]
        nows = [int(x) for x in
                np.asarray(jax.device_get(state.now)).ravel()]
        active = [t for t in nows if t < FROZEN_NOW]
        m = {
            "format": STACKED_FORMAT_VERSION,
            "window": max(wins),
            "windows": wins,
            "t_ns": min(active) if active else min(nows),
            "t_ns_worlds": nows,
            "frozen": [k for k, t in enumerate(nows) if t >= FROZEN_NOW],
            "n_worlds": int(w),
        }
    m["shape"] = shapes.key_manifest(shapes.shape_key(probe_s, probe_p))
    if getattr(probe_s, "dg", None) is not None:
        # Statescope stamp: `shadow1-tpu diff` refuses to compare runs
        # whose digest cadence or field-group schema differ, by name
        # (shadow1_tpu/diff.py), instead of mis-aligning streams.
        from .core.state import DIGEST_SCHEMA
        m["digest"] = {"every": int(probe_s.dg.every),
                       "schema": DIGEST_SCHEMA,
                       "shards": int(probe_s.dg.n_shards)}
    m.update(extra)
    return m


def save(path: str, state, params, manifest: dict | None = None) -> None:
    """Write state + params to `path` (.npz).

    `manifest` extras (devices, hosts_real, ...) merge into the stamped
    world_manifest.  Sharded mesh states save transparently: the single
    device_get below gathers every shard's rows into the full host-side
    array (parallel/sharding.py unshard), so the file layout is
    identical to a single-device save of the same world.

    The write is ATOMIC: bytes land in `path + ".tmp"` and only an
    os.replace publishes them under `path`, so a crash mid-save leaves
    either the previous complete file or a stray .tmp -- never a torn
    checkpoint under the real name.  A crash during save must never
    destroy the recovery anchor the supervisor resumes from
    (docs/robustness.md).
    """
    import os
    from .parallel.sharding import unshard
    m = world_manifest(state, params, **(manifest or {}))
    state, params = unshard((state, params))
    s_leaves = jax.tree_util.tree_leaves(state)
    p_leaves = jax.tree_util.tree_leaves(params)
    out = {f"s{i}": np.asarray(x) for i, x in enumerate(s_leaves)}
    out.update({f"p{i}": np.asarray(x) for i, x in enumerate(p_leaves)})
    out["_s_struct"] = np.array(_fingerprint(state))
    out["_p_struct"] = np.array(_fingerprint(params))
    out["_manifest"] = np.array(json.dumps(m, sort_keys=True))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **out)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_manifest(path: str) -> dict | None:
    """The manifest stamped into a checkpoint, or None for files written
    before the manifest existed."""
    with np.load(path, allow_pickle=False) as z:
        if "_manifest" not in z.files:
            return None
        return json.loads(str(z["_manifest"]))


def _mismatch_detail(z, template_state, template_params) -> str:
    """Name what differs between a checkpoint and a template: the
    manifest comparison names the first differing block/static; legacy
    files fall back to the bare structure message."""
    if "_manifest" not in z.files:
        return "different config, app, or version"
    from . import shapes
    from .core.state import world_count
    saved = json.loads(str(z["_manifest"]))
    if world_count(template_state) is not None:
        template_state = _world0(template_state)
        template_params = _world0(template_params)
    cur = shapes.key_manifest(
        shapes.shape_key(template_state, template_params))
    detail = shapes.describe_key_mismatch(saved.get("shape", {}), cur)
    if detail is None:
        # Identical ShapeKeys but different tree structure: app type or
        # params version changed in a way the key doesn't capture.
        return ("same shape fingerprint but different pytree structure "
                "(app or params version mismatch)")
    return detail


def _check_worlds(saved, template_worlds, world, path):
    """The world-axis gate: refuse MISMATCHED world counts by name
    (both values, plus the `--worlds N` that matches), and validate a
    `world=K` slice request.  Legacy files without the stamp are solo
    by construction (missing means 1)."""
    saved_worlds = int((saved or {}).get("n_worlds", 1))
    tw = 1 if template_worlds is None else int(template_worlds)
    if world is not None:
        k = int(world)
        if saved_worlds == 1:
            raise ValueError(
                f"{path}: world={k} requested but the checkpoint is a "
                f"solo snapshot (n_worlds 1); world slicing only "
                f"applies to stacked ensemble checkpoints")
        if template_worlds is not None:
            raise ValueError(
                f"{path}: world={k} restores ONE member solo; pass a "
                f"solo template, not a {tw}-world stacked one")
        if not 0 <= k < saved_worlds:
            raise ValueError(
                f"{path}: world={k} is out of range; the checkpoint "
                f"holds worlds 0..{saved_worlds - 1}")
        return saved_worlds
    if saved_worlds != tw:
        if template_worlds is None:
            raise ValueError(
                f"checkpoint was saved by a {saved_worlds}-world "
                f"ensemble run: loading it into a solo run would "
                f"silently mix world axes; re-run the ensemble "
                f"(--worlds {saved_worlds}), or slice one member out "
                f"(checkpoint.load(..., world=K) / replay --world K)")
        raise ValueError(
            f"checkpoint world count mismatch: the file holds "
            f"n_worlds {saved_worlds} but the template is a "
            f"{tw}-world stack; re-run with --worlds {saved_worlds} "
            f"to match the saved ensemble")
    return saved_worlds


def load(path: str, template_state, template_params, world=None):
    """Rebuild (state, params) from `path` using the templates' structure.

    Every leaf value comes from the file; shapes and dtypes must match the
    template (same config/apps), which is also verified structurally and
    -- for manifest-stamped files -- against the template's ShapeKey, so
    the error names the differing block or static.

    Stacked checkpoints (format 2) load into an equally-stacked template
    -- mismatched world counts are refused naming both values -- or,
    with `world=K`, slice member K off every leaf's leading axis into a
    SOLO template: the restored world is bitwise the slice
    `ensemble.world(estate, eparams, K)` of the saved stack, which is
    what `replay --world K` anchors on.
    """
    from .core.state import world_count
    template_worlds = world_count(template_state)
    with np.load(path, allow_pickle=False) as z:
        # Manifest check first: a same-structure world with different
        # shapes (more hosts, a wider slab) would otherwise surface as a
        # bare "leaf s8" error; the ShapeKey comparison names the block
        # or static instead.  The world-axis gate runs before any shape
        # comparison so axis mixing is named as such.
        saved = None
        if "_manifest" in z.files:
            saved = json.loads(str(z["_manifest"]))
        _check_worlds(saved, template_worlds, world, path)
        if world is not None:
            # ensemble.stack forces megakernel off on every member
            # (Pallas has no vmap batching rule), so the stacked file
            # was saved with it off.  Statics ride the template treedef,
            # not the file: normalize so the restored solo member runs
            # the same reference path the ensemble ran (bitwise replay).
            template_params = template_params.replace(megakernel=False)
        if saved is not None:
            from . import shapes
            probe_s, probe_p = template_state, template_params
            if template_worlds is not None:
                probe_s, probe_p = _world0(probe_s), _world0(probe_p)
            cur = shapes.key_manifest(shapes.shape_key(probe_s, probe_p))
            detail = shapes.describe_key_mismatch(
                saved.get("shape", {}), cur)
            if detail is not None:
                raise ValueError(
                    "checkpoint does not match the template: " + detail)
        if str(z["_s_struct"]) != _fingerprint(template_state) or \
                str(z["_p_struct"]) != _fingerprint(template_params):
            raise ValueError(
                "checkpoint structure does not match the template: "
                + _mismatch_detail(z, template_state, template_params))

        def rebuild(template, prefix):
            leaves, treedef = jax.tree_util.tree_flatten(template)
            vals = []
            for i, leaf in enumerate(leaves):
                v = z[f"{prefix}{i}"]
                if world is not None:
                    # Slice member K off the leading world axis; the
                    # remaining dims must match the solo template.
                    v = v[int(world)]
                want = jax.numpy.asarray(leaf)
                if v.shape != want.shape or v.dtype != want.dtype:
                    hint = ""
                    if v.ndim == 2 and want.ndim == 2 and \
                            v.shape[0] == want.shape[0] and \
                            v.shape[1] != want.shape[1]:
                        # Same row count, different column count: almost
                        # certainly a packed-block width mismatch (the
                        # outbox/inbox narrow for TCP-free worlds).
                        hint = ("; packed blocks narrow for TCP-free "
                                "worlds (core/state.py pool_cols) -- "
                                "build the template with the saved "
                                "run's uses_tcp setting")
                    raise ValueError(
                        f"checkpoint leaf {prefix}{i} is {v.dtype}{v.shape}, "
                        f"template wants {want.dtype}{want.shape}{hint}")
                vals.append(jax.numpy.asarray(v))
            return jax.tree_util.tree_unflatten(treedef, vals)

        state = rebuild(template_state, "s")
        params = rebuild(template_params, "p")
    return state, params
