"""Command-line front end: run a shadow.config.xml on the TPU engine.

The reference binary is `shadow [options] config.xml` (options.c); this
is the same surface for the rebuilt engine:

    python -m shadow1_tpu run examples/shadow.config.xml

Runs the simulation in bounded device launches, then prints a run summary
(per-host transfer completions, traffic counters) to stdout.

World assembly lives in `build_world` so `run` and `replay` construct
bitwise-identical templates from the same flags: a checkpointed run
records its world flags in ckpt/run.json (replay.write_run_json), and
`shadow1-tpu replay` feeds them back through build_world to rebuild the
exact pytree the checkpoints restore into (docs/observability.md
"Time-travel replay").
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import types

import jax
import jax.numpy as jnp

from .core import engine, simtime
from .supervise import (RC_FAILED, RC_INVARIANT, RC_OK, RC_USAGE,
                        UnrecoveredFailure)

SEC = simtime.SIMTIME_ONE_SECOND
MS = simtime.SIMTIME_ONE_MILLISECOND


class CliError(Exception):
    """A user-facing CLI failure: message for stderr plus an exit code."""

    def __init__(self, msg: str, rc: int = 2):
        super().__init__(msg)
        self.rc = rc


# The flags that determine the WORLD -- pytree structure, shapes, and
# initial values.  A checkpointed run stamps exactly these into
# ckpt/run.json; replay rebuilds its load template from them.  Flags
# outside this list (--data-directory, --heartbeat-frequency,
# --progress, --quiet) affect only host-side I/O, never the world.
_WORLD_ARGS = (
    "config", "seed", "stop_time", "sock_slots", "pool_slab",
    "tcp_congestion_control", "interface_qdisc", "cpu_threshold",
    "cpu_precision", "pcap", "pcap_ring", "netem", "churn",
    "churn_downtime", "log_level", "log_ring", "profile", "bucket",
    "devices", "scope", "trace_packets", "flight_rows",
    "digest_every", "digest_rows", "checkpoint_every")


def world_args(args) -> dict:
    """The world-determining flags as a JSON-able dict (paths made
    absolute so a replay launched from another cwd still resolves
    them)."""
    import os
    d = {k: getattr(args, k, None) for k in _WORLD_ARGS}
    d["config"] = os.path.abspath(d["config"])
    if d.get("netem"):
        d["netem"] = os.path.abspath(d["netem"])
    return d


def _add_run_flags(r, *, config_required: bool = True):
    """The full run-flag surface, shared verbatim by `run` and
    `submit`: a submit spec is exactly a run invocation shipped over
    the serve socket, so the two surfaces can never drift apart.
    `config_required=False` makes the config positional optional
    (submit also accepts --world / --replay request kinds)."""
    if config_required:
        r.add_argument("config", help="shadow.config.xml path")
    else:
        r.add_argument("config", nargs="?", default=None,
                       help="shadow.config.xml path (or pass --world / "
                            "--replay instead)")
    r.add_argument("--seed", type=int, default=1,
                   help="root RNG seed (reference --seed)")
    r.add_argument("--stop-time", type=int, default=None,
                   help="override <shadow stoptime> (seconds)")
    r.add_argument("--sock-slots", type=int, default=None,
                   help="per-host socket-table slots (default: auto)")
    r.add_argument("--pool-slab", type=int, default=128,
                   help="packet-pool slots per host")
    r.add_argument("--tcp-congestion-control", choices=("reno", "cubic"),
                   default="reno",
                   help="TCP congestion-control algorithm "
                        "(reference --tcp-congestion-control)")
    r.add_argument("--interface-qdisc", choices=("fifo", "rr"),
                   default="fifo",
                   help="NIC socket-selection discipline "
                        "(reference --interface-qdisc)")
    r.add_argument("--cpu-threshold", type=int, default=-1,
                   help="microseconds of CPU backlog after which a host "
                        "blocks; -1 disables (reference --cpu-threshold)")
    r.add_argument("--cpu-precision", type=int, default=200,
                   help="CPU wake-time rounding in microseconds "
                        "(reference --cpu-precision)")
    r.add_argument("--data-directory", default=None,
                   help="where to write heartbeat/summary files")
    r.add_argument("--pcap", action="store_true",
                   help="capture sent packets and write capture.pcap to "
                        "the data directory (reference logpcap)")
    r.add_argument("--pcap-ring", type=int, default=1 << 17,
                   help="capture ring capacity; older records are "
                        "silently overwritten on wrap (each packet now "
                        "costs up to two records: send + receive "
                        "direction, hence the doubled default)")
    r.add_argument("--netem", metavar="EVENTS.json", default=None,
                   help="network-dynamics schedule: JSON events file "
                        "(link_down/up, host_down/up, latency_scale, "
                        "loss, partition, bandwidth_scale; host names "
                        "resolve against the config's DNS) applied "
                        "inside the device step -- see docs/netem.md")
    r.add_argument("--churn", type=float, metavar="RATE", default=None,
                   help="seeded chaos mode: every host flaps down at "
                        "RATE times per second on average (exponential "
                        "up/down times, bitwise reproducible per --seed)")
    r.add_argument("--churn-downtime", type=float, default=5.0,
                   metavar="SECONDS",
                   help="mean down-time per chaos flap (default 5s)")
    r.add_argument("--heartbeat-frequency", type=int, default=1,
                   help="heartbeat interval in sim seconds (0 = off)")
    r.add_argument("--log-level", choices=("off", "warning", "debug"),
                   default="off",
                   help="simulation event log level (reference --log-level); "
                        "writes shadow.log to the data directory.  NOTE: "
                        "debug logs EVERY send/deliver -- for large worlds "
                        "scope it to hosts of interest via <host "
                        "loglevel=\"debug\"> in the config, or the ring "
                        "overflows between drains (lost records are "
                        "counted and reported)")
    r.add_argument("--log-ring", type=int, default=0,
                   help="event-log ring capacity (0 = auto: 64k, grown to "
                        "1M under global debug so a full drain interval "
                        "fits)")
    r.add_argument("--profile", action="store_true",
                   help="profile the run: write trace.json (Chrome "
                        "trace-event format; open in chrome://tracing or "
                        "ui.perfetto.dev) and metrics.json (per-phase "
                        "p50/p95 wall times, transfer bytes, JIT compile "
                        "count) to the data directory and print a phase "
                        "summary table (see docs/observability.md)")
    r.add_argument("--progress", action="store_true",
                   help="print a one-line live status to stderr every few "
                        "seconds of wall time: sim time covered, event "
                        "rate, window rate, ETA -- for long runs that "
                        "would otherwise be silent")
    r.add_argument("--quiet", action="store_true")
    r.add_argument("--bucket", action="store_true",
                   help="pad the world up to its shape bucket "
                        "(shapes.pad_world_to_bucket: host count rounded "
                        "up the geometric ladder, real-host rows bitwise "
                        "identical to the exact-size run) so different-"
                        "sized configs reuse one compiled graph -- see "
                        "docs/shapes.md.  Composes with --devices: bucket "
                        "first, then mesh-pad")
    r.add_argument("--devices", type=int, default=1, metavar="N",
                   help="shard the run across N devices "
                        "(parallel.mesh_run_until: the window loop under "
                        "shard_map with a dst-bucketed all-to-all exchange; "
                        "bitwise-identical to single-device, see "
                        "docs/parallel.md).  Worlds whose host count does "
                        "not divide N are padded with inert hosts.  The "
                        "observability stack (--pcap, --log-level, "
                        "--profile, heartbeats) runs sharded; only "
                        "real-process plugins remain single-device")
    r.add_argument("--worlds", type=int, default=1, metavar="N",
                   help="ensemble mode (docs/ensemble.md): run N whole "
                        "simulations as one vmapped batch over a "
                        "leading world axis -- one compiled graph "
                        "serves every world.  World k runs with seed "
                        "SEED+k and is bitwise identical to the solo "
                        "run `--seed SEED+k` on the same launch grid.  "
                        "Artifact rows (heartbeat.csv, digests.jsonl, "
                        "...) carry a world column; summary.json holds "
                        "one summary per world.  Composes with "
                        "--devices: worlds are placed world-major over "
                        "the device mesh (N must divide the world "
                        "count).  Composes with --checkpoint-every / "
                        "--auto-resume / --watchdog (stacked anchors, "
                        "per-world quarantine -- docs/robustness.md "
                        "\"Ensemble resilience\") and with serve/"
                        "submit.  Unsupported combos (--pcap, "
                        "--profile, real-process plugins) are refused "
                        "by name")
    r.add_argument("--sweep", metavar="SWEEP.json", default=None,
                   help="ensemble sweep spec: JSON object, either "
                        "{\"seeds\": [1, 2, ...]} (one world per seed) "
                        "or {\"worlds\": [{\"seed\": 1, \"churn\": "
                        "0.5}, ...]} with per-world overrides of "
                        "seed/churn/churn_downtime -- only knobs that "
                        "leave compile shapes unchanged may vary, so "
                        "every world runs the same compiled graph.  "
                        "Implies --worlds <count>; the resolved spec "
                        "is recorded in run.json")
    r.add_argument("--scope", metavar="SPEC", default=None,
                   help="flowscope: sample per-flow TCP state (cwnd, "
                        "ssthresh, srtt, inflight, retransmits, bytes) "
                        "and/or per-host link state (bytes forwarded, "
                        "queue depth, netem-scaled capacity, drops) on "
                        "the device at a sim-time cadence, drained to "
                        "flows.jsonl/links.jsonl in the data directory.  "
                        "SPEC is 'flows[,links][:interval]', e.g. "
                        "'flows', 'flows,links:50ms' (default interval "
                        "100ms).  Sampling never perturbs the "
                        "trajectory; see docs/observability.md")
    r.add_argument("--trace-packets", metavar="RATE", default=None,
                   help="packet lineage: assign trace IDs to a seeded, "
                        "deterministic RATE-sized sample of packets at "
                        "emission (e.g. 0.01, 1%%, or 'all') and record "
                        "one span per hop (emit/stage/tx/link/exchange/"
                        "deliver, with the drop reason where a packet "
                        "died) to spans.jsonl in the data directory.  "
                        "Tracing never perturbs the trajectory; see "
                        "docs/observability.md 'Packet lineage'")
    r.add_argument("--digest-every", type=int, default=None, metavar="N",
                   help="statescope: fold every state field-group "
                        "(pool, inbox, socks, hosts, rng, netem, app) "
                        "into a 64-bit per-shard checksum at the close "
                        "of every N-th window, drained to "
                        "digests.jsonl in the data directory.  Digests "
                        "are deterministic and trajectory-neutral; two "
                        "digest-recorded runs feed `shadow1-tpu diff`, "
                        "which names the first divergent (window, "
                        "field group, shard) and -- for checkpointed "
                        "runs -- the first differing state element "
                        "(docs/observability.md 'Statescope')")
    r.add_argument("--digest-rows", type=int, default=4096, metavar="C",
                   help="digest ring capacity in rows (default 4096): "
                        "size it above windows-per-drain-interval / N "
                        "to keep digests.jsonl gap-free (wrapped rows "
                        "are counted and reported)")
    r.add_argument("--flight-rows", type=int, default=None, metavar="N",
                   help="flight-recorder ring capacity in windows "
                        "(default 4096): size it above the number of "
                        "windows between drains/checkpoints to keep "
                        "windows.jsonl gap-free (wrapped windows lose "
                        "their per-window row; lifetime totals stay "
                        "exact either way)")
    r.add_argument("--checkpoint-every", type=float, metavar="SECONDS",
                   default=None,
                   help="make the run replayable (docs/observability.md "
                        "'Time-travel replay'): snapshot the full "
                        "simulation to DATA_DIR/ckpt/win_<K>.npz every "
                        "SECONDS of sim time (at existing launch-"
                        "boundary syncs -- compiled graphs and the "
                        "trajectory are bitwise unchanged), record every "
                        "window to windows.jsonl, and stamp the replay "
                        "recipe into ckpt/run.json for `shadow1-tpu "
                        "replay`.  Requires --data-directory")
    r.add_argument("--auto-resume", action="store_true",
                   help="self-healing run (docs/robustness.md): install "
                        "the device-side invariant sentinel, supervise "
                        "every launch, classify failures (sentinel "
                        "violation, NaN, OOM, hung device, interrupt), "
                        "and walk a checkpoint-anchored degradation "
                        "ladder (retry -> megakernel off -> halve chunk "
                        "-> single device) before surrendering with a "
                        "structured DATA_DIR/crash.json.  If DATA_DIR "
                        "already holds checkpoints from an earlier "
                        "(killed) run of the SAME config, the run "
                        "resumes from the newest readable one instead "
                        "of starting over -- bitwise identical to the "
                        "uninterrupted run.  Requires --checkpoint-every")
    r.add_argument("--watchdog", type=float, metavar="SECONDS",
                   default=None,
                   help="with --auto-resume: wall-clock deadline per "
                        "device launch; a launch that exceeds it is "
                        "classified 'hung' and the run surrenders with "
                        "crash.json (in-process recovery is unsafe while "
                        "a launch thread may hold the device).  Armed "
                        "only after the first launch completes: a cold "
                        "graph's compile time never counts against the "
                        "deadline (docs/robustness.md)")
    r.add_argument("--no-pipeline", action="store_true",
                   help="disable the async window pipeline and restore "
                        "the sequential launch -> block -> drain order "
                        "at every window boundary.  The pipeline is "
                        "host-side only -- the compiled graphs and "
                        "every artifact row are byte-identical either "
                        "way (docs/observability.md \"Async window "
                        "pipeline\") -- so this is an escape hatch for "
                        "debugging wall-clock interleavings, not a "
                        "semantics switch")


def _add_client_flags(p):
    """Socket discovery shared by submit/status/cancel."""
    p.add_argument("--server", metavar="DIR", default=None,
                   help="the server's --data-directory; the socket is "
                        "found at DIR/server/sock")
    p.add_argument("--socket", metavar="PATH", default=None,
                   help="explicit serve socket path (overrides "
                        "--server)")


def _parser():
    p = argparse.ArgumentParser(
        prog="shadow1-tpu",
        description="TPU-native discrete-event network simulator "
                    "(shadow.config.xml compatible)")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("run", help="run a simulation config")
    _add_run_flags(r, config_required=True)

    rp = sub.add_parser(
        "replay",
        help="time-travel replay: restore the nearest checkpoint before "
             "a target window of a --checkpoint-every run, re-run the "
             "span (optionally with instrumentation the original run "
             "lacked), and verify it bitwise against the recorded "
             "windows.jsonl (docs/observability.md)")
    rp.add_argument("--data-directory", required=True,
                    help="the checkpointed run's data directory "
                         "(ckpt/ + windows.jsonl)")
    tgt = rp.add_mutually_exclusive_group()
    tgt.add_argument("--window", type=int, default=None, metavar="K",
                     help="target global window index (default: the "
                          "last recorded window)")
    tgt.add_argument("--time", type=float, default=None, metavar="T",
                     help="target sim time in seconds: replays through "
                          "the window containing T")
    rp.add_argument("--world", type=int, default=None, metavar="K",
                    help="for a --worlds/--sweep run's stacked "
                         "checkpoints: restore world K solo off the "
                         "stacked anchor and replay just that member, "
                         "verified bitwise against its own "
                         "windows.jsonl rows (required for ensemble "
                         "runs, refused for solo runs -- both by "
                         "name); the member runs on one device")
    rp.add_argument("--out", default=None,
                    help="where replay outputs land (default: "
                         "DATA_DIR/replay)")
    rp.add_argument("--devices", type=int, default=None, metavar="N",
                    help="execution override: the original mesh size "
                         "(default) or 1 to gather a mesh checkpoint "
                         "onto one device (refused when per-shard "
                         "cap/log/scope rings are present)")
    rp.add_argument("--scope", metavar="SPEC", default=None,
                    help="install flowscope sampling on the replayed "
                         "span (same SPEC as run --scope) even if the "
                         "original run had none -- trajectory-neutral, "
                         "so the replay still verifies bitwise")
    rp.add_argument("--trace-packets", metavar="RATE", default=None,
                    help="install packet-lineage tracing on the "
                         "replayed span (same RATE spec as run "
                         "--trace-packets) even if the original run "
                         "had none: sampling is a seeded function of "
                         "(source host, emission counter), so the "
                         "replay traces exactly the packets the "
                         "original run would have -- trajectory-"
                         "neutral, so the replay still verifies "
                         "bitwise; spans land in OUT/spans.jsonl")
    rp.add_argument("--log-level", choices=("off", "warning", "debug"),
                    default="off",
                    help="event-log the replayed span to "
                         "OUT/shadow.log even if the original run "
                         "logged nothing")
    rp.add_argument("--log-ring", type=int, default=0,
                    help="replay log ring capacity (0 = auto)")
    rp.add_argument("--pcap", action="store_true",
                    help="capture the replayed span to OUT/capture.pcap")
    rp.add_argument("--pcap-ring", type=int, default=1 << 17,
                    help="replay capture ring capacity")
    rp.add_argument("--profile", action="store_true",
                    help="profile the replayed span (trace.json + "
                         "metrics.json in OUT)")
    rp.add_argument("--progress", action="store_true",
                    help="live status line for the replayed span")
    rp.add_argument("--no-verify", action="store_true",
                    help="skip the bitwise cross-check against the "
                         "recorded windows.jsonl")
    rp.add_argument("--quiet", action="store_true")

    df = sub.add_parser(
        "diff",
        help="statescope first-divergence localization: align two "
             "digest-recorded runs' digests.jsonl streams, name the "
             "first divergent (window, field group, shard), then "
             "restore each run's last agreeing checkpoint, re-execute "
             "the offending window, and name the first differing state "
             "element -- field, host, index, expected/got values, "
             "abs/ulp delta for floats (docs/observability.md "
             "'Statescope')")
    df.add_argument("run_a", help="first run's data directory "
                                  "(digests.jsonl, optionally ckpt/)")
    df.add_argument("run_b", help="second run's data directory")
    df.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout instead of "
                         "the human-readable one")
    df.add_argument("--no-localize", action="store_true",
                    help="stop at the digest-stream comparison: report "
                         "the first divergent (window, group, shard) "
                         "without the checkpoint-anchored re-execution")
    df.add_argument("--devices", type=int, default=None, metavar="N",
                    help="execution override for the re-execution, "
                         "same contract as replay --devices: each "
                         "run's original mesh size (default) or 1 to "
                         "gather a mesh checkpoint onto one device")
    df.add_argument("--max-elements", type=int, default=8, metavar="N",
                    help="report at most N differing elements per "
                         "field (default 8)")
    df.add_argument("--quiet", action="store_true")

    w = sub.add_parser(
        "warm",
        help="pre-compile the standard shape buckets into the "
             "persistent XLA cache (docs/shapes.md)")
    w.add_argument("--buckets", type=int, nargs="+", default=None,
                   metavar="H",
                   help="host bucket sizes to warm (default: the "
                        "standard set, shapes.STANDARD_HOST_BUCKETS)")
    w.add_argument("--apps", nargs="+", default=("phold", "bulk"),
                   choices=("phold", "bulk", "tgen", "onion", "gossip",
                            "bulk-scope"),
                   help="world flavors to warm (default: phold + bulk; "
                        "tgen/onion/gossip cover the example-ladder "
                        "worlds, bulk-scope the --scope-sampled variant "
                        "so flowscope runs hit the warm cache too)")
    w.add_argument("--quiet", action="store_true")

    sv = sub.add_parser(
        "serve",
        help="resident run server (docs/robustness.md 'Run server'): "
             "warm the standard buckets once, then accept submit/"
             "status/cancel requests over DATA_DIR/server/sock, "
             "running each under per-request supervision with a "
             "crash-safe write-ahead journal; SIGTERM drains "
             "(checkpoint + park in-flight runs, exit 0)")
    sv.add_argument("--data-directory", required=True,
                    help="server root: server/ (socket + journal) and "
                         "runs/<id>/ per-request data directories")
    sv.add_argument("--queue-limit", type=int, default=8, metavar="N",
                    help="max WAITING requests (default 8); a submit "
                         "past the limit is refused loudly with rc 2 "
                         "naming the depth and this knob (0 refuses "
                         "every submit -- useful for drills)")
    sv.add_argument("--workers", type=int, default=1, metavar="N",
                    help="concurrent request executors (default 1: "
                         "strict warm-graph affinity; raise it when "
                         "the accelerator has memory for concurrent "
                         "worlds)")
    sv.add_argument("--max-lanes", type=int, default=4, metavar="N",
                    help="continuous-batching width (default 4): up "
                         "to N concurrent same-shape builder requests "
                         "share one vmapped launch train, each lane "
                         "bitwise-identical to the same request run "
                         "solo (docs/robustness.md 'Continuous "
                         "batching'); 1 disables batching")
    sv.add_argument("--checkpoint-every", type=float, default=2.0,
                    metavar="SECONDS",
                    help="default checkpoint cadence applied to "
                         "requests that set none (every request runs "
                         "checkpointed -- crash-safety requires an "
                         "anchor; default 2.0)")
    sv.add_argument("--watchdog", type=float, default=None,
                    metavar="SECONDS",
                    help="default per-launch watchdog applied to "
                         "requests that set none")
    sv.add_argument("--auto-resume", action="store_true",
                    help="re-admit journaled queued/running/parked "
                         "requests from a previous server life; each "
                         "in-flight run resumes from its newest "
                         "checkpoint, bitwise identical to an "
                         "uninterrupted run")
    sv.add_argument("--no-warm", action="store_true",
                    help="skip the background AOT bucket warm")
    sv.add_argument("--warm-apps", nargs="+", default=("phold", "bulk"),
                    choices=("phold", "bulk", "tgen", "onion", "gossip",
                             "bulk-scope"),
                    help="world flavors to warm (default phold + bulk)")
    sv.add_argument("--warm-buckets", type=int, nargs="+", default=None,
                    metavar="H",
                    help="bucket sizes to warm (default: the standard "
                         "set)")
    sv.add_argument("--quiet", action="store_true")

    sb = sub.add_parser(
        "submit",
        help="submit a scenario to a running `serve` instance and (by "
             "default) stream its progress until done, exiting with "
             "the run's rc -- the same unified exit-code table as "
             "`run`")
    _add_run_flags(sb, config_required=False)
    _add_client_flags(sb)
    sb.add_argument("--world", metavar="NAME", default=None,
                    help="builder request: run sim.build_NAME(...) "
                         "server-side instead of a config file (e.g. "
                         "phold, bulk, tgen, gossip, onion)")
    sb.add_argument("--world-kwargs", metavar="JSON", default=None,
                    help="JSON kwargs for --world (e.g. "
                         "'{\"num_hosts\": 64, \"seed\": 3}')")
    sb.add_argument("--replay", metavar="RUN", default=None,
                    help="replay request: time-travel replay of RUN (a "
                         "server run id, or a checkpointed data "
                         "directory) as a service request")
    sb.add_argument("--window", type=int, default=None, metavar="K",
                    help="with --replay: target global window index "
                         "(default: the last recorded window)")
    sb.add_argument("--timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="per-request wall-clock budget, queued time "
                         "included; an expired request is refused / "
                         "stopped with rc 2 naming this knob")
    sb.add_argument("--no-wait", action="store_true",
                    help="print the request id and return immediately "
                         "instead of streaming to completion")

    st = sub.add_parser(
        "status",
        help="one run's record (state, rc, trail, crash path) or the "
             "whole server snapshot")
    st.add_argument("id", nargs="?", default=None,
                    help="request id (omit for the full snapshot)")
    _add_client_flags(st)
    st.add_argument("--wait", action="store_true",
                    help="with an id: block until the run settles and "
                         "exit with its rc")

    sx = sub.add_parser(
        "stats",
        help="live fleet view of a run server (Servescope): queue, "
             "workers, affinity hit rate, recent completions")
    _add_client_flags(sx)
    sx.add_argument("--watch", type=float, default=None, metavar="N",
                    help="redraw every N seconds until interrupted")
    sx.add_argument("--json", action="store_true",
                    help="print the raw stats JSON instead of the "
                         "rendered screen")

    cn = sub.add_parser("cancel", help="cancel a queued or running "
                                       "request (rc 3 on its record)")
    cn.add_argument("id", help="request id")
    _add_client_flags(cn)
    return p


def build_world(args, *, quiet: bool = False, want_mesh: bool = True,
                allow_substrate: bool = True,
                netem_n_events: int | None = None) -> types.SimpleNamespace:
    """Assemble and instrument a world from the run flags.

    The single world-construction path `run` and `replay` share: config
    assembly, netem merge, observability ring installs (in mesh layout
    when the run shards), bucket and mesh padding, and the flight
    recorder -- everything that shapes the state/params pytrees, in a
    fixed order, so a replay template is structurally identical to the
    original run's world.  Host-side actors (trackers, drains,
    profiler files) stay with the caller.  Raises CliError on
    user-facing failures.

    `want_mesh=False` skips Mesh construction and the visible-device
    check but still applies mesh PADDING -- a single-device gather
    replay of a mesh checkpoint needs the padded shapes without the
    mesh.  `allow_substrate=False` refuses configs with real-process
    plugins (replay cannot restore external process state).

    `netem_n_events` pads the netem event arrays up to a fixed slot
    count (netem/state.py make_netem_block): ensemble builds pass the
    max event count across worlds so seed-dependent chaos timelines
    stack into one block shape (docs/ensemble.md).
    """
    from .config import assemble

    asm = assemble.load(args.config, seed=args.seed,
                        sock_slots=args.sock_slots,
                        pool_slab=args.pool_slab,
                        qdisc=args.interface_qdisc,
                        cpu_threshold_us=args.cpu_threshold,
                        cpu_precision_us=args.cpu_precision,
                        cong=args.tcp_congestion_control)
    stop = (args.stop_time * SEC) if args.stop_time else asm.stop_time
    if not quiet:
        print(f"[shadow1-tpu] {len(asm.hostnames)} hosts, "
              f"{asm.topology.num_vertices} vertices, "
              f"stop={stop / SEC:.0f}s, backend={jax.default_backend()}",
              file=sys.stderr)

    state, params, app = asm.state, asm.params, asm.app

    # Network dynamics: merge the config's <netem> section (already
    # installed by assemble) with the CLI's --netem/--churn additions into
    # one schedule and reinstall.  Reinstalling over an already-shrunk
    # lookahead can only shrink it further -- conservative, never wrong.
    if args.netem or args.churn is not None or (
            netem_n_events is not None and asm.netem is not None):
        from . import netem as netem_mod
        tl = asm.netem if asm.netem is not None else netem_mod.timeline()
        if args.netem:
            add = netem_mod.load_json(
                args.netem,
                resolve=lambda n: asm.dns.resolve_name(n).host_index)
            tl.events.extend(add.events)
            tl.groups.update(add.groups)
        if args.churn is not None:
            tl.chaos(params.seed_key, len(asm.hostnames), args.churn,
                     mean_down_s=args.churn_downtime, t_end=int(stop))
        state, params = netem_mod.install(
            state.replace(nm=None), params, tl,
            n_events=netem_n_events)
        if not quiet:
            print(f"[shadow1-tpu] netem: {tl.describe()}", file=sys.stderr)

    # Observability rings are built in the mesh layout when the run will
    # shard (per-shard segments + cursors; docs/observability.md).
    n_dev = max(1, args.devices)

    want_pcap = args.pcap or (asm.pcap_mask is not None
                              and asm.pcap_mask.any())
    if want_pcap:
        if not args.data_directory:
            raise CliError("packet capture requires --data-directory")
        from .core.state import make_capture_ring
        state = state.replace(cap=make_capture_ring(args.pcap_ring,
                                                    shards=n_dev))
        if args.pcap:
            # An explicit global capture must not be filtered down by
            # per-host logpcap masks.
            params = params.replace(
                pcap_mask=jnp.ones_like(params.pcap_mask))

    # Leveled sim-time event log (reference ShadowLogger): enabled by
    # --log-level or any per-host <host loglevel>.
    _LVL = {None: 0, "off": 0, "error": 1, "critical": 1, "warning": 1,
            "message": 1, "info": 2, "debug": 2, "trace": 2}
    global_lvl = _LVL[args.log_level]
    host_lvls = []
    for lv in (asm.loglevels or [None] * len(asm.hostnames)):
        key = (lv or "").lower() or None
        if key not in _LVL:
            print(f"[shadow1-tpu] WARNING: unknown loglevel {lv!r} "
                  f"(known: {sorted(k for k in _LVL if k)}); treating as "
                  f"'off'", file=sys.stderr)
        host_lvls.append(max(_LVL.get(key, 0), global_lvl))
    if any(host_lvls):
        if not args.data_directory:
            raise CliError("--log-level requires --data-directory")
        from .core.state import make_log_ring
        ring = args.log_ring
        if ring <= 0:
            # Debug level (global OR per-host) logs ~2 records per
            # delivered packet; a 64k ring loses most of a busy drain
            # interval.  Auto-grow.
            ring = (1 << 20) if max(host_lvls) >= 2 else (1 << 16)
        state = state.replace(
            log=make_log_ring(ring, shards=n_dev),
            log_level=jnp.asarray(host_lvls, jnp.int32))

    # Real-process plugins (config <plugin path> pointing at an actual
    # executable): spawn them under the substrate at their start times
    # and drive the run through the window-protocol bridge.
    substrate = None
    if asm.real_procs:
        if not allow_substrate:
            raise CliError(
                "this run drives real-process plugins under the "
                "substrate; replay cannot restore external process "
                "state")
        import os as _os

        from .substrate import Substrate
        dns = asm.dns

        def _res_ip(ip):
            try:
                return dns.resolve_ip(ip).host_index
            except KeyError:
                return None

        def _res_name(name):
            try:
                return dns.resolve_name(name).ip
            except KeyError:
                return None

        workdir = args.data_directory or "shadow1-procs"
        substrate = Substrate(
            resolve_ip=_res_ip,
            workdir=_os.path.join(workdir, "procs"),
            # Low slots belong to the modeled side (tgen listener=0,
            # client=1); real processes allocate above them.
            sock_slot_base=2,
            resolve_name=_res_name,
            host_ip=lambda i: dns.address_of(i).ip)
        for host_i, argv, start_ns, stop_ns in asm.real_procs:
            substrate.spawn_at(host_i, argv, start_ns, stop_ns)
        if not quiet:
            print(f"[shadow1-tpu] {len(asm.real_procs)} real process(es) "
                  f"under the substrate", file=sys.stderr)

    if args.profile:
        from . import trace
        # Device-side per-window counters, fetched once per drain point.
        state = trace.ensure_counters(state)

    if args.bucket:
        # Bucket BEFORE any mesh padding: ladder rungs divide every
        # power-of-two device count up to 64, so the mesh pass below is
        # normally an identity on a bucketed world (docs/shapes.md).
        from . import shapes
        h0 = int(state.hosts.num_hosts)
        state, params = shapes.pad_world_to_bucket(state, params)
        if not quiet and int(state.hosts.num_hosts) != h0:
            print(f"[shadow1-tpu] bucket: {h0} -> "
                  f"{int(state.hosts.num_hosts)} hosts", file=sys.stderr)

    mesh = None
    if args.devices > 1:
        # The observability stack runs sharded (rings built with
        # shards=N above, counters finalized across shards); only the
        # substrate bridge remains single-device (per-host syscall RPC
        # serialized through one device).
        if substrate is not None:
            raise CliError(
                "--devices is incompatible with real-process "
                "plugins (<plugin> with a real executable): the "
                "substrate bridge drives one device.  That is the only "
                "remaining refusal -- --pcap, --log-level, --profile, "
                "--progress and heartbeats all run sharded (see "
                "docs/parallel.md)")
        from . import parallel as parallel_mod
        if want_mesh:
            devs = jax.devices()
            if len(devs) < args.devices:
                raise CliError(
                    f"--devices {args.devices} but only {len(devs)} "
                    f"{jax.default_backend()} device(s) visible")
            mesh = parallel_mod.make_mesh(devs[:args.devices])
        state, params = parallel_mod.pad_world_to_mesh(
            state, params, args.devices)
        if not quiet:
            print(f"[shadow1-tpu] mesh: {args.devices} devices, "
                  f"{int(state.hosts.num_hosts) // args.devices} hosts "
                  f"per shard", file=sys.stderr)

    if args.profile or getattr(args, "checkpoint_every", None) \
            or getattr(args, "flight_rows", None):
        # Per-window flight recorder (installed AFTER mesh padding so the
        # shard matrices match the padded host count); drained at the
        # same chunk boundaries as the counters -- no extra syncs.
        # Checkpointed runs always carry it: windows.jsonl is the record
        # replay verifies against.  --flight-rows overrides the 4096-row
        # default for drain cadences that would wrap the ring.
        from . import trace
        state = trace.ensure_flight_recorder(
            state, shards=n_dev, rows=getattr(args, "flight_rows", None))

    if args.scope:
        # Flowscope sampling block (same AFTER-mesh-padding rule: each
        # shard owns a ring segment sized off the padded host count).
        from . import trace as _trace_mod
        try:
            scope_kw = _trace_mod.parse_scope_spec(args.scope)
        except ValueError as e:
            raise CliError(str(e))
        state = _trace_mod.ensure_flowscope(state, shards=n_dev,
                                            **scope_kw)
        if not quiet:
            print(f"[shadow1-tpu] scope: {args.scope}", file=sys.stderr)

    if getattr(args, "trace_packets", None):
        # Packet-lineage tracer (same AFTER-mesh-padding rule: span-ring
        # segments and the pool/inbox side arrays are laid out per
        # shard).
        from . import trace as _trace_mod2
        try:
            rate = _trace_mod2.parse_lineage_rate(args.trace_packets)
        except ValueError as e:
            raise CliError(str(e))
        state = _trace_mod2.ensure_lineage(state, rate=rate,
                                           shards=n_dev)
        if not quiet:
            print(f"[shadow1-tpu] lineage: sampling {rate:g} of "
                  f"emissions", file=sys.stderr)

    if getattr(args, "digest_every", None):
        # Statescope digest block (same AFTER-mesh-padding rule: one
        # checksum column per shard, so the shard count is baked into
        # the ring shape).
        from . import trace as _trace_mod3
        try:
            state = _trace_mod3.ensure_digests(
                state, every=args.digest_every,
                capacity=getattr(args, "digest_rows", None) or 4096,
                shards=n_dev)
        except ValueError as e:
            raise CliError(str(e))
        if not quiet:
            print(f"[shadow1-tpu] digest: every {args.digest_every} "
                  f"window(s)", file=sys.stderr)

    return types.SimpleNamespace(
        asm=asm, state=state, params=params, app=app, stop=int(stop),
        n_dev=n_dev, mesh=mesh, substrate=substrate,
        want_pcap=want_pcap, host_lvls=host_lvls)


class _EmitStream:
    """A write/flush file-object shim that forwards Progress's status
    lines as {"event": "progress"} records to an emit callback -- the
    run server relays them to the submitting client."""

    def __init__(self, emit):
        self._emit = emit

    def write(self, s):
        if s and s.strip():
            self._emit({"event": "progress", "line": s})

    def flush(self):
        pass


# Per-world override knobs a --sweep spec may vary.  Everything else
# (host counts, slab sizes, app wiring, netem presence) is a compile
# shape or a block-presence static: varying it across worlds would
# break the one-compiled-graph contract, so stack() would refuse the
# build anyway -- refuse here first, by name.
_SWEEP_KEYS = ("seed", "churn", "churn_downtime")


def _sweep_overrides(args):
    """Resolve --worlds/--sweep into one flag-override dict per world
    (plus the raw spec for run.json bookkeeping).

    Plain `--worlds N` runs world k with seed SEED+k: distinct integer
    seeds give independent threefry root keys (core/rng.py), and every
    world stays reproducible SOLO as `--seed SEED+k` -- the bitwise
    world-vs-solo contract (docs/ensemble.md) holds per world with no
    extra bookkeeping.  A --sweep file replaces the derived seeds with
    an explicit spec."""
    spec = None
    if args.sweep:
        try:
            with open(args.sweep) as f:
                spec = json.load(f)
        except OSError as e:
            raise CliError(f"--sweep: cannot read {args.sweep}: {e}")
        except ValueError as e:
            raise CliError(
                f"--sweep: {args.sweep} is not valid JSON: {e}")
        if not isinstance(spec, dict) or not ({"seeds", "worlds"}
                                              & set(spec)):
            raise CliError(
                '--sweep spec must be a JSON object with "seeds" (a '
                'list of integers, one world per seed) or "worlds" (a '
                'list of per-world override objects)')
    if spec is None:
        return [{"seed": args.seed + k}
                for k in range(max(1, args.worlds))], None
    if "seeds" in spec and "worlds" in spec:
        raise CliError(
            '--sweep spec has both "seeds" and "worlds"; give one')
    if "seeds" in spec:
        seeds = spec["seeds"]
        if not isinstance(seeds, list) or not seeds or \
                not all(isinstance(s, int) and not isinstance(s, bool)
                        for s in seeds):
            raise CliError(
                '--sweep "seeds" must be a non-empty list of integers')
        overrides = [{"seed": s} for s in seeds]
    else:
        ws = spec["worlds"]
        if not isinstance(ws, list) or not ws or \
                not all(isinstance(w, dict) for w in ws):
            raise CliError(
                '--sweep "worlds" must be a non-empty list of objects')
        overrides = []
        for k, w in enumerate(ws):
            bad = sorted(set(w) - set(_SWEEP_KEYS))
            if bad:
                raise CliError(
                    f"--sweep world {k} overrides {bad}; only "
                    f"{list(_SWEEP_KEYS)} may vary per world (anything "
                    f"else changes compile shapes or block presence, "
                    f"which would break the one-compiled-graph "
                    f"contract -- vary those across separate runs)")
            overrides.append({"seed": w.get("seed", args.seed + k),
                              **{kk: w[kk] for kk in _SWEEP_KEYS[1:]
                                 if kk in w}})
    if args.worlds > 1 and args.worlds != len(overrides):
        raise CliError(
            f"--worlds {args.worlds} but the --sweep spec defines "
            f"{len(overrides)} world(s); drop --worlds or make them "
            f"agree")
    return overrides, spec


def _run_ensemble_config(args, *, control=None, emit=None,
                         profiler=None) -> int:
    """Execute a `run --worlds N` / `--sweep` invocation: build every
    world through build_world (per-world seeds, devices forced to 1 --
    ensemble sharding places whole worlds, not host shards), stack,
    and hand off to sim.run_ensemble (docs/ensemble.md).

    The refusal surface is explicit: combos whose host-side machinery
    has no world axis are refused rc 2 BY NAME, naming the limitation
    and the solo workaround, instead of silently writing solo-shaped
    artifacts that a later reader would mis-join."""
    from . import sim as sim_mod
    from .ensemble import EnsembleMismatch

    try:
        overrides, spec = _sweep_overrides(args)
        nw = len(overrides)
        if getattr(args, "worlds", 1) < 1:
            raise CliError("--worlds must be >= 1")
        if args.profile:
            raise CliError(
                "--profile is unsupported with --worlds/--sweep: the "
                "profiler's phase spans and counter files are per-run "
                "with no world column; profile one world solo "
                "(--seed <that world's seed>)")
        if args.pcap:
            raise CliError(
                "--pcap is unsupported with --worlds/--sweep: the "
                "capture ring and pcap writer have no world column, "
                "so packets from different worlds would interleave "
                "into one capture; capture one world solo (--seed "
                "<that world's seed>)")
        # Checkpointed / supervised ensembles save STACKED anchors
        # (checkpoint format 2, docs/robustness.md "Ensemble
        # resilience"); the flag contract matches the solo path.
        ck_every_ns = None
        if getattr(args, "checkpoint_every", None):
            if args.checkpoint_every <= 0:
                raise CliError("--checkpoint-every must be positive")
            if not args.data_directory:
                raise CliError(
                    "--checkpoint-every requires --data-directory")
            ck_every_ns = int(args.checkpoint_every * SEC)
        supervise_on = bool(getattr(args, "auto_resume", False))
        if supervise_on and not ck_every_ns:
            raise CliError(
                "--auto-resume requires --checkpoint-every "
                "(recovery is checkpoint-anchored)")
        if getattr(args, "watchdog", None) and not supervise_on:
            raise CliError("--watchdog requires --auto-resume")
        if args.devices > 1:
            if nw % args.devices != 0:
                raise CliError(
                    f"--devices {args.devices} shards the WORLD axis "
                    f"in ensemble mode (world-major, "
                    f"docs/ensemble.md) and needs n_worlds % devices "
                    f"== 0; got {nw} world(s)")
            devs = jax.devices()
            if len(devs) < args.devices:
                raise CliError(
                    f"--devices {args.devices} but only {len(devs)} "
                    f"{jax.default_backend()} device(s) visible")

        def build(k, n_events=None):
            a = argparse.Namespace(**vars(args))
            a.devices = 1
            for key, val in overrides[k].items():
                setattr(a, key, val)
            try:
                return build_world(a, quiet=args.quiet or k > 0,
                                   want_mesh=False,
                                   allow_substrate=False,
                                   netem_n_events=n_events)
            except CliError as e:
                if "substrate" in str(e):
                    raise CliError(
                        "--worlds/--sweep cannot run real-process "
                        "plugins: the substrate drives one set of "
                        "external processes with no world axis; run "
                        "plugin configs solo") from e
                raise

        built = [build(k) for k in range(nw)]
        if any(b.want_pcap for b in built):
            raise CliError(
                "this config enables packet capture (<host logpcap>), "
                "which is unsupported with --worlds/--sweep: the "
                "capture ring has no world column; capture one world "
                "solo (--seed <that world's seed>)")
        has_nm = [b.state.nm is not None for b in built]
        if any(has_nm) and not all(has_nm):
            raise CliError(
                "every sweep world must carry netem or none: the nm "
                "block's presence is a compile static "
                "(shapes.ShapeKey), so worlds with and without churn "
                "cannot share one compiled graph -- give every world "
                "a churn rate (0.0 keeps the block with no flaps) or "
                "none")
        # Seed-dependent chaos timelines draw different event counts
        # per world; rebuild on the shared max-count bucket so the nm
        # block stacks (netem_n_events pads the tail with inert
        # never-fire slots -- docs/ensemble.md).
        ev_counts = [int(b.state.nm.ev_time.shape[0])
                     for b in built if b.state.nm is not None]
        nm_bucket = None
        if ev_counts and len(set(ev_counts)) > 1:
            nm_bucket = max(ev_counts)
            if not args.quiet:
                print(f"[shadow1-tpu] ensemble: netem event counts "
                      f"{sorted(set(ev_counts))} -> bucket {nm_bucket}",
                      file=sys.stderr)
            built = [build(k, n_events=nm_bucket) for k in range(nw)]

        sweep_record = None
        if spec is not None or nw > 1:
            sweep_record = {"worlds": overrides}
            if args.sweep:
                import os
                sweep_record["file"] = os.path.abspath(args.sweep)

        run_extra = None
        sup_opts: dict | bool = False
        world_cmds = None
        if ck_every_ns:
            # The replay recipe: solo-shaped flags plus the per-world
            # override table and netem bucket, so `replay --world K`
            # can rebuild one member bitwise (replay.rebuild_world).
            run_extra = {
                "world": {"kind": "config", "args": world_args(args)},
                "netem_n_events": nm_bucket,
            }
        if supervise_on:
            from . import supervise as sup_mod
            sup_mod.install_sigterm()
            wflag = f" --sweep {args.sweep}" if args.sweep \
                else f" --worlds {nw}"
            sup_opts = {
                "watchdog_s": getattr(args, "watchdog", None),
                "quiet": args.quiet,
                "resume_cmd": (
                    f"shadow1-tpu run {args.config}{wflag} "
                    f"--auto-resume --checkpoint-every "
                    f"{args.checkpoint_every:g} "
                    f"--data-directory {args.data_directory}"),
            }

            def world_cmds(k, window):
                # Per-member crash.json commands: replay the bad
                # window solo, or re-run that world solo from t=0.
                ov = " ".join(
                    f"--{key.replace('_', '-')} {val:g}"
                    for key, val in sorted(overrides[k].items()))
                cmds = {"rerun": f"shadow1-tpu run {args.config} {ov}"}
                if window is not None and int(window) >= 0:
                    cmds["replay"] = (
                        f"shadow1-tpu replay --data-directory "
                        f"{args.data_directory} --world {k} "
                        f"--window {int(window)}")
                return cmds

        t_wall = time.perf_counter()
        try:
            estate, eparams, app, summaries = sim_mod.run_ensemble(
                [(b.state, b.params, b.app) for b in built],
                until=built[0].stop,
                data_dir=args.data_directory,
                digest=getattr(args, "digest_every", None),
                heartbeat_s=(args.heartbeat_frequency
                             if args.data_directory else 0),
                devices=(args.devices if args.devices > 1 else None),
                hostnames=list(built[0].asm.hostnames),
                sweep=sweep_record,
                quiet=args.quiet,
                checkpoint_every=ck_every_ns,
                supervise=sup_opts,
                resume=supervise_on,
                control=control, emit=emit,
                run_extra=run_extra, world_cmds=world_cmds,
                pipeline=not getattr(args, "no_pipeline", False))
        except EnsembleMismatch as e:
            raise CliError(f"worlds do not stack: {e}")
        except UnrecoveredFailure as e:
            print(f"error: {e}", file=sys.stderr)
            print(json.dumps({"crash": e.crash}))
            if emit is not None:
                emit({"event": "crash", "rc": e.rc, "crash": e.crash,
                      "path": e.path})
            return e.rc
    except CliError as e:
        print(f"error: {e}", file=sys.stderr)
        return e.rc

    if control is not None and control.outcome is not None:
        # Park / cancel / timeout decided inside run_ensemble's loop
        # (identical contract to the solo run_config loop).
        if control.outcome == "parked":
            return RC_OK
        if control.outcome == "cancelled":
            return RC_FAILED
        print("error: ensemble stopped: --timeout expired",
              file=sys.stderr)
        return RC_USAGE

    bad = [s for s in summaries if s["err_flags"]]
    quarantined = [s["world"] for s in summaries
                   if s.get("quarantined")]
    summary = {"n_worlds": nw,
               "simulated_seconds": int(built[0].stop) / SEC,
               "worlds": summaries}
    if supervise_on:
        summary["quarantined"] = quarantined
    print(json.dumps(summary))
    if emit is not None:
        emit({"event": "summary", "summary": summary})
    if not args.quiet or bad or quarantined:
        for s in summaries:
            flag = (f", ERR=0x{s['err_flags']:x}" if s["err_flags"]
                    else "")
            if s.get("quarantined"):
                flag += ", QUARANTINED"
            print(f"[shadow1-tpu] world {s['world']}: "
                  f"{s['events']} events, {s['packets_sent']} packets, "
                  f"{s['drops']} drops{flag}", file=sys.stderr)
        print(f"[shadow1-tpu] ensemble: {nw} world(s) in "
              f"{time.perf_counter() - t_wall:.2f}s wall",
              file=sys.stderr)
    if bad:
        print(f"error: {len(bad)} world(s) raised invariant-violation "
              f"flags (err_flags above; docs/robustness.md)",
              file=sys.stderr)
        return RC_INVARIANT
    if quarantined:
        print(f"error: world(s) {quarantined} were quarantined "
              f"(deterministic per-world failure; crash report in "
              f"{args.data_directory}/crash.json names per-world "
              f"replay commands); the surviving worlds finished "
              f"normally", file=sys.stderr)
        return RC_INVARIANT
    return RC_OK


def run_config(args, *, control=None, emit=None, profiler=None) -> int:
    """Execute a `run` invocation.  `control` / `emit` are the run
    server's hooks (server.RunControl + an event callback): the loop
    polls `control` at every launch boundary -- "park" checkpoints and
    stops (control.outcome="parked", rc 0), "cancel" stops (rc 3),
    "timeout" stops with a refusal naming --timeout (rc 2) -- and
    `emit` receives progress/summary/crash events for relay.  All
    default to None: the batch CLI path is unchanged.  `profiler` is
    the server's per-request accounting Profiler (counters=False, so
    the state pytree stays untouched); --profile overrides it with the
    CLI's own sync+counters one."""
    import os

    from . import trace

    if getattr(args, "sweep", None) or getattr(args, "worlds", 1) > 1:
        # Ensemble mode: N whole simulations vmapped over a leading
        # world axis (docs/ensemble.md).  Its flag surface is a strict
        # subset -- unsupported combos are refused by name inside.
        return _run_ensemble_config(args, control=control, emit=emit,
                                    profiler=profiler)

    if args.profile:
        if not args.data_directory:
            print("error: --profile requires --data-directory",
                  file=sys.stderr)
            return RC_USAGE
        profiler = trace.install(trace.Profiler(sync=True))
    elif profiler is not None:
        profiler = trace.install(profiler)

    scope_kw = None
    if args.scope:
        if not args.data_directory:
            print("error: --scope requires --data-directory",
                  file=sys.stderr)
            return RC_USAGE
        try:
            scope_kw = trace.parse_scope_spec(args.scope)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if getattr(args, "trace_packets", None):
        if not args.data_directory:
            print("error: --trace-packets requires --data-directory",
                  file=sys.stderr)
            return RC_USAGE
        try:
            trace.parse_lineage_rate(args.trace_packets)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return RC_USAGE

    if getattr(args, "digest_every", None):
        if args.digest_every < 1:
            print("error: --digest-every must be a positive window "
                  "count", file=sys.stderr)
            return RC_USAGE
        if not args.data_directory:
            print("error: --digest-every requires --data-directory",
                  file=sys.stderr)
            return RC_USAGE

    ck_every_ns = None
    if getattr(args, "checkpoint_every", None):
        if args.checkpoint_every <= 0:
            print("error: --checkpoint-every must be positive",
                  file=sys.stderr)
            return RC_USAGE
        if not args.data_directory:
            print("error: --checkpoint-every requires --data-directory",
                  file=sys.stderr)
            return RC_USAGE
        ck_every_ns = int(args.checkpoint_every * SEC)

    supervise_on = bool(getattr(args, "auto_resume", False))
    if supervise_on and not ck_every_ns:
        print("error: --auto-resume requires --checkpoint-every "
              "(recovery is checkpoint-anchored)", file=sys.stderr)
        return RC_USAGE
    if getattr(args, "watchdog", None) and not supervise_on:
        print("error: --watchdog requires --auto-resume", file=sys.stderr)
        return RC_USAGE

    t_wall = time.perf_counter()
    try:
        w = build_world(args, quiet=args.quiet)
    except CliError as e:
        print(f"error: {e}", file=sys.stderr)
        return e.rc
    asm = w.asm
    state, params, app = w.state, w.params, w.app
    stop, n_dev, mesh, substrate = w.stop, w.n_dev, w.mesh, w.substrate

    if substrate is not None and ck_every_ns:
        print("error: --checkpoint-every is incompatible with "
              "real-process plugins: external process state cannot be "
              "snapshotted or replayed", file=sys.stderr)
        return RC_USAGE
    if substrate is not None:
        from .substrate import bridge as _bridge

    resumed_from = None
    if supervise_on:
        # The invariant sentinel rides every supervised run, so every
        # checkpoint carries it and resume templates always match.
        state = trace.ensure_sentinel(state)
        import glob as _glob

        from . import checkpoint as ckpt_mod
        from . import replay as replay_mod
        from . import supervise as sup_mod
        if _glob.glob(os.path.join(args.data_directory, "ckpt",
                                   "win_*.npz")):
            try:
                path, man = replay_mod.find_checkpoint(
                    args.data_directory, None)
            except FileNotFoundError as e:
                import warnings
                warnings.warn(
                    f"--auto-resume: existing checkpoints are all "
                    f"unreadable; starting the run over ({e})",
                    RuntimeWarning, stacklevel=1)
                path = None
            if path is not None:
                try:
                    state, params = ckpt_mod.load(path, state, params)
                except ValueError as e:
                    print(f"error: --auto-resume: the newest checkpoint "
                          f"in {args.data_directory} was saved by a "
                          f"different config: {e}", file=sys.stderr)
                    return RC_USAGE
                resumed_from = {
                    "file": os.path.basename(path),
                    "window": int(man["window"]),
                    "t_ns": int(man["t_ns"])}
                dropped = sup_mod.trim_windows(
                    os.path.join(args.data_directory, "windows.jsonl"),
                    resumed_from["window"])
                if not args.quiet:
                    print(f"[shadow1-tpu] auto-resume: restored window "
                          f"{resumed_from['window']} "
                          f"(t={resumed_from['t_ns'] / SEC:g}s) from "
                          f"{resumed_from['file']}; trimmed {dropped} "
                          f"superseded row(s)", file=sys.stderr)
                if emit is not None:
                    emit({"event": "resumed", **resumed_from})

    tracker = None
    if args.data_directory and args.heartbeat_frequency > 0:
        from .observe import Tracker
        tracker = Tracker(args.data_directory, asm.hostnames,
                          interval_s=args.heartbeat_frequency,
                          per_host_interval_s=asm.heartbeat_freq_s)

    drain = None
    if state.log is not None and args.data_directory:
        from .observe import LogDrain
        drain = LogDrain(os.path.join(args.data_directory, "shadow.log"),
                         asm.hostnames)

    flight = None
    if state.fr is not None and args.data_directory:
        # A resumed run appends after the trim above, starting at the
        # restored window so the ring's pre-resume rows (already in the
        # file) are not re-emitted.
        flight = trace.FlightDrain(
            os.path.join(args.data_directory, "windows.jsonl"),
            start=resumed_from["window"] if resumed_from else 0,
            mode="a" if resumed_from else "w")

    scope = None
    if scope_kw is not None and state.scope is not None:
        scope = trace.ScopeDrain(
            flows_path=os.path.join(args.data_directory, "flows.jsonl")
            if scope_kw["flows"] else None,
            links_path=os.path.join(args.data_directory, "links.jsonl")
            if scope_kw["links"] else None,
            real_hosts=len(asm.hostnames))

    spans = None
    if state.lineage is not None and args.data_directory:
        spans = trace.LineageDrain(
            os.path.join(args.data_directory, "spans.jsonl"))

    digests = None
    if state.dg is not None and args.data_directory:
        digests = trace.DigestDrain(
            os.path.join(args.data_directory, "digests.jsonl"))

    ck = None
    if ck_every_ns:
        from . import replay as replay_mod
        ck = replay_mod.Checkpointer(
            args.data_directory, ck_every_ns, devices=n_dev,
            bucket=args.bucket, hosts_real=len(asm.hostnames))
        write_recipe = resumed_from is None
        if resumed_from is not None:
            # Torn-file hardening parity (docs/robustness.md): a torn
            # run.json -- the process died inside a legacy non-atomic
            # write, or the file was damaged externally -- must not
            # strand an otherwise resumable run.  The recipe is a pure
            # function of the current flags, so rewrite it from them.
            try:
                replay_mod.load_run(args.data_directory)
            except (FileNotFoundError, ValueError,
                    json.JSONDecodeError) as e:
                import warnings
                warnings.warn(
                    f"auto-resume: ckpt/run.json is unreadable ({e}); "
                    f"rewriting the replay recipe from the current "
                    f"flags", RuntimeWarning, stacklevel=1)
                write_recipe = True
        if write_recipe:
            replay_mod.write_run_json(args.data_directory, {
                "world": {"kind": "config", "args": world_args(args)},
                "hb_ns": tracker.sample_interval_ns if tracker else None,
                "every_ns": ck_every_ns, "stop_ns": int(stop),
                "chunk_ns": engine.CHUNK_NS, "devices": n_dev,
                "bucket": bool(args.bucket),
                "hosts_real": len(asm.hostnames),
                "scope": args.scope, "profile": bool(args.profile),
                "flight_rows": int(state.fr.steps.shape[0]),
                "lineage": getattr(args, "trace_packets", None),
                "digest": (int(state.dg.every)
                           if state.dg is not None else None),
                "digest_rows": (int(state.dg.capacity)
                                if state.dg is not None else None),
                "sentinel": supervise_on, "supervise": supervise_on})
        if resumed_from is None:
            ck.save(state, params)  # win_0: a replay anchor always exists
        if not args.quiet:
            print(f"[shadow1-tpu] checkpoints: every "
                  f"{args.checkpoint_every}s -> {ck.dir}",
                  file=sys.stderr)

    progress = None
    if args.progress:
        from .observe import Progress
        progress = Progress(int(stop),
                            out=_EmitStream(emit) if emit is not None
                            else None)

    from .replay import next_sync
    if mesh is not None:
        from . import parallel as parallel_mod
    sup = None
    if supervise_on:
        from . import supervise as sup_mod
        sup_mod.install_sigterm()
        sup = sup_mod.Supervisor(
            args.data_directory, app, mesh=mesh,
            chunk_ns=engine.CHUNK_NS,
            watchdog_s=getattr(args, "watchdog", None),
            quiet=args.quiet,
            resume_cmd=(f"shadow1-tpu run {args.config} --auto-resume "
                        f"--checkpoint-every {args.checkpoint_every:g} "
                        f"--data-directory {args.data_directory}"),
            on_violation=(lambda st: flight.drain(st, profiler))
            if flight is not None else None,
            emit=emit)
    hb_ns = tracker.sample_interval_ns if tracker else None
    t = int(state.now)
    # Every synchronous host-side drain behind one call (sim.Drains):
    # heartbeat, event log, counters, flight / scope / lineage / digest
    # rings -- the checkpointed sim.run loop drains through the same
    # helper, so a new ring slots into both loops in one place.
    from .sim import Drains, WindowPipeline
    drains = Drains(tracker=tracker, log=drain, flight=flight,
                    scope=scope, spans=spans, digests=digests,
                    profiler=profiler)
    # The async window pipeline (sim.WindowPipeline,
    # docs/observability.md): dispatch window N+1 before draining
    # window N, with byte-identical artifacts.  The substrate bridge
    # owns its own launch/sync cadence (managed-process RPCs ARE host
    # work between launches), so bridged runs stay sequential.
    pipe = None
    prev_sync = None
    if not getattr(args, "no_pipeline", False) and substrate is None:
        pipe = WindowPipeline(profiler)
        if profiler is not None and profiler.sync:
            # --profile syncs per chunk inside the engine loop, which
            # would serialize the pipeline; the pipeline records its
            # own dispatch->ready device_window spans instead.
            prev_sync = True
            profiler.sync = False

    def _close_drains():
        if pipe is not None:
            try:
                pipe.flush()  # best-effort: land the pending window
            except Exception:
                pass
        if prev_sync and profiler is not None:
            profiler.sync = True
        for closer in (flight, drain, spans, digests, scope):
            if closer is not None:
                try:
                    closer.close()
                except Exception:
                    pass

    try:
        while t < stop:
            act = control.poll() if control is not None else None
            if act is not None:
                # The run server asked this request to stop at a launch
                # boundary: park (checkpoint now, resume on the next
                # --auto-resume life), cancel, or a --timeout expiry.
                if pipe is not None:
                    pipe.flush()  # the last window's drains land first
                if act == "park":
                    if ck is not None:
                        ck.save(state, params)
                    control.outcome = "parked"
                    _close_drains()
                    if emit is not None:
                        emit({"event": "parked", "t_ns": int(t),
                              "window": int(state.n_windows)})
                    return RC_OK
                if act == "cancel":
                    control.outcome = "cancelled"
                    _close_drains()
                    return RC_FAILED
                control.outcome = "timed_out"
                _close_drains()
                print(f"error: run stopped at t={t / SEC:g}s: "
                      f"--timeout expired", file=sys.stderr)
                return RC_USAGE
            # Advance to the next launch boundary on the memoryless
            # union grid of heartbeat and checkpoint multiples
            # (replay.next_sync): the tracker samples between bounded
            # device launches, the checkpointer saves on cadence
            # multiples, and a replay can re-derive the identical
            # boundary sequence from any mid-run checkpoint (window
            # ends clip at launch targets, so the flight-recorder
            # record depends on this schedule).
            t_next = next_sync(t, int(stop), hb_ns, ck_every_ns)
            t0 = time.perf_counter()
            if substrate is not None:
                state = _bridge.run(substrate, state, params, app, t_next)
            elif sup is not None:
                state = sup.launch(
                    state, params, t_next,
                    overlap=pipe.settle if pipe is not None else None)
            elif mesh is not None:
                state = parallel_mod.mesh_run_chunked(state, params, app,
                                                      t_next, mesh=mesh)
            else:
                state = engine.run_chunked(state, params, app, t_next)
            t = t_next
            if pipe is None:
                drains.drain_all(state, t)
                if ck is not None:
                    ck.maybe(state, params, t)
                if progress is not None:
                    progress.update(state, t)
                continue
            if sup is None:
                # Drain window N while window N+1 executes (supervised
                # launches ran this via the overlap hook, between
                # dispatch and their watchdog-bounded block).
                pipe.settle()

            def _boundary(st=state, ts=t):
                drains.drain_all(st, ts)
                if ck is not None:
                    ck.maybe(st, params, ts)
                if progress is not None:
                    progress.update(st, ts)
            # Supervised launches block (and span) internally; t0=None
            # keeps the pipeline from re-recording their window.
            pipe.push(state, _boundary, t0 if sup is None else None)
    except UnrecoveredFailure as e:
        _close_drains()
        print(f"error: {e}", file=sys.stderr)
        print(json.dumps({"crash": e.crash}))
        if emit is not None:
            emit({"event": "crash", "rc": e.rc, "crash": e.crash,
                  "path": e.path})
        return e.rc
    if pipe is not None:
        pipe.flush()  # the drain point of the final window
    if prev_sync and profiler is not None:
        profiler.sync = True
    if progress is not None:
        progress.update(state, t, force=True)
    jax.block_until_ready(state)
    wall = time.perf_counter() - t_wall

    # --- run summary --------------------------------------------------------
    a = state.app
    done = int(jnp.sum(a.streams_done)) if hasattr(a, "streams_done") else 0
    failed = int(jnp.sum(a.streams_failed)) if hasattr(a, "streams_failed") else 0
    summary = {
        "simulated_seconds": t / SEC,
        "wall_seconds": round(wall, 3),
        "hosts": len(asm.hostnames),
        "streams_completed": done,
        "streams_failed": failed,
        "packets_sent": int(jnp.sum(state.hosts.pkts_sent)),
        "packets_received": int(jnp.sum(state.hosts.pkts_recv)),
        "bytes_sent": int(jnp.sum(state.hosts.bytes_sent)),
        "drops_inet": int(jnp.sum(state.hosts.pkts_dropped_inet)),
        "drops_router": int(jnp.sum(state.hosts.pkts_dropped_router)),
        "drops_pool": int(jnp.sum(state.hosts.pkts_dropped_pool)),
        "acks_thinned": int(jnp.sum(state.hosts.acks_thinned)),
        "err_flags": int(state.err),
    }
    if sup is not None:
        summary["supervise"] = {
            "recoveries": sup.recoveries,
            "ladder": sup.ladder,
            "sentinel": sup.sentinel.row,
            "resumed_from": resumed_from,
        }
    if state.nm is not None:
        summary["netem"] = {
            "events_applied": int(state.nm.cursor),
            "packets_killed": int(state.nm.killed),
            "hosts_down_at_stop": int(jnp.sum(state.nm.host_up == 0)),
        }
    if w.want_pcap and args.data_directory:
        import os as _os
        from .observe import write_pcap
        ip_of = lambda i: asm.dns.address_of(i).ip  # noqa: E731
        import jax as _jax
        state = state.replace(cap=_jax.device_get(state.cap))  # fetch ONCE
        if args.pcap:
            n = write_pcap(
                _os.path.join(args.data_directory, "capture.pcap"),
                state.cap, ip_of_host=ip_of)
            summary["pcap_records"] = n
        # Per-host captures (reference <host logpcap pcapdir>).
        if asm.pcap_mask is not None:
            for hi in [i for i, m in enumerate(asm.pcap_mask) if m]:
                d = (asm.pcap_dirs or {}).get(hi, args.data_directory)
                _os.makedirs(d, exist_ok=True)
                write_pcap(
                    _os.path.join(d, f"{asm.hostnames[hi]}.pcap"),
                    state.cap, ip_of_host=ip_of, host_filter=hi)
    if drain is not None:
        drain.drain(state)
        drain.close()
    if scope is not None:
        scope.drain(state, profiler)
        scope.close()
        summary["net"] = scope.summary()
        if profiler is not None:
            profiler.set_scope(scope.flow_rows, scope.link_rows,
                               summary["net"])
    if spans is not None:
        spans.drain(state, profiler)
        spans.close()
        summary["lineage"] = spans.summary()
        if profiler is not None:
            profiler.set_lineage(spans.rows, summary["lineage"])
    if digests is not None:
        digests.drain(state, profiler)
        digests.close()
        summary["digest"] = digests.summary()
        if profiler is not None:
            profiler.set_digest(summary["digest"])
    if tracker is not None:
        tracker.summary(summary, state)
    if substrate is not None:
        procs = substrate.procs
        summary["processes"] = len(procs)
        def _scheduled_stop(p):
            return p.exit_code == -15 and p.stop_ns is not None
        summary["processes_exited_ok"] = sum(
            1 for p in procs if p.exited and
            (p.exit_code == 0 or _scheduled_stop(p)))
        summary["processes_failed"] = sum(
            1 for p in procs if p.exited and p.exit_code != 0
            and not _scheduled_stop(p))
        summary["processes_running_at_stop"] = sum(
            1 for p in procs if not p.exited)
    if profiler is not None:
        trace.fetch_counters(state, profiler)
    if flight is not None:
        flight.drain(state, profiler)
        flight.close()
    if ck is not None:
        summary["checkpoints"] = {
            "dir": ck.dir, "count": len(ck.saved),
            "every_seconds": ck_every_ns / SEC,
            "last_window": ck.saved[-1]["window"] if ck.saved else None,
        }
    if profiler is not None:
        import os as _os2
        if flight is not None:
            profiler.set_flight(
                flight.rows, flight.summary(state, n_devices=n_dev))
        trace_path = _os2.path.join(args.data_directory, "trace.json")
        metrics_path = _os2.path.join(args.data_directory, "metrics.json")
        profiler.write_trace(trace_path)
        m = profiler.write_metrics(
            metrics_path, extra={"simulated_seconds": t / SEC})
        summary["profile"] = {"trace": trace_path, "metrics": metrics_path,
                              "compile_count": m["compile"]["count"]}
        if flight is not None:
            summary["profile"]["windows"] = flight.path
        if not args.quiet:
            print(profiler.summary_table(), file=sys.stderr)
        trace.install(None)
    print(json.dumps(summary))
    if emit is not None:
        emit({"event": "summary", "summary": summary})
    if substrate is not None and summary["processes_failed"]:
        return RC_FAILED
    # A set err bitmask means the simulation violated its own capacity
    # invariants (pool/socket/udp overflow) -- the same "simulation is
    # wrong" class as a sentinel violation or replay divergence.
    return RC_OK if int(state.err) == 0 else RC_INVARIANT


def replay_cmd(args) -> int:
    """`shadow1-tpu replay`: restore, re-run, verify.  Exit codes
    (supervise.py's unified table): 0 verified OK, 1 the simulation is
    wrong (replay DIVERGED at the printed window, or the replayed span
    reproduced a sentinel violation), 2 usage/environment errors."""
    from . import replay as replay_mod
    from .trace import ReplayDivergence
    try:
        summary = replay_mod.replay(
            args.data_directory, window=args.window, time_s=args.time,
            world=args.world,
            out_dir=args.out, devices=args.devices, scope=args.scope,
            lineage=args.trace_packets,
            log_level=args.log_level, pcap=args.pcap,
            pcap_ring=args.pcap_ring, log_ring=args.log_ring,
            profile=args.profile, progress=args.progress,
            verify=not args.no_verify, quiet=args.quiet)
    except ReplayDivergence as e:
        print(f"error: {e}", file=sys.stderr)
        print(json.dumps({"replay_diverged": {
            "window": e.window, "fields": e.fields,
            "got": e.got, "want": e.want}}))
        return RC_INVARIANT
    except CliError as e:
        print(f"error: {e}", file=sys.stderr)
        return e.rc
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_USAGE
    print(json.dumps(summary))
    sn = summary.get("sentinel")
    if sn and sn.get("violations"):
        # The replayed span re-tripped the device invariant probes: the
        # deterministic reproduction of a supervised run's crash.json.
        print(f"replay reproduced a sentinel violation "
              f"({'+'.join(sn['classes'])}) at window "
              f"{sn['first_bad_window']}", file=sys.stderr)
        return RC_INVARIANT
    return RC_OK


def diff_cmd(args) -> int:
    """`shadow1-tpu diff`: align two runs' digest streams, localize the
    first divergence.  Exit codes (supervise.py's unified table): 0 the
    runs agree over every compared window, 1 they diverge (the report
    names where), 2 usage errors -- a directory that is not a
    digest-recorded run, or incomparable digest configs (cadence /
    schema / --devices mismatch, named in the message)."""
    from . import diff as diff_mod
    try:
        report = diff_mod.diff_runs(
            args.run_a, args.run_b, localize=not args.no_localize,
            devices=args.devices, max_elements=args.max_elements,
            quiet=args.quiet)
    except CliError as e:
        print(f"error: {e}", file=sys.stderr)
        return e.rc
    except diff_mod.DiffUsageError as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_USAGE
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return RC_USAGE
    if args.json:
        print(json.dumps(report))
    else:
        print(diff_mod.format_report(report))
    return RC_INVARIANT if report.get("divergence") else RC_OK


def warm_cmd(args) -> int:
    from . import shapes
    log = None
    if not args.quiet:
        def log(rec):  # noqa: E306
            print(f"[shadow1-tpu] warm {rec['app']} @ "
                  f"{rec['bucket_hosts']} hosts: lower "
                  f"{rec['lower_s']}s, compile {rec['compile_s']}s",
                  file=sys.stderr)
    records = shapes.warm_buckets(buckets=args.buckets, apps=args.apps,
                                  log=log)
    print(json.dumps({"warmed": records}))
    return RC_OK


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if args.cmd == "run":
        return run_config(args)
    if args.cmd == "replay":
        return replay_cmd(args)
    if args.cmd == "diff":
        return diff_cmd(args)
    if args.cmd == "warm":
        return warm_cmd(args)
    if args.cmd == "serve":
        from .server import serve
        return serve(args)
    if args.cmd == "submit":
        from .client import submit_cmd
        return submit_cmd(args)
    if args.cmd == "status":
        from .client import status_cmd
        return status_cmd(args)
    if args.cmd == "stats":
        from .client import stats_cmd
        return stats_cmd(args)
    if args.cmd == "cancel":
        from .client import cancel_cmd
        return cancel_cmd(args)
    return RC_USAGE


if __name__ == "__main__":
    sys.exit(main())
