"""Command-line front end: run a shadow.config.xml on the TPU engine.

The reference binary is `shadow [options] config.xml` (options.c); this
is the same surface for the rebuilt engine:

    python -m shadow1_tpu run examples/shadow.config.xml

Runs the simulation in bounded device launches, then prints a run summary
(per-host transfer completions, traffic counters) to stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp

from .core import engine, simtime

SEC = simtime.SIMTIME_ONE_SECOND
MS = simtime.SIMTIME_ONE_MILLISECOND

def _parser():
    p = argparse.ArgumentParser(
        prog="shadow1-tpu",
        description="TPU-native discrete-event network simulator "
                    "(shadow.config.xml compatible)")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("run", help="run a simulation config")
    r.add_argument("config", help="shadow.config.xml path")
    r.add_argument("--seed", type=int, default=1,
                   help="root RNG seed (reference --seed)")
    r.add_argument("--stop-time", type=int, default=None,
                   help="override <shadow stoptime> (seconds)")
    r.add_argument("--sock-slots", type=int, default=None,
                   help="per-host socket-table slots (default: auto)")
    r.add_argument("--pool-slab", type=int, default=128,
                   help="packet-pool slots per host")
    r.add_argument("--tcp-congestion-control", choices=("reno", "cubic"),
                   default="reno",
                   help="TCP congestion-control algorithm "
                        "(reference --tcp-congestion-control)")
    r.add_argument("--interface-qdisc", choices=("fifo", "rr"),
                   default="fifo",
                   help="NIC socket-selection discipline "
                        "(reference --interface-qdisc)")
    r.add_argument("--cpu-threshold", type=int, default=-1,
                   help="microseconds of CPU backlog after which a host "
                        "blocks; -1 disables (reference --cpu-threshold)")
    r.add_argument("--cpu-precision", type=int, default=200,
                   help="CPU wake-time rounding in microseconds "
                        "(reference --cpu-precision)")
    r.add_argument("--data-directory", default=None,
                   help="where to write heartbeat/summary files")
    r.add_argument("--pcap", action="store_true",
                   help="capture sent packets and write capture.pcap to "
                        "the data directory (reference logpcap)")
    r.add_argument("--pcap-ring", type=int, default=1 << 17,
                   help="capture ring capacity; older records are "
                        "silently overwritten on wrap (each packet now "
                        "costs up to two records: send + receive "
                        "direction, hence the doubled default)")
    r.add_argument("--netem", metavar="EVENTS.json", default=None,
                   help="network-dynamics schedule: JSON events file "
                        "(link_down/up, host_down/up, latency_scale, "
                        "loss, partition, bandwidth_scale; host names "
                        "resolve against the config's DNS) applied "
                        "inside the device step -- see docs/netem.md")
    r.add_argument("--churn", type=float, metavar="RATE", default=None,
                   help="seeded chaos mode: every host flaps down at "
                        "RATE times per second on average (exponential "
                        "up/down times, bitwise reproducible per --seed)")
    r.add_argument("--churn-downtime", type=float, default=5.0,
                   metavar="SECONDS",
                   help="mean down-time per chaos flap (default 5s)")
    r.add_argument("--heartbeat-frequency", type=int, default=1,
                   help="heartbeat interval in sim seconds (0 = off)")
    r.add_argument("--log-level", choices=("off", "warning", "debug"),
                   default="off",
                   help="simulation event log level (reference --log-level); "
                        "writes shadow.log to the data directory.  NOTE: "
                        "debug logs EVERY send/deliver -- for large worlds "
                        "scope it to hosts of interest via <host "
                        "loglevel=\"debug\"> in the config, or the ring "
                        "overflows between drains (lost records are "
                        "counted and reported)")
    r.add_argument("--log-ring", type=int, default=0,
                   help="event-log ring capacity (0 = auto: 64k, grown to "
                        "1M under global debug so a full drain interval "
                        "fits)")
    r.add_argument("--profile", action="store_true",
                   help="profile the run: write trace.json (Chrome "
                        "trace-event format; open in chrome://tracing or "
                        "ui.perfetto.dev) and metrics.json (per-phase "
                        "p50/p95 wall times, transfer bytes, JIT compile "
                        "count) to the data directory and print a phase "
                        "summary table (see docs/observability.md)")
    r.add_argument("--progress", action="store_true",
                   help="print a one-line live status to stderr every few "
                        "seconds of wall time: sim time covered, event "
                        "rate, window rate, ETA -- for long runs that "
                        "would otherwise be silent")
    r.add_argument("--quiet", action="store_true")
    r.add_argument("--bucket", action="store_true",
                   help="pad the world up to its shape bucket "
                        "(shapes.pad_world_to_bucket: host count rounded "
                        "up the geometric ladder, real-host rows bitwise "
                        "identical to the exact-size run) so different-"
                        "sized configs reuse one compiled graph -- see "
                        "docs/shapes.md.  Composes with --devices: bucket "
                        "first, then mesh-pad")
    r.add_argument("--devices", type=int, default=1, metavar="N",
                   help="shard the run across N devices "
                        "(parallel.mesh_run_until: the window loop under "
                        "shard_map with a dst-bucketed all-to-all exchange; "
                        "bitwise-identical to single-device, see "
                        "docs/parallel.md).  Worlds whose host count does "
                        "not divide N are padded with inert hosts.  The "
                        "observability stack (--pcap, --log-level, "
                        "--profile, heartbeats) runs sharded; only "
                        "real-process plugins remain single-device")
    r.add_argument("--scope", metavar="SPEC", default=None,
                   help="flowscope: sample per-flow TCP state (cwnd, "
                        "ssthresh, srtt, inflight, retransmits, bytes) "
                        "and/or per-host link state (bytes forwarded, "
                        "queue depth, netem-scaled capacity, drops) on "
                        "the device at a sim-time cadence, drained to "
                        "flows.jsonl/links.jsonl in the data directory.  "
                        "SPEC is 'flows[,links][:interval]', e.g. "
                        "'flows', 'flows,links:50ms' (default interval "
                        "100ms).  Sampling never perturbs the "
                        "trajectory; see docs/observability.md")

    w = sub.add_parser(
        "warm",
        help="pre-compile the standard shape buckets into the "
             "persistent XLA cache (docs/shapes.md)")
    w.add_argument("--buckets", type=int, nargs="+", default=None,
                   metavar="H",
                   help="host bucket sizes to warm (default: the "
                        "standard set, shapes.STANDARD_HOST_BUCKETS)")
    w.add_argument("--apps", nargs="+", default=("phold", "bulk"),
                   choices=("phold", "bulk", "tgen", "onion", "gossip",
                            "bulk-scope"),
                   help="world flavors to warm (default: phold + bulk; "
                        "tgen/onion/gossip cover the example-ladder "
                        "worlds, bulk-scope the --scope-sampled variant "
                        "so flowscope runs hit the warm cache too)")
    w.add_argument("--quiet", action="store_true")
    return p


def run_config(args) -> int:
    from .config import assemble

    profiler = None
    if args.profile:
        if not args.data_directory:
            print("error: --profile requires --data-directory",
                  file=sys.stderr)
            return 2
        from . import trace
        profiler = trace.install(trace.Profiler(sync=True))

    scope_kw = None
    if args.scope:
        if not args.data_directory:
            print("error: --scope requires --data-directory",
                  file=sys.stderr)
            return 2
        from . import trace as _trace_mod
        try:
            scope_kw = _trace_mod.parse_scope_spec(args.scope)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    t_wall = time.perf_counter()
    asm = assemble.load(args.config, seed=args.seed,
                        sock_slots=args.sock_slots,
                        pool_slab=args.pool_slab,
                        qdisc=args.interface_qdisc,
                        cpu_threshold_us=args.cpu_threshold,
                        cpu_precision_us=args.cpu_precision,
                        cong=args.tcp_congestion_control)
    stop = (args.stop_time * SEC) if args.stop_time else asm.stop_time
    if not args.quiet:
        print(f"[shadow1-tpu] {len(asm.hostnames)} hosts, "
              f"{asm.topology.num_vertices} vertices, "
              f"stop={stop / SEC:.0f}s, backend={jax.default_backend()}",
              file=sys.stderr)

    tracker = None
    if args.data_directory and args.heartbeat_frequency > 0:
        from .observe import Tracker
        tracker = Tracker(args.data_directory, asm.hostnames,
                          interval_s=args.heartbeat_frequency,
                          per_host_interval_s=asm.heartbeat_freq_s)

    state, params, app = asm.state, asm.params, asm.app

    # Network dynamics: merge the config's <netem> section (already
    # installed by assemble) with the CLI's --netem/--churn additions into
    # one schedule and reinstall.  Reinstalling over an already-shrunk
    # lookahead can only shrink it further -- conservative, never wrong.
    if args.netem or args.churn is not None:
        from . import netem as netem_mod
        tl = asm.netem if asm.netem is not None else netem_mod.timeline()
        if args.netem:
            add = netem_mod.load_json(
                args.netem,
                resolve=lambda n: asm.dns.resolve_name(n).host_index)
            tl.events.extend(add.events)
            tl.groups.update(add.groups)
        if args.churn is not None:
            tl.chaos(params.seed_key, len(asm.hostnames), args.churn,
                     mean_down_s=args.churn_downtime, t_end=int(stop))
        state, params = netem_mod.install(
            state.replace(nm=None), params, tl)
        if not args.quiet:
            print(f"[shadow1-tpu] netem: {tl.describe()}", file=sys.stderr)

    # Observability rings are built in the mesh layout when the run will
    # shard (per-shard segments + cursors; docs/observability.md).
    n_dev = max(1, args.devices)

    want_pcap = args.pcap or (asm.pcap_mask is not None
                              and asm.pcap_mask.any())
    if want_pcap:
        if not args.data_directory:
            print("error: packet capture requires --data-directory",
                  file=sys.stderr)
            return 2
        from .core.state import make_capture_ring
        state = state.replace(cap=make_capture_ring(args.pcap_ring,
                                                    shards=n_dev))
        if args.pcap:
            # An explicit global capture must not be filtered down by
            # per-host logpcap masks.
            import jax.numpy as jnp_m
            params = params.replace(
                pcap_mask=jnp_m.ones_like(params.pcap_mask))

    # Leveled sim-time event log (reference ShadowLogger): enabled by
    # --log-level or any per-host <host loglevel>.
    _LVL = {None: 0, "off": 0, "error": 1, "critical": 1, "warning": 1,
            "message": 1, "info": 2, "debug": 2, "trace": 2}
    global_lvl = _LVL[args.log_level]
    host_lvls = []
    for lv in (asm.loglevels or [None] * len(asm.hostnames)):
        key = (lv or "").lower() or None
        if key not in _LVL:
            print(f"[shadow1-tpu] WARNING: unknown loglevel {lv!r} "
                  f"(known: {sorted(k for k in _LVL if k)}); treating as "
                  f"'off'", file=sys.stderr)
        host_lvls.append(max(_LVL.get(key, 0), global_lvl))
    drain = None
    if any(host_lvls):
        if not args.data_directory:
            print("error: --log-level requires --data-directory",
                  file=sys.stderr)
            return 2
        import jax.numpy as jnp_
        from .core.state import make_log_ring
        from .observe import LogDrain
        ring = args.log_ring
        if ring <= 0:
            # Debug level (global OR per-host) logs ~2 records per
            # delivered packet; a 64k ring loses most of a busy drain
            # interval.  Auto-grow.
            ring = (1 << 20) if max(host_lvls) >= 2 else (1 << 16)
        state = state.replace(
            log=make_log_ring(ring, shards=n_dev),
            log_level=jnp_.asarray(host_lvls, jnp_.int32))
        drain = LogDrain(
            __import__("os").path.join(args.data_directory, "shadow.log"),
            asm.hostnames)
    # Real-process plugins (config <plugin path> pointing at an actual
    # executable): spawn them under the substrate at their start times
    # and drive the run through the window-protocol bridge.
    substrate = None
    if asm.real_procs:
        from .substrate import Substrate, bridge as _bridge
        dns = asm.dns

        def _res_ip(ip):
            try:
                return dns.resolve_ip(ip).host_index
            except KeyError:
                return None

        def _res_name(name):
            try:
                return dns.resolve_name(name).ip
            except KeyError:
                return None

        workdir = args.data_directory or "shadow1-procs"
        substrate = Substrate(
            resolve_ip=_res_ip,
            workdir=__import__("os").path.join(workdir, "procs"),
            # Low slots belong to the modeled side (tgen listener=0,
            # client=1); real processes allocate above them.
            sock_slot_base=2,
            resolve_name=_res_name,
            host_ip=lambda i: dns.address_of(i).ip)
        for host_i, argv, start_ns, stop_ns in asm.real_procs:
            substrate.spawn_at(host_i, argv, start_ns, stop_ns)
        if not args.quiet:
            print(f"[shadow1-tpu] {len(asm.real_procs)} real process(es) "
                  f"under the substrate", file=sys.stderr)

    if profiler is not None:
        from . import trace
        # Device-side per-window counters, fetched once per drain point.
        state = trace.ensure_counters(state)

    if args.bucket:
        # Bucket BEFORE any mesh padding: ladder rungs divide every
        # power-of-two device count up to 64, so the mesh pass below is
        # normally an identity on a bucketed world (docs/shapes.md).
        from . import shapes
        h0 = int(state.hosts.num_hosts)
        state, params = shapes.pad_world_to_bucket(state, params)
        if not args.quiet and int(state.hosts.num_hosts) != h0:
            print(f"[shadow1-tpu] bucket: {h0} -> "
                  f"{int(state.hosts.num_hosts)} hosts", file=sys.stderr)

    mesh = None
    parallel_mod = None
    if args.devices > 1:
        # The observability stack runs sharded (rings built with
        # shards=N above, counters finalized across shards); only the
        # substrate bridge remains single-device (per-host syscall RPC
        # serialized through one device).
        if substrate is not None:
            print("error: --devices is incompatible with real-process "
                  "plugins (<plugin> with a real executable): the "
                  "substrate bridge drives one device.  That is the only "
                  "remaining refusal -- --pcap, --log-level, --profile, "
                  "--progress and heartbeats all run sharded (see "
                  "docs/parallel.md)", file=sys.stderr)
            return 2
        from . import parallel as parallel_mod
        devs = jax.devices()
        if len(devs) < args.devices:
            print(f"error: --devices {args.devices} but only {len(devs)} "
                  f"{jax.default_backend()} device(s) visible",
                  file=sys.stderr)
            return 2
        mesh = parallel_mod.make_mesh(devs[:args.devices])
        state, params = parallel_mod.pad_world_to_mesh(
            state, params, args.devices)
        if not args.quiet:
            print(f"[shadow1-tpu] mesh: {args.devices} devices, "
                  f"{int(state.hosts.num_hosts) // args.devices} hosts "
                  f"per shard", file=sys.stderr)

    flight = None
    if profiler is not None:
        # Per-window flight recorder (installed AFTER mesh padding so the
        # shard matrices match the padded host count); drained at the
        # same chunk boundaries as the counters -- no extra syncs.
        state = trace.ensure_flight_recorder(state, shards=n_dev)
        flight = trace.FlightDrain(
            __import__("os").path.join(args.data_directory,
                                       "windows.jsonl"))

    scope = None
    if scope_kw is not None:
        # Flowscope sampling block (same AFTER-mesh-padding rule: each
        # shard owns a ring segment sized off the padded host count).
        from . import trace as _trace_mod
        _os_s = __import__("os")
        state = _trace_mod.ensure_flowscope(state, shards=n_dev,
                                            **scope_kw)
        scope = _trace_mod.ScopeDrain(
            flows_path=_os_s.path.join(args.data_directory, "flows.jsonl")
            if scope_kw["flows"] else None,
            links_path=_os_s.path.join(args.data_directory, "links.jsonl")
            if scope_kw["links"] else None,
            real_hosts=len(asm.hostnames))
        if not args.quiet:
            print(f"[shadow1-tpu] scope: {args.scope}", file=sys.stderr)

    progress = None
    if args.progress:
        from .observe import Progress
        progress = Progress(int(stop))

    t = int(state.now)
    hb_next = 0
    while t < stop:
        # Advance one heartbeat interval (or to the end) per outer step so
        # the tracker samples between bounded device launches.
        t_next = min(t + (tracker.sample_interval_ns if tracker else stop),
                     stop)
        if substrate is not None:
            state = _bridge.run(substrate, state, params, app, t_next)
        elif mesh is not None:
            state = parallel_mod.mesh_run_chunked(state, params, app,
                                                  t_next, mesh=mesh)
        else:
            state = engine.run_chunked(state, params, app, t_next)
        t = t_next
        if tracker is not None and t >= hb_next:
            tracker.heartbeat(state, t)
            hb_next = t + tracker.sample_interval_ns
        if drain is not None:
            drain.drain(state)
        if profiler is not None:
            trace.fetch_counters(state, profiler)
        if flight is not None:
            flight.drain(state, profiler)
        if scope is not None:
            scope.drain(state, profiler)
        if progress is not None:
            progress.update(state, t)
    if progress is not None:
        progress.update(state, t, force=True)
    jax.block_until_ready(state)
    wall = time.perf_counter() - t_wall

    # --- run summary --------------------------------------------------------
    a = state.app
    done = int(jnp.sum(a.streams_done)) if hasattr(a, "streams_done") else 0
    failed = int(jnp.sum(a.streams_failed)) if hasattr(a, "streams_failed") else 0
    summary = {
        "simulated_seconds": t / SEC,
        "wall_seconds": round(wall, 3),
        "hosts": len(asm.hostnames),
        "streams_completed": done,
        "streams_failed": failed,
        "packets_sent": int(jnp.sum(state.hosts.pkts_sent)),
        "packets_received": int(jnp.sum(state.hosts.pkts_recv)),
        "bytes_sent": int(jnp.sum(state.hosts.bytes_sent)),
        "drops_inet": int(jnp.sum(state.hosts.pkts_dropped_inet)),
        "drops_router": int(jnp.sum(state.hosts.pkts_dropped_router)),
        "drops_pool": int(jnp.sum(state.hosts.pkts_dropped_pool)),
        "acks_thinned": int(jnp.sum(state.hosts.acks_thinned)),
        "err_flags": int(state.err),
    }
    if state.nm is not None:
        summary["netem"] = {
            "events_applied": int(state.nm.cursor),
            "packets_killed": int(state.nm.killed),
            "hosts_down_at_stop": int(jnp.sum(state.nm.host_up == 0)),
        }
    if want_pcap and args.data_directory:
        import os as _os
        from .observe import write_pcap
        ip_of = lambda i: asm.dns.address_of(i).ip  # noqa: E731
        import jax as _jax
        state = state.replace(cap=_jax.device_get(state.cap))  # fetch ONCE
        if args.pcap:
            n = write_pcap(
                _os.path.join(args.data_directory, "capture.pcap"),
                state.cap, ip_of_host=ip_of)
            summary["pcap_records"] = n
        # Per-host captures (reference <host logpcap pcapdir>).
        if asm.pcap_mask is not None:
            for hi in [i for i, m in enumerate(asm.pcap_mask) if m]:
                d = (asm.pcap_dirs or {}).get(hi, args.data_directory)
                _os.makedirs(d, exist_ok=True)
                write_pcap(
                    _os.path.join(d, f"{asm.hostnames[hi]}.pcap"),
                    state.cap, ip_of_host=ip_of, host_filter=hi)
    if drain is not None:
        drain.drain(state)
        drain.close()
    if scope is not None:
        scope.drain(state, profiler)
        scope.close()
        summary["net"] = scope.summary()
        if profiler is not None:
            profiler.set_scope(scope.flow_rows, scope.link_rows,
                               summary["net"])
    if tracker is not None:
        tracker.summary(summary, state)
    if substrate is not None:
        procs = substrate.procs
        summary["processes"] = len(procs)
        def _scheduled_stop(p):
            return p.exit_code == -15 and p.stop_ns is not None
        summary["processes_exited_ok"] = sum(
            1 for p in procs if p.exited and
            (p.exit_code == 0 or _scheduled_stop(p)))
        summary["processes_failed"] = sum(
            1 for p in procs if p.exited and p.exit_code != 0
            and not _scheduled_stop(p))
        summary["processes_running_at_stop"] = sum(
            1 for p in procs if not p.exited)
    if profiler is not None:
        import os as _os2
        trace.fetch_counters(state, profiler)
        if flight is not None:
            flight.drain(state, profiler)
            flight.close()
            profiler.set_flight(
                flight.rows, flight.summary(state, n_devices=n_dev))
        trace_path = _os2.path.join(args.data_directory, "trace.json")
        metrics_path = _os2.path.join(args.data_directory, "metrics.json")
        profiler.write_trace(trace_path)
        m = profiler.write_metrics(
            metrics_path, extra={"simulated_seconds": t / SEC})
        summary["profile"] = {"trace": trace_path, "metrics": metrics_path,
                              "compile_count": m["compile"]["count"]}
        if flight is not None:
            summary["profile"]["windows"] = flight.path
        if not args.quiet:
            print(profiler.summary_table(), file=sys.stderr)
        trace.install(None)
    print(json.dumps(summary))
    if substrate is not None and summary["processes_failed"]:
        return 3
    return 0 if int(state.err) == 0 else 2


def warm_cmd(args) -> int:
    from . import shapes
    log = None
    if not args.quiet:
        def log(rec):  # noqa: E306
            print(f"[shadow1-tpu] warm {rec['app']} @ "
                  f"{rec['bucket_hosts']} hosts: lower "
                  f"{rec['lower_s']}s, compile {rec['compile_s']}s",
                  file=sys.stderr)
    records = shapes.warm_buckets(buckets=args.buckets, apps=args.apps,
                                  log=log)
    print(json.dumps({"warmed": records}))
    return 0


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if args.cmd == "run":
        return run_config(args)
    if args.cmd == "warm":
        return warm_cmd(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
