"""NIC token buckets and the upstream-router CoDel AQM control law.

The reference gives every host a per-interface pair of token buckets
refilled every 1ms of sim time by self-scheduled tasks
(/root/reference/src/main/host/network_interface.c:32-40,93-190), with
capacity = one refill + MTU (network_interface.c:192-226), and an
upstream-ISP router whose queue runs CoDel per RFC 8289: target 10ms,
interval 100ms, drop-next spacing interval/sqrt(count)
(/root/reference/src/main/routing/router_queue_codel.c:33-56,198-267).

TPU-shaped differences:

* Refill is lazy and continuous: tokens accrue as `(now - last) * rate`
  in **scaled units of byte-nanoseconds** (1 byte == 1e9 units), so
  integer accrual is exact with no per-ms events and no rounding drift.
  The reference's 1ms quantization is a burstier special case; capacity
  is the same one-interval + MTU.
* CoDel drops at most one packet per dequeue; the engine re-ticks the
  host at the same instant to continue draining, which reproduces the
  reference's dequeue-while-dropping loop across micro-steps.

The rate fed to `time_until` is the netem-scaled effective uplink rate
(netem.apply.effective_rates), and that same per-window value is what
the flowscope link ring records as `cap_Bps` (`--scope links`,
engine._scope_sample) -- so link-utilization numbers in
tools/parse.py / plot.py are fractions of the capacity the NIC actually
enforced during that window, faults included.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import simtime
from .state import I32, I64, MTU

SCALE = 1_000_000_000  # token units per byte (1 byte-second / 1e9 ns)

REFILL_INTERVAL_NS = simtime.SIMTIME_ONE_MILLISECOND

CODEL_TARGET_NS = 10 * simtime.SIMTIME_ONE_MILLISECOND
CODEL_INTERVAL_NS = 100 * simtime.SIMTIME_ONE_MILLISECOND


def bucket_capacity(rate_Bps):
    """Scaled capacity: one refill interval of line rate plus an MTU."""
    return rate_Bps * REFILL_INTERVAL_NS + MTU * SCALE


def refill(tokens, last, rate_Bps, now, mask):
    """Lazy continuous refill ([H] scaled tokens).  Returns (tokens, last)
    updated where mask.  dt is clamped to the bucket fill time so
    `dt * rate` cannot overflow i64 after long idle periods."""
    fill_time = REFILL_INTERVAL_NS + (MTU * SCALE) // jnp.maximum(rate_Bps, 1) + 1
    dt = jnp.clip(now - last, 0, fill_time)
    accrued = jnp.minimum(bucket_capacity(rate_Bps), tokens + dt * rate_Bps)
    return (jnp.where(mask, accrued, tokens),
            jnp.where(mask, now, last))


def time_until(deficit_scaled, rate_Bps):
    """ns until `deficit_scaled` more tokens accrue (ceil)."""
    r = jnp.maximum(rate_Bps, 1)
    return (deficit_scaled + r - 1) // r


def codel_dequeue(hosts, mask, now, sojourn, backlog_after):
    """One CoDel dequeue decision per masked host.

    Args: `sojourn` [H] ns the candidate packet spent queued,
    `backlog_after` [H] i32 packets that would remain after this dequeue.
    Returns (hosts', drop [H] bool): drop=True means discard the candidate
    instead of delivering it.  State fields follow RFC 8289 pseudocode /
    the reference's _codel_* helpers.
    """
    count = hosts.codel_count
    dropping = hosts.codel_dropping
    fa = hosts.codel_first_above
    drop_next = hosts.codel_drop_next

    # ok_to_drop: sojourn above target for a full interval, and the queue
    # is not nearly-empty (reference checks bytes <= MTU; one queued
    # packet is our analog).
    below = (sojourn < CODEL_TARGET_NS) | (backlog_after <= 0)
    fa_new = jnp.where(below, 0,
                       jnp.where(fa == 0, now + CODEL_INTERVAL_NS, fa))
    ok = mask & ~below & (fa_new != 0) & (now >= fa_new)

    def spacing(cnt):
        return (CODEL_INTERVAL_NS /
                jnp.sqrt(jnp.maximum(cnt, 1).astype(jnp.float32))).astype(I64)

    # In dropping state: leave it if not ok; else drop when due.
    drop_in = dropping & ok & (now >= drop_next)
    count_in = count + jnp.where(drop_in, 1, 0)
    next_in = jnp.where(drop_in, drop_next + spacing(count_in), drop_next)

    # Entering dropping state.
    recent = (now - drop_next) < (16 * CODEL_INTERVAL_NS)
    enter = mask & ~dropping & ok
    count_enter = jnp.where(recent & (count > 2), count - 2, 1)
    next_enter = now + spacing(count_enter)

    drop = drop_in | enter
    new_dropping = jnp.where(mask, (dropping & ok) | enter, dropping)
    new_count = jnp.where(enter, count_enter,
                          jnp.where(mask & dropping, count_in, count))
    new_next = jnp.where(enter, next_enter,
                         jnp.where(mask & dropping, next_in, drop_next))
    hosts = hosts.replace(
        codel_first_above=jnp.where(mask, fa_new, fa),
        codel_dropping=new_dropping,
        codel_count=new_count.astype(I32),
        codel_drop_next=new_next.astype(I64),
    )
    return hosts, drop
