"""Fused Pallas micro-step: the window loop's phase graph as two kernels.

The reference micro-step (engine._microstep_core) traces ~5k HLO ops and
XLA's fusion boundaries roughly double every shared subexpression that
crosses them, so at small worlds the step is KERNEL-COUNT bound, not
data bound (PERF.md rounds 4-8; "Event Tensor" makes the same case for
dynamic event graphs).  This module packages the phase graph into two
hand-fused Pallas kernels over per-host slab blocks:

* K_DELIVER -- event drain + transport delivery: the whole `_rx_phase`
  (router enqueue, NIC rx tokens + CoDel, UDP/TCP arrival processing)
  for a block of hosts.
* K_TRANSPORT -- TCP transmit, emission staging (`_stage_emissions`,
  including routing + loopback), the parked-TX drain, virtual-CPU
  accounting, and the post-step per-host scan (`_scan_all` semantics),
  so the inner while body needs no separate re-scan.

Between the kernels run the phases the kernels must not carry: TCP
timers (already diet-gated) and the application tick.  The tick stays
outside even when an app's tick is provably row-local, because bitwise
equality forbids moving f32 TRANSCENDENTALS between compilation
contexts: XLA CPU compiles e.g. phold's log1p delay draw to ulp-
different results inside the interpret-mode kernel body than in the
main graph (measured -- jit vs eager of the identical reference window
loop already disagree by 1-2ns per draw).  Integer math is context-
stable, which is why every phase inside the kernels below is safe: the
f32 the kernels do touch (loss/reliability comparisons) is linear
arithmetic on rng bits, not transcendental expansions.

Blocking contract: every phase inside the kernels is ROW-LOCAL over
hosts -- per-host slab reductions, one-hot merges, row-local allocation.
The only cross-row inputs are read-only replicated tables (route_blk,
host_vertex, the netem overlay, seed_key), which every block reads
whole, and the only cross-row outputs are integer accumulators (event
count, error bitmask, netem kill count) which the kernels emit as
per-block partials merged outside (integer sum/OR are associative, so
the merge is bitwise-exact against the reference reduction).

The kernel bodies CALL the reference implementations on the blocked
state: `shadow1_tpu.core.engine` remains the single source of semantic
truth, and the fused path is bitwise-identical to the reference path by
construction (tests/test_megakernel.py asserts full-pytree equality).
Global host identity inside a block comes from the `hoff` mechanism the
mesh already uses: block b of a shard at offset `base` runs with
hoff = base + b * block_hosts, so RNG keys, packet SRC columns, and
host_vertex slicing see global ids.

On TPU the kernels lower through Mosaic; on every other backend they run
in Pallas interpret mode, so CPU tests exercise the same code path
(`docs/megakernel.md` has the full contract).  The flag is static
(params.megakernel, in ShapeKey), so buckets never mix fused and
reference graphs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import emit, engine
from .state import I32, I64, ICOLS, STAGE_IN_FLIGHT, STAGE_TX_QUEUED, SimState

INV = engine.INV

# Per-host NetParams leaves: sliced to local rows under the mesh
# (parallel/mesh.py _PARAM_LOCAL) and blocked per kernel invocation.
_PARAMS_LOCAL = ("bw_up_Bps", "bw_down_Bps", "cpu_ns_per_event",
                 "autotune_snd", "autotune_rcv", "iface_buf_pkts",
                 "pcap_mask")
# Replicated leaves: global tables + scalars, read whole by every block.
_PARAMS_REP = ("route_blk", "host_vertex", "min_latency_ns", "seed_key",
               "stop_time", "bootstrap_end", "cpu_threshold_ns",
               "cpu_precision_ns", "qdisc")


def enabled(state: SimState, params, app) -> bool:
    """Trace-time static: does this world take the fused path?  The
    log/capture rings and the lineage span ring append at global cursors
    (cross-row state the kernels do not carry), so those worlds fall
    back to the reference graph.  Every OTHER instrumentation block --
    flowscope sampling, statescope digests, the sentinel, the flight
    recorder, trace counters -- is window-close bookkeeping outside the
    micro-step loop and deliberately does NOT gate: --scope and
    --digest-every worlds keep the fused (and persistent) op diet,
    pinned bitwise by tests/test_megakernel.py's instrumented-world
    battery (docs/megakernel.md, "What gates and what doesn't")."""
    if not getattr(params, "megakernel", False):
        return False
    return state.log is None and state.cap is None \
        and state.lineage is None


def persistent_enabled(state: SimState, params, app) -> bool:
    """Trace-time static: does this world run whole windows through the
    persistent K_WINDOW region (window_fused)?  Requires the megakernel
    path to be admissible at all, the params.persistent static, and an
    off-mesh world: the mesh's loop predicates and exchange are
    collectives (pmin/all_to_all), which cannot live inside a kernel, so
    sharded runs keep the per-phase fused kernels per shard."""
    if not enabled(state, params, app):
        return False
    if not getattr(params, "persistent", False):
        return False
    return state.hoff is None


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _grid(h: int) -> int:
    """Blocks per kernel launch.  Grid 1 degenerates to the reference
    fusion behavior (XLA unrolls single-trip loops), so prefer the
    largest small divisor; odd host counts fall back to 1 (correct,
    just without the op-count win)."""
    for g in (8, 4, 2):
        if h % g == 0:
            return g
    return 1


# ---------------------------------------------------------------------------
# Generic blocked pallas_call over pytrees
# ---------------------------------------------------------------------------


def _shard_spec(shape, g):
    bs = (shape[0] // g,) + tuple(shape[1:])
    nd = len(shape)
    return pl.BlockSpec(bs, lambda i, _n=nd: (i,) + (0,) * (_n - 1))


def _full_spec(shape):
    nd = len(shape)
    return pl.BlockSpec(tuple(shape), lambda i, _n=nd: (0,) * nd)


def _call_blocked(body, g, shard_in, full_in):
    """Run `body(shard_block, full, block_idx) -> (shard_out, accum_out)`
    over `g` host blocks as ONE pallas_call.

    `shard_in` leaves are blocked on their leading axis (which must be a
    multiple of g: [H], [H, k], or the host-major packed [H*k, C]
    slabs); `full_in` leaves are replicated to every block.  `shard_out`
    leaves are reassembled on the leading axis; `accum_out` leaves (per-
    block partials, any shape) come back stacked [g, ...] for the caller
    to reduce.  0-d leaves are boxed to (1,) across the pallas boundary
    and zero-size leaves are rebuilt as constants inside (an empty array
    carries no data), both transparently.

    Shard outputs whose pytree path matches a shard input of the same
    shape/dtype (state slabs updated in place: hosts, inbox, socks,
    pool, em) alias that input's buffer, so XLA elides the defensive
    copy and the output-init broadcast at every kernel boundary --
    pure buffer reuse, bitwise-neutral."""
    paths_s, td_s = jax.tree_util.tree_flatten_with_path(shard_in)
    flat_s = [l for _p, l in paths_s]
    flat_f, td_f = jax.tree_util.tree_flatten(full_in)

    f_meta = [(l.ndim == 0, l.size == 0, tuple(l.shape), l.dtype)
              for l in flat_f]
    f_pass = [l.reshape(1) if l.ndim == 0 else l
              for l in flat_f if l.size > 0]

    blk_s = [jax.ShapeDtypeStruct((l.shape[0] // g,) + tuple(l.shape[1:]),
                                  l.dtype) for l in flat_s]
    abs_shard = jax.tree_util.tree_unflatten(td_s, blk_s)
    out_sh_av, out_ac_av = jax.eval_shape(
        body, abs_shard, full_in, jax.ShapeDtypeStruct((), jnp.int32))
    sh_paths, td_osh = jax.tree_util.tree_flatten_with_path(out_sh_av)
    sh_av = [a for _p, a in sh_paths]
    ac_av, td_oac = jax.tree_util.tree_flatten(out_ac_av)

    in_path_idx = {jax.tree_util.keystr(p): i
                   for i, (p, _l) in enumerate(paths_s)}
    aliases = {}
    for j, (p, a) in enumerate(sh_paths):
        i = in_path_idx.get(jax.tree_util.keystr(p))
        if i is not None and tuple(flat_s[i].shape[1:]) == tuple(a.shape[1:]) \
                and flat_s[i].dtype == a.dtype:
            aliases[i] = j

    n_s, n_f = len(flat_s), len(f_pass)

    def kernel(*refs):
        rs = refs[:n_s]
        rf = refs[n_s:n_s + n_f]
        ro = refs[n_s + n_f:]
        svals = [r[...] for r in rs]
        it = iter(rf)
        fvals = []
        for boxed, empty_leaf, shape, dtype in f_meta:
            if empty_leaf:
                fvals.append(jnp.zeros(shape, dtype))
            else:
                v = next(it)[...]
                fvals.append(v.reshape(()) if boxed else v)
        s_tree = jax.tree_util.tree_unflatten(td_s, svals)
        f_tree = jax.tree_util.tree_unflatten(td_f, fvals)
        o_sh, o_ac = body(s_tree, f_tree, pl.program_id(0))
        o_flat = jax.tree_util.tree_leaves(o_sh) + \
            [jnp.asarray(x)[None] for x in jax.tree_util.tree_leaves(o_ac)]
        for r, v in zip(ro, o_flat):
            r[...] = v

    out_shape = (
        [jax.ShapeDtypeStruct((a.shape[0] * g,) + tuple(a.shape[1:]),
                              a.dtype) for a in sh_av] +
        [jax.ShapeDtypeStruct((g,) + tuple(a.shape), a.dtype)
         for a in ac_av])
    out_specs = (
        [_shard_spec(s.shape, g) for s in out_shape[:len(sh_av)]] +
        [pl.BlockSpec((1,) + tuple(a.shape),
                      lambda i, _n=a.ndim: (i,) + (0,) * _n)
         for a in ac_av])
    in_specs = ([_shard_spec(l.shape, g) for l in flat_s] +
                [_full_spec(l.shape) for l in f_pass])

    res = pl.pallas_call(
        kernel, grid=(g,), in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, input_output_aliases=aliases,
        interpret=_interpret(),
    )(*flat_s, *f_pass)
    res = list(res) if isinstance(res, (list, tuple)) else [res]
    out_sh = jax.tree_util.tree_unflatten(td_osh, res[:len(sh_av)])
    out_ac = jax.tree_util.tree_unflatten(td_oac, res[len(sh_av):])
    return out_sh, out_ac


def exchange_call(pool, ib, h, params):
    """engine._exchange_core as ONE single-block pallas call: the
    boundary exchange's ~600-op rank/splice graph (sort, two ranking
    passes, the destination-slab scatters) collapses to a single
    launch per window.  The destination scatter is cross-host, so the
    exchange cannot block on hosts: every grid step sees the full
    arrays, the work runs under `pl.when(step == 0)`, and the grid is
    2 rather than 1 because XLA's while-loop simplifier unrolls
    trip-count-1 loops -- which would dissolve the kernel region back
    into the surrounding graph (no single launch, and nothing for
    kernelcount to classify).  All-integer slab shuffling, so it is
    fusion-context stable (docs/megakernel.md, "f32 stability")."""
    flat_in, td_in = jax.tree_util.tree_flatten({"pool": pool, "inbox": ib})
    in_paths = {jax.tree_util.keystr(p): i for i, (p, _l) in
                enumerate(jax.tree_util.tree_flatten_with_path(
                    {"pool": pool, "inbox": ib})[0])}
    def _core(p, i):
        p2, i2, total, tprot, nfree = engine._exchange_core(
            p, i, h, params)
        return {"pool": p2, "inbox": i2, "total": total,
                "tprot": tprot, "nfree": nfree}

    out_av = jax.eval_shape(_core, pool, ib)
    out_paths, td_out = jax.tree_util.tree_flatten_with_path(out_av)
    flat_av = [a for _p, a in out_paths]
    aliases = {}
    for j, (p, a) in enumerate(out_paths):
        i = in_paths.get(jax.tree_util.keystr(p))
        if i is not None and flat_in[i].shape == a.shape \
                and flat_in[i].dtype == a.dtype:
            aliases[i] = j
    n_in = len(flat_in)

    def kernel(*refs):
        @pl.when(pl.program_id(0) == 0)
        def _work():
            vals = [r[...] for r in refs[:n_in]]
            d = jax.tree_util.tree_unflatten(td_in, vals)
            outs = _core(d["pool"], d["inbox"])
            for r, v in zip(refs[n_in:],
                            jax.tree_util.tree_leaves(outs)):
                r[...] = v

    full = [pl.BlockSpec(tuple(l.shape),
                         lambda i, _n=l.ndim: (0,) * _n)
            for l in flat_in]
    outs = [pl.BlockSpec(tuple(a.shape),
                         lambda i, _n=a.ndim: (0,) * _n)
            for a in flat_av]
    res = pl.pallas_call(
        kernel, grid=(2,), in_specs=full, out_specs=outs,
        out_shape=[jax.ShapeDtypeStruct(a.shape, a.dtype)
                   for a in flat_av],
        input_output_aliases=aliases, interpret=_interpret(),
    )(*flat_in)
    res = list(res) if isinstance(res, (list, tuple)) else [res]
    out = jax.tree_util.tree_unflatten(td_out, res)
    return (out["pool"], out["inbox"], out["total"], out["tprot"],
            out["nfree"])


# ---------------------------------------------------------------------------
# Persistent window kernel (K_WINDOW)
# ---------------------------------------------------------------------------


def _call_full(core, inputs):
    """Run `core(inputs_pytree) -> outputs_pytree` as ONE full-array,
    single-region pallas call: the exchange_call pattern generalized to
    arbitrary pytrees.  Every grid step sees the full arrays, the work
    runs under `pl.when(step == 0)`, and the grid is 2 rather than 1
    because XLA's while-loop simplifier unrolls trip-count-1 loops --
    which would dissolve the kernel region back into the surrounding
    graph.

    0-d leaves are boxed to (1,) across the pallas boundary and
    zero-size leaves are dropped on the way in / rebuilt as constants on
    the way out (an empty array carries no data), both transparently.
    Output leaves whose pytree path matches an input leaf of the same
    shape/dtype alias that input's buffer (state slabs updated in
    place), eliding the defensive copy per crossing leaf."""
    paths_in, td_in = jax.tree_util.tree_flatten_with_path(inputs)
    flat_in = [l for _p, l in paths_in]
    in_meta = [(l.ndim == 0, l.size == 0, tuple(l.shape), l.dtype)
               for l in flat_in]
    pass_in = []
    pass_idx = {}              # original leaf index -> passed operand idx
    for i, l in enumerate(flat_in):
        if l.size == 0:
            continue
        pass_idx[i] = len(pass_in)
        pass_in.append(l.reshape(1) if l.ndim == 0 else l)

    out_av = jax.eval_shape(core, inputs)
    out_paths, td_out = jax.tree_util.tree_flatten_with_path(out_av)
    out_meta = [(a.ndim == 0, a.size == 0, tuple(a.shape), a.dtype)
                for _p, a in out_paths]

    in_by_path = {jax.tree_util.keystr(p): i
                  for i, (p, _l) in enumerate(paths_in)}
    out_shapes = []
    aliases = {}
    for (p, _a), (boxed, empty_leaf, shape, dtype) in zip(out_paths,
                                                          out_meta):
        if empty_leaf:
            continue
        j = len(out_shapes)
        out_shapes.append(jax.ShapeDtypeStruct((1,) if boxed else shape,
                                               dtype))
        i = in_by_path.get(jax.tree_util.keystr(p))
        if i is not None and i in pass_idx \
                and in_meta[i][2] == shape and in_meta[i][3] == dtype:
            aliases[pass_idx[i]] = j

    n_in = len(pass_in)

    def kernel(*refs):
        @pl.when(pl.program_id(0) == 0)
        def _work():
            it = iter(refs[:n_in])
            vals = []
            for boxed, empty_leaf, shape, dtype in in_meta:
                if empty_leaf:
                    vals.append(jnp.zeros(shape, dtype))
                else:
                    v = next(it)[...]
                    vals.append(v.reshape(()) if boxed else v)
            tree = jax.tree_util.tree_unflatten(td_in, vals)
            outs = core(tree)
            ro = iter(refs[n_in:])
            for v, (boxed, empty_leaf, _s, _d) in zip(
                    jax.tree_util.tree_leaves(outs), out_meta):
                if empty_leaf:
                    continue
                r = next(ro)
                r[...] = jnp.asarray(v)[None] if boxed else v

    in_specs = [pl.BlockSpec(tuple(l.shape),
                             lambda i, _n=l.ndim: (0,) * _n)
                for l in pass_in]
    out_specs = [pl.BlockSpec(tuple(s.shape),
                              lambda i, _n=len(s.shape): (0,) * _n)
                 for s in out_shapes]
    res = pl.pallas_call(
        kernel, grid=(2,), in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shapes, input_output_aliases=aliases,
        interpret=_interpret(),
    )(*pass_in)
    res = list(res) if isinstance(res, (list, tuple)) else [res]
    it = iter(res)
    leaves = []
    for boxed, empty_leaf, shape, dtype in out_meta:
        if empty_leaf:
            leaves.append(jnp.zeros(shape, dtype))
        else:
            v = next(it)
            leaves.append(v.reshape(()) if boxed else v)
    return jax.tree_util.tree_unflatten(td_out, leaves)


def window_fused(state: SimState, params, app, t_target):
    """One whole conservative window as ONE pallas region (K_WINDOW):
    the boundary exchange, the per-window scan, the window bounds, the
    netem advance, and the micro-step while loop with its gmin
    loop-continue predicate all run inside a single kernel invocation,
    so a window costs O(1) launches instead of O(steps x phases).

    The body is `engine._window_body_ref` -- reference implementations
    only (a pallas region cannot nest another pallas_call), with the
    whole params pytree and t_target riding in as kernel operands
    (closure-captured tracers are illegal in a kernel body).  The f32
    contract that makes this bitwise-admissible is the in-kernel one
    documented in docs/megakernel.md ("Persistent window kernel"):
    every op inside is integer, exactly-rounded f32, or an f64
    transcendental that lowers to a context-independent libm call
    (phold's delay draw moved to f64 log1p in the ensemble round for
    exactly this property).

    Returns (state, t_h, gmin, ws, we); the caller runs the
    window-close instrumentation hooks on ws/we outside the region."""
    t_target = jnp.asarray(t_target, I64)

    def _core(d):
        st, t_h, gmin, ws, we = engine._window_body_ref(
            d["st"], d["par"], app, d["tt"])
        return {"st": st, "t_h": t_h, "gmin": gmin, "ws": ws, "we": we}

    out = _call_full(_core, {"st": state, "par": params, "tt": t_target})
    return out["st"], out["t_h"], out["gmin"], out["ws"], out["we"]


# ---------------------------------------------------------------------------
# Fused micro-step
# ---------------------------------------------------------------------------


def _hoff_blk(base, i, hb):
    """Global host id of a block's row 0: the shard offset (if any) plus
    the block offset.  Installing it as the block state's hoff makes
    host_ids()/_lrows()/_loopback_insert address globally/locally
    exactly as the mesh path already does."""
    off = jnp.asarray(i, I32) * jnp.asarray(hb, I32)
    if base is not None:
        off = off + base.astype(I32)
    return off


def _rebuild_params(params, local, rep):
    """Blocked NetParams: every pytree leaf replaced from kernel inputs
    (closure-captured leaves would be baked into the kernel as
    constants), statics carried over from the traced params object."""
    return params.replace(**local, **rep)


def _or_all(x):
    return jax.lax.reduce(x, jnp.zeros((), x.dtype),
                          jax.lax.bitwise_or, (0,))


def microstep_fused(state: SimState, params, app, t_h, window_end,
                    ctx=None):
    """One micro-step through the fused kernels.  Returns
    (state, t_h_next, gmin_next): the post-step per-host scan rides out
    of K_TRANSPORT, so callers need no separate _scan_all.

    Bitwise-identical to `engine._microstep_core` followed by
    `engine._scan_all` -- the kernel bodies call those same reference
    implementations on blocked rows (see module docstring)."""
    from ..transport import tcp as tcp_mod

    if ctx is None:
        ctx = engine._window_ctx(state, params)
    bw_up, bw_dn, alive = ctx

    h = state.hosts.num_hosts
    g = _grid(h)
    hb = h // g
    uses_tcp = engine._uses_tcp(app)
    if uses_tcp and state.inbox.blk.shape[1] < ICOLS:
        raise ValueError(
            "this world's inbox was built narrow (uses_tcp=False in "
            "make_sim_state) but the app uses TCP; TCP segments need the "
            "TS/SACK inbox columns")

    window_end = jnp.asarray(window_end, I64)
    active = t_h < window_end
    tick_t = jnp.where(active, t_h, window_end)
    state = state.replace(
        hosts=state.hosts.replace(t_resume=jnp.where(
            active, jnp.asarray(INV, I64), state.hosts.t_resume)))

    d_rounds = max(1, int(getattr(app, "rx_batch", 1)))
    # rx_batch bound, evaluated at batch start exactly where the
    # reference evaluates it (post re-arm, pre any rx mutation); the
    # kernel does not carry app state, so it rides in per-host.
    aux0 = engine._aux_times(state, params, app) if d_rounds > 1 else None

    if uses_tcp:
        n_lanes = emit.NUM_SLOTS + max(0, d_rounds - 1)
    else:
        n_lanes = emit.SLOT_APP + max(1, int(getattr(app, "app_tx_lanes",
                                                     1)))
    cols = state.pool.blk.shape[1]
    nm = state.nm
    base = state.hoff

    p_local = {k: getattr(params, k) for k in _PARAMS_LOCAL}
    p_rep = {k: getattr(params, k) for k in _PARAMS_REP}
    if params.hosts_real is not None:
        p_rep["hosts_real"] = params.hosts_real

    # ---- K_DELIVER: the whole _rx_phase on a block of hosts -----------
    shard_in = dict(hosts=state.hosts, inbox=state.inbox,
                    socks=state.socks, tick_t=tick_t, active=active,
                    bw_dn=bw_dn, p_local=p_local)
    if alive is not None:
        shard_in["alive"] = alive
    if aux0 is not None:
        shard_in["aux0"] = aux0
    full_in = dict(p_rep=p_rep, we=window_end)
    if nm is not None:
        full_in["nm"] = nm
    if base is not None:
        full_in["hoff"] = base

    def k_deliver(s, f, i):
        par = _rebuild_params(params, s["p_local"], f["p_rep"])
        nm_blk = None
        if nm is not None:
            nm_blk = f["nm"].replace(
                killed=jnp.zeros_like(f["nm"].killed))
        st = SimState(
            now=None, pool=None, inbox=s["inbox"], socks=s["socks"],
            hosts=s["hosts"], err=jnp.zeros((), I32), nm=nm_blk,
            hoff=_hoff_blk(f.get("hoff"), i, hb))
        em = emit.empty(hb, n_lanes, cols=cols)
        st, em, delivered_n, t_post = engine._rx_phase(
            st, par, em, s["tick_t"], s["active"], app, f["we"],
            bw_dn=s["bw_dn"], alive=s.get("alive"),
            aux_bound=s.get("aux0"))
        out = dict(hosts=st.hosts, inbox=st.inbox, socks=st.socks,
                   em=em, delivered_n=delivered_n, t_post=t_post)
        acc = dict(err=st.err)
        if nm is not None:
            acc["killed"] = st.nm.killed
        return out, acc

    o, a = _call_blocked(k_deliver, g, shard_in, full_in)
    state = state.replace(hosts=o["hosts"], inbox=o["inbox"],
                          socks=o["socks"],
                          err=state.err | _or_all(a["err"]))
    if nm is not None:
        state = state.replace(nm=state.nm.replace(
            killed=state.nm.killed + jnp.sum(a["killed"])))
    em, delivered_n, t_post = o["em"], o["delivered_n"], o["t_post"]

    # ---- between kernels: timers + app tick (main-graph f32 context) --
    if uses_tcp:
        state, em = tcp_mod.run_timers(state, params, em, t_post, active)
    t_app = None
    if app is not None:
        if getattr(app, "wants_window_end", False):
            state, em = app.on_tick(state, params, em, t_post, active,
                                    window_end=window_end)
        else:
            state, em = app.on_tick(state, params, em, t_post, active)
        # Post-step app wake times: transport never touches app state,
        # so the scan term is exact when computed here and carried in.
        t_app = jnp.broadcast_to(
            jnp.asarray(app.next_time(state), I64), (h,))

    # ---- K_TRANSPORT: transmit -> stage -> drain -> accounting -> scan
    shard_in2 = dict(hosts=state.hosts, pool=state.pool,
                     inbox=state.inbox, socks=state.socks, em=em,
                     tick_t=tick_t, active=active, t_post=t_post,
                     bw_up=bw_up, delivered_n=delivered_n,
                     p_local=p_local)
    if t_app is not None:
        shard_in2["t_app"] = t_app
    full_in2 = dict(p_rep=p_rep)
    if nm is not None:
        full_in2["nm"] = nm
    if base is not None:
        full_in2["hoff"] = base

    def k_transport(s, f, i):
        par = _rebuild_params(params, s["p_local"], f["p_rep"])
        nm_blk = None
        if nm is not None:
            nm_blk = f["nm"].replace(
                killed=jnp.zeros_like(f["nm"].killed))
        st = SimState(
            now=None, pool=s["pool"], inbox=s["inbox"],
            socks=s["socks"], hosts=s["hosts"],
            err=jnp.zeros((), I32), nm=nm_blk,
            hoff=_hoff_blk(f.get("hoff"), i, hb))
        em_b, t_post_b, active_b = s["em"], s["t_post"], s["active"]
        if uses_tcp:
            st, em_b = tcp_mod.transmit(st, par, em_b, t_post_b,
                                        active_b)
        st, _placed = engine._stage_emissions(st, par, em_b, t_post_b,
                                              active_b, app,
                                              bw_up=s["bw_up"])
        # Parked-TX drain.  skip_refill: staging just refilled this
        # bucket at the same instant, so the reference's second refill
        # accrues exactly 0 tokens.  Without it the diet gate's
        # refill-only branch is the identity, so the gate collapses to
        # cond(any-parked, drain-body, identity).
        if params.kernel_diet:
            st = jax.lax.cond(
                jnp.any(st.pool.stage == STAGE_TX_QUEUED),
                lambda x: engine._tx_drain_body(
                    x, par, t_post_b, active_b, s["bw_up"],
                    skip_refill=True),
                lambda x: x, st)
        else:
            st = engine._tx_drain_body(st, par, t_post_b, active_b,
                                       s["bw_up"], skip_refill=True)

        # Virtual-CPU accounting (engine._microstep_core tail).
        cpu_on = par.cpu_ns_per_event > 0
        events = s["delivered_n"].astype(I64) + \
            jnp.sum(em_b.valid, axis=1).astype(I64)
        cost = par.cpu_ns_per_event * events
        avail = jnp.maximum(st.hosts.cpu_avail, s["tick_t"])
        new_avail = jnp.where(cpu_on & active_b, avail + cost,
                              st.hosts.cpu_avail)
        st = st.replace(hosts=st.hosts.replace(cpu_avail=new_avail))

        # Post-step per-host scan (engine._scan_all on the block; the
        # app term was computed outside and rides in).
        ib = st.inbox
        ki = ib.capacity // hb
        t2 = ib.times().reshape(hb, ki)
        drive = (ib.stage == STAGE_IN_FLIGHT).reshape(hb, ki)
        t_in = jnp.min(jnp.where(drive, t2, jnp.asarray(INV, I64)),
                       axis=1)
        aux = st.hosts.t_resume
        if uses_tcp:
            t_tmr = jnp.minimum(
                jnp.minimum(jnp.min(st.socks.t_rto, axis=1),
                            jnp.min(st.socks.t_persist, axis=1)),
                jnp.minimum(jnp.min(st.socks.t_delack, axis=1),
                            jnp.min(st.socks.t_tw, axis=1)),
            )
            aux = jnp.minimum(aux, t_tmr)
        if "t_app" in s:
            aux = jnp.minimum(aux, s["t_app"])
        th = engine._cpu_clamp(st, par, jnp.minimum(t_in, aux))

        out = dict(hosts=st.hosts, pool=st.pool, inbox=st.inbox,
                   socks=st.socks, th=th)
        acc = dict(err=st.err, ev=jnp.sum(events))
        if nm is not None:
            acc["killed"] = st.nm.killed
        return out, acc

    o2, a2 = _call_blocked(k_transport, g, shard_in2, full_in2)
    state = state.replace(
        hosts=o2["hosts"], pool=o2["pool"], inbox=o2["inbox"],
        socks=o2["socks"], err=state.err | _or_all(a2["err"]),
        n_steps=state.n_steps + 1,
        n_events=state.n_events + jnp.sum(a2["ev"]))
    if nm is not None:
        state = state.replace(nm=state.nm.replace(
            killed=state.nm.killed + jnp.sum(a2["killed"])))
    th = o2["th"]
    return state, th, jnp.min(th)
