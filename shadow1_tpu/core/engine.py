"""The windowed discrete-event engine: conservative PDES as compiled loops.

Semantics preserved from the reference:

* Conservative time windows with min-latency lookahead: all hosts process
  events in [window_start, window_end), then the window advances by the
  topology's minimum cross-host latency ("min time jump",
  /root/reference/src/main/core/master.c:133-159,450-480).  A packet sent
  at t >= window_start arrives at t + latency >= window_end, so hosts are
  independent within a window -- the property the reference enforces with
  per-host queues + barriers (scheduler.c:359-414) and that we exploit to
  advance every host in one vectorized step.

* Deterministic per-host event order: within a host, events execute in
  (time, category, packet-id) order, reproducing the role of the
  reference's total order (time, dstHostID, srcHostID, srcHostEventID)
  (core/work/event.c:110-153).  Between hosts no order is needed --
  windows make them independent -- so the result is bitwise identical for
  any device mesh.

Structure: `run_until` runs an outer while_loop over windows; each window
runs an inner while_loop of *micro-steps*.  One micro-step advances every
host's earliest pending work simultaneously:

  phase A  packet arrivals -> transport/socket processing (1/host/tick)
  phase B  socket timer expirations (RTO, delayed ACK, TIME_WAIT)
  phase C  application model tick (consume delivered data, timed sends)
  phase D  TCP transmit + flush staged emissions into the packet pool

The per-phase work is bounded per tick (one arrival per host, a few
emission slots), so each micro-step is a fixed-shape dataflow graph; hosts
with nothing due are masked off.  "Find the next event" is a segment-min
over the packet pool plus element-wise mins over timer tables -- the
replacement for the reference's binary-heap pops (scheduler_pop,
core/scheduler/scheduler.c:359).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import emit, nic, rng, simtime
# Reliability-dropped packets are never materialized in the pool (they are
# counted in HostTable.pkts_dropped_inet instead), so PDS_INET_DROPPED is
# deliberately absent here.
from .state import (ERR_POOL_OVERFLOW, I32, I64, PROTO_TCP, PROTO_UDP,
                    STAGE_FREE, STAGE_IN_FLIGHT, STAGE_RX_QUEUED,
                    STAGE_TX_QUEUED, TCP_HEADER_SIZE, UDP_HEADER_SIZE,
                    PDS_INET_SENT, PDS_RCV_SOCKET_PROCESSED,
                    PDS_ROUTER_DROPPED, PDS_ROUTER_ENQUEUED,
                    PDS_SND_CREATED, PDS_SND_INTERFACE_SENT, SimState)

INV = simtime.SIMTIME_INVALID


def _seg_min(values, seg, num, mask):
    big = jnp.asarray(INV, values.dtype)
    data = jnp.where(mask, values, big)
    return jax.ops.segment_min(data, seg, num_segments=num)


# ---------------------------------------------------------------------------
# Next-event scan (replaces priority-queue peeks)
# ---------------------------------------------------------------------------


def _uses_tcp(app) -> bool:
    """Static app capability: apps that never open TCP sockets (pure-UDP
    phold) let the whole TCP machine trace away from the compiled step."""
    return getattr(app, "uses_tcp", True)


def _slot_bits(p: int) -> int:
    """Bits needed to pack a pool slot index into the low end of a key."""
    return max(1, (p - 1).bit_length())


def rx_scan(state: SimState):
    """ONE segment-min over the pool giving, per destination host, the
    earliest inbound packet (IN_FLIGHT or RX_QUEUED) and its pool slot.

    This single reduction serves both roles the engine needs each
    micro-step -- "when is each host's next arrival" (the next-event scan)
    and "which packet does the NIC drain next" (the rx selection) -- so
    the expensive dst-keyed scatter-min runs once per micro-step instead
    of three times.  The key packs (absolute time << slot_bits) | slot;
    ties at equal time break by pool slot, which is mesh-invariant and
    deterministic (slab slots are allocated in deterministic per-source
    order).

    Returns (t_arr [H] i64 arrival time or INV, rx_slot [H] i32 or -1).
    """
    pool, hosts = state.pool, state.hosts
    h = hosts.num_hosts
    p = pool.capacity
    bits = _slot_bits(p)
    # time << bits must fit below the INV sentinel: sim time is bounded by
    # 2^(62-bits) ns (19 hours at the default 64k pool).
    live = (pool.stage == STAGE_IN_FLIGHT) | (pool.stage == STAGE_RX_QUEUED)
    key = (pool.time << bits) | jnp.arange(p, dtype=I64)
    kmin = _seg_min(key, pool.dst, h, live)
    have = kmin != jnp.asarray(INV, I64)
    t_arr = jnp.where(have, kmin >> bits, jnp.asarray(INV, I64))
    rx_slot = jnp.where(have, (kmin & ((1 << bits) - 1)).astype(I32), -1)
    # Only future (IN_FLIGHT) candidates drive the time scan: a backlogged
    # RX_QUEUED head's arrival is in the past, and re-processing it is
    # owned by the t_resume wake machinery (armed whenever backlog
    # remains), so letting it set t_h would freeze virtual time.
    stage_at = pool.stage[jnp.clip(rx_slot, 0, p - 1)]
    t_drive = jnp.where(have & (stage_at == STAGE_IN_FLIGHT), t_arr,
                        jnp.asarray(INV, I64))
    return t_drive, rx_slot


def _aux_times(state: SimState, params, app):
    """Per-host earliest non-packet event: timers, app, re-ticks."""
    socks, hosts = state.socks, state.hosts
    t_h = hosts.t_resume
    if _uses_tcp(app):
        t_tmr = jnp.minimum(
            jnp.minimum(jnp.min(socks.t_rto, axis=1),
                        jnp.min(socks.t_persist, axis=1)),
            jnp.minimum(jnp.min(socks.t_delack, axis=1),
                        jnp.min(socks.t_tw, axis=1)),
        )
        t_h = jnp.minimum(t_h, t_tmr)
    if app is not None:
        t_h = jnp.minimum(t_h, app.next_time(state))
    return t_h


def _cpu_clamp(state: SimState, params, t_h):
    """Virtual CPU gate (reference cpu_isBlocked + event deferral,
    cpu.c:56-75, event.c:71-84): a host whose accumulated CPU backlog
    exceeds the threshold cannot tick before the backlog drains back to
    it, so its events execute late by exactly the built-up delay.

    Like the reference's --cpu-threshold (options.c: default -1 =
    disabled), a negative threshold turns blocking off entirely; wake
    times are rounded to cpu_precision_ns."""
    prec = jnp.maximum(params.cpu_precision_ns, 1)
    ready = state.hosts.cpu_avail - params.cpu_threshold_ns
    rem = ready % prec
    ready = ready - rem + jnp.where(rem >= prec // 2, prec, 0)
    clamp = (params.cpu_ns_per_event > 0) & (t_h != INV) & \
        (params.cpu_threshold_ns >= 0)
    return jnp.where(clamp, jnp.maximum(t_h, ready), t_h)


def _scan_all(state: SimState, params, app):
    """The combined per-micro-step scan: per-host next event time, its
    global min, and the rx candidate slot.  Single source of truth for
    both the jitted loop and the public next_times."""
    t_arr, rx_slot = rx_scan(state)
    t_h = jnp.minimum(t_arr, _aux_times(state, params, app))
    t_h = _cpu_clamp(state, params, t_h)
    return t_h, jnp.min(t_h), rx_slot


def next_times(state: SimState, params, app):
    """Per-host earliest pending event time [H] and its global min."""
    t_h, gmin, _ = _scan_all(state, params, app)
    return t_h, gmin


# ---------------------------------------------------------------------------
# Phase A: router enqueue -> NIC receive (token bucket + CoDel) -> delivery
# ---------------------------------------------------------------------------


def _wire_bytes(proto, length):
    """On-the-wire size charged against token buckets (payload + header;
    reference packet_getTotalSize with CONFIG_HEADER_SIZE_*)."""
    return length + jnp.where(proto == PROTO_TCP, TCP_HEADER_SIZE,
                              UDP_HEADER_SIZE)


def _packet_latency(params, vs, vd, src, ctr):
    """Path latency with the per-packet jitter draw: uniform in
    +/- jitter_ns, keyed by (src, per-src counter) so the same packet
    draws the same perturbation wherever its departure is computed
    (reference carries per-edge jitter, topology.c:81-105)."""
    lat = params.latency_ns[vs, vd]
    jit = params.jitter_ns[vs, vd]
    key = rng.purpose_key(params.seed_key, rng.PURPOSE_JITTER)
    u = rng.keyed_uniform(key, src, ctr.astype(jnp.uint32),
                          (ctr >> 32).astype(jnp.uint32))
    delta = ((2.0 * u - 1.0) * jit.astype(jnp.float32)).astype(I64)
    return jnp.maximum(lat + jnp.where(jit > 0, delta, 0),
                       simtime.SIMTIME_ONE_NANOSECOND)


def _select_tx_slab(pool, tick_t, active, h):
    """Pick per SOURCE host the earliest due TX_QUEUED packet.

    Packets live in their source's pool slab (slot // K == src), so this
    is a reshape-min over [H, K] -- no dst-keyed scatter at all.  Ties at
    equal time break by within-slab index (deterministic allocation
    order).  Returns ([H] pool index or -1, [P] chosen mask).
    """
    p = pool.capacity
    k = p // h
    kb = _slot_bits(k)
    stage2 = pool.stage.reshape(h, k)
    time2 = pool.time.reshape(h, k)
    due = (stage2 == STAGE_TX_QUEUED) & (time2 <= tick_t[:, None]) & \
        active[:, None]
    key = jnp.where(due, (time2 << kb) | jnp.arange(k, dtype=I64)[None, :],
                    jnp.asarray(INV, I64))
    kmin = jnp.min(key, axis=1)
    have = kmin != jnp.asarray(INV, I64)
    j = (kmin & ((1 << kb) - 1)).astype(I32)
    slot_of_host = jnp.where(have, jnp.arange(h, dtype=I32) * k + j, -1)
    chosen = ((jnp.arange(k, dtype=I32)[None, :] == j[:, None]) &
              have[:, None]).reshape(-1)
    return slot_of_host, chosen


def _router_enqueue(state: SimState, tick_t, active):
    """Move due in-flight packets into the destination's upstream-router
    queue (reference _worker_runDeliverPacketTask -> router_enqueue,
    worker.c:236-241, router.c:104-123).  Purely a stage tag flip; `time`
    keeps the wire-arrival instant so CoDel can compute sojourn."""
    pool, hosts = state.pool, state.hosts
    h = hosts.num_hosts
    due = (pool.stage == STAGE_IN_FLIGHT) & (pool.time <= tick_t[pool.dst]) \
        & active[pool.dst]
    pool = pool.replace(
        stage=jnp.where(due, STAGE_RX_QUEUED, pool.stage),
        status=jnp.where(due, pool.status | PDS_ROUTER_ENQUEUED, pool.status),
    )
    counts = jax.ops.segment_sum(jnp.where(due, 1, 0), pool.dst,
                                 num_segments=h)
    hosts = hosts.replace(rx_queued=hosts.rx_queued + counts.astype(I32))
    return state.replace(pool=pool, hosts=hosts)


def _rx_drain(state: SimState, params, tick_t, active, rx_slot):
    """NIC receive: drain one packet per host from the router queue,
    gated by the downstream token bucket and the CoDel drop law
    (reference networkinterface_receivePackets, network_interface.c:421-455
    + router_queue_codel.c).  `rx_slot` is the per-dst earliest inbound
    packet from the previous micro-step's rx_scan (every packet staged
    since then arrives beyond the conservative window, so the candidate
    set cannot have changed).  Returns (state, slot_of_host,
    chosen_deliver) for the transport layer."""
    pool, hosts = state.pool, state.hosts
    h = hosts.num_hosts

    slot = jnp.clip(rx_slot, 0, pool.capacity - 1)
    have = (rx_slot >= 0) & active & (pool.time[slot] <= tick_t)
    slot_of_host = jnp.where(have, rx_slot, -1)
    # <=1 chosen per pool slot (a slot's dst is fixed) and only True is
    # ever written (non-candidates target the dropped sentinel index), so
    # the scatter is collision-free; update count is H, not P.
    chosen = jnp.zeros((pool.capacity,), bool).at[
        jnp.where(have, slot, pool.capacity)].set(True, mode="drop")

    tokens, last = nic.refill(hosts.tokens_rx, hosts.last_refill_rx,
                              params.bw_down_Bps, tick_t, active)
    size = _wire_bytes(pool.proto[slot], pool.length[slot]).astype(I64) \
        * nic.SCALE
    loop = pool.src[slot] == pool.dst[slot]
    boot = tick_t < params.bootstrap_end
    free_pass = loop | boot
    funded = have & (free_pass | (tokens >= size))

    # CoDel decision for funded, non-loopback dequeues.
    sojourn = tick_t - pool.time[slot]
    backlog_after = hosts.rx_queued - 1
    hosts, drop = nic.codel_dequeue(hosts, funded & ~loop, tick_t, sojourn,
                                    backlog_after)
    deliver = funded & ~drop

    # Charge the bucket for everything dequeued (delivered or dropped).
    tokens = tokens - jnp.where(funded & ~free_pass, size, 0)
    hosts = hosts.replace(tokens_rx=tokens, last_refill_rx=last)

    # Dropped packets leave the pool.
    chosen_drop = chosen & drop[pool.dst]
    pool = pool.replace(
        stage=jnp.where(chosen_drop, STAGE_FREE, pool.stage),
        status=jnp.where(chosen_drop, pool.status | PDS_ROUTER_DROPPED,
                         pool.status),
    )
    hosts = hosts.replace(
        rx_queued=hosts.rx_queued - jnp.where(funded, 1, 0).astype(I32),
        pkts_dropped_router=hosts.pkts_dropped_router +
        jnp.where(drop, 1, 0),
    )

    # Wake-ups: backlog remains -> re-tick now; starved -> when tokens
    # accrue for this packet.
    t_tok = tick_t + nic.time_until(size - tokens, params.bw_down_Bps)
    t_res = jnp.where(
        have & ~funded, t_tok,
        jnp.where(funded & (hosts.rx_queued > 0), tick_t,
                  jnp.asarray(INV, I64)))
    hosts = hosts.replace(t_resume=jnp.minimum(hosts.t_resume, t_res))

    state = state.replace(pool=pool, hosts=hosts)
    slot_deliver = jnp.where(deliver, slot_of_host, -1)
    return state, slot_deliver, chosen & deliver[pool.dst]


def _deliver(state: SimState, params, em, tick_t, pool_slot, chosen, app):
    """Deliver the selected packets to their sockets (UDP now; TCP hooks in
    transport/tcp.py once present)."""
    from ..transport import tcp as tcp_mod
    from ..transport import udp as udp_mod

    pool = state.pool
    have = pool_slot >= 0
    slot = jnp.clip(pool_slot, 0, pool.capacity - 1)

    g = lambda a: a[slot]
    src, sport, dport = g(pool.src), g(pool.sport), g(pool.dport)
    proto, length, payload = g(pool.proto), g(pool.length), g(pool.payload_id)

    # UDP
    udp_mask = have & (proto == PROTO_UDP)
    socks, _accepted = udp_mod.deliver(state.socks, udp_mask, src, sport,
                                       dport, length, payload)
    state = state.replace(socks=socks)

    # TCP
    if _uses_tcp(app):
        tcp_mask = have & (proto == PROTO_TCP)
        state, em = tcp_mod.process_arrivals(state, params, em, tick_t, slot,
                                             tcp_mask)

    # Consume delivered packets & account (elementwise via the [P] mask --
    # no duplicate-index scatters).
    pool = pool.replace(
        stage=jnp.where(chosen, STAGE_FREE, pool.stage),
        status=jnp.where(chosen, pool.status | PDS_RCV_SOCKET_PROCESSED,
                         pool.status),
    )
    hosts = state.hosts
    hosts = hosts.replace(
        pkts_recv=hosts.pkts_recv + jnp.where(have, 1, 0),
        bytes_recv=hosts.bytes_recv + jnp.where(have, length, 0),
    )
    return state.replace(pool=pool, hosts=hosts), em


# ---------------------------------------------------------------------------
# Emission staging (packets leave their source this tick)
# ---------------------------------------------------------------------------


def _stage_emissions(state: SimState, params, em: emit.Emissions, tick_t,
                     active):
    """Assign pkt_ids, apply routing latency + reliability drops, and
    scatter staged emissions into free pool slots -- direct to IN_FLIGHT
    when the tx token bucket covers them, else parked in TX_QUEUED.

    The reference equivalent is the interface send path + worker_sendPacket
    (/root/reference/src/main/host/network_interface.c:466-540,
    src/main/core/worker.c:243-304): qdisc select under token budget,
    reliability draw, latency lookup, push event to the destination host
    queue.  Loopback bypasses the NIC with a 1ns delay like the
    reference's local path (network_interface.c:548-555); the bootstrap
    period bypasses bandwidth (network_interface.c:432-434,522).
    """
    pool, hosts = state.pool, state.hosts
    h, e = em.valid.shape
    p = pool.capacity

    valid = em.valid
    rank = jnp.cumsum(valid, axis=1) - 1              # [H,E] within-host order
    counts = jnp.sum(valid, axis=1).astype(I64)       # [H]
    ctr = hosts.send_ctr                               # [H]

    src2 = jnp.broadcast_to(jnp.arange(h, dtype=I32)[:, None], (h, e))
    ctr2 = ctr[:, None] + rank
    pkt_id2 = (src2.astype(I64) << 40) | ctr2

    # Routing: latency (+ per-packet jitter) + reliability, loopback
    # shortcut.
    vs = params.host_vertex[src2]
    vd = params.host_vertex[jnp.clip(em.dst, 0, params.host_vertex.shape[0] - 1)]
    lat = _packet_latency(params, vs, vd, src2, ctr2)
    rel = params.reliability[vs, vd]
    loop = em.dst == src2
    lat = jnp.where(loop, simtime.SIMTIME_ONE_NANOSECOND, lat)
    rel = jnp.where(loop, 1.0, rel)

    drop_key = rng.purpose_key(params.seed_key, rng.PURPOSE_PACKET_DROP)
    u = rng.keyed_uniform(drop_key, src2, ctr2.astype(jnp.uint32),
                          (ctr2 >> 32).astype(jnp.uint32))
    dropped = valid & (u >= rel)
    live = valid & ~dropped

    # Allocate free pool slots to live emissions from the emitting host's
    # own slab: the pool is partitioned into H contiguous slabs of K slots
    # (see make_sim_state), so allocation is a per-slab scan of K elements
    # -- no full-pool nonzero/cumsum per micro-step (which blew the TPU
    # scoped-VMEM budget as a [P]-length u32 reduce-window at P=64k) and
    # no cross-host allocation order to keep deterministic.
    k = p // h
    assert p == h * k, "pool capacity must be num_hosts * slab"
    free = (pool.stage == STAGE_FREE).reshape(h, k)
    # Sort keys put free slots first in ascending index order, so entry r
    # of `order` is the r-th free slot of the slab.
    slab_ids = jnp.arange(k, dtype=I32)[None, :]
    order = jnp.argsort(jnp.where(free, slab_ids, slab_ids + k), axis=1)
    n_free = jnp.sum(free, axis=1)                     # [H]
    live_rank = jnp.cumsum(live, axis=1) - 1           # [H,E] 0-based
    within = jnp.take_along_axis(order, jnp.clip(live_rank, 0, k - 1),
                                 axis=1)               # [H,E]
    have_slot = live & (live_rank < n_free[:, None])
    # Sentinel for "no slot" is `p`, NOT -1: negative scatter indices wrap
    # in XLA even under mode='drop'; only >= size is dropped.
    slot = jnp.where(have_slot,
                     jnp.arange(h, dtype=I32)[:, None] * k + within,
                     p).reshape(-1)
    overflow = jnp.any(live & ~have_slot)

    send_t = jnp.broadcast_to(tick_t[:, None], (h, e)).reshape(-1)
    arr_t = send_t + lat.reshape(-1)

    # Only emissions that actually got a pool slot exist from here on:
    # slab-exhausted ones are counted drops (pkts_dropped_pool below) and
    # must not charge tokens, park, or count as sent.
    placed = live & have_slot

    # --- NIC tx admission: direct-admit under the token budget, else park
    # in TX_QUEUED for _tx_drain (FIFO is preserved because any backlog
    # forces parking).
    tokens, last = nic.refill(hosts.tokens_tx, hosts.last_refill_tx,
                              params.bw_up_Bps, tick_t, active)
    sizes = _wire_bytes(em.proto, em.length).astype(I64) * nic.SCALE
    nonloop = placed & ~loop
    sizes_nl = jnp.where(nonloop, sizes, 0)
    prefix = jnp.cumsum(sizes_nl, axis=1)
    boot2 = (tick_t < params.bootstrap_end)[:, None]
    ok_budget = (hosts.tx_queued == 0)[:, None] & (prefix <= tokens[:, None])
    admit = placed & (loop | boot2 | ok_budget)
    spent = jnp.sum(jnp.where(admit & ~loop & ~boot2, sizes, 0), axis=1)
    tokens = tokens - spent
    admitf = admit.reshape(-1)
    parked = placed & ~admit
    hosts = hosts.replace(
        tokens_tx=tokens, last_refill_tx=last,
        tx_queued=hosts.tx_queued +
        jnp.sum(parked, axis=1).astype(I32))

    stage_v = jnp.where(admitf, STAGE_IN_FLIGHT, STAGE_TX_QUEUED)
    time_v = jnp.where(admitf, arr_t, send_t)
    status_v = jnp.where(
        admitf,
        PDS_SND_CREATED | PDS_SND_INTERFACE_SENT | PDS_INET_SENT,
        PDS_SND_CREATED)

    def sc(a, val, dtype=None):
        v = val.reshape(-1) if hasattr(val, "reshape") else val
        if dtype is not None:
            v = v.astype(dtype)
        return a.at[slot].set(v, mode="drop")

    pool = pool.replace(
        stage=sc(pool.stage, stage_v),
        src=sc(pool.src, src2),
        dst=sc(pool.dst, em.dst),
        sport=sc(pool.sport, em.sport),
        dport=sc(pool.dport, em.dport),
        proto=sc(pool.proto, em.proto),
        flags=sc(pool.flags, em.flags),
        seq=sc(pool.seq, em.seq),
        ack=sc(pool.ack, em.ack),
        wnd=sc(pool.wnd, em.wnd),
        length=sc(pool.length, em.length),
        time=sc(pool.time, time_v),
        pkt_id=sc(pool.pkt_id, pkt_id2),
        ts=sc(pool.ts, send_t),
        ts_echo=sc(pool.ts_echo, em.ts_echo),
        payload_id=sc(pool.payload_id, em.payload_id),
        priority=sc(pool.priority, em.priority),
        status=sc(pool.status, status_v),
    )

    sent_bytes = jnp.sum(jnp.where(placed, em.length, 0), axis=1).astype(I64)
    hosts = hosts.replace(
        send_ctr=ctr + counts,
        pkts_sent=hosts.pkts_sent + jnp.sum(placed, axis=1),
        bytes_sent=hosts.bytes_sent + sent_bytes,
        pkts_dropped_inet=hosts.pkts_dropped_inet + jnp.sum(dropped, axis=1),
        pkts_dropped_pool=hosts.pkts_dropped_pool +
        jnp.sum(live & ~have_slot, axis=1),
    )
    err = state.err | jnp.where(overflow, ERR_POOL_OVERFLOW, 0).astype(jnp.int32)
    state = state.replace(pool=pool, hosts=hosts, err=err)

    # Packet capture (PCAP analog; only traced when a CaptureRing is
    # installed): record every placed emission at send time.
    if state.cap is not None:
        cap = state.cap
        c = cap.capacity
        placedf = placed.reshape(-1)
        rank = jnp.cumsum(placedf) - 1
        n_new = jnp.sum(placedf).astype(I64)
        pos = ((cap.total + rank) % c).astype(I32)
        # One batch larger than the ring would wrap onto itself and make
        # the surviving record per slot scatter-order-dependent; keep the
        # first `c` records of such a batch instead (deterministic) --
        # size the ring above H*NUM_SLOTS to never hit this.  `total` must
        # then also advance by what was *written*, not what was staged, or
        # the writer would treat never-written slots as valid records.
        idx = jnp.where(placedf & (rank < c), pos, c)  # c = dropped write
        n_new = jnp.minimum(n_new, c)

        def cw(a, val, dtype=None):
            v = val.reshape(-1) if hasattr(val, "reshape") else val
            if dtype is not None:
                v = v.astype(dtype)
            return a.at[idx].set(v, mode="drop")

        state = state.replace(cap=cap.replace(
            time=cw(cap.time, send_t),
            src=cw(cap.src, src2),
            dst=cw(cap.dst, em.dst),
            sport=cw(cap.sport, em.sport),
            dport=cw(cap.dport, em.dport),
            proto=cw(cap.proto, em.proto),
            flags=cw(cap.flags, em.flags),
            length=cw(cap.length, em.length),
            seq=cw(cap.seq, em.seq),
            ack=cw(cap.ack, em.ack),
            total=cap.total + n_new,
        ))
    return state


def _tx_drain(state: SimState, params, tick_t, active):
    """Drain one parked TX_QUEUED packet per host onto the wire, gated by
    the upstream token bucket (reference _networkinterface_sendPackets,
    network_interface.c:519-561: dequeue under token budget, then
    router_forward -> worker_sendPacket)."""
    pool, hosts = state.pool, state.hosts
    h = hosts.num_hosts

    slot_of_host, chosen = _select_tx_slab(pool, tick_t, active, h)
    have = slot_of_host >= 0
    slot = jnp.clip(slot_of_host, 0, pool.capacity - 1)

    tokens, last = nic.refill(hosts.tokens_tx, hosts.last_refill_tx,
                              params.bw_up_Bps, tick_t, active)
    size = _wire_bytes(pool.proto[slot], pool.length[slot]).astype(I64) \
        * nic.SCALE
    boot = tick_t < params.bootstrap_end
    funded = have & (boot | (tokens >= size))
    tokens = tokens - jnp.where(funded & ~boot, size, 0)

    # Departure: arrival = now + path latency (drop draw already happened
    # at staging, keyed by pkt_id, so loss is independent of queueing; the
    # jitter draw keys on the same (src, ctr) identity).
    nv = params.host_vertex.shape[0]
    vs = params.host_vertex[jnp.clip(pool.src[slot], 0, h - 1)]
    vd = params.host_vertex[jnp.clip(pool.dst[slot], 0, nv - 1)]
    pid = pool.pkt_id[slot]
    arr = tick_t + _packet_latency(params, vs, vd,
                                   (pid >> 40).astype(I32),
                                   pid & ((jnp.int64(1) << 40) - 1))
    chosen_dep = chosen & funded[pool.src]
    pool = pool.replace(
        stage=jnp.where(chosen_dep, STAGE_IN_FLIGHT, pool.stage),
        time=jnp.where(chosen_dep, arr[pool.src], pool.time),
        status=jnp.where(chosen_dep,
                         pool.status | PDS_SND_INTERFACE_SENT | PDS_INET_SENT,
                         pool.status),
    )

    hosts = hosts.replace(
        tokens_tx=tokens, last_refill_tx=last,
        tx_queued=hosts.tx_queued - jnp.where(funded, 1, 0).astype(I32))

    t_tok = tick_t + nic.time_until(size - tokens, params.bw_up_Bps)
    t_res = jnp.where(
        have & ~funded, t_tok,
        jnp.where(funded & (hosts.tx_queued > 0), tick_t,
                  jnp.asarray(INV, I64)))
    hosts = hosts.replace(t_resume=jnp.minimum(hosts.t_resume, t_res))
    return state.replace(pool=pool, hosts=hosts)


# ---------------------------------------------------------------------------
# Micro-step and loops
# ---------------------------------------------------------------------------


def _microstep_core(state: SimState, params, app, t_h, window_end, rx_slot):
    """Advance every host's earliest pending event (< window_end)."""
    from ..transport import tcp as tcp_mod

    h = state.hosts.num_hosts
    active = t_h < window_end
    tick_t = jnp.where(active, t_h, window_end)

    # Active hosts' resume flags are re-armed by this tick's phases;
    # inactive hosts keep theirs (token-accrual wake-ups must survive).
    state = state.replace(
        hosts=state.hosts.replace(t_resume=jnp.where(
            active, jnp.asarray(INV, I64), state.hosts.t_resume)))

    em = emit.empty(h)

    # Phase A: wire arrivals -> router queue -> NIC rx (tokens + CoDel)
    # -> transport delivery.
    state = _router_enqueue(state, tick_t, active)
    state, pool_slot, chosen = _rx_drain(state, params, tick_t, active,
                                         rx_slot)
    state, em = _deliver(state, params, em, tick_t, pool_slot, chosen, app)

    # Phase B: transport timers.
    if _uses_tcp(app):
        state, em = tcp_mod.run_timers(state, params, em, tick_t, active)

    # Phase C: application tick.
    if app is not None:
        state, em = app.on_tick(state, params, em, tick_t, active)

    # Phase D: TCP transmission, flush staged emissions through the NIC tx
    # bucket (direct-admit or park), then drain parked packets.
    if _uses_tcp(app):
        state, em = tcp_mod.transmit(state, params, em, tick_t, active)
    state = _stage_emissions(state, params, em, tick_t, active)
    state = _tx_drain(state, params, tick_t, active)

    # Virtual CPU accounting (reference cpu_updateTime + cpu_addDelay,
    # cpu.c:77-108): every delivered packet and staged emission costs
    # cpu_ns_per_event.  Costs accumulate exactly; precision rounding
    # happens where the backlog is consulted (_cpu_clamp), so per-step
    # increments smaller than the precision are never lost.
    cpu_on = params.cpu_ns_per_event > 0
    events = jnp.where(pool_slot >= 0, 1, 0).astype(I64) + \
        jnp.sum(em.valid, axis=1).astype(I64)
    cost = params.cpu_ns_per_event * events
    avail = jnp.maximum(state.hosts.cpu_avail, tick_t)
    new_avail = jnp.where(cpu_on & active, avail + cost,
                          state.hosts.cpu_avail)
    state = state.replace(hosts=state.hosts.replace(cpu_avail=new_avail))
    return state


def microstep(state: SimState, params, app, t_h, window_end):
    """One micro-step (compatibility wrapper computing its own rx scan;
    the jitted loop threads the scan through the carry instead)."""
    _, rx_slot = rx_scan(state)
    return _microstep_core(state, params, app, t_h, window_end, rx_slot)


@functools.partial(jax.jit, static_argnames=("app",))
def run_until(state: SimState, params, app, t_target):
    """Run windows until simulated time reaches t_target (jitted whole)."""
    t_target = jnp.asarray(t_target, I64)

    # (t_h, gmin, rx_slot) ride in the loop carry: the combined next-event
    # scan + rx selection -- the one expensive dst-keyed reduction in the
    # simulator -- runs exactly once per micro-step, at the end, where it
    # sees everything that step staged (all of which arrives beyond the
    # conservative window, so the carried selection stays valid).
    def scan_all(s):
        return _scan_all(s, params, app)

    def window_cond(carry):
        st, _t_h, gmin, _rx = carry
        return (st.now < t_target) & (gmin < t_target)

    def window_body(carry):
        st, t_h, gmin, rx = carry
        ws = jnp.maximum(st.now, gmin)
        we = jnp.minimum(ws + params.min_latency_ns, t_target)

        def icond(icarry):
            _s, _th, g, _rx = icarry
            return g < we

        def ibody(icarry):
            s, th, _, rxs = icarry
            s = _microstep_core(s, params, app, th, we, rxs)
            th2, g2, rxs2 = scan_all(s)
            return s, th2, g2, rxs2

        st, t_h, gmin, rx = jax.lax.while_loop(icond, ibody,
                                               (st, t_h, gmin, rx))
        return st.replace(now=we), t_h, gmin, rx

    c0 = scan_all(state)
    state, _, _, _ = jax.lax.while_loop(window_cond, window_body,
                                        (state, *c0))
    return state.replace(now=t_target)


# One device launch covers this much simulated time: short enough that no
# single launch trips device/tunnel watchdogs, long enough to amortize
# dispatch (the compiled executable is reused -- t_target is traced).
CHUNK_NS = 250 * simtime.SIMTIME_ONE_MILLISECOND


def run_chunked(state: SimState, params, app, t_target: int,
                chunk_ns: int = CHUNK_NS):
    """Host-side loop of bounded `run_until` launches up to t_target."""
    t = int(state.now)
    t_target = int(t_target)
    while t < t_target:
        t = min(t + chunk_ns, t_target)
        state = run_until(state, params, app, t)
    return state
