"""The windowed discrete-event engine: conservative PDES as compiled loops.

Semantics preserved from the reference:

* Conservative time windows with min-latency lookahead: all hosts process
  events in [window_start, window_end), then the window advances by the
  topology's minimum cross-host latency ("min time jump",
  /root/reference/src/main/core/master.c:133-159,450-480).  A packet sent
  at t >= window_start arrives at t + latency >= window_end, so hosts are
  independent within a window -- the property the reference enforces with
  per-host queues + barriers (scheduler.c:359-414) and that we exploit to
  advance every host in one vectorized step.

* Deterministic per-host event order: within a host, events execute in
  (time, category, packet-id) order, reproducing the role of the
  reference's total order (time, dstHostID, srcHostID, srcHostEventID)
  (core/work/event.c:110-153).  Between hosts no order is needed --
  windows make them independent -- so the result is bitwise identical for
  any device mesh, any pool capacity, and any chunking of run_until calls.

Data layout (the whole performance story; numbers in tools/opbench*.py):

* OUTBOX (state.pool): per-SOURCE slabs.  Emissions are staged into the
  emitting host's own slab by row-local one-hot merges -- no scatter ops
  in the hot loop (an XLA scatter costs ~1us/update inside a compiled
  loop; a one-hot masked merge fuses for free).

* INBOX (state.inbox): per-DESTINATION slabs, packed into one [P1, C]
  i32 block.  Every per-micro-step reduction the engine needs -- next
  arrival per host, NIC drain candidate, CoDel backlog -- is a row-local
  reshape-min/sum over [H, slab] (~0ms) instead of the dst-keyed
  segment-min over the whole pool that dominated the previous design
  (12.7 ms per micro-step at 16k hosts).

* WINDOW-BOUNDARY EXCHANGE (`_exchange`): packets that left their source
  (stage IN_FLIGHT) move outbox -> inbox in bulk, once per window.  The
  conservative invariant guarantees anything sent during window w arrives
  at >= window_end(w), so arrivals for a window are fully known at its
  start.  The move is one packed i32 row-scatter plus a hierarchical
  rank-by-destination (scatter-add counts over superblocks + an exclusive
  cumsum + in-superblock pairwise ranks): ~5ms per window, amortized over
  the window's micro-steps.  This replaces the reference's per-packet
  push onto locked destination-host queues (worker.c:293-300) with the
  PDES equivalent of an all-to-all collective -- under a sharded mesh the
  scatter IS the ICI all-to-all.

Same-host loopback bypasses the exchange (reference's local path,
network_interface.c:548-555): those packets are inserted straight into
the sender's own inbox slab at staging time, which is row-local.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import emit, nic, rng, simtime
# Reliability-dropped packets are never materialized in the pool (they are
# counted in HostTable.pkts_dropped_inet instead), so PDS_INET_DROPPED is
# deliberately absent here.
from .state import (ERR_POOL_OVERFLOW, I32, I64, U32, PROTO_TCP, PROTO_UDP,
                    STAGE_FREE, STAGE_IN_FLIGHT, STAGE_RX_QUEUED,
                    STAGE_TX_QUEUED, TCP_HEADER_SIZE, UDP_HEADER_SIZE,
                    PDS_INET_SENT, PDS_RCV_SOCKET_PROCESSED,
                    PDS_ROUTER_DROPPED, PDS_ROUTER_ENQUEUED,
                    PDS_SND_CREATED, PDS_SND_INTERFACE_SENT,
                    ICOL_SRC, ICOL_SPORT, ICOL_DPORT, ICOL_PROTO, ICOL_FLAGS,
                    ICOL_SEQ, ICOL_ACK, ICOL_WND, ICOL_LEN, ICOL_PAYLOAD,
                    ICOL_TIME_LO, ICOL_TIME_HI, ICOL_CTR_LO, ICOL_CTR_HI,
                    ICOL_TS_LO, ICOL_TS_HI, ICOL_TSE_LO, ICOL_TSE_HI,
                    ICOL_SACK0_LO, ICOL_SACK0_HI, ICOL_SACK2_HI, ICOLS,
                    OEXT_DST, OEXT_LAT_LO, OEXT_LAT_HI, OEXT_PRIO, ext_base,
                    LOG_WARNING, LOG_DEBUG, LOG_DROP_INET, LOG_DROP_ROUTER,
                    LOG_DROP_TAIL, LOG_DROP_POOL, LOG_DELIVER, LOG_SEND,
                    LOG_NETEM_DOWN,
                    SPAN_EMIT, SPAN_STAGE, SPAN_TX, SPAN_LINK, SPAN_EXCHANGE,
                    SPAN_DELIVER, LREASON_QDISC, LREASON_LOSS,
                    LREASON_HOST_DOWN, LREASON_ACK_SHED, LREASON_POOL,
                    SENTINEL_CONSERVATION, SENTINEL_TIME, SENTINEL_BOUNDS,
                    SENTINEL_NONFINITE, SENTINEL_TIMER_MAX_NS,
                    DIGEST_GROUPS,
                    enc_lo, enc_hi, dec_i64, SimState, host_ids)
# Fault/dynamics overlay operators (netem/apply.py).  Every call site
# guards on `state.nm is None` (a trace-time pytree check), so worlds
# without a fault schedule compile the overlay away entirely.
from ..netem import apply as netem_apply

INV = simtime.SIMTIME_INVALID

# Mesh axis name the sharded entry (parallel/mesh.py) maps hosts over.
# Defined here (not imported from parallel/) so core never depends on the
# parallel package; parallel.sharding.HOST_AXIS must match.
MESH_AXIS = "hosts"


def _on_mesh(state: SimState) -> bool:
    """Trace-time static: is this trace running inside the shard_map body
    of parallel.mesh_run_until?  Off-mesh (hoff None) every mesh branch
    below traces away, keeping the single-device graph byte-identical."""
    return state.hoff is not None


def _lrows(state: SimState, vec):
    """Slice a [H_global] per-host vector down to this shard's local rows
    (identity off-mesh).  Only needed for the few per-host inputs that
    stay replicated under the mesh because they are also gathered by
    global ids (params.host_vertex)."""
    if state.hoff is None:
        return vec
    return jax.lax.dynamic_slice_in_dim(vec, state.hoff,
                                        state.hosts.num_hosts)


def _uses_tcp(app) -> bool:
    """Static app capability: apps that never open TCP sockets (pure-UDP
    phold) let the whole TCP machine trace away from the compiled step."""
    return getattr(app, "uses_tcp", True)


def _may_loopback(app) -> bool:
    """Static app capability: apps that never send to their own host let
    the loopback insert path (an [H*E]-row scatter per micro-step) trace
    away entirely."""
    return getattr(app, "may_loopback", True)


def _bitcast_i32_u32(x):
    return jax.lax.bitcast_convert_type(x.astype(I32), U32)


class RxPkt:
    """Field registers of the (at most one) packet delivered to each host
    this micro-step -- [H] vectors decoded from the inbox block."""

    __slots__ = ("src", "sport", "dport", "proto", "flags", "seq", "ack",
                 "wnd", "length", "payload_id", "time", "ts", "ts_echo",
                 "pkt_id", "sack_lo", "sack_hi")

    def __init__(self, row, keys_row, time_row):
        self.src = row[:, ICOL_SRC]
        self.sport = row[:, ICOL_SPORT]
        self.dport = row[:, ICOL_DPORT]
        self.proto = row[:, ICOL_PROTO]
        self.flags = row[:, ICOL_FLAGS]
        self.seq = _bitcast_i32_u32(row[:, ICOL_SEQ])
        self.ack = _bitcast_i32_u32(row[:, ICOL_ACK])
        self.wnd = row[:, ICOL_WND]
        self.length = row[:, ICOL_LEN]
        self.payload_id = row[:, ICOL_PAYLOAD]
        self.time = time_row
        if row.shape[1] >= ICOLS:
            self.ts = dec_i64(row[:, ICOL_TS_LO], row[:, ICOL_TS_HI])
            self.ts_echo = dec_i64(row[:, ICOL_TSE_LO], row[:, ICOL_TSE_HI])
            self.sack_lo = _bitcast_i32_u32(
                row[:, ICOL_SACK0_LO:ICOL_SACK2_HI + 1:2])
            self.sack_hi = _bitcast_i32_u32(
                row[:, ICOL_SACK0_HI:ICOL_SACK2_HI + 2:2])
        else:
            # Narrow (TCP-free) inbox: the TCP machine is traced away, so
            # these registers are never consumed; keep them as zeros for
            # shape stability.
            z = jnp.zeros_like(time_row)
            self.ts = z
            self.ts_echo = z
            self.sack_lo = jnp.zeros((row.shape[0], 3), U32)
            self.sack_hi = jnp.zeros((row.shape[0], 3), U32)
        self.pkt_id = keys_row


def _cap_append(state: SimState, mask, *, time_v, src, dst, sport, dport,
                proto, flags, length, seq, ack, kind) -> SimState:
    """Append masked flat records to the capture ring (both traffic
    directions route through here; traced away when capture is off).

    One batch larger than the ring would wrap onto itself and make the
    surviving record per slot scatter-order-dependent; keep the first
    `c` records of such a batch instead (deterministic) -- size the ring
    above the per-step record volume to never hit this.  `total` must
    then advance by what was *written*, not staged, or the writer would
    treat never-written slots as valid records."""
    cap = state.cap
    c = cap.capacity        # local segment size under a mesh shard
    if cap.total.ndim == 1 and cap.total.shape[0] != 1:
        raise ValueError(
            "sharded capture ring outside a mesh: a ring built with "
            "make_capture_ring(shards=N) only runs under "
            "parallel.mesh_run_until (each shard needs its own cursor "
            "slice); build it with shards=1 for single-device runs")
    tot0 = cap.total.reshape(())   # scalar, or this shard's [1] cursor
    crank = jnp.cumsum(mask) - 1
    n_new = jnp.minimum(jnp.sum(mask).astype(I64), c)
    pos = ((tot0 + crank) % c).astype(I32)
    idx = jnp.where(mask & (crank < c), pos, c)  # c = dropped write

    def cw(a, val, dtype=None):
        v = val.reshape(-1) if hasattr(val, "reshape") else val
        if dtype is not None:
            v = v.astype(dtype)
        return a.at[idx].set(v, mode="drop")

    return state.replace(cap=cap.replace(
        time=cw(cap.time, time_v),
        src=cw(cap.src, src),
        dst=cw(cap.dst, dst),
        sport=cw(cap.sport, sport),
        dport=cw(cap.dport, dport),
        proto=cw(cap.proto, proto),
        flags=cw(cap.flags, flags),
        length=cw(cap.length, length),
        seq=cw(cap.seq, seq),
        ack=cw(cap.ack, ack),
        kind=cap.kind.at[idx].set(kind, mode="drop"),
        total=cap.total + n_new,
    ))


def _log_append(state: SimState, mask, code: int, level: int, time_v,
                host_v, arg_v):
    """Append one event per set mask element into the log ring (traced
    away entirely when logging is off).  `mask`/`time_v`/`host_v`/`arg_v`
    are flat arrays of equal length; per-host level gating applies.

    `host_v` carries GLOBAL host ids (identical to local rows off-mesh):
    the ring records global ids for the drain, while the level lookup
    shifts them back to this shard's local log_level rows."""
    if state.log is None:
        return state
    lg = state.log
    c = lg.capacity         # local segment size under a mesh shard
    if lg.total.ndim == 1 and lg.total.shape[0] != 1:
        raise ValueError(
            "sharded log ring outside a mesh: a ring built with "
            "make_log_ring(shards=N) only runs under "
            "parallel.mesh_run_until (each shard needs its own cursor "
            "slice); build it with shards=1 for single-device runs")
    tot0 = lg.total.reshape(())    # scalar, or this shard's [1] cursor
    loc = host_v if state.hoff is None \
        else host_v - state.hoff.astype(host_v.dtype)
    lvl_ok = state.log_level[jnp.clip(loc, 0,
                                      state.log_level.shape[0] - 1)] >= level
    m = mask & lvl_ok
    rank = jnp.cumsum(m) - 1
    n_tot = jnp.sum(m).astype(I64)
    n_new = jnp.minimum(n_tot, c)
    pos = ((tot0 + rank) % c).astype(I32)
    idx = jnp.where(m & (rank < c), pos, c)
    return state.replace(log=lg.replace(
        time=lg.time.at[idx].set(time_v, mode="drop"),
        host=lg.host.at[idx].set(host_v.astype(I32), mode="drop"),
        code=lg.code.at[idx].set(code, mode="drop"),
        arg=lg.arg.at[idx].set(arg_v.astype(I32), mode="drop"),
        total=lg.total + n_new,
        lost=lg.lost + (n_tot - n_new),
    ))


def _lineage_append(state: SimState, mask, *, time_v, id_v, host_v, stage,
                    reason_v=0):
    """Append one span row per set mask element into the lineage ring
    (traced away entirely when no tracer is installed).  `mask`/`time_v`/
    `id_v`/`host_v` are flat arrays of equal length; untraced rows
    (id 0) are masked out here so call sites pass raw side-array
    gathers.  `host_v` carries GLOBAL host ids; `stage` is a static
    SPAN_* code and `reason_v` an LREASON_* scalar or flat array.

    The overflow policy is the capture ring's: one batch larger than
    the ring keeps its first `c` rows deterministically, and `lost`
    counts what a bigger ring would have kept."""
    if state.lineage is None:
        return state
    ln = state.lineage
    c = ln.capacity         # local segment size under a mesh shard
    if ln.total.ndim == 1 and ln.total.shape[0] != 1:
        raise ValueError(
            "sharded lineage ring outside a mesh: a tracer built with "
            "make_lineage(shards=N) only runs under "
            "parallel.mesh_run_until (each shard needs its own cursor "
            "slice); build it with shards=1 for single-device runs")
    tot0 = ln.total.reshape(())    # scalar, or this shard's [1] cursor
    m = mask & (id_v != 0)
    rank = jnp.cumsum(m) - 1
    n_tot = jnp.sum(m).astype(I64)
    n_new = jnp.minimum(n_tot, c)
    pos = ((tot0 + rank) % c).astype(I32)
    idx = jnp.where(m & (rank < c), pos, c)
    return state.replace(lineage=ln.replace(
        s_time=ln.s_time.at[idx].set(time_v, mode="drop"),
        s_id=ln.s_id.at[idx].set(id_v.astype(I32), mode="drop"),
        s_host=ln.s_host.at[idx].set(host_v.astype(I32), mode="drop"),
        s_stage=ln.s_stage.at[idx].set(stage, mode="drop"),
        s_reason=ln.s_reason.at[idx].set(
            reason_v if not hasattr(reason_v, "astype")
            else reason_v.astype(I32), mode="drop"),
        total=ln.total + n_new,
        lost=ln.lost + (n_tot - n_new),
    ))


# ---------------------------------------------------------------------------
# Next-event scan (replaces priority-queue peeks)
# ---------------------------------------------------------------------------


def _aux_times(state: SimState, params, app):
    """Per-host earliest non-packet event: timers, app, re-ticks."""
    socks, hosts = state.socks, state.hosts
    t_h = hosts.t_resume
    if _uses_tcp(app):
        t_tmr = jnp.minimum(
            jnp.minimum(jnp.min(socks.t_rto, axis=1),
                        jnp.min(socks.t_persist, axis=1)),
            jnp.minimum(jnp.min(socks.t_delack, axis=1),
                        jnp.min(socks.t_tw, axis=1)),
        )
        t_h = jnp.minimum(t_h, t_tmr)
    if app is not None:
        t_h = jnp.minimum(t_h, app.next_time(state))
    return t_h


def _cpu_clamp(state: SimState, params, t_h):
    """Virtual CPU gate (reference cpu_isBlocked + event deferral,
    cpu.c:56-75, event.c:71-84): a host whose accumulated CPU backlog
    exceeds the threshold cannot tick before the backlog drains back to
    it, so its events execute late by exactly the built-up delay.

    Like the reference's --cpu-threshold (options.c: default -1 =
    disabled), a negative threshold turns blocking off entirely; wake
    times are rounded to cpu_precision_ns."""
    prec = jnp.maximum(params.cpu_precision_ns, 1)
    ready = state.hosts.cpu_avail - params.cpu_threshold_ns
    rem = ready % prec
    ready = ready - rem + jnp.where(rem >= prec // 2, prec, 0)
    clamp = (params.cpu_ns_per_event > 0) & (t_h != INV) & \
        (params.cpu_threshold_ns >= 0)
    return jnp.where(clamp, jnp.maximum(t_h, ready), t_h)


def _scan_all(state: SimState, params, app):
    """Per-host next event time [H] + its global min.

    Arrival candidates come from the inbox only: IN_FLIGHT entries drive
    the clock (their arrival instant); RX_QUEUED backlog (arrival in the
    past, waiting on rx tokens) is owned by the t_resume wake machinery,
    so it never drags virtual time backward.  Packets still in the outbox
    are invisible here by design -- the conservative window invariant
    puts their arrivals beyond the current window, and the boundary
    exchange makes them visible before the next window's scan."""
    ib = state.inbox
    h = state.hosts.num_hosts
    ki = ib.capacity // h
    t2 = ib.times().reshape(h, ki)
    drive = (ib.stage == STAGE_IN_FLIGHT).reshape(h, ki)
    t_in = jnp.min(jnp.where(drive, t2, jnp.asarray(INV, I64)), axis=1)
    t_h = jnp.minimum(t_in, _aux_times(state, params, app))
    t_h = _cpu_clamp(state, params, t_h)
    return t_h, jnp.min(t_h)


def next_times(state: SimState, params, app):
    """Per-host earliest pending event time [H] and its global min."""
    return _scan_all(state, params, app)


def _outbox_pending(state: SimState):
    """Global earliest arrival among packets still awaiting the boundary
    exchange (scalar i64; INV if none).  Keeps the outer window loop from
    terminating while traffic is still in flight toward the inbox."""
    pool = state.pool
    t = jnp.where(pool.stage == STAGE_IN_FLIGHT, pool.time,
                  jnp.asarray(INV, I64))
    return jnp.min(t)


# ---------------------------------------------------------------------------
# Window-boundary exchange: outbox IN_FLIGHT -> inbox slabs
# ---------------------------------------------------------------------------


def _superblock(n: int, h: int) -> int:
    """Items per rank superblock.  Memory: the pairwise rank cube is
    n*M bytes and the per-block count table is (n/M)*h*4 bytes, so the
    sweet spot is M ~ sqrt(4h); clamp to [64, 512] and keep both sides
    bounded at 10k-host scale (n can exceed a million items)."""
    m = int((4 * max(h, 1)) ** 0.5)
    m = max(64, min(512, (m // 64) * 64 if m >= 64 else 64))
    return min(m, max(64, n))


def _rank_by_dst(mask, dstp, h, m):
    """Per-item rank among masked same-destination items, in flat order
    (hierarchical: scatter-add superblock counts + exclusive cumsum +
    in-superblock pairwise ranks).  Returns ([npad] rank, [H] totals)."""
    npad = dstp.shape[0]
    blkid = jnp.arange(npad, dtype=I32) // m
    b = npad // m
    ones = jnp.where(mask, 1, 0).astype(I32)
    cnt = jnp.zeros((b, h), I32).at[blkid, dstp].add(ones, mode="drop")
    csum = jnp.cumsum(cnt, axis=0)
    off = csum - cnt                                   # exclusive over blocks
    total = csum[-1]                                   # [H] items per dst
    d3 = dstp.reshape(b, m)
    l3 = mask.reshape(b, m)
    eq = (d3[:, :, None] == d3[:, None, :]) & l3[:, None, :]
    lower = jnp.tril(jnp.ones((m, m), bool), -1)[None]
    rank_in = jnp.sum(eq & lower, axis=2, dtype=I32).reshape(-1)
    return off.reshape(-1)[blkid * h + dstp] + rank_in, total


def _exchange_core(pool, ib, h, params, ret_islot=False):
    """Slab machinery of the boundary exchange, free of SimState
    packaging: rank movers by destination, splice them into inbox free
    slots, clear the outbox stage.  Returns (pool, inbox, total,
    total_prot, n_free) -- the three [H] per-destination tallies are
    what the accounting tail (_exchange_body) derives drops, trace
    counters and recorder rows from.  `ret_islot` (lineage tracing)
    appends the slot-assignment internals (islot, ok, mvp, pad) so the
    tail can move trace ids under the identical permutation.

    Split out so the megakernel path can run it as ONE single-block
    pallas call (megakernel.exchange_call): every op here is integer
    slab shuffling, so it is fusion-context stable (see the "f32
    stability" section of docs/megakernel.md)."""
    p0 = pool.capacity
    p1 = ib.capacity
    ki = p1 // h
    ic = ib.blk.shape[1]          # ICOLS, or NCOLS_UDP for TCP-free worlds

    moving = pool.stage == STAGE_IN_FLIGHT             # [P0], src-major order
    dst = jnp.clip(pool.dst, 0, h - 1)

    # --- per-item rank among same-destination movers, in flat (src-major)
    # order.  Flat order == (src, emission counter) order within a window
    # because outbox slots free only at boundaries, so allocation indices
    # are monotone across the window's micro-steps -- this reproduces the
    # reference's (srcHostID, srcHostEventID) tiebreak (event.c:110-153).
    m = _superblock(p0, h)
    npad = -(-p0 // m) * m
    pad = npad - p0
    dstp = jnp.pad(dst, (0, pad))
    mvp = jnp.pad(moving, (0, pad))
    rank, total = _rank_by_dst(mvp, dstp, h, m)

    free2 = (ib.stage == STAGE_FREE).reshape(h, ki)
    ids = jnp.arange(ki, dtype=I32)[None, :]
    n_free = jnp.sum(free2, axis=1, dtype=I32)          # [H]

    # --- ACK-before-data shedding (TCP worlds, overflow windows only):
    # when a destination slab can't take every mover, deliberately shed
    # pure ACKs first -- the vectorized analog of ACK compression under
    # router pressure.  Cumulative ACKing absorbs the loss (the next ACK
    # supersedes the shed one), so only DATA/control drops are protocol-
    # visible and only they raise ERR_POOL_OVERFLOW.  Implemented as a
    # class-aware re-rank: protected movers keep their rank among
    # protected; pure ACKs rank after all protected for that dst.  Slot
    # positions don't affect delivery order ((time, pkt_id) row-min), so
    # the re-rank changes only WHO overflows, deterministically.
    if ic >= ICOLS:
        blk_f = pool.blk
        from ..transport.tcp import pure_ack as _pure_ack
        pure_ack = _pure_ack(blk_f[:, ICOL_PROTO], blk_f[:, ICOL_FLAGS],
                             blk_f[:, ICOL_LEN])
        ackp = jnp.pad(pure_ack, (0, pad)) & mvp
        overflow = jnp.any(total > n_free)

        def two_class(_):
            rank_prot, total_prot = _rank_by_dst(mvp & ~ackp, dstp, h, m)
            r = jnp.where(ackp, total_prot[dstp] + (rank - rank_prot),
                          rank_prot)
            return r, total_prot

        rank_eff, total_prot = jax.lax.cond(
            overflow & jnp.any(ackp), two_class,
            lambda _: (rank, total), None)
    else:
        rank_eff, total_prot = rank, total

    # --- destination slab free-slot assignment (ascending slot order).
    order2 = jnp.argsort(jnp.where(free2, ids, ids + ki), axis=1).astype(I32)
    within = order2.reshape(-1)[dstp * ki + jnp.clip(rank_eff, 0, ki - 1)]
    ok = mvp & (rank_eff < n_free[dstp])
    islot = jnp.where(ok, dstp * ki + within, p1)       # p1 = drop sentinel

    # --- forward the packed rows verbatim: the outbox block's first `ic`
    # columns ARE the inbox layout; only the TIME columns need splicing
    # from the authoritative `time` array (the block's copy went stale if
    # _tx_drain restamped the departure).
    vals = jnp.concatenate(
        [pool.blk[:, :ICOL_TIME_LO],
         enc_lo(pool.time)[:, None], enc_hi(pool.time)[:, None],
         pool.blk[:, ICOL_TIME_HI + 1:ic]], axis=1)       # [P0, ic]
    vals = jnp.pad(vals, ((0, pad), (0, 0)))              # [npad, ic]

    ib = ib.replace(
        blk=ib.blk.at[islot].set(vals, mode="drop"),
        stage=ib.stage.at[islot].set(STAGE_IN_FLIGHT, mode="drop"),
        status=ib.status.at[islot].set(jnp.pad(pool.status, (0, pad)),
                                       mode="drop")
        if params.pds_trail else ib.status,
    )

    # Movers leave the outbox whether they fit or overflowed; who
    # overflowed (and whether it was a shed ACK or a counted drop) is
    # the accounting tail's business, derived from the tallies below.
    pool = pool.replace(stage=jnp.where(moving, STAGE_FREE, pool.stage))
    if ret_islot:
        return pool, ib, total, total_prot, n_free, (islot, ok, mvp, pad)
    return pool, ib, total, total_prot, n_free


def _exchange_body(state: SimState, params, fused: bool = False) -> SimState:
    hosts = state.hosts
    h = hosts.num_hosts
    p0 = state.pool.capacity
    ki = state.inbox.capacity // h
    moving = state.pool.stage == STAGE_IN_FLIGHT        # pre-clear copy
    dst = jnp.clip(state.pool.dst, 0, h - 1)

    if fused:
        from . import megakernel as mk
        pool, ib, total, total_prot, n_free = mk.exchange_call(
            state.pool, state.inbox, h, params)
    elif state.lineage is not None:
        # Trace ids ride the IDENTICAL slot permutation the packed rows
        # take: movers' pool_id entries scatter into inbox_id via the
        # core's islot, moved rows clear, and each placed/overflowed
        # mover gets an EXCHANGE/DELIVER-reason span.  Pure observation
        # on side arrays -- pool/inbox bytes are untouched.
        pool, ib, total, total_prot, n_free, (islot_l, ok_l, mvp_l,
                                              pad_l) = _exchange_core(
            state.pool, state.inbox, h, params, ret_islot=True)
        ln = state.lineage
        lpad = jnp.pad(ln.pool_id, (0, pad_l))
        state = state.replace(lineage=ln.replace(
            inbox_id=ln.inbox_id.at[islot_l].set(lpad, mode="drop"),
            pool_id=jnp.where(moving, 0, ln.pool_id)))
        now_p = jnp.broadcast_to(state.now, lpad.shape)
        dst_p = jnp.pad(dst, (0, pad_l))
        state = _lineage_append(state, ok_l, time_v=now_p, id_v=lpad,
                                host_v=dst_p, stage=SPAN_EXCHANGE)
        # Overflowed movers die here: shed pure ACKs vs counted drops
        # (the two-class re-rank puts acks last exactly when drops
        # exist, so a dropped pure ack under overflow IS a shed one).
        if state.inbox.blk.shape[1] >= ICOLS:
            from ..transport.tcp import pure_ack as _pure_ack_l
            shed_l = jnp.pad(_pure_ack_l(
                state.pool.blk[:, ICOL_PROTO], state.pool.blk[:, ICOL_FLAGS],
                state.pool.blk[:, ICOL_LEN]), (0, pad_l)) & mvp_l
        else:
            shed_l = jnp.zeros_like(mvp_l)
        state = _lineage_append(
            state, mvp_l & ~ok_l, time_v=now_p, id_v=lpad, host_v=dst_p,
            stage=SPAN_EXCHANGE,
            reason_v=jnp.where(shed_l, LREASON_ACK_SHED, LREASON_POOL))
    else:
        pool, ib, total, total_prot, n_free = _exchange_core(
            state.pool, state.inbox, h, params)

    # Profiler counter block (trace.py), present only when a run opted
    # in: packets moved this exchange + peak destination-slab occupancy.
    if state.tr is not None:
        fit = jnp.minimum(total, n_free)                # [H] movers placed
        occ = jnp.max(ki - n_free + fit)                # [H] -> max slots used
        state = state.replace(tr=state.tr.replace(
            exchanges=state.tr.exchanges + 1,
            pkts_exchanged=state.tr.pkts_exchanged
            + jnp.sum(fit.astype(I64)),
            occ_max=jnp.maximum(state.tr.occ_max, occ.astype(I32))))

    # Flight recorder (state.FlightRecorder): this window's src->dst
    # LOGICAL-SHARD traffic matrix, counted over offered movers.  The
    # shard of a host is id // (h // D), matching the mesh partition, so
    # a single-device run of a D-sharded world writes bitwise the same
    # matrix the mesh exchange derives from its all_to_all ranking.
    # Pool rows are src-major (slab per source host), so a row's source
    # shard is just row // (p0 // D).
    if state.fr is not None:
        dm = state.fr.n_shards
        src_sh = jnp.arange(p0, dtype=I32) // (p0 // dm)
        dst_sh = (dst // (h // dm)).astype(I32)
        ones_m = jnp.where(moving, 1, 0).astype(I32)
        byt_m = jnp.where(moving, state.pool.blk[:, ICOL_LEN], 0).astype(I64)
        state = state.replace(fr=state.fr.replace(
            cur_ex_cnt=jnp.zeros((dm, dm), I32).at[src_sh, dst_sh]
            .add(ones_m),
            cur_ex_bytes=jnp.zeros((dm, dm), I64).at[src_sh, dst_sh]
            .add(byt_m)))

    # Shed pure ACKs are accounted as thinning; DATA/control overflow is
    # a counted drop and raises the capacity escape-hatch flag.
    drops_all = jnp.maximum(total - n_free, 0).astype(I64)
    data_drops = jnp.minimum(
        drops_all, jnp.maximum(total_prot - n_free, 0).astype(I64))
    acks_shed = drops_all - data_drops
    hosts = hosts.replace(
        pkts_dropped_pool=hosts.pkts_dropped_pool + data_drops,
        acks_thinned=hosts.acks_thinned + acks_shed)
    err = state.err | jnp.where(jnp.any(data_drops > 0), ERR_POOL_OVERFLOW,
                                0).astype(state.err.dtype)
    state = state.replace(pool=pool, inbox=ib, hosts=hosts, err=err)
    if state.log is not None:
        from .state import LOG_ACK_THIN
        rows = jnp.arange(h, dtype=I32)
        now_v = jnp.broadcast_to(state.now, (h,))
        state = _log_append(state, data_drops > 0, LOG_DROP_POOL,
                            LOG_WARNING, now_v, rows, data_drops)
        state = _log_append(state, acks_shed > 0, LOG_ACK_THIN,
                            LOG_WARNING, now_v, rows, acks_shed)
    return state


def _exchange_body_mesh(state: SimState, params) -> SimState:
    """Boundary exchange across a device mesh: the dst-bucketed
    all-to-all the single-device scatter becomes when hosts shard.

    Three stages, each reusing the single-device machinery at a
    different granularity:

    1. SEND BUCKETING: movers rank by destination SHARD (`_rank_by_dst`
       with h = n_shards) in local flat (src-major) order, then scatter
       their spliced rows -- plus a global-dst trailer column (and the
       status trail when enabled) -- into a [D*B, C+] send buffer of D
       fixed-size blocks.  B = local pool capacity is an exact bound:
       a shard can never have more movers than outbox slots.

    2. COLLECTIVE: one tiled `lax.all_to_all` swaps block d of every
       shard to shard d.  Received block s holds sender s's movers in
       sender-local flat order, so concatenated blocks s=0..D-1 are in
       GLOBAL flat (src-major) order -- exactly the order the
       single-device rank walks, which is what keeps the per-dst rank
       (and therefore slot assignment, overflow choice, and ACK-shed
       choice) bitwise identical to the single-device run.

    3. LOCAL SPLICE: the received rows re-rank by LOCAL destination and
       take free inbox slots in ascending order -- the unchanged
       single-device tail, with the two ACK-shed gate predicates
       (overflow anywhere / any pure ACK among movers) reduced across
       shards first: they are global `any`s on one device, and shards
       must agree on the shed-vs-keep regime or slot layouts (including
       stale bytes under later writes) diverge leaf-for-leaf."""
    pool, ib, hosts = state.pool, state.inbox, state.hosts
    h = hosts.num_hosts                   # local hosts on this shard
    p0 = pool.capacity                    # local outbox rows
    p1 = ib.capacity
    ki = p1 // h
    ic = ib.blk.shape[1]
    d = jax.lax.psum(1, MESH_AXIS)        # static shard count
    hg = h * d                            # global hosts

    moving = pool.stage == STAGE_IN_FLIGHT          # [p0] local src-major
    dst_g = jnp.clip(pool.dst, 0, hg - 1)           # global dst ids
    dev = dst_g // h                                # destination shard

    # --- stage 1: rank by destination shard, in local flat order.
    m = _superblock(p0, d)
    npad = -(-p0 // m) * m
    pad = npad - p0
    devp = jnp.pad(dev, (0, pad))
    mvp = jnp.pad(moving, (0, pad))
    brank, bt = _rank_by_dst(mvp, devp, d, m)

    # Flight recorder: `bt` is this shard's movers per destination shard
    # -- exactly one row of the src->dst traffic matrix.  all_gather
    # stacks the rows src-major, leaving the full [D, D] matrix
    # replicated on every shard (the recorder block stays replicated).
    if state.fr is not None:
        lenp = jnp.pad(pool.blk[:, ICOL_LEN], (0, pad))
        bby = jnp.zeros((d,), I64).at[devp].add(
            jnp.where(mvp, lenp, 0).astype(I64))
        state = state.replace(fr=state.fr.replace(
            cur_ex_cnt=jax.lax.all_gather(bt, MESH_AXIS).astype(I32),
            cur_ex_bytes=jax.lax.all_gather(bby, MESH_AXIS)))

    # Spliced rows exactly as the single-device exchange forwards them
    # (TIME columns refreshed from the authoritative `time` array).
    vals = jnp.concatenate(
        [pool.blk[:, :ICOL_TIME_LO],
         enc_lo(pool.time)[:, None], enc_hi(pool.time)[:, None],
         pool.blk[:, ICOL_TIME_HI + 1:ic]], axis=1)        # [p0, ic]
    trail = [dst_g[:, None]]
    if params.pds_trail:
        trail.append(pool.status[:, None])
    if state.lineage is not None:
        # Trace ids travel the collective as one extra trailer column,
        # so they ride the exact permutation the packed rows take; the
        # packed row width itself is untouched.
        trail.append(state.lineage.pool_id[:, None])
    row = jnp.pad(jnp.concatenate([vals] + trail, axis=1),
                  ((0, pad), (0, 0)))                      # [npad, cs]
    cs = row.shape[1]

    b = p0                                 # bucket capacity (exact bound)
    send_idx = jnp.where(mvp, devp * b + jnp.clip(brank, 0, b - 1), d * b)
    sb = jnp.full((d * b, cs), -1, I32).at[send_idx].set(row, mode="drop")

    # --- stage 2: the collective.  Received block s = sender s's bucket
    # for this shard, preserving sender-local order.
    rb = jax.lax.all_to_all(sb, MESH_AXIS, split_axis=0, concat_axis=0,
                            tiled=True)                    # [d*b, cs]

    # --- stage 3: local splice (the single-device tail on rb rows).
    rdst_g = rb[:, ic]                     # -1 marks bucket padding
    rvalid = rdst_g >= 0
    rdst = jnp.clip(rdst_g - state.hoff, 0, h - 1)         # local dst row

    n = d * b
    m2 = _superblock(n, h)
    npad2 = -(-n // m2) * m2
    pad2 = npad2 - n
    rdstp = jnp.pad(rdst, (0, pad2))
    rvp = jnp.pad(rvalid, (0, pad2))
    rank, total = _rank_by_dst(rvp, rdstp, h, m2)

    free2 = (ib.stage == STAGE_FREE).reshape(h, ki)
    ids = jnp.arange(ki, dtype=I32)[None, :]
    n_free = jnp.sum(free2, axis=1, dtype=I32)

    if ic >= ICOLS:
        from ..transport.tcp import pure_ack as _pure_ack
        pure_ack = _pure_ack(rb[:, ICOL_PROTO], rb[:, ICOL_FLAGS],
                             rb[:, ICOL_LEN])
        ackp = jnp.pad(pure_ack, (0, pad2)) & rvp
        # GLOBAL gate predicates (see docstring): reduce before the cond.
        overflow = jax.lax.pmax(
            jnp.any(total > n_free).astype(I32), MESH_AXIS) > 0
        any_ack = jax.lax.pmax(
            jnp.any(ackp).astype(I32), MESH_AXIS) > 0

        def two_class(_):
            rank_prot, total_prot = _rank_by_dst(rvp & ~ackp, rdstp, h, m2)
            r = jnp.where(ackp, total_prot[rdstp] + (rank - rank_prot),
                          rank_prot)
            return r, total_prot

        rank_eff, total_prot = jax.lax.cond(
            overflow & any_ack, two_class, lambda _: (rank, total), None)
    else:
        rank_eff, total_prot = rank, total

    order2 = jnp.argsort(jnp.where(free2, ids, ids + ki), axis=1).astype(I32)
    within = order2.reshape(-1)[rdstp * ki + jnp.clip(rank_eff, 0, ki - 1)]
    ok = rvp & (rank_eff < n_free[rdstp])
    islot = jnp.where(ok, rdstp * ki + within, p1)

    rvals = jnp.pad(rb[:, :ic], ((0, pad2), (0, 0)))
    ib = ib.replace(
        blk=ib.blk.at[islot].set(rvals, mode="drop"),
        stage=ib.stage.at[islot].set(STAGE_IN_FLIGHT, mode="drop"),
        status=ib.status.at[islot].set(jnp.pad(rb[:, ic + 1], (0, pad2)),
                                       mode="drop")
        if params.pds_trail else ib.status,
    )

    if state.lineage is not None:
        # Receive side of the trailer column: splice arriving trace ids
        # into this shard's inbox_id under the same islot, clear the ids
        # of every local mover (they all left, placed or not), and write
        # spans.  Hosts are GLOBAL dst ids and the time is the uniform
        # window-open `now`, so the mesh span multiset matches the
        # single-device exchange row for row.
        ln = state.lineage
        lin_col = ic + 1 + (1 if params.pds_trail else 0)
        rlin = jnp.pad(rb[:, lin_col], (0, pad2))
        state = state.replace(lineage=ln.replace(
            inbox_id=ln.inbox_id.at[islot].set(rlin, mode="drop"),
            pool_id=jnp.where(moving, 0, ln.pool_id)))
        now_p = jnp.broadcast_to(state.now, rlin.shape)
        rdst_p = jnp.pad(rdst_g, (0, pad2))
        state = _lineage_append(state, ok, time_v=now_p, id_v=rlin,
                                host_v=rdst_p, stage=SPAN_EXCHANGE)
        if ic >= ICOLS:
            shed_l = ackp
        else:
            shed_l = jnp.zeros_like(rvp)
        state = _lineage_append(
            state, rvp & ~ok, time_v=now_p, id_v=rlin, host_v=rdst_p,
            stage=SPAN_EXCHANGE,
            reason_v=jnp.where(shed_l, LREASON_ACK_SHED, LREASON_POOL))

    if state.tr is not None:
        # Local partials; pkts_exchanged / occ_max are finalized across
        # shards by mesh_run_until (psum of the delta / pmax).
        fit = jnp.minimum(total, n_free)
        occ = jnp.max(ki - n_free + fit)
        state = state.replace(tr=state.tr.replace(
            exchanges=state.tr.exchanges + 1,
            pkts_exchanged=state.tr.pkts_exchanged
            + jnp.sum(fit.astype(I64)),
            occ_max=jnp.maximum(state.tr.occ_max, occ.astype(I32))))

    pool = pool.replace(stage=jnp.where(moving, STAGE_FREE, pool.stage))
    drops_all = jnp.maximum(total - n_free, 0).astype(I64)
    data_drops = jnp.minimum(
        drops_all, jnp.maximum(total_prot - n_free, 0).astype(I64))
    acks_shed = drops_all - data_drops
    hosts = hosts.replace(
        pkts_dropped_pool=hosts.pkts_dropped_pool + data_drops,
        acks_thinned=hosts.acks_thinned + acks_shed)
    # err is a per-shard partial here; mesh_run_until ORs it across
    # shards before returning (nothing inside the run branches on it).
    err = state.err | jnp.where(jnp.any(data_drops > 0), ERR_POOL_OVERFLOW,
                                0).astype(state.err.dtype)
    state = state.replace(pool=pool, inbox=ib, hosts=hosts, err=err)
    if state.log is not None:
        # Mesh parity with the single-device tail: records carry GLOBAL
        # host ids (the drain maps them to names) and land in this
        # shard's log segment.
        from .state import LOG_ACK_THIN
        rows_g = host_ids(state, I32)
        now_v = jnp.broadcast_to(state.now, (h,))
        state = _log_append(state, data_drops > 0, LOG_DROP_POOL,
                            LOG_WARNING, now_v, rows_g, data_drops)
        state = _log_append(state, acks_shed > 0, LOG_ACK_THIN,
                            LOG_WARNING, now_v, rows_g, acks_shed)
    return state


def _exchange(state: SimState, params, fused: bool = False) -> SimState:
    """Run the boundary exchange iff anything moved this window.
    `fused` routes the slab core through the single-block pallas call
    (megakernel.exchange_call); the mesh body keeps the reference core
    regardless -- its collectives cannot live inside a kernel."""
    moving = jnp.any(state.pool.stage == STAGE_IN_FLIGHT)
    if _on_mesh(state):
        # The mesh body contains collectives, so every shard must take
        # the same branch: any mover anywhere runs the exchange on all.
        moving = jax.lax.pmax(moving.astype(I32), MESH_AXIS) > 0
        return jax.lax.cond(moving,
                            lambda s: _exchange_body_mesh(s, params),
                            lambda s: s, state)
    return jax.lax.cond(moving,
                        lambda s: _exchange_body(s, params, fused=fused),
                        lambda s: s, state)


# ---------------------------------------------------------------------------
# Flight recorder: per-window row write (state.FlightRecorder)
# ---------------------------------------------------------------------------


def _fr_snapshot(state: SimState):
    """Window-open bookkeeping for the flight recorder: zero the exchange
    scratch matrix (a skipped exchange must record zero traffic, and the
    cond may bypass the body entirely) and capture the counters whose
    per-window deltas become the row.  Traced away when no recorder is
    installed."""
    fr = state.fr
    state = state.replace(fr=fr.replace(
        cur_ex_cnt=jnp.zeros_like(fr.cur_ex_cnt),
        cur_ex_bytes=jnp.zeros_like(fr.cur_ex_bytes)))
    snap = (state.n_events,
            state.n_steps,
            jnp.sum(state.hosts.pkts_recv.astype(I64)),
            jnp.sum(state.hosts.pkts_dropped_inet.astype(I64))
            + jnp.sum(state.hosts.pkts_dropped_router.astype(I64))
            + jnp.sum(state.hosts.pkts_dropped_pool.astype(I64)),
            jnp.asarray(0, I64) if state.nm is None
            else state.nm.killed.astype(I64))
    return state, snap


def _fr_record(state: SimState, snap, ws, we) -> SimState:
    """Append one row for the window that just closed: the exchange that
    opened it (scratch matrix) plus the micro-step activity inside it
    (counter deltas vs the _fr_snapshot).  Under a mesh the shard-local
    deltas psum to globals, so the replicated recorder block stays
    bitwise identical on every shard -- and identical to a single-device
    run of the same world with the same chunking."""
    fr = state.fr
    mesh = _on_mesh(state)
    ev0, steps0, recv0, drop0, kill0 = snap
    d_ev = state.n_events - ev0
    d_recv = jnp.sum(state.hosts.pkts_recv.astype(I64)) - recv0
    d_drop = (jnp.sum(state.hosts.pkts_dropped_inet.astype(I64))
              + jnp.sum(state.hosts.pkts_dropped_router.astype(I64))
              + jnp.sum(state.hosts.pkts_dropped_pool.astype(I64))) - drop0
    d_kill = (jnp.asarray(0, I64) if state.nm is None
              else state.nm.killed.astype(I64) - kill0)
    if mesh:
        # n_steps is uniform across shards (uniform loop predicates);
        # these four are shard-local partials inside the window loop.
        d_ev = jax.lax.psum(d_ev, MESH_AXIS)
        d_recv = jax.lax.psum(d_recv, MESH_AXIS)
        d_drop = jax.lax.psum(d_drop, MESH_AXIS)
        if state.nm is not None:
            d_kill = jax.lax.psum(d_kill, MESH_AXIS)
    idx = (fr.total % fr.capacity).astype(I32)
    return state.replace(fr=fr.replace(
        win_start=fr.win_start.at[idx].set(ws),
        win_end=fr.win_end.at[idx].set(we),
        steps=fr.steps.at[idx].set((state.n_steps - steps0).astype(I32)),
        events=fr.events.at[idx].set(d_ev.astype(I64)),
        routed=fr.routed.at[idx].set(jnp.sum(fr.cur_ex_cnt.astype(I64))),
        delivered=fr.delivered.at[idx].set(d_recv),
        dropped=fr.dropped.at[idx].set(d_drop),
        killed=fr.killed.at[idx].set(d_kill),
        ex_cnt=fr.ex_cnt.at[idx].set(fr.cur_ex_cnt),
        ex_bytes=fr.ex_bytes.at[idx].set(fr.cur_ex_bytes),
        ex_cnt_sum=fr.ex_cnt_sum + fr.cur_ex_cnt.astype(I64),
        ex_bytes_sum=fr.ex_bytes_sum + fr.cur_ex_bytes,
        total=fr.total + 1))


# ---------------------------------------------------------------------------
# Invariant sentinel: per-window health checks (state.SentinelBlock)
# ---------------------------------------------------------------------------


def _sentinel_counters(state: SimState):
    """Shard-local conservation ledger at a window boundary: lifetime
    emission/delivery/drop sums plus the live slot census.  Taken at
    window OPEN (before the exchange, which thins acks and drops data)
    and again at close; the per-window deltas satisfy

        d_sent - d_recv - d_router - d_thinned - d_occupied
            in [0, d_inet + d_pool + d_killed]

    exactly: every packet placed in the pool (pkts_sent) leaves the
    system through delivery, a router drop, ack thinning, a
    delivery-side inet/pool drop or netem kill, or still occupies a
    slot -- and the stage-side halves of the inet/pool counters are
    non-negative.  Seeded worlds and mid-run installs are immune
    because only deltas are checked."""
    h = state.hosts
    occ = (jnp.sum((state.pool.stage != STAGE_FREE).astype(I64))
           + jnp.sum((state.inbox.stage != STAGE_FREE).astype(I64)))
    return (jnp.sum(h.pkts_sent.astype(I64)),
            jnp.sum(h.pkts_recv.astype(I64)),
            jnp.sum(h.pkts_dropped_router.astype(I64)),
            jnp.sum(h.acks_thinned.astype(I64)),
            jnp.sum(h.pkts_dropped_inet.astype(I64)),
            jnp.sum(h.pkts_dropped_pool.astype(I64)),
            jnp.asarray(0, I64) if state.nm is None
            else state.nm.killed.astype(I64),
            occ)


def _sentinel_check(state: SimState, snap, ws, we) -> SimState:
    """Run every invariant probe for the window that just closed and
    fold the result into the sentinel block.  Under a mesh the deltas
    psum and the ok-flags pmin/pmax to globals first (the _fr_record
    rule), so the replicated block stays bitwise identical per shard.
    Only the sentinel block is written: installing it never perturbs
    the trajectory."""
    sn = state.sentinel
    mesh = _on_mesh(state)

    # -- packet conservation (window delta vs the open snapshot) --------
    d = [b - a for a, b in zip(snap, _sentinel_counters(state))]
    if mesh:
        d = [jax.lax.psum(x, MESH_AXIS) for x in d]
    d_sent, d_recv, d_rtr, d_ack, d_inet, d_pool, d_kill, d_occ = d
    resid_low = d_sent - d_recv - d_rtr - d_ack - d_occ
    resid_high = d_inet + d_pool + d_kill - resid_low
    # Overflow windows (err bit set) legitimately leak the identity --
    # the ERR_* flag is already the loud signal for those.
    err_any = state.err
    if mesh:
        err_any = jax.lax.pmax(err_any, MESH_AXIS)
    v_cons = ((resid_low < 0) | (resid_high < 0)) & (err_any == 0)

    # -- window-time monotonicity ---------------------------------------
    # we/ws are uniform across shards (pmin'd predicates), so this needs
    # no reduction.
    v_time = (we <= sn.last_we) | (we < ws)

    # -- stage domain / queue accounting / ring cursor bounds -----------
    ok = (jnp.all((state.pool.stage >= STAGE_FREE)
                  & (state.pool.stage <= STAGE_IN_FLIGHT))
          & jnp.all((state.inbox.stage >= STAGE_FREE)
                    & (state.inbox.stage <= STAGE_RX_QUEUED)
                    & (state.inbox.stage != STAGE_TX_QUEUED))
          & jnp.all(state.hosts.tx_queued >= 0)
          & jnp.all(state.hosts.rx_queued >= 0)
          & (jnp.sum(state.hosts.tx_queued.astype(I64))
             == jnp.sum((state.pool.stage == STAGE_TX_QUEUED).astype(I64)))
          & (jnp.sum(state.hosts.rx_queued.astype(I64))
             == jnp.sum((state.inbox.stage == STAGE_RX_QUEUED)
                        .astype(I64))))
    if state.fr is not None:
        ok = ok & (state.fr.total >= 0)
    if state.cap is not None:
        ok = ok & jnp.all(state.cap.total >= 0)
    if state.log is not None:
        ok = ok & jnp.all(state.log.total >= 0)
    if state.scope is not None:
        ok = ok & jnp.all(state.scope.f_total >= 0) \
            & jnp.all(state.scope.l_total >= 0)
    if mesh:
        ok = jax.lax.pmin(ok.astype(I32), MESH_AXIS) > 0
    v_bounds = ~ok

    # -- finiteness probe over the float islands + timer plausibility --
    # The float-dtype filter is a trace-time static, so int-only worlds
    # pay nothing here beyond the three timer-leaf range checks.
    bad = jnp.asarray(0, I64)
    for leaf in jax.tree_util.tree_leaves(state):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            bad = bad + jnp.sum(~jnp.isfinite(leaf), dtype=I64)
    # srtt/rttvar/rto live in i64 ns: a NaN bit pattern poisoning them
    # lands as a huge positive integer, so a range ceiling catches it.
    for t in (state.socks.srtt, state.socks.rttvar, state.socks.rto):
        bad = bad + jnp.sum((t < 0) | (t > SENTINEL_TIMER_MAX_NS),
                            dtype=I64)
    if mesh:
        bad = jax.lax.pmax(bad, MESH_AXIS)
    v_fin = bad > 0

    bits = (jnp.where(v_cons, SENTINEL_CONSERVATION, 0)
            | jnp.where(v_time, SENTINEL_TIME, 0)
            | jnp.where(v_bounds, SENTINEL_BOUNDS, 0)
            | jnp.where(v_fin, SENTINEL_NONFINITE, 0)).astype(I32)
    win = state.n_windows - 1  # the just-closed window's global index
    fresh = (bits != 0) & (sn.first_bad_window < 0)
    return state.replace(sentinel=sn.replace(
        checks=sn.checks + 1,
        violations=sn.violations | bits,
        last_violation=bits,
        first_bad_window=jnp.where(fresh, win, sn.first_bad_window),
        first_bad_t=jnp.where(fresh, we, sn.first_bad_t),
        last_we=jnp.asarray(we, I64),
        resid_low=resid_low,
        resid_high=resid_high,
        nonfinite=bad))


# ---------------------------------------------------------------------------
# Statescope digests: per-window state checksums (state.DigestBlock)
# ---------------------------------------------------------------------------


def _mix64(x):
    """murmur3 fmix64 in i64 (XLA integer arithmetic wraps two's
    complement and logical shifts act on the bit pattern, so this is
    bit-identical to the canonical u64 finalizer)."""
    s = jnp.asarray(33, I64)
    x = x ^ jax.lax.shift_right_logical(x, s)
    x = x * (-49064778989728563)       # 0xFF51AFD7ED558CCD
    x = x ^ jax.lax.shift_right_logical(x, s)
    x = x * (-4265267296055464877)     # 0xC4CEB9FE1A85EC53
    return x ^ jax.lax.shift_right_logical(x, s)


def _dg_bits(x):
    """Bit-normalize a state leaf to i64: floats by bitcast (so the
    digest sees f32 islands bitwise, not approximately), narrower ints
    by extension.  Deterministic on both the mesh and off-mesh paths."""
    x = jnp.asarray(x)
    if x.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(x, I32).astype(I64)
    if x.dtype == jnp.float64:
        return jax.lax.bitcast_convert_type(x, I64)
    return x.astype(I64)


_M64 = (1 << 64) - 1


def _dg_tag(group: int, leaf_idx: int) -> int:
    """Distinct i64 constant per (group, leaf): the element hash keys on
    it, so equal values at equal indices in different leaves still
    contribute different terms.  Host-side fmix64 (python ints)."""
    x = ((group << 32) ^ leaf_idx ^ 0x5851F42D4C957F2D) & _M64
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & _M64
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & _M64
    x ^= x >> 33
    return x - (1 << 64) if x >= (1 << 63) else x


def _digest_group_leaves(state: SimState) -> dict:
    """DIGEST_GROUPS name -> the state leaves that group covers.  The
    RNG counters get their own column (divergence there means the
    *sampling* went different ways, the first thing to rule out), so
    the hosts group excludes them by identity.

    The netem group drops `nm.killed`: under a mesh each shard holds a
    per-shard PARTIAL of that counter (parallel/mesh.py finalizes it by
    psum only at launch end), so mid-run it cannot be digested
    shard-invariantly; kills still surface through the pool/inbox state
    they mutate."""
    h = state.hosts
    rng_leaves = [h.rng_ctr, h.send_ctr]
    nm_leaves = ([l for l in jax.tree_util.tree_leaves(state.nm)
                  if l is not state.nm.killed]
                 if state.nm is not None else [])
    return {
        "pool": jax.tree_util.tree_leaves(state.pool),
        "inbox": jax.tree_util.tree_leaves(state.inbox),
        "socks": jax.tree_util.tree_leaves(state.socks),
        "hosts": [l for l in jax.tree_util.tree_leaves(h)
                  if not any(l is r for r in rng_leaves)],
        "rng": rng_leaves,
        "netem": nm_leaves,
        "app": jax.tree_util.tree_leaves(state.app),
    }


def _digest_sums(state: SimState) -> jnp.ndarray:
    """[G, D] i64 checksum matrix of the current state: per DIGEST_GROUPS
    row, per logical-host-shard column.

    Each element contributes `_mix64(bits + _mix64(global_index + tag))`
    (keyed on the GLOBAL flat index, so a permutation of equal values
    still diverges) and a group checksum is the WRAPPING i64 SUM of its
    contributions.  Summation is commutative and element ownership is
    exact, so the [G, D] matrix is bitwise identical between a D-shard
    mesh run and a single-device run installed with shards=D -- and
    summing columns over D reproduces the shards=1 digest.  Replicated
    leaves (netem overlay, scalars) contribute once, into column 0.

    Under a mesh each shard computes its local column and one
    all_gather assembles the identical full matrix on every shard (the
    flight-recorder replication rule)."""
    dg = state.dg
    D = dg.n_shards
    mesh = _on_mesh(state)
    h = state.hosts.num_hosts
    row_axes = (h, state.pool.capacity, state.inbox.capacity)
    groups = _digest_group_leaves(state)
    cols, repl = [], []
    for g, name in enumerate(DIGEST_GROUPS):
        col = jnp.zeros((1 if mesh else D,), I64)
        rep = jnp.asarray(0, I64)
        for i, leaf in enumerate(groups[name]):
            v = _dg_bits(leaf).reshape(-1)
            tag = _dg_tag(g, i)
            # The netem overlay is REPLICATED under a mesh (every shard
            # holds the full arrays), so its leaves must not take the
            # leading-axis shard rule even off-mesh -- group-level
            # classification keeps the two paths identical.
            sharded = (name != "netem" and jnp.ndim(leaf) >= 1
                       and leaf.shape[0] in row_axes)
            if sharded:
                if mesh:
                    # Global flat offset of this shard's element 0: the
                    # leading axis is a multiple of the host axis, so
                    # rows stay contiguous chunks under flattening.
                    off = state.hoff.astype(I64) * (v.shape[0] // h)
                else:
                    off = jnp.asarray(0, I64)
                idx = jnp.arange(v.shape[0], dtype=I64) + off
                contrib = _mix64(v + _mix64(idx + tag))
                if mesh:
                    col = col + jnp.sum(contrib, dtype=I64)[None]
                else:
                    col = col + contrib.reshape(D, -1).sum(
                        axis=1, dtype=I64)
            else:
                idx = jnp.arange(v.shape[0], dtype=I64)
                rep = rep + jnp.sum(_mix64(v + _mix64(idx + tag)),
                                    dtype=I64)
        cols.append(col)
        repl.append(rep)
    col_m = jnp.stack(cols)  # [G, 1] local under mesh; [G, D] off-mesh
    if mesh:
        col_m = jax.lax.all_gather(col_m[:, 0], MESH_AXIS).T  # [G, D]
    return col_m.at[:, 0].add(jnp.stack(repl))


def _digest_record(state: SimState, we) -> SimState:
    """Append one digest row when the just-closed window lands on the
    cadence.  `n_windows` is replicated (uniform window predicates), so
    every shard takes the same branch -- the all_gather inside the
    taken branch is collective-safe, the `_exchange` cond rule."""
    dg = state.dg
    win = state.n_windows - 1  # the just-closed window's global index
    due = (win % dg.every) == 0

    def rec(s):
        d = s.dg
        sums = _digest_sums(s)
        idx = (d.total % d.capacity).astype(I32)
        return s.replace(dg=d.replace(
            win=d.win.at[idx].set(win),
            t_end=d.t_end.at[idx].set(jnp.asarray(we, I64)),
            sums=d.sums.at[idx].set(sums),
            total=d.total + 1))

    return jax.lax.cond(due, rec, lambda s: s, state)


# ---------------------------------------------------------------------------
# Flowscope: cadence-gated flow/link sampling (state.FlowScope)
# ---------------------------------------------------------------------------


def _u32_dist(a, b):
    """i32 distance a-b in u32 sequence space (local copy of the
    transport's wrap-safe diff; core must not import transport)."""
    return (a.astype(U32) - b.astype(U32)).astype(I32)


def _ring_append(arrays, values, tot0, c, mask):
    """Masked bulk append into one ring segment (the _log_append
    recipe): first-`c`-of-batch deterministic overflow, drop-sentinel
    scatter.  Returns (updated arrays dict, n_new, n_lost)."""
    rank = jnp.cumsum(mask) - 1
    n_tot = jnp.sum(mask).astype(I64)
    n_new = jnp.minimum(n_tot, c)
    pos = ((tot0 + rank) % c).astype(I32)
    idx = jnp.where(mask & (rank < c), pos, c)  # c = dropped write
    out = {k: arrays[k].at[idx].set(v.reshape(-1).astype(arrays[k].dtype),
                                    mode="drop")
           for k, v in values.items()}
    return out, n_new, n_tot - n_new


def _scope_sample(state: SimState, ctx, we) -> SimState:
    """One flowscope sample epoch, taken when the closing window reached
    the cadence boundary (`we >= next_due`); otherwise an exact no-op.
    Traced away entirely when no scope block is installed.

    Flow rows: every TCP socket past LISTEN (handshake through
    teardown) on this shard's hosts.  Link rows: every local host NIC.
    Host ids are GLOBAL; rows land in this shard's ring segment under
    its own cursor.  `we` is uniform across shards (pmin'd window
    predicates) and next_due/samples replicated, so every shard takes
    the same branch here -- the cond is collective-safe."""
    from .state import SOCK_TCP, TCPS_CLOSED, TCPS_LISTEN

    scope = state.scope
    if scope.f_total.ndim == 1 and scope.f_total.shape[0] != 1:
        raise ValueError(
            "sharded flowscope outside a mesh: a block built with "
            "make_flowscope(shards=N) only runs under "
            "parallel.mesh_run_until (each shard needs its own cursor "
            "slice); build it with shards=1 for single-device runs")

    socks, hosts = state.socks, state.hosts
    h = hosts.num_hosts
    bw_up = ctx[0]
    gids = host_ids(state, I32)

    def _take(scope):
        if scope.sample_flows:
            s_n = socks.slots
            live = (socks.stype == SOCK_TCP) & \
                (socks.tcp_state != TCPS_CLOSED) & \
                (socks.tcp_state != TCPS_LISTEN)
            fm = live.reshape(-1)
            inflight = _u32_dist(socks.snd_nxt, socks.snd_una)
            acked = jnp.maximum(
                socks.bytes_sent - jnp.maximum(inflight, 0).astype(I64), 0)
            c = scope.flow_capacity
            arrays = {k: getattr(scope, "f_" + k) for k in (
                "time", "host", "slot", "peer", "cwnd", "ssthresh",
                "srtt", "inflight", "retx", "acked", "sent", "recv")}
            values = {
                "time": jnp.broadcast_to(we, (h * s_n,)),
                "host": jnp.broadcast_to(gids[:, None], (h, s_n)),
                "slot": jnp.broadcast_to(
                    jnp.arange(s_n, dtype=I32)[None, :], (h, s_n)),
                "peer": socks.peer_host,
                "cwnd": socks.cwnd,
                "ssthresh": socks.ssthresh,
                "srtt": socks.srtt,
                "inflight": inflight,
                "retx": socks.retx_segs,
                "acked": acked,
                "sent": socks.bytes_sent,
                "recv": socks.bytes_recv,
            }
            out, n_new, n_lost = _ring_append(
                arrays, values, scope.f_total.reshape(()), c, fm)
            scope = scope.replace(
                f_total=scope.f_total + n_new,
                f_lost=scope.f_lost + n_lost,
                **{"f_" + k: v for k, v in out.items()})

        if scope.sample_links:
            c = scope.link_capacity
            arrays = {k: getattr(scope, "l_" + k) for k in (
                "time", "host", "tx", "rx", "qdepth", "cap", "drops")}
            values = {
                "time": jnp.broadcast_to(we, (h,)),
                "host": gids,
                "tx": hosts.bytes_sent,
                "rx": hosts.bytes_recv,
                "qdepth": hosts.tx_queued + hosts.rx_queued,
                "cap": bw_up.astype(I64),
                "drops": (hosts.pkts_dropped_inet
                          + hosts.pkts_dropped_router
                          + hosts.pkts_dropped_pool),
            }
            out, n_new, n_lost = _ring_append(
                arrays, values, scope.l_total.reshape(()), c,
                jnp.ones((h,), bool))
            scope = scope.replace(
                l_total=scope.l_total + n_new,
                l_lost=scope.l_lost + n_lost,
                **{"l_" + k: v for k, v in out.items()})

        return scope.replace(
            samples=scope.samples + 1,
            next_due=(we // scope.interval + 1) * scope.interval)

    scope = jax.lax.cond(we >= scope.next_due, _take, lambda s: s, scope)
    return state.replace(scope=scope)


# ---------------------------------------------------------------------------
# Phase A: inbox enqueue -> NIC receive (token bucket + CoDel) -> delivery
# ---------------------------------------------------------------------------


def _wire_bytes(proto, length):
    """On-the-wire size charged against token buckets (payload + header;
    reference packet_getTotalSize with CONFIG_HEADER_SIZE_*)."""
    return length + jnp.where(proto == PROTO_TCP, TCP_HEADER_SIZE,
                              UDP_HEADER_SIZE)


def _rx_phase(state: SimState, params, em, tick_t, active, app,
              window_end, bw_dn=None, alive=None, aux_bound=None):
    """Arrivals: router enqueue (stage flip), NIC token/CoDel drain of one
    packet per host, transport delivery, inbox slot free.

    Merges the reference's _worker_runDeliverPacketTask -> router_enqueue
    -> networkinterface_receivePackets -> socket_pushInPacket chain
    (worker.c:236-241, router.c:104-123, network_interface.c:421-455)
    into row-local ops over the destination slabs."""
    from ..transport import tcp as tcp_mod
    from ..transport import udp as udp_mod

    ib, hosts = state.inbox, state.hosts
    h = hosts.num_hosts
    p1 = ib.capacity
    ki = p1 // h

    t_arr = ib.times()
    t2 = t_arr.reshape(h, ki)
    st2 = ib.stage.reshape(h, ki)

    # Router enqueue: wire arrivals whose time has come join the upstream
    # router queue (a stage tag flip; `time` keeps the arrival instant so
    # CoDel can compute sojourn).
    due = (st2 == STAGE_IN_FLIGHT) & (t2 <= tick_t[:, None]) & \
        active[:, None]

    # Interface receive buffer (reference <host interfacebuffer>): a
    # bounded router backlog tail-drops the latest arrivals beyond
    # capacity before CoDel sees them.  Rank dues within the row by
    # (time, id) so the drop order is deterministic.  The ranking is an
    # [H, slab, slab] comparison cube, so it only exists in the compiled
    # step when some host actually configures a buffer bound (STATIC
    # params.has_iface_buf; the default unbounded case traces it away).
    k2 = ib.order_keys().reshape(h, ki)
    if params.has_iface_buf:
        # The deterministic tail-drop ranking materializes an [H, ki, ki]
        # comparison cube per micro-step.  That is affordable only for
        # modest inbox slabs; fail loudly at trace time instead of
        # letting one configured host OOM/compile-explode a large world
        # (tools/opbench.py economics; ADVICE r3).
        if h * ki * ki > (1 << 28):
            raise ValueError(
                f"<host interfacebuffer> needs an [H={h}, k={ki}, k={ki}] "
                f"ranking cube (> 2^28 elements) in the compiled step; "
                f"shrink the inbox slab (--pool-slab) or drop the "
                f"interfacebuffer bound for worlds this large")
        cap = params.iface_buf_pkts
        bounded = cap > 0
        later = due[:, None, :] & (
            (t2[:, None, :] < t2[:, :, None]) |
            ((t2[:, None, :] == t2[:, :, None]) &
             (k2[:, None, :] < k2[:, :, None])))
        due_rank = jnp.sum(later & due[:, :, None], axis=2, dtype=I32)
        room = jnp.maximum(cap - hosts.rx_queued, 0)
        tail_drop = due & bounded[:, None] & (due_rank >= room[:, None])
        due = due & ~tail_drop
    else:
        tail_drop = jnp.zeros_like(due)

    # Tail drops are receive-side events a masked receiver must see in
    # its capture (they are exactly the overflow traffic an operator
    # enables capture to diagnose); only traced when a host configures
    # an interface buffer.
    if state.cap is not None and params.has_iface_buf:
        from .state import CAP_RDROP
        # Capture records carry GLOBAL host ids (identity off-mesh).
        rows_b = jnp.broadcast_to(
            host_ids(state, I32)[:, None], (h, ki))
        td_mask = (tail_drop & params.pcap_mask[:, None]).reshape(-1)
        blk = ib.blk
        state = _cap_append(
            state, td_mask,
            time_v=jnp.broadcast_to(tick_t[:, None], (h, ki)),
            src=blk[:, ICOL_SRC], dst=rows_b,
            sport=blk[:, ICOL_SPORT], dport=blk[:, ICOL_DPORT],
            proto=blk[:, ICOL_PROTO], flags=blk[:, ICOL_FLAGS],
            length=blk[:, ICOL_LEN],
            seq=_bitcast_i32_u32(blk[:, ICOL_SEQ]),
            ack=_bitcast_i32_u32(blk[:, ICOL_ACK]), kind=CAP_RDROP)

    st2 = jnp.where(due, STAGE_RX_QUEUED, st2)
    st2 = jnp.where(tail_drop, STAGE_FREE, st2)
    if params.pds_trail:
        status = jnp.where(due.reshape(-1),
                           ib.status | PDS_ROUTER_ENQUEUED, ib.status)
        status = jnp.where(tail_drop.reshape(-1),
                           status | PDS_ROUTER_DROPPED, status)
    else:
        status = ib.status
    hosts = hosts.replace(
        pkts_dropped_router=hosts.pkts_dropped_router +
        jnp.sum(tail_drop, axis=1),
        rx_queued=hosts.rx_queued + jnp.sum(due, axis=1, dtype=I32))

    if state.lineage is not None and params.has_iface_buf:
        # Interface-buffer tail drops end a traced packet's life at the
        # router: one DELIVER/qdisc span, then the freed slot's id
        # clears.
        td_f = tail_drop.reshape(-1)
        ln = state.lineage
        state = _lineage_append(
            state, td_f,
            time_v=jnp.broadcast_to(tick_t[:, None], (h, ki)).reshape(-1),
            id_v=ln.inbox_id,
            host_v=jnp.broadcast_to(host_ids(state, I32)[:, None],
                                    (h, ki)).reshape(-1),
            stage=SPAN_DELIVER, reason_v=LREASON_QDISC)
        state = state.replace(lineage=state.lineage.replace(
            inbox_id=jnp.where(td_f, 0, state.lineage.inbox_id)))

    # -- delivery rounds -----------------------------------------------------
    # Round 0 delivers each host's earliest queued packet at tick_t, like
    # the reference's one-event-per-pop.  Apps that declare `rx_batch` > 1
    # (bursty TCP fan-in) get extra rounds that may also consume arrivals
    # slightly in the FUTURE of tick_t -- legal as long as no other event
    # (timer, app wake, re-tick) lies between tick_t and the arrival, and
    # bounded by a small span so timers armed during the batch cannot be
    # outrun.  Each round uses the ARRIVAL's own time as its clock, so
    # ACK stamps, RTT samples, and timer arms are exact per packet.
    d_rounds = max(1, int(getattr(app, "rx_batch", 1)))
    ids = jnp.arange(ki, dtype=I32)[None, :]
    rows = jnp.arange(h, dtype=I32)
    # Packet SRC columns carry GLOBAL host ids; under a mesh the local row
    # index must be shifted before comparing against them (loopback test).
    rows_g = host_ids(state, I32)
    boot = tick_t < params.bootstrap_end
    if bw_dn is None:
        assert state.hoff is None, \
            "mesh runs must pass the window ctx (local bw slices)"
        bw_dn = netem_apply.rate(state.nm, params.bw_down_Bps)
    tokens, last = nic.refill(hosts.tokens_rx, hosts.last_refill_rx,
                              bw_dn, tick_t, active)
    hosts = hosts.replace(last_refill_rx=last)
    if d_rounds > 1:
        span = simtime.SIMTIME_ONE_MILLISECOND
        # Ordering invariant for future-delivery rounds: the bound uses
        # _aux_times evaluated at batch START, so any timer ARMED DURING
        # the batch must not be able to fire inside the remaining span --
        # i.e. every armable timer delay must exceed `span`.  A future
        # sub-ms timer (e.g. pacing) would silently reorder events; this
        # trace-time check turns that into a loud failure.
        from ..transport import tcp as _tcp_c
        _min_timer = min(_tcp_c.RTO_MIN, _tcp_c.DELACK_DELAY,
                         _tcp_c.TIMEWAIT_DELAY)
        assert _min_timer > span, (
            f"rx_batch future-delivery span ({span} ns) must stay below "
            f"every armable TCP timer delay (min {_min_timer} ns); a "
            f"timer armed mid-batch could otherwise fire inside the "
            f"batch and be outrun")
        # The megakernel path pre-computes _aux_times OUTSIDE the Pallas
        # kernel (it reads app state the kernel does not carry) and
        # passes the per-host slice in as `aux_bound`; both expressions
        # are evaluated at batch start, so they are bitwise identical.
        aux0 = (_aux_times(state, params, app)
                if aux_bound is None else aux_bound)
        bound = jnp.minimum(aux0, tick_t + span)
        bound = jnp.minimum(bound, window_end - 1)
    else:
        bound = tick_t

    delivered_n = jnp.zeros((h,), I32)
    state = state.replace(hosts=hosts)
    for r in range(d_rounds):
        limit = tick_t if r == 0 else bound
        hosts = state.hosts
        # Candidates: the queued backlog, plus (rounds > 0, unbounded
        # interface buffers only) in-flight arrivals within the bound.
        cand = st2 == STAGE_RX_QUEUED
        if r > 0 and not params.has_iface_buf:
            cand = cand | ((st2 == STAGE_IN_FLIGHT) &
                           (t2 <= limit[:, None]))
        cand = cand & active[:, None]
        tq = jnp.where(cand, t2, jnp.asarray(INV, I64))
        tmin = jnp.min(tq, axis=1)
        at_t = cand & (tq == tmin[:, None])
        kq = jnp.where(at_t, k2, jnp.asarray(INV, I64))
        kmin = jnp.min(kq, axis=1)
        at = at_t & (kq == kmin[:, None])
        col = jnp.min(jnp.where(at, ids, ki), axis=1)
        have = active & (col < ki) & (tmin <= limit)
        col = jnp.clip(col, 0, ki - 1)
        flat = rows * ki + col
        was_queued = have & (st2.reshape(-1)[flat] == STAGE_RX_QUEUED)
        t_eff = jnp.maximum(tick_t, jnp.where(have, tmin, 0))

        # One packed gather for every field of the chosen packet.
        row = ib.blk[flat]                              # [H, ICOLS]
        pkt = RxPkt(row, jnp.where(have, kmin, 0),
                    jnp.where(have, tmin, 0))

        # NIC rx: token bucket + CoDel (at the packet's own instant --
        # tokens accrue up to t_eff so a packet the reference would fund
        # at its arrival time is funded here too).
        if r > 0:
            tokens, last = nic.refill(tokens, hosts.last_refill_rx,
                                      bw_dn, t_eff, have)
            hosts = hosts.replace(last_refill_rx=last)
        size = _wire_bytes(pkt.proto, pkt.length).astype(I64) * nic.SCALE
        loop = pkt.src == rows_g
        free_pass = loop | boot
        funded = have & (free_pass | (tokens >= size))

        sojourn = jnp.maximum(t_eff - pkt.time, 0)
        rx_q_now = hosts.rx_queued
        backlog_after = rx_q_now - jnp.where(was_queued, 1, 0)
        hosts, drop = nic.codel_dequeue(hosts, funded & ~loop, t_eff,
                                        sojourn, backlog_after)
        deliver = funded & ~drop
        # Netem delivery gate: a packet reaching a DOWN destination is
        # lost at the interface (in-flight packets when the host crashed,
        # plus loopback sends that bypass the staging drop).  The slot
        # still frees (funded), so nothing strands.
        if state.nm is not None:
            up = alive if alive is not None else \
                netem_apply.alive(state.nm)
            nm_kill = deliver & ~up
            deliver = deliver & ~nm_kill
        else:
            nm_kill = None

        tokens = tokens - jnp.where(funded & ~free_pass, size, 0)
        hosts = hosts.replace(tokens_rx=tokens)

        # Inbox slot release + status trail for everything dequeued.
        oh = (ids == col[:, None])
        st2 = jnp.where(oh & funded[:, None], STAGE_FREE, st2)
        if params.pds_trail:
            fm = (oh & (funded & drop)[:, None]).reshape(-1)
            status = jnp.where(fm, status | PDS_ROUTER_ENQUEUED |
                               PDS_ROUTER_DROPPED, status)
            dm = (oh & deliver[:, None]).reshape(-1)
            status = jnp.where(dm, status | PDS_ROUTER_ENQUEUED |
                               PDS_RCV_SOCKET_PROCESSED, status)

        hosts = hosts.replace(
            rx_queued=rx_q_now -
            jnp.where(funded & was_queued, 1, 0).astype(I32),
            pkts_dropped_router=hosts.pkts_dropped_router +
            jnp.where(drop, 1, 0),
        )
        if nm_kill is not None:
            hosts = hosts.replace(
                pkts_dropped_inet=hosts.pkts_dropped_inet +
                jnp.where(nm_kill, 1, 0))
            state = state.replace(nm=state.nm.replace(
                killed=state.nm.killed + jnp.sum(nm_kill)))

        if r == d_rounds - 1:
            # Wake-ups: backlog remains -> re-tick now; starved -> when
            # tokens accrue for this packet.
            t_tok = tick_t + nic.time_until(size - tokens, bw_dn)
            t_res = jnp.where(
                have & ~funded, t_tok,
                jnp.where(funded & (hosts.rx_queued > 0), tick_t,
                          jnp.asarray(INV, I64)))
            hosts = hosts.replace(
                t_resume=jnp.minimum(hosts.t_resume, t_res))

        state = state.replace(
            inbox=ib.replace(stage=st2.reshape(-1), status=status),
            hosts=hosts)
        ib = state.inbox

        if state.lineage is not None:
            # Every funded dequeue ends this hop: delivered (reason 0),
            # CoDel/router-dropped (qdisc), or killed at a down host.
            # Read the slot's id before the freed slot clears it.
            ln = state.lineage
            lid_h = jnp.where(have, ln.inbox_id[flat], 0)
            reason_h = jnp.where(drop, LREASON_QDISC, 0)
            if nm_kill is not None:
                reason_h = jnp.where(nm_kill, LREASON_HOST_DOWN, reason_h)
            state = _lineage_append(state, funded, time_v=t_eff,
                                    id_v=lid_h, host_v=rows_g,
                                    stage=SPAN_DELIVER, reason_v=reason_h)
            freed = (oh & funded[:, None]).reshape(-1)
            state = state.replace(lineage=state.lineage.replace(
                inbox_id=jnp.where(freed, 0, state.lineage.inbox_id)))

        # Event log (traced away when disabled).  Records carry GLOBAL
        # host ids (rows_g == rows off-mesh).
        if state.log is not None:
            if r == 0:
                rows2 = jnp.broadcast_to(rows_g[:, None],
                                         (h, ki)).reshape(-1)
                src_col = state.inbox.blk[:, ICOL_SRC]
                t_flat = jnp.broadcast_to(tick_t[:, None],
                                          (h, ki)).reshape(-1)
                state = _log_append(state, tail_drop.reshape(-1),
                                    LOG_DROP_TAIL, LOG_WARNING, t_flat,
                                    rows2, src_col)
            state = _log_append(state, drop, LOG_DROP_ROUTER, LOG_WARNING,
                                t_eff, rows_g, pkt.src)
            if nm_kill is not None:
                state = _log_append(state, nm_kill, LOG_NETEM_DOWN,
                                    LOG_WARNING, t_eff, rows_g, pkt.src)
            state = _log_append(state, deliver, LOG_DELIVER, LOG_DEBUG,
                                t_eff, rows_g, pkt.src)

        # Receive-direction capture (reference captures both directions
        # per interface, network_interface.c:337-373,415-418): delivered
        # packets AND received-but-router-dropped ones, at the receive
        # instant.
        if state.cap is not None:
            from .state import CAP_DELIVER, CAP_RDROP
            rec_rx = (deliver | drop) & params.pcap_mask
            state = _cap_append(
                state, rec_rx, time_v=t_eff, src=pkt.src, dst=rows_g,
                sport=pkt.sport, dport=pkt.dport, proto=pkt.proto,
                flags=pkt.flags, length=pkt.length, seq=pkt.seq,
                ack=pkt.ack,
                kind=jnp.where(drop, CAP_RDROP, CAP_DELIVER))

        # Transport delivery (each round stamps at the arrival's time).
        udp_mask = deliver & (pkt.proto == PROTO_UDP)
        socks, _accepted = udp_mod.deliver(state.socks, udp_mask, pkt.src,
                                           pkt.sport, pkt.dport,
                                           pkt.length, pkt.payload_id)
        state = state.replace(socks=socks)
        if _uses_tcp(app):
            tcp_mask = deliver & (pkt.proto == PROTO_TCP)
            reply_slot = emit.SLOT_RX_REPLY if r == 0 \
                else emit.NUM_SLOTS + r - 1

            def _arrivals(args, _pkt=pkt, _mask=tcp_mask, _t=t_eff,
                          _slot=reply_slot):
                s_, e_ = args
                return tcp_mod.process_arrivals(s_, params, e_, _t, _pkt,
                                                _mask, reply_slot=_slot)

            if params.kernel_diet:
                # KERNEL-DIET GATE: rounds with no TCP arrival anywhere
                # skip the whole per-round arrival machine (socket
                # match, ACK clocking, reassembly).  Exact skip: every
                # write in process_arrivals is masked by (a subset of)
                # tcp_mask, and emit.put under a false mask is the
                # identity.
                state, em = jax.lax.cond(jnp.any(tcp_mask), _arrivals,
                                         lambda a: a, (state, em))
            else:
                state, em = _arrivals((state, em))

        hosts = state.hosts
        hosts = hosts.replace(
            pkts_recv=hosts.pkts_recv + jnp.where(deliver, 1, 0),
            bytes_recv=hosts.bytes_recv + jnp.where(deliver, pkt.length,
                                                    0),
        )
        state = state.replace(hosts=hosts)
        delivered_n = delivered_n + jnp.where(deliver, 1, 0)
        if r == 0:
            t_post = jnp.where(deliver, t_eff, tick_t)
        else:
            t_post = jnp.where(deliver, jnp.maximum(t_post, t_eff), t_post)
    return state, em, delivered_n, t_post


# ---------------------------------------------------------------------------
# Emission staging (packets leave their source this tick)
# ---------------------------------------------------------------------------


def _route(params, vs, vd, src, ctr):
    """Packed routing lookup + per-packet jitter draw: ONE row gather for
    (latency, jitter, reliability).  Jitter perturbs latency uniformly in
    +/- the pair's amplitude, keyed by (src, per-src counter) so the same
    packet draws the same perturbation wherever its departure is computed
    (reference carries per-edge jitter, topology.c:81-105).

    Returns (latency_ns i64, reliability f32)."""
    if not params.has_jitter:
        # STATIC no-jitter world: the perturbation is provably zero
        # (jit == 0 makes the where() drop delta), so the keyed-uniform
        # hash chain traces away entirely and the routing gather narrows
        # to the leading (lat, rel) columns.  RNG draws are functionally
        # keyed -- skipping one consumes nothing -- so this is bitwise-
        # neutral.
        lat, rel = params.route_narrow(vs, vd)
        return jnp.maximum(lat, simtime.SIMTIME_ONE_NANOSECOND), rel
    lat, jit, rel = params.route(vs, vd)
    key = rng.purpose_key(params.seed_key, rng.PURPOSE_JITTER)
    u = rng.keyed_uniform(key, src, ctr.astype(jnp.uint32),
                          (ctr >> 32).astype(jnp.uint32))
    delta = ((2.0 * u - 1.0) * jit.astype(jnp.float32)).astype(I64)
    lat = jnp.maximum(lat + jnp.where(jit > 0, delta, 0),
                      simtime.SIMTIME_ONE_NANOSECOND)
    return lat, rel


def _free_slot_pick(free2, rank2):
    """Scatter/sort-free slab allocation: `free2` [H,K] marks free slots,
    `rank2` [H,E] is each emission's 0-based ordinal among its host's
    allocations this tick.  Returns [H,E] slot columns such that the r-th
    allocation takes the r-th free slot in ascending index order (callers
    must mask by rank2 < n_free).  Pure cumsum + one-hot -- an argsort
    here costs milliseconds in host-major layout."""
    h, k = free2.shape
    pos = jnp.cumsum(free2, axis=1) - 1           # rank of each free slot
    ids = jnp.arange(k, dtype=I32)[None, None, :]
    onehot = free2[:, None, :] & (pos[:, None, :] == rank2[:, :, None]) & \
        (rank2 >= 0)[:, :, None]
    return jnp.sum(jnp.where(onehot, ids, 0), axis=2, dtype=I32)


def _patched_rows(em, src2, ctr2, time_v, send_t, lat, stage_v, status_v):
    """[H,E,C+2] staging rows: the emission block with the engine-owned
    columns patched in (SRC, TIME, CTR, TS, LAT) plus the merge-scratch
    STAGE/STATUS columns.  Pure slicing + stacking; one concatenate.
    Width-adaptive: a narrow (TCP-free) emission block has no TS/TSE/SACK
    columns to carry, so those pieces vanish from the concatenate and the
    merge downstream shrinks with them."""
    eb = em.blk
    base = ext_base(eb.shape[2])

    def c(x):
        return x[:, :, None].astype(I32)

    pieces = [
        c(src2),                                   # ICOL_SRC
        eb[:, :, 1:ICOL_TIME_LO],                  # SPORT..PAYLOAD
        c(enc_lo(time_v)), c(enc_hi(time_v)),      # ICOL_TIME_*
        c(enc_lo(ctr2)), c(enc_hi(ctr2)),          # ICOL_CTR_*
    ]
    if base >= ICOLS:
        pieces += [
            c(enc_lo(send_t)), c(enc_hi(send_t)),  # ICOL_TS_*
            eb[:, :, ICOL_TSE_LO:base + 1],        # TSE, SACK, DST
        ]
    else:
        pieces += [eb[:, :, base + OEXT_DST:base + OEXT_DST + 1]]
    pieces += [
        c(enc_lo(lat)), c(enc_hi(lat)),            # OEXT_LAT_*
        eb[:, :, base + OEXT_PRIO:base + OEXT_PRIO + 1],
        c(stage_v), c(status_v),                   # stage/status scratch
    ]
    return jnp.concatenate(pieces, axis=2)


def _stage_emissions(state: SimState, params, em: emit.Emissions, tick_t,
                     active, app, bw_up=None):
    """Assign pkt_ids, apply routing latency + reliability drops, and
    merge staged emissions into free OUTBOX slots of the emitting host's
    own slab -- direct to IN_FLIGHT when the tx token bucket covers them,
    else parked in TX_QUEUED.  Same-host loopback packets go straight
    into the sender's inbox slab with a 1ns delay (reference local path,
    network_interface.c:548-555).

    The reference equivalent is the interface send path + worker_sendPacket
    (/root/reference/src/main/host/network_interface.c:466-540,
    src/main/core/worker.c:243-304): qdisc select under token budget,
    reliability draw, latency lookup, push event to the destination host
    queue.  The bootstrap period bypasses bandwidth
    (network_interface.c:432-434,522)."""
    pool, hosts = state.pool, state.hosts
    h, e = em.valid.shape
    p0 = pool.capacity
    ko = p0 // h

    valid = em.valid
    rank = jnp.cumsum(valid, axis=1) - 1              # [H,E] within-host order
    counts = jnp.sum(valid, axis=1).astype(I64)       # [H]
    ctr = hosts.send_ctr                               # [H]

    # GLOBAL source ids: they key the jitter/drop RNG draws and ride the
    # packet SRC column, so they must be mesh-invariant (identity arange
    # off-mesh).
    src2 = jnp.broadcast_to(host_ids(state, I32)[:, None], (h, e))
    ctr2 = ctr[:, None] + rank

    if state.lineage is not None:
        # Lineage sampling + trace-id assignment, functionally keyed by
        # (src, per-src emission counter): any mesh shape samples -- and
        # ids -- exactly the same packets.  The threshold rides as
        # TRACED data (state.lineage.rate_x1p32), so one compiled graph
        # serves every sample rate.  Ids are odd-ended 31-bit positives
        # ((bits >> 1) | 1), so 0 stays the "untraced" sentinel;
        # collisions are possible and harmless (docs/observability.md).
        lkey = rng.purpose_key(params.seed_key, rng.PURPOSE_LINEAGE)
        lc_lo = ctr2.astype(jnp.uint32)
        lc_hi = (ctr2 >> 32).astype(jnp.uint32)
        sampled = valid & (rng.keyed_bits(lkey, src2, lc_lo, lc_hi)
                           <= state.lineage.rate_x1p32)
        lid2 = jnp.where(sampled, ((rng.keyed_bits(lkey, lc_lo, lc_hi, src2)
                                    >> jnp.uint32(1)) | jnp.uint32(1))
                         .astype(I32), 0)
    else:
        sampled = None
        lid2 = None

    # Routing: latency (+ per-packet jitter) + reliability, loopback
    # shortcut.  vs is the emitting host's own vertex -- a broadcast, not
    # a gather.  host_vertex stays replicated under the mesh (em.dst holds
    # global ids), so the own-vertex broadcast slices it to local rows.
    vs = jnp.broadcast_to(_lrows(state, params.host_vertex)[:, None],
                          (h, e))
    vd = params.host_vertex[jnp.clip(em.dst, 0, params.host_vertex.shape[0] - 1)]
    lat, rel = _route(params, vs, vd, src2, ctr2)
    if state.nm is not None:
        # Fault overlay BEFORE the loopback override: blocked pairs
        # (endpoint down / link down / partitioned) get rel 0.0 and die
        # through the ordinary reliability drop below; loopback stays
        # exempt from link faults.
        rel_base = rel
        lat, rel = netem_apply.route_overlay(state.nm, src2, em.dst,
                                             lat, rel)
    loop = em.dst == src2
    lat = jnp.where(loop, simtime.SIMTIME_ONE_NANOSECOND, lat)
    rel = jnp.where(loop, 1.0, rel)

    if params.has_loss or state.nm is not None:
        drop_key = rng.purpose_key(params.seed_key,
                                   rng.PURPOSE_PACKET_DROP)
        u = rng.keyed_uniform(drop_key, src2, ctr2.astype(jnp.uint32),
                              (ctr2 >> 32).astype(jnp.uint32))
        dropped = valid & (u >= rel)
    else:
        # STATIC loss-free world with no fault overlay: every rel is
        # exactly 1.0 and keyed_uniform draws in [0, 1), so u >= rel can
        # never hold -- the whole drop hash chain traces away (the
        # keyed draw consumes nothing, so skipping it is bitwise-
        # neutral).
        dropped = jnp.zeros_like(valid)
    if state.nm is not None:
        # Injected-fault kills: dropped here but the BASE draw would have
        # survived -- exactly the packets netem killed (blocked pairs or
        # added loss), separated from baseline wire unreliability.
        nm_kill = dropped & (u < rel_base)
        state = state.replace(nm=state.nm.replace(
            killed=state.nm.killed + jnp.sum(nm_kill)))
    live = valid & ~dropped
    lb = live & loop if _may_loopback(app) else jnp.zeros_like(live)
    nl = live & ~lb

    # --- outbox slab allocation for non-loopback emissions: free slots in
    # ascending index order; the r-th live emission takes the r-th free
    # slot.  (Allocation order is monotone across a window's micro-steps
    # because outbox slots free only at boundaries -- see _exchange.)
    free = (pool.stage == STAGE_FREE).reshape(h, ko)
    ids = jnp.arange(ko, dtype=I32)[None, :]
    n_free = jnp.sum(free, axis=1)
    nl_rank = jnp.where(nl, jnp.cumsum(nl, axis=1) - 1, -1)  # [H,E] 0-based
    within = _free_slot_pick(free, nl_rank)
    have_slot = nl & (nl_rank >= 0) & (nl_rank < n_free[:, None])
    placed = have_slot                                  # outbox-placed

    send_t = jnp.where(em.t_send > 0, em.t_send,
                       jnp.broadcast_to(tick_t[:, None], (h, e)))
    arr_t = send_t + lat

    # --- NIC tx admission: direct-admit under the token budget, else park
    # in TX_QUEUED for _tx_drain (FIFO is preserved because any backlog
    # forces parking).
    if bw_up is None:
        assert state.hoff is None, \
            "mesh runs must pass the window ctx (local bw slices)"
        bw_up = netem_apply.rate(state.nm, params.bw_up_Bps)
    tokens, last = nic.refill(hosts.tokens_tx, hosts.last_refill_tx,
                              bw_up, tick_t, active)
    sizes = _wire_bytes(em.proto, em.length).astype(I64) * nic.SCALE
    sizes_nl = jnp.where(placed, sizes, 0)
    prefix = jnp.cumsum(sizes_nl, axis=1)
    boot2 = (tick_t < params.bootstrap_end)[:, None]
    ok_budget = (hosts.tx_queued == 0)[:, None] & (prefix <= tokens[:, None])
    admit = placed & (boot2 | ok_budget)
    spent = jnp.sum(jnp.where(admit & ~boot2, sizes, 0), axis=1)
    tokens = tokens - spent
    parked = placed & ~admit
    # A parked packet stamped in the future (rx_batch reply lanes) is
    # invisible to _select_tx_slab until its send instant; arm a wake
    # there or it strands until an unrelated event ticks the host.
    t_park = jnp.min(jnp.where(parked, send_t, jnp.asarray(INV, I64)),
                     axis=1)
    hosts = hosts.replace(
        tokens_tx=tokens, last_refill_tx=last,
        t_resume=jnp.minimum(hosts.t_resume, t_park),
        tx_queued=hosts.tx_queued + jnp.sum(parked, axis=1).astype(I32))

    stage_v = jnp.where(admit, STAGE_IN_FLIGHT, STAGE_TX_QUEUED)
    time_v = jnp.where(admit, arr_t, send_t)
    status_v = jnp.where(
        admit,
        PDS_SND_CREATED | PDS_SND_INTERFACE_SENT | PDS_INET_SENT,
        PDS_SND_CREATED)

    # --- scatter-free merge into the outbox slab rows: ONE one-hot merge
    # of the whole packed row (round 4 did ~21 per-field merges here; the
    # step cost at small H is kernel-count-bound, see PERF.md).
    oh = (within[:, :, None] == ids[:, None, :]) & have_slot[:, :, None]
    hit = jnp.any(oh, axis=1)

    pc = pool.blk.shape[1]                             # world block width
    val3 = _patched_rows(em, src2, ctr2, time_v, send_t, lat,
                         stage_v, status_v)            # [H,E,pc+2]
    v = jnp.sum(jnp.where(oh[:, :, :, None], val3[:, :, None, :], 0),
                axis=1, dtype=I32)                     # [H,Ko,pc+2]
    blk3 = pool.blk.reshape(h, ko, pc)
    hit3 = hit[:, :, None]
    pool = pool.replace(
        blk=jnp.where(hit3, v[:, :, :pc], blk3).reshape(-1, pc),
        stage=jnp.where(hit, v[:, :, pc],
                        pool.stage.reshape(h, ko)).reshape(-1),
        status=jnp.where(hit, v[:, :, pc + 1],
                         pool.status.reshape(h, ko)).reshape(-1)
        if params.pds_trail else pool.status,
        time=jnp.where(hit, dec_i64(v[:, :, ICOL_TIME_LO],
                                    v[:, :, ICOL_TIME_HI]),
                       pool.time.reshape(h, ko)).reshape(-1),
    )
    state = state.replace(pool=pool, hosts=hosts)

    if state.lineage is not None:
        # Trace ids enter the outbox side array under the SAME one-hot
        # merge as the packed rows: every freshly claimed slot gets its
        # emission's id (0 when untraced), untouched slots keep theirs.
        ln = state.lineage
        lv = jnp.sum(jnp.where(oh, lid2[:, :, None], 0), axis=1, dtype=I32)
        state = state.replace(lineage=ln.replace(
            pool_id=jnp.where(hit, lv,
                              ln.pool_id.reshape(h, ko)).reshape(-1)))

    # --- loopback: straight into the sender's own inbox slab (row-local
    # allocation; the block write is an [H*E]-row scatter, traced away
    # when the app never loops back).
    lb_placed = jnp.zeros_like(lb)
    if _may_loopback(app):
        state, lb_placed = _loopback_insert(state, params, em, lb, src2,
                                            ctr2, send_t, lin_ids=lid2)

    all_placed = placed | lb_placed
    overflow = jnp.any(live & ~all_placed & ~lb) | jnp.any(lb & ~lb_placed)
    sent_bytes = jnp.sum(jnp.where(all_placed, em.length, 0),
                         axis=1).astype(I64)
    hosts = state.hosts
    hosts = hosts.replace(
        send_ctr=ctr + counts,
        pkts_sent=hosts.pkts_sent + jnp.sum(all_placed, axis=1),
        bytes_sent=hosts.bytes_sent + sent_bytes,
        pkts_dropped_inet=hosts.pkts_dropped_inet + jnp.sum(dropped, axis=1),
        pkts_dropped_pool=hosts.pkts_dropped_pool +
        jnp.sum(live & ~all_placed, axis=1),
    )
    err = state.err | jnp.where(overflow, ERR_POOL_OVERFLOW,
                                0).astype(jnp.int32)
    state = state.replace(hosts=hosts, err=err)

    # Event log (traced away when disabled).
    if state.log is not None:
        hostf = src2.reshape(-1)
        timef = send_t.reshape(-1)
        dstf = em.dst.reshape(-1)
        state = _log_append(state, dropped.reshape(-1), LOG_DROP_INET,
                            LOG_WARNING, timef, hostf, dstf)
        state = _log_append(state, (live & ~all_placed).reshape(-1),
                            LOG_DROP_POOL, LOG_WARNING, timef, hostf, dstf)
        state = _log_append(state, all_placed.reshape(-1), LOG_SEND,
                            LOG_DEBUG, timef, hostf, dstf)

    if state.lineage is not None:
        # One EMIT span per sampled emission -- with the death reason
        # when it never left the source (reliability draw, netem block,
        # slab overflow) -- then the hop the survivors took: parked
        # under the token bucket (STAGE), straight onto the wire (TX),
        # or the loopback shortcut (LINK).
        reason2 = jnp.where(dropped, LREASON_LOSS, 0)
        if state.nm is not None:
            br = netem_apply.block_reason(state.nm, src2, em.dst)
            reason2 = jnp.where(dropped & (br > 0), br, reason2)
        reason2 = jnp.where(live & ~all_placed, LREASON_POOL, reason2)
        lhost = src2.reshape(-1)
        ltime = send_t.reshape(-1)
        lidf = lid2.reshape(-1)
        state = _lineage_append(state, sampled.reshape(-1), time_v=ltime,
                                id_v=lidf, host_v=lhost, stage=SPAN_EMIT,
                                reason_v=reason2.reshape(-1))
        state = _lineage_append(state, (parked & sampled).reshape(-1),
                                time_v=ltime, id_v=lidf, host_v=lhost,
                                stage=SPAN_STAGE)
        state = _lineage_append(state, (admit & sampled).reshape(-1),
                                time_v=ltime, id_v=lidf, host_v=lhost,
                                stage=SPAN_TX)
        state = _lineage_append(state, (lb_placed & sampled).reshape(-1),
                                time_v=ltime, id_v=lidf, host_v=lhost,
                                stage=SPAN_LINK)
        state = state.replace(lineage=state.lineage.replace(
            n_assigned=state.lineage.n_assigned
            + jnp.sum(sampled).astype(I64)))

    # Packet capture (PCAP analog; only traced when a CaptureRing is
    # installed): record every placed emission at send time.
    if state.cap is not None:
        from .state import CAP_SEND
        # Send direction records for marked SOURCES only; a marked
        # destination's inbound view is the CAP_DELIVER/CAP_RDROP records
        # written at delivery (_rx_phase) -- a dst-gated send record here
        # would never be exported and only pressure the ring.
        rec = all_placed & params.pcap_mask[:, None]
        state = _cap_append(
            state, rec.reshape(-1), time_v=send_t, src=src2, dst=em.dst,
            sport=em.sport, dport=em.dport, proto=em.proto, flags=em.flags,
            length=em.length, seq=em.seq, ack=em.ack, kind=CAP_SEND)
    return state, all_placed


def _loopback_insert(state: SimState, params, em, lb, src2, ctr2,
                     send_t, lin_ids=None):
    """Insert loopback emissions into the sender's own inbox slab.
    Arrival = send + 1ns (reference network_interface.c:548-555).
    `lin_ids` [H,E] carries lineage trace ids into the claimed slots'
    inbox_id rows (present exactly when the tracer is installed)."""
    ib = state.inbox
    h, e = lb.shape
    p1 = ib.capacity
    ki = p1 // h

    free2 = (ib.stage == STAGE_FREE).reshape(h, ki)
    n_free = jnp.sum(free2, axis=1)
    lb_rank = jnp.where(lb, jnp.cumsum(lb, axis=1) - 1, -1)
    within = _free_slot_pick(free2, lb_rank)
    ok = lb & (lb_rank >= 0) & (lb_rank < n_free[:, None])
    # src2 carries GLOBAL ids (they ride the SRC column); slab addressing
    # is local, so shift back under a mesh.
    src_l = src2 if state.hoff is None \
        else src2 - state.hoff.astype(I32)
    islot = jnp.where(ok, src_l * ki + within, p1).reshape(-1)

    # Packed rows in inbox layout: the emission block's first ICOLS
    # columns with SRC/TIME/CTR/TS patched (arrival = send + 1ns).
    arr = send_t + simtime.SIMTIME_ONE_NANOSECOND

    def c(x):
        return x[:, :, None].astype(I32)

    ic = ib.blk.shape[1]          # ICOLS, or NCOLS_UDP for TCP-free worlds
    pieces = [
        c(src2),
        em.blk[:, :, 1:ICOL_TIME_LO],
        c(enc_lo(arr)), c(enc_hi(arr)),
        c(enc_lo(ctr2)), c(enc_hi(ctr2)),
    ]
    if ic >= ICOLS:
        pieces += [c(enc_lo(send_t)), c(enc_hi(send_t)),
                   em.blk[:, :, ICOL_TSE_LO:ICOLS]]
    vals = jnp.concatenate(pieces, axis=2).reshape(-1, ic)

    pds = PDS_SND_CREATED | PDS_SND_INTERFACE_SENT | PDS_INET_SENT
    ib = ib.replace(
        blk=ib.blk.at[islot].set(vals, mode="drop"),
        stage=ib.stage.at[islot].set(STAGE_IN_FLIGHT, mode="drop"),
        status=ib.status.at[islot].set(pds, mode="drop")
        if params.pds_trail else ib.status,
    )
    state = state.replace(inbox=ib)
    if state.lineage is not None and lin_ids is not None:
        ln = state.lineage
        state = state.replace(lineage=ln.replace(
            inbox_id=ln.inbox_id.at[islot].set(
                jnp.where(ok, lin_ids, 0).reshape(-1), mode="drop")))
    return state, ok


def _select_tx_slab(pool, tick_t, active, h):
    """Pick per SOURCE host the earliest due TX_QUEUED packet.

    Two-phase row-min (time, then within-slab index) over the source's
    own slab -- deterministic and free of any packed-key time bound.
    Returns ([H] pool index or -1, [P] chosen mask)."""
    p = pool.capacity
    k = p // h
    stage2 = pool.stage.reshape(h, k)
    time2 = pool.time.reshape(h, k)
    due = (stage2 == STAGE_TX_QUEUED) & (time2 <= tick_t[:, None]) & \
        active[:, None]
    td = jnp.where(due, time2, jnp.asarray(INV, I64))
    tmin = jnp.min(td, axis=1)
    ids = jnp.arange(k, dtype=I32)[None, :]
    at = due & (td == tmin[:, None])
    j = jnp.min(jnp.where(at, ids, k), axis=1)
    have = j < k
    j = jnp.clip(j, 0, k - 1)
    slot_of_host = jnp.where(have, jnp.arange(h, dtype=I32) * k + j, -1)
    chosen = ((ids == j[:, None]) & have[:, None]).reshape(-1)
    return slot_of_host, chosen


def _tx_drain(state: SimState, params, tick_t, active, bw_up=None):
    """Drain one parked TX_QUEUED packet per host onto the wire, gated by
    the upstream token bucket (reference _networkinterface_sendPackets,
    network_interface.c:519-561: dequeue under token budget, then
    router_forward -> worker_sendPacket).

    KERNEL-DIET GATE: apps that never park (unbounded bandwidth, or
    sends always under budget) pay only a cheap any() here instead of
    replaying the slab row-min + packed gather every micro-step.  The
    skip is exact -- with no TX_QUEUED packet anywhere the body reduces
    to the bare token refill (have/funded/chosen all false leave pool,
    tx_queued and t_resume bitwise untouched), and the refill itself
    stays unconditional so token/timestamp state never diverges."""
    if bw_up is None:
        assert state.hoff is None, \
            "mesh runs must pass the window ctx (local bw slices)"
        bw_up = netem_apply.rate(state.nm, params.bw_up_Bps)
    if not params.kernel_diet:
        return _tx_drain_body(state, params, tick_t, active, bw_up)

    def _refill_only(s):
        tokens, last = nic.refill(s.hosts.tokens_tx,
                                  s.hosts.last_refill_tx,
                                  bw_up, tick_t, active)
        return s.replace(hosts=s.hosts.replace(tokens_tx=tokens,
                                               last_refill_tx=last))

    return jax.lax.cond(
        jnp.any(state.pool.stage == STAGE_TX_QUEUED),
        lambda s: _tx_drain_body(s, params, tick_t, active, bw_up),
        _refill_only, state)


def _tx_drain_body(state: SimState, params, tick_t, active, bw_up,
                   skip_refill=False):
    pool, hosts = state.pool, state.hosts
    h = hosts.num_hosts

    slot_of_host, chosen = _select_tx_slab(pool, tick_t, active, h)
    have = slot_of_host >= 0
    slot = jnp.clip(slot_of_host, 0, pool.capacity - 1)

    if skip_refill:
        # Megakernel path: _stage_emissions already refilled the tx
        # bucket at this same instant, so a second refill accrues
        # exactly 0 tokens (dt=0; tokens never exceed capacity).
        tokens, last = hosts.tokens_tx, hosts.last_refill_tx
    else:
        tokens, last = nic.refill(hosts.tokens_tx, hosts.last_refill_tx,
                                  bw_up, tick_t, active)
    # One packed row gather for every field of the chosen packet.
    row = pool.blk[slot]                                 # [H, C]
    size = _wire_bytes(row[:, ICOL_PROTO], row[:, ICOL_LEN]).astype(I64) \
        * nic.SCALE
    boot = tick_t < params.bootstrap_end
    funded = have & (boot | (tokens >= size))
    tokens = tokens - jnp.where(funded & ~boot, size, 0)

    # Departure: arrival = now + the latency fixed at staging (which
    # already includes this packet's keyed jitter draw, so departure needs
    # no routing lookup; the reliability draw also happened at staging, so
    # loss is independent of queueing).
    eb = ext_base(pool.blk.shape[1])
    arr = tick_t + dec_i64(row[:, eb + OEXT_LAT_LO], row[:, eb + OEXT_LAT_HI])
    ko = pool.capacity // h
    funded_b = jnp.broadcast_to(funded[:, None], (h, ko)).reshape(-1)
    arr_b = jnp.broadcast_to(arr[:, None], (h, ko)).reshape(-1)
    chosen_dep = chosen & funded_b
    pool = pool.replace(
        stage=jnp.where(chosen_dep, STAGE_IN_FLIGHT, pool.stage),
        time=jnp.where(chosen_dep, arr_b, pool.time),
        status=jnp.where(chosen_dep,
                         pool.status | PDS_SND_INTERFACE_SENT | PDS_INET_SENT,
                         pool.status) if params.pds_trail else pool.status,
    )

    hosts = hosts.replace(
        tokens_tx=tokens, last_refill_tx=last,
        tx_queued=hosts.tx_queued - jnp.where(funded, 1, 0).astype(I32))

    t_tok = tick_t + nic.time_until(size - tokens, bw_up)
    t_res = jnp.where(
        have & ~funded, t_tok,
        jnp.where(funded & (hosts.tx_queued > 0), tick_t,
                  jnp.asarray(INV, I64)))
    hosts = hosts.replace(t_resume=jnp.minimum(hosts.t_resume, t_res))
    state = state.replace(pool=pool, hosts=hosts)
    if state.lineage is not None:
        # A parked packet departing the NIC: the row stays in place
        # (stage flip only), so the side array needs no move -- just the
        # TX hop span at the drain instant.
        state = _lineage_append(state, funded, time_v=tick_t,
                                id_v=state.lineage.pool_id[slot],
                                host_v=host_ids(state, I32), stage=SPAN_TX)
    return state


# ---------------------------------------------------------------------------
# Micro-step and loops
# ---------------------------------------------------------------------------


def _window_ctx(state: SimState, params):
    """Window-invariant inputs of the micro-step, hoisted out of the
    inner while body: the netem overlay only changes at window
    boundaries (netem_apply.advance runs before the window's ticks), so
    the effective NIC rates and the host-liveness mask are constant
    across every micro-step of a window.  Returns (bw_up, bw_dn, alive);
    alive is None for worlds without a fault overlay.

    Under a mesh the bw params arrive pre-sliced to local rows (shard_map
    in_specs) while the nm overlay stays replicated, so the overlay
    factors are sliced to match (netem_apply.rate_rows/alive_rows)."""
    if state.hoff is None:
        return (netem_apply.rate(state.nm, params.bw_up_Bps),
                netem_apply.rate(state.nm, params.bw_down_Bps),
                None if state.nm is None else netem_apply.alive(state.nm))
    h = state.hosts.num_hosts
    return (netem_apply.rate_rows(state.nm, params.bw_up_Bps,
                                  state.hoff, h),
            netem_apply.rate_rows(state.nm, params.bw_down_Bps,
                                  state.hoff, h),
            None if state.nm is None
            else netem_apply.alive_rows(state.nm, state.hoff, h))


def _microstep_core(state: SimState, params, app, t_h, window_end,
                    ctx=None):
    """Advance every host's earliest pending event (< window_end)."""
    from ..transport import tcp as tcp_mod

    if ctx is None:
        ctx = _window_ctx(state, params)
    bw_up, bw_dn, alive = ctx

    h = state.hosts.num_hosts
    if _uses_tcp(app) and state.inbox.blk.shape[1] < ICOLS:
        raise ValueError(
            "this world's inbox was built narrow (uses_tcp=False in "
            "make_sim_state) but the app uses TCP; TCP segments need the "
            "TS/SACK inbox columns")
    active = t_h < window_end
    tick_t = jnp.where(active, t_h, window_end)

    # Active hosts' resume flags are re-armed by this tick's phases;
    # inactive hosts keep theirs (token-accrual wake-ups must survive).
    state = state.replace(
        hosts=state.hosts.replace(t_resume=jnp.where(
            active, jnp.asarray(INV, I64), state.hosts.t_resume)))

    if _uses_tcp(app):
        # Extra reply lanes for rx_batch delivery rounds beyond the first
        # (each round's TCP reply needs its own emission slot).
        n_lanes = emit.NUM_SLOTS + max(0, int(getattr(app, "rx_batch", 1))
                                       - 1)
    else:
        # Pure-UDP apps may batch several sends per tick into extra lanes
        # (app_tx_lanes), each stamped with its own t_send.
        n_lanes = emit.SLOT_APP + max(1, int(getattr(app, "app_tx_lanes",
                                                     1)))
    # The staging block matches the world's outbox width: TCP-free worlds
    # stage 18-column rows (no TS/TSE/SACK), shrinking both emit.put's
    # row stack and the staging merge (PERF.md round 7).
    em = emit.empty(h, n_lanes, cols=state.pool.blk.shape[1])

    # Phase A: arrivals through the destination slab (router queue, NIC rx
    # tokens + CoDel, transport delivery).
    state, em, delivered_n, t_post = _rx_phase(state, params, em, tick_t,
                                               active, app, window_end,
                                               bw_dn=bw_dn, alive=alive)

    # Phases B-D run at the POST-BATCH per-host instant: when rx_batch
    # rounds consumed arrivals slightly after tick_t, every downstream
    # effect (timer arming, app reaction, transmitted segments) is
    # stamped at-or-after its cause.  The batching bound guarantees no
    # timer/app event was due inside (tick_t, t_post], so ordering is
    # preserved.  For rx_batch=1 apps t_post == tick_t exactly.
    if _uses_tcp(app):
        state, em = tcp_mod.run_timers(state, params, em, t_post, active)

    # Phase C: application tick.
    if app is not None:
        if getattr(app, "wants_window_end", False):
            # The window bound lets the app pre-emit future sends that
            # provably precede its next possible arrival (send batching).
            state, em = app.on_tick(state, params, em, t_post, active,
                                    window_end=window_end)
        else:
            state, em = app.on_tick(state, params, em, t_post, active)

    # Phase D: TCP transmission, merge staged emissions into the outbox
    # (direct-admit or park) or own inbox (loopback), then drain parked
    # packets through the tx bucket.
    if _uses_tcp(app):
        state, em = tcp_mod.transmit(state, params, em, t_post, active)
    state, placed = _stage_emissions(state, params, em, t_post, active,
                                     app, bw_up=bw_up)
    state = _tx_drain(state, params, t_post, active, bw_up=bw_up)

    # Virtual CPU accounting (reference cpu_updateTime + cpu_addDelay,
    # cpu.c:77-108): every delivered packet and staged emission costs
    # cpu_ns_per_event.  Costs accumulate exactly; precision rounding
    # happens where the backlog is consulted (_cpu_clamp), so per-step
    # increments smaller than the precision are never lost.
    cpu_on = params.cpu_ns_per_event > 0
    events = delivered_n.astype(I64) + \
        jnp.sum(em.valid, axis=1).astype(I64)
    cost = params.cpu_ns_per_event * events
    avail = jnp.maximum(state.hosts.cpu_avail, tick_t)
    new_avail = jnp.where(cpu_on & active, avail + cost,
                          state.hosts.cpu_avail)
    state = state.replace(
        hosts=state.hosts.replace(cpu_avail=new_avail),
        n_steps=state.n_steps + 1,
        n_events=state.n_events + jnp.sum(events),
    )
    return state


def microstep(state: SimState, params, app, t_h, window_end):
    """One micro-step (public wrapper).  Dispatches to the fused Pallas
    path when params.megakernel applies (trace-time static), so tooling
    that lowers this wrapper (tools/kernelcount.py) sees the graph the
    window loop actually runs."""
    from . import megakernel as mk
    if mk.enabled(state, params, app):
        st, _t_h, _gmin = mk.microstep_fused(state, params, app, t_h,
                                             window_end)
        return st
    return _microstep_core(state, params, app, t_h, window_end)


def _window_body_ref(state: SimState, params, app, t_target):
    """One whole conservative window, reference implementations only:
    boundary exchange -> per-window scan -> window bounds -> netem
    advance -> hoisted window ctx -> the micro-step while loop -> window
    close.  This is the interior of K_WINDOW
    (megakernel.window_fused): it runs INSIDE a Pallas region, so it
    must not launch nested kernels (fused=False throughout) and must
    not touch the window-close instrumentation blocks (scope/sentinel/
    dg ride outside the kernel; fr/tr ride through because the exchange
    writes them with integer scatter-adds).  Off-mesh only -- the
    loop-driving pmin collectives cannot live inside a kernel.

    Returns (state, t_h, gmin, ws, we); the op sequence per phase is
    the same one the main-graph window body traces, which is what the
    persistent path's bitwise contract rests on (docs/megakernel.md,
    "Persistent window kernel")."""
    st = _exchange(state, params, fused=False)
    t_h, gmin = _scan_all(st, params, app)
    ws = jnp.maximum(st.now, gmin)
    we = jnp.minimum(ws + params.min_latency_ns, t_target)
    if st.nm is not None:
        st = st.replace(nm=netem_apply.advance(st.nm, we))
    ctx = _window_ctx(st, params)

    def icond(icarry):
        _s, _th, g = icarry
        return g < we

    def ibody(icarry):
        s, th, _ = icarry
        s = _microstep_core(s, params, app, th, we, ctx=ctx)
        th2, g2 = _scan_all(s, params, app)
        return s, th2, g2

    st, t_h, gmin = jax.lax.while_loop(icond, ibody, (st, t_h, gmin))
    st = st.replace(now=we, n_windows=st.n_windows + 1)
    return st, t_h, gmin, ws, we


@functools.partial(jax.jit, static_argnames=("app",))
def run_until(state: SimState, params, app, t_target):
    """Run windows until simulated time reaches t_target (jitted whole)."""
    return run_until_impl(state, params, app, t_target)


def run_until_impl(state: SimState, params, app, t_target):
    """Window-loop body shared by the jitted single-device entry above
    and the shard_map body of parallel.mesh_run_until.

    Mesh mode (state.hoff set) changes exactly three things, all gated
    at trace time so the single-device graph is byte-identical:

    * the two loop-driving reductions -- per-window global min event
      time and earliest outbox-pending arrival -- get a cross-shard
      `pmin`, making every loop predicate uniform across shards (the
      reference's `master_slaveFinishedCurrentRound` window-advance
      reduction, master.c:450-480, as one collective);
    * `_exchange` takes the all-to-all body (and a pmax'd predicate);
    * `_window_ctx` slices the replicated netem overlay to local rows.

    Uniform predicates guarantee identical window/micro-step trip counts
    on every shard, which is what lets collectives live inside the
    while_loops at all -- and makes n_steps/n_windows/now replicated for
    free.

    Ensemble mode (ensemble/__init__.py) needs NO changes here, and must
    never get any: under `jax.vmap` the while_loops batch by running
    while ANY world's predicate holds and select-freezing finished
    lanes, so each world advances by its own per-world gmin -- worlds
    never synchronize each other's windows, and a finished world's state
    is carried through untouched (the select keeps it bitwise frozen).
    Keeping this function vmap-transparent is what makes an ensemble
    world bitwise equal to its solo run AND keeps ensemble-absent runs
    lowering byte-identical HLO (the tier-0 pins in
    tests/test_ensemble.py check both)."""
    from . import megakernel as mk
    t_target = jnp.asarray(t_target, I64)
    mesh = _on_mesh(state)
    fused = mk.enabled(state, params, app)
    persistent = mk.persistent_enabled(state, params, app)

    def scan(s):
        t_h, gmin = _scan_all(s, params, app)
        if mesh:
            gmin = jax.lax.pmin(gmin, MESH_AXIS)
        return t_h, gmin

    def outbox_pending(s):
        g = _outbox_pending(s)
        if mesh:
            g = jax.lax.pmin(g, MESH_AXIS)
        return g

    def window_cond(carry):
        st, _t_h, gmin, gout = carry
        g = jnp.minimum(gmin, gout)
        return (st.now < t_target) & (g < t_target)

    def window_body(carry):
        st, _, _, _ = carry
        if st.fr is not None:
            st, fr_snap = _fr_snapshot(st)
        if st.sentinel is not None:
            # Conservation ledger at window open, before the exchange
            # (which thins acks and drops data mid-identity).
            sn_snap = _sentinel_counters(st)
        if persistent:
            # K_WINDOW: the whole window -- exchange, scan, bounds,
            # netem advance, and the micro-step while loop -- as ONE
            # Pallas region (megakernel.window_fused), so the window
            # costs O(1) kernel launches.  The window-close
            # instrumentation blocks are only touched here, outside the
            # fused region: scope/sentinel/dg are stripped around the
            # call (the kernel never reads them) and their hooks run on
            # the ws/we scalars the kernel emits; fr/tr ride through
            # because the exchange writes them inside (integer
            # scatter-adds, fusion-context stable).  The scope ctx is
            # recomputed from the post-advance overlay -- netem factors
            # are all-integer, so the recompute is bitwise.
            scope_b, sent_b, dg_b = st.scope, st.sentinel, st.dg
            core = st.replace(scope=None, sentinel=None, dg=None)
            core, t_h, gmin, ws, we = mk.window_fused(
                core, params, app, t_target)
            st = core.replace(scope=scope_b, sentinel=sent_b, dg=dg_b)
            if st.fr is not None:
                st = _fr_record(st, fr_snap, ws, we)
            if st.scope is not None:
                st = _scope_sample(st, _window_ctx(st, params), we)
            if st.sentinel is not None:
                st = _sentinel_check(st, sn_snap, ws, we)
            if st.dg is not None:
                st = _digest_record(st, we)
            return st, t_h, gmin, outbox_pending(st)
        # Boundary exchange first: everything in flight becomes visible
        # in the destination slabs before the window's scan.
        st = _exchange(st, params, fused=fused and not mesh)
        t_h, gmin = scan(st)
        ws = jnp.maximum(st.now, gmin)
        we = jnp.minimum(ws + params.min_latency_ns, t_target)
        if st.nm is not None:
            # Apply every fault event inside this window before any of
            # its ticks: an event takes effect at the start of the
            # conservative window containing its timestamp (install()
            # already shrank the lookahead for sub-1.0 latency scales).
            st = st.replace(nm=netem_apply.advance(st.nm, we))

        # Hoist the window-invariant micro-step inputs here: the inner
        # while body closes over them, so XLA computes them once per
        # window instead of once per micro-step.
        ctx = _window_ctx(st, params)

        def icond(icarry):
            _s, _th, g = icarry
            return g < we

        def ibody(icarry):
            s, th, _ = icarry
            if fused:
                # The fused transport kernel already emits the post-step
                # per-host scan (bitwise _scan_all), so the re-scan
                # collapses to the cross-shard reduction.
                s, th2, g2 = mk.microstep_fused(s, params, app, th, we,
                                                ctx=ctx)
                if mesh:
                    g2 = jax.lax.pmin(g2, MESH_AXIS)
            else:
                s = _microstep_core(s, params, app, th, we, ctx=ctx)
                th2, g2 = scan(s)
            return s, th2, g2

        st, t_h, gmin = jax.lax.while_loop(icond, ibody, (st, t_h, gmin))
        st = st.replace(now=we, n_windows=st.n_windows + 1)
        if st.fr is not None:
            st = _fr_record(st, fr_snap, ws, we)
        if st.scope is not None:
            # Sample at window close: the cadence check and cursors are
            # replicated, so every shard takes the same branch.
            st = _scope_sample(st, ctx, we)
        if st.sentinel is not None:
            st = _sentinel_check(st, sn_snap, ws, we)
        if st.dg is not None:
            # Digest at window close: the cadence predicate is a
            # function of the replicated window counter, so every shard
            # takes the same branch around the gather inside.
            st = _digest_record(st, we)
        return st, t_h, gmin, outbox_pending(st)

    t_h0, gmin0 = scan(state)
    state, _, _, _ = jax.lax.while_loop(
        window_cond, window_body,
        (state, t_h0, gmin0, outbox_pending(state)))
    if state.nm is not None:
        # Catch up through idle spans the window loop skipped, so the
        # cursor (and every counter derived from it) is canonical at
        # t_target regardless of how the run was chunked.
        state = state.replace(nm=netem_apply.advance(state.nm, t_target))
    return state.replace(now=t_target)


# One device launch covers this much simulated time: long enough to
# amortize the ~100ms per-call dispatch cost of the TPU tunnel (the
# compiled executable is reused -- t_target is traced), short enough that
# no single launch trips device/tunnel watchdogs.
CHUNK_NS = 2 * simtime.SIMTIME_ONE_SECOND


def run_chunked(state: SimState, params, app, t_target: int,
                chunk_ns: int = CHUNK_NS):
    """Host-side loop of bounded `run_until` launches up to t_target.

    When a profiler is active (trace.install), each launch is recorded
    as a `device_step` span; in sync mode the launch is blocked on so
    the span measures device execution rather than async dispatch."""
    from .. import trace

    t = int(state.now)
    t_target = int(t_target)
    prof = trace.current()
    while t < t_target:
        t = min(t + chunk_ns, t_target)
        with prof.span("device_step", t_ns=t):
            state = run_until(state, params, app, t)
            if prof.sync:
                jax.block_until_ready(state)
    return state
