"""Simulator state as dense structure-of-arrays pytrees.

The reference keeps one heap-allocated object graph per host (Host owns
NetworkInterfaces, Routers, Descriptors, TCP structs; reference
src/main/host/host.c:57-105) and a locked priority queue of event objects
per host (scheduler_policy_host_single.c).  Here the same information lives
in fixed-capacity dense arrays with a leading `hosts` axis, so one compiled
device step advances every host at once and the host axis can be sharded
over a TPU mesh.

Three big tables:

* `PacketPool` -- every packet in the simulated world, in any lifecycle
  stage (reference: Packet objects + per-queue linked lists,
  src/main/routing/packet.c:40-63).  A packet's position in the network is
  a `stage` tag, not a container: FREE -> TX_QUEUED (socket/qdisc/token
  bucket at source, reference network_interface.c:466-540) -> IN_FLIGHT
  (latency line, reference worker.c:243-304) -> RX_QUEUED (destination
  upstream-router CoDel queue, reference router_queue_codel.c) -> consumed.
  Stage transitions are vectorized masked updates; "queues" are recovered
  by sorting on (time, id) keys, which reproduces the reference's
  deterministic event total order (src/main/core/work/event.c:110-153).

* `SocketTable` -- `[H, S]` per-host socket slots holding the entire
  transport state machine as int fields (reference TCP struct,
  src/main/host/descriptor/tcp.c:125-230).

* `HostTable` -- `[H]` per-host NIC token buckets, RNG counters, and
  tracker counters (reference network_interface.c:32-40, tracker.c).

Payload *bytes* never live on device: packets carry a `length` and an
optional host-side arena id (`payload_id`), mirroring how the reference
shares one refcounted Payload across hosts (src/main/routing/payload.c) --
the device only ever needs metadata.
"""

from __future__ import annotations

from flax import struct
import jax
import jax.numpy as jnp

from . import simtime

# ---------------------------------------------------------------------------
# Enums / constants
# ---------------------------------------------------------------------------

# Packet lifecycle stages.
STAGE_FREE = 0
STAGE_TX_QUEUED = 1   # waiting for source NIC tokens / qdisc
STAGE_IN_FLIGHT = 2   # traversing the latency line
STAGE_RX_QUEUED = 3   # in destination upstream-router (CoDel) queue

# IP protocols (only these two exist in the simulated net, like the
# reference's PTCP/PUDP/PLOCAL protocol tags, packet.h).
PROTO_NONE = 0
PROTO_TCP = 6
PROTO_UDP = 17

# TCP header flags.
TCP_FLAG_FIN = 1
TCP_FLAG_SYN = 2
TCP_FLAG_RST = 4
TCP_FLAG_PSH = 8   # used as the zero-window probe marker (forces an ACK)
TCP_FLAG_ACK = 16

# Socket slot types.
SOCK_FREE = 0
SOCK_UDP = 1
SOCK_TCP = 2

# TCP states (reference tcp.c:41-55).
TCPS_CLOSED = 0
TCPS_LISTEN = 1
TCPS_SYNSENT = 2
TCPS_SYNRECEIVED = 3
TCPS_ESTABLISHED = 4
TCPS_FINWAIT1 = 5
TCPS_FINWAIT2 = 6
TCPS_CLOSING = 7
TCPS_TIMEWAIT = 8
TCPS_CLOSEWAIT = 9
TCPS_LASTACK = 10

# Packet delivery-status trail bits, the observability analog of the
# reference's PDS_* flags (src/main/routing/packet.h:18-41).
PDS_SND_CREATED = 1 << 0
PDS_SND_TCP_ENQUEUE_THROTTLED = 1 << 1
PDS_SND_INTERFACE_SENT = 1 << 2
PDS_INET_SENT = 1 << 3
PDS_INET_DROPPED = 1 << 4
PDS_ROUTER_ENQUEUED = 1 << 5
PDS_ROUTER_DROPPED = 1 << 6
PDS_RCV_INTERFACE_RECEIVED = 1 << 7
PDS_RCV_SOCKET_PROCESSED = 1 << 8
PDS_DESTROYED = 1 << 9

# Error flag bits (raised to the host between windows; the escape hatch for
# fixed-capacity overflow).
ERR_POOL_OVERFLOW = 1 << 0
ERR_SOCKET_OVERFLOW = 1 << 1
ERR_UDPQ_OVERFLOW = 1 << 2

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
F32 = jnp.float32

MTU = 1500          # reference CONFIG_MTU, definitions.h:188
TCP_HEADER_SIZE = 40   # reference CONFIG_HEADER_SIZE_TCPIPETH ballpark
UDP_HEADER_SIZE = 28
TCP_MSS = MTU - TCP_HEADER_SIZE


def _full(shape, dtype, value):
    return jnp.full(shape, value, dtype=dtype)


def _zeros(shape, dtype):
    return jnp.zeros(shape, dtype=dtype)


# ---------------------------------------------------------------------------
# Packet pool
# ---------------------------------------------------------------------------


@struct.dataclass
class PacketPool:
    """All packets in the world (the OUTBOX half); fixed capacity P.

    Layout (round 5, narrowed round 7): every per-packet field that is
    written ONCE at staging lives in a packed [P, C] i32 block whose
    prefix columns are byte-identical to the world's inbox layout
    (C = OCOLS for TCP worlds, NCOLS_UDP + OEXT_COLS for TCP-free ones;
    see pool_cols/ext_base) -- emission staging writes the block with
    ONE one-hot merge (instead of ~21 per-field merges, the largest
    phase of the round-4 step), and the boundary exchange forwards rows
    into the inbox with a 2-column time splice instead of a 24-field
    re-pack.  Only the hot-mutated lifecycle
    fields stay as separate arrays: `stage` (every phase), `time`
    (authoritative; _tx_drain restamps departures), `status` (PDS trail).

    The deterministic total-order tiebreaker pkt_id = (src << 40) | ctr
    lives in the block's CTR columns, mirroring the reference's
    (srcHostID, srcHostEventID) order component (event.c:110-153); drop
    draws are keyed by it so loss is identical across meshes and window
    batchings.
    """

    blk: jnp.ndarray          # [P, C] i32 packed (immutable per stay;
                              # TIME cols stale after _tx_drain -- `time`
                              # below is authoritative).  C = OCOLS, or
                              # NCOLS_UDP + OEXT_COLS for TCP-free worlds
                              # (pool_cols); extension columns sit at the
                              # END of the block (ext_base + OEXT_*).
    stage: jnp.ndarray        # [P] i32 STAGE_*
    time: jnp.ndarray         # [P] i64 stage-dependent: ready/deliver/arrive
    status: jnp.ndarray       # [P] i32 PDS_* trail

    @property
    def capacity(self) -> int:
        return self.stage.shape[0]

    # Decoded views (observability / tests; column slices are cheap).
    @property
    def src(self):
        return self.blk[:, ICOL_SRC]

    @property
    def dst(self):
        return self.blk[:, ext_base(self.blk.shape[1]) + OEXT_DST]

    @property
    def proto(self):
        return self.blk[:, ICOL_PROTO]

    @property
    def length(self):
        return self.blk[:, ICOL_LEN]

    @property
    def lat_ns(self):
        b = ext_base(self.blk.shape[1])
        return dec_i64(self.blk[:, b + OEXT_LAT_LO],
                       self.blk[:, b + OEXT_LAT_HI])

    @property
    def priority(self):
        b = ext_base(self.blk.shape[1])
        return jax.lax.bitcast_convert_type(self.blk[:, b + OEXT_PRIO], F32)

    @property
    def pkt_id(self):
        src = self.blk[:, ICOL_SRC].astype(I64)
        ctr = dec_i64(self.blk[:, ICOL_CTR_LO], self.blk[:, ICOL_CTR_HI])
        return (src << 40) | ctr


def make_packet_pool(capacity: int, cols: int = None) -> PacketPool:
    return PacketPool(
        blk=_zeros((capacity, OCOLS if cols is None else cols), I32),
        stage=_zeros((capacity,), I32),
        time=_full((capacity,), I64, simtime.SIMTIME_INVALID),
        status=_zeros((capacity,), I32),
    )


# ---------------------------------------------------------------------------
# Inbox: per-DESTINATION slabs of arrived/arriving packets
# ---------------------------------------------------------------------------

# Column indices of the packed inbox block.  Everything is i32: packed
# row scatters of i32 are ~10x cheaper than i64 on this backend
# (tools/opbench.py), so i64 fields are split into (lo31, hi) pairs and
# u32 fields are bitcast.  All values are non-negative, so the 31-bit
# split round-trips exactly.
(ICOL_SRC, ICOL_SPORT, ICOL_DPORT, ICOL_PROTO, ICOL_FLAGS, ICOL_SEQ,
 ICOL_ACK, ICOL_WND, ICOL_LEN, ICOL_PAYLOAD,
 ICOL_TIME_LO, ICOL_TIME_HI, ICOL_CTR_LO, ICOL_CTR_HI,
 ICOL_TS_LO, ICOL_TS_HI, ICOL_TSE_LO, ICOL_TSE_HI,
 ICOL_SACK0_LO, ICOL_SACK0_HI, ICOL_SACK1_LO, ICOL_SACK1_HI,
 ICOL_SACK2_LO, ICOL_SACK2_HI) = range(24)
ICOLS = 24

# Narrow inbox width for worlds whose app never opens TCP sockets: the
# TS/TSE/SACK columns (14..23) only feed the TCP machine, and the
# window-boundary exchange's packed row scatter is the single most
# expensive op per window (tools/exchprof.py) -- scattering 14 columns
# instead of 24 cuts it ~40% for pure-UDP worlds (phold).
NCOLS_UDP = ICOL_CTR_HI + 1

# Outbox/emission extension columns: the packed OUTBOX block (and the
# emission staging block) shares the inbox's first ICOLS columns exactly,
# then appends the send-side-only fields.  One layout end to end means
# emit.put writes rows in their final wire format, staging merges ONE
# block, and the boundary exchange forwards rows verbatim (time spliced).
OCOL_DST = ICOLS + 0       # destination host
OCOL_LAT_LO = ICOLS + 1    # path latency incl. the packet's jitter draw,
OCOL_LAT_HI = ICOLS + 2    # fixed at staging (parked departures skip routing)
OCOL_PRIO = ICOLS + 3      # qdisc priority (f32 bitcast)
OCOLS = ICOLS + 4

# Width-relative extension addressing (round 7): the outbox block (and
# the emission staging block) is the inbox prefix -- ICOLS columns, or
# NCOLS_UDP for TCP-free worlds, matching the world's inbox width --
# followed by the four send-side extension columns ABOVE.  Extension
# columns are addressed from the END of the block (ext_base(C) + OEXT_*)
# so the same code compiles for both widths; the OCOL_* constants are the
# full-width (C == OCOLS) spellings and keep working for TCP worlds.
# Narrowing the outbox drops the TS/TSE/SACK columns that only feed the
# TCP machine from emit.put's row stack AND the staging merge's
# [H, E, Ko] one-hot -- the largest micro-step phase (PERF.md round 7).
(OEXT_DST, OEXT_LAT_LO, OEXT_LAT_HI, OEXT_PRIO) = range(4)
OEXT_COLS = 4


def ext_base(cols: int) -> int:
    """First extension column of a width-`cols` packed outbox block."""
    return cols - OEXT_COLS


def pool_cols(uses_tcp: bool) -> int:
    """Packed outbox/emission block width for a world: the world's inbox
    width plus the send-side extension columns."""
    return (ICOLS if uses_tcp else NCOLS_UDP) + OEXT_COLS


# Staging-scratch columns appended to the merge (split off into the
# separate stage/status arrays after the one big one-hot merge).  These
# are spelled relative to the block width at the staging site -- the
# full-width constants below exist for the C == OCOLS case.
MCOL_STAGE = OCOLS + 0
MCOL_STATUS = OCOLS + 1
MCOLS = OCOLS + 2

# SACK blocks carried per segment (reference packet TCP header
# selectiveACKs list, packet.c; RFC 2018 allows 3-4 -- 3 fit the
# timestamped header).
SACK_BLOCKS = 3

_LO_MASK = (1 << 31) - 1


def enc_lo(x):
    """Low 31 bits of a non-negative i64 as i32."""
    return (x & _LO_MASK).astype(I32)


def enc_hi(x):
    """High bits (>> 31) of a non-negative i64 as i32."""
    return (x >> 31).astype(I32)


def dec_i64(lo, hi):
    return (hi.astype(I64) << 31) | lo.astype(I64)


def onehot_slot(slots: int, slot):
    """[H,S] one-hot for a per-host slot index (clipped).  Indexed [H,S]
    gather/scatter costs real milliseconds inside a compiled loop; one-hot
    masked selects fuse for free (tools/opbench2.py)."""
    safe = jnp.clip(slot, 0, slots - 1)
    return safe[..., None] == jnp.arange(slots, dtype=I32)


def onehot_gather(tab, oh):
    """Gather [H] from [H,S] (or [H,S,R] with an [H,S,R] one-hot) under a
    one-hot mask; bool tables reduce with any()."""
    axes = tuple(range(1, tab.ndim)) if oh.ndim == tab.ndim else (1,)
    if tab.dtype == jnp.bool_:
        return jnp.any(oh & tab, axis=axes)
    return jnp.sum(jnp.where(oh, tab, 0), axis=axes, dtype=tab.dtype)


@struct.dataclass
class Inbox:
    """Packets at (or heading to) their destination, in per-destination
    slabs: slot `d * slab + k` belongs to destination host `d`.

    This is the receive half of the packet world (the reference's
    in-flight event queue + per-host upstream-router queue,
    src/main/core/worker.c:243-304 + router_queue_codel.c) laid out so
    every per-micro-step question -- "when is each host's next arrival",
    "which packet does the NIC drain next", "how deep is the router
    backlog" -- is a row-local reshape op over [H, slab] instead of a
    dst-keyed segment reduction over the whole pool (12.7ms vs ~0ms per
    micro-step at 16k hosts; tools/opbench*.py).  Packets enter in bulk
    at window boundaries (engine._exchange) or directly for same-host
    loopback; `stage`/`status` are the only fields mutated in the hot
    loop, elementwise.
    """

    blk: jnp.ndarray      # [P1, C] i32 packed fields (immutable per stay;
                          # C = ICOLS, or NCOLS_UDP for TCP-free worlds)
    # stage/status stay SEPARATE [P1] arrays: packing them into a [P1,2]
    # block made every hot-loop stage read a stride-2 load and cost ~25%
    # of phold throughput for one saved per-window scatter (measured r5).
    stage: jnp.ndarray    # [P1] i32 STAGE_FREE / IN_FLIGHT / RX_QUEUED
    status: jnp.ndarray   # [P1] i32 PDS_* trail

    @property
    def capacity(self) -> int:
        return self.stage.shape[0]

    def times(self):
        """[P1] i64 arrival times (decode of the packed columns)."""
        return dec_i64(self.blk[:, ICOL_TIME_LO], self.blk[:, ICOL_TIME_HI])

    def order_keys(self):
        """[P1] i64 deterministic total-order tiebreak (src << 40) | ctr,
        identical to the outbox pkt_id (reference event.c:110-153)."""
        src = self.blk[:, ICOL_SRC].astype(I64)
        ctr = dec_i64(self.blk[:, ICOL_CTR_LO], self.blk[:, ICOL_CTR_HI])
        return (src << 40) | ctr


def make_inbox(num_hosts: int, slab: int, cols: int = ICOLS) -> Inbox:
    p1 = num_hosts * slab
    return Inbox(
        blk=_zeros((p1, cols), I32),
        stage=_zeros((p1,), I32),
        status=_zeros((p1,), I32),
    )


# ---------------------------------------------------------------------------
# Socket table
# ---------------------------------------------------------------------------

SACK_RANGES = 8  # out-of-order reassembly: byte ranges held past rcv_nxt
SSACK_RANGES = 4  # sender-side sacked-range scoreboard (smaller: holes
                  # refill quickly and every range costs compiled-graph ops)
UDP_RING = 8     # per-UDP-socket datagram ring entries


@struct.dataclass
class SocketTable:
    """[H, S] socket slots; the whole descriptor/transport layer.

    The reference's vtable hierarchy Descriptor->Transport->Socket->TCP/UDP
    (descriptor/socket.h) collapses into one table of int fields; the
    "vtable dispatch" is a vectorized select on `stype`/`tcp_state`.
    """

    stype: jnp.ndarray        # [H,S] i32 SOCK_*
    tcp_state: jnp.ndarray    # [H,S] i32 TCPS_*
    local_port: jnp.ndarray   # [H,S] i32 0 = unbound
    peer_host: jnp.ndarray    # [H,S] i32 -1 = none
    peer_port: jnp.ndarray    # [H,S] i32
    parent: jnp.ndarray       # [H,S] i32 listener slot for accepted children, -1
    accepted: jnp.ndarray     # [H,S] bool child handed to app via accept()
    child_order: jnp.ndarray  # [H,S] i64 SYN pkt_id: deterministic accept order
    backlog: jnp.ndarray      # [H,S] i32 listen backlog

    # --- send side (sequence space, reference tcp.c:125-150) ---
    snd_una: jnp.ndarray      # [H,S] u32 oldest unacked
    snd_nxt: jnp.ndarray      # [H,S] u32 next to transmit
    snd_end: jnp.ndarray      # [H,S] u32 end of app-supplied data
    snd_wnd: jnp.ndarray      # [H,S] i32 peer receive window
    snd_buf_cap: jnp.ndarray  # [H,S] i32 send buffer capacity (bytes)
    cwnd: jnp.ndarray         # [H,S] i32 congestion window (bytes)
    ssthresh: jnp.ndarray     # [H,S] i32
    dup_acks: jnp.ndarray     # [H,S] i32
    recover: jnp.ndarray      # [H,S] u32 fast-recovery high-water mark
    in_recovery: jnp.ndarray  # [H,S] bool
    retrans_nxt: jnp.ndarray  # [H,S] u32 retransmission cursor
    retrans_end: jnp.ndarray  # [H,S] u32 retransmission bound: retx pending
                              # while retrans_nxt < min(retrans_end, snd_nxt).
                              # Fast retransmit/partial ACK set a one-segment
                              # span; RTO sets the full go-back-N window.
    app_closed: jnp.ndarray   # [H,S] bool app called close(); FIN at snd_end

    # --- receive side ---
    rcv_nxt: jnp.ndarray      # [H,S] u32 next expected
    rcv_read: jnp.ndarray     # [H,S] u32 seq consumed by app
    rcv_buf_cap: jnp.ndarray  # [H,S] i32
    # Out-of-order reassembly scoreboard: up to SACK_RANGES disjoint byte
    # ranges [lo, hi) held past rcv_nxt, sorted by distance from rcv_nxt;
    # empty slot encoded as lo == hi.  The vectorized analog of the
    # reference's unordered-input pqueue + SACK list (tcp.c:222-230) and
    # the remora range arithmetic (tcp_retransmit_tally.cc).
    sack_lo: jnp.ndarray      # [H,S,SACK_RANGES] u32
    sack_hi: jnp.ndarray      # [H,S,SACK_RANGES] u32
    fin_seq: jnp.ndarray      # [H,S] u32 peer FIN sequence, 0 = none seen

    # --- timers & RTT (reference tcp.c:175-220) ---
    ts_recent: jnp.ndarray    # [H,S] i64 last in-window segment timestamp (TS.recent)
    srtt: jnp.ndarray         # [H,S] i64 ns, 0 = no sample yet
    rttvar: jnp.ndarray       # [H,S] i64 ns
    rto: jnp.ndarray          # [H,S] i64 ns
    t_rto: jnp.ndarray        # [H,S] i64 retransmit timer expiry, SIMTIME_INVALID = off
    t_delack: jnp.ndarray     # [H,S] i64 delayed-ACK timer
    t_tw: jnp.ndarray         # [H,S] i64 TIME_WAIT / misc timer
    t_persist: jnp.ndarray    # [H,S] i64 zero-window probe timer
    delack_pending: jnp.ndarray  # [H,S] i32 segments since last ACK sent
    # --- receive-buffer autotuning (reference tcp.c:535-561) ---
    at_bytes: jnp.ndarray     # [H,S] i64 bytes delivered since last adjust
    at_last: jnp.ndarray      # [H,S] i64 time of last adjustment
    # --- congestion-control algorithm state (transport/cong.py): CUBIC
    # epoch start + W_max; untouched under Reno ---
    cub_epoch: jnp.ndarray    # [H,S] i64 congestion-avoidance epoch start
    cub_wmax: jnp.ndarray     # [H,S] i32 window before the last reduction
    # --- sender-side SACK scoreboard (reference tcp_retransmit_tally.cc
    # marked-lost/sacked range arithmetic): byte ranges the peer has
    # selectively acknowledged; retransmission skips them ---
    ssack_lo: jnp.ndarray     # [H,S,SSACK_RANGES] u32
    ssack_hi: jnp.ndarray     # [H,S,SSACK_RANGES] u32
    retx_segs: jnp.ndarray    # [H,S] i32 segments retransmitted (telemetry)

    # --- UDP datagram ring ---
    udp_head: jnp.ndarray     # [H,S] i32
    udp_count: jnp.ndarray    # [H,S] i32
    udp_src: jnp.ndarray      # [H,S,UDP_RING] i32
    udp_sport: jnp.ndarray    # [H,S,UDP_RING] i32
    udp_len: jnp.ndarray      # [H,S,UDP_RING] i32
    udp_payload: jnp.ndarray  # [H,S,UDP_RING] i32 arena id

    # --- error & accounting ---
    error: jnp.ndarray        # [H,S] i32 pending socket error (errno-like)
    bytes_sent: jnp.ndarray   # [H,S] i64
    bytes_recv: jnp.ndarray   # [H,S] i64

    # --- per-host socket defaults (reference <host socketsendbuffer
    # socketrecvbuffer>, configuration.h:24-101 -> host.c:162-220): new
    # sockets initialize their buffer caps from these, so a config
    # override applies to every socket the host ever creates.
    def_snd_buf: jnp.ndarray  # [H] i32
    def_rcv_buf: jnp.ndarray  # [H] i32

    @property
    def num_hosts(self) -> int:
        return self.stype.shape[0]

    @property
    def slots(self) -> int:
        return self.stype.shape[1]


def make_socket_table(num_hosts: int, slots: int) -> SocketTable:
    hs = (num_hosts, slots)
    return SocketTable(
        stype=_zeros(hs, I32),
        tcp_state=_zeros(hs, I32),
        local_port=_zeros(hs, I32),
        peer_host=_full(hs, I32, -1),
        peer_port=_zeros(hs, I32),
        parent=_full(hs, I32, -1),
        accepted=_zeros(hs, jnp.bool_),
        child_order=_zeros(hs, I64),
        backlog=_zeros(hs, I32),
        snd_una=_zeros(hs, U32),
        snd_nxt=_zeros(hs, U32),
        snd_end=_zeros(hs, U32),
        snd_wnd=_zeros(hs, I32),
        snd_buf_cap=_zeros(hs, I32),
        cwnd=_zeros(hs, I32),
        ssthresh=_zeros(hs, I32),
        dup_acks=_zeros(hs, I32),
        recover=_zeros(hs, U32),
        in_recovery=_zeros(hs, jnp.bool_),
        retrans_nxt=_zeros(hs, U32),
        retrans_end=_zeros(hs, U32),
        app_closed=_zeros(hs, jnp.bool_),
        rcv_nxt=_zeros(hs, U32),
        rcv_read=_zeros(hs, U32),
        rcv_buf_cap=_zeros(hs, I32),
        sack_lo=_zeros(hs + (SACK_RANGES,), U32),
        sack_hi=_zeros(hs + (SACK_RANGES,), U32),
        fin_seq=_zeros(hs, U32),
        ts_recent=_zeros(hs, I64),
        srtt=_zeros(hs, I64),
        rttvar=_zeros(hs, I64),
        rto=_zeros(hs, I64),
        t_rto=_full(hs, I64, simtime.SIMTIME_INVALID),
        t_delack=_full(hs, I64, simtime.SIMTIME_INVALID),
        t_tw=_full(hs, I64, simtime.SIMTIME_INVALID),
        t_persist=_full(hs, I64, simtime.SIMTIME_INVALID),
        delack_pending=_zeros(hs, I32),
        at_bytes=_zeros(hs, I64),
        at_last=_zeros(hs, I64),
        cub_epoch=_zeros(hs, I64),
        cub_wmax=_zeros(hs, I32),
        ssack_lo=_zeros(hs + (SSACK_RANGES,), U32),
        ssack_hi=_zeros(hs + (SSACK_RANGES,), U32),
        retx_segs=_zeros(hs, I32),
        udp_head=_zeros(hs, I32),
        udp_count=_zeros(hs, I32),
        udp_src=_full(hs + (UDP_RING,), I32, -1),
        udp_sport=_zeros(hs + (UDP_RING,), I32),
        udp_len=_zeros(hs + (UDP_RING,), I32),
        udp_payload=_full(hs + (UDP_RING,), I32, -1),
        error=_zeros(hs, I32),
        bytes_sent=_zeros(hs, I64),
        bytes_recv=_zeros(hs, I64),
        # Defaults match the reference's CONFIG_SEND/RECV_BUFFER_SIZE
        # (definitions.h:101-164); overridden per host by assembly.
        def_snd_buf=_full((num_hosts,), I32, 131072),
        def_rcv_buf=_full((num_hosts,), I32, 174760),
    )


# ---------------------------------------------------------------------------
# Host table (NIC + per-host counters)
# ---------------------------------------------------------------------------


@struct.dataclass
class HostTable:
    """[H] per-host state outside the socket table.

    Token buckets mirror the reference's per-interface up/down buckets with
    1ms refill (network_interface.c:93-190); refill is computed lazily and
    continuously from `last_refill` instead of scheduling a refill event
    per ms per host (smoother than the reference's 1ms quantization;
    capacity is one refill interval + MTU like network_interface.c:192-226).

    CoDel fields implement the RFC 8289 control law of the reference's
    upstream-router queue (router_queue_codel.c:33-56,198-267): target
    sojourn 10ms, interval 100ms, drop-next spacing interval/sqrt(count).
    """

    rng_ctr: jnp.ndarray       # [H] u32 per-host app draw counter
    send_ctr: jnp.ndarray      # [H] i64 per-host packet emission counter (pkt_id low bits)
    cpu_avail: jnp.ndarray     # [H] i64 virtual-CPU available-at time
                               # (reference cpu.c timeCPUAvailable)
    rr_next: jnp.ndarray       # [H] i32 round-robin qdisc cursor
                               # (reference network_interface.c:466-540)
    t_resume: jnp.ndarray      # [H] i64 host has more same-time work (e.g. open
                               # TCP window not fully transmitted); SIMTIME_INVALID = none
    tokens_tx: jnp.ndarray     # [H] i64 bytes available to transmit
    tokens_rx: jnp.ndarray     # [H] i64 bytes available to receive
    last_refill_tx: jnp.ndarray  # [H] i64 last lazy-refill timestamp
    last_refill_rx: jnp.ndarray  # [H] i64 last lazy-refill timestamp
    tx_queued: jnp.ndarray     # [H] i32 packets parked in STAGE_TX_QUEUED
    rx_queued: jnp.ndarray     # [H] i32 packets parked in STAGE_RX_QUEUED
    # CoDel AQM state (reference router_queue_codel.c).
    codel_count: jnp.ndarray       # [H] i32 drops in current dropping cycle
    codel_dropping: jnp.ndarray    # [H] bool in dropping state
    codel_first_above: jnp.ndarray  # [H] i64 when sojourn first exceeded target
    codel_drop_next: jnp.ndarray   # [H] i64 next scheduled drop time
    # Tracker counters (reference tracker.c).
    bytes_sent: jnp.ndarray    # [H] i64
    bytes_recv: jnp.ndarray    # [H] i64
    pkts_sent: jnp.ndarray     # [H] i64
    pkts_recv: jnp.ndarray     # [H] i64
    pkts_dropped_inet: jnp.ndarray   # [H] i64 reliability drops
    pkts_dropped_router: jnp.ndarray  # [H] i64 CoDel/overflow drops
    pkts_dropped_pool: jnp.ndarray   # [H] i64 slab-exhaustion drops of
                                     # protocol-visible packets (the
                                     # fixed-capacity escape hatch; also
                                     # raises ERR_POOL_OVERFLOW)
    acks_thinned: jnp.ndarray        # [H] i64 pure ACKs deliberately shed
                                     # at exchange overflow (ACK-compression
                                     # analog: cumulative ACKing absorbs
                                     # them; NOT an error)

    @property
    def num_hosts(self) -> int:
        return self.rng_ctr.shape[0]


def make_host_table(num_hosts: int) -> HostTable:
    h = (num_hosts,)
    return HostTable(
        rng_ctr=_zeros(h, U32),
        send_ctr=_zeros(h, I64),
        cpu_avail=_zeros(h, I64),
        rr_next=_zeros(h, I32),
        t_resume=_full(h, I64, simtime.SIMTIME_INVALID),
        tokens_tx=_zeros(h, I64),
        tokens_rx=_zeros(h, I64),
        last_refill_tx=_zeros(h, I64),
        last_refill_rx=_zeros(h, I64),
        tx_queued=_zeros(h, I32),
        rx_queued=_zeros(h, I32),
        codel_count=_zeros(h, I32),
        codel_dropping=_zeros(h, jnp.bool_),
        codel_first_above=_zeros(h, I64),
        codel_drop_next=_zeros(h, I64),
        bytes_sent=_zeros(h, I64),
        bytes_recv=_zeros(h, I64),
        pkts_sent=_zeros(h, I64),
        pkts_recv=_zeros(h, I64),
        pkts_dropped_inet=_zeros(h, I64),
        pkts_dropped_router=_zeros(h, I64),
        pkts_dropped_pool=_zeros(h, I64),
        acks_thinned=_zeros(h, I64),
    )


# ---------------------------------------------------------------------------
# Packet capture ring (PCAP analog)
# ---------------------------------------------------------------------------


@struct.dataclass
class CaptureRing:
    """Fixed-capacity ring of sent-packet records, the device-side source
    for PCAP export (reference per-host capture,
    network_interface.c:337-373 + utility/pcap_writer.c).  Present in
    SimState only when capture is enabled, so disabled runs trace without
    any capture cost.  Older records are overwritten when the ring wraps;
    `total` counts lifetime appends so the writer knows."""

    time: jnp.ndarray    # [C] i64 send timestamp
    src: jnp.ndarray     # [C] i32
    dst: jnp.ndarray     # [C] i32
    sport: jnp.ndarray   # [C] i32
    dport: jnp.ndarray   # [C] i32
    proto: jnp.ndarray   # [C] i32
    flags: jnp.ndarray   # [C] i32
    length: jnp.ndarray  # [C] i32 payload bytes
    seq: jnp.ndarray     # [C] u32
    ack: jnp.ndarray     # [C] u32
    kind: jnp.ndarray    # [C] i32 CAP_* direction/disposition
    total: jnp.ndarray   # i64 scalar: lifetime records appended

    @property
    def capacity(self) -> int:
        return self.time.shape[0]


# Capture record kinds: the send direction (recorded at the source
# interface) vs the receive direction (recorded at the destination when
# delivered / when the router dropped it) -- the two per-interface views
# the reference's capture produces (network_interface.c:337-373,415-418).
CAP_SEND = 0
CAP_DELIVER = 1
CAP_RDROP = 2


def make_capture_ring(capacity: int = 1 << 16,
                      shards: int = 1) -> CaptureRing:
    """shards > 1 builds the MESH layout (parallel/mesh.py): the slot
    arrays grow to a multiple of `shards` and partition into per-shard
    segments, and `total` becomes a [shards] cursor vector so every
    shard appends into its own segment with its own cursor.  The drain
    side (observe.write_pcap) merges segments in time order.  shards=1
    keeps the original single-cursor layout byte-for-byte."""
    capacity = -(-capacity // shards) * shards
    total = jnp.asarray(0, I64) if shards == 1 \
        else _zeros((shards,), I64)
    return CaptureRing(
        time=_zeros((capacity,), I64),
        src=_zeros((capacity,), I32),
        dst=_zeros((capacity,), I32),
        sport=_zeros((capacity,), I32),
        dport=_zeros((capacity,), I32),
        proto=_zeros((capacity,), I32),
        flags=_zeros((capacity,), I32),
        length=_zeros((capacity,), I32),
        seq=_zeros((capacity,), U32),
        ack=_zeros((capacity,), U32),
        kind=_zeros((capacity,), I32),
        total=total,
    )


# ---------------------------------------------------------------------------
# Event log ring (leveled, sim-time-stamped; ShadowLogger analog)
# ---------------------------------------------------------------------------

# Log levels (reference support/logger/log_level.c): per-host gating.
LOG_OFF = 0
LOG_WARNING = 1   # drops, resets
LOG_DEBUG = 2     # + deliveries and sends

# Event codes drained into "[simtime] [host] message" lines (observe.py).
LOG_DROP_INET = 1      # reliability drop on the wire
LOG_DROP_ROUTER = 2    # CoDel drop at the destination router
LOG_DROP_TAIL = 3      # interface-buffer tail drop
LOG_DROP_POOL = 4      # slab-capacity drop (capacity escape hatch)
LOG_DELIVER = 5        # packet delivered to a socket
LOG_SEND = 6           # packet placed on the wire
LOG_ACK_THIN = 7       # pure ACKs shed at exchange overflow (not an error)
LOG_NETEM_DOWN = 8     # delivery killed: destination host is netem-down


@struct.dataclass
class LogRing:
    """Bounded device-side event ring, drained and sim-time-sorted by the
    host between chunks -- the two-tier design of the reference's
    ShadowLogger (per-thread queues + helper-thread merge,
    core/logger/shadow_logger.c:25-58) with the device as the "threads"
    and the drain as the merge.  Present in SimState only when logging is
    enabled, so disabled runs trace with zero cost."""

    time: jnp.ndarray    # [C] i64
    host: jnp.ndarray    # [C] i32
    code: jnp.ndarray    # [C] i32 LOG_*
    arg: jnp.ndarray     # [C] i32 event argument (peer, count, bytes)
    total: jnp.ndarray   # i64 lifetime appends (records actually written)
    lost: jnp.ndarray    # i64 records dropped because one append exceeded
                         # the ring capacity (reported by the drain)

    @property
    def capacity(self) -> int:
        return self.time.shape[0]


def make_log_ring(capacity: int = 1 << 16, shards: int = 1) -> LogRing:
    """shards > 1 builds the MESH layout (parallel/mesh.py): slot arrays
    grow to a multiple of `shards` and partition into per-shard segments,
    and `total`/`lost` become [shards] vectors so each shard appends into
    its own segment with its own cursor.  observe.LogDrain merges the
    segments in sim-time order.  shards=1 keeps the original
    single-cursor layout byte-for-byte."""
    capacity = -(-capacity // shards) * shards
    if shards == 1:
        total = jnp.asarray(0, I64)
        lost = jnp.asarray(0, I64)
    else:
        total = _zeros((shards,), I64)
        lost = _zeros((shards,), I64)
    return LogRing(
        time=_zeros((capacity,), I64),
        host=_zeros((capacity,), I32),
        code=_zeros((capacity,), I32),
        arg=_zeros((capacity,), I32),
        total=total,
        lost=lost,
    )


# ---------------------------------------------------------------------------
# Flight recorder (per-window run telemetry; trace.py drains it)
# ---------------------------------------------------------------------------


@struct.dataclass
class FlightRecorder:
    """Fixed-capacity device-side ring recording ONE ROW PER WINDOW --
    the run's black box.  Present in SimState only when installed
    (trace.ensure_flight_recorder), so recorder-less runs trace
    byte-identical graphs, like cap/log/tr/nm.

    A row covers the boundary exchange that OPENED window w plus the
    micro-steps run DURING w.  The ring is written entirely inside the
    compiled window loop and drained at chunk boundaries together with
    the trace counters, so recording adds zero extra host syncs.

    `ex_cnt`/`ex_bytes` are [C, D, D] src->dst LOGICAL-SHARD traffic
    matrices, D = `n_shards` chosen at install time.  On a D-device mesh
    a cell is the packets one shard sent another in that window's
    exchange (derived from the all_to_all send ranking); off-mesh the
    same matrix is computed from host ids, so a single-device run of a
    D-sharded world produces bitwise the same matrices as the mesh run.
    The cur_* scratch holds the current window's matrix between the
    exchange and the row write; the *_sum accumulators are lifetime
    totals that survive ring wrap (bench reads those)."""

    win_start: jnp.ndarray  # [C] i64 window start (ws)
    win_end: jnp.ndarray    # [C] i64 window end (we)
    steps: jnp.ndarray      # [C] i32 micro-steps run in the window
    events: jnp.ndarray     # [C] i64 events drained (deliveries+emissions)
    routed: jnp.ndarray     # [C] i64 packets moved by the opening exchange
    delivered: jnp.ndarray  # [C] i64 packets delivered to sockets
    dropped: jnp.ndarray    # [C] i64 inet+router+pool drops
    killed: jnp.ndarray     # [C] i64 netem delivery kills (0 w/o netem)
    ex_cnt: jnp.ndarray     # [C, D, D] i32 exchange movers per src->dst shard
    ex_bytes: jnp.ndarray   # [C, D, D] i64 exchange payload bytes per pair
    cur_ex_cnt: jnp.ndarray    # [D, D] i32 scratch: this window's matrix
    cur_ex_bytes: jnp.ndarray  # [D, D] i64 scratch
    ex_cnt_sum: jnp.ndarray    # [D, D] i64 lifetime movers (wrap-proof)
    ex_bytes_sum: jnp.ndarray  # [D, D] i64 lifetime bytes
    total: jnp.ndarray      # i64 scalar: lifetime rows written

    @property
    def capacity(self) -> int:
        return self.win_start.shape[0]

    @property
    def n_shards(self) -> int:
        return self.cur_ex_cnt.shape[0]


def make_flight_recorder(capacity: int = 4096,
                         shards: int = 1) -> FlightRecorder:
    return FlightRecorder(
        win_start=_zeros((capacity,), I64),
        win_end=_zeros((capacity,), I64),
        steps=_zeros((capacity,), I32),
        events=_zeros((capacity,), I64),
        routed=_zeros((capacity,), I64),
        delivered=_zeros((capacity,), I64),
        dropped=_zeros((capacity,), I64),
        killed=_zeros((capacity,), I64),
        ex_cnt=_zeros((capacity, shards, shards), I32),
        ex_bytes=_zeros((capacity, shards, shards), I64),
        cur_ex_cnt=_zeros((shards, shards), I32),
        cur_ex_bytes=_zeros((shards, shards), I64),
        ex_cnt_sum=_zeros((shards, shards), I64),
        ex_bytes_sum=_zeros((shards, shards), I64),
        total=jnp.asarray(0, I64),
    )


# ---------------------------------------------------------------------------
# Flowscope (per-flow TCP + per-link NIC telemetry; trace.ScopeDrain)
# ---------------------------------------------------------------------------


@struct.dataclass
class FlowScope:
    """Device-resident network telemetry sampler: a FLOW ring of
    per-sampled-socket TCP rows and a LINK ring of per-host-NIC rows,
    both appended inside the compiled window loop at a sim-time cadence
    (`interval`) and drained at chunk boundaries (trace.ScopeDrain).
    Present in SimState only when installed (trace.ensure_flowscope),
    so scope-less runs trace byte-identical graphs -- the same
    present-or-None contract as cap/log/tr/fr/nm.

    Rows carry CUMULATIVE lifetime counters (bytes sent/recv/acked,
    retransmitted segments, forwarded bytes, drops), so a ring wrap
    loses time resolution but never totals -- the newest surviving row
    of a flow or link still states its exact lifetime sums.  `f_total`/
    `l_total` count lifetime appends (the drain's wrap accounting) and
    `samples` counts sample epochs; `f_lost`/`l_lost` count rows a
    single oversized epoch could not fit (size the rings above
    sampled-rows-per-epoch to keep them zero).

    Host ids are GLOBAL (host_ids: shifted by `hoff` under a mesh).
    Under a mesh each shard samples its local hosts/sockets into its
    own ring segment with its own cursor slice (make_flowscope
    shards=N, the cap/log layout); the drain merges segments in
    sim-time order.  `interval`/`next_due`/`samples` are replicated --
    uniform window predicates advance them identically on every shard.

    Row timestamps are window-quantized (samples fire at the close of
    the first window that reaches `next_due`, stamped at the window
    end), so the exact row times depend on windowing but never on
    chunking -- and sampling never perturbs the simulation itself
    (bitwise trajectory-neutral; tests/test_flowscope.py)."""

    interval: jnp.ndarray   # i64 scalar: sampling cadence (sim ns)
    next_due: jnp.ndarray   # i64 scalar: next sample epoch boundary
    samples: jnp.ndarray    # i64 scalar: lifetime sample epochs taken

    # Flow ring [Cf]: one row per sampled ESTABLISHED-ish TCP socket.
    f_time: jnp.ndarray      # [Cf] i64 sample time (window end)
    f_host: jnp.ndarray      # [Cf] i32 GLOBAL host id
    f_slot: jnp.ndarray      # [Cf] i32 socket slot (host+slot+peer = flow)
    f_peer: jnp.ndarray      # [Cf] i32 peer host id
    f_cwnd: jnp.ndarray      # [Cf] i32 congestion window (bytes)
    f_ssthresh: jnp.ndarray  # [Cf] i32
    f_srtt: jnp.ndarray      # [Cf] i64 smoothed RTT (ns, 0 = no sample)
    f_inflight: jnp.ndarray  # [Cf] i32 bytes in flight (snd_nxt - snd_una)
    f_retx: jnp.ndarray      # [Cf] i32 lifetime retransmitted segments
    f_acked: jnp.ndarray     # [Cf] i64 lifetime bytes acked (sent-inflight)
    f_sent: jnp.ndarray      # [Cf] i64 lifetime stream bytes sent (no retx)
    f_recv: jnp.ndarray      # [Cf] i64 lifetime stream bytes received
    f_total: jnp.ndarray     # i64 scalar | [D]: lifetime rows appended
    f_lost: jnp.ndarray      # i64 scalar | [D]: rows dropped (epoch > ring)

    # Link ring [Cl]: one row per host NIC per sample epoch.
    l_time: jnp.ndarray      # [Cl] i64 sample time (window end)
    l_host: jnp.ndarray      # [Cl] i32 GLOBAL host id
    l_tx: jnp.ndarray        # [Cl] i64 lifetime bytes forwarded (sent)
    l_rx: jnp.ndarray        # [Cl] i64 lifetime bytes received
    l_qdepth: jnp.ndarray    # [Cl] i32 packets parked (tx+rx queues)
    l_cap: jnp.ndarray       # [Cl] i64 netem-scaled up-link capacity (B/s)
    l_drops: jnp.ndarray     # [Cl] i64 lifetime drops (inet+router+pool)
    l_total: jnp.ndarray     # i64 scalar | [D]: lifetime rows appended
    l_lost: jnp.ndarray      # i64 scalar | [D]: rows dropped

    # Static enables (part of the jit cache key, like block presence):
    # a disabled ring's sampling pass traces away entirely and its slot
    # arrays shrink to one slot per shard.
    sample_flows: bool = struct.field(pytree_node=False, default=True)
    sample_links: bool = struct.field(pytree_node=False, default=True)

    @property
    def flow_capacity(self) -> int:
        return self.f_time.shape[0]

    @property
    def link_capacity(self) -> int:
        return self.l_time.shape[0]

    @property
    def n_shards(self) -> int:
        return 1 if self.f_total.ndim == 0 else self.f_total.shape[0]


def make_flowscope(flow_capacity: int = 1 << 16,
                   link_capacity: int = 1 << 14,
                   interval_ns: int = 100_000_000,
                   shards: int = 1,
                   flows: bool = True,
                   links: bool = True) -> FlowScope:
    """Build the sampler block.  `flows=False`/`links=False` disable a
    ring statically: its sampling pass traces away and its slot arrays
    shrink to one slot per shard (the fields must exist for pytree
    stability, but cost nothing).  shards > 1 builds the MESH layout
    (cap/log pattern): slot arrays grow to a multiple of `shards` and
    partition into per-shard segments, cursors become [shards]
    vectors so each shard appends into its own segment."""
    fc = max(flow_capacity if flows else 0, shards)
    lc = max(link_capacity if links else 0, shards)
    fc = -(-fc // shards) * shards
    lc = -(-lc // shards) * shards

    def _cursor():
        return jnp.asarray(0, I64) if shards == 1 else _zeros((shards,), I64)

    return FlowScope(
        interval=jnp.asarray(max(int(interval_ns), 1), I64),
        next_due=jnp.asarray(0, I64),
        samples=jnp.asarray(0, I64),
        f_time=_zeros((fc,), I64),
        f_host=_zeros((fc,), I32),
        f_slot=_zeros((fc,), I32),
        f_peer=_zeros((fc,), I32),
        f_cwnd=_zeros((fc,), I32),
        f_ssthresh=_zeros((fc,), I32),
        f_srtt=_zeros((fc,), I64),
        f_inflight=_zeros((fc,), I32),
        f_retx=_zeros((fc,), I32),
        f_acked=_zeros((fc,), I64),
        f_sent=_zeros((fc,), I64),
        f_recv=_zeros((fc,), I64),
        f_total=_cursor(),
        f_lost=_cursor(),
        l_time=_zeros((lc,), I64),
        l_host=_zeros((lc,), I32),
        l_tx=_zeros((lc,), I64),
        l_rx=_zeros((lc,), I64),
        l_qdepth=_zeros((lc,), I32),
        l_cap=_zeros((lc,), I64),
        l_drops=_zeros((lc,), I64),
        l_total=_cursor(),
        l_lost=_cursor(),
        sample_flows=bool(flows),
        sample_links=bool(links),
    )


# ---------------------------------------------------------------------------
# Packet lineage (sampled per-packet span tracing; trace.LineageDrain)
# ---------------------------------------------------------------------------

# Span stage enum: where in a packet's life a LineageBlock span row was
# written.  A traced packet's life story is the time-ordered chain of its
# span rows (tools/parse.py spans).
SPAN_EMIT = 0      # emission staged at the source (reason set if it died there)
SPAN_STAGE = 1     # parked TX_QUEUED under the uplink token bucket
SPAN_TX = 2        # departed the NIC onto the wire (direct admit or _tx_drain)
SPAN_LINK = 3      # same-host loopback wire hop (bypasses the exchange)
SPAN_EXCHANGE = 4  # moved outbox -> inbox at a window-boundary exchange
SPAN_DELIVER = 5   # delivery attempt at the destination NIC/transport

SPAN_STAGE_NAMES = {
    SPAN_EMIT: "emit",
    SPAN_STAGE: "stage",
    SPAN_TX: "tx",
    SPAN_LINK: "link",
    SPAN_EXCHANGE: "exchange",
    SPAN_DELIVER: "deliver",
}

# Drop-reason enum (span rows; 0 = the hop succeeded).  A nonzero reason
# marks the hop where the packet left the simulation.
LREASON_NONE = 0
LREASON_QDISC = 1      # router/CoDel drop or interface-buffer tail drop
LREASON_LOSS = 2       # reliability draw (baseline wire loss or netem loss)
LREASON_LINK_DOWN = 3  # netem: the src<->dst link is down
LREASON_PARTITION = 4  # netem: endpoints on opposite partition sides
LREASON_HOST_DOWN = 5  # netem: an endpoint host is down
LREASON_ACK_SHED = 6   # pure ACK shed at an overflowing boundary exchange
LREASON_TTL = 7        # reserved: hop-limit expiry (engine has no TTL yet)
LREASON_POOL = 8       # slab-capacity overflow (staging or exchange)

LREASON_NAMES = {
    LREASON_NONE: "none",
    LREASON_QDISC: "qdisc_overflow",
    LREASON_LOSS: "loss",
    LREASON_LINK_DOWN: "link_down",
    LREASON_PARTITION: "partition",
    LREASON_HOST_DOWN: "host_down",
    LREASON_ACK_SHED: "ack_shed",
    LREASON_TTL: "ttl",
    LREASON_POOL: "pool_overflow",
}


@struct.dataclass
class LineageBlock:
    """Sampled per-packet span tracer -- request tracing for packets.
    Present in SimState only when installed (trace.ensure_lineage), so
    lineage-less runs trace byte-identical graphs: the same
    present-or-None contract as cap/log/tr/fr/scope/nm.

    A seeded, deterministic sample of emissions is assigned a nonzero
    i32 trace id at staging (PURPOSE_LINEAGE-keyed on (src, send_ctr),
    core/rng.py), so single-device and mesh runs of the same world
    sample -- and id -- exactly the same packets.  `rate_x1p32` is the
    sample threshold in uint32 space (sample iff keyed bits <= it) and
    rides as TRACED data, so one compiled graph serves every rate.

    The id travels in `pool_id`/`inbox_id`: side arrays shaped like the
    outbox/inbox row axes, moved under the exact permutations the
    engine applies to the packed blocks (staging one-hot merge, the
    exchange scatter / all_to_all trailer column, delivery slot free)
    -- the packed 18/28-column widths are untouched.

    Every hop appends one span row (sim time, GLOBAL host id, SPAN_*
    stage, LREASON_* drop reason) into the span ring.  Under a mesh the
    ring partitions into per-shard segments with [D] cursors (the
    cap/log layout); trace.LineageDrain merges segments in sim-time
    order into spans.jsonl.  Lifetime counters (`n_assigned`, `total`,
    `lost`) survive ring wrap.

    The block only ever observes: installing it never perturbs the
    trajectory (bitwise-neutral, tests/test_lineage.py)."""

    rate_x1p32: jnp.ndarray  # u32 scalar: sample threshold (traced)
    n_assigned: jnp.ndarray  # i64 scalar: lifetime sampled emissions

    pool_id: jnp.ndarray     # [P0] i32 trace id of each outbox row (0=none)
    inbox_id: jnp.ndarray    # [P1] i32 trace id of each inbox row (0=none)

    s_time: jnp.ndarray      # [C] i64 sim time of the hop
    s_id: jnp.ndarray        # [C] i32 trace id (always nonzero)
    s_host: jnp.ndarray      # [C] i32 GLOBAL host id where the hop happened
    s_stage: jnp.ndarray     # [C] i32 SPAN_* stage enum
    s_reason: jnp.ndarray    # [C] i32 LREASON_* drop reason (0 = alive)
    total: jnp.ndarray       # i64 scalar | [D]: lifetime span rows appended
    lost: jnp.ndarray        # i64 scalar | [D]: rows dropped (batch > ring)

    @property
    def capacity(self) -> int:
        return self.s_time.shape[0]

    @property
    def n_shards(self) -> int:
        return 1 if self.total.ndim == 0 else self.total.shape[0]


def lineage_rate_bits(rate: float) -> int:
    """Sample-rate fraction -> uint32 threshold (sample iff
    keyed_bits <= threshold).  rate >= 1.0 traces every packet."""
    r = float(rate)
    if not (0.0 < r <= 1.0):
        raise ValueError(f"lineage sample rate must be in (0, 1], got {r}")
    if r >= 1.0:
        return 0xFFFFFFFF
    return max(0, min(int(round(r * 4294967296.0)) - 1, 0xFFFFFFFF))


def make_lineage(pool_rows: int, inbox_rows: int, rate: float = 0.01,
                 capacity: int = 1 << 16, shards: int = 1) -> LineageBlock:
    """Build the tracer block for a world whose outbox/inbox row axes are
    `pool_rows`/`inbox_rows` (install AFTER mesh/bucket padding, so the
    side arrays match the padded pools).  shards > 1 builds the MESH
    layout (cap/log pattern): the span ring grows to a multiple of
    `shards` and partitions into per-shard segments, cursors become
    [shards] vectors so each shard appends into its own segment."""
    capacity = -(-max(int(capacity), shards) // shards) * shards

    def _cursor():
        return jnp.asarray(0, I64) if shards == 1 else _zeros((shards,), I64)

    return LineageBlock(
        rate_x1p32=jnp.asarray(lineage_rate_bits(rate), U32),
        n_assigned=jnp.asarray(0, I64),
        pool_id=_zeros((pool_rows,), I32),
        inbox_id=_zeros((inbox_rows,), I32),
        s_time=_zeros((capacity,), I64),
        s_id=_zeros((capacity,), I32),
        s_host=_zeros((capacity,), I32),
        s_stage=_zeros((capacity,), I32),
        s_reason=_zeros((capacity,), I32),
        total=_cursor(),
        lost=_cursor(),
    )


# ---------------------------------------------------------------------------
# Invariant sentinel (per-window health checks; trace.SentinelDrain)
# ---------------------------------------------------------------------------


# Violation classes (SentinelBlock.violations bitmask).
SENTINEL_CONSERVATION = 1 << 0  # packet conservation identity broken
SENTINEL_TIME = 1 << 1          # window end not strictly monotone
SENTINEL_BOUNDS = 1 << 2        # stage domain / queue count / cursor bounds
SENTINEL_NONFINITE = 1 << 3     # non-finite float leaf or implausible timer

SENTINEL_CLASS_NAMES = {
    SENTINEL_CONSERVATION: "conservation",
    SENTINEL_TIME: "time",
    SENTINEL_BOUNDS: "bounds",
    SENTINEL_NONFINITE: "nonfinite",
}

# Plausibility ceiling for the TCP timer leaves (srtt/rttvar/rto live in
# i64 ns, so a NaN bit pattern lands as a huge positive integer rather
# than a float NaN; any sane RTT estimate sits far below ten minutes).
SENTINEL_TIMER_MAX_NS = 600 * 1_000_000_000


@struct.dataclass
class SentinelBlock:
    """Per-window invariant monitor -- the run's smoke detector.
    Present in SimState only when installed (trace.ensure_sentinel), so
    sentinel-less runs trace byte-identical graphs: the same
    present-or-None contract as cap/log/tr/fr/scope/nm.

    engine._sentinel_check runs at every window close on cheap
    reductions of state the window already touched: the packet
    conservation identity (emitted = delivered + dropped + thinned +
    still-occupied, bounded by the stage-vs-delivery drop split),
    window-end monotonicity, stage-domain / queue-count / ring-cursor
    bounds, and a finiteness probe over the float leaves plus a
    plausibility ceiling on the i64 TCP timers.  All fields are scalars
    computed from psum/pmin/pmax-reduced inputs, so the block is
    REPLICATED under a mesh (the flight-recorder rule) and bitwise
    identical on every shard.

    The block only ever observes: installing it never perturbs the
    trajectory (bitwise-neutral, tests/test_sentinel.py).  Violations
    are sticky; `first_bad_window`/`first_bad_t` freeze the earliest
    failure so a drain long after the fact still points replay at the
    right window."""

    checks: jnp.ndarray            # i64 lifetime windows checked
    violations: jnp.ndarray        # i32 sticky SENTINEL_* bitmask
    last_violation: jnp.ndarray    # i32 most recent window's bits
    first_bad_window: jnp.ndarray  # i64 window index of first violation, -1
    first_bad_t: jnp.ndarray      # i64 window end (sim ns) at first violation
    last_we: jnp.ndarray          # i64 previous window end (monotonicity)
    resid_low: jnp.ndarray        # i64 conservation lower slack (>= 0 ok)
    resid_high: jnp.ndarray       # i64 conservation upper slack (>= 0 ok)
    nonfinite: jnp.ndarray        # i64 bad float/timer elements last check


def make_sentinel() -> SentinelBlock:
    return SentinelBlock(
        checks=jnp.asarray(0, I64),
        violations=jnp.asarray(0, I32),
        last_violation=jnp.asarray(0, I32),
        first_bad_window=jnp.asarray(-1, I64),
        first_bad_t=jnp.asarray(-1, I64),
        last_we=jnp.asarray(-1, I64),
        resid_low=jnp.asarray(0, I64),
        resid_high=jnp.asarray(0, I64),
        nonfinite=jnp.asarray(0, I64),
    )


# ---------------------------------------------------------------------------
# Statescope digests (per-window state checksums; trace.DigestDrain)
# ---------------------------------------------------------------------------


# Field groups a digest row covers, in column order.  The grouping is
# the diff vocabulary ("the pool diverged at window 41"), so changing
# membership or order is a schema change: bump DIGEST_SCHEMA and diff
# refuses to compare across versions by name instead of mis-aligning
# columns.
DIGEST_GROUPS = ("pool", "inbox", "socks", "hosts", "rng", "netem", "app")
DIGEST_SCHEMA = 1


@struct.dataclass
class DigestBlock:
    """Per-window state checksums -- the divergence tripwire.  Present
    in SimState only when installed (trace.ensure_digests), so
    digest-less runs trace byte-identical graphs: the same
    present-or-None contract as cap/log/tr/fr/scope/nm.

    engine._digest_record runs at window close (cadence `every`
    windows): each SimState leaf is bit-normalized to i64, every
    element hashed against its GLOBAL flat index, and the hashes
    wrapping-summed per DIGEST_GROUPS column and per logical host
    shard.  Summation is commutative, so per-shard columns summed over
    D reproduce the shards=1 digest bitwise -- which is what lets
    `shadow1-tpu diff` compare a mesh run against a single-device run
    column-reduced, and is the property tests/test_statescope.py pins.

    The row ring (`win`/`t_end`/`sums`) is REPLICATED under a mesh:
    each shard computes its local column and one all_gather assembles
    the identical [G, D] row everywhere (the flight-recorder rule).
    `every` is replicated and the cadence predicate is a function of
    the replicated window counter, so every shard takes the same
    branch.  `total` counts lifetime rows (the drain's wrap
    accounting); the block only ever reads trajectory state, so
    installing it is bitwise trajectory-neutral."""

    every: jnp.ndarray  # i64 scalar: digest cadence in windows
    win: jnp.ndarray    # [C] i64 global window index of the row
    t_end: jnp.ndarray  # [C] i64 window end (sim ns)
    sums: jnp.ndarray   # [C, G, D] i64 per-group / per-shard checksums
    total: jnp.ndarray  # i64 scalar: lifetime rows written

    @property
    def capacity(self) -> int:
        return self.win.shape[0]

    @property
    def n_shards(self) -> int:
        return self.sums.shape[2]


def make_digest(capacity: int = 4096, shards: int = 1,
                every: int = 1) -> DigestBlock:
    return DigestBlock(
        every=jnp.asarray(max(1, int(every)), I64),
        win=_zeros((capacity,), I64),
        t_end=_zeros((capacity,), I64),
        sums=_zeros((capacity, len(DIGEST_GROUPS), shards), I64),
        total=jnp.asarray(0, I64),
    )


# ---------------------------------------------------------------------------
# Trace counter block (runtime profiling; trace.py)
# ---------------------------------------------------------------------------


@struct.dataclass
class TraceCounters:
    """Device-side runtime counters for the profiler (trace.py): scalars
    accumulated inside the compiled step and fetched ONCE per drain, so
    profiling costs one extra small transfer per chunk, not per window.
    Present in SimState only when tracing is on (like cap/log), so
    unprofiled runs trace without any counter cost."""

    exchanges: jnp.ndarray       # i64 boundary exchanges that moved packets
    pkts_exchanged: jnp.ndarray  # i64 packets forwarded outbox -> inbox
    occ_max: jnp.ndarray         # i32 max inbox-slab occupancy seen (slots)

    def occupancy_frac(self, state) -> float:
        """Peak inbox-slab fill fraction (host-side convenience)."""
        ki = state.inbox.capacity // state.hosts.num_hosts
        return float(self.occ_max) / max(ki, 1)


def make_trace_counters() -> TraceCounters:
    return TraceCounters(
        exchanges=jnp.asarray(0, I64),
        pkts_exchanged=jnp.asarray(0, I64),
        occ_max=jnp.asarray(0, I32),
    )


# ---------------------------------------------------------------------------
# Whole-simulation state
# ---------------------------------------------------------------------------


@struct.dataclass
class SimState:
    """Everything that evolves during a run; one pytree, checkpointable.

    `pool` is the OUTBOX: per-source slabs holding packets from emission
    until they leave their source (parked TX_QUEUED under the token
    bucket, or IN_FLIGHT awaiting the next window-boundary exchange into
    the destination's inbox).  `inbox` is the per-destination receive
    half (see Inbox)."""

    now: jnp.ndarray          # i64 scalar: current window start
    pool: PacketPool          # outbox, per-SOURCE slabs
    inbox: Inbox              # per-DESTINATION slabs
    socks: SocketTable
    hosts: HostTable
    app: any = struct.field(pytree_node=True, default=None)  # application-model state
    err: jnp.ndarray = struct.field(default=None)  # i32 scalar ERR_* bitmask
    cap: any = struct.field(pytree_node=True, default=None)  # CaptureRing | None
    log: any = struct.field(pytree_node=True, default=None)  # LogRing | None
    # Per-host log level mask (LOG_*), only consulted when log is set.
    log_level: any = struct.field(pytree_node=True, default=None)  # [H] i32
    tr: any = struct.field(pytree_node=True, default=None)  # TraceCounters | None
    # Per-window flight recorder (trace.ensure_flight_recorder): present
    # only when installed, so recorder-less runs trace byte-identical
    # graphs.  Replicated (never sharded) under a mesh -- every shard
    # computes identical rows from psum/all_gather-reduced inputs.
    fr: any = struct.field(pytree_node=True, default=None)  # FlightRecorder | None
    # Per-flow TCP + per-link NIC sampler (trace.ensure_flowscope):
    # present only when installed, so scope-less runs trace
    # byte-identical graphs.  Sharded under a mesh (per-shard ring
    # segments + cursor slices, the cap/log layout).
    scope: any = struct.field(pytree_node=True, default=None)  # FlowScope | None
    # Network dynamics / fault injection (netem/state.py): present only
    # when a fault schedule is installed, so static worlds compile the
    # whole overlay away.
    nm: any = struct.field(pytree_node=True, default=None)  # NetemBlock | None
    # Per-window invariant monitor (trace.ensure_sentinel): present only
    # when installed, so unsupervised runs trace byte-identical graphs.
    # Replicated (never sharded) under a mesh -- every shard computes
    # identical scalars from psum/pmin/pmax-reduced inputs.
    sentinel: any = struct.field(pytree_node=True, default=None)  # SentinelBlock | None
    # Sampled per-packet span tracer (trace.ensure_lineage): present only
    # when installed, so untraced runs trace byte-identical graphs.
    # Sharded under a mesh (per-shard span-ring segments + cursor slices,
    # the cap/log layout); pool_id/inbox_id shard with their pools.
    lineage: any = struct.field(pytree_node=True, default=None)  # LineageBlock | None
    # Per-window state digests (trace.ensure_digests): present only when
    # installed, so digest-less runs trace byte-identical graphs.
    # Replicated (never sharded) under a mesh -- every shard assembles
    # identical rows from all_gather'd per-shard checksum columns.
    dg: any = struct.field(pytree_node=True, default=None)  # DigestBlock | None
    # Telemetry (reference scheduler built-in timers, scheduler.c:266-268):
    n_steps: jnp.ndarray = struct.field(default=None)    # i64 micro-steps
    n_windows: jnp.ndarray = struct.field(default=None)  # i64 windows run
    n_events: jnp.ndarray = struct.field(default=None)   # i64 deliveries+emissions
    # Mesh shard offset (parallel/mesh.py): global host id of this shard's
    # row 0.  None off-mesh -- `state.hoff is None` is a trace-time static,
    # so single-device graphs compile byte-identical to before the field
    # existed.  Set only inside the shard_map body, never persisted.
    hoff: any = struct.field(pytree_node=True, default=None)  # i32 scalar


def host_ids(state, dtype=I32) -> jnp.ndarray:
    """GLOBAL host ids of this state's rows: arange(h) off-mesh, shifted by
    the shard offset under a mesh.  Use wherever a host id feeds RNG keys,
    packet src fields, or comparisons against global-valued ids (app dst
    leaves, packet.src) -- local row indices are only valid for slab
    addressing."""
    ids = jnp.arange(state.hosts.num_hosts, dtype=dtype)
    if state.hoff is None:
        return ids
    return ids + state.hoff.astype(dtype)


def world_count(state) -> int | None:
    """Number of worlds when `state` carries an ensemble's leading world
    axis (ensemble.stack), else None for an ordinary solo state.

    Probes `state.now` -- an i64 scalar in every solo state, so a stacked
    state is unambiguously ndim == 1.  Host-side introspection helpers
    that read row counts off leaf shapes (e.g. `hosts.num_hosts`, which
    returns leaf.shape[0]) are WRONG on a stacked state: slice a world
    out first (`ensemble.world(estate, eparams, k)`) before calling
    them."""
    now = jnp.asarray(state.now)
    if now.ndim == 0:
        return None
    return int(now.shape[0])


# Known-bad region of the TPU tunnel backend (BASELINE.md;
# tools/repro_tunnel_crash.py r4 finding): slab >= 128 at >= 10k hosts
# reproducibly faults the tunnel worker.  One source of truth for the
# thresholds -- warn_known_bad_pool warns at world build and
# shapes.bucket_for refuses to ROUND a world into the region.
KNOWN_BAD_POOL_SLAB = 128
KNOWN_BAD_POOL_HOSTS = 10_000


def warn_known_bad_pool(num_hosts: int, slab: int) -> None:
    """Loud warning for the known-bad region of the TPU tunnel backend
    (BASELINE.md; tools/repro_tunnel_crash.py r4 finding): the exchange-
    rank superblock tables scale with hosts*slab, and slab 128 at 10k
    hosts reproducibly faults the tunnel worker during the first
    simulated second.  Slab 64 is measured stable at the same scale.
    Called from make_sim_state so every world builder (config assemble,
    hand-built states) is covered."""
    if slab >= KNOWN_BAD_POOL_SLAB and num_hosts >= KNOWN_BAD_POOL_HOSTS:
        import warnings
        warnings.warn(
            f"pool slab {slab} at {num_hosts} hosts is in the known-bad "
            f"region of the TPU tunnel backend (worker kernel fault, "
            f"see tools/repro_tunnel_crash.py); pool_slab=64 is "
            f"measured stable at this scale -- pass pool_slab=64 "
            f"unless deliberately bisecting the backend bug",
            RuntimeWarning, stacklevel=3)


def make_sim_state(num_hosts: int, sock_slots: int = 16,
                   pool_capacity: int = 1 << 15, app=None,
                   inbox_capacity: int | None = None,
                   uses_tcp: bool = True) -> SimState:
    # Both pools are partitioned into per-host slabs: the outbox by SOURCE
    # (engine._stage_emissions allocates from the emitting host's slab),
    # the inbox by DESTINATION (engine._exchange fills it at window
    # boundaries).  Capacities round up to a multiple of num_hosts with at
    # least 8 slots per host.  The inbox defaults to the outbox size; size
    # it by expected fan-IN (a popular server needs a deeper inbox slab).
    slab = max(8, -(-pool_capacity // num_hosts))
    warn_known_bad_pool(num_hosts, slab)
    if inbox_capacity is None:
        inbox_capacity = pool_capacity
    islab = max(8, -(-inbox_capacity // num_hosts))
    return SimState(
        now=jnp.asarray(0, I64),
        pool=make_packet_pool(num_hosts * slab, cols=pool_cols(uses_tcp)),
        inbox=make_inbox(num_hosts, islab,
                         cols=ICOLS if uses_tcp else NCOLS_UDP),
        socks=make_socket_table(num_hosts, sock_slots),
        hosts=make_host_table(num_hosts),
        app=app,
        err=jnp.asarray(0, I32),
        n_steps=jnp.asarray(0, I64),
        n_windows=jnp.asarray(0, I64),
        n_events=jnp.asarray(0, I64),
    )
