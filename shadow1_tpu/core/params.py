"""Static (per-run constant) simulation parameters.

The reference resolves latency/reliability lazily per source via Dijkstra
with a path cache (/root/reference/src/main/routing/topology.c:1678-1875).
Here the whole all-pairs answer is precomputed once at startup into dense
matrices indexed by topology vertex (see routing/apsp.py), and per-packet
"routing" is a 2-D gather -- the TPU-shaped replacement for the path cache.
"""

from __future__ import annotations

from flax import struct
import jax
import jax.numpy as jnp

from . import simtime
from .state import I32, I64, F32

QDISC_FIFO = 0
QDISC_RR = 1


# Columns of the packed routing block (all i32; i64 split lo/hi, f32
# bitcast).  One [V*V, 5] block means per-packet routing is ONE row
# gather instead of three separate [V,V] gathers -- gathers are among the
# few ops with real per-index cost inside a compiled loop
# (tools/opbench*.py), and the hot path issues them at [H, E] volume.
# Column ORDER is load-bearing: the always-needed fields (latency,
# reliability) come first so jitter-free worlds (the common case) gather
# only the leading RCOLS_NARROW columns per packet.
(RCOL_LAT_LO, RCOL_LAT_HI, RCOL_REL, RCOL_JIT_LO, RCOL_JIT_HI) = range(5)
RCOLS = 5
RCOLS_NARROW = 3            # lat lo/hi + reliability


@struct.dataclass
class NetParams:
    """Constant under jit for a whole run (still a pytree of arrays so it
    can be donated/sharded)."""

    route_blk: jnp.ndarray      # [V*V, RCOLS] i32 packed per-pair routing:
                                # one-way latency ns (i64 as lo/hi),
                                # jitter amplitude ns (i64 as lo/hi;
                                # per-packet latency perturbed uniformly in
                                # +/- this, reference edge attr
                                # topology.c:81-105), delivery probability
                                # (f32 bitcast)
    host_vertex: jnp.ndarray    # [H] i32 topology vertex each host attached to
    bw_up_Bps: jnp.ndarray      # [H] i64 upstream bytes/sec
    bw_down_Bps: jnp.ndarray    # [H] i64 downstream bytes/sec
    min_latency_ns: jnp.ndarray  # i64 scalar: conservative lookahead (min jump)
    seed_key: jax.Array         # PRNG root key
    stop_time: jnp.ndarray      # i64 scalar
    bootstrap_end: jnp.ndarray  # i64 scalar: before this, bandwidth unlimited
                                # (reference master.c:261-268, worker.c:445-453)
    # Virtual CPU model (reference cpu.c:15-108 + event deferral
    # event.c:71-84): every delivered packet / staged emission costs
    # cpu_ns_per_event of virtual CPU time; when the accumulated backlog
    # exceeds the threshold the host stops executing events until the
    # backlog drains.  0 = no CPU model for that host.
    cpu_ns_per_event: jnp.ndarray  # [H] i64
    cpu_threshold_ns: jnp.ndarray  # i64 scalar (reference --cpu-threshold)
    cpu_precision_ns: jnp.ndarray  # i64 scalar (reference --cpu-precision)
    # Interface qdisc (reference --interface-qdisc,
    # network_interface.c:466-540): QDISC_FIFO serves the lowest eligible
    # socket slot (creation order); QDISC_RR round-robins across them.
    qdisc: jnp.ndarray             # i32 scalar QDISC_*
    # Per-host TCP buffer autotuning switches: explicitly configured
    # socket buffers disable the corresponding autotune, mirroring the
    # reference (tcp.c autotune only when not user-set).
    autotune_snd: jnp.ndarray      # [H] bool
    autotune_rcv: jnp.ndarray      # [H] bool
    # Interface receive buffer in packets (reference <host
    # interfacebuffer> bytes / MTU; network_interface.c receive-side
    # bound): arrivals beyond this router backlog are tail-dropped
    # before CoDel even sees them.  0 = unbounded.
    iface_buf_pkts: jnp.ndarray    # [H] i32
    # Per-host capture gate (reference <host logpcap>): a packet is
    # recorded when its source OR destination host is marked.  Only
    # consulted when a CaptureRing is installed.
    pcap_mask: jnp.ndarray         # [H] bool
    # Traced REAL host count (present-or-None, the SimState.hoff
    # pattern): installed by shapes.pad_world_to_bucket when a world is
    # padded up to a shape bucket, so app-level global draws (phold's
    # dst pick) see the real count while every [H] array carries padded
    # rows.  None is a trace-time static -- un-bucketed worlds compile
    # byte-identical graphs to before this field existed.  When present
    # it is a runtime input, so every world padded into the same bucket
    # shares ONE compiled graph (docs/shapes.md).
    hosts_real: any = struct.field(pytree_node=True, default=None)  # i32 scalar | None
    # Congestion-control algorithm (reference --tcp-congestion-control,
    # tcp_cong.h hook table): STATIC -- part of the compiled step's
    # identity, so the untaken algorithm traces away.
    cong: str = struct.field(pytree_node=False, default="reno")
    # STATIC: any host has a bounded interface buffer.  The tail-drop
    # ranking costs an [H, slab, slab] comparison cube per micro-step, so
    # it must trace away entirely for the (default) unbounded case.
    has_iface_buf: bool = struct.field(pytree_node=False, default=False)
    # STATIC: maintain the per-packet PDS_* delivery-status trail
    # (reference packet.h:18-41).  Pure observability -- nothing consumes
    # it programmatically -- and it costs a packed scatter per window plus
    # masked updates in every micro-step, so it traces away by default.
    pds_trail: bool = struct.field(pytree_node=False, default=False)
    # STATIC: any pair has reliability < 1.0.  When False (and no fault
    # overlay is installed) the per-emission drop draw is provably never
    # taken, so the whole keyed-uniform hash chain traces away.  The
    # default True is the conservative always-correct setting; builders
    # going through make_net_params get the computed value.
    has_loss: bool = struct.field(pytree_node=False, default=True)
    # STATIC: any pair has jitter > 0.  When False the per-packet jitter
    # draw traces away AND routing gathers only the narrow (lat, rel)
    # leading columns of route_blk.
    has_jitter: bool = struct.field(pytree_node=False, default=True)
    # STATIC master switch for the dynamic micro-step gates (lax.cond
    # around _tx_drain / TCP timers / arrivals / transmit): the gated
    # graph is bitwise-identical to the ungated one -- this switch exists
    # so tests can run both variants and assert exactly that
    # (tests/test_kernel_diet.py).
    kernel_diet: bool = struct.field(pytree_node=False, default=True)
    # STATIC: compile the micro-step phase graph (drain -> route ->
    # deliver -> transport) into the hand-fused Pallas kernels in
    # core/megakernel.py instead of the reference XLA op-graph.  Default
    # on; on non-TPU backends the kernels run in Pallas interpret mode so
    # CPU tests exercise the same code path (docs/megakernel.md).  The
    # reference path (megakernel=False) stays intact as the correctness
    # oracle and lowers byte-identical HLO to pre-megakernel builds.
    megakernel: bool = struct.field(pytree_node=False, default=True)
    # STATIC: compile the WHOLE conservative window -- the boundary
    # exchange, the per-window scan, the netem advance, and the
    # micro-step while loop with its gmin loop predicate -- into one
    # persistent Pallas region (core/megakernel.py window_fused), so a
    # window costs O(1) kernel launches instead of O(steps x phases).
    # Only consulted when the megakernel path is admissible at all
    # (megakernel.persistent_enabled); off-mesh only -- the mesh's
    # loop-driving collectives cannot live inside a kernel, so sharded
    # runs keep the per-phase fused kernels.  persistent=False lowers
    # byte-identical HLO to pre-persistent builds.
    persistent: bool = struct.field(pytree_node=False, default=True)

    def global_hosts(self):
        """Global host count for app-level draws ("pick a random host"):
        the traced `hosts_real` scalar when installed (bucket-padded
        world, where the static row count would see the PADDED size and
        change every draw), else the static row count (a Python int, so
        the graph is byte-identical to pre-bucketing code).  Row counts
        stay exact in f32 up to 2**24, far above the 1M-host ladder cap,
        so the draw arithmetic is bitwise the same either way."""
        if self.hosts_real is not None:
            return self.hosts_real
        return self.host_vertex.shape[0]

    @property
    def n_vertices(self) -> int:
        v = int(round(self.route_blk.shape[0] ** 0.5))
        assert v * v == self.route_blk.shape[0]
        return v

    def route(self, vs, vd):
        """Packed routing lookup: one row gather.  Returns
        (latency_ns i64, jitter_ns i64, reliability f32) for any
        broadcastable integer index shapes."""
        from .state import dec_i64
        rows = self.route_blk[vs * self.n_vertices + vd]
        lat = dec_i64(rows[..., RCOL_LAT_LO], rows[..., RCOL_LAT_HI])
        jit = dec_i64(rows[..., RCOL_JIT_LO], rows[..., RCOL_JIT_HI])
        rel = jax.lax.bitcast_convert_type(rows[..., RCOL_REL], F32)
        return lat, jit, rel

    def route_narrow(self, vs, vd):
        """Jitter-free routing lookup: gather only the leading
        (lat lo/hi, rel) columns per packet.  The static column slice is
        loop-invariant, so XLA hoists it out of the micro-step while
        body and the per-packet gather moves 3/5 the bytes.  Returns
        (latency_ns i64, reliability f32)."""
        from .state import dec_i64
        narrow = self.route_blk[:, :RCOLS_NARROW]
        rows = narrow[vs * self.n_vertices + vd]
        lat = dec_i64(rows[..., RCOL_LAT_LO], rows[..., RCOL_LAT_HI])
        rel = jax.lax.bitcast_convert_type(rows[..., RCOL_REL], F32)
        return lat, rel

    @property
    def latency_ns(self):
        """[V,V] i64 latency matrix (decoded view, for host-side use)."""
        v = self.n_vertices
        from .state import dec_i64
        return dec_i64(self.route_blk[:, RCOL_LAT_LO],
                       self.route_blk[:, RCOL_LAT_HI]).reshape(v, v)

    @property
    def jitter_ns(self):
        v = self.n_vertices
        from .state import dec_i64
        return dec_i64(self.route_blk[:, RCOL_JIT_LO],
                       self.route_blk[:, RCOL_JIT_HI]).reshape(v, v)

    @property
    def reliability(self):
        v = self.n_vertices
        return jax.lax.bitcast_convert_type(
            self.route_blk[:, RCOL_REL], F32).reshape(v, v)

    def pair_latency(self, src_host, dst_host):
        """One-way latency between two hosts (ns)."""
        vs = self.host_vertex[src_host]
        vd = self.host_vertex[dst_host]
        return self.route(vs, vd)[0]

    def pair_reliability(self, src_host, dst_host):
        vs = self.host_vertex[src_host]
        vd = self.host_vertex[dst_host]
        return self.route(vs, vd)[2]


def make_net_params(
    latency_ns,
    reliability,
    host_vertex,
    bw_up_Bps,
    bw_down_Bps,
    seed: int = 1,
    stop_time: int = simtime.SIMTIME_ONE_SECOND,
    bootstrap_end: int = 0,
    min_latency_ns=None,
    jitter_ns=None,
    cpu_ns_per_event=None,
    cpu_threshold_ns: int = -1,  # reference --cpu-threshold default:
                                 # negative = CPU never blocks
    cpu_precision_ns: int = 200 * simtime.SIMTIME_ONE_MICROSECOND,
    qdisc: int = QDISC_FIFO,
    autotune_snd=None,
    autotune_rcv=None,
    iface_buf_pkts=None,
    pcap_mask=None,
    cong: str = "reno",
    megakernel: bool = True,
    persistent: bool = True,
) -> NetParams:
    from . import rng

    latency_ns = jnp.asarray(latency_ns, I64)
    if jitter_ns is None:
        jitter_ns = jnp.zeros_like(latency_ns)
    jitter_ns = jnp.asarray(jitter_ns, I64)
    if min_latency_ns is None:
        # Minimum latency over every pair that can carry CROSS-HOST
        # traffic bounds the lookahead window, like the reference's min
        # time jump with a 10ms default when the topology gives nothing
        # (master.c:133-159).  Jitter can shorten a path, so the
        # conservative bound subtracts it.  A vertex's self-path counts
        # whenever two or more hosts share that vertex (same-host
        # loopback bypasses the matrix and never constrains the window).
        v = latency_ns.shape[0]
        hv = jnp.asarray(host_vertex, I32)
        occupants = jnp.zeros((v,), I32).at[hv].add(1)
        shared_self = occupants >= 2
        eye = jnp.eye(v, dtype=bool)
        eligible = (~eye) | (eye & shared_self[None, :])
        eff = jnp.maximum(latency_ns - jitter_ns, 1)
        inv = jnp.asarray(simtime.SIMTIME_INVALID, I64)
        cand = jnp.where(eligible & (latency_ns > 0), eff, inv)
        m = jnp.min(cand)
        min_latency_ns = jnp.where(
            m == simtime.SIMTIME_INVALID,
            jnp.asarray(10 * simtime.SIMTIME_ONE_MILLISECOND, I64),
            m,
        )
    h = jnp.asarray(host_vertex).shape[0]
    if cpu_ns_per_event is None:
        cpu_ns_per_event = jnp.zeros((h,), I64)
    if autotune_snd is None:
        autotune_snd = jnp.ones((h,), bool)
    if autotune_rcv is None:
        autotune_rcv = jnp.ones((h,), bool)
    if iface_buf_pkts is None:
        iface_buf_pkts = jnp.zeros((h,), I32)
    if pcap_mask is None:
        pcap_mask = jnp.ones((h,), bool)
    from .state import enc_lo, enc_hi
    rel_m = jnp.asarray(reliability, F32)
    route_blk = jnp.stack([
        enc_lo(latency_ns.reshape(-1)),
        enc_hi(latency_ns.reshape(-1)),
        jax.lax.bitcast_convert_type(rel_m.reshape(-1), I32),
        enc_lo(jitter_ns.reshape(-1)),
        enc_hi(jitter_ns.reshape(-1)),
    ], axis=1)
    return NetParams(
        route_blk=route_blk,
        host_vertex=jnp.asarray(host_vertex, I32),
        bw_up_Bps=jnp.asarray(bw_up_Bps, I64),
        bw_down_Bps=jnp.asarray(bw_down_Bps, I64),
        min_latency_ns=jnp.asarray(min_latency_ns, I64),
        # `seed` is an int (the common case) or an already-derived PRNG
        # key -- ensemble.replicate builds world k from
        # rng.world_key(root_key(seed), k) and hands the key through.
        seed_key=(seed if isinstance(seed, jnp.ndarray)
                  else rng.root_key(seed)),
        stop_time=jnp.asarray(stop_time, I64),
        bootstrap_end=jnp.asarray(bootstrap_end, I64),
        cpu_ns_per_event=jnp.asarray(cpu_ns_per_event, I64),
        cpu_threshold_ns=jnp.asarray(cpu_threshold_ns, I64),
        cpu_precision_ns=jnp.asarray(cpu_precision_ns, I64),
        qdisc=jnp.asarray(qdisc, I32),
        autotune_snd=jnp.asarray(autotune_snd, bool),
        autotune_rcv=jnp.asarray(autotune_rcv, bool),
        iface_buf_pkts=jnp.asarray(iface_buf_pkts, I32),
        pcap_mask=jnp.asarray(pcap_mask, bool),
        cong=cong,
        has_iface_buf=bool(jnp.any(jnp.asarray(iface_buf_pkts, I32) > 0)),
        has_loss=bool(jnp.any(rel_m < 1.0)),
        has_jitter=bool(jnp.any(jitter_ns > 0)),
        megakernel=bool(megakernel),
        persistent=bool(persistent),
    )
