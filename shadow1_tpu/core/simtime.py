"""Simulation time: int64 nanoseconds since simulation start.

Mirrors the reference's SimulationTime contract
(/root/reference/src/main/core/support/definitions.h:28-64): nanosecond
resolution, with an emulated wall clock offset so applications that ask for
the time see a date shortly after Jan 1 2000
(definitions.h:78, src/main/core/worker.c:385-390).

All constants are plain Python ints; device arrays carrying times must be
jnp.int64 (the package enables x64 at import).
"""

import jax.numpy as jnp

# One nanosecond is the base unit.
SIMTIME_ONE_NANOSECOND = 1
SIMTIME_ONE_MICROSECOND = 1_000
SIMTIME_ONE_MILLISECOND = 1_000_000
SIMTIME_ONE_SECOND = 1_000_000_000
SIMTIME_ONE_MINUTE = 60 * SIMTIME_ONE_SECOND
SIMTIME_ONE_HOUR = 60 * SIMTIME_ONE_MINUTE

# Sentinel for "no event pending" / invalid time. Using int64 max means a
# plain jnp.min over next-event candidates naturally ignores empty slots.
SIMTIME_INVALID = (1 << 63) - 1

# Greatest representable simulation time (kept distinct from the sentinel so
# clamping logic can't accidentally produce "invalid").
SIMTIME_MAX = SIMTIME_INVALID - 1

# Emulated Unix epoch offset: applications observe wall-clock time starting
# at 946_684_800s (2000-01-01T00:00:00Z), like the reference's
# EMULATED_TIME_OFFSET (definitions.h:78).
EMULATED_TIME_OFFSET = 946_684_800 * SIMTIME_ONE_SECOND

TIME_DTYPE = jnp.int64


def simtime(value) -> jnp.ndarray:
    """Lift a scalar/array to the canonical time dtype."""
    return jnp.asarray(value, dtype=TIME_DTYPE)


def from_seconds(seconds: float) -> int:
    return int(round(seconds * SIMTIME_ONE_SECOND))


def from_millis(ms: float) -> int:
    return int(round(ms * SIMTIME_ONE_MILLISECOND))


def to_seconds(t) -> float:
    return float(t) / SIMTIME_ONE_SECOND


def emulated_time(sim_now):
    """Virtual wall-clock time an application observes (ns since Unix epoch)."""
    return simtime(sim_now) + EMULATED_TIME_OFFSET
