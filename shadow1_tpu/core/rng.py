"""Deterministic, order-independent random draws.

The reference derives determinism from a seeded chain of rand_r generators
handed master -> slave -> scheduler -> host
(/root/reference/src/main/utility/random.c:15-50, master.c:95, slave.c:301).
That scheme is inherently sequential: a draw's value depends on how many
draws happened before it on the same generator.

A TPU-native simulator cannot afford (and does not want) sequential draw
order: events for all hosts are processed in one vectorized step, and the
set of draws must be identical regardless of device mesh shape or window
batching.  So every random draw here is *functionally keyed*: a counter-based
PRNG (JAX threefry) evaluated at a key derived from the global seed plus the
stable identifiers of the thing being drawn for -- e.g. (packet id, hop) for
a drop decision, (host id, per-host draw counter) for application
randomness.  Two runs with the same seed produce bitwise-identical draws on
any sharding, which upgrades the reference's determinism contract
(reference src/test/determinism/) from "same worker count" to "any mesh".
"""

import jax
import jax.numpy as jnp

# Purpose tags keep independent subsystems' draws decorrelated even when the
# rest of the key material collides.
PURPOSE_PACKET_DROP = 1
PURPOSE_HOST_APP = 2
PURPOSE_ATTACH = 3
PURPOSE_JITTER = 4
PURPOSE_SCHED = 5


def root_key(seed: int) -> jax.Array:
    """Root PRNG key for a simulation (reference: --seed, options.c)."""
    return jax.random.PRNGKey(seed)


def purpose_key(key: jax.Array, purpose: int) -> jax.Array:
    return jax.random.fold_in(key, purpose)


def keyed_uniform(key: jax.Array, *ids) -> jax.Array:
    """U[0,1) keyed by a sequence of integer ids (scalars or same-shape arrays).

    Vectorized: if ids are arrays, returns an array of independent draws of
    the broadcast shape.
    """
    ids = [jnp.asarray(i, dtype=jnp.uint32) for i in ids]
    shape = jnp.broadcast_shapes(*(i.shape for i in ids))
    # Mix the ids into per-element key data with a threefry fold-in chain.
    def fold_all(scalars):
        k = key
        for s in scalars:
            k = jax.random.fold_in(k, s)
        return jax.random.uniform(k, (), dtype=jnp.float32)

    # Scalars route through a size-1 batch: shape-() random ops hang on the
    # axon TPU backend (observed 2026-07-29), and the batch path is what the
    # engine exercises anyway.
    flat = [jnp.broadcast_to(i, shape).reshape(-1) for i in ids]
    out = jax.vmap(lambda *s: fold_all(s))(*flat)
    return out.reshape(shape)


def keyed_bits(key: jax.Array, *ids) -> jax.Array:
    """uint32 random bits keyed by integer ids (same contract as keyed_uniform)."""
    ids = [jnp.asarray(i, dtype=jnp.uint32) for i in ids]
    shape = jnp.broadcast_shapes(*(i.shape for i in ids))

    def fold_all(scalars):
        k = key
        for s in scalars:
            k = jax.random.fold_in(k, s)
        return jax.random.bits(k, (), dtype=jnp.uint32)

    flat = [jnp.broadcast_to(i, shape).reshape(-1) for i in ids]
    out = jax.vmap(lambda *s: fold_all(s))(*flat)
    return out.reshape(shape)
