"""Deterministic, order-independent random draws.

The reference derives determinism from a seeded chain of rand_r generators
handed master -> slave -> scheduler -> host
(/root/reference/src/main/utility/random.c:15-50, master.c:95, slave.c:301).
That scheme is inherently sequential: a draw's value depends on how many
draws happened before it on the same generator.

A TPU-native simulator cannot afford (and does not want) sequential draw
order: events for all hosts are processed in one vectorized step, and the
set of draws must be identical regardless of device mesh shape or window
batching.  So every random draw here is *functionally keyed*: a stateless
integer hash evaluated at the global seed plus the stable identifiers of
the thing being drawn for -- e.g. (packet id, hop) for a drop decision,
(host id, per-host draw counter) for application randomness.  Two runs
with the same seed produce bitwise-identical draws on any sharding, which
upgrades the reference's determinism contract (reference
src/test/determinism/) from "same worker count" to "any mesh".
"""

import jax
import jax.numpy as jnp

# Purpose tags keep independent subsystems' draws decorrelated even when the
# rest of the key material collides.
PURPOSE_PACKET_DROP = 1
PURPOSE_HOST_APP = 2
PURPOSE_ATTACH = 3
PURPOSE_JITTER = 4
PURPOSE_SCHED = 5
PURPOSE_CHAOS = 6   # netem churn process draws (netem/timeline.py)
PURPOSE_LINEAGE = 7  # packet-lineage sampling + trace-id assignment
PURPOSE_WORLD = 8   # ensemble world-id fold (ensemble/__init__.py)


def root_key(seed: int) -> jax.Array:
    """Root PRNG key for a simulation (reference: --seed, options.c)."""
    return jax.random.PRNGKey(seed)


def purpose_key(key: jax.Array, purpose: int) -> jax.Array:
    return jax.random.fold_in(key, purpose)


def world_key(key: jax.Array, world: int) -> jax.Array:
    """Seed key for world `world` of an ensemble replicated from `key`.

    World 0 is the IDENTITY -- `ensemble.replicate(n)[0]` is bitwise the
    solo run seeded the same way, which is what the tier-0 ensemble pins
    compare against.  Worlds k>0 fold the world id under PURPOSE_WORLD so
    their streams are decorrelated from every solo seed and from each
    other (a plain fold_in(key, k) would collide with fold_in paths that
    already consume small integers).  Host-side, build-time only: the
    fold happens once per world before stacking, never inside the
    compiled graph."""
    if world == 0:
        return key
    return jax.random.fold_in(purpose_key(key, PURPOSE_WORLD), world)


# Plain Python int, wrapped per-trace: a module-level jnp constant would run
# an eager device op at import time and initialize whatever backend is
# ambient -- `import shadow1_tpu` must never touch a backend (the multichip
# dryrun forces CPU in a child process *after* deciding via env only).
_GOLDEN = 0x9E3779B9   # odd constants decorrelate id positions


def _mix32(x):
    """Full-avalanche 32-bit finalizer (murmur3/splitmix lineage): every
    input bit flips each output bit with ~1/2 probability.  Statistical
    (not cryptographic) quality -- exactly what drop draws, jitter, and
    app randomness need, at ~8 VPU int ops per element instead of a
    per-element threefry chain (the previous vmap'd fold_in was a
    measurable slice of the micro-step at 4k hosts)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _key_words(key: jax.Array):
    kd = jax.random.key_data(key).astype(jnp.uint32).reshape(-1)
    return kd[0], kd[-1]


def keyed_bits(key: jax.Array, *ids) -> jax.Array:
    """uint32 random bits keyed by integer ids (scalars or same-shape
    arrays; vectorized over the broadcast shape).

    Functionally keyed: the value depends only on (key, ids), never on
    draw order -- the determinism-across-meshes contract."""
    ids = [jnp.asarray(i, dtype=jnp.uint32) for i in ids]
    k0, k1 = _key_words(key)
    h = _mix32(k0 ^ jnp.uint32(0x85EBCA6B))
    for n, idv in enumerate(ids):
        h = _mix32(h ^ (idv + jnp.uint32((_GOLDEN * (2 * n + 1)) & 0xFFFFFFFF)))
    return _mix32(h ^ k1)


def keyed_uniform(key: jax.Array, *ids) -> jax.Array:
    """U[0,1) keyed by integer ids (same contract as keyed_bits); f32 with
    24 bits of mantissa entropy."""
    bits = keyed_bits(key, *ids)
    return (bits >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1 / (1 << 24))
